"""Metrics registry + Prometheus endpoint (obs.metrics) — no jax needed.

Covers: counter/gauge/histogram semantics and the text exposition format
(validated with a strict line grammar), label children, the ledger->
registry sink mapping for every event type it consumes (steps, stalls,
skew, health, hbm, decode), pre-registered zero-valued series, thread
safety, and a real HTTP scrape against the daemon-thread endpoint.
"""

import json
import re
import threading
import urllib.request

import pytest

from tpu_dist.obs.ledger import Ledger
from tpu_dist.obs.metrics import (MetricsRegistry, MetricsServer,
                                  metrics_ledger_sink, serve_metrics)

# one Prometheus text-format sample line: name{labels} value
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?"
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$")


def assert_prometheus_parseable(text: str) -> int:
    """Every non-comment line must match the sample grammar; returns the
    number of samples."""
    n = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE.match(line), f"unparseable sample line: {line!r}"
        n += 1
    assert n > 0
    return n


def test_counter_gauge_histogram_render():
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "requests")
    c.inc()
    c.inc(2.5)
    g = reg.gauge("t_temp", "temperature")
    g.set(-3.5)
    h = reg.histogram("t_lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render()
    assert_prometheus_parseable(text)
    assert "# TYPE t_requests_total counter" in text
    assert "t_requests_total 3.5" in text
    assert "t_temp -3.5" in text
    assert 't_lat_seconds_bucket{le="0.1"} 1' in text
    assert 't_lat_seconds_bucket{le="1"} 2' in text
    assert 't_lat_seconds_bucket{le="+Inf"} 3' in text
    assert "t_lat_seconds_sum 5.55" in text
    assert "t_lat_seconds_count 3" in text
    # registry snapshot is JSON-safe (it rides the metrics_snapshot event)
    json.dumps(reg.snapshot())
    # same-name re-registration returns the same object; kind clash raises
    assert reg.counter("t_requests_total") is c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("t_requests_total")


def test_labels_and_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("t_trips_total", "trips by kind")

    def spam():
        for _ in range(200):
            c.labels(kind="a").inc()

    threads = [threading.Thread(target=spam) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    c.labels(kind="b")  # registered but never incremented -> renders 0
    text = reg.render()
    assert 't_trips_total{kind="a"} 800' in text
    assert 't_trips_total{kind="b"} 0' in text
    assert_prometheus_parseable(text)


def test_ledger_sink_maps_events_to_series():
    reg = MetricsRegistry()
    led = Ledger(None)
    led.add_sink(metrics_ledger_sink(reg))
    # pre-registration: the operator series exist at zero before any event
    text = reg.render()
    assert "tpu_dist_stalls_total 0" in text
    assert 'tpu_dist_health_trips_total{kind="nonfinite"} 0' in text

    led.emit("step", step=0, loss=1.5, throughput=1000.0, unit="tok/s",
             data_s=0.1, dispatch_s=0.2, device_s=0.7, comm_s=0.3,
             mfu=0.45, steps_in_dispatch=2, items=4096)
    led.emit("stall", idle_s=12.5, threshold_s=5.0, stacks="...")
    led.emit("skew", step=0, p50_s=0.1, p99_s=0.2, spread_s=0.05,
             straggler=3)
    led.emit("health", step=1, kind="nonfinite", policy="skip",
             action="skip", value=2.0)
    led.emit("health", step=2, kind="loss_spike", policy="record",
             action="record", value=9.1)
    led.emit("hbm", bytes_in_use=123456)
    led.emit("decode", tokens=64, seconds=0.5, throughput=128.0)
    led.emit("epoch", epoch=4, start_ts=0.0, seconds=10.0,
             throughput=1.0, unit="tok/s", loss=1.0)
    led.emit("eval", epoch=4, loss=0.75)
    led.close()

    text = reg.render()
    n = assert_prometheus_parseable(text)
    assert n > 20  # acceptance surface: a real scrape, not two lines
    assert "tpu_dist_steps_total 2" in text
    assert "tpu_dist_items_total 4096" in text
    assert 'tpu_dist_step_throughput{unit="tok/s"} 1000' in text
    assert "tpu_dist_mfu 0.45" in text
    assert "tpu_dist_loss 1.5" in text
    assert 'tpu_dist_phase_seconds_total{phase="device"} 0.7' in text
    assert 'tpu_dist_phase_seconds_total{phase="comm"} 0.3' in text
    assert "tpu_dist_stalls_total 1" in text
    assert "tpu_dist_stall_idle_seconds 12.5" in text
    assert "tpu_dist_skew_spread_seconds 0.05" in text
    assert "tpu_dist_straggler_index 3" in text
    assert 'tpu_dist_health_trips_total{kind="nonfinite"} 1' in text
    assert 'tpu_dist_health_trips_total{kind="loss_spike"} 1' in text
    assert "tpu_dist_hbm_bytes_in_use 123456" in text
    assert "tpu_dist_decode_tokens_total 64" in text
    assert "tpu_dist_epoch 4" in text
    assert "tpu_dist_eval_loss 0.75" in text
    # the (data+dispatch+device)/steps_in_dispatch wall landed in the hist
    assert "tpu_dist_step_seconds_count 1" in text


def test_http_scrape_endpoint():
    reg = MetricsRegistry()
    reg.counter("t_up", "liveness").inc()
    srv = serve_metrics(reg, port=0, host="127.0.0.1")  # ephemeral port
    assert isinstance(srv, MetricsServer) and srv.port > 0
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
    finally:
        srv.close()
    assert "t_up 1" in body
    assert_prometheus_parseable(body)
    # closed: the port no longer answers
    with pytest.raises(Exception):
        urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/metrics",
                               timeout=0.5)


def test_build_info_gauge_from_run_start():
    """The run_start event materializes the build_info identity gauge:
    value 1, labels carrying run id / config hash / jax version /
    quant / tp_impl — the join key across runs (PR 6 satellite)."""
    reg = MetricsRegistry()
    sink = metrics_ledger_sink(reg)
    # pre-registered family renders (HELP/TYPE) before any run_start
    assert "tpu_dist_build_info" in reg.render()
    sink({"event": "run_start", "ts": 1234.5, "pid": 0, "kind": "lm",
          "config": {"quant": "int8", "tp_impl": "ring", "lr": 0.1},
          "jax_version": "9.9.9"})
    text = reg.render()
    assert_prometheus_parseable(text)
    (line,) = [ln for ln in text.splitlines()
               if ln.startswith("tpu_dist_build_info{")]
    assert line.endswith(" 1")
    for frag in ('run_id="1234-p0"', 'kind="lm"', 'quant="int8"',
                 'tp_impl="ring"', 'jax="9.9.9"'):
        assert frag in line, (frag, line)
    # config hash is stable across identical configs, distinct otherwise
    import hashlib

    chash = hashlib.sha1(json.dumps(
        {"quant": "int8", "tp_impl": "ring", "lr": 0.1},
        sort_keys=True, default=str).encode()).hexdigest()[:12]
    assert f'config_hash="{chash}"' in line


def test_healthz_liveness_path():
    """/healthz and /livez answer 'ok' without rendering the registry;
    every other path still serves the scrape payload."""
    reg = MetricsRegistry()
    reg.counter("t_up", "liveness").inc()
    srv = serve_metrics(reg, port=0, host="127.0.0.1")
    try:
        for path in ("/healthz", "/livez"):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}", timeout=5) as r:
                assert r.status == 200
                assert r.read().decode() == "ok\n"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            assert "t_up 1" in r.read().decode()
    finally:
        srv.close()
