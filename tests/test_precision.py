"""Precision policy + dynamic loss scaling (apex AMP semantics, C11/C12)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.ops.precision import (LossScaleState, make_policy, scale_loss,
                                    unscale_and_update)


def test_policy_dtypes():
    assert make_policy("fp32").compute_dtype == jnp.float32
    assert make_policy("bf16").compute_dtype == jnp.bfloat16
    assert make_policy("bf16").param_dtype == jnp.float32        # O1-ish
    assert make_policy("bf16_params").param_dtype == jnp.bfloat16  # O2-ish
    with pytest.raises(ValueError):
        make_policy("fp16")


def test_no_scaling_passthrough():
    grads = {"w": jnp.ones((2,))}
    out, state, finite = unscale_and_update(grads, None)
    assert state is None and bool(finite)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones((2,)))


def test_loss_scale_roundtrip():
    s = LossScaleState.create(1024.0)
    loss = scale_loss(jnp.float32(2.0), s)
    assert float(loss) == 2048.0
    grads = {"w": jnp.full((3,), 1024.0)}
    out, s2, finite = unscale_and_update(grads, s)
    assert bool(finite)
    np.testing.assert_allclose(np.asarray(out["w"]), np.ones((3,)))


def test_loss_scale_halves_on_overflow_and_grows():
    s = LossScaleState.create(1024.0)
    bad = {"w": jnp.array([jnp.inf, 1.0])}
    _, s2, finite = unscale_and_update(bad, s)
    assert not bool(finite)
    assert float(s2.scale) == 512.0  # apex: halve on non-finite
    good = {"w": jnp.array([1.0, 1.0])}
    _, s3, finite = unscale_and_update(good, s2, growth_interval=1)
    assert bool(finite)
    assert float(s3.scale) == 1024.0  # doubled after growth_interval good steps
