"""Ring attention == full attention over a sequence-sharded mesh (exactness)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from tpu_dist._compat import shard_map
from jax.sharding import PartitionSpec as P

from tpu_dist.models.transformer import full_attention
from tpu_dist.parallel.mesh import make_mesh
from tpu_dist.parallel.ring_attention import ring_attention


def _qkv(B=2, L=64, H=4, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_ring_matches_full_attention(causal, n_shards):
    mesh = make_mesh((n_shards,), ("seq",),
                     devices=jax.devices()[:n_shards])
    q, k, v = _qkv()
    ref = full_attention(q, k, v, causal=causal)
    ring = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"), check_vma=False))
    out = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_full_attention():
    mesh = make_mesh((4,), ("seq",), devices=jax.devices()[:4])
    q, k, v = _qkv(L=32)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq"),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"), check_vma=False)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_full, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=5e-5)


def test_ring_fp32_accumulation_under_bf16_inputs():
    mesh = make_mesh((4,), ("seq",), devices=jax.devices()[:4])
    q, k, v = _qkv(L=32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ring = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq"),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"), check_vma=False))
    out = ring(qb, kb, vb)
    assert out.dtype == jnp.bfloat16
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)
