"""MoE layer + expert parallelism: dispatch math, training, EP equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dist.engine.lm_steps import make_lm_batches, make_lm_train_step
from tpu_dist.engine.state import TrainState
from tpu_dist.models.moe import MoEMLP, MoETransformerLM
from tpu_dist.ops import make_optimizer
from tpu_dist.parallel.ep import ep_param_specs
from tpu_dist.parallel.mesh import make_mesh, replicated

V, L, B, E = 64, 32, 16, 4


def test_moe_mlp_shapes_and_aux():
    m = MoEMLP(num_experts=E)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, 16)),
                    jnp.float32)
    variables = m.init(jax.random.PRNGKey(0), x)
    out, muts = m.apply(variables, x, mutable=["intermediates"])
    assert out.shape == x.shape
    (aux,) = muts["intermediates"]["aux_loss"]
    # balanced-uniform lower bound is 1.0; any gating gives >= 1
    # (plus the small z-loss term)
    assert float(aux) >= 0.99


def test_moe_capacity_drops_are_residual_passthrough():
    """With capacity factor ~0 every token is dropped -> MoE output is zero
    (the block's residual carries the activations)."""
    m = MoEMLP(num_experts=E, capacity_factor=1e-9)
    x = jnp.ones((1, 8, 16))
    variables = m.init(jax.random.PRNGKey(0), x)
    out = m.apply(variables, x)
    # capacity 1 per expert: at most E tokens contribute, rest are zeros
    nonzero_rows = jnp.sum(jnp.any(out.reshape(8, 16) != 0, axis=-1))
    assert int(nonzero_rows) <= E


@pytest.fixture(scope="module")
def moe_setup():
    model = MoETransformerLM(vocab_size=V, max_len=L, num_experts=E)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=1000)
    rng_np = np.random.default_rng(0)
    tokens = rng_np.integers(0, V, (B, L + 1)).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    return model, params, tx, inputs, targets


@pytest.mark.slow  # tier-1 budget (PR 20): loss-goes-down smoke over the same moe_setup step whose math test_expert_parallel_matches_dp pins exactly in-budget
def test_moe_lm_trains(moe_setup):
    model, params, tx, inputs, targets = moe_setup
    mesh = make_mesh((8,), ("data",))
    st = jax.device_put(TrainState.create(params, {}, tx), replicated(mesh))
    step = make_lm_train_step(model, tx, mesh, donate=False)
    sh = NamedSharding(mesh, P("data"))
    inputs_d, targets_d = jax.device_put(inputs, sh), jax.device_put(targets, sh)
    losses = []
    for _ in range(15):
        st, m = step(st, inputs_d, targets_d, jax.random.PRNGKey(1))
        # distlint: disable=DL002 -- CPU test: per-step loss assertion needs the value now
        mm = jax.device_get(m)
        losses.append(float(mm["loss_sum"]) / float(mm["count"]))
    assert losses[-1] < losses[0] * 0.9


def test_expert_parallel_matches_dp(moe_setup):
    model, params, tx, inputs, targets = moe_setup
    specs = [s for s in jax.tree.leaves(ep_param_specs(params),
                                        is_leaf=lambda x: isinstance(x, P))
             if s != P()]
    assert len(specs) == 4  # 2 layers x (w_in, w_out); gate NOT sharded

    mesh_dp = make_mesh((8,), ("data",))
    st = jax.device_put(TrainState.create(params, {}, tx), replicated(mesh_dp))
    step = make_lm_train_step(model, tx, mesh_dp, donate=False)
    sh = NamedSharding(mesh_dp, P("data"))
    _, m_dp = step(st, jax.device_put(inputs, sh), jax.device_put(targets, sh),
                   jax.random.PRNGKey(1))

    mesh_ep = make_mesh((2, 4), ("data", "expert"))
    from tpu_dist.parallel.ep import shard_state_ep
    st_ep = shard_state_ep(mesh_ep, TrainState.create(params, {}, tx))
    assert st_ep.params["block0"]["moe"]["w_in"].sharding.spec[0] == "expert"
    # momentum buffers for expert weights are sharded too (EP memory scaling)
    mom_specs = [l.sharding.spec for l in jax.tree.leaves(st_ep.opt_state)
                 if hasattr(l, "ndim") and l.ndim == 3]
    assert mom_specs and all(s[0] == "expert" for s in mom_specs)
    step_ep = make_lm_train_step(model, tx, mesh_ep, donate=False)
    sh_ep = NamedSharding(mesh_ep, P("data"))
    _, m_ep = step_ep(st_ep, jax.device_put(inputs, sh_ep),
                      jax.device_put(targets, sh_ep), jax.random.PRNGKey(1))
    a = float(jax.device_get(m_dp["loss_sum"]))
    b = float(jax.device_get(m_ep["loss_sum"]))
    assert b == pytest.approx(a, rel=1e-4)


def test_top2_routing_dispatches_two_experts():
    """Top-2: every token's combine weights sum to ~1 (renormalized gates
    over BOTH dispatched experts); top-1's sum to gate1 < 1."""
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 8, 16)),
                    jnp.float32)
    m2 = MoEMLP(num_experts=E, router_top_k=2, capacity_factor=4.0)
    variables = m2.init(jax.random.PRNGKey(0), x)
    out, muts = m2.apply(variables, x, mutable=["intermediates"])
    assert out.shape == x.shape
    (mass2,) = muts["intermediates"]["combine_mass"]
    np.testing.assert_allclose(np.asarray(mass2),
                               np.ones_like(np.asarray(mass2)), atol=1e-5)
    m1 = MoEMLP(num_experts=E, router_top_k=1, capacity_factor=4.0)
    out1, muts1 = m1.apply(variables, x, mutable=["intermediates"])
    (mass1,) = muts1["intermediates"]["combine_mass"]
    # top-1 mass = gate1 strictly below 1 (softmax over E>=2 experts)
    assert float(jnp.max(mass1)) < 1.0
    # and the second expert's contribution changes the output
    assert float(jnp.max(jnp.abs(out - out1))) > 1e-6


@pytest.mark.slow  # tier-1 budget (PR 18): ~6s near-duplicate — the train
# loop stays covered in-budget by test_moe_lm_trains (top-1, same step
# builder) and top-2 routing semantics by the combine-mass unit +
# test_top2_capacity_overflow_drops_second_choice
def test_top2_moe_lm_trains(moe_setup):
    _, _, tx, inputs, targets = (*moe_setup,)
    model = MoETransformerLM(vocab_size=V, max_len=L, num_experts=E,
                             router_top_k=2)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, L), jnp.int32), train=False)["params"]
    mesh = make_mesh((8,), ("data",))
    state = jax.device_put(TrainState.create(params, {}, tx),
                           replicated(mesh))
    step = make_lm_train_step(model, tx, mesh, donate=False)
    sh = NamedSharding(mesh, P("data"))
    di, dt = jax.device_put(inputs, sh), jax.device_put(targets, sh)
    key = jax.random.PRNGKey(1)
    losses = []
    for _ in range(6):
        state, m = step(state, di, dt, key)
        # distlint: disable=DL002 -- CPU test: per-step loss assertion needs the value now
        losses.append(float(jax.device_get(m["loss_sum"]))
                      / float(jax.device_get(m["count"])))
    assert losses[-1] < losses[0], losses


def test_router_z_loss_in_aux():
    """z-loss contributes: scaling it changes the sown aux value."""
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 8, 16)),
                    jnp.float32)
    lo = MoEMLP(num_experts=E, z_loss_coef=0.0)
    hi = MoEMLP(num_experts=E, z_loss_coef=10.0)
    variables = lo.init(jax.random.PRNGKey(0), x)
    _, m_lo = lo.apply(variables, x, mutable=["intermediates"])
    _, m_hi = hi.apply(variables, x, mutable=["intermediates"])
    (a_lo,) = m_lo["intermediates"]["aux_loss"]
    (a_hi,) = m_hi["intermediates"]["aux_loss"]
    assert float(a_hi) > float(a_lo)


def test_top2_capacity_overflow_drops_second_choice():
    """Top-2 under tight capacity: second-choice tokens queue BEHIND every
    first-choice token (GShard order), so when an expert's queue overflows
    the SECOND choices drop first — combine mass falls below 1 for exactly
    the over-capacity tokens, and the aux/diagnostic plumbing reports it."""
    x = jnp.asarray(np.random.default_rng(4).normal(size=(1, 16, 16)),
                    jnp.float32)
    # capacity_factor chosen so cap < tokens-per-expert under any routing:
    # with E=4, S=16, top-2: cap = int(16/4 * 0.3 * 2) = 2 slots per expert
    # but 16 tokens place 32 choices -> 8 per expert on average >> 2
    m = MoEMLP(num_experts=E, router_top_k=2, capacity_factor=0.3)
    variables = m.init(jax.random.PRNGKey(0), x)
    out, muts = m.apply(variables, x, mutable=["intermediates"])
    (mass,) = muts["intermediates"]["combine_mass"]
    mass = np.asarray(mass)
    # overflow must actually occur and be visible in the diagnostic
    assert float(mass.min()) < 0.999, "no token lost any routing mass"
    # fully-dropped tokens (both choices over capacity) pass through as
    # zeros: their MoE output is exactly zero (residual carries them)
    fully_dropped = mass < 1e-6
    if fully_dropped.any():
        np.testing.assert_allclose(
            np.asarray(out).reshape(-1, 16)[fully_dropped.reshape(-1)], 0.0,
            atol=1e-6)
    # nothing ever exceeds mass 1 (each token contributes once per choice)
    assert float(mass.max()) <= 1.0 + 1e-5


@pytest.mark.slow  # tier-1 budget (PR 19): 13s compiled-FLOPs/memory property
# on the 8-way mesh; EP stays exercised in-budget by
# test_expert_parallel_matches_dp (same (data=1, expert=8) mesh, loss
# parity vs dp) and test_moe_tp_composition_matches_dp
def test_ep_actually_shards_expert_compute():
    """'EP is EP' (VERDICT r2 weak #5): on the SAME (data=1, expert=8) mesh
    with the SAME global batch, expert-sharding the params must cut the
    per-device compiled FLOPs (each device runs only its experts' MLPs) and
    live temp memory, not just the parameter bytes. GSPMD lowers the
    dispatch/combine einsums to expert-axis partial sums (an all-reduce
    formulation of the classic all-to-all exchange); if it silently
    all-gathered the experts instead, per-device FLOPs would NOT drop and
    this test fails."""
    from tpu_dist.parallel.ep import shard_state_ep

    moe = MoETransformerLM(vocab_size=V, num_layers=2, d_model=128,
                           num_heads=4, num_experts=8, max_len=L)
    params = moe.init({"params": jax.random.PRNGKey(0)},
                      jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=100)
    tokens = np.random.default_rng(0).integers(0, V, (B, L + 1)).astype(
        np.int32)
    i, t = make_lm_batches(tokens)
    mesh = make_mesh((1, 8), ("data", "expert"))
    from tpu_dist.parallel.mesh import batch_sharding
    sh = batch_sharding(mesh)

    def compiled(sharder):
        st = sharder(mesh, TrainState.create(params, {}, tx))
        step = make_lm_train_step(moe, tx, mesh, donate=False)
        return step.lower(st, jax.device_put(i, sh), jax.device_put(t, sh),
                          jax.random.PRNGKey(1)).compile()

    def flops(comp):
        ca = comp.cost_analysis()
        return float((ca[0] if isinstance(ca, list) else ca)["flops"])

    rep = compiled(lambda mesh, st: jax.device_put(st, replicated(mesh)))
    ep = compiled(shard_state_ep)
    f_rep, f_ep = flops(rep), flops(ep)
    assert f_ep < 0.5 * f_rep, (f_ep, f_rep)  # expert MLP work divided
    m_rep = int(rep.memory_analysis().temp_size_in_bytes)
    m_ep = int(ep.memory_analysis().temp_size_in_bytes)
    assert m_ep < m_rep, (m_ep, m_rep)
    # and the expert weights themselves live 1/8 per device
    st = shard_state_ep(mesh, TrainState.create(params, {}, tx))
    w = st.params["block0"]["moe"]["w_in"]
    assert w.addressable_shards[0].data.shape[0] == 1  # 8 experts / 8 devs


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_moe_remat_matches_no_remat(moe_setup):
    """--remat with MoE (VERDICT r3 #4): per-block rematerialization must
    change memory, never math — identical loss/metrics and updated params,
    with the sown aux-loss/router-mass intermediates surviving nn.remat."""
    _, _, tx, inputs, targets = moe_setup
    mesh = make_mesh((8,), ("data",))
    sh = NamedSharding(mesh, P("data"))
    di, dt = jax.device_put(inputs, sh), jax.device_put(targets, sh)

    def one_step(remat):
        model = MoETransformerLM(vocab_size=V, max_len=L, num_experts=E,
                                 num_layers=4, remat=remat)
        params = model.init({"params": jax.random.PRNGKey(0)},
                            jnp.zeros((1, L), jnp.int32),
                            train=False)["params"]
        st = jax.device_put(TrainState.create(params, {}, tx),
                            replicated(mesh))
        step = make_lm_train_step(model, tx, mesh, donate=False)
        lowered = step.lower(st, di, dt, jax.random.PRNGKey(1)).compile()
        st, m = step(st, di, dt, jax.random.PRNGKey(1))
        return (jax.device_get(st.params), jax.device_get(m),
                int(lowered.memory_analysis().temp_size_in_bytes))

    p_plain, m_plain, mem_plain = one_step(False)
    p_remat, m_remat, mem_remat = one_step(True)
    for k in ("loss_sum", "correct1", "count", "router_mass_sum"):
        assert float(m_remat[k]) == pytest.approx(float(m_plain[k]),
                                                  rel=1e-5), k
    assert float(m_remat["router_mass_n"]) > 0  # sow survives nn.remat
    flat_a = {jax.tree_util.keystr(p): v for p, v in
              jax.tree_util.tree_leaves_with_path(p_plain)}
    flat_b = {jax.tree_util.keystr(p): v for p, v in
              jax.tree_util.tree_leaves_with_path(p_remat)}
    for path in flat_a:
        np.testing.assert_allclose(np.asarray(flat_b[path]),
                                   np.asarray(flat_a[path]),
                                   rtol=1e-5, atol=1e-7, err_msg=path)
    # and remat actually buys activation memory at depth
    assert mem_remat < mem_plain, (mem_remat, mem_plain)


@pytest.mark.slow  # tier-1 budget (PR 20): composition of two single-axis parities that stay in-budget (test_expert_parallel_matches_dp, test_lm.py::test_tp_matches_dp) — the PR 11 dp x tp convention
def test_moe_tp_composition_matches_dp(moe_setup):
    """MoE x TP (VERDICT r3 #4): a (data=2, expert=2, model=2) mesh with
    expert weights Megatron-split over 'model' on top of their 'expert'
    shard must reproduce the replicated-DP step."""
    from tpu_dist.parallel.ep import shard_state_ep

    model, params, tx, inputs, targets = moe_setup
    mesh_dp = make_mesh((8,), ("data",))
    st = jax.device_put(TrainState.create(params, {}, tx),
                        replicated(mesh_dp))
    step = make_lm_train_step(model, tx, mesh_dp, donate=False)
    sh = NamedSharding(mesh_dp, P("data"))
    st_dp, m_dp = step(st, jax.device_put(inputs, sh),
                       jax.device_put(targets, sh), jax.random.PRNGKey(1))

    mesh = make_mesh((2, 2, 2), ("data", "expert", "model"))
    st_tp = shard_state_ep(mesh, TrainState.create(params, {}, tx))
    w_in = st_tp.params["block0"]["moe"]["w_in"]
    assert w_in.sharding.spec == P("expert", None, "model")
    local = w_in.addressable_shards[0].data.shape
    assert local[0] == w_in.shape[0] // 2 and local[2] == w_in.shape[2] // 2
    qkv = st_tp.params["block0"]["qkv"]["kernel"]
    assert qkv.sharding.spec == P(None, "model")
    step_tp = make_lm_train_step(model, tx, mesh, donate=False)
    sh_tp = NamedSharding(mesh, P("data"))
    st_tp, m_tp = step_tp(st_tp, jax.device_put(inputs, sh_tp),
                          jax.device_put(targets, sh_tp),
                          jax.random.PRNGKey(1))

    for k in ("loss_sum", "correct1", "count"):
        assert float(jax.device_get(m_tp[k])) == pytest.approx(
            float(jax.device_get(m_dp[k])), rel=1e-4), k
    flat_dp = {jax.tree_util.keystr(p): v for p, v in
               jax.tree_util.tree_leaves_with_path(jax.device_get(st_dp.params))}
    flat_tp = {jax.tree_util.keystr(p): v for p, v in
               jax.tree_util.tree_leaves_with_path(jax.device_get(st_tp.params))}
    for path in flat_dp:
        np.testing.assert_allclose(np.asarray(flat_tp[path]),
                                   np.asarray(flat_dp[path]),
                                   rtol=2e-4, atol=2e-6, err_msg=path)


def test_moe_analytical_flops_accounting():
    """The MoE MFU formula (VERDICT r3 #4): counts top_k-activated expert
    params (not all E) plus the dispatch/combine einsum term, and feeds a
    real (non-None) TFLOP/s figure through LMTrainer._mfu."""
    from tpu_dist.utils.mfu import lm_flops_per_token, moe_lm_flops_per_token

    model = MoETransformerLM(vocab_size=V, max_len=L, num_experts=E)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, L), jnp.int32), train=False)["params"]
    kw = dict(num_layers=2, seq_len=L, d_model=64, num_experts=E,
              total_tokens=B * L)
    f1 = moe_lm_flops_per_token(params, router_top_k=1, **kw)
    f2 = moe_lm_flops_per_token(params, router_top_k=2, **kw)
    assert f2 > f1  # top-2 activates twice the expert params
    # dense formula over the same params counts ALL experts -> overstates
    dense_all = lm_flops_per_token(params, 2, L, 64)
    expert_sz = sum(int(np.prod(v.shape)) for p, v in
                    jax.tree_util.tree_leaves_with_path(params)
                    if "w_in" in jax.tree_util.keystr(p)
                    or "w_out" in jax.tree_util.keystr(p))
    assert f1 < dense_all + 12 * E * 64 * 64 * 2  # loose sanity ceiling
    assert f1 > 6.0 * expert_sz / E              # at least one expert's MLP

    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer
    cfg = LMConfig(batch_size=8, seq_len=32, d_model=32, num_layers=1,
                   num_heads=2, vocab_size=64, synth_tokens=2000,
                   num_experts=4, print_freq=100, epochs=1, max_steps=2)
    tr = LMTrainer(cfg)
    tr.train_epoch(0)
    tflops, _ = tr._mfu(1000.0)
    assert tflops is not None and tflops > 0


def test_moe_training_reports_router_mass(tmp_path):
    """The dropped-token diagnostic reaches the training surface: a dp-moe
    LMTrainer epoch's meters carry RMass (mean combine mass per token)."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    cfg = LMConfig(batch_size=8, seq_len=32, d_model=32, num_layers=1,
                   num_heads=2, vocab_size=64, synth_tokens=2000,
                   num_experts=4, print_freq=100, epochs=1, max_steps=3)
    tr = LMTrainer(cfg)
    metrics = tr.train_epoch(0)
    assert "rmass" in metrics
    assert 0.0 < metrics["rmass"] <= 1.0 + 1e-5


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_moe_sp_composition_matches_dp():
    """MoE + sequence parallelism (round 4): with a router group size that
    divides the shard's tokens, sp grouping partitions each row into the
    SAME contiguous segments as the dp grouping, so one sp train step
    (aux_weight=0 — the balance loss averages differently across shards)
    equals one dp step parameter-for-parameter."""
    from functools import partial

    from tpu_dist.engine.lm_steps import make_lm_sp_train_step

    rng_np = np.random.default_rng(3)
    tokens = rng_np.integers(0, V, (8, L + 1)).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    # sp shard per device: (8/2) x (32/4) = 32 tokens; group 8 divides the
    # shard AND each row's 8-token segments, matching dp's row-major
    # (B*L)/8 grouping segment for segment
    ctor = partial(MoETransformerLM, vocab_size=V, max_len=L,
                   num_experts=E, group_size=8)
    model = ctor()
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=1000)
    key = jax.random.PRNGKey(7)

    mesh_dp = make_mesh((8,), ("data",))
    st = jax.device_put(TrainState.create(params, {}, tx),
                        replicated(mesh_dp))
    dp_step = make_lm_train_step(model, tx, mesh_dp, aux_weight=0.0,
                                 donate=False)
    sh = NamedSharding(mesh_dp, P("data"))
    st_dp, _ = dp_step(st, jax.device_put(inputs, sh),
                       jax.device_put(targets, sh), key)

    mesh_sp = make_mesh((2, 4), ("data", "seq"))
    st2 = jax.device_put(TrainState.create(params, {}, tx),
                         replicated(mesh_sp))
    sp_step = make_lm_sp_train_step(ctor, tx, mesh_sp, aux_weight=0.0,
                                    donate=False)
    sh_sp = NamedSharding(mesh_sp, P("data", "seq"))
    st_sp, _ = sp_step(st2, jax.device_put(inputs, sh_sp),
                       jax.device_put(targets, sh_sp), key)

    flat_dp = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
               jax.tree_util.tree_flatten_with_path(
                   jax.device_get(st_dp.params))[0]}
    flat_sp = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
               jax.tree_util.tree_flatten_with_path(
                   jax.device_get(st_sp.params))[0]}
    assert flat_dp.keys() == flat_sp.keys()
    for k in flat_dp:
        np.testing.assert_allclose(flat_sp[k], flat_dp[k],
                                   rtol=2e-4, atol=1e-5, err_msg=k)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_moe_sp_trains_via_lm_trainer():
    """LMTrainer accepts data=2,seq=4 + --num-experts (the round-3 'not
    supported yet' rejection is gone) and trains + evaluates end to end."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    cfg = LMConfig(mesh_shape=(2, 4), mesh_axes=("data", "seq"),
                   num_experts=4, moe_group_size=8, batch_size=8,
                   seq_len=32, d_model=32, num_layers=2, num_heads=2,
                   vocab_size=64, synth_tokens=3000, seed=3, epochs=2,
                   optimizer="adamw", lr=3e-3, print_freq=100,
                   data_placement="host")
    tr = LMTrainer(cfg)
    tr.fit()
    loss, ppl, acc = tr.validate()
    assert np.isfinite(loss) and ppl < 64  # better than uniform


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_moe_pp_gpipe_matches_dp():
    """MoE + pipeline (round 4, GPipe only): 4 MoE blocks over 4 stages,
    aux_weight=0 and a group size dividing each row's segments — one
    pp-gpipe step equals one dp step parameter-for-parameter."""
    from tpu_dist.parallel.pp import (make_lm_pp_train_step,
                                     shard_state_pp, stack_pipeline_params,
                                     unstack_pipeline_params)

    rng_np = np.random.default_rng(5)
    tokens = rng_np.integers(0, V, (8, L + 1)).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    model = MoETransformerLM(vocab_size=V, max_len=L, num_experts=E,
                             num_layers=4, group_size=8)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=1000)
    key = jax.random.PRNGKey(9)

    mesh_dp = make_mesh((8,), ("data",))
    st = jax.device_put(TrainState.create(params, {}, tx),
                        replicated(mesh_dp))
    dp_step = make_lm_train_step(model, tx, mesh_dp, aux_weight=0.0,
                                 donate=False)
    sh = NamedSharding(mesh_dp, P("data"))
    st_dp, m_dp = dp_step(st, jax.device_put(inputs, sh),
                          jax.device_put(targets, sh), key)

    mesh_pp = make_mesh((2, 4), ("data", "stage"))
    pp_params = stack_pipeline_params(params, 4)
    st_pp = shard_state_pp(mesh_pp, TrainState.create(pp_params, {}, tx))
    pp_step = make_lm_pp_train_step(model, tx, mesh_pp, num_microbatches=2,
                                    donate=False, aux_weight=0.0)
    sh_pp = NamedSharding(mesh_pp, P("data", None))
    st_pp2, m_pp = pp_step(st_pp, jax.device_put(inputs, sh_pp),
                           jax.device_put(targets, sh_pp), key)

    np.testing.assert_allclose(float(m_pp["loss_sum"]),
                               float(m_dp["loss_sum"]), rtol=1e-5)
    flat_dp = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
               jax.tree_util.tree_flatten_with_path(
                   jax.device_get(st_dp.params))[0]}
    flat_pp = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
               jax.tree_util.tree_flatten_with_path(unstack_pipeline_params(
                   jax.device_get(st_pp2.params)))[0]}
    assert flat_dp.keys() == flat_pp.keys()
    for k in flat_dp:
        np.testing.assert_allclose(flat_pp[k], flat_dp[k],
                                   rtol=2e-4, atol=1e-5, err_msg=k)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_moe_pp_trains_via_lm_trainer(schedule):
    """LMTrainer drives MoE x pp end to end (aux ON) under BOTH schedules —
    the round-4 'MoE + pipeline requires gpipe' rejection is gone: the
    1f1b tick threads the router aux through its manual vjp."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    kw = dict(num_experts=4, moe_group_size=8, batch_size=8, seq_len=32,
              d_model=32, num_layers=4, num_heads=2, vocab_size=64,
              synth_tokens=3000, seed=3, epochs=2, optimizer="adamw",
              lr=3e-3, print_freq=100, data_placement="host",
              pp_microbatches=2, pp_schedule=schedule)
    cfg = LMConfig(mesh_shape=(2, 4), mesh_axes=("data", "stage"), **kw)
    tr = LMTrainer(cfg)
    tr.fit()
    loss, ppl, acc = tr.validate()
    assert np.isfinite(loss) and ppl < 64


def test_moe_pp_tp_trains_via_lm_trainer():
    """The TRAINER accepts MoE over a (data, stage, model) mesh — the
    round-5 composition reachable end to end, not just via the pp.py
    makers (guard regression: the 'MoE + pure tensor parallelism' check
    must exempt pipeline meshes)."""
    from tpu_dist._compat import PARTIAL_MANUAL_SHARD_MAP
    if not PARTIAL_MANUAL_SHARD_MAP:
        # the same gate test_pp's pp x tp test carries (PR 1 contract:
        # _pp_shard_map raises cleanly on old jax, tests skip) — it was
        # missing here and only surfaced once the tier-1 budget fix let
        # the suite actually reach this file
        pytest.skip("pp x tp needs partial-manual shard_map (jax >= 0.6); "
                    "this jax's experimental shard_map aborts in the SPMD "
                    "partitioner (_compat.PARTIAL_MANUAL_SHARD_MAP)")
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    cfg = LMConfig(mesh_shape=(2, 2, 2),
                   mesh_axes=("data", "stage", "model"),
                   num_experts=4, moe_group_size=8, batch_size=8,
                   seq_len=32, d_model=32, num_layers=4, num_heads=2,
                   vocab_size=64, synth_tokens=3000, seed=3, epochs=2,
                   optimizer="adamw", lr=3e-3, print_freq=100,
                   data_placement="host", pp_microbatches=2)
    tr = LMTrainer(cfg)
    assert tr.mode == "pp-gpipe+tp"
    tr.fit()
    loss, ppl, acc = tr.validate()
    assert np.isfinite(loss) and ppl < 64


@pytest.mark.slow  # tier-1 budget (PR 14): near-duplicate composition —
# MoE x pp parity vs dp stays in-budget via test_moe_pp_gpipe_matches_dp,
# and the 1f1b-vs-gpipe schedule equivalence (the only other variable
# here) is pinned pure-pp by test_pp.py::test_pp_1f1b_loss_chunk_matches_dp
def test_moe_pp_1f1b_matches_gpipe_with_aux():
    """MoE x 1f1b == MoE x GPipe *with the router aux loss ON* (round 5):
    the manual-vjp schedule must thread aux_weight/M per microbatch through
    each stage's vjp AND propagate the aux input-cotangent across the
    backward ppermute ring. GPipe-by-autodiff on the SAME microbatch
    geometry is the ground truth — the aux term is a per-apply mean of a
    product of group means, so it is schedule-geometry-dependent by
    construction (dp's global-batch aux differs mathematically; the CE
    loss and routing stay dp-identical and are asserted against dp in
    test_moe_pp_gpipe_matches_dp)."""
    from tpu_dist.parallel.pp import (make_lm_pp_1f1b_train_step,
                                      make_lm_pp_train_step,
                                      shard_state_pp, stack_pipeline_params,
                                      unstack_pipeline_params)

    rng_np = np.random.default_rng(5)
    tokens = rng_np.integers(0, V, (8, L + 1)).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    model = MoETransformerLM(vocab_size=V, max_len=L, num_experts=E,
                             num_layers=4, group_size=8)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=1000)
    key = jax.random.PRNGKey(9)
    mesh_pp = make_mesh((2, 4), ("data", "stage"))
    sh_pp = NamedSharding(mesh_pp, P("data", None))
    di, dt = jax.device_put(inputs, sh_pp), jax.device_put(targets, sh_pp)

    def run(maker):
        pp_params = stack_pipeline_params(params, 4)
        st = shard_state_pp(mesh_pp, TrainState.create(pp_params, {}, tx))
        step = maker(model, tx, mesh_pp, 2, donate=False, aux_weight=0.05)
        st2, m = step(st, di, dt, key)
        return (unstack_pipeline_params(jax.device_get(st2.params)),
                jax.device_get(m))

    p_g, m_g = run(make_lm_pp_train_step)
    p_f, m_f = run(make_lm_pp_1f1b_train_step)

    np.testing.assert_allclose(float(m_f["loss_sum"]),
                               float(m_g["loss_sum"]), rtol=1e-5)
    # the router-mass diagnostic reaches the 1f1b metrics too
    assert float(m_f["router_mass_n"]) > 0
    assert float(m_f["router_mass_n"]) == pytest.approx(
        float(m_g["router_mass_n"]), rel=1e-6)
    flat_g = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
              jax.tree_util.tree_flatten_with_path(p_g)[0]}
    flat_f = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
              jax.tree_util.tree_flatten_with_path(p_f)[0]}
    assert flat_g.keys() == flat_f.keys()
    for k in flat_g:
        np.testing.assert_allclose(flat_f[k], flat_g[k],
                                   rtol=2e-4, atol=1e-5, err_msg=k)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_moe_pp_tp_matches_pp(schedule):
    """MoE x pp x tp (round 5, the last composition hole): a (data=2,
    stage=2, model=2) mesh with the stacked expert kernels Megatron-split
    over 'model' on top of their 'stage' shard must reproduce the same
    schedule on a plain (data=2, stage=2) mesh — with the router aux loss
    ON, so the only variable is the 'model' partitioning (pp == dp is
    covered by test_moe_pp_gpipe_matches_dp; aux is schedule-geometry
    dependent, see test_moe_pp_1f1b_matches_gpipe_with_aux)."""
    from tpu_dist._compat import PARTIAL_MANUAL_SHARD_MAP
    if not PARTIAL_MANUAL_SHARD_MAP:
        # see test_moe_pp_tp_trains_via_lm_trainer: the test_pp gate,
        # restored here once tier-1 started reaching this file
        pytest.skip("pp x tp needs partial-manual shard_map (jax >= 0.6); "
                    "this jax's experimental shard_map aborts in the SPMD "
                    "partitioner (_compat.PARTIAL_MANUAL_SHARD_MAP)")
    from tpu_dist.parallel.pp import (make_lm_pp_1f1b_train_step,
                                      make_lm_pp_train_step,
                                      shard_state_pp, stack_pipeline_params,
                                      unstack_pipeline_params)

    rng_np = np.random.default_rng(7)
    tokens = rng_np.integers(0, V, (8, L + 1)).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    model = MoETransformerLM(vocab_size=V, max_len=L, num_experts=E,
                             num_layers=4, group_size=8)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=1000)
    key = jax.random.PRNGKey(9)
    maker = (make_lm_pp_1f1b_train_step if schedule == "1f1b"
             else make_lm_pp_train_step)

    def run(mesh_shape, axes):
        ndev = int(np.prod(mesh_shape))
        mesh = make_mesh(mesh_shape, axes, devices=jax.devices()[:ndev])
        pp_params = stack_pipeline_params(params, mesh.shape["stage"])
        st = shard_state_pp(mesh, TrainState.create(pp_params, {}, tx))
        if "model" in axes:
            # expert kernels split over BOTH stage and model axes: w_in is
            # (S, layers, E, D, F) with S on 'stage' and F on 'model'
            w_in = st.params["blocks"]["moe"]["w_in"]
            local = w_in.addressable_shards[0].data.shape
            assert local[0] == w_in.shape[0] // 2
            assert local[-1] == w_in.shape[-1] // 2
        step = maker(model, tx, mesh, 2, donate=False, aux_weight=0.05)
        sh = NamedSharding(mesh, P("data", None))
        st2, m = step(st, jax.device_put(inputs, sh),
                      jax.device_put(targets, sh), key)
        return (unstack_pipeline_params(jax.device_get(st2.params)),
                jax.device_get(m))

    p_pp, m_pp = run((2, 2), ("data", "stage"))
    p_tp, m_tp = run((2, 2, 2), ("data", "stage", "model"))

    np.testing.assert_allclose(float(m_tp["loss_sum"]),
                               float(m_pp["loss_sum"]), rtol=1e-4)
    flat_pp = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
               jax.tree_util.tree_flatten_with_path(p_pp)[0]}
    flat_tp = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
               jax.tree_util.tree_flatten_with_path(p_tp)[0]}
    assert flat_pp.keys() == flat_tp.keys()
    for k in flat_pp:
        np.testing.assert_allclose(flat_tp[k], flat_pp[k],
                                   rtol=5e-4, atol=1e-5,
                                   err_msg=f"{schedule} {k}")


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_moe_aux_weight_flag_reaches_objective():
    """--moe-aux-weight threads into the training objective: zero weight
    trains different parameters than the 0.01 default (same seed), and the
    router-gate grads vanish only in balance direction when weight=0."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    kw = dict(num_experts=4, batch_size=8, seq_len=32, d_model=32,
              num_layers=2, num_heads=2, vocab_size=64, synth_tokens=2000,
              seed=3, epochs=1, lr=1e-2, print_freq=100,
              data_placement="host")

    def vec(tr):
        return np.concatenate([np.asarray(x, np.float32).ravel()
                               for x in jax.tree_util.tree_leaves(
                                   jax.device_get(tr.state.params))])

    t_default = LMTrainer(LMConfig(**kw)); t_default.fit()
    t_zero = LMTrainer(LMConfig(moe_aux_weight=0.0, **kw)); t_zero.fit()
    t_default2 = LMTrainer(LMConfig(moe_aux_weight=0.01, **kw))
    t_default2.fit()
    # explicit 0.01 == the default; 0.0 genuinely changes the objective
    np.testing.assert_allclose(vec(t_default2), vec(t_default), rtol=1e-6)
    assert not np.allclose(vec(t_zero), vec(t_default), rtol=1e-4)
