"""DL006 negative fixture: conformant emit() call sites."""


def emit_well(ledger, extra):
    ledger.emit("compile", program="train_step", flops=None)
    ledger.emit("run_end", steps=3, seconds=1.5, **extra)  # extras may splat
    return ledger


def forward_wrapper(led, event, fields):
    # declared forwarding wrapper: re-exposes emit()'s own signature
    return led.emit(event, **fields)  # ledger-schema: forward


def emit_fault_well(led):
    # round 10: obs.faults' injection record (site/step/spec required)
    led.emit("fault", site="hard_exit", step=3,
             spec="hard_exit@step=3,attempt=0", attempt=0)
