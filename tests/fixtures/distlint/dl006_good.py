"""DL006 negative fixture: conformant emit() call sites."""


def emit_well(ledger, extra):
    ledger.emit("compile", program="train_step", flops=None)
    ledger.emit("run_end", steps=3, seconds=1.5, **extra)  # extras may splat
    return ledger


def forward_wrapper(led, event, fields):
    # declared forwarding wrapper: re-exposes emit()'s own signature
    return led.emit(event, **fields)  # ledger-schema: forward


def emit_fault_well(led):
    # round 10: obs.faults' injection record (site/step/spec required)
    led.emit("fault", site="hard_exit", step=3,
             spec="hard_exit@step=3,attempt=0", attempt=0)


def emit_serving_well(ledger):
    # round 11: the serving events (engine.serve) — admission decision,
    # completed request, and paged-pool pressure snapshot
    ledger.emit("admit", rid=7, accepted=False, queue_depth=9,
                pages_free=0, reason="page_watermark")
    ledger.emit("request", rid=7, tokens=12, queue_wait_s=0.25,
                admit_ts=1.0, first_token_ts=1.5, finish_ts=2.0,
                prompt_len=8, ttft_s=0.5)
    # round 16: the pressure snapshot carries the prefix-sharing and
    # speculative-acceptance counters (shared/cow/hits required; the
    # spec_* trend fields ride as extras); round 19 adds the sp-sharded
    # pool width and the chunked-prefill backlog as required fields
    ledger.emit("kv_cache", pages_free=3, pages_used=13, active_seqs=4,
                shared_pages=2, cow_copies=1, prefix_hits=6,
                sharded_devices=4, chunks_pending=2,
                pages_total=16, high_water_used=16, slots=4, tick=40,
                spec_emitted=80, spec_slot_ticks=40, chunk_ticks=12)


def emit_scale_well(ledger):
    # round 13: elastic-capacity transitions (supervisor consensus +
    # engine preemption snapshot) — action/processes/epoch required
    ledger.emit("scale", action="shrink", processes=2, epoch=1,
                hosts=[0, 2], world_from=3)
    ledger.emit("scale", action="preempt_snapshot", processes=1, epoch=0,
                step=20)


def emit_fleet_well(ledger):
    # round 14: the fleet-simulation events (tpu_dist.sim.runner) —
    # scenario identity + periodic/final fleet rollups
    ledger.emit("scenario", name="ci", seed=7, hosts=3, ticks=200,
                tick_s=0.02)
    ledger.emit("fleet", hosts_live=3, goodput_ratio=None,
                slo_breaches=None, final=False)
    ledger.emit("fleet", hosts_live=0, goodput_ratio=0.31, slo_breaches=4,
                final=True)


def emit_span_well(ledger, tid, sid, attrs):
    # round 17: the request-trace span event (obs.reqtrace writes ids,
    # engine.serve / engine.kv_cache / sim.worker emit) — the seven
    # identity+interval fields are required; per-phase detail (bucket,
    # ticks, reason, ...) and the tracer's job/attempt/host stamp splat
    # as extras, exactly the serve.py call shape
    ledger.emit("span", trace_id=tid, span_id=sid, parent_id=None,
                name="queue", rid=7, start=1.25, end=1.5,
                queue_depth=3, tenant="t0", **attrs)
    ledger.emit("span", trace_id=tid, span_id=sid, parent_id=sid,
                name="decode", rid=7, start=1.5, end=2.0,
                ticks=8, tokens=8, spec_drafted=0, **attrs)


def emit_plan_well(ledger):
    # round 15: the step-plan events (tpu_dist.plan) — the engines' plan
    # stamp and tools/tune.py's per-device-kind search record
    ledger.emit("plan", source="plans.json", plan_hash="c456df519e8b",
                knobs={"quant": "int8"}, device_kind="cpu")
    ledger.emit("tune", device_kind="cpu", candidates=72,
                best_hash="c456df519e8b", best_step_s=0.0021,
                measured=True, peaks_nominal=False)


def emit_autoscale_well(ledger):
    # round 20: the autoscaling decision (obs.autoscale.emit_decision)
    # and the supervisor's applied follow-up — full attribution required
    # (tick and the retune's device count ride as extras)
    ledger.emit("scale_decision", decision="d0", direction="up",
                hosts_from=2, target_hosts=3, signal="queue_wait_ema_s",
                value=0.105, threshold=0.08, window_ticks=16,
                bundle=None, tick=48)
    ledger.emit("applied", decision="d0", action="expand", processes=3,
                epoch=1, plan_hash="31cea7bec68a", devices=6)


def emit_audit_well(ledger):
    # round 18: the program-audit event (analysis.proglint findings,
    # emitted by plan.compile's audit pass) — findings is the UNWAIVERED
    # count; the waived count and per-finding detail ride as extras
    ledger.emit("audit", program="train_step", mode="record", findings=0,
                waived=1, detail=[{"check": "PL003", "waived": True}])
