"""DL006 negative fixture: conformant emit() call sites."""


def emit_well(ledger, extra):
    ledger.emit("compile", program="train_step", flops=None)
    ledger.emit("run_end", steps=3, seconds=1.5, **extra)  # extras may splat
    return ledger


def forward_wrapper(led, event, fields):
    # declared forwarding wrapper: re-exposes emit()'s own signature
    return led.emit(event, **fields)  # ledger-schema: forward
