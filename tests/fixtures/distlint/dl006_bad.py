"""DL006 positive fixture: ledger emit() schema violations."""


def emit_badly(ledger, name, fields):
    ledger.emit("no_such_event", x=1)          # undeclared event
    ledger.emit(name, step=1)                  # computed event name
    ledger.emit("step", **fields)              # required fields in a splat
    ledger.emit()                              # no event at all


def emit_fault_badly(led):
    # round 10: the fault-injection event is schema-checked like the rest
    led.emit("fault", spec="hard_exit@step=3")  # missing site + step


def emit_serving_badly(ledger):
    # round 11: the serving events (engine.serve) are schema-checked too
    ledger.emit("request", rid=7, tokens=12)   # missing the timeline fields
    ledger.emit("kv_cache", pages_free=3)      # missing used/active_seqs
    # round 19: a pre-long-context snapshot shape — missing the now-
    # required sharded_devices/chunks_pending serving-plane fields
    ledger.emit("kv_cache", pages_free=3, pages_used=13, active_seqs=4,
                shared_pages=2, cow_copies=1, prefix_hits=6)


def emit_scale_badly(ledger):
    # round 13: the elasticity event without its world size / epoch
    ledger.emit("scale", action="expand")


def emit_fleet_badly(ledger):
    # round 14: the fleet-simulation events (tpu_dist.sim.runner) are
    # schema-checked like the rest
    ledger.emit("scenario", name="ci")               # missing seed/hosts/ticks
    ledger.emit("fleet", hosts_live=3)               # missing ratio/breaches


def emit_span_badly(ledger, ids):
    # round 17: the request-trace span event is schema-checked like the
    # rest — identity and interval must be explicit at the call site
    ledger.emit("span", name="queue", rid=7)     # missing ids + interval
    ledger.emit("span", **ids)                   # required fields in a splat


def emit_plan_badly(ledger):
    # round 15: the step-plan events (tpu_dist.plan) are schema-checked
    ledger.emit("plan", source="plans.json")     # missing plan_hash/knobs
    ledger.emit("tune", device_kind="v5e")       # missing candidates/best


def emit_autoscale_badly(ledger, dec):
    # round 20: the autoscaling decision + its applied follow-up are
    # schema-checked like the rest — attribution must be explicit
    ledger.emit("scale_decision", direction="up")  # missing attribution
    ledger.emit("applied", **dec)                  # required in a splat


def emit_audit_badly(ledger, meta):
    # round 18: the program-audit event (analysis.proglint via
    # plan.compile) is schema-checked like the rest
    ledger.emit("audit", program="train_step")   # missing mode + findings
    ledger.emit("audit", **meta)                 # required fields in a splat
