"""DL006 positive fixture: ledger emit() schema violations."""


def emit_badly(ledger, name, fields):
    ledger.emit("no_such_event", x=1)          # undeclared event
    ledger.emit(name, step=1)                  # computed event name
    ledger.emit("step", **fields)              # required fields in a splat
    ledger.emit()                              # no event at all
