"""DL104 negative fixture: flag-setting handler that chains its prior."""

import signal

_flag = {"term": False}
_PREV = {}


def _on_term(signum, frame):
    _flag["term"] = True               # just a flag; no io in the handler
    prev = _PREV.get("h")
    if callable(prev):
        prev(signum, frame)


def install():
    _PREV["h"] = signal.signal(signal.SIGTERM, _on_term)   # captured+chained
