"""DL003 negative fixture: declared axes and variable axis names."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def good_specs(mesh, axis):
    a = NamedSharding(mesh, P("data", "model"))
    b = P(None, "fsdp")
    c = P(("stage", "expert"), "seq")
    d = P(axis)                              # dynamic: not statically checked
    return a, b, c, d


def good_collective(x, axis_name):
    return jax.lax.psum(x, "data") + jax.lax.psum(x, axis_name)
