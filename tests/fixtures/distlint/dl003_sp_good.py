"""DL003 negative fixture (sp serving-parallel spellings): the same
call-site shapes spelled against the DECLARED 'sp' axis — the authority
learned it from parallel/mesh.py the moment SP_AXIS landed there."""

import jax
from jax.sharding import PartitionSpec as P


def good_gather(pages):
    return jax.lax.psum(pages, "sp")


def good_ownership():
    return jax.lax.axis_index("sp")


def good_pool_width(mesh, cfg):
    n = mesh.shape["sp"]
    return cfg.num_pages // n


def good_arena_spec(arena):
    return P("sp"), arena
