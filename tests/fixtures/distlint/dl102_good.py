"""DL102 negative fixture: snapshot under the lock, I/O outside it."""

import threading
import urllib.request


class PushSink:
    def __init__(self, url):
        self._lock = threading.Lock()
        self._buf = []
        self._url = url

    def sink(self, rec):
        with self._lock:
            self._buf.append(rec)

    def push(self):
        with self._lock:                # only the cheap snapshot inside
            rows = list(self._buf)
            self._buf.clear()
        for rec in rows:                # the slow half runs lock-free
            urllib.request.urlopen(self._url, data=rec)
