"""DL001 positive fixture: collectives reachable on a subset of processes.

Never imported or executed — linted only (tests/test_distlint.py). The
directory is excluded from tree walks (distlint SKIP_DIRS), so the
clean-tree sweep never sees these deliberate violations.
"""

import jax

from tpu_dist.data import assemble_global
from tpu_dist.engine import checkpoint as ckpt


def gather_on_main_only(sharding, host_batch):
    if jax.process_index() == 0:
        # only process 0 enters the collective assembly -> the other
        # hosts wait in their next collective forever
        return assemble_global(sharding, host_batch)
    return None


def save_after_guarded_return(state, path, is_main):
    if is_main:
        pass
    if jax.process_index() != 0:
        return None
    # everything from here on runs on process 0 only; the sharded-state
    # gather inside save_checkpoint is collective
    return ckpt.save_checkpoint(path, state, 0, 0.0, "lm", False)
