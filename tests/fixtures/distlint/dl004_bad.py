"""DL004 positive fixture: untraced side effects inside jitted code."""

import time
from functools import partial

import jax


@jax.jit
def decorated_step(state, batch):
    print("stepping", batch.shape)     # fires once at trace time, then never
    t0 = time.time()                   # constant-folded into the program
    return state, t0


@partial(jax.jit, donate_argnums=(0,))
def donated_step(state, batch):
    time.perf_counter()                # same hazard through partial(jit)
    return state


def make_step(ledger):
    def inner(state, batch):
        ledger.emit("step", step=0)    # a trace-time ledger write is a lie
        return state

    return jax.jit(inner)
