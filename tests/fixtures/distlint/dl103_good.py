"""DL103 negative fixture: daemon helpers, or a join on shutdown."""

import threading


def start_worker(q):
    t = threading.Thread(target=_pump, args=(q,), daemon=True)
    t.start()
    return t


def _pump(q):
    while True:
        q.get()


class Sampler:
    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def _run(self):
        pass

    def close(self):                     # the shutdown-path join
        self._thread.join(timeout=1.0)
