"""DL002 positive fixture: blocking host syncs inside a hot step loop.

``train_step`` is a real jit product, so the loop is hot with GRAPH
EVIDENCE (tier 2) and the full blocking set applies — including the
implicit-sync heuristics (np.asarray on a device value).
"""

import jax
import numpy as np

train_step = jax.jit(lambda s, i, l: (s, {"loss_sum": i, "count": l}))


def train_epoch(loader, state):
    for images, labels in loader:
        state, metrics = train_step(state, images, labels)
        loss_sum = np.asarray(metrics["loss_sum"])     # implicit device_get
        host = jax.device_get(metrics)                 # explicit sync
        count = host["count"].item()                   # .item() sync
        print(loss_sum / count)
    return state
