"""DL002 positive fixture: blocking host syncs inside a hot step loop."""

import jax
import numpy as np


def train_epoch(loader, step_fn, state):
    for images, labels in loader:
        state, metrics = step_fn(state, images, labels)
        loss_sum = np.asarray(metrics["loss_sum"])     # implicit device_get
        host = jax.device_get(metrics)                 # explicit sync
        count = host["count"].item()                   # .item() sync
        print(loss_sum / count)
    return state
