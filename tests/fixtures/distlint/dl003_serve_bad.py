"""DL003 positive fixture (serving-era spellings): mesh.shape[...]
subscripts and axis_size() with axis names the mesh never declared."""

import jax


def bad_pool_sizing(mesh, cfg):
    # 'modle' typo in the paged-pool sizing path: KeyError only when the
    # serve tick first sizes the axis on hardware
    tp = mesh.shape["modle"]
    return cfg.pages_total // tp


def bad_draft_span(x):
    # the spec-decode draft fan-out sized off a typo'd axis
    n = jax.lax.axis_size("dataa")
    return x * n
