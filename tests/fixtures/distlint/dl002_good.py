"""DL002 negative fixture: the drain-boundary pattern the engines use.

The reachability pass sees ``_drain`` from the hot loop (it is no longer
invisible for living outside the loop's lexical extent), so the sanctioned
sync point carries the same reasoned pin the engines' own drain
boundaries do — the pattern this fixture documents.
"""

import time

import jax


def train_epoch(loader, step_fn, state, meters):
    pending = []
    end = time.time()
    for i, (images, labels) in enumerate(loader):
        state, metrics = step_fn(state, images, labels)
        pending.append(metrics)            # queue device values, no sync
        if i % 10 == 0:
            _drain(pending, meters)        # the ONE sync per window
        meters.update("Time", time.time() - end)   # host clock: not blocking
        end = time.time()
    return state


def _drain(pending, meters):
    # the deliberate sync point lives OUTSIDE the hot-loop functions
    # distlint: disable=DL002 -- the sanctioned drain boundary: one fetch per window
    for m in jax.device_get(pending):
        meters.update("Loss", float(m["loss_sum"]))
    pending.clear()
