"""DL103 positive fixture: non-daemon threads nobody ever joins."""

import threading


def start_worker(q):
    t = threading.Thread(target=_pump, args=(q,))    # no daemon, no join
    t.start()
    return t


def _pump(q):
    while True:
        q.get()


class Sampler:
    def start(self):
        self._thread = threading.Thread(target=self._run)   # same hazard
        self._thread.start()

    def _run(self):
        pass
