"""DL007 negative fixture: rebinding (or not donating) is safe."""

import jax

step = jax.jit(lambda s, b: s, donate_argnums=(0,))


def train(state, batch):
    state = step(state, batch)         # rebind: the dead buffer is gone
    return state.step


def undonated(state, batch):
    f = jax.jit(lambda s, b: s)
    out = f(state, batch)
    return state, out                  # no donation: free to read
