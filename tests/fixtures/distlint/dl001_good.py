"""DL001 negative fixture: the same calls, collectively-safe shapes."""

import jax

from tpu_dist.data import assemble_global
from tpu_dist.engine import checkpoint as ckpt


def gather_everywhere(sharding, host_batch):
    out = assemble_global(sharding, host_batch)  # every process participates
    if jax.process_index() == 0:
        print("assembled")  # divergent guard around a PRINT is fine
    return out


def save_everywhere_then_log(state, path):
    p = ckpt.save_checkpoint(path, state, 0, 0.0, "lm", False)
    if jax.process_index() != 0:
        return None
    print("saved", p)  # only host-local work after the divergent return
    return p
