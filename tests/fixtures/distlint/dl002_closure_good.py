"""DL002 closure-seam negative: queue in the loop, drain after it."""

import jax

step = jax.jit(lambda s, b: s)


def train_epoch(batches, state):
    pending = []
    for b in batches:
        state, m = step(state, b)
        pending.append(m)                 # queue only: no per-step sync
    fetched = jax.device_get(pending)     # one drain after the loop
    return state, [m["loss"] for m in fetched]
