"""DL005 positive fixture: key reuse and global RNG state."""

import jax
import numpy as np


def correlated_noise(key, shape):
    noise = jax.random.normal(key, shape)
    jitter = jax.random.uniform(key, shape)   # key reused: correlated draws
    return noise, jitter


def hidden_global_state(shape):
    np.random.seed(0)                  # races with every other seed() caller
    return np.random.rand(*shape)      # per-process hidden stream
