"""DL007 positive fixture: donated buffers referenced after the call."""

import jax

step = jax.jit(lambda s, b: s, donate_argnums=(0,))


def train(state, batch):
    new_state = step(state, batch)
    return state.step, new_state       # donated 'state' read again: finding


def accumulate(state, batches):
    outs = []
    for b in batches:
        outs.append(step(state, b))    # donates 'state' once...
    return outs, state                 # ...then reads it: finding
