"""DL004 negative fixture: traced-safe debugging + host-side effects."""

import time

import jax


@jax.jit
def step(state, batch):
    jax.debug.print("loss {l}", l=batch.sum())   # runs per execution
    return state


def make_host_step(ledger):
    def inner(state, batch):
        return state

    wrapped = jax.jit(inner)

    def host_step(state, batch):
        t0 = time.time()               # host side of the dispatch: fine
        out = wrapped(state, batch)
        ledger.emit("step", step=0, loss=None, throughput=0.0, unit="x/s",
                    data_s=0.0, dispatch_s=time.time() - t0, device_s=0.0,
                    mfu=None)
        return out

    return host_step
