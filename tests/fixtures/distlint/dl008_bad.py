"""DL008 positive fixture: bare device_put on the hot step path.

``train_step`` is a real jit product, so the loop is hot with graph
evidence; the inline ``jax.device_put`` charges the upload to the step
loop's critical path (lexical finding) and ``stage()`` is called from the
loop body, so its device_put is caught by the reachability pass too.
"""

import jax

train_step = jax.jit(lambda s, b: s)


def stage(batch, sharding):
    return jax.device_put(batch, sharding)       # reachable from the loop


def train_epoch(loader, state, sharding):
    for batch in loader:
        dev = jax.device_put(batch, sharding)    # upload on the hot path
        state = train_step(state, dev)
        state = train_step(state, stage(batch, sharding))
    return state
