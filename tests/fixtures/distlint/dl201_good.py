"""DL201 negative fixture: branch collective sequences that match (or
contain no collectives at all) — the safe cond/switch shapes."""

import jax


def identical_sequences(pred, x):
    # both arms issue psum("data") then pmax("data"): any process pairing
    # is consistent regardless of the branch taken
    def hot(v):
        v = jax.lax.psum(v * 2.0, "data")
        return jax.lax.pmax(v, "data")

    def cold(v):
        v = jax.lax.psum(v * 0.5, "data")
        return jax.lax.pmax(v, "data")

    return jax.lax.cond(pred, hot, cold, x)


def no_collectives(pred, x):
    # pure element-wise branches: nothing to mismatch (the pp.py microbatch
    # gating shape — collectives stay OUTSIDE the cond)
    y = jax.lax.cond(pred, lambda v: v * 2.0, lambda v: v + 1.0, x)
    return jax.lax.psum(y, "data")


def padded_branch(pred, x):
    # the sanctioned fix for a one-armed reduce: the other arm issues the
    # SAME collective on a zero operand
    return jax.lax.cond(pred,
                        lambda v: jax.lax.psum(v, "data"),
                        lambda v: v + jax.lax.psum(v * 0.0, "data"), x)


def dynamic_branches(pred, fns, x):
    # branch list built at runtime: not statically resolvable, stays silent
    return jax.lax.switch(pred, fns, x)
