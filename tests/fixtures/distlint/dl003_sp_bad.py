"""DL003 positive fixture (sp serving-parallel spellings): the
long-context serving plane's 'sp' axis, misspelled at every call-site
shape the sharded pool actually uses — gather psum, axis_index
ownership tests, and mesh.shape sizing."""

import jax
from jax.sharding import PartitionSpec as P


def bad_gather(pages):
    # the sp page-gather's replication psum over a typo'd axis
    return jax.lax.psum(pages, "spp")


def bad_ownership():
    # the local-block-table ownership test against a typo'd axis
    return jax.lax.axis_index("sp_serve")


def bad_pool_width(mesh, cfg):
    # per-device page budget sized off an undeclared axis name
    n = mesh.shape["sq"]
    return cfg.num_pages // n


def bad_arena_spec(arena):
    # the arena sharding spec: 'sp' misspelled in PartitionSpec
    return P("spd"), arena
