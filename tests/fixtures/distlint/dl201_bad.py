"""DL201 positive fixture: cond/switch branches whose collective
sequences diverge — the statically-provable MPI-matching deadlock."""

import jax
from functools import partial


def asymmetric_order(pred, x):
    # both branches issue the SAME collectives but in OPPOSITE order: a
    # process taking the other arm pairs its psum with the peer's pmax
    def hot(v):
        v = jax.lax.psum(v, "data")
        return jax.lax.pmax(v, "data")

    def cold(v):
        v = jax.lax.pmax(v, "data")
        return jax.lax.psum(v, "data")

    return jax.lax.cond(pred, hot, cold, x)


def one_armed_collective(pred, x):
    # lambda branches: the true arm reduces, the false arm doesn't — the
    # excluded processes never enter the psum and the pod hangs
    return jax.lax.cond(pred,
                        lambda v: jax.lax.psum(v, "data"),
                        lambda v: v * 2.0, x)


def _gather_path(v):
    return jax.lax.all_gather(v, "model")


def _reduce_path(v):
    return jax.lax.psum(v, "model")


def divergent_switch(idx, x):
    # switch over helper refs resolved through the call graph: three
    # branches, three different collective sequences
    return jax.lax.switch(idx, [_gather_path, _reduce_path,
                                lambda v: v], x)


def partial_head(pred, x, scale):
    # partial() heads resolve to their wrapped callable
    return jax.lax.cond(pred, partial(_reduce_path),
                        lambda v: v + scale, x)
