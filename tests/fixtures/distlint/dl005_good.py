"""DL005 negative fixture: split-per-consumer keys, seeded generators."""

import jax
import numpy as np


def independent_noise(key, shape, train):
    k_noise, k_jitter = jax.random.split(key)
    noise = jax.random.normal(k_noise, shape)
    jitter = jax.random.uniform(k_jitter, shape)
    if train:
        extra = jax.random.bernoulli(jax.random.fold_in(key, 1), 0.5, shape)
    else:
        extra = jax.random.bernoulli(jax.random.fold_in(key, 2), 0.5, shape)
    return noise, jitter, extra


def branch_local_reuse(key, shape, flip):
    # only ONE arm executes: this is not a reuse
    if flip:
        return jax.random.normal(key, shape)
    else:
        return jax.random.uniform(key, shape)


def seeded_host_rng(shape, seed):
    rng = np.random.default_rng(seed)          # the sanctioned numpy path
    return rng.random(shape)
