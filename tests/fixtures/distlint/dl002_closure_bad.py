"""DL002 closure-seam fixture (the old false negative): a sync inside a
nested def called from the hot loop used to escape the lexical scan
because nested defs were excluded wholesale; the reachability pass makes
it decidable."""

import jax

step = jax.jit(lambda s, b: s)


def train_epoch(batches, state):
    def log(metrics):
        return metrics["loss"].item()     # runs every iteration: finding

    losses = []
    for b in batches:
        state, m = step(state, b)
        losses.append(log(m))
    return state, losses
