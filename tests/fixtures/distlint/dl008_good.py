"""DL008 negative fixture: uploads staged off the hot path.

One-time state placement BEFORE the loop is fine (DL008 only looks inside
hot loop bodies and functions reachable from them), and batches arriving
already device-resident (the loader's prefetcher staged them on its
producer thread) give the step loop nothing to upload.
"""

import jax

train_step = jax.jit(lambda s, b: s)


def train_epoch(prefetched, state, sharding):
    state = jax.device_put(state, sharding)      # one-time, before the loop
    for batch in prefetched:                     # already device-resident
        state = train_step(state, batch)
    return state
