"""DL003 negative fixture (serving-era spellings): declared axes and
dynamic keys in mesh.shape[...] / axis_size() call sites."""

import jax
import numpy as np


def good_pool_sizing(mesh, cfg, axis):
    tp = mesh.shape["model"]                 # declared axis
    dyn = mesh.shape[axis]                   # dynamic key: not checked
    return cfg.pages_total // (tp * dyn)


def good_draft_span(x, axis_name):
    n = jax.lax.axis_size("data")            # declared axis
    m = jax.lax.axis_size(axis_name)         # dynamic: not checked
    return x * n * m


def int_shape_subscripts(batch):
    # array .shape subscripts are ints — never axis names, never flagged
    rows = batch.shape[0]
    return np.zeros((rows, batch.shape[1]))
