"""DL104 positive fixture: io in the handler body, dropped prior hook."""

import logging
import signal
import sys


def _on_term(signum, frame):
    logging.error("terminating")       # logging is not reentrant: finding
    sys.stderr.flush()                 # flush chain in a handler: finding
    raise SystemExit(1)


def install():
    signal.signal(signal.SIGTERM, _on_term)   # prior handler dropped: finding
