"""DL101 negative fixture: the shipped PR-5 RLock fix shape.

A plain (non-reentrant) threading.Lock guards both the main-thread emit
site and a method the SIGTERM handler reaches: a signal landing while the
main thread is inside emit() re-enters cleanly in finalize().
"""

import signal
import threading


class Recorder:
    def __init__(self):
        self._lock = threading.RLock()     # reentrant: the PR-5 fix
        self._rows = []
        self._prev = signal.signal(signal.SIGTERM, self._on_sigterm)

    def emit(self, row):                   # main-thread emit site
        with self._lock:
            self._rows.append(row)

    def finalize(self):
        with self._lock:                   # handler-reachable acquire
            self._rows.append("end")

    def _on_sigterm(self, signum, frame):
        self.finalize()
        if callable(self._prev):
            self._prev(signum, frame)


def main():
    rec = Recorder()
    rec.emit("step")


if __name__ == "__main__":
    main()
