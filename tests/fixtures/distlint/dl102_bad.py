"""DL102 positive fixture: blocking I/O while holding the sink lock."""

import threading
import time
import urllib.request


class PushSink:
    def __init__(self, url):
        self._lock = threading.Lock()
        self._buf = []
        self._url = url

    def sink(self, rec):                # the emit fan-out half
        with self._lock:
            self._buf.append(rec)

    def push(self):
        with self._lock:
            for rec in self._buf:       # HTTP under the shared lock: finding
                urllib.request.urlopen(self._url, data=rec)
            time.sleep(0.1)             # sleep under the lock: finding
            self._buf.clear()
