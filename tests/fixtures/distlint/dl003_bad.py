"""DL003 positive fixture: axis names the mesh never declared."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def bad_specs(mesh):
    # 'modle' is a typo for 'model' — every CPU test passes, XLA rejects
    # it at trace time on the pod
    a = NamedSharding(mesh, P("modle"))
    b = P(None, "batch")                     # torch habit; axis is 'data'
    return a, b


def bad_collective(x):
    return jax.lax.psum(x, "dataa")          # typo'd collective axis
