"""Transformer LM engine: DP / DPxTP / DPxSP parallelism equivalence + training.

The core guarantee the reference could never state (it had no model or
sequence parallelism): the SAME weights and data produce the SAME loss and
updates under every parallelism layout.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dist.engine.lm_steps import (make_lm_batches, make_lm_sp_train_step,
                                      make_lm_train_step)
from tpu_dist.engine.state import TrainState
from tpu_dist.models.transformer import tiny_lm
from tpu_dist.ops import make_optimizer
from tpu_dist.parallel.mesh import make_mesh, replicated
from tpu_dist.parallel.tp import lm_param_specs, shard_lm_params

B, L, V = 8, 64, 256


@pytest.fixture(scope="module")
def setup():
    rng_np = np.random.default_rng(0)
    tokens = rng_np.integers(0, V, (B, L + 1)).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    model = tiny_lm(vocab_size=V, max_len=L)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.01, 0.9, 0.0, steps_per_epoch=100)
    return model, params, tx, inputs, targets


def _loss(m):
    # distlint: disable=DL002 -- test helper: drains one metrics tree for assertions
    m = jax.device_get(m)
    return float(m["loss_sum"]) / float(m["count"])


def _run_dp(setup_data, mesh):
    model, params, tx, inputs, targets = setup_data
    st = jax.device_put(TrainState.create(params, {}, tx), replicated(mesh))
    step = make_lm_train_step(model, tx, mesh, donate=False)
    sh = NamedSharding(mesh, P("data"))
    s, m = step(st, jax.device_put(inputs, sh), jax.device_put(targets, sh),
                jax.random.PRNGKey(1))
    return s, _loss(m)


def test_dp_trains(setup):
    mesh = make_mesh((8,), ("data",))
    _, loss = _run_dp(setup, mesh)
    assert 4.0 < loss < 8.0  # ~ln(256)=5.5 at init


def test_tp_matches_dp(setup):
    model, params, tx, inputs, targets = setup
    _, loss_dp = _run_dp(setup, make_mesh((8,), ("data",)))

    mesh = make_mesh((4, 2), ("data", "model"))
    specs = jax.tree.leaves(lm_param_specs(params),
                            is_leaf=lambda x: isinstance(x, P))
    assert sum(s != P() for s in specs) >= 8  # qkv/proj/mlp x layers + head
    st = TrainState.create(params, {}, tx)
    st = TrainState(step=jax.device_put(st.step, NamedSharding(mesh, P())),
                    params=shard_lm_params(mesh, st.params), batch_stats={},
                    opt_state=jax.device_put(st.opt_state,
                                             NamedSharding(mesh, P())),
                    loss_scale=None)
    step = make_lm_train_step(model, tx, mesh, donate=False)
    sh = NamedSharding(mesh, P("data"))
    _, m = step(st, jax.device_put(inputs, sh), jax.device_put(targets, sh),
                jax.random.PRNGKey(1))
    assert _loss(m) == pytest.approx(loss_dp, abs=2e-4)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_sp_ring_matches_dp(setup):
    model, params, tx, inputs, targets = setup
    _, loss_dp = _run_dp(setup, make_mesh((8,), ("data",)))

    mesh = make_mesh((2, 4), ("data", "seq"))
    st = jax.device_put(TrainState.create(params, {}, tx), replicated(mesh))
    step = make_lm_sp_train_step(partial(tiny_lm, vocab_size=V, max_len=L),
                                 tx, mesh, donate=False)
    sh = NamedSharding(mesh, P("data", "seq"))
    s, m = step(st, jax.device_put(inputs, sh), jax.device_put(targets, sh),
                jax.random.PRNGKey(1))
    assert _loss(m) == pytest.approx(loss_dp, abs=2e-4)
    # params updated identically to the DP run (replicated, exact psum'd grads)
    s_dp, _ = _run_dp(setup, make_mesh((8,), ("data",)))
    fa = np.concatenate([np.asarray(x).ravel() for x in jax.tree.leaves(s.params)])
    fb = np.concatenate([np.asarray(x).ravel() for x in jax.tree.leaves(s_dp.params)])
    np.testing.assert_allclose(fa, fb, rtol=2e-3, atol=1e-5)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_lm_learns_structured_sequence():
    """Convergence smoke: deterministic next-token rule is learnable fast."""
    mesh = make_mesh((8,), ("data",))
    model = tiny_lm(vocab_size=64, max_len=32)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 32), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=1000)
    st = jax.device_put(TrainState.create(params, {}, tx), replicated(mesh))
    step = make_lm_train_step(model, tx, mesh, donate=False)
    sh = NamedSharding(mesh, P("data"))

    rng_np = np.random.default_rng(1)
    start = rng_np.integers(0, 64, (16, 1))
    rows = [start]
    for _ in range(32):
        rows.append((rows[-1] * 3 + 1) % 64)
    tokens = np.concatenate(rows, axis=1).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    inputs = jax.device_put(inputs, sh)
    targets = jax.device_put(targets, sh)

    losses = []
    for i in range(25):
        st, m = step(st, inputs, targets, jax.random.PRNGKey(2))
        losses.append(_loss(m))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_fsdp_matches_dp_and_stays_sharded(setup):
    """ZeRO-3-style placement: same step fn, same math, sharded memory."""
    from tpu_dist.parallel.fsdp import fsdp_specs, shard_state_fsdp

    model, params, tx, inputs, targets = setup
    s_dp, loss_dp = _run_dp(setup, make_mesh((8,), ("data",)))

    mesh = make_mesh((8,), ("data",))
    st = shard_state_fsdp(mesh, TrainState.create(params, {}, tx))
    emb_spec = st.params["tok_emb"]["embedding"].sharding.spec
    assert emb_spec[0] == "data"  # actually sharded
    step = make_lm_train_step(model, tx, mesh, donate=False)
    sh = NamedSharding(mesh, P("data"))
    s_f, m = step(st, jax.device_put(inputs, sh), jax.device_put(targets, sh),
                  jax.random.PRNGKey(1))
    assert _loss(m) == pytest.approx(loss_dp, rel=1e-5)
    fa = np.concatenate([np.asarray(x).ravel()
                         for x in jax.tree.leaves(jax.device_get(s_dp.params))])
    fb = np.concatenate([np.asarray(x).ravel()
                         for x in jax.tree.leaves(jax.device_get(s_f.params))])
    np.testing.assert_allclose(fa, fb, rtol=1e-4, atol=1e-6)
    # updates must not silently re-replicate the weights
    post = s_f.params["tok_emb"]["embedding"].sharding.spec
    assert post and post[0] == "data"
    # small leaves (norm scales) stay replicated by the min_size rule
    specs = fsdp_specs({"tiny": np.zeros((8,))}, 8)
    assert specs["tiny"] == P()


def test_lm_eval_step_exact_metrics():
    """Eval metric sums equal a hand-computed forward (counts, not means)."""
    import numpy as np
    from tpu_dist.engine.lm_steps import (lm_loss_and_metrics,
                                          make_lm_batches, make_lm_eval_step)
    from tpu_dist.models.transformer import tiny_lm
    from tpu_dist.parallel.mesh import make_mesh

    lm = tiny_lm(vocab_size=32, num_layers=1, d_model=32, num_heads=2,
                 max_len=16)
    params = lm.init({"params": jax.random.PRNGKey(0)},
                     jnp.zeros((1, 16), jnp.int32), train=False)["params"]
    tokens = np.random.default_rng(0).integers(0, 32, (8, 17)).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    mesh = make_mesh((8,), ("data",))
    step = make_lm_eval_step(lm, mesh)
    m = jax.device_get(step(params, jnp.asarray(inputs), jnp.asarray(targets),
                            jnp.ones((inputs.shape[0],), jnp.float32)))

    logits = lm.apply({"params": params}, jnp.asarray(inputs), train=False)
    _, ref = lm_loss_and_metrics(logits, jnp.asarray(targets),
                                 jnp.ones(targets.shape, jnp.float32))
    assert float(m["count"]) == targets.size
    assert float(m["loss_sum"]) == pytest.approx(float(ref["loss_sum"]),
                                                 rel=1e-5)
    assert float(m["correct1"]) == float(ref["correct1"])
