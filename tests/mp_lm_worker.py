"""One worker process of a loopback multi-process LM run (test_multiprocess).

The LM twin of mp_worker.py: each process owns a slice of virtual CPU
devices, rendezvouses through tpu_dist.parallel.launch, and drives the SAME
LMTrainer as single-process runs over the SAME synthetic corpus — the
N-process bit-match check the image engine has had since round 2, applied to
the token path (sampler rows, windows, distributed eval included).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    out = os.environ["TPU_DIST_TEST_OUT"]
    local_devices = int(os.environ.get("TPU_DIST_LOCAL_DEVICES", "2"))

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist._compat import set_cpu_device_count
    set_cpu_device_count(local_devices)

    from tpu_dist.parallel import launch

    info = launch.initialize()
    expected = int(os.environ.get("TPU_DIST_EXPECT_PROCS", "1"))
    assert jax.process_count() == expected, (jax.process_count(), expected)

    import numpy as np

    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    cfg = LMConfig(
        batch_size=8, seq_len=32, d_model=32, num_layers=1, num_heads=2,
        vocab_size=64, synth_tokens=2000, seed=5, print_freq=100, epochs=1,
        lr=1e-2, checkpoint_dir=os.path.join(out, "ckpt"),
        steps_per_dispatch=int(os.environ.get("TPU_DIST_TEST_K", "1")),
        loss_chunk=int(os.environ.get("TPU_DIST_TEST_LOSS_CHUNK", "0")),
        data_placement=os.environ.get("TPU_DIST_TEST_PLACEMENT", "auto"))
    trainer = LMTrainer(cfg)
    best_ppl = trainer.fit()

    if jax.process_index() == 0:
        leaves = jax.tree_util.tree_leaves(
            jax.device_get(trainer.state.params))
        np.savez(os.path.join(out, "params.npz"),
                 **{f"p{i}": np.asarray(x, np.float32)
                    for i, x in enumerate(leaves)})
        with open(os.path.join(out, "result.json"), "w") as f:
            json.dump({"best_ppl": float(best_ppl),
                       "process_count": jax.process_count(),
                       "method": info.method,
                       "step": int(jax.device_get(trainer.state.step))}, f)


if __name__ == "__main__":
    main()
