"""One worker of the 2-process skew-monitor test (tests/test_obs.py).

Each process records synthetic per-step timings into obs.skew.SkewMonitor;
process 1 reports an artificially slower step time, so the allgathered skew
stats must finger process 1 as the straggler on EVERY process. Exercises
the real cross-process ``multihost_utils.process_allgather`` path the
single-process tests can't reach."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    out = os.environ["TPU_DIST_TEST_OUT"]
    local_devices = int(os.environ.get("TPU_DIST_LOCAL_DEVICES", "2"))

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist._compat import set_cpu_device_count
    set_cpu_device_count(local_devices)

    from tpu_dist.parallel import launch

    launch.initialize()
    rank = jax.process_index()

    from tpu_dist.obs.ledger import Ledger, per_process_path
    from tpu_dist.obs.skew import SkewMonitor

    ledger = Ledger(per_process_path(os.path.join(out, "skew.jsonl"), rank),
                    process_index=rank)
    mon = SkewMonitor(every=2, ledger=ledger)
    # process 1 is the injected straggler: 3x the step time, more data wait
    step_s = 0.010 if rank == 0 else 0.030
    stats = None
    for step in range(4):
        s = mon.record(step, step_s, data_s=step_s / 2)
        stats = s or stats
    ledger.close()
    assert stats is not None, "no exchange happened"
    with open(os.path.join(out, f"skew-result-{rank}.json"), "w") as f:
        json.dump({"rank": rank, "stats": stats,
                   "process_count": jax.process_count()}, f)


if __name__ == "__main__":
    main()
