"""Observability subsystem (round 6): ledger / tracer / skew / watchdog.

Covers: schema round-trip for every declared event type, tracer span
nesting + accumulation, watchdog firing on an injected stall (and staying
silent on a healthy loop) WITHOUT killing the run, the skew monitor's
straggler math (single-process inline; 2 real processes via mp_obs_worker
behind the CPU_MULTIPROCESS gate), both engines' CPU smoke runs producing
fully-populated step records, the epoch-CSV-as-sink parity, and the static
schema checker as a plain test (tier-1 schema-drift tripwire)."""

import json
import os
import threading
import time

import numpy as np
import pytest

from tpu_dist.obs import (EVENT_SCHEMA, EpochCsvSink, Ledger, ProgressSink,
                          SkewMonitor, StepTracer, Watchdog,
                          per_process_path, phase_totals, read_ledger)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- ledger
def _required_stub(event):
    """A value for every required field of ``event`` (None is legal)."""
    return {k: None for k in EVENT_SCHEMA[event]}


def test_ledger_schema_roundtrip_every_event(tmp_path):
    path = str(tmp_path / "run.jsonl")
    led = Ledger(path)
    for event in EVENT_SCHEMA:
        led.emit(event, **_required_stub(event))  # ledger-schema: forward
    led.close()
    recs = read_ledger(path)  # validates: declared event + required fields
    assert [r["event"] for r in recs] == list(EVENT_SCHEMA)
    for r in recs:
        assert r["ts"] > 0 and r["pid"] == 0


def test_ledger_run_start_captures_config_and_mesh(tmp_path):
    path = str(tmp_path / "run.jsonl")
    led = Ledger(path)
    led.emit("run_start", kind="test", config={"lr": 0.1, "arch": "lenet"},
             mesh={"data": 4, "model": 2}, devices=["cpu"], process_count=1)
    led.close()
    (rec,) = read_ledger(path)
    assert rec["config"]["arch"] == "lenet"
    assert rec["mesh"] == {"data": 4, "model": 2}


def test_ledger_rejects_undeclared_event_and_missing_fields(tmp_path):
    led = Ledger(str(tmp_path / "x.jsonl"))
    with pytest.raises(ValueError, match="undeclared"):
        led.emit("not_an_event", foo=1)  # ledger-schema: forward
    with pytest.raises(ValueError, match="missing required"):
        led.emit("step", step=0)  # ledger-schema: forward
    led.close()


def test_ledger_pathless_sink_only_and_thread_safe():
    seen = []
    led = Ledger(None)
    led.add_sink(seen.append)

    def spam():
        for i in range(50):
            led.emit("hbm", bytes_in_use=i)

    threads = [threading.Thread(target=spam) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(seen) == 200
    assert led.last["event"] == "hbm"
    led.close()


def test_per_process_path():
    assert per_process_path("run.jsonl", 0) == "run.jsonl"
    assert per_process_path("run.jsonl", 3) == "run.p3.jsonl"
    assert per_process_path("/a/b/tele.csv", 1) == "/a/b/tele.p1.csv"
    assert per_process_path("", 2) == ""


def test_epoch_csv_sink_renders_legacy_row(tmp_path):
    """The cookbook-parity CSV row is a VIEW of the ledger's epoch event:
    [wall_start, seconds, rate, hbm] — identical to what the loops wrote
    inline through round 5."""
    import csv

    path = str(tmp_path / "ep.csv")
    led = Ledger(None)
    led.add_sink(EpochCsvSink(path))
    led.emit("epoch", epoch=0, start_ts=123.5, seconds=7.25,
             throughput=1234.56, unit="img/s", loss=0.5, hbm_bytes=999)
    led.emit("epoch", epoch=1, start_ts=130.75, seconds=6.0,
             throughput=2000.0, unit="img/s", loss=0.4, hbm_bytes=None)
    led.close()
    rows = list(csv.reader(open(path)))
    assert rows == [["123.5", "7.25", "1234.6", "999"],
                    ["130.75", "6.0", "2000.0", ""]]


def test_all_none_records_render_without_crashing(tmp_path):
    """The schema pins PRESENCE, not non-nullness — every renderer
    (ProgressSink, ledger_report.summarize) must survive records whose
    required fields are all None (e.g. a backend with no counters)."""
    path = str(tmp_path / "n.jsonl")
    led = Ledger(path)
    for event in EVENT_SCHEMA:
        led.emit(event, **_required_stub(event))  # ledger-schema: forward
    led.close()
    recs = read_ledger(path)
    sink = ProgressSink(printer=lambda s: None)
    for r in recs:
        sink(r)
    from tools.ledger_report import summarize

    summarize(recs, out=lambda s: None)


def test_watchdog_beat_derives_interdrain_durations():
    """beat() (the loops' drain-point signal) self-derives durations: the
    first beat only arms, later beats append the inter-beat gap, and a
    beat after pause() re-arms without polluting the median with the
    eval-phase gap."""
    wd = Watchdog(factor=2.0, min_timeout_s=0.01, poll_s=5.0)
    wd.beat()  # arms only
    assert len(wd._durations) == 0
    time.sleep(0.05)
    wd.beat()
    assert len(wd._durations) == 1 and wd._durations[0] >= 0.04
    wd.pause()
    time.sleep(0.1)  # an eval-sized gap that must NOT enter the median
    wd.beat()
    assert len(wd._durations) == 1  # re-armed, no new duration
    time.sleep(0.03)
    wd.beat()
    assert len(wd._durations) == 2 and wd._durations[-1] < 0.09
    wd.stop()


def test_progress_sink_renders_step_line():
    lines = []
    sink = ProgressSink(printer=lines.append)
    sink({"event": "step", "step": 3, "loss": 1.25, "throughput": 1000.0,
          "unit": "tok/s", "mfu": 0.5, "data_s": 0.1, "dispatch_s": 0.2,
          "device_s": 0.3})
    assert "step 3" in lines[0] and "MFU 50.0%" in lines[0]
    assert "1,000 tok/s" in lines[0]


# ---------------------------------------------------------------- tracer
def test_tracer_span_nesting_and_accumulation():
    tr = StepTracer()
    with tr.span("data"):
        time.sleep(0.02)
        with tr.span("decode"):
            time.sleep(0.02)
    with tr.span("data"):  # accumulates into the same key
        time.sleep(0.01)
    ph = tr.pop()
    assert set(ph) == {"data", "data/decode"}
    # parent includes the child (wall-clock truth), second span adds on
    assert ph["data"] >= ph["data/decode"] >= 0.02
    assert ph["data"] >= 0.03
    # pop() reset
    assert tr.pop() == {}
    tr.add("device", 1.5)
    tr.add("device", 0.5)
    assert tr.pop() == {"device": 2.0}


def test_tracer_span_annotation_flag_off_by_default():
    # annotate=False must not import/require a live profiler
    tr = StepTracer(annotate=False)
    with tr.span("dispatch"):
        pass
    assert "dispatch" in tr.pop()


# -------------------------------------------------------------- watchdog
def test_watchdog_fires_on_stall_without_killing_run(tmp_path):
    import io

    path = str(tmp_path / "wd.jsonl")
    led = Ledger(path)
    err = io.StringIO()
    wd = Watchdog(factor=2.0, ledger=led, min_timeout_s=0.05, poll_s=0.02,
                  stream=err)
    for _ in range(5):
        wd.step_done(0.02)
    time.sleep(0.5)  # the injected stall: no step completes
    assert wd.stall_count == 1  # fired ONCE per stall, not per poll
    dump = err.getvalue()
    assert "NO STEP COMPLETED" in dump
    assert "tpu-dist-watchdog" not in dump.split("--- thread")[0]
    assert "--- thread" in dump  # stack dump includes thread frames
    # the run is NOT killed: stepping resumes and re-arms cleanly
    wd.step_done(0.02)
    time.sleep(0.1)
    assert wd.stall_count == 2  # a second stall fires again after re-arm
    wd.stop()
    led.close()
    stalls = [r for r in read_ledger(path) if r["event"] == "stall"]
    assert len(stalls) == 2
    assert stalls[0]["idle_s"] >= 0.05
    assert "--- thread" in stalls[0]["stacks"]


def test_watchdog_silent_on_healthy_loop_and_when_paused(tmp_path):
    led = Ledger(str(tmp_path / "wd2.jsonl"))
    wd = Watchdog(factor=2.0, ledger=led, min_timeout_s=0.05, poll_s=0.02)
    for _ in range(20):  # healthy cadence well under the threshold
        wd.step_done(0.01)
        time.sleep(0.01)
    assert wd.stall_count == 0
    wd.pause()  # eval/ckpt phase: no steps complete, by design
    time.sleep(0.3)
    assert wd.stall_count == 0
    wd.stop()
    led.close()
    assert not [r for r in read_ledger(led.path) if r["event"] == "stall"]


# ------------------------------------------------------------------ skew
def test_skew_monitor_single_process(tmp_path):
    led = Ledger(str(tmp_path / "skew.jsonl"))
    mon = SkewMonitor(every=3, ledger=led)
    assert mon.record(0, 0.01) is None  # not at the boundary yet
    assert mon.record(1, 0.01) is None
    stats = mon.record(2, 0.02, data_s=0.005)
    assert stats is not None
    assert stats["straggler"] == 0 and stats["n_procs"] == 1
    assert stats["spread_s"] == 0.0
    assert stats["p50_s"] == pytest.approx(np.mean([0.01, 0.01, 0.02]))
    led.close()
    (rec,) = [r for r in read_ledger(led.path) if r["event"] == "skew"]
    assert rec["step"] == 2 and rec["straggler"] == 0


def test_skew_monitor_two_real_processes(tmp_path):
    """Straggler detection over an actual process boundary: process 1
    reports 3x step times; every process's allgathered stats must agree
    that process 1 is the straggler (reuses the mp_worker spawn pattern)."""
    from tpu_dist._compat import CPU_MULTIPROCESS
    if not CPU_MULTIPROCESS:
        pytest.skip("this jax's CPU backend has no multi-process "
                    "computations (_compat.CPU_MULTIPROCESS)")
    from test_multiprocess import run_workers  # tests/ is on sys.path

    worker = os.path.join(ROOT, "tests", "mp_obs_worker.py")
    outdir = run_workers(str(tmp_path), "skew", nprocs=2, local_devices=2,
                         worker=worker)
    for rank in (0, 1):
        with open(os.path.join(outdir, f"skew-result-{rank}.json")) as f:
            res = json.load(f)
        assert res["process_count"] == 2
        assert res["stats"]["n_procs"] == 2
        assert res["stats"]["straggler"] == 1  # the injected slow process
        assert res["stats"]["spread_s"] == pytest.approx(0.020, abs=1e-6)
    # each process wrote its OWN ledger file (.pN suffix for non-main)
    assert os.path.exists(os.path.join(outdir, "skew.jsonl"))
    assert os.path.exists(os.path.join(outdir, "skew.p1.jsonl"))
    # acceptance: the two REAL per-process ledgers merge into one valid
    # Chrome trace with a lane per process
    from tools.trace_merge import merge_ledgers

    trace = json.loads(json.dumps(
        merge_ledgers([os.path.join(outdir, "skew.jsonl"),
                       os.path.join(outdir, "skew.p1.jsonl")])))
    assert trace["otherData"]["processes"] == 2
    assert {e["pid"] for e in trace["traceEvents"]} == {0, 1}


# -------------------------------------------------- engine smoke (CPU)
def _assert_step_records_complete(recs, unit):
    steps = [r for r in recs if r["event"] == "step"]
    assert steps, "no step events in ledger"
    for r in steps:
        for k in ("data_s", "dispatch_s", "device_s", "mfu", "throughput",
                  "loss"):
            assert r[k] is not None, (k, r)
        # the fused health probes (obs.health) ride every step record
        for k in ("grad_norm", "nonfinite_count", "update_norm"):
            assert r[k] is not None, (k, r)
        assert r["nonfinite_count"] == 0  # a healthy smoke run
        assert r["unit"] == unit
    assert phase_totals(steps)["dispatch_s"] > 0
    return steps


def _assert_run_shape(recs):
    events = [r["event"] for r in recs]
    assert events[0] == "run_start" and events[-1] == "run_end"
    assert "compile" in events and "epoch" in events and "eval" in events
    # cost attribution rides the compile probe (obs.attr): buckets with
    # real flops, matmul (or attention) among them
    (cm,) = [r for r in recs if r["event"] == "cost_model"]
    assert cm["total_flops"] > 0 and cm["buckets"]
    assert any(c in cm["buckets"] for c in ("matmul", "attention"))
    run = recs[0]
    assert run["config"] and run["devices"] and run["mesh"]
    # crash-safe shutdown: a clean run stamps status=ok, and the registry
    # snapshot lands just before run_end
    assert recs[-1]["status"] == "ok"
    assert events[-2] == "metrics_snapshot"
    assert recs[-2]["metrics"]["tpu_dist_steps_total"]


def test_image_engine_ledger_smoke(tmp_path):
    """Acceptance: a CPU run of the image engine with ledger_path set
    yields step records with non-null phase breakdown, MFU and throughput,
    and tools/ledger_report renders the file."""
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine.loop import Trainer

    path = str(tmp_path / "img.jsonl")
    cfg = TrainConfig(arch="lenet", dataset="synthetic", epochs=1,
                      batch_size=16, workers=1, print_freq=2, seed=0,
                      synth_train_size=64, synth_val_size=32,
                      checkpoint_dir=str(tmp_path / "ck"),
                      ledger_path=path, log_csv=str(tmp_path / "ep.csv"),
                      skew_every=2)
    Trainer(cfg).fit()
    recs = read_ledger(path)
    _assert_run_shape(recs)
    _assert_step_records_complete(recs, "img/s")
    assert [r for r in recs if r["event"] == "skew"]
    assert [r for r in recs if r["event"] == "ckpt"]
    # the legacy CSV rendered as a sink, same values as the epoch event
    import csv

    (ep,) = [r for r in recs if r["event"] == "epoch"]
    (row,) = list(csv.reader(open(tmp_path / "ep.csv")))
    assert float(row[0]) == pytest.approx(ep["start_ts"])
    assert float(row[2]) == pytest.approx(round(ep["throughput"], 1))
    # the report tool renders it
    from tools.ledger_report import summarize

    lines = []
    counts = summarize(recs, out=lines.append)
    assert counts["steps"] > 0 and counts["epochs"] == 1
    assert any("phase time share" in ln for ln in lines)


def test_lm_engine_ledger_smoke(tmp_path):
    """Acceptance twin for the LM engine, windowed (K>1) path included —
    plus the live-metrics acceptance: a curl-equivalent scrape of the
    Prometheus endpoint DURING the run returns parseable text carrying
    step throughput, MFU, and the stall/health-trip counters."""
    import socket
    import urllib.request

    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    path = str(tmp_path / "lm.jsonl")
    tr = None
    for _ in range(5):  # free-port probe is TOCTOU; retry on the rare race
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        cfg = LMConfig(epochs=1, batch_size=8, seq_len=32, vocab_size=64,
                       num_layers=1, d_model=32, num_heads=2,
                       synth_tokens=4096, print_freq=4, seed=0,
                       steps_per_dispatch=3, ledger_path=path,
                       metrics_port=port)
        tr = LMTrainer(cfg)
        if tr.obs.metrics_server is not None:
            break
        os.remove(path)  # the lost race left a stale ledger; start clean
    assert tr.obs.metrics_server is not None
    scraped = {}

    def scrape_mid_run(rec):
        # the epoch event lands mid-run (before run_end closes the
        # endpoint): scrape exactly then, deterministically
        if rec.get("event") == "epoch" and "text" not in scraped:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                scraped["text"] = r.read().decode()

    tr.obs.ledger.add_sink(scrape_mid_run)
    tr.fit()
    from test_metrics import assert_prometheus_parseable

    text = scraped["text"]
    assert_prometheus_parseable(text)
    assert "tpu_dist_steps_total" in text and "tpu_dist_mfu" in text
    assert 'tpu_dist_step_throughput{unit="tok/s"}' in text
    assert "tpu_dist_stalls_total 0" in text
    assert 'tpu_dist_health_trips_total{kind="nonfinite"} 0' in text
    recs = read_ledger(path)
    _assert_run_shape(recs)
    steps = _assert_step_records_complete(recs, "tok/s")
    # the windowed path records K-step dispatches
    assert max(r["steps_in_dispatch"] for r in steps) == 3
    (ep,) = [r for r in recs if r["event"] == "epoch"]
    assert ep["unit"] == "tok/s" and ep["ppl"] > 0


def test_crash_safe_run_end_stamps_status(tmp_path):
    """The crash-shutdown satellite: an unhandled exception inside the
    loop reaches run_end through fit()'s finally with status='crashed'
    and a truncated traceback — and the line-buffered JSONL means every
    prior event already survived on disk."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    path = str(tmp_path / "crash.jsonl")
    cfg = LMConfig(epochs=1, batch_size=8, seq_len=32, vocab_size=64,
                   num_layers=1, d_model=32, num_heads=2, synth_tokens=2048,
                   print_freq=2, seed=0, ledger_path=path)
    tr = LMTrainer(cfg)

    def boom(epoch=0):
        raise RuntimeError("injected mid-run crash")

    tr.validate = boom  # dies after the train epoch, inside fit()
    with pytest.raises(RuntimeError, match="injected"):
        tr.fit()
    recs = read_ledger(path)
    (end,) = [r for r in recs if r["event"] == "run_end"]
    assert end["status"] == "crashed"
    assert "injected mid-run crash" in end["error"]
    assert [r for r in recs if r["event"] == "step"]  # prior events intact
    # the guard disarmed cleanly (compare the underlying functions — a
    # bound method is a fresh object per attribute access, so `is`
    # against tr.obs._excepthook would be vacuous)
    import signal as _signal
    import sys as _sys

    from tpu_dist.obs import RunObs

    assert tr.obs._prev_excepthook is None
    assert getattr(_sys.excepthook, "__func__", None) \
        is not RunObs._excepthook
    assert getattr(_signal.getsignal(_signal.SIGTERM), "__func__", None) \
        is not RunObs._on_sigterm


def test_generate_ledger_decode_event(tmp_path):
    import jax.numpy as jnp

    from tpu_dist.engine.generate import generate
    from tpu_dist.models.transformer import tiny_lm

    model = tiny_lm(vocab_size=32, num_layers=1, d_model=16, num_heads=2,
                    max_len=16)
    import jax

    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 16), jnp.int32),
                        train=False)["params"]
    led = Ledger(str(tmp_path / "gen.jsonl"))
    prompt = jnp.zeros((2, 4), jnp.int32)
    out = generate(model, params, prompt, steps=5, ledger=led)
    led.close()
    assert out.shape == (2, 9)
    (rec,) = [r for r in read_ledger(led.path) if r["event"] == "decode"]
    assert rec["tokens"] == 10 and rec["throughput"] > 0
    assert rec["dispatch_s"] >= 0 and rec["device_s"] >= 0


# ------------------------------------------------------- static checker
def test_check_ledger_schema_tree_is_clean():
    """Tier-1 tripwire: every ledger.emit call site in the tree names a
    declared event and passes its required fields (AST walk, no jax)."""
    from tools.check_ledger_schema import check_tree, load_schema

    assert load_schema() == EVENT_SCHEMA  # AST extraction == runtime dict
    assert check_tree() == []


def test_check_ledger_schema_catches_drift(tmp_path):
    """The checker actually rejects: undeclared events, computed event
    names, and required fields hidden in a **splat."""
    from tools.check_ledger_schema import check_file, load_schema

    bad = tmp_path / "bad.py"
    bad.write_text(
        "ledger.emit('no_such_event', x=1)\n"
        "ledger.emit(name, step=1)\n"
        "ledger.emit('step', **fields)\n"
        "self.obs.ledger.emit('ckpt', epoch=1, path='p', is_best=False)\n")
    out = check_file(str(bad), load_schema(), "bad.py")
    assert len(out) == 3  # the last line is conformant
    assert any("undeclared" in v for v in out)
    assert any("literal" in v for v in out)
    assert any("missing required" in v for v in out)
