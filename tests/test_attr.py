"""HLO cost attribution (obs.attr): buckets, exactness, roofline render.

Covers: the stdlib HLO-text parser on a canned module (no jax), exact
matmul-flop and collective-byte attribution on a REAL tiny jitted
matmul+psum program, attention-scope bucketing, the cost_model ledger
event (schema-conformant emit via emit_cost_model), program_stats'
with_hlo extension, and the ledger_report roofline section rendered from
synthetic records (cost model vs measured columns).
"""

import pytest

from tpu_dist.obs.attr import bucket_totals, cost_buckets

# the optimized-HLO shape of a dot + relu-sum fusion + psum program (a
# trimmed real compiled.as_text() dump) — the no-jax parse fixture
CANNED_HLO = """\
HloModule jit_f, is_scheduled=true

%fused_computation (param_0.2: f32[8,32]) -> f32[] {
  %param_0.2 = f32[8,32]{1,0} parameter(0)
  %constant.3 = f32[] constant(0)
  %broadcast.2 = f32[8,32]{1,0} broadcast(f32[] %constant.3), dimensions={}
  %maximum.2 = f32[8,32]{1,0} maximum(f32[8,32]{1,0} %param_0.2, f32[8,32]{1,0} %broadcast.2)
  ROOT %reduce.1 = f32[] reduce(f32[8,32]{1,0} %maximum.2, f32[] %constant.3), dimensions={0,1}, to_apply=%region_0.8
}

ENTRY %main.25 (Arg_0.1: f32[8,16], Arg_1.2: f32[16,32]) -> f32[] {
  %Arg_0.1 = f32[8,16]{1,0} parameter(0), metadata={op_name="x"}
  %Arg_1.2 = f32[16,32]{1,0} parameter(1), metadata={op_name="w"}
  %dot.0 = f32[8,32]{1,0} dot(f32[8,16]{1,0} %Arg_0.1, f32[16,32]{1,0} %Arg_1.2), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/dot_general"}
  %maximum_reduce_fusion = f32[] fusion(f32[8,32]{1,0} %dot.0), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(f)/reduce_sum"}
  ROOT %all-reduce.0 = f32[] all-reduce(f32[] %maximum_reduce_fusion), channel_id=1, replica_groups={{0}}, use_global_device_ids=true, to_apply=%region_1.12, metadata={op_name="jit(f)/psum"}
}
"""


def test_cost_buckets_canned_hlo_no_jax():
    b = cost_buckets(CANNED_HLO)
    # dot: 2 * |out 8x32| * K=16, bytes = out + both operands (f32)
    assert b["matmul"]["flops"] == 2 * 8 * 32 * 16
    assert b["matmul"]["bytes"] == (8 * 32 + 8 * 16 + 16 * 32) * 4
    assert b["matmul"]["count"] == 1
    # the fusion call site charges its operand+result bytes; inner
    # elementwise flops (broadcast+maximum+reduce over 8x32) recurse in
    assert b["fusion"]["bytes"] == (8 * 32 + 0) * 4 + 4
    assert b["fusion"]["flops"] >= 2 * 8 * 32  # maximum + reduce at least
    # collective: bytes in+out, zero flops
    assert b["collective:all-reduce"] == {"flops": 0.0, "bytes": 8.0,
                                          "count": 1}
    tot = bucket_totals(b)
    assert tot["collective_bytes"] == 8.0
    assert tot["flops"] == sum(x["flops"] for x in b.values())


def test_cost_buckets_attention_scope_overrides():
    hlo = CANNED_HLO.replace('op_name="jit(f)/dot_general"',
                             'op_name="jit(f)/block0/bqhd,bkhd->bhqk/'
                             'dot_general"')
    b = cost_buckets(hlo)
    assert "matmul" not in b
    assert b["attention"]["flops"] == 2 * 8 * 32 * 16


def test_cost_buckets_real_jitted_matmul_psum():
    """ACCEPTANCE sanity: attribute an actual compiled matmul+psum
    program — matmul flops exact, a collective bucket present."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_dist._compat import shard_map
    from tpu_dist.parallel.mesh import make_mesh

    n = jax.device_count()
    mesh = make_mesh((n,), ("data",))

    def f(x, w):
        return jax.lax.psum(jax.nn.relu(jnp.dot(x, w)), "data")

    g = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(), P()),
                          out_specs=P(), check_vma=False))
    x = jnp.ones((8, 16), jnp.float32)
    w = jnp.ones((16, 32), jnp.float32)
    txt = g.lower(x, w).compile().as_text()
    b = cost_buckets(txt)
    assert b["matmul"]["flops"] == 2 * 8 * 32 * 16  # exact contraction
    assert b["collective:all-reduce"]["bytes"] >= 2 * 8 * 32 * 4  # in+out
    assert b["collective:all-reduce"]["flops"] == 0.0
    assert bucket_totals(b)["flops"] > 0


def test_program_stats_with_hlo_and_emit_cost_model(tmp_path):
    """program_stats(..., with_hlo=True) returns the optimized HLO from
    the SAME lower+compile, and emit_cost_model turns it into a
    schema-valid cost_model ledger event with peaks stamped."""
    import jax
    import jax.numpy as jnp

    from tpu_dist.obs import Ledger, read_ledger
    from tpu_dist.obs.attr import emit_cost_model
    from tpu_dist.utils.telemetry import program_stats

    fn = jax.jit(lambda a, b: jnp.dot(a, b).sum())
    a = jnp.ones((4, 8)), jnp.ones((8, 16))
    st = program_stats(fn, *a)          # default: no hlo key
    assert "hlo" not in st
    st = program_stats(fn, *a, with_hlo=True)
    assert st["hlo"] and "HloModule" in st["hlo"]
    path = str(tmp_path / "run.jsonl")
    led = Ledger(path)
    rec = emit_cost_model(led, "train_step", st["hlo"],
                          xla_flops=st["flops"])
    led.close()
    assert rec["program"] == "train_step"
    assert rec["buckets"]["matmul"]["flops"] == 2 * 4 * 16 * 8
    assert rec["peak_tflops"] > 0 and rec["peak_gbps"] > 0
    (back,) = read_ledger(path)  # validates schema round-trip
    assert back["event"] == "cost_model"
    assert back["total_flops"] >= back["buckets"]["matmul"]["flops"]


def test_roofline_section_renders_cost_vs_measured():
    """ledger_report's roofline: per-category shares + ideal s/step from
    the cost_model event against measured device/comm seconds — no jax."""
    from tools.ledger_report import summarize

    records = [
        {"event": "run_start", "kind": "lm", "config": {}, "mesh": None,
         "devices": ["tpu"], "process_count": 1, "peak_tflops": 100.0},
        {"event": "cost_model", "program": "window_step",
         "buckets": {
             "matmul": {"flops": 8e9, "bytes": 2e8, "count": 10},
             "attention": {"flops": 1e9, "bytes": 5e7, "count": 4},
             "collective:all-reduce": {"flops": 0.0, "bytes": 1e8,
                                       "count": 2},
             "elementwise": {"flops": 1e8, "bytes": 3e8, "count": 50}},
         "total_flops": 9.1e9, "total_bytes": 6.5e8,
         "collective_bytes": 1e8, "xla_flops": 9e9,
         "peak_tflops": 100.0, "peak_gbps": 800.0,
         "peak_is_nominal": False},
    ] + [
        {"event": "step", "step": i, "loss": 1.0, "throughput": 1e5,
         "unit": "tok/s", "data_s": 0.001, "dispatch_s": 0.002,
         "device_s": 0.01, "comm_s": 0.002, "mfu": 0.4,
         "steps_in_dispatch": 1, "warm": i == 0}
        for i in range(4)
    ]
    lines = []
    summary = summarize(records, out=lines.append)
    text = "\n".join(lines)
    assert "roofline" in text and "matmul" in text and "bound" in text
    assert "measured: device" in text
    rl = summary["roofline"]
    assert rl["program"] == "window_step"
    # matmul at these peaks: 8e9 flops / 100 TF = 8e-5 s vs 2e8 B /
    # 800 GB/s = 2.5e-4 s -> memory-bound, ideal = the byte time
    assert rl["categories"]["matmul"]["ideal_s"] == pytest.approx(2.5e-4)
    assert rl["categories"]["matmul"]["bound"] == "memory"
    # attention: 1e9/1e14 = 1e-5 s vs 5e7/8e11 = 6.25e-5 s -> memory too
    assert rl["categories"]["attention"]["ideal_s"] == pytest.approx(6.25e-5)
    assert rl["categories"]["collective:all-reduce"]["bound"] == "comm"
    # measured per-step device seconds exclude the warm record
    assert rl["measured_device_s_per_step"] == pytest.approx(0.01)
    assert rl["measured_comm_s_per_step"] == pytest.approx(0.002)
    assert rl["gap_vs_ideal"] == pytest.approx(0.01 / rl["ideal_s_per_step"])
    assert rl["mfu_mean"] == pytest.approx(0.4)


def test_cost_buckets_tolerates_garbage():
    assert cost_buckets("") == {}
    assert cost_buckets("not hlo at all\n{}\n") == {}
