"""Fleet observatory (round 14): scenario schedules, the FleetLedger
stitcher, fleet metrics/trace/report plumbing, and the CI acceptance
scenario.

The pins that matter:

* the scenario compile is DETERMINISTIC: same schedule + seed -> byte-
  identical admitted-request and injected-fault sequences, with exact
  event counts for the checked-in ``scripts/fleet_ci.json`` (no jax);
* the fleet stitcher tolerates a torn/partial per-host ledger and its
  goodput categories + goodput account for ~100% of aggregate wall;
* the ACCEPTANCE scenario (3 virtual hosts, one preemption wave with a
  host return through the real consensus path, diurnal Poisson serve
  traffic, a slow host, an overload burst) runs on CPU and — read
  entirely from ``tools/fleet_report.py --json`` — shows restart classes
  matching the schedule EXACTLY, the goodput sum-check at ~100%, and an
  SLO-breach count inside the pinned bounded range.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from tpu_dist.obs import faults
from tpu_dist.obs.goodput import fleet_accounting, load_job_records
from tpu_dist.obs.ledger import Ledger, read_ledger
from tpu_dist.obs.metrics import MetricsRegistry, metrics_ledger_sink
from tpu_dist.sim.fleet import FleetLedger
from tpu_dist.sim.scenario import (RID_STRIDE, Scenario,
                                   compile_host_plans,
                                   expected_restart_classes, load_scenario,
                                   parse_scenario)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CI_SCENARIO = os.path.join(ROOT, "scripts", "fleet_ci.json")


@pytest.fixture(autouse=True)
def _clean_fault_state(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


# ---------------------------------------------------------------------------
# scenario grammar + deterministic compile (no jax)

def _doc(**over):
    doc = {"name": "t", "seed": 3, "hosts": 2, "ticks": 40,
           "traffic": {"base_rate": 0.2}}
    doc.update(over)
    return doc


def test_scenario_validation_refuses_garbage():
    with pytest.raises(ValueError, match="missing required key"):
        parse_scenario({"name": "x"})
    with pytest.raises(ValueError, match="unknown event type"):
        parse_scenario(_doc(events=[{"type": "meteor", "tick": 1}]))
    with pytest.raises(ValueError, match="hosts list"):
        parse_scenario(_doc(events=[{"type": "crash", "tick": 1,
                                     "hosts": [9]}]))
    with pytest.raises(ValueError, match="consensus host"):
        parse_scenario(_doc(events=[{"type": "preempt", "tick": 1,
                                     "hosts": [0]}]))
    with pytest.raises(ValueError, match="return_tick"):
        parse_scenario(_doc(events=[{"type": "preempt", "tick": 30,
                                     "hosts": [1], "return_tick": 10}]))
    with pytest.raises(ValueError, match="prompt range"):
        parse_scenario(_doc(traffic={"tenants": [
            {"name": "bad", "prompt": [9, 2]}]}))
    with pytest.raises(ValueError, match="exceeds"):
        parse_scenario(_doc(model={"max_len": 8},
                            traffic={"tenants": [
                                {"name": "big", "prompt": [6, 8],
                                 "out": [4, 6]}]}))


def test_scenario_roundtrips_through_doc_form():
    sc = load_scenario(CI_SCENARIO)
    sc2 = parse_scenario(sc.to_doc())
    assert sc2 == sc


def test_diurnal_rate_peaks_bursts_and_clamps():
    sc = parse_scenario(_doc(
        traffic={"base_rate": 0.2, "amplitude": 1.5, "period": 40},
        events=[{"type": "burst", "tick": 5, "ticks": 3, "rate": 2.0}]))
    assert sc.rate(10, 0) > 0.2              # sin peak at period/4
    assert sc.rate(30, 0) == 0.0             # deep trough clamps at zero
    assert sc.rate(5, 0) == pytest.approx(sc.rate(4, 0) + 2.0, abs=0.2)
    assert sc.rate(8, 0) < 2.0               # burst window closed


def test_compile_is_deterministic_with_exact_ci_counts():
    """THE determinism pin: the checked-in CI scenario compiles to the
    same arrivals/faults/actions every time, with exact counts."""
    sc = load_scenario(CI_SCENARIO)
    p1, a1 = compile_host_plans(sc)
    p2, a2 = compile_host_plans(sc)
    key = lambda plans: [(x.tick, x.rid, x.tenant, x.prompt_len, x.out_len)
                         for h in sorted(plans) for x in plans[h].arrivals]
    assert key(p1) == key(p2)
    assert a1 == a2
    # exact per-host admitted-request counts for seed 7 (any change to
    # the schedule, the sampler, or the seed shows up HERE, not in a
    # flaky acceptance run)
    assert [len(p1[h].arrivals) for h in range(3)] == [65, 56, 49]
    assert p1[1].faults == "preempt_sigterm@step=56,attempt=0"
    assert p1[0].faults == "" and p1[2].faults == ""
    assert p1[2].skew == 1.5
    assert [(a.tick, a.action, a.host) for a in a1] == \
        [(56, "leave", 1), (120, "register", 1)]
    # rids are fleet-unique by namespace
    rids = [x.rid for h in p1 for x in p1[h].arrivals]
    assert len(set(rids)) == len(rids)
    assert all(x.rid // RID_STRIDE == h for h in p1
               for x in p1[h].arrivals)


def test_compile_seed_changes_the_schedule():
    sc = load_scenario(CI_SCENARIO)
    other = parse_scenario({**sc.to_doc(), "seed": sc.seed + 1})
    p1, _ = compile_host_plans(sc)
    p2, _ = compile_host_plans(other)
    assert [(x.tick, x.prompt_len) for x in p1[0].arrivals] != \
        [(x.tick, x.prompt_len) for x in p2[0].arrivals]


def test_expected_restart_classes_follow_the_schedule():
    sc = load_scenario(CI_SCENARIO)
    assert expected_restart_classes(sc) == {
        # consensus host: one rescale per membership change (leave+return)
        0: ["preemption_snapshotted", "preemption_snapshotted", "clean"],
        1: ["preemption_snapshotted", "clean"],   # the wave target
        2: ["clean"]}                             # the slow host
    # a hang predicts "crash" in record mode (no watchdog in the serve
    # worker: the SIGKILLed attempt leaves neither run_end nor stall)
    crashy = parse_scenario(_doc(events=[
        {"type": "crash", "tick": 5, "hosts": [1]},
        {"type": "hang", "tick": 20, "hosts": [1]}]))
    assert expected_restart_classes(crashy)[1] == \
        ["crash", "crash", "clean"]


def test_fault_specs_use_the_standard_grammar():
    sc = parse_scenario(_doc(events=[
        {"type": "crash", "tick": 7, "hosts": [1]},
        {"type": "hang", "tick": 9, "hosts": [1], "secs": 5}]))
    plans, _ = compile_host_plans(sc)
    plan = faults.FaultPlan.parse(plans[1].faults)  # must parse cleanly
    assert plan.sites() == {"hard_exit", "hang"}
    # the k-th disruption is gated on attempt k: the restarted worker
    # (attempt 1) must still be able to fire the second scheduled fault
    assert plans[1].faults == \
        "hard_exit@step=7,attempt=0;hang@step=9,attempt=1,secs=5"


# ---------------------------------------------------------------------------
# the fleet stitcher over hand-built ledgers (no jax)

def _emit_line(f, **rec):
    f.write(json.dumps(rec) + "\n")


def _host_ledger(path, t0, *, attempt=0, steps=2, status="ok",
                 tenant="chat", slo=0, scale=None, torn=False):
    """One attempt ledger: run_start -> compile -> step(s) -> serving
    events -> run_end, with optional slo/scale events and a torn tail."""
    with open(path, "w") as f:
        _emit_line(f, event="run_start", ts=t0, pid=0, kind="fleet_sim",
                   config={}, mesh=None, devices=["cpu"], process_count=1,
                   attempt=attempt)
        _emit_line(f, event="compile", ts=t0 + 1.0, pid=0, program="serve")
        for i in range(steps):
            _emit_line(f, event="step", ts=t0 + 1.5 + i, pid=0, step=i,
                       loss=None, throughput=10.0, unit="tok/s",
                       data_s=0.0, dispatch_s=0.1, device_s=0.4,
                       comm_s=None, mfu=None)
        _emit_line(f, event="request", ts=t0 + 1.6, pid=0, rid=1, tokens=4,
                   queue_wait_s=0.05, admit_ts=0.0, first_token_ts=0.1,
                   finish_ts=0.4, tenant=tenant, ttft_s=0.1)
        for i in range(slo):
            _emit_line(f, event="slo", ts=t0 + 2.0 + i, pid=0, step=i,
                       kind="queue_wait", value=0.9, floor=0.5)
        if scale:
            _emit_line(f, event="scale", ts=t0 + 2.5, pid=0, **scale)
        if torn:
            f.write('{"event": "step", "ts": ')   # the killed writer
        else:
            _emit_line(f, event="run_end", ts=t0 + 1.5 + steps, pid=0,
                       steps=steps, seconds=1.5 + steps, status=status)


def _build_fleet_dir(root):
    t0 = 1000.0
    h0 = os.path.join(root, "host0")
    h1 = os.path.join(root, "host1")
    os.makedirs(h0)
    os.makedirs(h1)
    # host 0: preempted attempt 0 + clean attempt 1 + a sup sibling
    _host_ledger(os.path.join(h0, "run.jsonl"), t0, status="preempted",
                 tenant="chat", slo=1)
    _host_ledger(os.path.join(h0, "run.a1.jsonl"), t0 + 10.0, attempt=1,
                 tenant="chat")
    with open(os.path.join(h0, "run.sup.jsonl"), "w") as f:
        _emit_line(f, event="scale", ts=t0 + 6.0, pid=0, action="shrink",
                   processes=1, epoch=1, world_from=2)
        _emit_line(f, event="scale", ts=t0 + 9.0, pid=0, action="expand",
                   processes=2, epoch=2, world_from=1)
    # host 1: one attempt whose writer died mid-line (torn tail, no
    # run_end) — the stitcher must tolerate AND classify it
    _host_ledger(os.path.join(h1, "run.jsonl"), t0 + 0.5, tenant="batch",
                 torn=True)
    with open(os.path.join(root, "fleet.jsonl"), "w") as f:
        _emit_line(f, event="scenario", ts=t0, pid=0, name="hand", seed=1,
                   hosts=2, ticks=10, tick_s=0.02)
        _emit_line(f, event="fleet", ts=t0 + 1.0, pid=0, hosts_live=2,
                   goodput_ratio=None, slo_breaches=None)
        _emit_line(f, event="fleet", ts=t0 + 20.0, pid=0, hosts_live=0,
                   goodput_ratio=0.4, slo_breaches=1, final=True)
    return root


def test_fleet_stitcher_tolerates_torn_ledger_and_sums_to_wall(tmp_path):
    fleet = FleetLedger.discover(_build_fleet_dir(str(tmp_path)),
                                 warn=lambda m: None)
    assert sorted(fleet.hosts) == [0, 1]
    # host 1's torn trailing line was dropped, the good records kept
    assert any(r["event"] == "request" for r in fleet.hosts[1])
    report = fleet.report()
    acct = report["fleet"]
    assert acct["hosts"] == 2
    # THE invariant: goodput + categories account for the aggregate wall
    explained = acct["goodput_s"] + sum(acct["categories"].values())
    assert explained == pytest.approx(acct["aggregate_wall_s"], rel=1e-6)
    assert acct["sum_check"] == pytest.approx(1.0, abs=1e-6)
    # host 0's two attempts stitched with their restart gap
    assert acct["per_host"][0]["attempts"] == 2
    assert acct["categories"]["restart_gap"] > 0
    assert report["restart_classes"] == {
        "0": ["preemption_snapshotted", "clean"], "1": ["crash"]}
    assert report["restart_histogram"] == {
        "preemption_snapshotted": 1, "clean": 1, "crash": 1}
    assert report["slo_breaches"] == 1
    # elasticity: the sup sibling's scale events, host-stamped, in order
    assert [(e["host"], e["action"]) for e in report["elasticity"]] == \
        [(0, "shrink"), (0, "expand")]
    assert report["elasticity"][0]["t_rel"] == pytest.approx(6.0)
    # per-tenant percentiles from the request events
    assert set(report["per_tenant"]) == {"chat", "batch"}
    assert report["per_tenant"]["chat"]["requests"] == 2
    assert report["per_tenant"]["chat"]["queue_wait_s"]["p50"] == \
        pytest.approx(0.05)
    assert report["scenario"]["name"] == "hand"
    assert [s["hosts_live"] for s in report["hosts_live"]] == [2, 0]
    json.dumps(report)  # the --json contract: serializable as-is


def test_fleet_report_cli_renders_and_jsons(tmp_path):
    root = _build_fleet_dir(str(tmp_path))
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fleet_report.py"),
         root, "--json"], capture_output=True, text=True, cwd=ROOT)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["restart_histogram"]["crash"] == 1
    human = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fleet_report.py"),
         root], capture_output=True, text=True, cwd=ROOT)
    assert "fleet goodput ratio" in human.stdout
    assert "restarts: histogram" in human.stdout
    assert "per-tenant serving" in human.stdout


def test_load_job_records_appends_sup_sibling(tmp_path):
    base = str(tmp_path / "run.jsonl")
    _host_ledger(base, 1000.0)
    with open(str(tmp_path / "run.sup.jsonl"), "w") as f:
        _emit_line(f, event="scale", ts=990.0, pid=0, action="shrink",
                   processes=1, epoch=1)
    records = load_job_records(base)
    # appended AFTER the attempt stream despite the earlier ts: a scale
    # event must never split a pseudo-attempt into the goodput math
    assert records[-1]["event"] == "scale"
    assert [r["event"] for r in records[:2]] == ["run_start", "compile"]
    assert load_job_records(base, discover=False)[-1]["event"] == "run_end"


def test_fleet_accounting_aggregates_and_abstains():
    assert fleet_accounting({}) is None
    j = {"wall_s": 10.0, "goodput_s": 4.0, "ratio": 0.4,
         "categories": {"startup": 2.0, "idle": 4.0}, "overrun_s": 0.0,
         "opt_steps": 7, "attempts": [{}]}
    agg = fleet_accounting({0: j, 1: j})
    assert agg["aggregate_wall_s"] == 20.0
    assert agg["goodput_ratio"] == pytest.approx(0.4)
    assert agg["sum_check"] == pytest.approx(1.0)
    assert agg["opt_steps"] == 14


# ---------------------------------------------------------------------------
# fleet Prometheus series (obs.metrics) — no jax

def test_fleet_metrics_series_and_breach_delta():
    reg = MetricsRegistry()
    sink = metrics_ledger_sink(reg)
    text = reg.render()
    for name in ("tpu_dist_fleet_goodput_ratio",
                 "tpu_dist_fleet_hosts_live",
                 "tpu_dist_fleet_slo_breaches_total"):
        assert f"{name} 0" in text    # pre-registered at zero
    sink({"event": "fleet", "hosts_live": 3, "goodput_ratio": None,
          "slo_breaches": 4})
    sink({"event": "fleet", "hosts_live": 0, "goodput_ratio": 0.31,
          "slo_breaches": 6})
    text = reg.render()
    assert "tpu_dist_fleet_hosts_live 0" in text
    assert "tpu_dist_fleet_goodput_ratio 0.31" in text
    # the counter moved by the DELTAS of the cumulative event values
    assert "tpu_dist_fleet_slo_breaches_total 6" in text
    sink({"event": "fleet", "hosts_live": 0, "goodput_ratio": 0.31,
          "slo_breaches": 6})   # repeat: no double count
    assert "tpu_dist_fleet_slo_breaches_total 6" in reg.render()


# ---------------------------------------------------------------------------
# trace_merge: the supervisor scale-event marker lane — no jax

def test_trace_merge_renders_sup_scale_lane(tmp_path):
    base = str(tmp_path / "run.jsonl")
    _host_ledger(base, 1000.0)
    with open(str(tmp_path / "run.sup.jsonl"), "w") as f:
        _emit_line(f, event="scale", ts=1002.0, pid=0, action="shrink",
                   processes=2, epoch=1, world_from=3)
        _emit_line(f, event="scale", ts=1004.0, pid=0, action="expand",
                   processes=3, epoch=2, world_from=2)
    sys.path.insert(0, ROOT)
    from tools.trace_merge import main as tm_main

    out = str(tmp_path / "trace.json")
    assert tm_main([base, "-o", out]) == 0
    with open(out) as f:
        trace = json.load(f)
    assert trace["otherData"]["scale_events"] == 2
    lanes = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert "supervisor" in lanes
    marks = [e for e in trace["traceEvents"]
             if e.get("name", "").startswith("scale:")]
    assert [m["name"] for m in marks] == ["scale:shrink", "scale:expand"]
    assert marks[0]["ts"] == pytest.approx(2.0 * 1e6)  # job clock (µs)
    assert marks[0]["args"]["world_from"] == 3


# ---------------------------------------------------------------------------
# supervisor scenario hooks (jax-free fake child)

_SLEEPY_CHILD = r"""
import json, sys, time
path = sys.argv[sys.argv.index("--ledger-path") + 1]
with open(path, "a") as f:
    f.write(json.dumps({"event": "run_start", "ts": time.time(),
                        "kind": "fake", "config": {}, "mesh": None,
                        "devices": [], "process_count": 1}) + "\n")
time.sleep(60)
"""


def test_supervisor_request_stop_tears_down_and_reports_stopped(tmp_path):
    from tpu_dist.parallel.supervisor import RestartPolicy, Supervisor

    script = tmp_path / "child.py"
    script.write_text(_SLEEPY_CHILD)
    seen = []
    sup = Supervisor(
        [sys.executable, str(script)], ledger=str(tmp_path / "run.jsonl"),
        policy=RestartPolicy(max_restarts=3, backoff_base_s=0.01,
                             stall_timeout_s=60.0,
                             preempt_deadline_s=2.0),
        poll_s=0.05, on_attempt=seen.append)
    threading.Timer(1.0, sup.request_stop).start()
    t0 = time.monotonic()
    res = sup.run()
    assert time.monotonic() - t0 < 30.0
    assert res.status == "stopped" and not res.ok
    assert len(res.attempts) == 1
    # the on_attempt hook observed the classified attempt
    assert [a.attempt for a in seen] == [0]
    assert seen[0].failure_class == res.attempts[0].failure_class


# ---------------------------------------------------------------------------
# ACCEPTANCE: the checked-in CI scenario end to end (CPU, real workers)

def test_fleet_ci_scenario_acceptance(tmp_path):
    """ISSUE 14 acceptance: 3 virtual hosts under scripts/fleet_ci.json —
    diurnal Poisson serve traffic, one preemption wave on host 1 with a
    host return through the real consensus path (shrink -> expand, rescale
    relaunches), a 1.5x slow host, an overload burst — and every assertion
    read from ``tools/fleet_report.py --json``:

    * stitched fleet goodput categories + goodput sum to ~100% of the
      aggregate wall;
    * per-host restart classes match the schedule's own prediction
      EXACTLY (consensus host: two rescale snapshots then clean; wave
      host: preemption_snapshotted then clean; slow host: clean);
    * the SLO-breach count lands in the pinned bounded range (the burst
      guarantees at least one; hysteresis re-arms bound the tail).
    """
    from tpu_dist.sim.runner import FleetSim

    out_dir = str(tmp_path / "fleet")
    sc = load_scenario(CI_SCENARIO)
    report_inline = FleetSim(CI_SCENARIO, out_dir).run()
    # the CI contract reads the report tool's --json, not runner internals
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fleet_report.py"),
         out_dir, "--json"], capture_output=True, text=True, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)

    # -- goodput sums to aggregate wall ---------------------------------
    acct = report["fleet"]
    assert acct["hosts"] == 3
    assert acct["sum_check"] == pytest.approx(1.0, abs=0.02)
    explained = acct["goodput_s"] + sum(acct["categories"].values())
    assert explained == pytest.approx(acct["aggregate_wall_s"], rel=0.02)
    assert acct["goodput_s"] > 0 and acct["goodput_ratio"] > 0
    # the wave host restarted: its crash->restart gap is on the books
    assert acct["categories"]["restart_gap"] > 0

    # -- restart classes match the schedule EXACTLY ---------------------
    want = {str(h): cls
            for h, cls in expected_restart_classes(sc).items()}
    assert report["restart_classes"] == want
    assert report["restart_histogram"] == {
        "preemption_snapshotted": 3, "clean": 3}

    # -- SLO breaches in the pinned bounded range -----------------------
    assert 1 <= report["slo_breaches"] <= 12

    # -- the elasticity story: shrink at the wave, expand at the return -
    consensus_scales = [e for e in report["elasticity"]
                        if e["host"] == 0 and e["action"] in
                        ("shrink", "expand")]
    assert [e["action"] for e in consensus_scales] == ["shrink", "expand"]
    assert consensus_scales[0]["processes"] == 2
    assert consensus_scales[1]["processes"] == 3
    # every preempted/rescaled worker drained gracefully
    assert any(e["action"] == "drain" and e["host"] == 1
               for e in report["elasticity"])

    # -- serving evidence: both tenants served, on every surviving host -
    assert set(report["per_tenant"]) == {"chat", "batch"}
    for t in report["per_tenant"].values():
        assert t["requests"] > 0
        assert t["queue_wait_s"]["p50"] is not None
    assert report["serving"]["completed"] > 0

    # -- request observatory: attribution sums, breaches have evidence --
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "request_report.py"),
         out_dir, "--json"], capture_output=True, text=True, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr
    req = json.loads(proc.stdout)
    assert req["completed_requests"] > 0
    ta = req["tail_attribution"]
    # the per-request sum-check: queue + prefill + decode + residue is an
    # identity against measured latency, and residue stays inside the
    # rounding tolerance for EVERY completed request
    assert ta["sum_check"]["ok"], ta["sum_check"]
    for row in req["per_request"]:
        assert row["latency_s"] == pytest.approx(
            row["queue_s"] + row["prefill_s"] + row["decode_s"]
            + row["residue_s"], abs=1e-6)
    # every slo breach resolves to >= 1 concrete exemplar trace — a
    # breach that points at nothing is a report bug, not a gap
    assert len(req["slo_exemplars"]) == report["slo_breaches"]
    for breach in req["slo_exemplars"]:
        assert len(breach["exemplars"]) >= 1, breach
    # the fleet report stitched the same traces the request report read
    assert len(report["traces"]) == req["traces"] > 0

    # -- the runner's own artifacts -------------------------------------
    assert report_inline["restart_classes"] == report["restart_classes"]
    assert report_inline["supervisors"]["0"]["status"] == "clean"
    with open(os.path.join(out_dir, "headline.json")) as f:
        headline = json.load(f)
    assert headline["fleet"]["goodput_ratio"] == acct["goodput_ratio"]
    # the fleet ledger's final rollup matches (and fed the fleet gauges)
    fleet_events = [r for r in read_ledger(
        os.path.join(out_dir, "fleet.jsonl"), strict=False)
        if r["event"] == "fleet" and r.get("final")]
    assert fleet_events[-1]["goodput_ratio"] == acct["goodput_ratio"]
    assert fleet_events[-1]["slo_breaches"] == report["slo_breaches"]
