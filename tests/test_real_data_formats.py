"""Real dataset format loaders: CIFAR-10 pickles, MNIST idx, ImageFolder.

The zero-egress environment trains on synthetic data, but users with the real
files on disk must get them loaded in the exact torchvision on-disk formats
(reference C4). These tests generate miniature files in those formats.
"""

import gzip
import os
import pickle
import struct

import numpy as np
import pytest

from tpu_dist.data.datasets import load_dataset


def _write_cifar(root, n_per_batch=20):
    d = os.path.join(root, "cifar-10-batches-py")
    os.makedirs(d)
    rng = np.random.default_rng(0)
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        batch = {
            b"data": rng.integers(0, 255, (n_per_batch, 3072)).astype(np.uint8),
            b"labels": rng.integers(0, 10, n_per_batch).tolist(),
        }
        with open(os.path.join(d, name), "wb") as f:
            pickle.dump(batch, f)


def test_cifar10_pickle_format(tmp_path):
    _write_cifar(str(tmp_path))
    tr, va = load_dataset("cifar10", str(tmp_path))
    assert tr.name == "cifar10-train"
    assert tr.images.shape == (100, 32, 32, 3)  # 5 batches x 20
    assert va.images.shape == (20, 32, 32, 3)
    assert tr.images.dtype == np.uint8
    assert tr.num_classes == 10


def _write_idx(path, arr, gz=False):
    ndim = arr.ndim
    header = struct.pack(">HBB", 0, 8, ndim) + struct.pack(
        ">" + "I" * ndim, *arr.shape)
    opener = gzip.open if gz else open
    with opener(path, "wb") as f:
        f.write(header + arr.tobytes())


@pytest.mark.parametrize("gz", [False, True])
def test_mnist_idx_format(tmp_path, gz):
    rng = np.random.default_rng(0)
    sfx = ".gz" if gz else ""
    d = str(tmp_path)
    _write_idx(os.path.join(d, "train-images-idx3-ubyte" + sfx),
               rng.integers(0, 255, (30, 28, 28)).astype(np.uint8), gz)
    _write_idx(os.path.join(d, "train-labels-idx1-ubyte" + sfx),
               rng.integers(0, 10, 30).astype(np.uint8), gz)
    _write_idx(os.path.join(d, "t10k-images-idx3-ubyte" + sfx),
               rng.integers(0, 255, (10, 28, 28)).astype(np.uint8), gz)
    _write_idx(os.path.join(d, "t10k-labels-idx1-ubyte" + sfx),
               rng.integers(0, 10, 10).astype(np.uint8), gz)
    tr, va = load_dataset("mnist", d)
    assert tr.name == "mnist-train"
    assert tr.images.shape == (30, 28, 28, 1)
    assert va.images.shape == (10, 28, 28, 1)
    assert tr.labels.dtype == np.int32


def test_imagefolder_format(tmp_path):
    PIL = pytest.importorskip("PIL.Image")
    rng = np.random.default_rng(0)
    for split, n in (("train", 3), ("val", 2)):
        for cls in ("cat", "dog"):
            d = os.path.join(str(tmp_path), split, cls)
            os.makedirs(d)
            for i in range(n):
                arr = rng.integers(0, 255, (64, 48, 3)).astype(np.uint8)
                PIL.fromarray(arr).save(os.path.join(d, f"{i}.png"))
    tr, va = load_dataset("imagenet", str(tmp_path))
    assert len(tr) == 6 and len(va) == 4
    assert tr.num_classes == 2
    imgs, labels = tr.get_batch(np.array([0, 5]))
    assert imgs.shape == (2, 224, 224, 3)
    assert set(np.unique(tr.labels)) == {0, 1}


def test_synthetic_fallback_when_files_absent(tmp_path):
    tr, va = load_dataset("cifar10", str(tmp_path), 64, 16)
    assert tr.name.startswith("synth")
