"""Real dataset format loaders: CIFAR-10 pickles, MNIST idx, ImageFolder.

The zero-egress environment trains on synthetic data, but users with the real
files on disk must get them loaded in the exact torchvision on-disk formats
(reference C4). These tests generate miniature files in those formats.
"""

import gzip
import os
import pickle
import struct

import numpy as np
import pytest

from tpu_dist.data.datasets import load_dataset


def _write_cifar(root, n_per_batch=20):
    d = os.path.join(root, "cifar-10-batches-py")
    os.makedirs(d)
    rng = np.random.default_rng(0)
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        batch = {
            b"data": rng.integers(0, 255, (n_per_batch, 3072)).astype(np.uint8),
            b"labels": rng.integers(0, 10, n_per_batch).tolist(),
        }
        with open(os.path.join(d, name), "wb") as f:
            pickle.dump(batch, f)


def test_cifar10_pickle_format(tmp_path):
    _write_cifar(str(tmp_path))
    tr, va = load_dataset("cifar10", str(tmp_path))
    assert tr.name == "cifar10-train"
    assert tr.images.shape == (100, 32, 32, 3)  # 5 batches x 20
    assert va.images.shape == (20, 32, 32, 3)
    assert tr.images.dtype == np.uint8
    assert tr.num_classes == 10


def _write_idx(path, arr, gz=False):
    ndim = arr.ndim
    header = struct.pack(">HBB", 0, 8, ndim) + struct.pack(
        ">" + "I" * ndim, *arr.shape)
    opener = gzip.open if gz else open
    with opener(path, "wb") as f:
        f.write(header + arr.tobytes())


@pytest.mark.parametrize("gz", [False, True])
def test_mnist_idx_format(tmp_path, gz):
    rng = np.random.default_rng(0)
    sfx = ".gz" if gz else ""
    d = str(tmp_path)
    _write_idx(os.path.join(d, "train-images-idx3-ubyte" + sfx),
               rng.integers(0, 255, (30, 28, 28)).astype(np.uint8), gz)
    _write_idx(os.path.join(d, "train-labels-idx1-ubyte" + sfx),
               rng.integers(0, 10, 30).astype(np.uint8), gz)
    _write_idx(os.path.join(d, "t10k-images-idx3-ubyte" + sfx),
               rng.integers(0, 255, (10, 28, 28)).astype(np.uint8), gz)
    _write_idx(os.path.join(d, "t10k-labels-idx1-ubyte" + sfx),
               rng.integers(0, 10, 10).astype(np.uint8), gz)
    tr, va = load_dataset("mnist", d)
    assert tr.name == "mnist-train"
    assert tr.images.shape == (30, 28, 28, 1)
    assert va.images.shape == (10, 28, 28, 1)
    assert tr.labels.dtype == np.int32


def test_imagefolder_format(tmp_path):
    PIL = pytest.importorskip("PIL.Image")
    rng = np.random.default_rng(0)
    for split, n in (("train", 3), ("val", 2)):
        for cls in ("cat", "dog"):
            d = os.path.join(str(tmp_path), split, cls)
            os.makedirs(d)
            for i in range(n):
                arr = rng.integers(0, 255, (64, 48, 3)).astype(np.uint8)
                PIL.fromarray(arr).save(os.path.join(d, f"{i}.png"))
    tr, va = load_dataset("imagenet", str(tmp_path))
    assert len(tr) == 6 and len(va) == 4
    assert tr.num_classes == 2
    imgs, labels = tr.get_batch(np.array([0, 5]))
    assert imgs.shape == (2, 224, 224, 3)
    assert set(np.unique(tr.labels)) == {0, 1}


def test_synthetic_fallback_when_files_absent(tmp_path):
    tr, va = load_dataset("cifar10", str(tmp_path), 64, 16)
    assert tr.name.startswith("synth")


# ---- end-to-end: the engines DRIVE these real on-disk formats (VERDICT r4
# #4): sampler -> transform -> train steps -> checkpoint round-trip through
# the actual file path, not just loader shape checks. ----


def _fit_through(tmp_path, dataset, writer, arch, epochs=2):
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    root = os.path.join(str(tmp_path), "data")
    os.makedirs(root)
    writer(root)
    ckdir = os.path.join(str(tmp_path), "ck")
    cfg = TrainConfig(dataset=dataset, data=root, arch=arch, epochs=epochs,
                      batch_size=16, lr=0.05, seed=0, print_freq=100,
                      checkpoint_dir=ckdir)
    tr = Trainer(cfg)
    assert not tr.train_ds.name.startswith("synth"), tr.train_ds.name
    tr.fit()
    return cfg, ckdir


def test_trainer_fit_over_real_cifar_pickles(tmp_path):
    """Trainer end-to-end over actual cifar-10-batches-py pickles: loss
    decreases epoch-over-epoch and the checkpoint resumes through the same
    real file path (reference 2.distributed.py:127-160 capability)."""
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg, ckdir = _fit_through(tmp_path, "cifar10", _write_cifar, "lenet")
    ck = os.path.join(ckdir, "lenet-checkpoint.msgpack")
    assert os.path.exists(ck)
    cfg2 = TrainConfig(**{**cfg.__dict__, "resume": ck, "epochs": 3})
    tr2 = Trainer(cfg2)
    assert tr2.start_epoch == 2              # resumed THROUGH the real files
    assert int((tr2.state.step)) > 0


def test_trainer_fit_over_real_mnist_idx(tmp_path):
    def write(root):
        rng = np.random.default_rng(0)
        _write_idx(os.path.join(root, "train-images-idx3-ubyte"),
                   rng.integers(0, 255, (48, 28, 28)).astype(np.uint8))
        _write_idx(os.path.join(root, "train-labels-idx1-ubyte"),
                   rng.integers(0, 10, 48).astype(np.uint8))
        _write_idx(os.path.join(root, "t10k-images-idx3-ubyte"),
                   rng.integers(0, 255, (16, 28, 28)).astype(np.uint8))
        _write_idx(os.path.join(root, "t10k-labels-idx1-ubyte"),
                   rng.integers(0, 10, 16).astype(np.uint8))

    _fit_through(tmp_path, "mnist", write, "lenet", epochs=1)


@pytest.mark.slow  # tier-1 budget (PR 14): the imagefolder DECODE path is
# pinned in-budget by test_imagefolder_format, and the trainer-over-real-
# files mechanics by test_trainer_fit_over_real_cifar_pickles — this
# variant only swaps which on-disk format feeds the same fit loop
def test_trainer_fit_over_real_imagefolder(tmp_path):
    PIL = pytest.importorskip("PIL.Image")

    def write(root):
        rng = np.random.default_rng(0)
        for split, n in (("train", 8), ("val", 8)):
            for ci, cls in enumerate(("cat", "dog")):
                d = os.path.join(root, split, cls)
                os.makedirs(d)
                for i in range(n):
                    arr = rng.integers(0, 255, (40, 40, 3)).astype(np.uint8)
                    PIL.fromarray(arr).save(os.path.join(d, f"{i}.png"))

    _fit_through(tmp_path, "imagenet", write, "lenet", epochs=1)


def test_lm_trainer_fit_over_memmap_bin_corpus(tmp_path):
    """LMTrainer epoch over a real nanoGPT-style .bin uint16 memmap file:
    loss decreases and the checkpoint round-trips (VERDICT r4 #4)."""
    import jax

    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    rng = np.random.default_rng(1)
    # learnable affine stream so one epoch measurably reduces loss
    V = 64
    toks = [int(rng.integers(0, V))]
    for _ in range(20000):
        toks.append((toks[-1] * 5 + 7) % V)
    path = os.path.join(str(tmp_path), "corpus.bin")
    np.asarray(toks, np.uint16).tofile(path)

    ckdir = os.path.join(str(tmp_path), "ck")
    kw = dict(data=path, vocab_size=V, seq_len=32, d_model=32, num_layers=1,
              num_heads=2, batch_size=16, lr=3e-2, seed=0, print_freq=200,
              checkpoint_dir=ckdir)
    tr = LMTrainer(LMConfig(epochs=2, **kw))
    assert len(tr.train_ds) > 0
    best_ppl = tr.fit()
    assert best_ppl < V  # learned something vs uniform
    ck = os.path.join(ckdir, "lm-checkpoint.msgpack")
    assert os.path.exists(ck)
    tr2 = LMTrainer(LMConfig(epochs=3, resume=ck, **kw))
    assert int(jax.device_get(tr2.state.step)) > 0
