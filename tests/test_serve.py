"""Continuous-batching serve engine + paged KV cache (engine.serve/kv_cache).

The pins that matter:
* greedy decode through the paged path is BIT-IDENTICAL to the contiguous
  flax-cache `generate` (fp32, bf16, int8_wo weights) — the paged cache is
  an allocator change, never a model change;
* mixed-length sequences fit a pool the contiguous per-slot allocator
  provably cannot (the fragmentation win paged caches exist for);
* continuous batching strictly beats static drain-batching on completed
  requests per tick AND occupancy at equal slot capacity (deterministic:
  both numbers are schedule math, not wall clocks);
* a forced overload sheds new work through SLO-aware admission control,
  emitting `slo` + rejection events that reach the flight recorder and the
  Prometheus gauges through the NORMAL sink fan-out (zero new plumbing);
* speculative decoding (round 16) emits BITWISE the non-speculative greedy
  stream for ANY draft — a perfect draft multiplies tokens/tick, a
  hostile draft degrades to >=1 token/tick, never to wrong tokens;
* copy-on-write prefix caching (round 16) maps repeated prompts onto
  shared refcounted pages at bit-identical output, forking only the one
  divergent frontier page — and the refcount discipline is pinned
  (double-free raises, sharing never inflates the footprint).
"""

import itertools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.engine.generate import generate
from tpu_dist.engine.kv_cache import PagedKVPool
from tpu_dist.engine.serve import DecodeRequest, ServeConfig, ServeEngine
from tpu_dist.models.transformer import tiny_lm
from tpu_dist.obs.ledger import Ledger, read_ledger
from tpu_dist.parallel.mesh import SP_AXIS, make_mesh

V, L = 64, 32


def _lm_and_params(seed=0, **kw):
    lm = tiny_lm(vocab_size=V, num_layers=2, d_model=64, num_heads=4,
                 max_len=L, **kw)
    params = lm.init({"params": jax.random.PRNGKey(seed)},
                     jnp.zeros((1, L), jnp.int32), train=False)["params"]
    return lm, params


# ---------------------------------------------------------------- pool
def test_pool_alloc_free_and_high_water():
    pool = PagedKVPool(num_layers=1, num_pages=8, page_size=4,
                       num_heads=2, head_dim=8)
    a = pool.alloc(3)
    b = pool.alloc(4)
    assert len(a) == 3 and len(b) == 4 and not (set(a) & set(b))
    assert pool.alloc(2) is None          # 1 page left: all-or-nothing
    assert pool.pages_free == 1
    pool.free(a)
    assert pool.pages_free == 4
    assert pool.high_water_used == 7      # the peak, not the current
    # the trash page exists beyond the allocatable range
    assert pool.layers()[0].k.shape[0] == 9
    assert pool.pages_needed(9) == 3


def test_pool_validates_flash_needs_int8():
    with pytest.raises(ValueError, match="flash"):
        PagedKVPool(1, 8, 4, 2, 8, read="flash")


# ------------------------------------------------- bit-identity pins
def _assert_serve_matches_generate(lm, params, quant="none", n_reqs=2):
    """Per-request generate (the contiguous cache) vs one serve run over
    requests of MIXED prompt lengths — every token bitwise equal.
    ``n_reqs=1`` is the budget-lean variant for the dtype/quant twins
    (one reference program instead of two; the mixed-length coverage
    rides the fp32 run)."""
    prompts = [np.array([1, 9, 17], np.int32),
               np.array([5], np.int32)][:n_reqs]
    steps = [10, 12][:n_reqs]
    refs = [np.asarray(generate(lm, params, jnp.asarray(p[None]), steps=s,
                                use_cache=True, quant=quant))[0]
            for p, s in zip(prompts, steps)]
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=2, page_size=8, num_pages=16, quant=quant))
    comps = eng.run([DecodeRequest(i, p, s)
                     for i, (p, s) in enumerate(zip(prompts, steps))])
    assert len(comps) == n_reqs
    for c in comps:
        np.testing.assert_array_equal(refs[c.rid], c.tokens)


def test_paged_greedy_bit_identical_to_generate():
    lm, params = _lm_and_params(seed=4)
    _assert_serve_matches_generate(lm, params)


@pytest.mark.slow  # tier-1 budget (PR 16): dtype twin of the fp32 pin —
# the paged==contiguous discipline stays in-budget via the mixed-length
# test_paged_greedy_bit_identical_to_generate
def test_paged_greedy_bit_identical_bf16():
    lm, params = _lm_and_params(seed=5, dtype=jnp.bfloat16)
    _assert_serve_matches_generate(lm, params, n_reqs=1)


@pytest.mark.slow  # tier-1 budget (PR 16): quant twin; the int8_wo paged
# serving path stays pinned bit-for-bit against quantized generate
# in-budget by test_spec_decode_bit_identical_int8_wo
def test_paged_greedy_bit_identical_int8_wo():
    lm, params = _lm_and_params(seed=6)
    _assert_serve_matches_generate(lm, params, quant="int8_wo", n_reqs=1)


def test_paged_sampling_is_deterministic_given_rng():
    lm, params = _lm_and_params(seed=7)
    reqs = lambda: [DecodeRequest(0, np.array([3, 1, 4], np.int32), 8)]
    cfg = ServeConfig(max_slots=1, page_size=8, num_pages=8,
                      temperature=0.9)
    a = ServeEngine(lm, params, cfg,
                    rng=jax.random.PRNGKey(11)).run(reqs())[0]
    b = ServeEngine(lm, params, cfg,
                    rng=jax.random.PRNGKey(11)).run(reqs())[0]
    c = ServeEngine(lm, params, cfg,
                    rng=jax.random.PRNGKey(12)).run(reqs())[0]
    np.testing.assert_array_equal(a.tokens, b.tokens)
    assert int(a.tokens.max()) < V and int(a.tokens.min()) >= 0
    assert not np.array_equal(a.tokens, c.tokens)


# ------------------------------------------------- int8 KV pages
def test_int8_kv_exact_and_flash_kernel_agree():
    """The gathered-int8 exact read (dequant + fp attention) and the
    Pallas length-masked kernel decode the SAME tokens — the kernel is a
    bandwidth optimization of the identical math (interpret mode off-TPU,
    like every Pallas test in this suite)."""
    lm, params = _lm_and_params(seed=8)
    req = lambda: [DecodeRequest(0, np.array([1, 9, 17, 25], np.int32), 10)]
    outs = {}
    for read in ("exact", "flash"):
        eng = ServeEngine(lm, params, ServeConfig(
            max_slots=1, page_size=8, num_pages=8, kv_quant="int8",
            attn_read=read))
        outs[read] = eng.run(req())[0].tokens
        assert int(outs[read].max()) < V
    np.testing.assert_array_equal(outs["exact"], outs["flash"])


# ------------------------------------------------- fragmentation pin
def test_mixed_lengths_fit_where_contiguous_cannot():
    """4 concurrent sequences with totals {32, 12, 8, 8} need 15 pages of
    4; a contiguous max_len-per-slot allocator would preallocate 32. A
    20-page pool therefore fits the paged layout and provably not the
    contiguous one — and the run completes with every sequence resident
    at once."""
    lm, params = _lm_and_params(seed=9)
    pool_pages = 20
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=4, page_size=4, num_pages=pool_pages))
    assert eng.pool.contiguous_pages_needed(4, L) > pool_pages
    reqs = [DecodeRequest(0, np.arange(16, dtype=np.int32) % V, 16),
            DecodeRequest(1, np.array([7, 8, 9, 10], np.int32), 8),
            DecodeRequest(2, np.array([1, 2], np.int32), 6),
            DecodeRequest(3, np.array([3, 4], np.int32), 6)]
    comps = eng.run(reqs)
    assert len(comps) == 4
    assert {c.rid for c in comps} == {0, 1, 2, 3}
    # all four were admitted before any finished (truly concurrent)
    assert eng.pool.high_water_used == 8 + 3 + 2 + 2
    assert eng.pool.pages_free == pool_pages  # everything reclaimed


# ------------------------------------------------- perf pin
def test_continuous_batching_beats_static_drain():
    """Equal capacity, same request set: iteration-level refill completes
    strictly more requests per decode tick at strictly higher occupancy
    than drain-batching (both numbers are pure schedule arithmetic —
    deterministic on any machine)."""
    lm, params = _lm_and_params(seed=10)
    rng = np.random.default_rng(0)
    reqs = lambda: [DecodeRequest(
        i, rng.integers(0, V, (int(rng.integers(2, 8)),)).astype(np.int32),
        int(rng.integers(2, 20))) for i in range(12)]
    stats = {}
    for refill in ("continuous", "drain"):
        rng = np.random.default_rng(0)   # same trace both modes
        eng = ServeEngine(lm, params, ServeConfig(
            max_slots=4, page_size=8, num_pages=64, refill=refill))
        comps = eng.run(reqs())
        assert len(comps) == 12
        stats[refill] = (len(comps) / eng.ticks, eng.occupancy)
    assert stats["continuous"][0] > stats["drain"][0], stats
    assert stats["continuous"][1] > stats["drain"][1], stats


# ------------------------------------------------- admission + overload
def test_admission_rejects_impossible_requests():
    lm, params = _lm_and_params(seed=11)
    led_records = []
    ledger = Ledger(None, sinks=(led_records.append,))
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=1, page_size=4, num_pages=4), ledger=ledger)
    # prompt + max_new beyond max_len
    assert not eng.submit(DecodeRequest(0, np.arange(30, dtype=np.int32),
                                        30))
    # needs more pages than the whole pool (but within max_len)
    assert not eng.submit(DecodeRequest(1, np.arange(20, dtype=np.int32),
                                        8))
    reasons = [r.get("reason") for r in led_records
               if r["event"] == "admit"]
    assert reasons == ["too_long", "exceeds_pool"]
    assert eng.rejected == 2


def test_overload_sheds_emits_slo_and_fires_flightrec(tmp_path):
    """Queue overload: the wait EMA breaches the SLO floor -> one `slo`
    event (which auto-triggers the flight recorder through the existing
    sink fan-out), shedding rejects new submits with `slo_shedding`, and
    the serving gauges land in the metrics registry — all through the
    standard ledger plumbing, zero serve-specific wiring."""
    from tpu_dist.obs.flightrec import FlightRecorder
    from tpu_dist.obs.metrics import MetricsRegistry, metrics_ledger_sink

    lm, params = _lm_and_params(seed=12)
    path = str(tmp_path / "serve.jsonl")
    ledger = Ledger(path)
    reg = MetricsRegistry()
    ledger.add_sink(metrics_ledger_sink(reg))
    fr = FlightRecorder(dir=str(tmp_path / "fr"), ledger=ledger,
                        trace_steps=0)
    ledger.add_sink(fr.sink)
    # a virtual clock that leaps 1s per reading: every queued request
    # accumulates huge waits, so the EMA breaches the 0.5s floor as soon
    # as min_samples admissions have happened
    clock = itertools.count()
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=1, page_size=4, num_pages=8, queue_depth_max=3,
        slo_queue_wait_s=0.5, slo_min_samples=1),
        ledger=ledger, now_fn=lambda: float(next(clock)))
    reqs = [DecodeRequest(i, np.array([1, 2, 3], np.int32), 4)
            for i in range(10)]
    accepted = [eng.submit(r) for r in reqs]
    assert not all(accepted)              # queue cap rejected some
    # step until the wait EMA breaches and shedding engages, then a fresh
    # submit is rejected for the SLO (not the queue cap)
    for _ in range(50):
        eng.step()
        if eng.shedding:
            break
    assert eng.shedding
    assert not eng.submit(DecodeRequest(99, np.array([1], np.int32), 2))
    # drain; idle decay then re-arms the breach (hysteresis downswing) —
    # a transient overload must not shed forever
    for _ in range(200):
        eng.step()
        if not eng.shedding and not eng.queue \
                and not any(s is not None for s in eng.slots):
            break
    assert not eng.shedding
    assert eng.submit(DecodeRequest(100, np.array([1], np.int32), 2))
    while eng.queue or any(s is not None for s in eng.slots):
        eng.step()
    ledger.close()
    recs = read_ledger(path)
    events = [r["event"] for r in recs]
    assert "slo" in events
    rejected = [r for r in recs if r["event"] == "admit"
                and not r["accepted"]]
    assert {r.get("reason") for r in rejected} >= {"queue_full",
                                                   "slo_shedding"}
    diags = [r for r in recs if r["event"] == "diagnosis"]
    assert diags and diags[0]["reason"] == "slo"
    assert os.path.isdir(diags[0]["bundle"])
    # the scrape carries the serving series
    scrape = reg.render()
    assert "tpu_dist_serve_queue_depth" in scrape
    assert "tpu_dist_kv_pages_free" in scrape
    assert reg.read_value("tpu_dist_serve_rejected_total") >= 2
    assert reg.read_value("tpu_dist_serve_requests_total") >= 1


# ------------------------------------------------- obs + report
def test_request_events_render_in_ledger_report(tmp_path):
    lm, params = _lm_and_params(seed=13)
    path = str(tmp_path / "serve.jsonl")
    ledger = Ledger(path)
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=2, page_size=8, num_pages=16, kv_event_every=1),
        ledger=ledger)
    comps = eng.run([DecodeRequest(i, np.array([1 + i, 5, 9], np.int32), 6)
                     for i in range(4)])
    ledger.close()
    assert len(comps) == 4
    recs = read_ledger(path)
    reqs = [r for r in recs if r["event"] == "request"]
    assert len(reqs) == 4
    for r in reqs:
        assert r["finish_ts"] >= r["first_token_ts"] >= r["admit_ts"]
        assert r["tokens"] == 6
    from tools.ledger_report import summarize

    summary = summarize(recs, out=lambda s: None)
    srv = summary["decode"]["serving"]
    assert srv["completed"] == 4 and srv["rejected"] == 0
    assert srv["queue_wait_s"]["p50"] is not None
    assert srv["ttft_s"]["p99"] >= srv["ttft_s"]["p50"]
    assert 0 < srv["occupancy"] <= 1


# ------------------------------------------------- quantize memo (bugfix)
def test_quantize_for_decode_lru_survives_alternating_trees():
    """The round-9 memo held ONE entry keyed on leaf identities: a server
    alternating two live base trees re-quantized on every call. The LRU
    keyed per (treedef, mode, leaves) must quantize each tree once."""
    import tpu_dist.ops.quant as quant_mod
    from tpu_dist.engine.generate import _quantize_for_decode

    lm, params_a = _lm_and_params(seed=14)
    _, params_b = _lm_and_params(seed=15)
    calls = []
    orig = quant_mod.wo_quantize_params
    quant_mod.wo_quantize_params = lambda p: (calls.append(1), orig(p))[1]
    try:
        for _ in range(3):
            _quantize_for_decode(lm, params_a, "int8_wo")
            _quantize_for_decode(lm, params_b, "int8_wo")
    finally:
        quant_mod.wo_quantize_params = orig
    assert len(calls) == 2, f"expected one quantization per tree, " \
                            f"got {len(calls)}"


# ------------------------------------------------- graceful drain (round 13)
def test_drain_finishes_inflight_sheds_queue_and_frees_pages():
    """Graceful preemption drain: in-flight sequences run to completion
    (their pages were paid for), queued requests are rejected with a
    `shed` admission record, the pool ends fully free, and a `run_end`
    lands — a drained server, not a mid-tick corpse."""
    lm, params = _lm_and_params(seed=13)
    led_records = []
    ledger = Ledger(None, sinks=(led_records.append,))
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=2, page_size=4, num_pages=32), ledger=ledger)
    reqs = [DecodeRequest(i, np.array([1, 2, 3], np.int32), 6)
            for i in range(6)]
    for r in reqs:
        assert eng.submit(r)
    eng.step()  # two slots prefilled + one decode tick; four still queued
    inflight = {s.req.rid for s in eng.slots if s is not None}
    assert len(inflight) == 2 and len(eng.queue) == 4
    comps = eng.drain(reason="sigterm")
    # the two in-flight sequences finished their full generation
    assert {c.rid for c in comps} == inflight
    assert all(c.n_generated == 6 for c in comps)
    # the queue was shed with per-request admission records
    shed = [r for r in led_records if r["event"] == "admit"
            and r.get("reason") == "shed"]
    assert len(shed) == 4
    assert eng.pool.pages_free == eng.pool.num_pages  # everything reclaimed
    ends = [r for r in led_records if r["event"] == "run_end"]
    assert len(ends) == 1 and ends[0]["status"] == "preempted"
    assert ends[0]["shed"] == 4 and ends[0]["completed"] == 2
    scales = [r for r in led_records if r["event"] == "scale"]
    assert [s["action"] for s in scales] == ["drain"]
    # draining is sticky: new submits shed, a second drain is a no-op
    assert not eng.submit(DecodeRequest(99, np.array([1], np.int32), 2))
    assert eng.drain() == []
    assert sum(1 for r in led_records if r["event"] == "run_end") == 1


# ------------------------------------- speculative decoding (round 16)
def _greedy_refs(lm, params, prompts, steps, quant="none"):
    return [np.asarray(generate(lm, params, jnp.asarray(p[None]), steps=s,
                                use_cache=True, quant=quant))[0]
            for p, s in zip(prompts, steps)]


def test_spec_decode_greedy_bit_identical_to_generate():
    """Self-speculation (draft == base) with k=3 over mixed-length
    requests: every emitted stream is BITWISE the non-speculative greedy
    decode — speculation is a throughput optimization, never a model
    change — and an always-agreeing draft clears >1 token per slot-tick,
    finishing in strictly fewer ticks than one-token-per-tick decode."""
    lm, params = _lm_and_params(seed=16)
    prompts = [np.array([1, 9, 17], np.int32), np.array([5], np.int32)]
    steps = [10, 12]
    refs = _greedy_refs(lm, params, prompts, steps)
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=2, page_size=8, num_pages=16, spec_k=3))
    comps = eng.run([DecodeRequest(i, p, s)
                     for i, (p, s) in enumerate(zip(prompts, steps))])
    assert len(comps) == 2
    for c in comps:
        np.testing.assert_array_equal(refs[c.rid], c.tokens)
    assert eng.accepted_per_tick > 1.0
    assert eng.ticks < max(steps)         # sublinear in emitted tokens


@pytest.mark.slow  # tier-1 budget (PR 20): quantized twin of test_spec_decode_greedy_bit_identical_to_generate (in-budget); the int8_wo path itself stays pinned by test_int8_kv_exact_and_flash_kernel_agree
def test_spec_decode_bit_identical_int8_wo():
    """The quantized twin: the draft rides the same int8_wo tree through
    the memoized quantize path; the verified stream stays bitwise the
    quantized ``generate``."""
    lm, params = _lm_and_params(seed=17)
    prompts = [np.array([2, 11, 23], np.int32)]
    refs = _greedy_refs(lm, params, prompts, [10], quant="int8_wo")
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=1, page_size=8, num_pages=16, quant="int8_wo",
        spec_k=2))
    comps = eng.run([DecodeRequest(0, prompts[0], 10)])
    np.testing.assert_array_equal(refs[0], comps[0].tokens)
    assert eng.accepted_per_tick > 1.0


def test_spec_reject_storm_still_progresses_bit_identical():
    """A deliberately wrong draft (same architecture, different random
    init) rejects nearly every proposal. The emission rule still commits
    the base model's own greedy correction every tick — >=1 token per
    slot-tick, output bitwise the non-speculative stream. A bad draft
    costs throughput, never correctness."""
    lm, params = _lm_and_params(seed=18)
    _, draft_params = _lm_and_params(seed=99)  # same shape, wrong weights
    prompts = [np.array([1, 2, 3], np.int32)]
    refs = _greedy_refs(lm, params, prompts, [10])
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=1, page_size=8, num_pages=16, spec_k=3),
        draft_model=lm, draft_params=draft_params)
    comps = eng.run([DecodeRequest(0, prompts[0], 10)])
    np.testing.assert_array_equal(refs[0], comps[0].tokens)
    assert eng.accepted_per_tick >= 1.0   # the progress floor
    assert eng.accepted_per_tick < 3.0    # the storm actually rejected


def test_spec_guards_reject_bad_configs():
    lm, params = _lm_and_params(seed=19)
    small = tiny_lm(vocab_size=32, num_layers=1, d_model=32, num_heads=2,
                    max_len=L)
    small_params = small.init({"params": jax.random.PRNGKey(0)},
                              jnp.zeros((1, L), jnp.int32),
                              train=False)["params"]
    with pytest.raises(ValueError, match="vocab"):
        ServeEngine(lm, params, ServeConfig(spec_k=2),
                    draft_model=small, draft_params=small_params)
    with pytest.raises(ValueError, match="spec_k"):
        ServeEngine(lm, params, ServeConfig(),
                    draft_model=lm, draft_params=params)
    with pytest.raises(ValueError, match="temperature"):
        ServeEngine(lm, params, ServeConfig(spec_k=2, temperature=0.5))


# ------------------------------------- CoW prefix caching (round 16)
def test_prefix_cache_cow_bit_identical_and_saves_pages():
    """Three requests with the SAME 18-token prompt (page_size 4: four
    full pages + a 2-token frontier) under ``prefix_cache``: outputs are
    bitwise the uncached greedy stream, the 2nd/3rd admission map the
    hot prompt onto shared pages, and each forks exactly ONE page — the
    frontier it is about to overwrite. Fresh allocations drop from 18
    (3x6 unshared) to 10, the pinned sublinear footprint."""
    lm, params = _lm_and_params(seed=20)
    prompt = ((np.arange(18, dtype=np.int32) * 5 + 3) % V).astype(np.int32)
    ref = _greedy_refs(lm, params, [prompt], [6])[0]
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=3, page_size=4, num_pages=32, prefix_cache=True))
    comps = eng.run([DecodeRequest(i, prompt, 6) for i in range(3)])
    assert len(comps) == 3
    for c in comps:
        np.testing.assert_array_equal(ref, c.tokens)
    pool = eng.pool
    assert pool.alloc_total == 6 + 2 + 2   # vs 18 without sharing
    assert pool.cow_copies == 2            # one frontier fork per sharer
    assert pool.prefix_hits == 10          # 5 prompt pages x 2 sharers
    assert eng.prefix_hit_rate == pytest.approx(10 / 15)
    assert eng.stats()["pages_per_request"] == pytest.approx(10 / 3)
    assert pool.pages_free == pool.num_pages   # cached pages still count


def test_spec_and_prefix_cache_compose_bit_identical():
    """Both round-16 features on at once (the serving configuration the
    bench publishes): shared-prefix admissions + speculative ticks still
    produce the exact non-speculative, uncached token streams."""
    lm, params = _lm_and_params(seed=21)
    prompt = ((np.arange(9, dtype=np.int32) * 7 + 1) % V).astype(np.int32)
    ref = _greedy_refs(lm, params, [prompt], [8])[0]
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=2, page_size=4, num_pages=32, spec_k=2,
        prefix_cache=True))
    comps = eng.run([DecodeRequest(i, prompt, 8) for i in range(2)])
    assert len(comps) == 2
    for c in comps:
        np.testing.assert_array_equal(ref, c.tokens)
    assert eng.accepted_per_tick > 1.0
    assert eng.pool.prefix_hits > 0


# ------------------------------------- pool refcounts + heap (round 16)
def test_pool_refcount_double_free_and_leak_pins():
    """The CoW refcount discipline: double-free raises (a silently
    recycled page corrupts another sequence's cache), a shared page
    survives its first holder's release, and a full share/release cycle
    leaks nothing — high_water_used stays at the unshared peak because
    sharing never inflates the physical footprint."""
    pool = PagedKVPool(num_layers=1, num_pages=8, page_size=4,
                       num_heads=2, head_dim=8)
    a = pool.alloc(2)
    pool.free(a)
    with pytest.raises(ValueError, match="double-free"):
        pool.free(a)
    prompt = np.arange(8, dtype=np.int32)     # two full pages
    b = pool.alloc(2)
    pool.register_prefix(prompt, b)
    m = pool.share_prefix(prompt)
    assert m.full == 2 and not m.partial and m.pages == b
    assert pool.shared_pages == 2
    pool.free(b)                  # first holder out: pages stay live
    assert pool.shared_pages == 0 and pool.pages_used == 2
    pool.free(m.pages)            # last ref: parked as reclaimable cache
    assert pool.pages_used == 0
    assert pool.pages_free == pool.num_pages      # no leak
    assert pool.high_water_used == 2              # sharing added nothing
    with pytest.raises(ValueError, match="double-free"):
        pool.free(m.pages)


def test_pool_heap_grants_lowest_index_first():
    """Round 16 swapped the free list's O(n log n) full-sort-per-free
    for a heap; the observable grant order is pinned unchanged —
    lowest index first, whatever order pages came back in."""
    pool = PagedKVPool(num_layers=1, num_pages=8, page_size=4,
                       num_heads=2, head_dim=8)
    assert pool.alloc(6) == [0, 1, 2, 3, 4, 5]
    pool.free([4, 1, 3])
    assert pool.alloc(3) == [1, 3, 4]
    pool.free([5, 0, 2])
    assert pool.alloc(4) == [0, 2, 5, 6]


def test_sigterm_routes_run_into_drain():
    """The preemption signal itself: install_sigterm_drain() turns
    SIGTERM into a flag, run() finishes the tick and drains instead of
    dying mid-tick (engine/serve.py round-11 behavior)."""
    import os
    import signal as _signal

    lm, params = _lm_and_params(seed=14)
    led_records = []
    ledger = Ledger(None, sinks=(led_records.append,))
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=1, page_size=4, num_pages=16), ledger=ledger)
    uninstall = eng.install_sigterm_drain()
    try:
        for i in range(4):
            assert eng.submit(DecodeRequest(i, np.array([1, 2], np.int32),
                                            4))
        eng.step()  # slot 0 in flight
        os.kill(os.getpid(), _signal.SIGTERM)  # the scheduler's notice
        comps = eng.run()  # would have processed all 4 without the signal
    finally:
        uninstall()
    # only the in-flight request finished; the rest were shed
    assert {c.rid for c in comps} == {0}
    shed = [r for r in led_records if r["event"] == "admit"
            and r.get("reason") == "shed"]
    assert len(shed) == 3
    assert eng.pool.pages_free == eng.pool.num_pages
    assert [r["status"] for r in led_records
            if r["event"] == "run_end"] == ["preempted"]
    # the handler was restored by uninstall
    assert _signal.getsignal(_signal.SIGTERM) not in (None,)


# ------------------- long-context serving plane (round 19)
def _sp_mesh(n):
    return make_mesh((n,), (SP_AXIS,), devices=jax.devices()[:n])


def test_chunked_prefill_bit_identical_fp32():
    """Chunked prefill (prefill_chunk=8) over MIXED prompt lengths emits
    token-for-token the monolithic greedy stream: each chunk writes its
    rows through the same per-row-position write mask the decode tick
    uses and re-reads the earlier chunks' pages, so splitting the prompt
    changes scheduling, never bits."""
    lm, params = _lm_and_params(seed=22)
    prompts = [((np.arange(13, dtype=np.int32) * 5 + 2) % V),
               ((np.arange(18, dtype=np.int32) * 3 + 7) % V)]
    refs = _greedy_refs(lm, params, prompts, [6, 6])
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=2, page_size=4, num_pages=32, prefill_chunk=8))
    comps = eng.run([DecodeRequest(i, p, 6) for i, p in enumerate(prompts)])
    assert len(comps) == 2
    for c in comps:
        np.testing.assert_array_equal(refs[c.rid], c.tokens)
    # ceil(13/8) + ceil(18/8) chunk dispatches, one per iteration
    assert eng.chunk_ticks == 2 + 3
    assert eng.prefill_token_work == 5 * 8
    assert eng.chunks_pending == 0
    assert eng.pool.pages_free == eng.pool.num_pages


def test_chunked_prefill_bit_identical_int8_wo():
    """Quant twin of the chunked pin: int8 weight-only serving (the
    deployment quant) chunks to the same tokens as its monolithic self.
    (int8 KV pages are the documented exception — chunked re-READS
    quantized rows monolithic prefill never quantizes.)"""
    lm, params = _lm_and_params(seed=23)
    prompt = ((np.arange(11, dtype=np.int32) * 7 + 1) % V).astype(np.int32)
    ref = _greedy_refs(lm, params, [prompt], [5], quant="int8_wo")[0]
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=1, page_size=4, num_pages=16, prefill_chunk=4,
        quant="int8_wo"))
    comps = eng.run([DecodeRequest(0, prompt, 5)])
    np.testing.assert_array_equal(ref, comps[0].tokens)
    assert eng.chunk_ticks == 3


def test_chunked_prefill_interleaves_with_decode():
    """The scheduling contract itself: while a long prompt chunks in, the
    already-decoding request keeps emitting one token per iteration — the
    chunk rides the SAME scheduler step as the decode tick, it never
    stalls the stream (the TPOT-interference bound decode_bench
    measures). Deterministic: pure schedule math, no clocks."""
    lm, params = _lm_and_params(seed=24)
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=2, page_size=4, num_pages=32, prefill_chunk=4))
    assert eng.submit(DecodeRequest(0, np.array([1, 2, 3], np.int32), 12))
    eng.step()                       # short admitted + first token
    short = eng.slots[0]
    assert short is not None and short.generated >= 1
    long_prompt = ((np.arange(17, dtype=np.int32) * 5 + 3) % V)
    assert eng.submit(DecodeRequest(1, long_prompt, 4))
    gen_before, ticks_before = short.generated, eng.ticks
    eng.step()                       # long admitted; chunk 1 + decode tick
    assert eng.chunk_ticks == 1
    assert eng.ticks == ticks_before + 1          # decode never skipped
    assert short.generated == gen_before + 1
    assert eng.chunks_pending == 4                # ceil(17/4) - 1 to go
    eng.run()                                     # drain both
    assert eng.completed == 2


def test_sp_prefill_bit_identical_fp32():
    """Sequence-parallel prefill over a 2-device sp mesh (ring attention
    inside shard_map, each device scattering K/V into ITS local pages)
    emits token-for-token the single-device stream — and a short prompt
    below the threshold rides the monolithic program over the SAME
    sharded pool (the flat block-table translation is exact either way)."""
    lm, params = _lm_and_params(seed=25)
    prompts = [((np.arange(12, dtype=np.int32) * 5 + 2) % V),
               np.array([5, 9], np.int32)]
    refs = _greedy_refs(lm, params, prompts, [6, 6])
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=2, page_size=8, num_pages=8, sp_prefill_threshold=9),
        mesh=_sp_mesh(2))
    comps = eng.run([DecodeRequest(i, p, 6) for i, p in enumerate(prompts)])
    assert len(comps) == 2
    for c in comps:
        np.testing.assert_array_equal(refs[c.rid], c.tokens)
    assert eng.sp_prefills == 1          # only the 12-token prompt
    assert eng.pool.sharded_devices == 2
    assert eng.pool.pages_free == eng.pool.num_pages


def test_sp_prefill_bit_identical_int8_wo():
    """Quant twin of the sp pin: int8 weight-only + sp-sharded prefill
    still matches single-device int8_wo greedy bit-for-bit."""
    lm, params = _lm_and_params(seed=26)
    prompt = ((np.arange(14, dtype=np.int32) * 3 + 5) % V).astype(np.int32)
    ref = _greedy_refs(lm, params, [prompt], [5], quant="int8_wo")[0]
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=1, page_size=8, num_pages=8, sp_prefill_threshold=9,
        quant="int8_wo"), mesh=_sp_mesh(2))
    comps = eng.run([DecodeRequest(0, prompt, 5)])
    np.testing.assert_array_equal(ref, comps[0].tokens)
    assert eng.sp_prefills == 1


def test_sp_context_exceeds_single_device_page_budget():
    """The capacity headline: a 4-device sp pool serves a context LONGER
    than any one device's page budget (23 tokens vs 8 per device), with
    tokens bitwise the unsharded stream — KV capacity scales with the
    mesh, which is what the sharded pool exists for. Eviction then
    returns every striped page to its owner's heap (second admit runs
    on a fully reclaimed pool)."""
    lm, params = _lm_and_params(seed=27)
    prompt = ((np.arange(17, dtype=np.int32) * 5 + 1) % V).astype(np.int32)
    ref = _greedy_refs(lm, params, [prompt], [6])[0]
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=1, page_size=4, num_pages=8, sp_prefill_threshold=9),
        mesh=_sp_mesh(4))
    budget = eng.pool.pages_per_device * eng.cfg.page_size
    assert prompt.size + 6 > budget      # the context one device can't hold
    for _ in range(2):                   # second wave = reclaim proof
        comps = eng.run([DecodeRequest(0, prompt, 6)])
        np.testing.assert_array_equal(ref, comps[0].tokens)
        assert eng.pool.pages_free == eng.pool.num_pages
    assert eng.sp_prefills == 2


def test_sp_and_chunked_guards():
    """Config guards: sp needs a mesh with the 'sp' axis and an sp-bucket-
    divisible max_len; speculative decoding over a sharded pool is the
    named residue and refuses loudly instead of corrupting pages."""
    lm, params = _lm_and_params(seed=28)
    with pytest.raises(ValueError, match="mesh"):
        ServeEngine(lm, params, ServeConfig(sp_prefill_threshold=8))
    with pytest.raises(ValueError, match="sp"):
        ServeEngine(lm, params, ServeConfig(),
                    mesh=make_mesh((2,), ("data",),
                                   devices=jax.devices()[:2]))
    with pytest.raises(ValueError, match="divisible"):
        ServeEngine(lm, params, ServeConfig(
            sp_prefill_threshold=8, page_size=4, max_len=28),
            mesh=_sp_mesh(4))
    with pytest.raises(NotImplementedError, match="speculative"):
        ServeEngine(lm, params, ServeConfig(spec_k=2), mesh=_sp_mesh(2))


def test_chunked_prefix_cache_compose_bit_identical():
    """Chunked prefill + CoW prefix caching: a LATER identical prompt
    maps onto the first one's pages — registered only at the FINAL chunk
    (a partial prompt must never be shareable, so two concurrent chunked
    admits of the same prompt correctly miss) — and both streams stay
    bitwise the uncached monolithic greedy."""
    lm, params = _lm_and_params(seed=29)
    prompt = ((np.arange(13, dtype=np.int32) * 5 + 3) % V).astype(np.int32)
    ref = _greedy_refs(lm, params, [prompt], [6])[0]
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=2, page_size=4, num_pages=32, prefill_chunk=4,
        prefix_cache=True))
    for _ in range(2):
        comps = eng.run([DecodeRequest(0, prompt, 6)])
        np.testing.assert_array_equal(ref, comps[0].tokens)
    assert eng.pool.prefix_hits > 0      # second admit rode shared pages
    assert eng.pool.cow_copies == 1
    assert eng.chunk_ticks >= 4          # both admissions chunked


def test_kv_cache_event_carries_serving_plane_fields(tmp_path):
    """The ledger contract the report + DL006 fixtures lean on: every
    kv_cache event now carries sharded_devices and chunks_pending (and
    the cumulative chunk_ticks for the occupancy trend) — mid-chunking
    snapshots show a nonzero backlog, the final one shows it drained."""
    lm, params = _lm_and_params(seed=30)
    path = tmp_path / "ledger.jsonl"
    ledger = Ledger(str(path))
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=1, page_size=4, num_pages=32, prefill_chunk=4,
        kv_event_every=1), ledger=ledger)
    prompt = ((np.arange(17, dtype=np.int32) * 3 + 2) % V)
    eng.submit(DecodeRequest(0, prompt, 4))
    depths = []
    while eng.queue or any(s is not None for s in eng.slots):
        depths.append(eng.chunks_pending)
        eng.step()
    eng._emit_kv_cache()
    ledger.close()
    kv = [r for r in read_ledger(str(path)) if r["event"] == "kv_cache"]
    assert kv, "no kv_cache events"
    for r in kv:
        assert r["sharded_devices"] == 1
        assert "chunks_pending" in r and "chunk_ticks" in r
    assert max(depths) > 0               # backlog was visible mid-flight
    assert kv[-1]["chunks_pending"] == 0
    assert kv[-1]["chunk_ticks"] == 5    # ceil(17/4)
