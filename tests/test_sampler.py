"""DistributedSampler-equivalence tests (reference C5 semantics)."""

import numpy as np
import pytest

from tpu_dist.data.sampler import DistributedSampler


def _shards(n, world, **kw):
    return [DistributedSampler(n, world, r, **kw).indices() for r in range(world)]


def test_shards_partition_padded_dataset():
    n, world = 103, 4
    shards = _shards(n, world, shuffle=False)
    allidx = np.concatenate(shards)
    # every original index appears at least once (wrap-around padding)
    assert set(range(n)) <= set(allidx.tolist())
    # equal shard sizes (static shapes requirement)
    assert len({len(s) for s in shards}) == 1


def test_strided_assignment_matches_torch_semantics():
    # torch DistributedSampler: rank r takes indices[r::world]
    n, world = 16, 4
    shards = _shards(n, world, shuffle=False)
    for r in range(world):
        np.testing.assert_array_equal(shards[r], np.arange(n)[r::world])


def test_set_epoch_reshuffles_deterministically():
    s = DistributedSampler(100, 2, 0, shuffle=True, seed=5)
    s.set_epoch(0)
    e0 = s.indices()
    s.set_epoch(1)
    e1 = s.indices()
    s.set_epoch(0)
    e0b = s.indices()
    assert not np.array_equal(e0, e1)       # reshuffled per epoch
    np.testing.assert_array_equal(e0, e0b)  # deterministic per (seed, epoch)


def test_same_epoch_consistent_across_ranks():
    # both ranks must derive the SAME permutation or shards overlap/miss
    a = DistributedSampler(50, 2, 0, shuffle=True, seed=9)
    b = DistributedSampler(50, 2, 1, shuffle=True, seed=9)
    a.set_epoch(3), b.set_epoch(3)
    union = set(a.indices().tolist()) | set(b.indices().tolist())
    assert union == set(range(50))
    assert len(set(a.indices().tolist()) & set(b.indices().tolist())) == 0


def test_batch_padding_gives_full_batches():
    s = DistributedSampler(1000, 4, 0, shuffle=True, batch_size=48)
    assert s.num_samples % 48 == 0


def test_drop_last():
    s = DistributedSampler(103, 4, 0, shuffle=False, batch_size=8, drop_last=True)
    assert s.total_size == 96
    assert s.num_samples == 24


def test_invalid_rank_raises():
    with pytest.raises(ValueError):
        DistributedSampler(10, 2, 2)


def test_valid_mask_marks_padding_exactly_once():
    n, world, bs = 103, 4, 8
    total_valid = 0
    for r in range(world):
        s = DistributedSampler(n, world, r, shuffle=False, batch_size=bs)
        idx, valid = s.indices_with_valid()
        assert len(idx) == len(valid)
        total_valid += int(valid.sum())
    # across all ranks, exactly the n real samples are marked valid
    assert total_valid == n


def test_valid_mask_all_true_when_no_padding():
    s = DistributedSampler(64, 4, 0, shuffle=True, batch_size=16)
    _, valid = s.indices_with_valid()
    assert valid.all()
