"""One worker process of a loopback multi-process run (test_multiprocess).

The multi-controller analog of the reference's per-GPU worker
(reference 3.multiprocessing_distributed.py:89-120: spawned child, tcp://
rendezvous, DDP train loop). Each process owns a slice of virtual CPU
devices, rendezvouses through tpu_dist.parallel.launch (env:// flavor), and
drives the SAME Trainer as single-process runs — multi-host is decided by how
the process was launched, not by the engine.

Run via tests/test_multiprocess.py, which injects TPU_DIST_COORDINATOR /
TPU_DIST_NUM_PROCESSES / TPU_DIST_PROCESS_ID and compares final parameters
against the single-process run.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    out = os.environ["TPU_DIST_TEST_OUT"]
    local_devices = int(os.environ.get("TPU_DIST_LOCAL_DEVICES", "2"))

    import jax

    # Per-process virtual CPU devices, pinned BEFORE the distributed client
    # initializes the backend (same recipe as tests/conftest.py).
    jax.config.update("jax_platforms", "cpu")
    from tpu_dist._compat import set_cpu_device_count
    set_cpu_device_count(local_devices)

    from tpu_dist.parallel import launch

    info = launch.initialize()
    expected = int(os.environ.get("TPU_DIST_EXPECT_PROCS", "1"))
    assert jax.process_count() == expected, (jax.process_count(), expected)
    assert jax.local_device_count() == local_devices, jax.local_device_count()

    import numpy as np

    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine.loop import Trainer

    cfg = TrainConfig(
        arch="lenet", dataset="synthetic", epochs=1, batch_size=16, lr=0.05,
        workers=1, print_freq=100, seed=0, synth_train_size=64,
        synth_val_size=32, checkpoint_dir=os.path.join(out, "ckpt"),
        variant=os.environ.get("TPU_DIST_TEST_VARIANT", "jit"),
        grad_compression=os.environ.get("TPU_DIST_TEST_COMPRESSION", "none"),
        steps_per_dispatch=int(os.environ.get("TPU_DIST_TEST_K", "1")))
    trainer = Trainer(cfg)
    best = trainer.fit()

    # Replicated state: every process sees identical global values; process 0
    # records them for the cross-run comparison.
    if jax.process_index() == 0:
        leaves = jax.tree_util.tree_leaves(jax.device_get(trainer.state.params))
        np.savez(os.path.join(out, "params.npz"),
                 **{f"p{i}": np.asarray(x, np.float32)
                    for i, x in enumerate(leaves)})
        with open(os.path.join(out, "result.json"), "w") as f:
            json.dump({"best_acc1": float(best),
                       "process_count": jax.process_count(),
                       "method": info.method,
                       "step": int(jax.device_get(trainer.state.step))}, f)


if __name__ == "__main__":
    main()
