"""Autoregressive decoding: determinism, shapes, and learned-rule recovery."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.engine.generate import generate
from tpu_dist.engine.lm_steps import make_lm_batches, make_lm_train_step
from tpu_dist.engine.state import TrainState
from tpu_dist.models.transformer import tiny_lm
from tpu_dist.ops import make_optimizer
from tpu_dist.parallel.mesh import make_mesh, replicated

V, L = 64, 32


def _lm_and_params(seed=0):
    lm = tiny_lm(vocab_size=V, num_layers=2, d_model=64, num_heads=4,
                 max_len=L)
    params = lm.init({"params": jax.random.PRNGKey(seed)},
                     jnp.zeros((1, L), jnp.int32), train=False)["params"]
    return lm, params


def test_greedy_is_deterministic_and_shaped():
    lm, params = _lm_and_params()
    prompt = jnp.asarray([[1, 2, 3, 4], [9, 8, 7, 6]], jnp.int32)
    a = generate(lm, params, prompt, steps=8)
    b = generate(lm, params, prompt, steps=8)
    assert a.shape == (2, 12)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(a[:, :4]), np.asarray(prompt))
    assert int(jnp.min(a)) >= 0 and int(jnp.max(a)) < V


def test_sampling_uses_rng():
    lm, params = _lm_and_params()
    prompt = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    a = generate(lm, params, prompt, steps=12, temperature=1.0,
                 rng=jax.random.PRNGKey(0))
    b = generate(lm, params, prompt, steps=12, temperature=1.0,
                 rng=jax.random.PRNGKey(1))
    assert not np.array_equal(np.asarray(a[:, 4:]), np.asarray(b[:, 4:]))


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_trained_lm_generates_the_learned_rule():
    """Train on the affine next-token stream (x -> 5x+7 mod V, the script-8
    dataset), then greedy generation must follow the rule."""
    lm, params = _lm_and_params()
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=1000)
    mesh = make_mesh((8,), ("data",))
    state = jax.device_put(TrainState.create(params, {}, tx),
                           replicated(mesh))
    step = make_lm_train_step(lm, tx, mesh, donate=False)

    rng = np.random.default_rng(0)
    start = rng.integers(0, V, (16, 1))
    rows = [start]
    for _ in range(L):
        rows.append((rows[-1] * 5 + 7) % V)  # noiseless rule
    tokens = np.concatenate(rows, axis=1).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, P("data"))
    di, dt = jax.device_put(inputs, sh), jax.device_put(targets, sh)
    key = jax.random.PRNGKey(1)
    for _ in range(60):
        state, _ = step(state, di, dt, key)
        # keep the async dispatch queue bounded: a 60-deep unfetched queue
        # intermittently SIGABRTs the virtual-device CPU backend
        # distlint: disable=DL002 -- bounds the virtual-device async queue (SIGABRT workaround above)
        jax.block_until_ready(state.step)

    prompt = jnp.asarray([[3, (3 * 5 + 7) % V]], jnp.int32)
    out = np.asarray(generate(lm, jax.device_get(state.params), prompt,
                              steps=16))
    follows = sum(int(out[0, i + 1]) == (int(out[0, i]) * 5 + 7) % V
                  for i in range(1, 17))
    assert follows >= 13, (follows, out)


def test_cached_decode_matches_full_recompute():
    """KV-cache decode produces the SAME greedy continuation as the
    full-recompute path (the cache is an optimization, not a model change)."""
    lm, params = _lm_and_params(seed=4)
    prompt = jnp.asarray([[1, 9, 17, 25], [2, 4, 8, 16]], jnp.int32)
    full = generate(lm, params, prompt, steps=10)
    cached = generate(lm, params, prompt, steps=10, use_cache=True)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))


def test_cached_decode_matches_sampling_stream():
    """Same rng + temperature > 0: cached and full paths sample the SAME
    tokens (the cache must not perturb the rng stream)."""
    lm, params = _lm_and_params(seed=5)
    prompt = jnp.asarray([[7, 3, 11, 2]], jnp.int32)
    key = jax.random.PRNGKey(42)
    full = generate(lm, params, prompt, steps=8, temperature=0.8, rng=key)
    cached = generate(lm, params, prompt, steps=8, temperature=0.8, rng=key,
                      use_cache=True)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))


def test_sample_truncation_unit():
    """The sampler math alone (engine.generate._sample, no model): top_k=1
    == argmax at any temperature, a peaked small-p nucleus == argmax, a
    permissive nucleus stays in-vocab — the cheap tier-1 sibling of the
    model-level truncation tests below (slow-marked, PR 11 budget)."""
    from tpu_dist.engine.generate import _sample

    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(0, 1, (4, V)).astype(np.float32))
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    k1, _ = _sample(logits, 2.0, jax.random.PRNGKey(0), top_k=1)
    np.testing.assert_array_equal(greedy, np.asarray(k1))
    peaked, _ = _sample(logits, 0.05, jax.random.PRNGKey(1), top_p=0.5)
    np.testing.assert_array_equal(greedy, np.asarray(peaked))
    free, _ = _sample(logits, 1.0, jax.random.PRNGKey(2), top_p=0.9)
    free = np.asarray(free)
    assert free.min() >= 0 and free.max() < V


@pytest.mark.slow  # tier-1 budget (PR 11): model-level twin of the _sample truncation unit above (test_sample_truncation_unit keeps k-truncation pinned in-budget)
def test_top_k_restricts_to_best_tokens():
    """top_k=1 sampling == greedy argmax regardless of temperature/rng."""
    lm, params = _lm_and_params(seed=6)
    prompt = jnp.asarray([[5, 9]], jnp.int32)
    greedy = generate(lm, params, prompt, steps=8)
    k1 = generate(lm, params, prompt, steps=8, temperature=2.0, top_k=1,
                  rng=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))


@pytest.mark.slow  # tier-1 budget (PR 11): model-level twin of the _sample truncation unit (test_sample_truncation_unit keeps nucleus masking pinned in-budget)
def test_top_p_nucleus_keeps_valid_tokens():
    """top_p sampling only ever emits tokens inside the nucleus: with a
    peaked distribution and small p, it matches greedy."""
    lm, params = _lm_and_params(seed=7)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    # temperature -> 0+ peaks the distribution so the nucleus is one token
    greedy = generate(lm, params, prompt, steps=6)
    p_small = generate(lm, params, prompt, steps=6, temperature=0.05,
                       top_p=0.5, rng=jax.random.PRNGKey(9))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(p_small))
    # and a permissive nucleus still emits in-vocab tokens
    out = generate(lm, params, prompt, steps=6, temperature=1.0, top_p=0.9,
                   rng=jax.random.PRNGKey(4), use_cache=True)
    assert int(jnp.min(out)) >= 0 and int(jnp.max(out)) < V


def test_generate_zero_steps_returns_prompt():
    """steps=0 is a no-op in BOTH paths (the cache prefill must not clamp
    its first-token write into the last prompt column)."""
    import jax.numpy as jnp
    model, params = _lm_and_params()
    prompt = jnp.arange(12, dtype=jnp.int32).reshape(2, 6) % 7
    for use_cache in (False, True):
        out = generate(model, params, prompt, 0, use_cache=use_cache)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))


def test_mesh_data_sharded_decode_matches_single_device():
    """Batch-sharded decode over a ('data',) mesh emits the SAME greedy
    tokens as single-device decode, both full-recompute and KV-cache paths
    (VERDICT r4 #3: sharded inference must be bit-identical on tokens)."""
    lm, params = _lm_and_params(seed=11)
    mesh = make_mesh((8,), ("data",))
    prompt = jnp.tile(jnp.asarray([[1, 5, 9, 2]], jnp.int32), (8, 1))
    prompt = prompt.at[:, 0].set(jnp.arange(8))  # distinct rows per shard
    single = generate(lm, params, prompt, steps=10)
    for use_cache in (False, True):
        sharded = generate(lm, params, prompt, steps=10, mesh=mesh,
                           use_cache=use_cache)
        np.testing.assert_array_equal(np.asarray(single), np.asarray(sharded))


def test_mesh_tp_decode_matches_single_device():
    """TP decode (heads + vocab sharded over 'model') matches single-device
    greedy tokens; KV cache shards its heads axis."""
    lm, params = _lm_and_params(seed=12)
    mesh = make_mesh((4,), ("model",), devices=jax.devices()[:4])
    prompt = jnp.asarray([[3, 7, 1, 4], [2, 2, 9, 9]], jnp.int32)
    single = generate(lm, params, prompt, steps=10)
    for use_cache in (False, True):
        tp = generate(lm, params, prompt, steps=10, mesh=mesh,
                      use_cache=use_cache)
        np.testing.assert_array_equal(np.asarray(single), np.asarray(tp))


@pytest.mark.slow  # tier-1 budget (PR 11): the dp x tp composition of two single-axis parity pins that stay in-budget (test_mesh_data_sharded_decode_matches_single_device, test_mesh_tp_decode_matches_single_device)
def test_mesh_dp_tp_decode_matches_single_device():
    """2-D ('data','model') decode: batch AND heads sharded together."""
    lm, params = _lm_and_params(seed=13)
    mesh = make_mesh((2, 4), ("data", "model"))
    prompt = jnp.asarray([[3, 7, 1, 4], [8, 2, 9, 9]], jnp.int32)
    single = generate(lm, params, prompt, steps=8, use_cache=True)
    both = generate(lm, params, prompt, steps=8, use_cache=True, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(single), np.asarray(both))


def test_mesh_tp_decode_rejects_indivisible_heads():
    import pytest
    lm, params = _lm_and_params(seed=14)  # tiny_lm: 4 heads
    mesh = make_mesh((8,), ("model",))
    with pytest.raises(ValueError, match="num_heads"):
        generate(lm, params, jnp.ones((1, 4), jnp.int32), steps=4, mesh=mesh)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_mesh_sampled_decode_reproduces_replicated_rng():
    """temperature>0 under a data mesh: the rng is replicated, so sampling
    is still deterministic given the key, and matches single-device."""
    lm, params = _lm_and_params(seed=15)
    mesh = make_mesh((8,), ("data",))
    prompt = jnp.tile(jnp.asarray([[6, 1, 3, 8]], jnp.int32), (8, 1))
    key = jax.random.PRNGKey(7)
    single = generate(lm, params, prompt, steps=8, temperature=0.7, rng=key,
                      use_cache=True)
    sharded = generate(lm, params, prompt, steps=8, temperature=0.7, rng=key,
                       use_cache=True, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(single), np.asarray(sharded))


def _moe_and_params(seed=0, **kw):
    from tpu_dist.models.moe import MoETransformerLM

    moe = MoETransformerLM(vocab_size=V, num_layers=2, d_model=64,
                           num_heads=4, num_experts=2, max_len=L, **kw)
    params = moe.init({"params": jax.random.PRNGKey(seed)},
                      jnp.zeros((1, L), jnp.int32), train=False)["params"]
    return moe, params


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_moe_cached_decode_matches_full_recompute():
    """MoE KV-cache decode == full recompute under drop-free capacity
    (capacity_factor >= E/k): per-expert capacity is group-LENGTH-dependent
    (cap = S/E * factor), and the prefill groups P tokens while the full
    path groups the whole padded buffer — only a capacity that admits every
    token makes the two dispatch patterns identical. B=1 additionally
    removes cross-row queue interference."""
    moe, params = _moe_and_params(seed=21, capacity_factor=2.0)
    prompt = jnp.asarray([[3, 9, 27, 17]], jnp.int32)
    full = generate(moe, params, prompt, steps=10)
    cached = generate(moe, params, prompt, steps=10, use_cache=True)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))


@pytest.mark.slow  # tier-1 budget (PR 11): MoE twin of the dense rng-stream pin (test_cached_decode_matches_sampling_stream stays; test_moe_cached_decode_batched_is_valid keeps MoE cached mechanics in-budget)
def test_moe_cached_decode_sampling_stream():
    moe, params = _moe_and_params(seed=22, capacity_factor=2.0)
    prompt = jnp.asarray([[5, 1, 8, 2]], jnp.int32)
    key = jax.random.PRNGKey(11)
    full = generate(moe, params, prompt, steps=6, temperature=0.9, rng=key)
    cached = generate(moe, params, prompt, steps=6, temperature=0.9,
                      rng=key, use_cache=True)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))


def test_moe_cached_decode_batched_is_valid():
    """B>1 MoE cached decode: in-vocab tokens, prompt preserved (exact
    full-path equality is not guaranteed under capacity pressure — see
    generate() docstring — but the mechanics must hold)."""
    moe, params = _moe_and_params(seed=23)
    prompt = jnp.asarray([[1, 2, 3, 4], [9, 8, 7, 6], [4, 4, 4, 4]],
                         jnp.int32)
    out = generate(moe, params, prompt, steps=8, use_cache=True)
    assert out.shape == (3, 12)
    np.testing.assert_array_equal(np.asarray(out[:, :4]), np.asarray(prompt))
    assert int(jnp.min(out)) >= 0 and int(jnp.max(out)) < V


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_moe_top2_cached_decode_matches_full():
    moe, params = _moe_and_params(seed=24, router_top_k=2,
                                  capacity_factor=1.0)  # top-2: E/k = 1
    prompt = jnp.asarray([[2, 6, 10, 14]], jnp.int32)
    full = generate(moe, params, prompt, steps=8)
    cached = generate(moe, params, prompt, steps=8, use_cache=True)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_moe_ep_sharded_decode_matches_single_device():
    """EP decode: expert weights sharded over 'expert' (GShard dispatch
    all-to-alls via GSPMD) emit the same greedy tokens as single-device,
    full-recompute AND cached paths (drop-free capacity)."""
    moe, params = _moe_and_params(seed=25, capacity_factor=2.0)
    mesh = make_mesh((2, 2), ("data", "expert"), devices=jax.devices()[:4])
    prompt = jnp.asarray([[4, 8, 15, 16], [23, 42, 7, 1]], jnp.int32)
    single = generate(moe, params, prompt, steps=8, use_cache=True)
    for use_cache in (False, True):
        ep = generate(moe, params, prompt, steps=8, mesh=mesh,
                      use_cache=use_cache)
        np.testing.assert_array_equal(np.asarray(single), np.asarray(ep))
