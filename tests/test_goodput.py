"""Goodput accounting, run lineage, and progress SLOs (obs.goodput).

The no-jax half hand-writes multi-attempt fixture ledgers with
deterministic timestamps and pins EXACT category expectations — 2
attempts, a torn trailing line, attempt 1 missing its run_end (the
SIGKILL signature) — through the accumulator, job stitching,
ledger_report's goodput/decode sections, and trace_merge's 2-attempt
lanes. The jax half is the acceptance smoke: a 2-attempt CPU LM run
(attempt 1 crashes mid-run, attempt 2 resumes from its checkpoint) whose
stitched goodput categories sum to ~100% of wall-clock including the
restart gap, and a forced progress-SLO breach that emits an `slo` event
and auto-triggers a flight-recorder bundle through the ledger-sink path.
"""

import json
import os

import pytest

from tpu_dist.obs.goodput import (GoodputAccumulator, GoodputMonitor,
                                  accounting, attempt_path,
                                  discover_attempt_paths, job_accounting,
                                  next_attempt_index, split_attempts)
from tpu_dist.obs.ledger import Ledger, read_ledger

# ---------------------------------------------------------------- lineage


def test_attempt_path_naming():
    assert attempt_path("run.jsonl", 0) == "run.jsonl"
    assert attempt_path("run.jsonl", 2) == "run.a2.jsonl"
    assert attempt_path("", 3) == ""


def test_next_attempt_index_and_discovery(tmp_path):
    base = str(tmp_path / "run.jsonl")
    assert next_attempt_index(base) == 0          # nothing on disk yet
    open(base, "w").close()
    assert next_attempt_index(base) == 1          # bare file taken
    open(str(tmp_path / "run.a1.jsonl"), "w").close()
    open(str(tmp_path / "run.a3.jsonl"), "w").close()
    assert next_attempt_index(base) == 4          # holes don't confuse it
    # discovery finds the whole family in attempt order, from ANY member
    fam = [base, str(tmp_path / "run.a1.jsonl"),
           str(tmp_path / "run.a3.jsonl")]
    assert discover_attempt_paths(base) == fam
    assert discover_attempt_paths(fam[2]) == fam
    # .pN process siblings are NOT attempts
    open(str(tmp_path / "run.p1.jsonl"), "w").close()
    assert discover_attempt_paths(base) == fam


def test_next_attempt_index_probes_own_process_files(tmp_path):
    """The shared-FS race guard: process 0 creating the bare ledger first
    must NOT make a later-starting process 1 of the SAME attempt
    self-assign attempt 1 — each process probes only its own files."""
    base = str(tmp_path / "run.jsonl")
    open(base, "w").close()                     # process 0, attempt 0, live
    assert next_attempt_index(base, process_index=1) == 0   # p1 joins a0
    open(str(tmp_path / "run.p1.jsonl"), "w").close()
    assert next_attempt_index(base, process_index=1) == 1   # p1 restarted
    open(str(tmp_path / "run.a1.p1.jsonl"), "w").close()
    assert next_attempt_index(base, process_index=1) == 2
    # process 0 meanwhile counts only its own lineage
    assert next_attempt_index(base, process_index=0) == 1


# ------------------------------------------------- fixture ledgers (no jax)
# Deterministic timestamps; category math pinned EXACTLY below.

def _attempt0_records():
    """Killed mid-run: no run_end; a torn line follows on disk."""
    return [
        {"event": "run_start", "ts": 100.0, "pid": 0, "kind": "lm",
         "config": {}, "mesh": None, "devices": ["cpu"],
         "process_count": 1, "job_id": "run", "attempt": 0},
        # startup: run_start -> compile gap (3.0s)
        {"event": "compile", "ts": 103.0, "pid": 0, "program": "train_step",
         "seconds": 2.5},
        # the warm record charges NOTHING: the compile event above already
        # covers its span via the run_start->compile gap (only streams
        # with no compile event fall back to charging warm spans)
        {"event": "step", "ts": 104.0, "pid": 0, "step": 0, "loss": 2.0,
         "throughput": 900.0, "unit": "tok/s", "data_s": 0.4,
         "dispatch_s": 0.1, "device_s": 0.1, "comm_s": None, "mfu": 0.1,
         "steps_in_dispatch": 1, "warm": True},
        # hot: data 0.5 / dispatch 0.3 / device 1.0 across 2 opt steps
        {"event": "step", "ts": 106.0, "pid": 0, "step": 2, "loss": 1.5,
         "throughput": 1000.0, "unit": "tok/s", "data_s": 0.5,
         "dispatch_s": 0.3, "device_s": 1.0, "comm_s": None, "mfu": 0.2,
         "steps_in_dispatch": 2},
        # a health skip moves that record's per-step device share
        # (1.0 / 2 = 0.5s) from goodput to 'skipped'
        {"event": "health", "ts": 106.1, "pid": 0, "step": 2,
         "kind": "nonfinite", "policy": "skip", "action": "skip",
         "value": 1.0},
        {"event": "step", "ts": 108.0, "pid": 0, "step": 4, "loss": 1.2,
         "throughput": 1100.0, "unit": "tok/s", "data_s": 0.2,
         "dispatch_s": 0.1, "device_s": 0.9, "comm_s": None, "mfu": 0.2,
         "steps_in_dispatch": 2},
    ]


def _attempt1_records():
    """The restarted attempt: completes, with exact eval/ckpt seconds and
    a watchdog stall whose wait resurfaces in the next record's device_s."""
    return [
        {"event": "run_start", "ts": 120.0, "pid": 0, "kind": "lm",
         "config": {}, "mesh": None, "devices": ["cpu"],
         "process_count": 1, "job_id": "run", "attempt": 1},
        {"event": "compile", "ts": 121.0, "pid": 0,
         "program": "train_step"},
        {"event": "step", "ts": 121.5, "pid": 0, "step": 4, "loss": 1.2,
         "throughput": 900.0, "unit": "tok/s", "data_s": 0.2,
         "dispatch_s": 0.1, "device_s": 0.2, "comm_s": None, "mfu": 0.1,
         "steps_in_dispatch": 1, "warm": True},
        {"event": "step", "ts": 124.0, "pid": 0, "step": 8, "loss": 1.0,
         "throughput": 1200.0, "unit": "tok/s", "data_s": 0.5,
         "dispatch_s": 0.5, "device_s": 2.0, "comm_s": None, "mfu": 0.25,
         "steps_in_dispatch": 4},
        # stall: 1.5s badput, deducted from the NEXT record's device_s
        {"event": "stall", "ts": 125.0, "pid": 0, "idle_s": 1.5,
         "threshold_s": 1.0, "stacks": "..."},
        {"event": "step", "ts": 127.0, "pid": 0, "step": 12, "loss": 0.9,
         "throughput": 1100.0, "unit": "tok/s", "data_s": 0.3,
         "dispatch_s": 0.2, "device_s": 2.0, "comm_s": None, "mfu": 0.22,
         "steps_in_dispatch": 4},
        # exact durations stamped by the engines since this round
        {"event": "eval", "ts": 128.0, "pid": 0, "epoch": 0, "loss": 0.8,
         "seconds": 0.8},
        {"event": "ckpt", "ts": 128.5, "pid": 0, "epoch": 1, "path": "ck",
         "is_best": True, "seconds": 0.2},
        {"event": "run_end", "ts": 129.0, "pid": 0, "steps": 9,
         "seconds": 9.0, "status": "ok"},
    ]


def _write_jsonl(path, records, torn=False):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
        if torn:
            f.write('{"event": "step", "ts": 999.0, "pid": 0, "loss"')
    return records


@pytest.fixture
def job_dir(tmp_path):
    base = str(tmp_path / "run.jsonl")
    _write_jsonl(base, _attempt0_records(), torn=True)
    _write_jsonl(str(tmp_path / "run.a1.jsonl"), _attempt1_records())
    return tmp_path


def test_attempt0_accounting_exact():
    part = accounting(_attempt0_records())
    # wall: 100 -> 108 (no run_end: last event stands in)
    assert part["wall_s"] == pytest.approx(8.0)
    cats = part["categories"]
    # run_start -> compile gap; the warm record's span is inside it (the
    # record is just EMITTED later, at the drain), so it adds nothing
    assert cats["startup"] == pytest.approx(3.0)
    assert cats["data_wait"] == pytest.approx(0.7)
    assert cats["dispatch"] == pytest.approx(0.4)
    assert cats["skipped"] == pytest.approx(0.5)         # 1.0 / 2 steps
    assert part["goodput_s"] == pytest.approx(1.9 - 0.5)  # device - skip
    assert cats["idle"] == pytest.approx(8.0 - 3.0 - 0.7 - 0.4 - 0.5 - 1.4)
    # the partition is exhaustive: categories + goodput == wall
    assert sum(cats.values()) + part["goodput_s"] == pytest.approx(8.0)
    assert part["overrun_s"] == 0.0 and part["status"] is None
    assert part["opt_steps"] == 4


def test_attempt1_accounting_exact_stall_and_seconds():
    part = accounting(_attempt1_records())
    assert part["wall_s"] == pytest.approx(9.0)
    cats = part["categories"]
    assert cats["startup"] == pytest.approx(1.0)  # warm span inside the gap
    assert cats["stall"] == pytest.approx(1.5)
    # the stall's wait resurfaced in the 127.0 record's device_s: its
    # contribution drops to 0.5, so goodput = 2.0 + 0.5
    assert part["goodput_s"] == pytest.approx(2.5)
    assert cats["eval"] == pytest.approx(0.8)   # exact field, not the gap
    assert cats["ckpt"] == pytest.approx(0.2)
    assert sum(cats.values()) + part["goodput_s"] == pytest.approx(9.0)
    assert part["status"] == "ok"


def test_job_accounting_stitches_attempts_with_restart_gap(job_dir):
    base = str(job_dir / "run.jsonl")
    records = []
    for p in discover_attempt_paths(base):
        records.extend(read_ledger(p, strict=False))  # torn line skipped
    attempts = split_attempts(records)
    assert len(attempts) == 2
    gp = job_accounting(attempts)
    # stitched wall 100 -> 129; gap 108 -> 120 charged as restart badput
    assert gp["wall_s"] == pytest.approx(29.0)
    assert gp["categories"]["restart_gap"] == pytest.approx(12.0)
    assert gp["goodput_s"] == pytest.approx(1.4 + 2.5)
    assert gp["ratio"] == pytest.approx(3.9 / 29.0, abs=1e-6)
    assert sum(gp["categories"].values()) + gp["goodput_s"] == \
        pytest.approx(29.0)
    a0, a1 = gp["attempts"]
    assert a0["status"] is None          # killed: no run_end on disk
    assert a1["status"] == "ok" and a1["restart_gap_s"] == pytest.approx(12)


def test_lost_intermediate_attempt_keeps_stamped_ordinals(tmp_path):
    """run.a1.jsonl lost: the survivors must keep their STAMPED attempt
    numbers (0 and 2) in both the report and the trace lanes — never be
    renumbered by list position."""
    from tools.trace_merge import main as merge_main

    base = str(tmp_path / "run.jsonl")
    _write_jsonl(base, _attempt0_records())
    a2 = [dict(r) for r in _attempt1_records()]
    a2[0]["attempt"] = 2
    _write_jsonl(str(tmp_path / "run.a2.jsonl"), a2)
    records = []
    for p in discover_attempt_paths(base):
        records.extend(read_ledger(p, strict=False))
    gp = job_accounting(split_attempts(records))
    assert [a["attempt"] for a in gp["attempts"]] == [0, 2]
    out = str(tmp_path / "trace.json")
    assert merge_main([base, "-o", out]) == 0
    with open(out) as f:
        trace = json.load(f)
    assert {e["pid"] for e in trace["traceEvents"]} == {0, 200}


def test_ledger_report_goodput_section_and_cli_discovery(job_dir, capsys):
    from tools.ledger_report import main as report_main, summarize

    base = str(job_dir / "run.jsonl")
    records = []
    for p in discover_attempt_paths(base):
        records.extend(read_ledger(p, strict=False))
    lines = []
    summary = summarize(records, out=lines.append)
    gp = summary["goodput"]
    assert gp["categories"]["restart_gap"] == pytest.approx(12.0)
    txt = "\n".join(lines)
    assert "goodput (2 attempt(s), stitched wall 29.0s)" in txt
    assert "restart gap" in txt and "health-skipped" in txt
    assert "MISSING run_end" in txt
    # the CLI auto-discovers the .a1 sibling from the bare path
    assert report_main([base]) == 0
    out = capsys.readouterr().out
    assert "stitching 2 attempt ledgers" in out
    assert "restart gap" in out
    # --json carries the same dict
    assert report_main([base, "--json"]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["goodput"]["categories"]["restart_gap"] == pytest.approx(12.0)


def test_ledger_report_decode_section(tmp_path, capsys):
    """Per-request serving latency from decode events (the decode_bench
    satellite's ledger half): nearest-rank p50/p99 + tok/s."""
    from tools.ledger_report import summarize

    recs = [{"event": "decode", "ts": 10.0 + i, "pid": 0, "tokens": 100,
             "seconds": 0.1 * (i + 1), "throughput": 100 / (0.1 * (i + 1))}
            for i in range(10)]
    lines = []
    summary = summarize(recs, out=lines.append)
    d = summary["decode"]
    assert d["requests"] == 10 and d["tokens"] == 1000
    assert d["latency_s"]["p50"] == pytest.approx(0.5)   # nearest-rank
    assert d["latency_s"]["p99"] == pytest.approx(1.0)
    assert d["tokens_per_sec"] == pytest.approx(1000 / 5.5, rel=1e-3)
    assert any("latency p50" in ln for ln in lines)


def test_ledger_report_serving_chunk_and_sharded_fields(capsys):
    """Round 19 ledger half: the serving section renders chunk-prefill
    occupancy (cumulative chunk_ticks over tick, first->last windows), the
    chunk-queue depth gauge (max backlog / drained-or-not last), and the
    sp-sharded pool's device count — all from the periodic kv_cache
    snapshots the engine already emits (no-jax: pure dict arithmetic)."""
    from tools.ledger_report import summarize

    reqs = [{"event": "request", "ts": 1.0 + i, "rid": i, "tokens": 8,
             "queue_wait_s": 0.01, "ttft_s": 0.02} for i in range(3)]
    kv = [{"event": "kv_cache", "ts": 2.0, "tick": 10, "chunk_ticks": 8,
           "chunks_pending": 6, "sharded_devices": 4, "active_seqs": 3,
           "slots": 4, "pages_free": 10},
          {"event": "kv_cache", "ts": 3.0, "tick": 20, "chunk_ticks": 12,
           "chunks_pending": 2, "sharded_devices": 4, "active_seqs": 2,
           "slots": 4, "pages_free": 12},
          {"event": "kv_cache", "ts": 4.0, "tick": 30, "chunk_ticks": 12,
           "chunks_pending": 0, "sharded_devices": 4, "active_seqs": 1,
           "slots": 4, "pages_free": 20}]
    lines = []
    summary = summarize(reqs + kv, out=lines.append)
    srv = summary["decode"]["serving"]
    co = srv["chunk_occupancy"]
    assert co["overall"] == pytest.approx(12 / 30)
    assert co["first"] == pytest.approx(0.8)      # 8 chunks / 10 steps
    assert co["last"] == pytest.approx(0.0)       # backlog drained
    assert srv["chunks_pending_max"] == 6
    assert srv["chunks_pending_last"] == 0
    assert srv["sharded_devices"] == 4
    txt = "\n".join(lines)
    assert "chunked prefill: 40% of steps ran a chunk" in txt
    assert "queue depth max 6, last 0" in txt
    assert "sp-sharded KV pool: 4 devices" in txt
    # unsharded single-device runs stay silent (no sp line, no chunk line
    # when the counters never moved)
    kv1 = [dict(k, sharded_devices=1, chunk_ticks=0) for k in kv]
    lines = []
    summary = summarize(reqs + kv1, out=lines.append)
    srv = summary["decode"]["serving"]
    assert srv["sharded_devices"] == 1
    txt = "\n".join(lines)
    assert "sp-sharded" not in txt
    assert "chunked prefill" not in txt


def test_trace_merge_two_attempt_lanes(job_dir):
    """The 2-attempt lane check: each attempt renders its own lane group,
    attempt 1 offset by its true wall distance, restart gap drawn."""
    from tools.trace_merge import main as merge_main

    base = str(job_dir / "run.jsonl")
    out = str(job_dir / "trace.json")
    assert merge_main([base, "-o", out]) == 0
    with open(out) as f:
        trace = json.load(f)
    assert trace["otherData"]["attempts"] == 2
    ev = trace["traceEvents"]
    assert {e["pid"] for e in ev} == {0, 100}     # one lane per attempt
    names = {e["pid"]: e["args"]["name"] for e in ev
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names[0].startswith("attempt 0") and \
        names[100].startswith("attempt 1")
    # attempt 1's clock is offset by its real distance from attempt 0's
    # run_start (20s), so its own events never sit at t~0
    a1_ts = [e["ts"] for e in ev if e["pid"] == 100 and "ts" in e
             and e["name"] != "restart gap"]
    assert min(a1_ts) >= 20e6 - 1
    (gap,) = [e for e in ev if e["name"] == "restart gap"]
    assert gap["dur"] == pytest.approx(12e6)
    assert gap["ts"] == pytest.approx(8e6)        # starts at attempt 0 end


# -------------------------------------------------- live monitor (no jax)

def _emit_step(led, step, **kw):
    # spans far smaller than the emit cadence, so the live partition's
    # itemization can never exceed the (tiny) wall between real emits
    rec = dict(step=step, loss=1.0, throughput=kw.pop("throughput", 1000.0),
               unit="tok/s", data_s=1e-6, dispatch_s=1e-6, device_s=1e-6,
               comm_s=None, mfu=0.1, steps_in_dispatch=1, **kw)
    return led.emit("step", **rec)  # ledger-schema: forward


def test_monitor_periodic_and_final_goodput_events(tmp_path):
    path = str(tmp_path / "run.jsonl")
    led = Ledger(path)
    mon = GoodputMonitor(led, every_s=0.0)  # final-only cadence
    led.add_sink(mon.sink)
    led.emit("run_start", kind="t", config={}, mesh=None, devices=["cpu"],
             process_count=1)
    for i in range(3):
        _emit_step(led, i)
    assert mon.emit_goodput(final=True) is not None
    led.close()
    recs = read_ledger(path)  # schema-valid round trip
    (gp,) = [r for r in recs if r["event"] == "goodput"]
    assert gp["final"] is True and 0.0 <= gp["ratio"] <= 1.0
    assert set(gp["categories"]) >= {"startup", "data_wait", "idle"}
    assert gp["slo_breaches"] == 0


def test_monitor_slo_breach_hysteresis_and_flightrec_autotrigger(tmp_path):
    """A breach emits ONE slo event per episode, and the event reaches the
    flight recorder through the ledger-sink fan-out — a diagnosis bundle
    with reason='slo' and zero new plumbing."""
    from tpu_dist.obs.flightrec import FlightRecorder

    path = str(tmp_path / "run.jsonl")
    led = Ledger(path)
    rec = FlightRecorder(dir=str(tmp_path / "fr"), ledger=led,
                         trace_steps=0)
    led.add_sink(rec.sink)
    # floor no run can meet -> breach as soon as the EMA arms
    mon = GoodputMonitor(led, every_s=0.0, slo_throughput=1e12,
                         unit="tok/s", min_records=2)
    led.add_sink(mon.sink)
    led.emit("run_start", kind="t", config={}, mesh=None, devices=["cpu"],
             process_count=1)
    for i in range(5):
        _emit_step(led, i)
    led.close()
    recs = read_ledger(path)
    slos = [r for r in recs if r["event"] == "slo"]
    assert len(slos) == 1                     # hysteresis: one per episode
    assert slos[0]["kind"] == "throughput" and slos[0]["floor"] == 1e12
    assert mon.breaches == 1
    diags = [r for r in recs if r["event"] == "diagnosis"]
    assert [d["reason"] for d in diags] == ["slo"]
    bundle = diags[0]["bundle"]
    assert os.path.isdir(bundle)
    with open(os.path.join(bundle, "manifest.json")) as f:
        assert json.load(f)["reason"] == "slo"


def test_monitor_steps_rate_ignores_eval_ckpt_boundaries(tmp_path):
    """An epoch boundary (eval + ckpt) legitimately pauses step
    completions; the first step after it must NOT read as a steps/min
    collapse and fire a spurious breach on a healthy run."""
    import time

    led = Ledger(str(tmp_path / "r.jsonl"))
    # floor 1000/min = one step per 60ms: back-to-back emits (µs apart)
    # clear it by orders of magnitude; the 0.3s boundary gap alone would
    # read as 200/min and breach — unless the boundary resets the sample
    mon = GoodputMonitor(led, every_s=0.0, slo_steps_per_min=1000.0,
                         min_records=1, alpha=1.0)  # EMA = last sample
    led.add_sink(mon.sink)
    led.emit("run_start", kind="t", config={}, mesh=None, devices=["cpu"],
             process_count=1)
    _emit_step(led, 0)
    _emit_step(led, 1)  # fast back-to-back: rate far above the floor
    assert mon.breaches == 0
    led.emit("eval", epoch=0, loss=1.0)
    led.emit("ckpt", epoch=1, path="ck", is_best=True)
    time.sleep(0.3)  # a "slow" boundary gap; dt alone would breach
    _emit_step(led, 2)  # first post-boundary step: no steps/min sample
    _emit_step(led, 3)  # and the next dt is steady again
    assert mon.breaches == 0
    led.close()


def test_monitor_recovery_rearms_breach(tmp_path):
    led = Ledger(str(tmp_path / "r.jsonl"))
    mon = GoodputMonitor(led, every_s=0.0, slo_throughput=500.0,
                         min_records=2, alpha=1.0)  # EMA = last sample
    led.add_sink(mon.sink)
    led.emit("run_start", kind="t", config={}, mesh=None, devices=["cpu"],
             process_count=1)
    for thr in (1000.0, 100.0, 100.0, 1000.0, 100.0):
        _emit_step(led, 0, throughput=thr)
    led.close()
    assert mon.breaches == 2  # breach, recover, breach again


def test_metrics_sink_goodput_gauges_and_slo_counter():
    from tpu_dist.obs.metrics import MetricsRegistry, metrics_ledger_sink

    reg = MetricsRegistry()
    sink = metrics_ledger_sink(reg)
    text = reg.render()
    # pre-registered at zero: absence and zero are different answers
    assert "tpu_dist_goodput_ratio 0" in text
    assert 'tpu_dist_slo_breaches_total{kind="steps_per_min"} 0' in text
    assert 'tpu_dist_badput_seconds{category="restart_gap"} 0' in text
    assert "tpu_dist_last_step_age_s -1" in text
    sink({"event": "goodput", "ts": 1.0, "wall_s": 10.0, "goodput_s": 4.0,
          "ratio": 0.4, "categories": {"startup": 3.0, "idle": 3.0}})
    sink({"event": "slo", "ts": 1.1, "step": 3, "kind": "throughput",
          "value": 10.0, "floor": 100.0})
    text = reg.render()
    assert "tpu_dist_goodput_ratio 0.4" in text
    assert 'tpu_dist_badput_seconds{category="startup"} 3' in text
    assert 'tpu_dist_slo_breaches_total{kind="throughput"} 1' in text


def test_healthz_reports_last_step_age(tmp_path):
    """The progress-aware /healthz satellite: the body carries
    last_step_age_s (computed at read time, no registry render); /livez
    stays a bare liveness probe."""
    import urllib.request

    from tpu_dist.obs.metrics import (MetricsRegistry, metrics_ledger_sink,
                                      serve_metrics)

    reg = MetricsRegistry()
    sink = metrics_ledger_sink(reg)
    srv = serve_metrics(reg, port=0, host="127.0.0.1")
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5) as r:
            assert r.read().decode() == "ok last_step_age_s=-1.000\n"
        import time

        sink({"event": "step", "ts": time.time(), "step": 0, "loss": 1.0,
              "throughput": 1.0, "unit": "t", "data_s": 0, "dispatch_s": 0,
              "device_s": 0, "comm_s": None, "mfu": None})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5) as r:
            body = r.read().decode()
        age = float(body.split("last_step_age_s=")[1])
        assert 0.0 <= age < 60.0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/livez", timeout=5) as r:
            assert r.read().decode() == "ok\n"
    finally:
        srv.close()


@pytest.mark.slow
def test_decode_bench_per_request_cli(tmp_path):
    """Full decode_bench CLI at a tiny geometry: per-request latency
    percentiles + request tok/s in the headline JSON, one decode ledger
    event per request (slow: a fresh-process jax import + compile; the
    percentile math and the report section are covered no-jax above)."""
    import subprocess
    import sys as _sys

    led = str(tmp_path / "dec.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [_sys.executable, "tools/decode_bench.py", "--batch", "2",
         "--prompt-len", "8", "--steps", "4", "--vocab-size", "64",
         "--d-model", "32", "--num-layers", "1", "--num-heads", "2",
         "--skip-full", "--trials", "1", "--requests", "3",
         "--ledger", led],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    head = json.loads(out.stdout.strip().splitlines()[-1])
    assert head["requests"] == 3
    assert head["latency_ms"]["p50_ms"] > 0
    assert head["latency_ms"]["p99_ms"] >= head["latency_ms"]["p50_ms"]
    assert head["request_tokens_per_sec"] > 0
    recs = read_ledger(led)
    assert len([r for r in recs if r["event"] == "decode"]) == 3
    from tools.ledger_report import summarize

    summary = summarize(recs, out=lambda s: None)
    assert summary["decode"]["requests"] == 3


# ------------------------------------------ ACCEPTANCE: 2-attempt LM smoke

def test_two_attempt_lm_smoke_goodput_slo_flightrec(tmp_path):
    """ISSUE 7 acceptance: attempt 1 dies mid-run, attempt 2 resumes from
    its checkpoint under attempt=-1 auto-lineage; ledger_report renders a
    goodput section whose categories sum to ~100% of the stitched wall
    including the restart gap, and a forced progress-SLO breach emits an
    `slo` event that auto-triggers a flightrec bundle."""
    import dataclasses

    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    path = str(tmp_path / "run.jsonl")
    ck = str(tmp_path / "ck")
    cfg = LMConfig(epochs=2, batch_size=8, seq_len=32, vocab_size=64,
                   num_layers=1, d_model=32, num_heads=2, synth_tokens=2048,
                   print_freq=4, seed=0, ledger_path=path,
                   checkpoint_dir=ck, flightrec_trace_steps=0,
                   goodput_every_s=0.0)
    tr1 = LMTrainer(cfg)
    assert tr1.obs.attempt == 0 and tr1.obs.job_id == "run"
    real_validate = tr1.validate

    def dies_in_epoch_1(epoch=0):
        if epoch >= 1:  # epoch 0 completes (ckpt lands), epoch 1 dies
            raise RuntimeError("preempted")
        return real_validate(epoch)

    tr1.validate = dies_in_epoch_1
    with pytest.raises(RuntimeError, match="preempted"):
        tr1.fit()
    assert os.path.exists(path)

    # attempt 2: auto-lineage picks .a1, resumes from the epoch-0 ckpt,
    # and a floor no CPU run can meet forces the SLO breach
    cfg2 = dataclasses.replace(
        cfg, attempt=-1, resume=os.path.join(ck, "lm-checkpoint.msgpack"),
        slo_steps_per_min=1e9)
    tr2 = LMTrainer(cfg2)
    assert tr2.obs.attempt == 1
    tr2.fit()
    a1 = str(tmp_path / "run.a1.jsonl")
    assert os.path.exists(a1)

    from tools.ledger_report import summarize

    records = read_ledger(path, strict=False) + read_ledger(a1,
                                                            strict=False)
    lines = []
    summary = summarize(records, out=lines.append)
    gp = summary["goodput"]
    # categories + goodput sum to ~100% of the stitched wall-clock,
    # restart gap included (idle absorbs residue; only double-attribution
    # could break the sum, and it must not have happened here)
    total = sum(gp["categories"].values()) + gp["goodput_s"]
    assert total == pytest.approx(gp["wall_s"], rel=0.01)
    assert gp["overrun_s"] == 0.0
    assert gp["categories"]["restart_gap"] > 0
    assert gp["goodput_s"] > 0 and gp["categories"]["startup"] > 0
    assert len(gp["attempts"]) == 2
    assert gp["attempts"][0]["status"] == "crashed"
    assert gp["attempts"][1]["status"] == "ok"
    txt = "\n".join(lines)
    assert "goodput (2 attempt(s)" in txt and "restart gap" in txt
    # each attempt emitted its final partition event
    finals = [r for r in records if r["event"] == "goodput"
              and r.get("final")]
    assert len(finals) == 2
    # the forced breach: slo event -> flightrec bundle, via the sink path
    slos = [r for r in records if r["event"] == "slo"]
    assert slos and slos[0]["kind"] == "steps_per_min"
    diags = [r for r in records if r["event"] == "diagnosis"
             and r["reason"] == "slo"]
    assert diags and os.path.isdir(diags[0]["bundle"])
    assert gp["slo_breaches"] == len(slos)
    # run lineage stamped in run_start
    starts = [r for r in records if r["event"] == "run_start"]
    assert [s["attempt"] for s in starts] == [0, 1]
    assert all(s["job_id"] == "run" for s in starts)
    assert starts[1]["resumed_from"] == cfg2.resume


@pytest.mark.slow  # tier-1 budget (PR 14): the serve-trace-replay
# mechanics this CLI drives are pinned in-budget at the engine level
# (test_serve.py continuous-vs-static schedule math) and end to end by the
# fleet acceptance (test_fleet.py::test_fleet_ci_scenario_acceptance),
# which replays Poisson traffic through the same ServeEngine across three
# supervised processes
def test_decode_bench_trace_replay_cli(tmp_path):
    """The throughput-under-load acceptance pin, on the real CLI surface:
    `decode_bench --trace` replays one seeded Poisson trace through the
    continuous-batching engine AND static drain-batching at equal slot
    capacity, and the headline JSON's `serving` block must show continuous
    strictly ahead on completed-requests-per-tick and occupancy (both are
    deterministic schedule arithmetic — the wall req/s rides along for the
    dashboards). Tiny geometry: the pin is the comparison, not the scale."""
    import subprocess
    import sys as _sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [_sys.executable, "tools/decode_bench.py", "--batch", "2",
         "--prompt-len", "8", "--steps", "4", "--vocab-size", "64",
         "--d-model", "32", "--num-layers", "1", "--num-heads", "2",
         "--skip-full", "--trials", "1", "--requests", "0",
         "--trace", "12", "--min-prompt", "4", "--max-prompt", "12",
         "--min-out", "2", "--max-out", "12", "--serve-slots", "3",
         "--page-size", "8"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    head = json.loads(out.stdout.strip().splitlines()[-1])
    srv = head["serving"]
    assert srv["requests"] == 12 and srv["completed"] == 12
    static = srv["static"]
    assert static["completed"] == 12
    # the perf pin: strictly more completed work per tick, busier slots
    assert srv["requests_per_tick"] > static["requests_per_tick"], srv
    assert srv["occupancy"] > static["occupancy"], srv
    assert srv["requests_per_sec"] > 0 and srv["tokens_per_sec"] > 0
    assert srv["ttft_ms"]["p99"] >= srv["ttft_ms"]["p50"] > 0
    assert srv["tpot_ms"]["p50"] > 0
    # and bench_track judges the serving number like data_s: a regressed
    # replay fails the gate, pre-serving history abstains
    from tools.bench_track import load_points, track

    hp = tmp_path / "head.json"
    hp.write_text(json.dumps(head))
    points = load_points([str(hp)])
    assert points[0]["serving_rpt"] == srv["requests_per_tick"]
    report = track(points, threshold_pct=5.0)
    m = report["metrics"][head["metric"]]
    assert m["serving_latest"] == srv["requests_per_tick"]
    assert m["serving_best_prior"] is None  # abstains: no prior history
    worse = dict(head, serving=dict(srv, requests_per_tick=srv[
        "requests_per_tick"] * 0.5))
    wp = tmp_path / "worse.json"
    wp.write_text(json.dumps(worse))
    report = track(load_points([str(hp), str(wp)]), threshold_pct=5.0)
    assert report["metrics"][head["metric"]]["serving_regressed"]


def test_decode_bench_long_context_acceptance_cli(tmp_path):
    """ISSUE 19 acceptance, on the real CLI surface: the checked-in
    mixed-traffic trace (tools/traces/longcontext_mix.json — 14 short chat
    requests + one 16384-token admit in flight) replays through chunked
    prefill under the virtual cost-model clock, and the headline JSON must
    show (a) short-request TPOT p99 within 25% of the no-long-prompt
    baseline — the whole point of chunking: interference is bounded by
    chunk/tick_floor (128/1024 = 12.5%), not prompt_len/tick_floor
    (1600%) — and (b) a context longer than ONE device's page budget
    served end-to-end on a 4-device cpu sp submesh. Both numbers are
    deterministic schedule arithmetic (virtual clock, seeded trace), so
    the bounds are exact pins, not flaky wall-clock measurements.
    bench_track then gates ttft_long_p99 and tpot_interference_pct like
    data_s: lower is better, pre-long-context history abstains."""
    import subprocess
    import sys as _sys

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run(
        [_sys.executable, "tools/decode_bench.py",
         "--long-context", "tools/traces/longcontext_mix.json",
         "--vocab-size", "256", "--d-model", "32", "--num-layers", "1",
         "--num-heads", "2", "--serve-slots", "4", "--page-size", "64",
         "--prefill-chunk", "128", "--sp-capacity", "4"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    head = json.loads(out.stdout.strip().splitlines()[-1])
    assert head["metric"] == "lm_longcontext_serving"
    srv = head["serving"]
    assert srv["mode"] == "long_context"
    assert srv["requests"] == 15 and srv["completed"] == 15
    assert srv["long_requests"] == 1
    # (a) the interference pin: a 16k admit in flight costs the short
    # requests' TPOT p99 at most 25% — chunked prefill's acceptance bound
    assert srv["tpot_interference_pct"] is not None
    assert srv["tpot_interference_pct"] <= 25.0, srv
    assert srv["ttft_long_p99"] is not None and srv["ttft_long_p99"] > 0
    assert srv["tpot_baseline_p99"] > 0
    # the 16384-token prompt really went through the chunk path
    assert srv["chunk_ticks"] >= 16384 // 128
    # (b) the sp capacity pin: context > one device's page budget, served
    sp = srv["sp_capacity"]
    assert sp["exceeds_single_device"], sp
    assert sp["context_tokens"] > sp["device_token_budget"]
    assert sp["completed"] == 1 and sp["sp_prefills"] == 1
    assert sp["devices"] == 4
    # bench_track: both tail numbers gate lower-is-better with abstention
    from tools.bench_track import load_points, track

    hp = tmp_path / "head.json"
    hp.write_text(json.dumps(head))
    points = load_points([str(hp)])
    assert points[0]["serving_ttfl"] == srv["ttft_long_p99"]
    assert points[0]["serving_tip"] == srv["tpot_interference_pct"]
    # kv_cache is null in long mode: requests_per_tick is the value
    assert points[0]["value"] == srv["requests_per_tick"]
    report = track(points, threshold_pct=5.0)
    m = report["metrics"]["lm_longcontext_serving"]
    assert m["ttft_long_best_prior"] is None      # abstains: no history
    assert m["interference_best_prior"] is None
    assert report["ok"]
    worse = dict(head, serving=dict(
        srv, ttft_long_p99=srv["ttft_long_p99"] * 1.5,
        tpot_interference_pct=srv["tpot_interference_pct"] + 30.0))
    wp = tmp_path / "worse.json"
    wp.write_text(json.dumps(worse))
    report = track(load_points([str(hp), str(wp)]), threshold_pct=5.0)
    m = report["metrics"]["lm_longcontext_serving"]
    assert m["ttft_long_regressed"] and m["interference_regressed"]
    assert not report["ok"]
    assert not report["ok"]
