"""Pallas fused AdamW kernel: exact optax.adamw numerics (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.ops.pallas_adamw import FusedAdamW, fused_adamw_leaf


def _scalars(lr, b1, b2, eps, wd, t, cs=1.0):
    # slot 7 is the global-norm clip scale; 1.0 = clipping off
    return jnp.asarray([[lr, b1, b2, eps, wd,
                         1.0 - b1 ** t, 1.0 - b2 ** t, cs]], jnp.float32)


@pytest.mark.parametrize("shape", [(7,), (130,), (3, 3, 16, 32)])
def test_fused_leaf_matches_reference_math(shape):
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.normal(size=shape), jnp.float32)
    g = jnp.asarray(rng.normal(size=shape), jnp.float32)
    m = jnp.asarray(rng.normal(size=shape), jnp.float32)
    v = jnp.asarray(rng.uniform(0.01, 1.0, size=shape), jnp.float32)
    lr, b1, b2, eps, wd, t = 0.1, 0.9, 0.95, 1e-8, 0.1, 3
    p2, m2, v2 = fused_adamw_leaf(p, g, m, v,
                                  _scalars(lr, b1, b2, eps, wd, t),
                                  interpret=True)
    m_ref = b1 * m + (1 - b1) * g
    v_ref = b2 * v + (1 - b2) * g * g
    mhat = m_ref / (1 - b1 ** t)
    vhat = v_ref / (1 - b2 ** t)
    p_ref = p - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * p)
    np.testing.assert_allclose(np.asarray(p2), np.asarray(p_ref),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m2), np.asarray(m_ref),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(v2), np.asarray(v_ref),
                               rtol=1e-6, atol=1e-7)


def test_fused_adamw_matches_optax_over_steps():
    """Multi-step trajectory equality with optax.adamw (the engine's adamw)
    over a small param tree, including bias-correction warmup steps."""
    from tpu_dist.ops.optim import make_optimizer

    rng = np.random.default_rng(1)
    params = {"w": jnp.asarray(rng.normal(size=(40, 9)), jnp.float32),
              "b": jnp.asarray(rng.normal(size=(9,)), jnp.float32)}
    sched = lambda s: 0.05
    tx_ref = make_optimizer(0.05, weight_decay=0.1, kind="adamw",
                            schedule=sched, b1=0.9, b2=0.95, eps=1e-8)
    tx_fused = FusedAdamW(sched, b1=0.9, b2=0.95, eps=1e-8,
                          weight_decay=0.1, interpret=True)
    p_ref, o_ref = params, tx_ref.init(params)
    p_f, o_f = params, tx_fused.init(params)
    for step in range(4):
        g = jax.tree.map(
            lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32),
            params)
        upd, o_ref = tx_ref.update(g, o_ref, p_ref)
        p_ref = jax.tree.map(lambda p, u: p + u, p_ref, upd)
        p_f, o_f = tx_fused.apply(p_f, g, o_f, jnp.int32(step))
        for k in params:
            np.testing.assert_allclose(np.asarray(p_f[k]),
                                       np.asarray(p_ref[k]),
                                       rtol=2e-5, atol=2e-6, err_msg=k)


@pytest.mark.slow  # tier-1 budget (PR 14): convergence follows from the
# exact optax equality already pinned in-budget
# (test_fused_adamw_matches_optax_over_steps +
# test_fused_adamw_clip_matches_optax_chain); this e2e fit only re-proves
# the same update rule through the trainer plumbing
def test_lm_trainer_with_fused_adamw_converges():
    """LMTrainer --optimizer fused_adamw end-to-end: perplexity drops on
    the learnable synthetic corpus (the engine dispatches on the apply()
    protocol — same plumbing as image fused_sgd)."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    kw = dict(vocab_size=64, seq_len=32, d_model=32, num_layers=1,
              num_heads=2, batch_size=16, epochs=2, synth_tokens=4096,
              lr=2e-2, seed=0, print_freq=200)
    ppl = LMTrainer(LMConfig(optimizer="fused_adamw", **kw)).fit()
    assert ppl < 40, ppl  # vocab 64: uniform would be 64


def test_fused_adamw_clip_matches_optax_chain():
    """clip_norm > 0 reproduces the optax clip_by_global_norm -> adamw
    chain exactly (the fused kernel applies the same scale inside the
    update sweep instead of a standalone clip pass). Grads are drawn large
    so the clip actually triggers, and one small-grad step checks the
    below-threshold identity branch too."""
    from tpu_dist.ops.optim import make_optimizer

    rng = np.random.default_rng(2)
    params = {"w": jnp.asarray(rng.normal(size=(33, 5)), jnp.float32),
              "b": jnp.asarray(rng.normal(size=(5,)), jnp.float32)}
    sched = lambda s: 0.05
    clip = 0.5
    tx_ref = make_optimizer(0.05, weight_decay=0.1, kind="adamw",
                            schedule=sched, b1=0.9, b2=0.95, eps=1e-8,
                            grad_clip=clip)
    tx_fused = FusedAdamW(sched, b1=0.9, b2=0.95, eps=1e-8,
                          weight_decay=0.1, clip_norm=clip, interpret=True)
    p_ref, o_ref = params, tx_ref.init(params)
    p_f, o_f = params, tx_fused.init(params)
    for step, mag in enumerate((3.0, 10.0, 1e-3)):  # clip, clip, identity
        g = jax.tree.map(
            lambda p: jnp.asarray(mag * rng.normal(size=p.shape),
                                  jnp.float32), params)
        upd, o_ref = tx_ref.update(g, o_ref, p_ref)
        p_ref = jax.tree.map(lambda p, u: p + u, p_ref, upd)
        p_f, o_f = tx_fused.apply(p_f, g, o_f, jnp.int32(step))
        for k in params:
            np.testing.assert_allclose(np.asarray(p_f[k]),
                                       np.asarray(p_ref[k]),
                                       rtol=2e-5, atol=2e-6, err_msg=k)
