"""Int8 quantized matmuls (ops.quant): numerics, STE, decode, shardings.

The quant subsystem's contract, pinned end to end: symmetric per-channel
quantization stays within half a scale step, the quantized forward tracks
the fp forward, the straight-through backward IS the fp backward, training
under quant="int8" still learns the tiny-LM harness, weight-only int8
decode reproduces bf16 greedy tokens, and the whole thing runs under a
dp x tp GSPMD mesh unchanged (scales are tiny replicated leaves).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dist.engine.generate import generate
from tpu_dist.engine.lm_steps import make_lm_batches, make_lm_train_step
from tpu_dist.engine.state import TrainState
from tpu_dist.models.transformer import tiny_lm
from tpu_dist.ops import make_optimizer
from tpu_dist.ops.quant import (QUANT_MODES, dequantize, quant_einsum,
                                quantize_int8, validate_quant,
                                wo_fake_quant, wo_quantize_params)
from tpu_dist.parallel.mesh import make_mesh, replicated
from tpu_dist.parallel.tp import shard_lm_params

V, L = 64, 32


def _lm(quant="none", **kw):
    return tiny_lm(vocab_size=V, num_layers=2, d_model=64, num_heads=4,
                   max_len=L, quant=quant, **kw)


def _params(lm, seed=0):
    return lm.init({"params": jax.random.PRNGKey(seed)},
                   jnp.zeros((1, L), jnp.int32), train=False)["params"]


# ---- quantize/dequantize ---------------------------------------------------

def test_roundtrip_error_within_half_scale():
    """Symmetric int8: |x - dequant(quant(x))| <= scale/2 elementwise, with
    one scale per output channel (amax over the contracting dim)."""
    w = jnp.asarray(np.random.default_rng(0).normal(size=(48, 24)) * 3.0,
                    jnp.float32)
    q, scale = quantize_int8(w, (0,))
    assert q.dtype == jnp.int8 and scale.shape == (1, 24)
    err = jnp.abs(dequantize(q, scale) - w)
    assert bool(jnp.all(err <= scale * 0.5 + 1e-6))
    # scale saturates at amax/127: the extreme element is exactly invertible
    assert bool(jnp.all(jnp.max(jnp.abs(dequantize(q, scale)), axis=0)
                        <= jnp.max(jnp.abs(w), axis=0) + 1e-6))


def test_all_zero_channel_quantizes_to_zero():
    w = jnp.zeros((16, 4), jnp.float32).at[:, 0].set(1.0)
    q, scale = quantize_int8(w, (0,))
    assert bool(jnp.all(q[:, 1:] == 0)) and bool(jnp.all(jnp.isfinite(scale)))


def test_validate_quant_rejects_unknown():
    for m in QUANT_MODES:
        assert validate_quant(m) == m
    with pytest.raises(ValueError):
        validate_quant("fp8")


# ---- quantized einsum ------------------------------------------------------

def test_quant_einsum_tracks_fp_dense():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 16, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 24)), jnp.float32)
    yq = quant_einsum("abd,dZ->abZ", x, w)
    yf = jnp.einsum("abd,dZ->abZ", x, w)
    # int8 x int8 with per-row/per-channel scales: ~1% relative error
    assert float(jnp.max(jnp.abs(yq - yf))) < 0.05 * float(jnp.max(jnp.abs(yf)))


def test_quant_einsum_batched_moe_spec():
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.normal(size=(2, 4, 8, 16)), jnp.float32)  # gecd
    w = jnp.asarray(rng.normal(size=(4, 16, 12)), jnp.float32)    # edf
    yq = quant_einsum("gecd,edf->gecf", a, w)
    yf = jnp.einsum("gecd,edf->gecf", a, w)
    assert float(jnp.max(jnp.abs(yq - yf))) < 0.05 * float(jnp.max(jnp.abs(yf)))


def test_ste_gradients_equal_fp_gradients():
    """The STE contract exactly: grads of the quantized dot == grads of the
    fp dot of the same operands (not merely 'close')."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    co = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)  # non-trivial g
    gq = jax.grad(lambda a, b: jnp.vdot(quant_einsum("ad,dZ->aZ", a, b), co),
                  argnums=(0, 1))(x, w)
    gf = jax.grad(lambda a, b: jnp.vdot(jnp.einsum("ad,dZ->aZ", a, b), co),
                  argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gq[0]), np.asarray(gf[0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gq[1]), np.asarray(gf[1]), rtol=1e-6)


def test_wo_fake_quant_ste_identity_gradient():
    w = jnp.asarray(np.random.default_rng(4).normal(size=(16, 8)), jnp.float32)
    g = jax.grad(lambda b: jnp.sum(wo_fake_quant(b) * 2.0))(w)
    np.testing.assert_allclose(np.asarray(g), 2.0 * np.ones_like(w))


# ---- model-level forward agreement ----------------------------------------

def test_quant_forward_tracks_bf16_forward():
    """quant='int8' logits stay close to the unquantized model's on the SAME
    params — close enough that next-token ranking is preserved for the
    overwhelming majority of positions at init."""
    lm_fp = _lm()
    params = _params(lm_fp)
    tok = jnp.asarray(np.random.default_rng(5).integers(0, V, (4, L)),
                      jnp.int32)
    logits_fp = lm_fp.apply({"params": params}, tok, train=False)
    for mode in ("int8", "int8_wo"):
        logits_q = _lm(mode).apply({"params": params}, tok, train=False)
        rel = (jnp.max(jnp.abs(logits_q - logits_fp))
               / jnp.max(jnp.abs(logits_fp)))
        assert float(rel) < 0.15, (mode, float(rel))
        agree = jnp.mean((jnp.argmax(logits_q, -1)
                          == jnp.argmax(logits_fp, -1)).astype(jnp.float32))
        assert float(agree) > 0.9, (mode, float(agree))


def test_param_tree_identical_across_modes():
    """The quant knob must never fork param structure (checkpoints, TP rules
    and the warm-start graft all key on the tree)."""
    ref = jax.tree_util.tree_structure(_params(_lm()))
    for mode in ("int8", "int8_wo"):
        assert jax.tree_util.tree_structure(_params(_lm(mode))) == ref


# ---- training --------------------------------------------------------------

def _affine_rows(n=16):
    rng = np.random.default_rng(0)
    rows = [rng.integers(0, V, (n, 1))]
    for _ in range(L):
        rows.append((rows[-1] * 5 + 7) % V)
    return np.concatenate(rows, axis=1).astype(np.int32)


def _train(lm, params, mesh, steps=60, lr=0.05):
    tx = make_optimizer(lr, 0.9, 0.0, steps_per_epoch=1000)
    state = jax.device_put(TrainState.create(params, {}, tx),
                           replicated(mesh))
    step = make_lm_train_step(lm, tx, mesh, donate=False)
    inputs, targets = make_lm_batches(_affine_rows())
    sh = NamedSharding(mesh, P("data"))
    di, dt = jax.device_put(inputs, sh), jax.device_put(targets, sh)
    key = jax.random.PRNGKey(1)
    m = None
    for _ in range(steps):
        state, m = step(state, di, dt, key)
        # distlint: disable=DL002 -- bounds the async queue on the CPU sim (trailing comment)
        jax.block_until_ready(state.step)  # bound the async queue (CPU sim)
    m = jax.device_get(m)
    return state, float(m["loss_sum"]) / float(m["count"])


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_int8_training_converges_on_tiny_lm_harness():
    """The tiny-LM convergence harness (the affine rule of
    test_generate/test_lm) under quant='int8': the quantized train step must
    drive the loss well below the ~ln(V)=4.16 init plateau, like the bf16
    path does — the existing parity bound for 'this engine still learns'."""
    mesh = make_mesh((8,), ("data",))
    lm_q = _lm("int8")
    _, loss_q = _train(lm_q, _params(lm_q), mesh)
    assert loss_q < 1.0, loss_q  # fp run reaches ~0.3; init is ~4.16


# ---- weight-only decode ----------------------------------------------------

def test_wo_quantize_params_structure():
    params = _params(_lm())
    wq = wo_quantize_params(params)
    # every dense kernel became int8 with a sibling fp32 scale
    for name in ("qkv", "proj", "mlp_in", "mlp_out"):
        sub = wq["block0"][name]
        assert sub["kernel"].dtype == jnp.int8
        assert sub["kernel_scale"].dtype == jnp.float32
    assert wq["lm_head"]["kernel"].dtype == jnp.int8
    # embeddings and norms untouched
    assert wq["tok_emb"]["embedding"].dtype == params["tok_emb"]["embedding"].dtype
    assert "scale" in wq["ln_f"] and wq["ln_f"]["scale"].dtype != jnp.int8


def test_int8_mode_refuses_prequantized_tree():
    """quant='int8' on a wo-quantized param tree must refuse loudly: the fp
    weights are gone, so the dynamic-activation int8 program cannot be
    built — silently running the wo path would return different numerics
    than the mode the caller asked for."""
    lm = _lm("int8")
    wq = wo_quantize_params(_params(_lm()))
    with pytest.raises(ValueError, match="pre-quantized"):
        lm.apply({"params": wq}, jnp.zeros((1, L), jnp.int32), train=False)


def test_generate_refuses_prequantized_tree_in_fp_modes():
    """generate() with quant='none' or 'int8' on a wo-quantized tree must
    refuse: plain nn.Dense would silently use the raw int8 kernels as
    weights (flax ignores the extra scale leaves) and decode garbage."""
    lm = _lm()
    wq = wo_quantize_params(_params(lm))
    prompt = jnp.zeros((1, 3), jnp.int32)
    for q in ("none", "int8"):
        with pytest.raises(ValueError, match="wo-quantized"):
            generate(lm, wq, prompt, steps=2, quant=q)


@pytest.mark.slow  # tier-1 budget (PR 11): the 27s training loop dominates; wo-greedy parity stays pinned in-budget by tests/test_serve.py::test_paged_greedy_bit_identical_int8_wo (wo greedy bit-equal across decode paths), test_wo_decode_params_are_int8_resident (int8-resident program) and test_quant_forward_tracks_bf16_forward (wo numerics)
def test_wo_decode_matches_bf16_greedy_on_trained_model():
    """Train the tiny LM on the affine rule, then weight-only int8 decode
    (cached AND full-recompute) must reproduce the bf16 path's greedy
    tokens exactly — per-channel int8 keeps the trained argmax margins."""
    mesh = make_mesh((8,), ("data",))
    lm = _lm()
    state, _ = _train(lm, _params(lm), mesh)
    params = jax.device_get(state.params)
    prompt = jnp.asarray([[3, (3 * 5 + 7) % V], [11, (11 * 5 + 7) % V]],
                         jnp.int32)
    ref = np.asarray(generate(lm, params, prompt, steps=12, use_cache=True))
    wo_cached = np.asarray(generate(lm, params, prompt, steps=12,
                                    use_cache=True, quant="int8_wo"))
    np.testing.assert_array_equal(ref, wo_cached)
    wo_full = np.asarray(generate(lm, params, prompt, steps=12,
                                  quant="int8_wo"))
    np.testing.assert_array_equal(ref, wo_full)


def test_wo_decode_params_are_int8_resident():
    """The decode program really consumes int8 weights (the memory-bound
    win), not a dequantized fp copy smuggled through the param tree."""
    params = _params(_lm())
    wq = wo_quantize_params(params)
    int8_bytes = sum(x.size for x in jax.tree.leaves(wq)
                     if x.dtype == jnp.int8)
    assert int8_bytes > 0
    # generate() accepts the PRE-quantized tree too (idempotent entry)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = generate(_lm(), wq, prompt, steps=4, use_cache=True,
                   quant="int8_wo")
    assert out.shape == (1, 7)


# ---- sharded smoke ---------------------------------------------------------

@pytest.mark.slow  # tier-1 budget (PR 15): int8 under dp x tp rides the
# ONE gspmd step template the plan compiler lowers for every GSPMD
# placement — the in-budget siblings are the plan parity suite's int8 leg
# (tests/test_plan.py::test_lm_plan_loss_parity_across_modes) and the fp
# tp-placement parity (tests/test_lm.py::test_tp_matches_dp)
def test_int8_train_step_under_dp_tp_mesh():
    """quant='int8' through the GSPMD dp x tp step: scales are tiny
    replicated leaves, so the Megatron param placement partitions the
    quantized program unchanged; loss matches the pure-DP quantized step."""
    lm = _lm("int8")
    params = _params(lm)
    inputs, targets = make_lm_batches(_affine_rows(8))
    tx = make_optimizer(0.01, 0.9, 0.0, steps_per_epoch=100)
    key = jax.random.PRNGKey(1)

    def run(mesh, place):
        st = TrainState.create(params, {}, tx)
        st = place(mesh, st)
        step = make_lm_train_step(lm, tx, mesh, donate=False)
        sh = NamedSharding(mesh, P("data"))
        _, m = step(st, jax.device_put(inputs, sh),
                    jax.device_put(targets, sh), key)
        m = jax.device_get(m)
        return float(m["loss_sum"]) / float(m["count"])

    loss_dp = run(make_mesh((8,), ("data",)),
                  lambda mesh, st: jax.device_put(st, replicated(mesh)))

    def place_tp(mesh, st):
        return TrainState(
            step=jax.device_put(st.step, NamedSharding(mesh, P())),
            params=shard_lm_params(mesh, st.params), batch_stats={},
            opt_state=jax.device_put(st.opt_state, NamedSharding(mesh, P())),
            loss_scale=None)

    loss_tp = run(make_mesh((4, 2), ("data", "model")), place_tp)
    assert np.isfinite(loss_dp) and np.isfinite(loss_tp)
    # quantization is elementwise + per-channel reduces: GSPMD partitioning
    # must not change the math beyond fp reduction order
    assert loss_tp == pytest.approx(loss_dp, rel=2e-3)


@pytest.mark.parametrize("schedule", [
    "gpipe",
    # tier-1 budget (PR 3): 1f1b x quant parity is a near-duplicate of
    # gpipe x quant (the schedules themselves are parity-pinned in
    # test_pp); slow-marked
    pytest.param("1f1b", marks=pytest.mark.slow),
])
@pytest.mark.parametrize("quant", [
    # tier-1 budget (PR 15): the whole quant x pp cross matrix is
    # slow-tier now — pp forwards the knob into its stage blocks through
    # the SAME ops.quant.quant_matmul the plan-compiled dense paths pin
    # in-budget (tests/test_plan.py::test_lm_plan_loss_parity_across_modes
    # int8 leg + test_quant_einsum_tracks_fp_dense), and the pp schedules'
    # own parity stays in-budget in test_pp
    pytest.param("int8", marks=pytest.mark.slow),
    # tier-1 budget (PR 7): int8_wo x pp is an 11s near-duplicate of the
    # int8 x pp parity (wo-mode itself is parity-pinned in the decode and
    # dense-layer tests); slow-marked
    pytest.param("int8_wo", marks=pytest.mark.slow),
])
def test_quant_pp_step_matches_dp(quant, schedule):
    """Both quant modes compose with pipeline parallelism: one pp step
    (either schedule) over a (data=2, stage=2) mesh reproduces the plain-DP
    quantized step's loss/metric sums — the pp schedules forward the quant
    knob into their rebuilt stage blocks and route the last-stage head
    matmul through ops.quant (pp._head_logits), so pp changes WHERE the
    quantized program runs, never what it computes."""
    from tpu_dist.parallel.pp import (make_lm_pp_1f1b_train_step,
                                      make_lm_pp_train_step, shard_state_pp,
                                      stack_pipeline_params)
    maker = (make_lm_pp_1f1b_train_step if schedule == "1f1b"
             else make_lm_pp_train_step)
    lm = _lm(quant)
    params = _params(lm)
    inputs, targets = make_lm_batches(_affine_rows(8))
    tx = make_optimizer(0.01, 0.9, 0.0, steps_per_epoch=100)
    key = jax.random.PRNGKey(1)

    mesh_dp = make_mesh((1,), ("data",), devices=jax.devices()[:1])
    st_dp = jax.device_put(TrainState.create(params, {}, tx),
                           replicated(mesh_dp))
    dp_step = make_lm_train_step(lm, tx, mesh_dp, donate=False)
    sh = NamedSharding(mesh_dp, P("data"))
    _, m_dp = dp_step(st_dp, jax.device_put(inputs, sh),
                      jax.device_put(targets, sh), key)

    mesh = make_mesh((2, 2), ("data", "stage"), devices=jax.devices()[:4])
    pp_params = stack_pipeline_params(params, num_stages=2)
    st_pp = shard_state_pp(mesh, TrainState.create(pp_params, {}, tx))
    pp_step = maker(lm, tx, mesh, num_microbatches=2, donate=False)
    sh_pp = NamedSharding(mesh, P("data", None))
    _, m_pp = pp_step(st_pp, jax.device_put(inputs, sh_pp),
                      jax.device_put(targets, sh_pp), key)

    for k in ("loss_sum", "correct1", "count"):
        assert float(jax.device_get(m_pp[k])) == pytest.approx(
            float(jax.device_get(m_dp[k])), rel=1e-5), k


@pytest.mark.slow  # tier-1 budget (PR 11): wo x mesh decode smoke; the fp mesh-decode parity pins (test_generate.py::test_mesh_tp_decode_matches_single_device) and the wo decode residency/parity tests above stay in-budget
def test_wo_sharded_decode_smoke():
    """int8_wo decode under a data-sharded mesh: scale leaves replicate
    (parallel.tp rule) and the program runs end to end."""
    lm = _lm()
    params = _params(lm, seed=7)
    mesh = make_mesh((8,), ("data",))
    prompt = jnp.asarray(np.tile([[2, 9, 4]], (8, 1)), jnp.int32)
    ref = np.asarray(generate(lm, params, prompt, steps=6, use_cache=True,
                              quant="int8_wo"))
    sharded = np.asarray(generate(lm, params, prompt, steps=6, use_cache=True,
                                  quant="int8_wo", mesh=mesh))
    np.testing.assert_array_equal(ref, sharded)
