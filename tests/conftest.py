"""Test env: 8 virtual CPU devices in one process.

SURVEY.md §4: the reference has no tests; our distributed logic is exercised
without a pod via XLA host-platform virtual devices — the clean analog of
"multi-node without a real cluster".
MUST be set before jax initializes, hence conftest import time.
"""

import os

# The image's sitecustomize pre-imports jax and registers the axon TPU plugin
# (JAX_PLATFORMS=axon), so env vars are too late here; jax.config still works
# because no backend has been initialized yet. Tests run on 8 virtual CPU
# devices unless TPU_DIST_TEST_TPU=1 opts into the real chip.
if os.environ.get("TPU_DIST_TEST_TPU") != "1":
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist._compat import set_cpu_device_count

    set_cpu_device_count(8)
os.environ.setdefault("JAX_ENABLE_X64", "0")


# ---- tier-1 budget self-observability -----------------------------------
# The suite runs 620-870s against an 870s timeout (±30% machine variance,
# ROADMAP budget guardrail); budget creep was being rediscovered by
# timeout instead of tracked. Every run writes its wall time and the
# top-20 test durations to TPU_DIST_TIER1_DURATIONS (default
# /tmp/tier1_durations.json) and prints one summary line, so a creeping
# test is visible in the run that introduced it. Hooks are best-effort:
# budget telemetry must never fail the suite.

import time as _time

_suite_t0 = _time.time()
_durations = []  # (seconds, nodeid) across setup+call+teardown


def pytest_runtest_logreport(report):
    try:
        if report.duration:
            _durations.append((float(report.duration), report.nodeid))
    except Exception:
        pass


def _is_full_suite(config) -> bool:
    """Only the tier-1-shaped run may overwrite the budget artifact: a
    `pytest tests/test_x.py -k one` or `-m slow` run would otherwise
    clobber the full-suite record the hook exists to track. The tier-1
    marker filter `-m 'not slow'` (and no filter at all) still counts."""
    if getattr(config.option, "keyword", ""):
        return False
    if getattr(config.option, "markexpr", "") not in ("", "not slow"):
        return False
    for a in config.invocation_params.args:
        a = str(a)
        if a.endswith(".py") or "::" in a:
            return False
    return True


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    try:
        import json

        wall = _time.time() - _suite_t0
        # sum setup/call/teardown phases per test, rank by total
        per_test = {}
        for secs, nodeid in _durations:
            per_test[nodeid] = per_test.get(nodeid, 0.0) + secs
        top = sorted(per_test.items(), key=lambda kv: -kv[1])[:20]
        path = os.environ.get("TPU_DIST_TIER1_DURATIONS",
                              "/tmp/tier1_durations.json")
        wrote = ""
        if _is_full_suite(config):
            with open(path, "w") as f:
                json.dump({"wall_s": round(wall, 1),
                           "tests": len(per_test),
                           "exitstatus": int(exitstatus),
                           "top": [{"nodeid": n, "s": round(s, 2)}
                                   for n, s in top]}, f, indent=1)
            wrote = f"; top-20 -> {path}"
        slowest = (f"; slowest {top[0][1]:.1f}s {top[0][0]}"
                   if top else "")
        terminalreporter.write_line(
            f"tier1-budget: {wall:.1f}s wall, {len(per_test)} tests"
            f"{slowest}{wrote}")
    except Exception:
        pass
