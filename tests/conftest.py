"""Test env: 8 virtual CPU devices in one process.

SURVEY.md §4: the reference has no tests; our distributed logic is exercised
without a pod via XLA host-platform virtual devices — the clean analog of
"multi-node without a real cluster".
MUST be set before jax initializes, hence conftest import time.
"""

import os

# The image's sitecustomize pre-imports jax and registers the axon TPU plugin
# (JAX_PLATFORMS=axon), so env vars are too late here; jax.config still works
# because no backend has been initialized yet. Tests run on 8 virtual CPU
# devices unless TPU_DIST_TEST_TPU=1 opts into the real chip.
if os.environ.get("TPU_DIST_TEST_TPU") != "1":
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist._compat import set_cpu_device_count

    set_cpu_device_count(8)
os.environ.setdefault("JAX_ENABLE_X64", "0")
