"""Engine integration tests on the 8-device mesh (SURVEY.md §4 plan).

Covers: loss decrease (convergence smoke), DDP-equiv vs horovod-equiv flavor
equivalence, single- vs multi-device update equivalence (the data-parallel
correctness property the reference could only test by training to accuracy).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.data import make_transform
from tpu_dist.engine.state import TrainState, init_model
from tpu_dist.engine.steps import (make_eval_step, make_shard_map_train_step,
                                   make_train_step)
from tpu_dist.models import create_model
from tpu_dist.ops import make_optimizer
from tpu_dist.parallel.mesh import batch_sharding, make_mesh, replicated


def _setup(mesh, arch="lenet", lr=0.1, shape=(28, 28, 1)):
    model = create_model(arch)
    params, stats = init_model(model, jax.random.PRNGKey(0), (2,) + shape)
    tx = make_optimizer(lr, 0.9, 1e-4, steps_per_epoch=1000)
    state = jax.device_put(TrainState.create(params, stats, tx),
                           replicated(mesh))
    transform = make_transform(np.full(shape[-1:], 0.5, np.float32),
                               np.full(shape[-1:], 0.25, np.float32))
    return model, tx, state, transform


def _batch(n=64, shape=(28, 28, 1), seed=0):
    rng = np.random.default_rng(seed)
    imgs = rng.integers(0, 255, (n,) + shape).astype(np.uint8)
    labels = (imgs.astype(np.int32).sum(axis=(1, 2, 3)) % 10).astype(np.int32)
    return imgs, labels


def test_loss_decreases_on_learnable_batch():
    mesh = make_mesh()
    model, tx, state, transform = _setup(mesh)
    step = make_train_step(model, tx, transform, mesh)
    imgs, labels = _batch(64)
    sh = batch_sharding(mesh)
    imgs, labels = jax.device_put(imgs, sh), jax.device_put(labels, sh)
    rng = jax.random.PRNGKey(42)
    losses = []
    for _ in range(12):
        state, metrics = step(state, imgs, labels, rng)
        # distlint: disable=DL002 -- CPU test: per-step loss assertion needs the value now
        m = jax.device_get(metrics)
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    assert losses[-1] < losses[0] * 0.7, losses


class _MLP:
    """Tiny BN-free/dropout-free model: the flavor-equivalence property
    (grad of sharded-batch mean == psum of per-shard grad means) is exact
    only without batch-coupled layers (BN) or per-device RNG (dropout)."""

    def __new__(cls):
        import flax.linen as nn

        class MLP(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = True):
                x = x.reshape((x.shape[0], -1))
                x = nn.Dense(32)(x)
                x = nn.relu(x)
                return nn.Dense(10)(x)

        return MLP()


def test_jit_and_shard_map_flavors_agree_exactly():
    """DDP-equiv (compiler collectives) vs horovod-equiv (explicit psum)
    produce the same update — the TPU analog of reference variants 2 vs 5
    training identically. Exact for batch-decoupled models; BN models differ
    intentionally (global-batch vs per-replica statistics)."""
    mesh = make_mesh()
    model = _MLP()
    params, stats = init_model(model, jax.random.PRNGKey(0), (2, 28, 28, 1))
    tx = make_optimizer(0.1, 0.9, 1e-4, steps_per_epoch=1000)
    state = jax.device_put(TrainState.create(params, stats, tx),
                           replicated(mesh))
    transform = make_transform(np.full((1,), 0.5, np.float32),
                               np.full((1,), 0.25, np.float32))
    step_a = make_train_step(model, tx, transform, mesh, donate=False)
    step_b = make_shard_map_train_step(model, tx, transform, mesh, donate=False)
    imgs, labels = _batch(64)
    sh = batch_sharding(mesh)
    imgs, labels = jax.device_put(imgs, sh), jax.device_put(labels, sh)
    rng = jax.random.PRNGKey(0)

    sa, ma = step_a(state, imgs, labels, rng)
    sb, mb = step_b(state, imgs, labels, rng)
    for k in ("loss_sum", "correct1", "correct5", "count"):
        assert float(jax.device_get(ma[k])) == pytest.approx(
            float(jax.device_get(mb[k])), rel=1e-5), k
    fa = jnp.concatenate([x.ravel() for x in jax.tree.leaves(sa.params)])
    fb = jnp.concatenate([x.ravel() for x in jax.tree.leaves(sb.params)])
    np.testing.assert_allclose(np.asarray(fa), np.asarray(fb),
                               rtol=1e-5, atol=1e-7)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_single_vs_multi_device_same_update():
    """Data parallelism must not change the math: 1-device mesh and 8-device
    mesh see the same global batch -> same params after one step."""
    mesh8 = make_mesh()
    mesh1 = make_mesh(devices=jax.devices()[:1])
    model, tx, state8, transform = _setup(mesh8, arch="resnet18",
                                          shape=(32, 32, 3))
    _, _, state1, _ = _setup(mesh1, arch="resnet18", shape=(32, 32, 3))
    step8 = make_train_step(model, tx, transform, mesh8, donate=False)
    step1 = make_train_step(model, tx, transform, mesh1, donate=False)
    imgs, labels = _batch(64, (32, 32, 3))
    rng = jax.random.PRNGKey(1)
    s8, _ = step8(state8, jax.device_put(imgs, batch_sharding(mesh8)),
                  jax.device_put(labels, batch_sharding(mesh8)), rng)
    s1, _ = step1(state1, jax.device_put(imgs, batch_sharding(mesh1)),
                  jax.device_put(labels, batch_sharding(mesh1)), rng)
    f8 = np.concatenate([np.asarray(x).ravel() for x in jax.tree.leaves(s8.params)])
    f1 = np.concatenate([np.asarray(x).ravel() for x in jax.tree.leaves(s1.params)])
    np.testing.assert_allclose(f8, f1, rtol=1e-4, atol=1e-6)


def test_eval_step_counts_mask_padding():
    mesh = make_mesh()
    model, tx, state, transform = _setup(mesh)
    estep = make_eval_step(model, transform, mesh)
    imgs, labels = _batch(32)
    sh = batch_sharding(mesh)
    # last 8 samples marked as sampler padding -> excluded from every metric
    valid = np.concatenate([np.ones(24, np.float32), np.zeros(8, np.float32)])
    m = jax.device_get(estep(state.params, state.batch_stats,
                             jax.device_put(imgs, sh),
                             jax.device_put(labels, sh),
                             jax.device_put(valid, sh)))
    assert float(m["count"]) == 24.0
    assert 0.0 <= float(m["correct1"]) <= 24.0
    assert float(m["correct5"]) >= float(m["correct1"])


def test_grad_compression_still_converges():
    mesh = make_mesh()
    model, tx, state, transform = _setup(mesh)
    step = make_shard_map_train_step(model, tx, transform, mesh,
                                     grad_compression="bf16")
    imgs, labels = _batch(64)
    sh = batch_sharding(mesh)
    imgs, labels = jax.device_put(imgs, sh), jax.device_put(labels, sh)
    rng = jax.random.PRNGKey(2)
    first = last = None
    for i in range(10):
        state, metrics = step(state, imgs, labels, rng)
        # distlint: disable=DL002 -- CPU test: per-step loss assertion needs the value now
        m = jax.device_get(metrics)
        loss = float(m["loss_sum"]) / float(m["count"])
        first = loss if first is None else first
        last = loss
    assert last < first


@pytest.mark.slow  # tier-1 budget (PR 15): the stacked and indexed windows
# wrap the ONE step template through the ONE plan-compiler window pass now;
# in-budget siblings: tests/test_plan.py::test_image_plan_loss_parity_
# across_modes (stacked == sequential, bit-level) and
# test_indexed_multi_step_equals_host_batches below (the indexed twin)
def test_multi_step_equals_sequential_steps():
    """K steps in one scan dispatch == K sequential jit dispatches."""
    from tpu_dist.engine.steps import make_multi_train_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh()
    model = _MLP()
    params, stats = init_model(model, jax.random.PRNGKey(0), (2, 28, 28, 1))
    tx = make_optimizer(0.1, 0.9, 1e-4, steps_per_epoch=1000)
    state0 = jax.device_put(TrainState.create(params, stats, tx),
                            replicated(mesh))
    transform = make_transform(np.full((1,), 0.5, np.float32),
                               np.full((1,), 0.25, np.float32))
    single = make_train_step(model, tx, transform, mesh, donate=False)
    multi = make_multi_train_step(model, tx, transform, mesh, donate=False)

    k, b = 3, 32
    rng_np = np.random.default_rng(0)
    imgs = rng_np.integers(0, 255, (k, b, 28, 28, 1)).astype(np.uint8)
    lbls = rng_np.integers(0, 10, (k, b)).astype(np.int32)
    key = jax.random.PRNGKey(7)

    sh = batch_sharding(mesh)
    s_seq = state0
    total = 0.0
    for i in range(k):
        # distlint: disable=DL008 -- CPU equivalence test stages its own per-step operands; no input pipeline in play
        s_seq, m = single(s_seq, jax.device_put(imgs[i], sh),
                          jax.device_put(lbls[i], sh), key)
        # distlint: disable=DL002 -- CPU test: per-step loss assertion needs the value now
        total += float(jax.device_get(m["loss_sum"]))

    sh2 = NamedSharding(mesh, P(None, "data"))
    s_multi, m_multi = multi(state0, jax.device_put(imgs, sh2),
                             jax.device_put(lbls, sh2), key)
    assert float(jax.device_get(m_multi["loss_sum"])) == pytest.approx(total, rel=1e-5)
    fa = np.concatenate([np.asarray(x).ravel() for x in jax.tree.leaves(s_seq.params)])
    fb = np.concatenate([np.asarray(x).ravel() for x in jax.tree.leaves(s_multi.params)])
    np.testing.assert_allclose(fa, fb, rtol=1e-5, atol=1e-7)
    assert int(jax.device_get(s_multi.step)) == k


def test_grad_accum_equals_big_batch():
    """K microbatches accumulated == one step over the concatenated batch
    (exact for batch-decoupled models)."""
    from tpu_dist.engine.steps import make_grad_accum_train_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh()
    model = _MLP()
    params, stats = init_model(model, jax.random.PRNGKey(0), (2, 28, 28, 1))
    tx = make_optimizer(0.1, 0.9, 1e-4, steps_per_epoch=1000)
    state0 = jax.device_put(TrainState.create(params, stats, tx),
                            replicated(mesh))
    transform = make_transform(np.full((1,), 0.5, np.float32),
                               np.full((1,), 0.25, np.float32))
    big = make_train_step(model, tx, transform, mesh, donate=False)
    accum = make_grad_accum_train_step(model, tx, transform, mesh,
                                       donate=False)

    k, b = 4, 16
    imgs, labels = _batch(k * b)
    key = jax.random.PRNGKey(3)
    s_big, m_big = big(state0, jax.device_put(imgs, batch_sharding(mesh)),
                       jax.device_put(labels, batch_sharding(mesh)), key)
    sh2 = NamedSharding(mesh, P(None, "data"))
    s_acc, m_acc = accum(state0,
                         jax.device_put(imgs.reshape(k, b, 28, 28, 1), sh2),
                         jax.device_put(labels.reshape(k, b), sh2), key)
    assert float(jax.device_get(m_acc["count"])) == k * b
    assert float(jax.device_get(m_acc["loss_sum"])) == pytest.approx(
        float(jax.device_get(m_big["loss_sum"])), rel=1e-5)
    fa = np.concatenate([np.asarray(x).ravel()
                         for x in jax.tree.leaves(s_big.params)])
    fb = np.concatenate([np.asarray(x).ravel()
                         for x in jax.tree.leaves(s_acc.params)])
    np.testing.assert_allclose(fa, fb, rtol=1e-5, atol=1e-7)


def test_indexed_multi_step_equals_host_batches():
    """Device-resident dataset + (K,B) index window == host-fed batches."""
    from tpu_dist.engine.steps import (make_indexed_multi_train_step,
                                       pack_images_for_device)
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh()
    model = _MLP()
    params, stats = init_model(model, jax.random.PRNGKey(0), (2, 28, 28, 1))
    tx = make_optimizer(0.1, 0.9, 1e-4, steps_per_epoch=1000)
    state0 = jax.device_put(TrainState.create(params, stats, tx),
                            replicated(mesh))
    transform = make_transform(np.full((1,), 0.5, np.float32),
                               np.full((1,), 0.25, np.float32))
    single = make_train_step(model, tx, transform, mesh, donate=False)
    indexed = make_indexed_multi_train_step(model, tx, transform, mesh,
                                            (28, 28, 1), donate=False)

    n, k, b = 256, 3, 32
    rng_np = np.random.default_rng(1)
    images_all = rng_np.integers(0, 255, (n, 28, 28, 1)).astype(np.uint8)
    labels_all = rng_np.integers(0, 10, (n,)).astype(np.int32)
    idx = rng_np.integers(0, n, (k, b)).astype(np.int32)
    key = jax.random.PRNGKey(7)

    sh = batch_sharding(mesh)
    s_seq = state0
    for i in range(k):
        # distlint: disable=DL008 -- CPU equivalence test stages its own per-step operands; no input pipeline in play
        s_seq, _ = single(s_seq, jax.device_put(images_all[idx[i]], sh),
                          jax.device_put(labels_all[idx[i]], sh), key)

    packed = pack_images_for_device(images_all)
    assert packed.dtype == np.int32  # 28*28*1 is word-divisible -> packed path
    repl = replicated(mesh)
    s_idx, m = indexed(state0, jax.device_put(packed, repl),
                       jax.device_put(labels_all, repl),
                       jax.device_put(idx, NamedSharding(mesh, P(None, "data"))),
                       key)
    assert float(jax.device_get(m["count"])) == k * b
    fa = np.concatenate([np.asarray(x).ravel()
                         for x in jax.tree.leaves(s_seq.params)])
    fb = np.concatenate([np.asarray(x).ravel()
                         for x in jax.tree.leaves(s_idx.params)])
    np.testing.assert_allclose(fa, fb, rtol=1e-5, atol=1e-7)
    assert int(jax.device_get(s_idx.step)) == k


def _trainer_params(tmp, k, placement="auto", epochs=1):
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg = TrainConfig(dataset="synthetic-mnist", arch="lenet", epochs=epochs,
                      batch_size=64, synth_train_size=320, synth_val_size=64,
                      seed=11, print_freq=100, checkpoint_dir=tmp,
                      steps_per_dispatch=k, data_placement=placement)
    tr = Trainer(cfg)
    tr.fit()
    return tr, np.concatenate([np.asarray(jax.device_get(x)).ravel()
                               for x in jax.tree.leaves(tr.state.params)])


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_trainer_windowed_device_data_matches_per_batch(tmp_path):
    """steps_per_dispatch=4 + HBM-resident dataset == the per-batch loop."""
    tr1, p1 = _trainer_params(str(tmp_path / "a"), k=1)
    tr4, p4 = _trainer_params(str(tmp_path / "b"), k=4)
    assert tr1.device_data is False and tr4.device_data is True
    assert (int(jax.device_get(tr1.state.step))
            == int(jax.device_get(tr4.state.step)) == 5)  # ceil(320/64)
    np.testing.assert_allclose(p1, p4, rtol=1e-5, atol=1e-7)


@pytest.mark.slow  # tier-1 budget (PR 7): near-duplicate of the device-data windowed parity (already slow); windowed train+eval stay exercised in-budget by test_windowed_eval_matches_host_eval
def test_trainer_windowed_host_mode_matches_per_batch(tmp_path):
    """steps_per_dispatch=2 with host-stacked windows (tail window of 1)."""
    _, p1 = _trainer_params(str(tmp_path / "a"), k=1)
    tr2, p2 = _trainer_params(str(tmp_path / "b"), k=2, placement="host")
    assert tr2.device_data is False
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-7)


def test_trainer_grad_accum_wiring(tmp_path):
    """--grad-accum-steps 2 through the Trainer: one optimizer step per
    GLOBAL batch (not per microbatch), metrics count every sample, and the
    model still learns. (Bit-exactness vs the big-batch step is covered by
    test_grad_accum_equals_big_batch; Trainer runs can't bit-match because
    dropout keys fold per microbatch.)"""
    import pytest
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg = TrainConfig(dataset="synthetic-mnist", arch="lenet", epochs=2,
                      batch_size=64, synth_train_size=192, synth_val_size=64,
                      seed=11, print_freq=100, grad_accum_steps=2,
                      checkpoint_dir=str(tmp_path))
    tr = Trainer(cfg)
    first = tr.train_epoch(0)
    second = tr.train_epoch(1)
    # 3 global batches/epoch -> 3 optimizer steps each, NOT 6
    assert int(jax.device_get(tr.state.step)) == 6
    assert second["loss"] < first["loss"]
    assert tr.validate(0) > 0.3  # learnable synthetic data separates fast

    # invalid combos fail fast
    with pytest.raises(ValueError):
        Trainer(TrainConfig(dataset="synthetic-mnist", arch="lenet",
                            batch_size=64, synth_train_size=192,
                            grad_accum_steps=2, variant="shard_map"))
    with pytest.raises(ValueError):
        Trainer(TrainConfig(dataset="synthetic-mnist", arch="lenet",
                            batch_size=64, synth_train_size=192,
                            grad_accum_steps=2, steps_per_dispatch=4))


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_trainer_windowed_mid_epoch_resume_step_exact(tmp_path):
    """Interrupt between windows, resume -> same params as uninterrupted."""
    import os
    import pytest
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    kw = dict(dataset="synthetic-mnist", arch="lenet", epochs=1,
              batch_size=64, synth_train_size=320, synth_val_size=64,
              seed=11, print_freq=100, steps_per_dispatch=2)
    _, p_full = _trainer_params(str(tmp_path / "full"), k=2)

    tr_int = Trainer(TrainConfig(checkpoint_dir=str(tmp_path / "int"), **kw))
    real = tr_int.window_step
    calls = {"n": 0}

    def limited(*a, **kws):
        if calls["n"] == 2:  # after 2 windows = 4 of 5 batches
            raise KeyboardInterrupt
        calls["n"] += 1
        return real(*a, **kws)

    tr_int.window_step = limited
    with pytest.raises(KeyboardInterrupt):
        tr_int.fit()

    ck = os.path.join(str(tmp_path / "int"), "lenet-checkpoint.msgpack")
    tr_res = Trainer(TrainConfig(checkpoint_dir=str(tmp_path / "res"),
                                 resume=ck, **kw))
    assert tr_res._skip_batches == 4
    tr_res.fit()
    p_res = np.concatenate([np.asarray(jax.device_get(x)).ravel()
                            for x in jax.tree.leaves(tr_res.state.params)])
    np.testing.assert_allclose(p_full, p_res, rtol=1e-5, atol=1e-7)


def test_windowed_eval_matches_host_eval(tmp_path):
    """One-dispatch HBM-resident eval == the host-fed per-batch eval,
    including sampler-padding masking (exact sums both ways)."""
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg = TrainConfig(dataset="synthetic-mnist", arch="lenet", epochs=1,
                      batch_size=64, synth_train_size=256,
                      synth_val_size=150,  # NOT a batch multiple: padding
                      seed=2, print_freq=100, steps_per_dispatch=4,
                      checkpoint_dir=str(tmp_path))
    tr = Trainer(cfg)
    assert tr._val_data_dev is not None
    tr.train_epoch(0)
    acc_dev = tr.validate(0)
    tr._val_data_dev = None  # force the host-fed path on the same state
    acc_host = tr.validate(0)
    assert acc_dev == acc_host
