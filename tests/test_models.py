"""Model-family tests: shapes + parameter-count parity with torchvision.

The reference's models ARE torchvision's (reference 1.dataparallel.py:97-102);
the strongest no-copy parity check available on CPU is exact trainable
parameter-count equality of our flax NHWC ResNets vs torchvision's plans.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.models import create_model, model_names


def _param_count(tree):
    return sum(np.prod(p.shape) for p in jax.tree.leaves(tree))


def test_registry_surface():
    assert {"resnet18", "resnet50", "resnet101", "lenet"} <= set(model_names)
    with pytest.raises(ValueError):
        create_model("resnet999")
    with pytest.raises(ValueError):
        create_model("resnet18", pretrained=True)  # zero-egress env


def test_lenet_forward_shape():
    m = create_model("lenet")
    x = jnp.zeros((4, 28, 28, 1))
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (4, 10)


# tier-1 budget (PR 10): resnet50's bottleneck-block compile is a 14s
# near-duplicate of the resnet18 basic-block forward; resnet18 stays the
# family's live compile, and resnet50's plan structure stays pinned by the
# eval_shape param-count test (no compile)
@pytest.mark.parametrize("arch", [
    "resnet18", pytest.param("resnet50", marks=pytest.mark.slow)])
def test_resnet_forward_shape(arch):
    m = create_model(arch, num_classes=10)
    x = jnp.zeros((2, 32, 32, 3))
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 10)


@pytest.mark.parametrize("arch", ["resnet18", "resnet34", "resnet50"])
def test_param_count_matches_torchvision(arch):
    torchvision = pytest.importorskip("torchvision")
    tm = torchvision.models.__dict__[arch](num_classes=10)
    torch_params = sum(p.numel() for p in tm.parameters())

    m = create_model(arch, num_classes=10)
    variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                      train=False)
    ours = _param_count(variables["params"])
    assert ours == torch_params, f"{arch}: {ours} vs torchvision {torch_params}"


# tier-1 budget (PR 3): the heavy zoo archs (10-23s of compile each on the
# CPU sim) are slow-marked; the cheap ones keep registry-breadth coverage
# in-budget, and test_param_count_matches_published still pins every plan's
# structure via eval_shape (no compile)
_HEAVY_ZOO = pytest.mark.slow
@pytest.mark.parametrize("arch", [
    # tier-1 budget (PR 8): vgg13/vgg19 are depth-only variants of the
    # same plan; vgg11 (cheapest) and vgg16 (the reference headliner)
    # stay as the family's live representatives
    "vgg16", "vgg11",
    pytest.param("vgg13", marks=_HEAVY_ZOO),
    pytest.param("vgg19", marks=_HEAVY_ZOO),
    pytest.param("densenet121", marks=_HEAVY_ZOO),
    pytest.param("densenet169", marks=_HEAVY_ZOO),
    pytest.param("mobilenet_v2", marks=_HEAVY_ZOO),
    # tier-1 budget (PR 7): the x1_0/1_1 flavors are 12-14s compiles each;
    # the 0_5/1_0 siblings keep a cheap live representative per family
    # (plan structure stays pinned via the eval_shape param-count tests).
    # PR 10 measurement: squeezenet1_0 compiles in 12s too — both flavors
    # slow-marked; alexnet/vgg11 stay the zoo's live compiles and the
    # eval_shape param test still pins both squeezenet plans
    pytest.param("squeezenet1_1", marks=_HEAVY_ZOO),
    pytest.param("squeezenet1_0", marks=_HEAVY_ZOO),
    pytest.param("shufflenet_v2_x1_0", marks=_HEAVY_ZOO),
    "shufflenet_v2_x0_5",
    pytest.param("efficientnet_b0", marks=_HEAVY_ZOO),
    "alexnet",
    pytest.param("googlenet", marks=_HEAVY_ZOO),
    pytest.param("mnasnet1_0", marks=_HEAVY_ZOO),
    pytest.param("mobilenet_v3_large", marks=_HEAVY_ZOO),
    pytest.param("mobilenet_v3_small", marks=_HEAVY_ZOO)])
def test_cnn_zoo_forward_shape(arch):
    """Non-ResNet CNN plans (registry-breadth parity with the reference's
    any-torchvision-arch factory, 1.dataparallel.py:23-24): same input sizes
    the reference pushes through its factory."""
    m = create_model(arch, num_classes=10)
    x = jnp.zeros((2, 32, 32, 3))
    variables = m.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    # squeezenet and alexnet are BN-free upstream too
    if not arch.startswith(("squeezenet", "alexnet")):
        assert "batch_stats" in variables  # BN plans carry running stats


# torchvision's published trainable-parameter counts at 1000 classes —
# checkable WITHOUT torchvision installed (this container has none), via
# eval_shape so no compile happens. VGG/AlexNet are absent by design: their
# GAP head replaces torchvision's fixed 7x7 flatten (module docstring).
TORCHVISION_PARAMS = {
    "densenet121": 7_978_856,
    "densenet161": 28_681_000,
    "densenet169": 14_149_480,
    "densenet201": 20_013_928,
    "squeezenet1_0": 1_248_424,
    "squeezenet1_1": 1_235_496,
    "shufflenet_v2_x0_5": 1_366_792,
    "shufflenet_v2_x1_0": 2_278_604,
    "shufflenet_v2_x1_5": 3_503_624,
    "shufflenet_v2_x2_0": 7_393_996,
    "mobilenet_v2": 3_504_872,
    "efficientnet_b0": 5_288_548,
    "googlenet": 6_624_904,     # aux_logits=False deploy network
    # published 27,161,264 minus the exactly-computable aux head
    # (768*128 + 2*128 + 128*768*25 + 2*768 + 768*1000 + 1000 = 3,326,696)
    "inception_v3": 23_834_568,
    "mnasnet0_5": 2_218_512,
    "mnasnet0_75": 3_170_208,
    "mnasnet1_0": 4_383_312,
    "mnasnet1_3": 6_282_256,
    "mobilenet_v3_large": 5_483_032,
    "mobilenet_v3_small": 2_542_856,
}


@pytest.mark.parametrize("arch", sorted(TORCHVISION_PARAMS))
def test_param_count_matches_published(arch):
    """Exact parameter parity with torchvision's published counts — the
    strongest no-copy plan check available in a zero-egress container."""
    size = 299 if arch == "inception_v3" else 224  # v3's nominal input
    m = create_model(arch, num_classes=1000)
    v = jax.eval_shape(lambda: m.init({"params": jax.random.PRNGKey(0)},
                                      jnp.zeros((1, size, size, 3)),
                                      train=False))
    assert _param_count(v["params"]) == TORCHVISION_PARAMS[arch]


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_inception_v3_forward_96px():
    """inception_v3's VALID stem needs >=75px (as upstream); 96px runs."""
    m = create_model("inception_v3", num_classes=10)
    v = m.init({"params": jax.random.PRNGKey(0)},
               jnp.zeros((2, 96, 96, 3)), train=False)
    assert m.apply(v, jnp.ones((2, 96, 96, 3)),
                   train=False).shape == (2, 10)
    assert "batch_stats" in v


# tier-1 budget (PR 10): the two bottleneck variants are ~9s compiles each
# and near-duplicates of one another; the grouped one keeps its exact
# param-count pin. PR 18 moves the widened one out of budget too: the
# standard-width Bottleneck forward stays live via resnet50 above, and the
# widened geometry keeps its exact pin in
# test_mobile_class_param_count_matches_torchvision[wide_resnet50_2]
@pytest.mark.parametrize("arch", [
    pytest.param("resnext50_32x4d", marks=pytest.mark.slow),
    pytest.param("wide_resnet50_2", marks=pytest.mark.slow)])
def test_resnet_variant_forward_shape(arch):
    """Grouped (ResNeXt) and widened (WideResNet) bottleneck plans."""
    m = create_model(arch, num_classes=10)
    x = jnp.zeros((2, 32, 32, 3))
    variables = m.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 10)


@pytest.mark.parametrize("arch", ["mobilenet_v2", "squeezenet1_1",
                                  "shufflenet_v2_x1_0", "efficientnet_b0",
                                  "resnext50_32x4d", "wide_resnet50_2"])
def test_mobile_class_param_count_matches_torchvision(arch):
    """The round-4 catalog additions map 1:1 onto torchvision's layer plans
    (depthwise/inverted-residual, fire-module, grouped- and widened-
    bottleneck families) — exact trainable-parameter equality like the
    resnet/densenet checks."""
    torchvision = pytest.importorskip("torchvision")
    tm = torchvision.models.__dict__[arch](num_classes=10)
    torch_params = sum(p.numel() for p in tm.parameters())
    m = create_model(arch, num_classes=10)
    variables = m.init({"params": jax.random.PRNGKey(0)},
                       jnp.zeros((1, 32, 32, 3)), train=False)
    ours = _param_count(variables["params"])
    assert ours == torch_params, f"{arch}: {ours} vs torchvision {torch_params}"


def test_densenet121_feature_param_count_matches_torchvision():
    """DenseNet121's conv/BN plan (no-bias convs, GAP head) maps 1:1 onto
    torchvision's — exact trainable-parameter equality."""
    torchvision = pytest.importorskip("torchvision")
    tm = torchvision.models.densenet121(num_classes=10)
    torch_params = sum(p.numel() for p in tm.parameters())
    m = create_model("densenet121", num_classes=10)
    variables = m.init({"params": jax.random.PRNGKey(0)},
                       jnp.zeros((1, 32, 32, 3)), train=False)
    ours = _param_count(variables["params"])
    assert ours == torch_params, f"{ours} vs torchvision {torch_params}"


def test_bf16_model_keeps_fp32_bn_stats():
    m = create_model("resnet18", dtype=jnp.bfloat16)
    variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                      train=False)
    stats = jax.tree.leaves(variables["batch_stats"])
    assert all(s.dtype == jnp.float32 for s in stats)
    out = m.apply(variables, jnp.zeros((1, 32, 32, 3)), train=False)
    assert out.dtype == jnp.float32  # logits cast back for a stable loss


def test_resnet_groupnorm_variant():
    """norm='gn': no batch_stats collection, train==eval math, runs e2e."""
    import jax.numpy as jnp
    from tpu_dist.engine.state import init_model
    from tpu_dist.models import create_model

    model = create_model("resnet18", num_classes=10, norm="gn")
    params, stats = init_model(model, jax.random.PRNGKey(0), (2, 32, 32, 3))
    assert stats == {}  # GroupNorm keeps no running statistics
    x = jnp.ones((2, 32, 32, 3))
    out_train = model.apply({"params": params}, x, train=True)
    out_eval = model.apply({"params": params}, x, train=False)
    assert out_train.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out_train), np.asarray(out_eval))


def test_vit_forward_and_grads():
    import jax.numpy as jnp
    from tpu_dist.engine.state import init_model
    from tpu_dist.models import create_model

    model = create_model("vit_cifar", num_classes=10)
    params, stats = init_model(model, jax.random.PRNGKey(0), (2, 32, 32, 3))
    assert stats == {}  # LayerNorm only — no running statistics
    x = jnp.ones((2, 32, 32, 3))
    out = model.apply({"params": params}, x, train=True)
    assert out.shape == (2, 10)
    g = jax.grad(lambda p: jnp.sum(
        model.apply({"params": p}, x, train=True) ** 2))(params)
    assert all(bool(jnp.any(l != 0)) for l in jax.tree.leaves(g)
               if l.size > 16)  # every big leaf gets gradient


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_vit_trains_via_trainer(tmp_path):
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg = TrainConfig(dataset="synthetic", arch="vit_cifar", epochs=2,
                      batch_size=64, synth_train_size=512, synth_val_size=128,
                      lr=0.01, seed=0, print_freq=100,
                      checkpoint_dir=str(tmp_path))
    tr = Trainer(cfg)
    acc = tr.fit()
    assert acc >= 0.5, acc  # learnable synthetic set separates quickly


def test_s2d_stem_spans_imagenet_stem():
    """The space-to-depth stem (stem='s2d') computes EXACTLY the imagenet
    7x7/s2 stem's function when its 4x4x12 kernel is the reindexed 7x7x3
    kernel: pad the 7x7 taps to 8x8, split tap (i,j) into (2a+u, 2b+v), and
    place w[2a+u,2b+v,c] at s2d-kernel position [a,b, u*2C+v*C+c]. Same
    per-image outputs => the s2d bench variant is the same model family,
    not a different workload (MLPerf-TPU ResNet equivalence)."""
    from tpu_dist.models import create_model

    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))

    ref = create_model("resnet18", num_classes=10)
    v_ref = ref.init(rng, x, train=False)

    s2d = create_model("resnet18", num_classes=10, stem="s2d")
    v_s2d = s2d.init(rng, x, train=False)

    w = v_ref["params"]["conv1"]["kernel"]            # (7, 7, 3, 64)
    w_pad = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))  # -> (8, 8, 3, 64)
    c = w.shape[2]
    # (2a+u, 2b+v, ch, co) -> (a, b, u*2c + v*c + ch, co)
    w2 = (w_pad.reshape(4, 2, 4, 2, c, 64)            # (a, u, b, v, c, co)
          .transpose(0, 2, 1, 3, 4, 5)                # (a, b, u, v, c, co)
          .reshape(4, 4, 4 * c, 64))
    assert v_s2d["params"]["conv1"]["kernel"].shape == w2.shape

    import flax
    params = flax.core.unfreeze(v_ref["params"])
    params["conv1"] = {"kernel": w2}
    out_ref = ref.apply(v_ref, x, train=False)
    out_s2d = s2d.apply({"params": params,
                         "batch_stats": v_ref["batch_stats"]}, x, train=False)
    np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_s2d),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_trainer_drives_norm_dtype_and_s2d_flags(tmp_path):
    """--norm-dtype bf16 --stem s2d reach the model through TrainConfig
    (the round-5 bench-default levers must be CLI-drivable, not bench-only)."""
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg = TrainConfig(dataset="synthetic-cifar10", arch="resnet18",
                      norm_dtype="bf16", stem="s2d", epochs=1,
                      batch_size=64, synth_train_size=128, synth_val_size=64,
                      seed=0, print_freq=100, checkpoint_dir=str(tmp_path))
    tr = Trainer(cfg)
    assert tr.model.stem == "s2d"
    assert tr.model.norm_dtype == jnp.bfloat16
    tr.fit()  # trains + validates end to end


def test_trainer_rejects_resnet_knobs_on_other_archs():
    import pytest as _pytest

    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    with _pytest.raises(ValueError, match="ResNet-family"):
        Trainer(TrainConfig(dataset="synthetic-mnist", arch="lenet",
                            stem="s2d", batch_size=32,
                            synth_train_size=64, synth_val_size=32))
