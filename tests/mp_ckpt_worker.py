"""Worker for the multi-process SHARDED-checkpoint test (test_multiprocess).

2 processes x 2 local devices, FSDP state sharded over the 4-device global
mesh — every sizeable leaf is NOT fully addressable from either process, so
save_checkpoint must take its collective process_allgather path (the case
round-1 checkpointing would have crashed on). Process 0 then restores the
blob and checks it equals the pre-shard host state.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    out = os.environ["TPU_DIST_TEST_OUT"]
    local_devices = int(os.environ.get("TPU_DIST_LOCAL_DEVICES", "2"))

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist._compat import set_cpu_device_count
    set_cpu_device_count(local_devices)

    from tpu_dist.parallel import launch

    launch.initialize()
    assert jax.process_count() == int(os.environ["TPU_DIST_EXPECT_PROCS"])

    import jax.numpy as jnp
    import numpy as np

    from tpu_dist.engine import checkpoint as ckpt
    from tpu_dist.engine.state import TrainState
    from tpu_dist.models.transformer import tiny_lm
    from tpu_dist.ops import make_optimizer
    from tpu_dist.parallel.fsdp import shard_state_fsdp
    from tpu_dist.parallel.mesh import make_mesh

    lm = tiny_lm(vocab_size=64, num_layers=2, d_model=64, num_heads=4,
                 max_len=32)
    params = lm.init({"params": jax.random.PRNGKey(0)},
                     jnp.zeros((1, 32), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.01, 0.9, 0.0, steps_per_epoch=10)
    ref = TrainState.create(params, {}, tx)
    ref_host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), ref)

    mesh = make_mesh((jax.device_count(),), ("data",))
    sharded = shard_state_fsdp(mesh, ref, min_size=256)
    n_nonaddr = sum(not leaf.is_fully_addressable
                    for leaf in jax.tree.leaves(sharded.params))
    assert n_nonaddr > 0, "test must cover the non-addressable gather path"

    path = ckpt.save_checkpoint(out, sharded, epoch=1, best_acc1=0.0,
                                arch="lm", is_best=False)

    if jax.process_index() == 0:
        template = TrainState.create(params, {}, tx)
        restored, meta = ckpt.load_checkpoint(path, template)
        mismatches = sum(
            not np.array_equal(np.asarray(a), np.asarray(jax.device_get(b)))
            for a, b in zip(jax.tree.leaves(ref_host.params),
                            jax.tree.leaves(restored.params)))
        mismatches += sum(
            not np.array_equal(np.asarray(a), np.asarray(jax.device_get(b)))
            for a, b in zip(jax.tree.leaves(ref_host.opt_state),
                            jax.tree.leaves(restored.opt_state)))
        with open(os.path.join(out, "ckpt_result.json"), "w") as f:
            json.dump({"ok": mismatches == 0, "mismatches": mismatches,
                       "nonaddressable_leaves": n_nonaddr,
                       "meta_epoch": meta.get("epoch")}, f)


if __name__ == "__main__":
    main()
