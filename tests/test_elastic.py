"""Elastic self-scaling (round 13): consensus renumbering, two-way
shrink/grow, coordinated preemption snapshots, peer state restore.

Every elastic path is PRODUCED on demand on one CPU box: the consensus
protocol as pure file-backed units, dense renumbering on a mid-numbered
host loss (closing the PR-10 ``degraded_env`` KNOWN LIMIT), the
rendezvous-epoch coordinator offset, per-host backoff jitter, the
``preemption_snapshotted`` class, a fake-child chaos run through the full
shrink -> degraded -> re-expansion cycle with ``scale``-event evidence,
and the ISSUE 13 acceptance smoke: an injected ``preempt_deadline``
produces a coordinated snapshot whose resume step equals the
pre-preemption step, visible in the ledger_report elasticity timeline.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tpu_dist.obs import faults
from tpu_dist.obs.goodput import discover_attempt_paths
from tpu_dist.obs.ledger import read_ledger
from tpu_dist.parallel.consensus import (ConsensusDir, MeshView,
                                         consensus_env, successor_hosts)
from tpu_dist.parallel.launch import detect_launch, epoch_coordinator
from tpu_dist.parallel.supervisor import (PREEMPT_SNAPSHOT_RC, RestartPolicy,
                                          Supervisor, classify_attempt,
                                          compute_backoff)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fault_state(monkeypatch):
    """Fault plans are process-global; tests must not leak them."""
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


# ---------------------------------------------------------------------------
# the consensus protocol as pure file-backed units

def _three_hosts(tmp_path, now, lease_s=5.0):
    return [ConsensusDir(str(tmp_path), h, planned=3, lease_s=lease_s,
                         now=lambda: now[0]) for h in range(3)]


def test_consensus_initial_epoch_is_full_sorted_mesh(tmp_path):
    now = [1000.0]
    hosts = _three_hosts(tmp_path, now)
    for c in hosts:
        c.register()
    views = [c.resolve() for c in hosts]
    assert all(v == MeshView(0, (0, 1, 2), 3) for v in views)
    assert not views[0].degraded


def test_mid_numbered_loss_renumbers_densely(tmp_path):
    # THE closed KNOWN LIMIT: host 1 (mid-numbered) dies; the survivors
    # agree on dense ids 0/1 instead of leaving the 0/2 hole that made
    # the shrunken rendezvous impossible
    now = [1000.0]
    hosts = _three_hosts(tmp_path, now)
    for c in hosts:
        c.register()
    hosts[0].resolve()
    hosts[1].leave()
    v0, v2 = hosts[0].resolve(), hosts[2].resolve()
    assert v0 == v2 == MeshView(1, (0, 2), 3)
    assert v0.degraded and v0.world_size == 2
    assert v0.process_id(0) == 0 and v0.process_id(2) == 1  # dense
    with pytest.raises(KeyError):
        v0.process_id(1)
    env = consensus_env({"TPU_DIST_NUM_PROCESSES": "3",
                         "TPU_DIST_PROCESS_ID": "2"}, v0, 2)
    assert env["TPU_DIST_NUM_PROCESSES"] == "2"
    assert env["TPU_DIST_PROCESS_ID"] == "1"
    assert env["TPU_DIST_DEGRADED"] == "1"
    assert env["TPU_DIST_MESH_EPOCH"] == "1"


def test_return_re_expands_survivors_first(tmp_path):
    # two-way shrink: the returning host appends AFTER the survivors, so
    # survivor ids never shift up and process 0 always holds live state
    now = [1000.0]
    hosts = _three_hosts(tmp_path, now)
    for c in hosts:
        c.register()
    hosts[0].resolve()
    hosts[1].leave()
    hosts[2].resolve()
    hosts[1].register()
    v = hosts[0].resolve()
    assert v == MeshView(2, (0, 2, 1), 3)
    assert not v.degraded
    assert v.process_id(2) == 1 and v.process_id(1) == 2
    env = consensus_env({"TPU_DIST_DEGRADED": "1"}, v, 1)
    assert "TPU_DIST_DEGRADED" not in env  # full strength: marker cleared


def test_lease_expiry_is_host_loss(tmp_path):
    now = [1000.0]
    hosts = _three_hosts(tmp_path, now, lease_s=5.0)
    for c in hosts:
        c.register()
    hosts[0].resolve()
    now[0] += 10.0           # everyone's heartbeat ages out...
    hosts[0].register()
    hosts[2].register()      # ...but 0 and 2 come back; 1 stays silent
    v = hosts[0].resolve()
    assert v.hosts == (0, 2) and v.epoch == 1 and v.degraded


def test_successor_hosts_is_pure_and_stable():
    assert successor_hosts([0, 1, 2], [0, 2]) == [0, 2]
    assert successor_hosts([0, 2], [0, 1, 2]) == [0, 2, 1]
    assert successor_hosts([], [2, 0]) == [0, 2]
    # racing writers with the same inputs compute identical views
    assert successor_hosts([3, 1], [1, 3, 0]) == \
        successor_hosts([3, 1], [0, 1, 3])


def test_host_return_fault_resurrects_lost_hosts(tmp_path):
    # the CPU-provable re-expansion trigger: no real second host needed
    now = [1000.0]
    hosts = _three_hosts(tmp_path, now)
    for c in hosts:
        c.register()
    hosts[0].resolve()
    hosts[1].leave()
    assert hosts[0].resolve().hosts == (0, 2)
    faults.install("host_return@nth=1")
    v = hosts[0].resolve()
    assert v.hosts == (0, 2, 1) and not v.degraded


# ---------------------------------------------------------------------------
# rendezvous-epoch coordinator offset (parallel.launch)

def test_epoch_coordinator_offsets_port():
    assert epoch_coordinator("10.0.0.1:8476", 0) == "10.0.0.1:8476"
    assert epoch_coordinator("10.0.0.1:8476", 3) == "10.0.0.1:8479"
    assert epoch_coordinator("[::1]:8476", 2) == "[::1]:8478"
    assert epoch_coordinator("not-a-port", 2) == "not-a-port"
    assert epoch_coordinator("", 2) == ""


def test_detect_launch_applies_mesh_epoch(monkeypatch):
    monkeypatch.setenv("TPU_DIST_COORDINATOR", "127.0.0.1:9000")
    monkeypatch.setenv("TPU_DIST_NUM_PROCESSES", "2")
    monkeypatch.setenv("TPU_DIST_PROCESS_ID", "1")
    monkeypatch.setenv("TPU_DIST_MESH_EPOCH", "2")
    info = detect_launch()
    assert info.coordinator == "127.0.0.1:9002"
    assert info.num_processes == 2 and info.process_id == 1
    monkeypatch.delenv("TPU_DIST_MESH_EPOCH")
    assert detect_launch().coordinator == "127.0.0.1:9000"


# ---------------------------------------------------------------------------
# per-host backoff jitter (the restart-stampede fix)

def test_backoff_jitter_is_deterministic_decorrelated_and_bounded():
    pol = RestartPolicy(backoff_base_s=1.0, backoff_max_s=8.0,
                        backoff_jitter=0.5)
    base = compute_backoff(3, pol)          # no host: bare exponential
    assert base == 4.0
    waits = [compute_backoff(3, pol, host_id=h) for h in range(8)]
    # every host gets its own offset (the stampede is broken)...
    assert len(set(waits)) == 8
    # ...within [base, base * (1 + jitter)]...
    assert all(base <= w <= base * 1.5 for w in waits)
    # ...and the same host always picks the same wait (reproducible runs)
    assert waits == [compute_backoff(3, pol, host_id=h) for h in range(8)]
    # the restart ordinal decorrelates REPEAT collisions too
    assert compute_backoff(4, pol, host_id=3) / 8.0 != \
        compute_backoff(3, pol, host_id=3) / 4.0
    # jitter off -> bare schedule even with a host id
    off = RestartPolicy(backoff_base_s=1.0, backoff_max_s=8.0,
                        backoff_jitter=0.0)
    assert compute_backoff(3, off, host_id=5) == 4.0


# ---------------------------------------------------------------------------
# the preemption_snapshotted class

@pytest.mark.parametrize("records,rc,want", [
    ([], PREEMPT_SNAPSHOT_RC, "preemption_snapshotted"),
    ([{"event": "run_end", "steps": 5, "seconds": 1.0,
       "status": "preempted", "snapshot_step": 5}], PREEMPT_SNAPSHOT_RC,
     "preemption_snapshotted"),
    # report-side view: records alone, no returncode
    ([{"event": "run_end", "steps": 5, "seconds": 1.0,
       "status": "preempted"}], None, "preemption_snapshotted"),
    # an unhonored SIGTERM still classifies as plain preemption
    ([], -15, "preemption"),
])
def test_classify_preemption_snapshotted(records, rc, want):
    assert classify_attempt(records, rc) == want


# ---------------------------------------------------------------------------
# peer state restore (checkpoint-less dp-pure recovery)

def test_peer_restore_state_unit():
    from tpu_dist.engine import checkpoint as ckpt

    state = {"w": np.ones((3,), np.float32), "step": np.int32(7)}
    # single process: identity no-op, no collective entered
    out, did = ckpt.peer_restore_state(state)
    assert out is state and not did
    # injected broadcast (the multi-host path's seam): every leaf is
    # host-gathered and replaced by the broadcast result
    calls = []

    def fake_broadcast(tree):
        calls.append(tree)
        return {"w": np.full((3,), 7.0, np.float32), "step": np.int32(42)}

    out, did = ckpt.peer_restore_state(state, broadcast=fake_broadcast)
    assert did and len(calls) == 1
    assert np.all(out["w"] == 7.0) and int(out["step"]) == 42
    assert isinstance(calls[0]["w"], np.ndarray)  # host-side tree


# ---------------------------------------------------------------------------
# chaos acceptance: the full shrink -> degraded -> re-expansion cycle with a
# stdlib-only fake child (3 fake hosts, kill host 1, host 1 returns)

_ELASTIC_CHILD = r"""
import json, os, signal, sys, time

argv = sys.argv[1:]
base = argv[argv.index("--ledger-base") + 1]
attempt = int(os.environ.get("TPU_DIST_ATTEMPT", "0"))
root, ext = os.path.splitext(base)
path = base if attempt == 0 else f"{root}.a{attempt}{ext}"
f = open(path, "a")

def emit(event, **kw):
    f.write(json.dumps({"event": event, "ts": time.time(), **kw}) + "\n")
    f.flush()

world = os.environ.get("TPU_DIST_NUM_PROCESSES")
degraded = os.environ.get("TPU_DIST_DEGRADED") == "1"
emit("run_start", attempt=attempt, kind="fake", config={},
     mesh=None, devices=[], process_count=int(world or 1),
     degraded=degraded,
     mesh_epoch=int(os.environ.get("TPU_DIST_MESH_EPOCH", "0") or 0))
emit("step", step=0, loss=None, throughput=None, unit="tok/s",
     data_s=None, dispatch_s=None, device_s=None, comm_s=None, mfu=None)

def on_term(signum, frame):
    # the engines' coordinated-snapshot contract, faked: run_end with
    # status=preempted, exit 75 (PREEMPT_SNAPSHOT_RC)
    emit("run_end", steps=1, seconds=0.1, status="preempted",
         snapshot_step=1)
    os._exit(75)

signal.signal(signal.SIGTERM, on_term)

if degraded:
    # the dense-id check: a 3-host mesh minus mid-numbered host 1 must
    # relaunch as a 2-process world with ids renumbered 0/1
    if world != "2" or os.environ.get("TPU_DIST_PROCESS_ID") != "0":
        sys.exit(9)
    time.sleep(30)  # run "forever"; the re-expansion SIGTERM ends us
    sys.exit(8)
# full-strength attempt after re-expansion: restored world + peer resume
if attempt > 0:
    ok = (world == "3" and os.environ.get("TPU_DIST_PEER_RESUME") == "1"
          and os.environ.get("TPU_DIST_DEGRADED") is None)
    if not ok:
        sys.exit(9)
emit("run_end", steps=1, seconds=0.1, status="ok")
"""


def test_chaos_shrink_then_reexpand_with_consensus(tmp_path):
    """ISSUE 13 acceptance (shrink/grow half): kill mid-numbered host 1 of
    a 3-host mesh -> the supervisor's first attempt runs dp-only on the
    dense-id survivors (NO restarts_exhausted); host 1 re-registers
    mid-attempt -> the supervisor SIGTERMs the child (which snapshots),
    relaunches at the restored world size with peer resume, and the whole
    cycle is on the record: scale events, attempt classes, and the
    ledger_report elasticity timeline."""
    script = tmp_path / "child.py"
    script.write_text(_ELASTIC_CHILD)
    ledger = str(tmp_path / "run.jsonl")
    cdir = str(tmp_path / "consensus")
    # 3 registered hosts, epoch 0 agreed; then mid-numbered host 1 dies
    peers = [ConsensusDir(cdir, h, planned=3, lease_s=60.0)
             for h in range(3)]
    for c in peers:
        c.register()
    assert peers[0].resolve().hosts == (0, 1, 2)
    peers[1].leave()

    env = dict(os.environ)
    env.update({"TPU_DIST_NUM_PROCESSES": "3", "TPU_DIST_PROCESS_ID": "0"})
    sup = Supervisor(
        [sys.executable, str(script), "--ledger-base", ledger],
        ledger=ledger,
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.01,
                             stall_timeout_s=60.0),
        env=env, forward_flags=False, poll_s=0.05,
        consensus=ConsensusDir(cdir, 0, planned=3, lease_s=60.0),
        consensus_poll_s=0.15)

    # host 1 returns while the degraded attempt is running
    returner = threading.Timer(1.0, peers[1].register)
    returner.start()
    try:
        res = sup.run()
    finally:
        returner.cancel()
    assert res.ok, [(a.failure_class, a.returncode) for a in res.attempts]
    # attempt 0: degraded run, ended by OUR rescale SIGTERM with a
    # snapshot; attempt 1: clean at the restored world size. The rescale
    # relaunch consumed NO restart budget.
    assert [a.failure_class for a in res.attempts] == \
        ["preemption_snapshotted", "clean"]
    assert sup.env["TPU_DIST_NUM_PROCESSES"] == "3"
    assert "TPU_DIST_DEGRADED" not in sup.env
    assert not sup.degraded

    # the supervisor's scale ledger: shrink (epoch 1) then expand (epoch 2)
    sup_ledger = str(tmp_path / "run.sup.jsonl")
    scales = [r for r in read_ledger(sup_ledger, validate=False,
                                     strict=False)
              if r.get("event") == "scale"]
    assert [s["action"] for s in scales] == ["shrink", "expand"]
    assert scales[0]["processes"] == 2 and scales[0]["hosts"] == [0, 2]
    assert scales[1]["processes"] == 3 and scales[1]["hosts"] == [0, 2, 1]

    # the stitched report: restarts + elasticity sections tell the story
    sys.path.insert(0, ROOT)
    from tools.ledger_report import elasticity_section, restarts_section
    records = []
    # the ledger_report merge shape: attempt files in order, the
    # supervisor sibling APPENDED (never ts-interleaved)
    for p in discover_attempt_paths(ledger) + [sup_ledger]:
        records += read_ledger(p, validate=False, strict=False)
    lines = []
    rep = restarts_section(records, out=lines.append)
    assert [a["class"] for a in rep["attempts"]] == \
        ["preemption_snapshotted", "clean"]
    assert rep["attempts"][0]["degraded"] is True
    assert rep["attempts"][1]["degraded"] is False
    rows = elasticity_section(records, out=lines.append)
    assert [r["action"] for r in rows] == ["shrink", "expand"]
    text = "\n".join(lines)
    assert "mesh shrink" in text and "mesh re-expansion" in text


# ---------------------------------------------------------------------------
# chaos acceptance: injected preempt_deadline -> coordinated snapshot whose
# resume step equals the pre-preemption step (real LM script on CPU)

def _script_env(**extra):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("TPU_DIST") and k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


_LM_TINY = ["--epochs", "2", "--batch-size", "4", "--seq-len", "32",
            "--d-model", "32", "--num-layers", "1", "--num-heads", "2",
            "--vocab-size", "64", "--synth-tokens", "2000",
            "--print-freq", "1"]


def test_preempt_deadline_snapshot_resumes_exact_step(tmp_path):
    """ISSUE 13 acceptance (snapshot half): an injected preempt_deadline
    at step 20 of attempt 0 makes the engine finish its in-flight work,
    write the coordinated snapshot and exit PREEMPT_SNAPSHOT_RC; the
    supervised restart resumes from EXACTLY the pre-preemption step (not
    the last periodic checkpoint), and the preemption is visible in the
    ledger_report elasticity timeline."""
    ledger = str(tmp_path / "run.jsonl")
    sup = Supervisor(
        [sys.executable, os.path.join(ROOT, "scripts",
                                      "8.lm_longcontext.py"), *_LM_TINY],
        ledger=ledger, ckpt_dir=str(tmp_path / "ck"),
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.05,
                             stall_timeout_s=300.0),
        env=_script_env(
            TPU_DIST_FAULTS="preempt_deadline@step=20,attempt=0"),
        poll_s=0.1)
    res = sup.run()
    assert res.ok, [(a.failure_class, a.returncode) for a in res.attempts]
    assert [a.failure_class for a in res.attempts] == \
        ["preemption_snapshotted", "clean"]
    assert res.attempts[0].returncode == PREEMPT_SNAPSHOT_RC

    paths = discover_attempt_paths(ledger)
    att0 = read_ledger(paths[0], validate=False, strict=False)
    att1 = read_ledger(paths[1], validate=False, strict=False)
    end0 = [r for r in att0 if r.get("event") == "run_end"][-1]
    assert end0["status"] == "preempted"
    snap_step = end0["snapshot_step"]
    # the snapshot step IS the pre-preemption step: every step the first
    # attempt applied is in it (fault fires before dispatching step 20,
    # with steps 0..19 already applied -> state.step == 20)
    steps0 = [r["step"] for r in att0 if r.get("event") == "step"]
    assert snap_step == len(steps0) == max(steps0) + 1 == 20
    # the committed snapshot container names exactly that step (read the
    # retained keep-K sibling: the bare pointer has since advanced past
    # it — the clean second attempt wrote its own epoch checkpoints)
    from tpu_dist.engine.checkpoint import read_checkpoint_meta
    snap = os.path.join(str(tmp_path / "ck"),
                        f"lm-checkpoint.r{snap_step}.msgpack")
    assert os.path.exists(snap)
    meta = read_checkpoint_meta(snap)
    assert meta["step"] == snap_step and meta.get("preempt") is True
    # and the restart resumed there: its first step record continues the
    # trajectory with no retrained (or skipped) steps
    starts1 = [r for r in att1 if r.get("event") == "run_start"]
    assert starts1[0]["config"]["resume"].endswith("lm-checkpoint.msgpack")
    steps1 = [r["step"] for r in att1 if r.get("event") == "step"]
    assert min(steps1) == snap_step
    # the engine's scale event + the elasticity timeline render it
    scales = [r for r in att0 if r.get("event") == "scale"]
    assert [s["action"] for s in scales] == ["preempt_snapshot"]
    assert scales[0]["step"] == snap_step
    sys.path.insert(0, ROOT)
    from tools.ledger_report import elasticity_section
    lines = []
    rows = elasticity_section(att0 + att1, out=lines.append)
    assert [r["action"] for r in rows] == ["preempt_snapshot"]
    assert "preemption snapshot" in "\n".join(lines)


@pytest.mark.slow  # tier-1 budget (PR 14): near-duplicate of the
# supervisor-driven snapshot path — the same SIGTERM -> in-flight-step ->
# coordinated-snapshot -> rc 75 contract is pinned in-budget by
# test_preempt_deadline_snapshot_resumes_exact_step (this twin only swaps
# who sends the signal), and the fleet acceptance
# (test_fleet.py::test_fleet_ci_scenario_acceptance) SIGTERMs real serve
# workers on every rescale
def test_sigterm_during_run_is_honored_with_snapshot(tmp_path):
    """The real signal path, no supervisor: SIGTERM to a training child
    mid-epoch produces the coordinated snapshot + rc 75 (the crash guard's
    old immediate-death path only remains for loops that never enabled
    snapshots)."""
    ledger = str(tmp_path / "run.jsonl")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(ROOT, "scripts",
                                      "8.lm_longcontext.py"), *_LM_TINY,
         "--ledger-path", ledger,
         "--checkpoint-dir", str(tmp_path / "ck")],
        env=_script_env(), stderr=subprocess.PIPE, text=True)
    # wait for the first step record (the run is mid-epoch), then preempt
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if os.path.exists(ledger) and any(
                r.get("event") == "step"
                for r in read_ledger(ledger, validate=False, strict=False)):
            break
        if proc.poll() is not None:
            break
        time.sleep(0.2)
    assert proc.poll() is None, proc.stderr.read()
    proc.send_signal(15)
    rc = proc.wait(timeout=120)
    proc.stderr.read()
    assert rc == PREEMPT_SNAPSHOT_RC
    records = read_ledger(ledger, validate=False, strict=False)
    end = [r for r in records if r.get("event") == "run_end"][-1]
    assert end["status"] == "preempted"
    assert any(r.get("event") == "scale"
               and r.get("action") == "preempt_snapshot" for r in records)
    # the snapshot container exists and its pointer step matches
    with open(os.path.join(str(tmp_path / "ck"),
                           "lm-checkpoint.index.json")) as f:
        assert json.load(f)["step"] == end["snapshot_step"]


# ---------------------------------------------------------------------------
# review-fix regressions

def test_resolve_view_keys_on_world_size_not_degraded_edges(tmp_path):
    """A second loss while ALREADY degraded (4->3->2) is still a shrink,
    and a partial return (2->3, still short of plan) is still an
    expansion that arms peer resume — transitions key on world-size
    changes, not on degraded-flag edges."""
    cdir = str(tmp_path / "c")
    peers = [ConsensusDir(cdir, h, planned=4, lease_s=60.0)
             for h in range(4)]
    for c in peers:
        c.register()
    sup = Supervisor(["true"], ledger=str(tmp_path / "run.jsonl"),
                     consensus=ConsensusDir(cdir, 0, planned=4,
                                            lease_s=60.0))
    assert sup._resolve_view().world_size == 4 and not sup.degraded
    peers[2].leave()
    sup._resolve_view()                     # 4 -> 3: shrink
    peers[3].leave()
    sup._resolve_view()                     # 3 -> 2: STILL a shrink
    peers[3].register()
    v = sup._resolve_view()                 # 2 -> 3: partial expansion
    assert v.degraded and sup._peer_resume_next
    sup._peer_resume_next = False
    peers[2].register()
    v = sup._resolve_view()                 # 3 -> 4: full strength
    assert not v.degraded and sup._peer_resume_next
    scales = [r for r in read_ledger(str(tmp_path / "run.sup.jsonl"),
                                     validate=False, strict=False)
              if r.get("event") == "scale"]
    assert [s["action"] for s in scales] == \
        ["shrink", "shrink", "expand", "expand"]
    assert [(s["world_from"], s["processes"]) for s in scales] == \
        [(4, 3), (3, 2), (2, 3), (3, 4)]


def test_detect_launch_slurm_honors_consensus_overrides(monkeypatch):
    """A supervisor relaunch after host loss exports shrunken TPU_DIST_*
    values while SLURM_* still describes the original allocation — the
    consensus renumbering and the epoch port offset must win."""
    for k in ("TPU_DIST_COORDINATOR", "TPU_DIST_NUM_PROCESSES",
              "TPU_DIST_PROCESS_ID", "TPU_DIST_MESH_EPOCH"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("SLURM_PROCID", "3")
    monkeypatch.setenv("SLURM_NPROCS", "4")
    monkeypatch.setenv("SLURM_JOB_NODELIST", "node[1-4]")
    info = detect_launch()
    assert info.method == "slurm"
    assert (info.num_processes, info.process_id) == (4, 3)
    assert info.coordinator.endswith(":8476")
    monkeypatch.setenv("TPU_DIST_NUM_PROCESSES", "3")
    monkeypatch.setenv("TPU_DIST_PROCESS_ID", "2")
    monkeypatch.setenv("TPU_DIST_MESH_EPOCH", "1")
    info = detect_launch()
    assert (info.num_processes, info.process_id) == (3, 2)
    assert info.coordinator.endswith(":8477")  # fresh epoch, fresh port


def test_preempt_deadline_fault_carries_secs():
    # the effects mapping delivers the injected deadline to the engine
    faults.install("preempt_deadline@step=5,secs=3")
    effects = faults.fire_step(5)
    assert set(effects) == {"preempt_deadline"}
    assert effects["preempt_deadline"].args["secs"] == 3.0


def test_host_return_injection_lands_a_fault_event(tmp_path):
    # injected re-expansions must stay distinguishable from organic ones
    from tpu_dist.obs.ledger import Ledger

    records = []
    c = ConsensusDir(str(tmp_path), 0, planned=2, lease_s=60.0)
    c.fault_ledger = Ledger(None, sinks=(records.append,))
    c.register()
    c.resolve()
    faults.install("host_return@nth=1")
    v = c.resolve()
    assert v.hosts == (0, 1)  # host 1 resurrected
    fault_events = [r for r in records if r["event"] == "fault"]
    assert len(fault_events) == 1
    assert fault_events[0]["site"] == "host_return"
