"""Bench regression tracker (tools/bench_track.py) — no jax.

Covers: the checked-in BENCH_r*.json history parsing (the real
140.8k -> 174.6k trajectory), the threshold check against an
injected-regression fixture (nonzero exit — the acceptance bar),
--headline appending the run under test, --json output shape, and
malformed/non-bench files being skipped rather than crashing."""

import json
import os
import shutil

import pytest

from tools.bench_track import load_points, main, track

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADLINE = "cifar10_resnet50_images_per_sec_per_chip"


def _write_round(dirpath, n, value, metric=HEADLINE, **parsed_extra):
    doc = {"n": n, "cmd": "python bench.py", "rc": 0,
           "parsed": {"metric": metric, "value": value,
                      "unit": "images/sec/chip", **parsed_extra}}
    path = os.path.join(dirpath, f"BENCH_r{n:02d}.json")
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def test_checked_in_history_reports_trend(capsys):
    """The repo's own BENCH_r01..r05 parse into the known trajectory and
    pass the gate (r05 is the trailing best)."""
    assert main(["--dir", ROOT, "--check"]) == 0
    out = capsys.readouterr().out
    assert HEADLINE in out
    assert "140,821.2" in out and "174,621.9" in out  # 140.8k -> 174.6k
    assert "ok: latest" in out


def test_injected_regression_exits_nonzero(tmp_path, capsys):
    """ACCEPTANCE: a fabricated regressed round fails --check."""
    for f in os.listdir(ROOT):
        if f.startswith("BENCH_r") and f.endswith(".json"):
            shutil.copy(os.path.join(ROOT, f), tmp_path)
    _write_round(str(tmp_path), 6, 100000.0)  # -42.7% vs r05's 174.6k
    assert main(["--dir", str(tmp_path), "--check"]) == 1
    err = capsys.readouterr().err
    assert "REGRESSION" in err and HEADLINE in err
    # without --check the report still renders, exit stays 0
    assert main(["--dir", str(tmp_path)]) == 0
    assert "REGRESSED 42.7%" in capsys.readouterr().out


def test_threshold_and_variant_metrics_track_independently(tmp_path):
    d = str(tmp_path)
    _write_round(d, 1, 1000.0)
    _write_round(d, 2, 960.0)  # -4% vs best: inside the default 5%
    points = load_points([os.path.join(d, f) for f in sorted(os.listdir(d))])
    report = track(points, threshold_pct=5.0)
    m = report["metrics"][HEADLINE]
    assert report["ok"] and not m["regressed"]
    assert m["drop_pct"] == pytest.approx(4.0)
    # a quant-variant metric regressing does not implicate the headline
    doc = {"n": 3, "rc": 0, "parsed": {"metric": "lm_int8_tok_s",
                                       "value": 50.0, "unit": "tok/s"}}
    p3 = os.path.join(d, "BENCH_r03.json")
    json.dump(doc, open(p3, "w"))
    _write_round(d, 4, 970.0)
    doc["n"] = 5
    doc["parsed"]["value"] = 10.0  # -80% on the variant only
    json.dump(doc, open(os.path.join(d, "BENCH_r05.json"), "w"))
    points = load_points([os.path.join(d, f) for f in sorted(os.listdir(d))])
    report = track(points, threshold_pct=5.0)
    assert report["metrics"]["lm_int8_tok_s"]["regressed"]
    assert not report["metrics"][HEADLINE]["regressed"]
    assert not report["ok"]


def test_headline_file_is_newest_point_and_gates(tmp_path, capsys):
    d = str(tmp_path)
    _write_round(d, 1, 1000.0)
    _write_round(d, 2, 1100.0)
    head = os.path.join(d, "head.json")
    json.dump({"metric": HEADLINE, "value": 900.0,
               "unit": "images/sec/chip"}, open(head, "w"))
    # --headline implies the gate: 900 vs best 1100 = -18% -> fail
    assert main(["--dir", d, "--headline", head]) == 1
    capsys.readouterr()
    json.dump({"metric": HEADLINE, "value": 1200.0,
               "unit": "images/sec/chip"}, open(head, "w"))
    assert main(["--dir", d, "--headline", head]) == 0
    capsys.readouterr()
    # a missing or unusable run-under-test must FAIL the gate, not
    # silently judge only the history
    assert main(["--dir", d, "--headline",
                 os.path.join(d, "nope.json")]) == 2
    with open(head, "w") as f:
        f.write("{truncated")
    assert main(["--dir", d, "--headline", head]) == 2
    assert "cannot be judged" in capsys.readouterr().err


def test_json_output_and_skipped_files(tmp_path, capsys):
    d = str(tmp_path)
    _write_round(d, 1, 1000.0, mfu=0.30)
    _write_round(d, 2, 1050.0, mfu=0.33)
    # a MULTICHIP-style file (no parsed metric), a corrupt file, and a
    # crashed round's value:null — all skipped with a note, never a crash
    json.dump({"n_devices": 8, "ok": True},
              open(os.path.join(d, "BENCH_r03.json"), "w"))
    with open(os.path.join(d, "BENCH_r04.json"), "w") as f:
        f.write("{not json")
    json.dump({"n": 5, "rc": 1, "parsed": {"metric": HEADLINE,
                                           "value": None}},
              open(os.path.join(d, "BENCH_r05.json"), "w"))
    assert main(["--dir", d, "--json"]) == 0
    cap = capsys.readouterr()
    report = json.loads(cap.out)
    assert "skipping" in cap.err
    m = report["metrics"][HEADLINE]
    assert [r["value"] for r in m["rounds"]] == [1000.0, 1050.0]
    assert m["rounds"][1]["delta_pct"] == pytest.approx(5.0)
    assert m["rounds"][1]["mfu"] == 0.33
    assert report["ok"] is True


def test_no_usable_points_is_distinct_error(tmp_path):
    assert main(["--dir", str(tmp_path)]) == 2


def test_fleet_goodput_gates_and_abstains_on_pre_fleet_history(tmp_path):
    """The round-14 fleet gate: `fleet.goodput_ratio` is judged like the
    headline (higher is better, threshold_pct) against the best prior
    point CARRYING a fleet block — the pre-fleet BENCH history abstains,
    exactly the data_s / serving.requests_per_tick convention."""
    d = str(tmp_path)
    _write_round(d, 1, 1000.0)                      # pre-fleet: no block
    _write_round(d, 2, 1000.0, fleet={"goodput_ratio": 0.40})
    paths = [os.path.join(d, f) for f in sorted(os.listdir(d))]
    points = load_points(paths)
    assert [p["fleet_goodput"] for p in points] == [None, 0.40]
    m = track(points, threshold_pct=5.0)["metrics"][HEADLINE]
    # one fleet point: nothing prior to judge against — abstain, ok
    assert m["fleet_latest"] == 0.40 and m["fleet_best_prior"] is None
    assert not m["fleet_regressed"]
    # a regressed ratio fails the gate even with the headline value flat
    _write_round(d, 3, 1000.0, fleet={"goodput_ratio": 0.30})  # -25%
    report = track(load_points(paths + [os.path.join(d, "BENCH_r03.json")]),
                   threshold_pct=5.0)
    m = report["metrics"][HEADLINE]
    assert m["fleet_regressed"] and not report["ok"]
    assert main(["--dir", d, "--check"]) == 1
    # inside the threshold: ok again
    _write_round(d, 3, 1000.0, fleet={"goodput_ratio": 0.395})  # -1.3%
    assert main(["--dir", d, "--check"]) == 0


def test_fleet_headline_from_the_sim_runner_shape(tmp_path, capsys):
    """The runner's headline.json (metric fleet_sim_goodput + fleet
    block) loads as a first point and renders the no-history abstention."""
    d = str(tmp_path)
    path = os.path.join(d, "headline.json")
    with open(path, "w") as f:
        json.dump({"metric": "fleet_sim_goodput", "value": 0.31,
                   "unit": "ratio",
                   "fleet": {"goodput_ratio": 0.31, "slo_breaches": 4,
                             "hosts": 3}}, f)
    assert main([path]) == 0
    out = capsys.readouterr().out
    assert "fleet_sim_goodput" in out
    assert "no prior fleet history" in out
