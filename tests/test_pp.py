"""Pipeline parallelism (GPipe over 'stage') == data-parallel ground truth.

The whole point of a parallelism axis is that it changes WHERE compute runs,
never WHAT is computed: one pp train step over a (data, stage) mesh must
reproduce the plain jit DP step's loss, metrics, and updated parameters.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.engine.lm_steps import make_lm_batches, make_lm_train_step
from tpu_dist.engine.state import TrainState
from tpu_dist.models.transformer import tiny_lm
from tpu_dist.ops import make_optimizer
from tpu_dist.parallel.mesh import make_mesh, replicated
from tpu_dist.parallel.pp import (make_lm_pp_train_step,
                                  shard_state_pp, stack_pipeline_params,
                                  unstack_pipeline_params)

V, L, B, D = 64, 32, 8, 64


def _setup(num_layers=4):
    lm = tiny_lm(vocab_size=V, num_layers=num_layers, d_model=D, num_heads=4,
                 max_len=L)
    params = lm.init({"params": jax.random.PRNGKey(0)},
                     jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=100)
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, V, (B, L + 1)).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    return lm, params, tx, inputs, targets


def test_stack_unstack_roundtrip():
    _, params, _, _, _ = _setup()
    pp = stack_pipeline_params(params, num_stages=4)
    back = unstack_pipeline_params(pp)
    a = {jax.tree_util.keystr(p): v for p, v
         in jax.tree_util.tree_leaves_with_path(params)}
    b = {jax.tree_util.keystr(p): v for p, v
         in jax.tree_util.tree_leaves_with_path(back)}
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_blocks_not_divisible_raises():
    _, params, _, _, _ = _setup(num_layers=4)
    with pytest.raises(ValueError, match="not divisible"):
        stack_pipeline_params(params, num_stages=3)


def _maker(schedule):
    if schedule == "1f1b":
        from tpu_dist.parallel.pp import make_lm_pp_1f1b_train_step
        return make_lm_pp_1f1b_train_step
    return make_lm_pp_train_step


# tier-1 budget (PR 10): the pure-pp gpipe parity is a 9s near-duplicate —
# gpipe parity stays in-budget via
# test_quant.test_quant_pp_step_matches_dp[int8-gpipe] (same step builder).
# PR 11: the bare 1f1b parity (19s) is likewise covered in-budget by
# test_pp_1f1b_loss_chunk_matches_dp (same schedule + builder vs DP, with
# the stricter chunked-head path on top); both full-geometry params stay
# live in the slow suite
@pytest.mark.parametrize("schedule", [
    pytest.param("gpipe", marks=pytest.mark.slow),
    pytest.param("1f1b", marks=pytest.mark.slow)])
@pytest.mark.parametrize("mesh_shape,axes,microbatches", [
    ((1, 4), ("data", "stage"), 4),   # pure pipeline
    # tier-1 budget (PR 3): the dp x pp and blocks-per-stage layouts are
    # heavy near-duplicates of the pure-pp parity; slow-marked
    pytest.param((2, 4), ("data", "stage"), 2,
                 marks=pytest.mark.slow),   # dp x pp
    pytest.param((2, 2), ("data", "stage"), 4,
                 marks=pytest.mark.slow),   # 2 blocks per stage
])
def test_pp_step_matches_dp(mesh_shape, axes, microbatches, schedule):
    """Either pipeline schedule == plain DP, loss/metrics/params — a
    schedule changes WHEN microbatches run, never what is computed."""
    lm, params, tx, inputs, targets = _setup()
    key = jax.random.PRNGKey(1)

    # ground truth: plain DP on a 1-device mesh
    mesh_dp = make_mesh((1,), ("data",), devices=jax.devices()[:1])
    st_dp = jax.device_put(TrainState.create(params, {}, tx),
                           replicated(mesh_dp))
    dp_step = make_lm_train_step(lm, tx, mesh_dp, donate=False)
    sh = jax.sharding.NamedSharding(mesh_dp, jax.sharding.PartitionSpec("data"))
    st_dp, m_dp = dp_step(st_dp, jax.device_put(inputs, sh),
                          jax.device_put(targets, sh), key)

    # pipeline over (data, stage)
    ndev = int(np.prod(mesh_shape))
    mesh = make_mesh(mesh_shape, axes, devices=jax.devices()[:ndev])
    pp_params = stack_pipeline_params(params, num_stages=mesh.shape["stage"])
    st_pp = shard_state_pp(mesh, TrainState.create(pp_params, {}, tx))
    pp_step = _maker(schedule)(lm, tx, mesh, microbatches, donate=False)
    sh_pp = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data", None))
    st_pp, m_pp = pp_step(st_pp, jax.device_put(inputs, sh_pp),
                          jax.device_put(targets, sh_pp), key)

    # identical loss/metric sums
    for k in ("loss_sum", "correct1", "count"):
        assert float(jax.device_get(m_pp[k])) == pytest.approx(
            float(jax.device_get(m_dp[k])), rel=1e-5), k

    # identical updated parameters, leaf for leaf
    back = unstack_pipeline_params(jax.device_get(st_pp.params))
    flat_dp = {jax.tree_util.keystr(p): v for p, v in
               jax.tree_util.tree_leaves_with_path(jax.device_get(st_dp.params))}
    flat_pp = {jax.tree_util.keystr(p): v for p, v in
               jax.tree_util.tree_leaves_with_path(back)}
    assert flat_dp.keys() == flat_pp.keys()
    for path in flat_dp:
        np.testing.assert_allclose(
            np.asarray(flat_dp[path]), np.asarray(flat_pp[path]),
            rtol=2e-5, atol=1e-7, err_msg=str(path))
    assert int(jax.device_get(st_pp.step)) == 1


def test_pp_1f1b_loss_chunk_matches_dp():
    """Chunked CE on the 1f1b head (round 5 — round 4 reached only gpipe):
    --loss-chunk swaps the last stage's full-logits head vjp for the
    ops.fused_xent custom_vjp inside head_loss; identical math, so a
    chunked 1f1b step must equal the plain DP step."""
    from tpu_dist.parallel.pp import make_lm_pp_1f1b_train_step

    lm, params, tx, inputs, targets = _setup()
    key = jax.random.PRNGKey(1)

    mesh_dp = make_mesh((1,), ("data",), devices=jax.devices()[:1])
    st_dp = jax.device_put(TrainState.create(params, {}, tx),
                           replicated(mesh_dp))
    dp_step = make_lm_train_step(lm, tx, mesh_dp, donate=False)
    sh = jax.sharding.NamedSharding(mesh_dp, jax.sharding.PartitionSpec("data"))
    st_dp, m_dp = dp_step(st_dp, jax.device_put(inputs, sh),
                          jax.device_put(targets, sh), key)

    mesh = make_mesh((2, 4), ("data", "stage"))
    pp_params = stack_pipeline_params(params, num_stages=4)
    st_pp = shard_state_pp(mesh, TrainState.create(pp_params, {}, tx))
    # chunk (17) deliberately does NOT divide the microbatch's token count
    # so the padded-tail path of the chunked kernel is exercised too
    pp_step = make_lm_pp_1f1b_train_step(lm, tx, mesh, 2, donate=False,
                                         loss_chunk=17)
    sh_pp = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data", None))
    st_pp, m_pp = pp_step(st_pp, jax.device_put(inputs, sh_pp),
                          jax.device_put(targets, sh_pp), key)

    for k in ("loss_sum", "correct1", "count"):
        assert float(jax.device_get(m_pp[k])) == pytest.approx(
            float(jax.device_get(m_dp[k])), rel=1e-5), k
    back = unstack_pipeline_params(jax.device_get(st_pp.params))
    flat_dp = {jax.tree_util.keystr(p): v for p, v in
               jax.tree_util.tree_leaves_with_path(jax.device_get(st_dp.params))}
    flat_pp = {jax.tree_util.keystr(p): v for p, v in
               jax.tree_util.tree_leaves_with_path(back)}
    for path in flat_dp:
        np.testing.assert_allclose(
            np.asarray(flat_dp[path]), np.asarray(flat_pp[path]),
            rtol=2e-5, atol=1e-7, err_msg=str(path))


@pytest.mark.slow  # tier-1 budget (PR 20): multi-step convergence twin of the exact single-step parities that stay in-budget (test_pp_step_matches_dp, test_pp_1f1b_loss_chunk_matches_dp)
def test_pp_multiple_steps_converge():
    """Loss decreases over repeated pp steps (end-to-end sanity)."""
    lm, params, tx, inputs, targets = _setup()
    mesh = make_mesh((2, 4), ("data", "stage"))
    pp_params = stack_pipeline_params(params, 4)
    st = shard_state_pp(mesh, TrainState.create(pp_params, {}, tx))
    step = make_lm_pp_train_step(lm, tx, mesh, num_microbatches=2,
                                 donate=False)
    sh = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data", None))
    di, dt = jax.device_put(inputs, sh), jax.device_put(targets, sh)
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(8):
        st, m = step(st, di, dt, key)
        # distlint: disable=DL002 -- CPU test: per-step loss assertion needs the value now
        losses.append(float(jax.device_get(m["loss_sum"]))
                      / float(jax.device_get(m["count"])))
    assert losses[-1] < losses[0] * 0.85, losses
    assert losses == sorted(losses, reverse=True), losses  # monotone descent


@pytest.mark.slow  # tier-1 budget (PR 7): 27s memory-property compile; 1f1b stays covered by pp_step_matches_dp[1f1b] + the loss_chunk parity
def test_pp_1f1b_activation_memory_independent_of_microbatches():
    """THE 1F1B property: compiled temp (activation) memory is flat in M,
    while GPipe-by-autodiff grows linearly (it stashes every tick input).
    Asserted from XLA's own memory analysis of the compiled programs."""
    from tpu_dist.parallel.pp import make_lm_pp_1f1b_train_step

    lm, params, tx, _, _ = _setup()
    mesh = make_mesh((2, 4), ("data", "stage"))
    pp_params = stack_pipeline_params(params, 4)

    def temp_bytes(maker, m):
        b = 2 * m * 2  # fixed microbatch size: B = data * mb_rows * M
        tokens = np.zeros((b, L + 1), np.int32)
        inputs, targets = make_lm_batches(tokens)
        st0 = shard_state_pp(mesh, TrainState.create(pp_params, {}, tx))
        sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data", None))
        step = maker(lm, tx, mesh, num_microbatches=m, donate=False)
        ma = step.lower(st0, jax.device_put(inputs, sh),
                        jax.device_put(targets, sh),
                        jax.random.PRNGKey(0)).compile().memory_analysis()
        return int(ma.temp_size_in_bytes)

    g4, g16 = (temp_bytes(make_lm_pp_train_step, m) for m in (4, 16))
    f4, f16 = (temp_bytes(make_lm_pp_1f1b_train_step, m) for m in (4, 16))
    assert g16 > g4 * 2          # gpipe: O(M) activation stash
    assert f16 < f4 * 1.25       # 1f1b: flat (stash depth 2(S-1)+1)
    assert f16 < g16 / 3         # and far below gpipe at large M


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pp_dead_work_gated_per_stage(schedule):
    """Dead-work gating (VERDICT r3 #1): in the optimized HLO every matmul
    — the full-vocab head, the embedding vjp scatter, AND the per-tick
    block compute — sits inside a lax.cond branch, so a stage executes the
    embed/head work only if it owns it and skips bubble ticks entirely.
    XLA's cost model counts both branches of a conditional, so the
    assertion is structural: ops traced inside lax.cond carry '/cond' in
    their op_name metadata, and no dot may live outside one."""
    import re

    lm, params, tx, inputs, targets = _setup()
    mesh = make_mesh((2, 4), ("data", "stage"))
    pp_params = stack_pipeline_params(params, 4)
    st = shard_state_pp(mesh, TrainState.create(pp_params, {}, tx))
    step = _maker(schedule)(lm, tx, mesh, num_microbatches=2, donate=False)
    sh = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data", None))
    txt = step.lower(st, jax.device_put(inputs, sh),
                     jax.device_put(targets, sh),
                     jax.random.PRNGKey(0)).compile().as_text()

    dots = [ln for ln in txt.splitlines() if " dot(" in ln]
    assert len(dots) >= 6, "expected matmuls in the compiled pipeline"
    ungated = []
    for ln in dots:
        m = re.search(r'op_name="([^"]*)"', ln)
        if not (m and "cond" in m.group(1)):
            ungated.append(ln.strip()[:120])
    assert not ungated, f"matmuls outside lax.cond branches: {ungated}"

    # the embedding table's backward scatter-add is stage-0-gated too
    scatters = [ln for ln in txt.splitlines() if " scatter(" in ln]
    for ln in scatters:
        m = re.search(r'op_name="([^"]*)"', ln)
        assert m and "cond" in m.group(1), ln.strip()[:120]


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pp_tp_composition_matches_dp(schedule):
    """PP x TP over a (data=2, stage=2, model=2) mesh == plain DP: the
    pipeline schedule stays manual (shard_map) while 'model' runs as a
    GSPMD auto axis, so each stage's block math is Megatron-sharded —
    weights verifiably split over BOTH stage and model axes."""
    from tpu_dist._compat import PARTIAL_MANUAL_SHARD_MAP
    if not PARTIAL_MANUAL_SHARD_MAP:
        pytest.skip("pp x tp needs partial-manual shard_map (jax >= 0.6); "
                    "this jax's experimental shard_map aborts in the SPMD "
                    "partitioner (_compat.PARTIAL_MANUAL_SHARD_MAP)")
    lm, params, tx, inputs, targets = _setup()
    key = jax.random.PRNGKey(1)

    mesh_dp = make_mesh((1,), ("data",), devices=jax.devices()[:1])
    st_dp = jax.device_put(TrainState.create(params, {}, tx),
                           replicated(mesh_dp))
    dp_step = make_lm_train_step(lm, tx, mesh_dp, donate=False)
    sh = jax.sharding.NamedSharding(mesh_dp, jax.sharding.PartitionSpec("data"))
    st_dp, m_dp = dp_step(st_dp, jax.device_put(inputs, sh),
                          jax.device_put(targets, sh), key)

    mesh = make_mesh((2, 2, 2), ("data", "stage", "model"))
    pp_params = stack_pipeline_params(params, num_stages=2)
    st_pp = shard_state_pp(mesh, TrainState.create(pp_params, {}, tx))
    # TP sharding actually applied: qkv kernel splits its LAST dim 2-ways
    w = st_pp.params["blocks"]["qkv"]["kernel"]
    assert w.addressable_shards[0].data.shape[-1] == w.shape[-1] // 2
    pp_step = _maker(schedule)(lm, tx, mesh, 2, donate=False)
    sh_pp = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data", None))
    st_pp, m_pp = pp_step(st_pp, jax.device_put(inputs, sh_pp),
                          jax.device_put(targets, sh_pp), key)

    for k in ("loss_sum", "correct1", "count"):
        assert float(jax.device_get(m_pp[k])) == pytest.approx(
            float(jax.device_get(m_dp[k])), rel=1e-5), k
    back = unstack_pipeline_params(jax.device_get(st_pp.params))
    flat_dp = {jax.tree_util.keystr(p): v for p, v in
               jax.tree_util.tree_leaves_with_path(jax.device_get(st_dp.params))}
    flat_pp = {jax.tree_util.keystr(p): v for p, v in
               jax.tree_util.tree_leaves_with_path(back)}
    for path in flat_dp:
        np.testing.assert_allclose(
            np.asarray(flat_dp[path]), np.asarray(flat_pp[path]),
            rtol=2e-4, atol=1e-6, err_msg=f"{schedule} {path}")
