"""Launch/rendezvous discovery tests (C23/C25 — the four rendezvous flavors)."""

import os
from unittest import mock

from tpu_dist.parallel.launch import _slurm_first_host, detect_launch


def test_local_default():
    with mock.patch.dict(os.environ, {}, clear=True):
        info = detect_launch()
        assert info.method == "local"
        assert info.num_processes == 1 and info.process_id == 0


def test_env_rendezvous():
    env = {"TPU_DIST_COORDINATOR": "10.0.0.1:8476",
           "TPU_DIST_NUM_PROCESSES": "4", "TPU_DIST_PROCESS_ID": "2"}
    with mock.patch.dict(os.environ, env, clear=True):
        info = detect_launch()
        assert info.method == "env"
        assert info.coordinator == "10.0.0.1:8476"
        assert info.num_processes == 4 and info.process_id == 2


def test_explicit_args_override_env():
    with mock.patch.dict(os.environ, {}, clear=True):
        info = detect_launch("h:1", 2, 1)
        assert (info.coordinator, info.num_processes, info.process_id) == \
            ("h:1", 2, 1)


def test_slurm_rendezvous():
    # reference 6.distributed_slurm_main.py:89-94 rank math
    env = {"SLURM_PROCID": "3", "SLURM_NPROCS": "4",
           "SLURM_JOB_NODELIST": "tpu-node[01-04]"}
    with mock.patch.dict(os.environ, env, clear=True):
        info = detect_launch()
        assert info.method == "slurm"
        assert info.process_id == 3 and info.num_processes == 4
        assert info.coordinator.startswith("tpu-node01:")


def test_slurm_nodelist_expansion():
    assert _slurm_first_host("host1") == "host1"
    assert _slurm_first_host("node[3-7]") == "node3"
    assert _slurm_first_host("gpu[11,13]") == "gpu11"
    assert _slurm_first_host("a01,a02") == "a01"


def test_single_slurm_proc_is_local():
    env = {"SLURM_PROCID": "0", "SLURM_NPROCS": "1"}
    with mock.patch.dict(os.environ, env, clear=True):
        assert detect_launch().method == "local"


def test_bool_flags_support_no_form():
    """BooleanOptionalAction: True-defaulted variant flags stay overridable."""
    from tpu_dist.configs import TrainConfig, parse_config

    d = TrainConfig(lr_scale_by_world=True)
    cfg = parse_config(["--no-lr-scale-by-world"], defaults=d)
    assert cfg.lr_scale_by_world is False
    cfg2 = parse_config([], defaults=d)
    assert cfg2.lr_scale_by_world is True
