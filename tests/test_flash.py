"""Blockwise + Pallas-flash attention == full attention (values AND grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.models.transformer import full_attention, tiny_lm
from tpu_dist.ops.flash_attention import (blockwise_attention_fn,
                                          flash_attention_fn)

B, L, H, D = 2, 128, 4, 32


def _qkv(seed=0, l=L):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (B, l, H, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("blk", [32, 64, 128])
def test_blockwise_matches_full(blk):
    q, k, v = _qkv()
    ref = full_attention(q, k, v)
    out = blockwise_attention_fn(blk)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_blockwise_grads_match_full():
    q, k, v = _qkv(1)

    def loss(fn, *args):
        return jnp.sum(fn(*args) ** 2)

    g_ref = jax.grad(lambda q_, k_, v_: loss(full_attention, q_, k_, v_),
                     argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(
        lambda q_, k_, v_: loss(blockwise_attention_fn(32), q_, k_, v_),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


def test_flash_forward_matches_full():
    q, k, v = _qkv(2)
    ref = full_attention(q, k, v)
    out = flash_attention_fn(block_q=64)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_grads_match_full():
    q, k, v = _qkv(3)

    def loss(fn, *args):
        return jnp.sum(fn(*args) ** 2)

    g_ref = jax.grad(lambda q_, k_, v_: loss(full_attention, q_, k_, v_),
                     argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(
        lambda q_, k_, v_: loss(flash_attention_fn(block_q=64), q_, k_, v_),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


def test_offsets_respected():
    """Shifted positions mask exactly like full attention's offsets."""
    q, k, v = _qkv(4, l=64)
    ref = full_attention(q, k, v, q_offset=64, kv_offset=0)
    blk = blockwise_attention_fn(32)(q, k, v, q_offset=64, kv_offset=0)
    fl = flash_attention_fn(block_q=32)(q, k, v, q_offset=64, kv_offset=0)
    np.testing.assert_allclose(np.asarray(blk), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(fl), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_fully_masked_rows_zero():
    """kv_offset > q_offset makes EVERY key future for the early queries:
    those rows must output zeros (not the unmasked mean of V, which the
    online softmax produces when masked probabilities aren't zeroed)."""
    q, k, v = _qkv(6, l=64)
    # kv block starts 64 positions AFTER the queries -> all rows fully masked
    blk = blockwise_attention_fn(32)(q, k, v, q_offset=0, kv_offset=64)
    fl = flash_attention_fn(block_q=32)(q, k, v, q_offset=0, kv_offset=64)
    np.testing.assert_allclose(np.asarray(blk), 0.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(fl), 0.0, atol=1e-6)
    # partial masking: kv_offset = q_offset + 32 -> first 32 rows masked
    blk2 = blockwise_attention_fn(32)(q, k, v, q_offset=0, kv_offset=32)
    ref = full_attention(q, k, v, q_offset=0, kv_offset=32)
    ref = jnp.nan_to_num(ref)  # full attention NaNs on all-masked rows
    np.testing.assert_allclose(np.asarray(blk2[:, :32]), 0.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(blk2[:, 32:]),
                               np.asarray(ref[:, 32:]), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("fn_name", ["blockwise", "flash"])
def test_lm_forward_same_logits(fn_name):
    """The SAME TransformerLM weights produce the same logits under the
    memory-efficient attention flavors (the attn_fn plug-in contract)."""
    attn = (blockwise_attention_fn(32) if fn_name == "blockwise"
            else flash_attention_fn(block_q=32))
    kw = dict(vocab_size=64, num_layers=2, d_model=64, num_heads=4,
              max_len=L)
    lm_full = tiny_lm(**kw)
    lm_eff = tiny_lm(attn_fn=attn, **kw)
    params = lm_full.init({"params": jax.random.PRNGKey(0)},
                          jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, (2, L)), jnp.int32)
    ref = lm_full.apply({"params": params}, tokens, train=False)
    out = lm_eff.apply({"params": params}, tokens, train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_block_k_alias_conflict_raises():
    """recompute_block is a legacy alias for block_k: passing both is an
    error, not a silent override (ADVICE r3)."""
    with pytest.raises(ValueError, match="not both"):
        flash_attention_fn(block_k=256, recompute_block=128)
    # the alias alone still works
    assert flash_attention_fn(recompute_block=128) is not None


def test_blockwise_non_divisible_length_fits_gcd():
    """Blockwise follows the flash _blocks fit rule: a kv length that is a
    multiple of 512 but not of the 1024 default shrinks to the gcd instead
    of raising (the round-4 attn_block default bump must not break it)."""
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 96, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 96, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 96, 2, 16)), jnp.float32)
    out = blockwise_attention_fn(64)(q, k, v)  # 96 % 64 != 0 -> gcd 32
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---- int8-KV decode-path variant (round 9) --------------------------------

def test_int8kv_flash_matches_full_on_dequantized_kv():
    """The int8-KV kernel's only approximation is the KV quantization
    itself: against full attention over the DEQUANTIZED keys/values the
    outputs must agree to flash tolerance (the in-kernel per-tile dequant
    is exact), and against the fp KV the error stays at int8 scale."""
    from tpu_dist.ops.flash_attention import (int8kv_flash_attention_fn,
                                              quantize_kv)

    q, k, v = _qkv(7)
    kv = quantize_kv(k, v)
    kq, ks, vq, vs = kv
    assert kq.dtype == jnp.int8 and ks.shape == k.shape[:3]
    k_dq = kq.astype(jnp.float32) * ks[..., None]
    v_dq = vq.astype(jnp.float32) * vs[..., None]
    out = int8kv_flash_attention_fn(block_q=64, block_k=64)(q, kv)
    ref = full_attention(q, k_dq, v_dq)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # int8 KV vs fp KV: bounded by the quantization step, not exact
    fp = full_attention(q, k, v)
    assert float(jnp.max(jnp.abs(out - fp))) < 0.15


def test_int8kv_flash_decode_offsets():
    """The decode shape: one new query block attending into a longer
    quantized cache via q_offset (causal against absolute positions)."""
    from tpu_dist.ops.flash_attention import (int8kv_flash_attention_fn,
                                              quantize_kv)

    q, k, v = _qkv(8)
    kv = quantize_kv(k, v)
    kq, ks, vq, vs = kv
    k_dq = kq.astype(jnp.float32) * ks[..., None]
    v_dq = vq.astype(jnp.float32) * vs[..., None]
    tail = q[:, 64:]                 # last 64 positions are the new block
    out = int8kv_flash_attention_fn(block_q=32, block_k=64)(
        tail, kv, q_offset=64)
    ref = full_attention(q, k_dq, v_dq)[:, 64:]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
