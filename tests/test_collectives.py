"""Collectives over the virtual 8-device mesh (SURVEY.md §5 backend parity)."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dist.parallel import (allreduce_bench, barrier, compress_grads,
                               make_mesh, reduce_mean)

from tpu_dist._compat import shard_map


def test_mesh_shapes():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    mesh2 = make_mesh((4, 2), ("data", "model"))
    assert mesh2.shape == {"data": 4, "model": 2}
    mesh3 = make_mesh((-1, 2), ("data", "model"))
    assert mesh3.shape["data"] == 4
    with pytest.raises(ValueError):
        make_mesh((3,))


def test_reduce_mean_equals_global_mean():
    """C16: per-replica means pmean'd == mean of all replicas' values."""
    mesh = make_mesh()
    vals = jnp.arange(8.0)

    def f(x):
        local = jnp.sum(x)  # one value per device
        return reduce_mean(local, "data")

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P()))(vals)
    assert float(out) == pytest.approx(float(jnp.mean(vals)))


def test_compress_grads_bf16_roundtrip():
    g = {"a": jnp.float32(1.5), "b": jnp.ones((3,), jnp.float32)}
    down, up = compress_grads(g, "bf16")
    assert down["b"].dtype == jnp.bfloat16
    restored = up(down)
    assert restored["b"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(restored["a"]), 1.5)
    with pytest.raises(ValueError):
        compress_grads(g, "int4")


def test_barrier_completes():
    barrier(make_mesh())


def test_allreduce_bench_runs_and_reports():
    res = allreduce_bench(make_mesh(), sizes_mb=(0.001,), iters=2)
    (stats,) = res.values()
    assert stats["us"] > 0
    assert stats["gbps"] > 0


def test_adasum_reduce_formula_and_properties():
    """Adasum over 4 replicas: matches the host-computed recursive formula;
    parallel identical gradients AVERAGE, orthogonal gradients ADD."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from tpu_dist._compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dist.parallel.collectives import adasum_reduce
    from tpu_dist.parallel.mesh import make_mesh

    mesh = make_mesh((4,), ("data",), devices=jax.devices()[:4])

    def run(per_rep):  # per_rep: (4, D) one gradient per replica
        f = shard_map(
            lambda g: adasum_reduce({"w": g[0]}, "data", 4)["w"][None],
            mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False)
        out = jax.jit(f)(jnp.asarray(per_rep, jnp.float32))
        return np.asarray(out)

    def ada(a, b):
        ab = float(np.dot(a, b))
        na = max(float(np.dot(a, a)), 1e-30)
        nb = max(float(np.dot(b, b)), 1e-30)
        return (1 - ab / (2 * na)) * a + (1 - ab / (2 * nb)) * b

    rng = np.random.default_rng(0)
    g = rng.normal(size=(4, 16)).astype(np.float32)
    out = run(g)
    # recursive halving: rounds pair (0,1),(2,3) then the two halves
    expect = ada(ada(g[0], g[1]), ada(g[2], g[3]))
    for r in range(4):  # symmetric formula -> identical on every replica
        np.testing.assert_allclose(out[r], expect, rtol=1e-5, atol=1e-6)

    same = np.tile(g[0], (4, 1))
    np.testing.assert_allclose(run(same)[0], g[0], rtol=1e-5, atol=1e-6)

    orth = np.zeros((4, 16), np.float32)
    for r in range(4):
        orth[r, r] = 1.0  # mutually orthogonal -> Adasum SUMS them
    np.testing.assert_allclose(run(orth)[0], orth.sum(0), rtol=1e-5,
                               atol=1e-6)


def test_adasum_per_leaf_vs_whole_tree_differ():
    """Horovod applies Adasum PER TENSOR (VERDICT r3 #7): with one leaf
    parallel across replicas (must AVERAGE) and one orthogonal (must ADD),
    per-leaf granularity treats each correctly while the whole-tree variant
    mixes their inner products and does neither exactly."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from tpu_dist._compat import shard_map
    from jax.sharding import PartitionSpec as P

    from tpu_dist.parallel.collectives import adasum_reduce
    from tpu_dist.parallel.mesh import make_mesh

    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])
    par = np.tile(np.arange(1, 5, dtype=np.float32), (2, 1))   # identical
    orth = np.zeros((2, 4), np.float32)
    orth[0, 0] = orth[1, 1] = 3.0                              # orthogonal

    def run(granularity):
        f = shard_map(
            lambda p, o: jax.tree.map(
                lambda x: x[None],
                adasum_reduce({"par": p[0], "orth": o[0]}, "data", 2,
                              granularity=granularity)),
            mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs={"par": P("data"), "orth": P("data")},
            check_vma=False)
        out = jax.jit(f)(jnp.asarray(par), jnp.asarray(orth))
        return {k: np.asarray(v)[0] for k, v in out.items()}

    leaf = run("leaf")
    np.testing.assert_allclose(leaf["par"], par[0], rtol=1e-6)     # averaged
    np.testing.assert_allclose(leaf["orth"], orth.sum(0), rtol=1e-6)  # added
    tree = run("tree")
    # the whole-tree inner products couple the leaves: parallel leaf no
    # longer averages exactly, orthogonal leaf no longer adds exactly
    assert not np.allclose(tree["par"], leaf["par"], rtol=1e-4)
    assert not np.allclose(tree["orth"], leaf["orth"], rtol=1e-4)

    with pytest.raises(ValueError, match="granularity"):
        adasum_reduce({"w": None}, "data", 2, granularity="bucket")


def test_adasum_trainer_converges(tmp_path):
    """--variant shard_map --adasum trains end-to-end and learns."""
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg = TrainConfig(dataset="synthetic-mnist", arch="lenet", epochs=1,
                      batch_size=64, synth_train_size=256, synth_val_size=64,
                      seed=4, print_freq=100, variant="shard_map",
                      adasum=True, lr=0.02,
                      checkpoint_dir=str(tmp_path))
    tr = Trainer(cfg)
    tr.train_epoch(0)
    assert tr.validate(0) > 0.3


def test_adasum_rejects_non_power_of_two():
    import pytest

    from tpu_dist.parallel.collectives import adasum_reduce

    with pytest.raises(ValueError, match="power-of-two"):
        adasum_reduce({"w": None}, "data", axis_size=3)
