"""Collectives over the virtual 8-device mesh (SURVEY.md §5 backend parity)."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dist.parallel import (allreduce_bench, barrier, compress_grads,
                               make_mesh, reduce_mean)

from jax import shard_map


def test_mesh_shapes():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    mesh2 = make_mesh((4, 2), ("data", "model"))
    assert mesh2.shape == {"data": 4, "model": 2}
    mesh3 = make_mesh((-1, 2), ("data", "model"))
    assert mesh3.shape["data"] == 4
    with pytest.raises(ValueError):
        make_mesh((3,))


def test_reduce_mean_equals_global_mean():
    """C16: per-replica means pmean'd == mean of all replicas' values."""
    mesh = make_mesh()
    vals = jnp.arange(8.0)

    def f(x):
        local = jnp.sum(x)  # one value per device
        return reduce_mean(local, "data")

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P()))(vals)
    assert float(out) == pytest.approx(float(jnp.mean(vals)))


def test_compress_grads_bf16_roundtrip():
    g = {"a": jnp.float32(1.5), "b": jnp.ones((3,), jnp.float32)}
    down, up = compress_grads(g, "bf16")
    assert down["b"].dtype == jnp.bfloat16
    restored = up(down)
    assert restored["b"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(restored["a"]), 1.5)
    with pytest.raises(ValueError):
        compress_grads(g, "int4")


def test_barrier_completes():
    barrier(make_mesh())


def test_allreduce_bench_runs_and_reports():
    res = allreduce_bench(make_mesh(), sizes_mb=(0.001,), iters=2)
    (stats,) = res.values()
    assert stats["us"] > 0
    assert stats["gbps"] > 0
