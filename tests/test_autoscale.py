"""Closed autoscaling loop (round 20): the observability plane drives
capacity, with an auditable decision ledger — no jax anywhere but the
acceptance run's worker children.

The pins that matter:

* the policy grammar refuses garbage and the checked-in exemplar
  (``scripts/autoscale_policy.json``) round-trips;
* the capacity monitor's signals fold deterministically from ledger
  records, scale-up attribution is the FIRST tripped signal in the
  canonical order, and scale-down needs sustained calm (hysteresis) with
  a breach-free window — pressure at max capacity resets the streak;
* ``replay_decisions`` over the canned fixture is byte-deterministic
  with exact decision pins (the same property ``scripts/lint.sh`` gates);
* every consumer speaks the events: the ledger schema, the Prometheus
  series, the trace_merge decision markers, ledger_report's decision
  section, the fleet stitcher's decision<->scale<->applied join, and
  bench_track's reaction-lag gate;
* the ACCEPTANCE scenario (``scripts/fleet_autoscale.json``: 3 hosts,
  one parked standby, a diurnal curve with an overload burst) runs end
  to end and — read from ``tools/fleet_report.py --json`` — shows
  hosts-live following traffic, every scale action paired 1:1 with a
  decision, zero shed requests lost, and the post-rescale plan hash
  matching a byte-deterministic re-run of the tuner at the new world
  size.
"""

import json
import os
import subprocess
import sys

import pytest

from tpu_dist.obs.autoscale import (CALM_SIGNAL, SIGNAL_NAMES,
                                    AutoscalePolicy, CapacityMonitor,
                                    LedgerTailer, emit_decision,
                                    replay_decisions)
from tpu_dist.obs.ledger import Ledger, read_ledger
from tpu_dist.obs.metrics import MetricsRegistry, metrics_ledger_sink
from tpu_dist.sim.fleet import FleetLedger
from tpu_dist.sim.scenario import (RID_STRIDE, load_scenario,
                                   parse_scenario)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
POLICY = os.path.join(ROOT, "scripts", "autoscale_policy.json")
SCENARIO = os.path.join(ROOT, "scripts", "fleet_autoscale.json")
FIXTURE = os.path.join(ROOT, "tests", "fixtures", "autoscale",
                       "ledger.jsonl")


# ---------------------------------------------------------------------------
# policy grammar

def _policy_doc(**over):
    doc = {"min_hosts": 1, "max_hosts": 3,
           "up": {"step": 1, "cooldown_ticks": 4,
                  "signals": {"queue_wait_ema_s": 0.1}},
           "down": {"step": 1, "cooldown_ticks": 4, "stable_ticks": 2,
                    "signals": {"queue_wait_ema_s": 0.05}}}
    doc.update(over)
    return doc


def test_policy_validation_refuses_garbage():
    with pytest.raises(ValueError, match="missing required key"):
        AutoscalePolicy.from_doc({"max_hosts": 2})
    with pytest.raises(ValueError, match="unknown signal"):
        AutoscalePolicy.from_doc(_policy_doc(
            up={"signals": {"vibes": 1.0}}))
    with pytest.raises(ValueError, match="step must be >= 1"):
        AutoscalePolicy.from_doc(_policy_doc(
            up={"step": 0, "signals": {"queue_wait_ema_s": 0.1}}))
    with pytest.raises(ValueError, match="max_hosts must be >="):
        AutoscalePolicy.from_doc(_policy_doc(min_hosts=5))
    with pytest.raises(ValueError, match="ema_alpha"):
        AutoscalePolicy.from_doc(_policy_doc(ema_alpha=0.0))
    with pytest.raises(ValueError, match="at least one trip"):
        AutoscalePolicy.from_doc(_policy_doc(up={"signals": {}}))
    # down-side signals without hysteresis would flap: refused
    with pytest.raises(ValueError, match="hysteresis is required"):
        AutoscalePolicy.from_doc(_policy_doc(
            down={"signals": {"queue_wait_ema_s": 0.05}}))


def test_checked_in_policy_loads_and_roundtrips():
    pol = AutoscalePolicy.load(POLICY)
    assert pol.min_hosts == 2 and pol.max_hosts == 3
    assert pol.up.signals and pol.down.signals
    assert pol.down.stable_ticks >= 1
    # the hysteresis band is real: every signal configured on both sides
    # trips up strictly ABOVE where it reads calm (no dead-zone overlap)
    for name, calm in pol.down.signals.items():
        trip = pol.up.signals.get(name)
        if trip is not None:
            assert trip > calm, (name, trip, calm)
    assert AutoscalePolicy.from_doc(pol.to_doc()).to_doc() == pol.to_doc()


# ---------------------------------------------------------------------------
# signal folding

def test_monitor_folds_signals_from_ledger_records():
    pol = AutoscalePolicy.from_doc(_policy_doc(ema_alpha=0.25))
    mon = CapacityMonitor(pol, hosts_live=1)
    assert all(mon.signal_value(n) is None or n == "slo_breaches_window"
               for n in SIGNAL_NAMES)
    mon.observe({"event": "request", "queue_wait_s": 0.2})
    assert mon.signal_value("queue_wait_ema_s") == pytest.approx(0.2)
    mon.observe({"event": "request", "queue_wait_s": 0.0})
    assert mon.signal_value("queue_wait_ema_s") == pytest.approx(0.15)
    mon.observe({"event": "admit", "queue_depth": 8})
    assert mon.signal_value("queue_depth_ema") == pytest.approx(8.0)
    mon.observe({"event": "kv_cache", "pages_free": 3, "pages_used": 13})
    assert mon.signal_value("free_page_frac") == pytest.approx(3 / 16)
    mon.observe({"event": "goodput", "ratio": 0.4})
    assert mon.signal_value("goodput_ratio") == pytest.approx(0.4)
    mon.observe({"event": "fleet", "tick": 5, "goodput_ratio": 0.5})
    assert mon.signal_value("goodput_ratio") == pytest.approx(0.5)
    assert mon.tick == 5
    # the slo window slides with the replay clock
    mon.observe({"event": "slo", "kind": "queue_wait"})
    assert mon.signal_value("slo_breaches_window") == 1.0
    mon.observe({"event": "fleet", "tick": 5 + pol.window_ticks + 1})
    assert mon.signal_value("slo_breaches_window") == 0.0
    # a sustained step-time regression pushes the changepoint ratio > 1
    for wall in (0.1,) * 8 + (0.3,) * 8:
        mon.observe({"event": "step", "data_s": 0.0, "dispatch_s": 0.0,
                     "device_s": wall, "steps_in_dispatch": 1})
    assert mon.signal_value("step_time_ratio") > 1.0
    mon.observe({"event": "diagnosis", "bundle": "bundles/b0"})
    with pytest.raises(ValueError, match="unknown autoscale signal"):
        mon.signal_value("vibes")
    dec = mon.evaluate(tick=40, hosts_live=1)
    assert dec is not None and dec["bundle"] == "bundles/b0"


# ---------------------------------------------------------------------------
# policy evaluation: attribution order, cooldown, hysteresis

def test_scale_up_attributes_first_tripped_signal_in_canonical_order():
    pol = AutoscalePolicy.from_doc(_policy_doc(
        up={"step": 1, "cooldown_ticks": 10,
            "signals": {"queue_depth_ema": 5.0,
                        "slo_breaches_window": 1.0}}))
    mon = CapacityMonitor(pol, hosts_live=1)
    mon.observe({"event": "admit", "queue_depth": 9})   # trips depth
    mon.observe({"event": "slo", "kind": "x"})          # trips slo too
    dec = mon.evaluate(tick=3)
    # slo_breaches_window precedes queue_depth_ema in SIGNALS: it names
    # the decision even though both tripped
    assert dec["signal"] == "slo_breaches_window"
    assert (dec["decision"], dec["direction"]) == ("d0", "up")
    assert (dec["hosts_from"], dec["target_hosts"]) == (1, 2)
    assert dec["tick"] == 3 and dec["threshold"] == 1.0
    # cooldown blocks an immediate repeat; expiry re-arms it
    assert mon.evaluate(tick=4) is None
    dec2 = mon.evaluate(tick=13)
    assert (dec2["decision"], dec2["target_hosts"]) == ("d1", 3)
    # at max capacity pressure produces NO decision
    assert mon.evaluate(tick=30) is None
    assert [d["decision"] for d in mon.decisions] == ["d0", "d1"]


def test_scale_down_needs_sustained_calm_and_breach_free_window():
    pol = AutoscalePolicy.from_doc(_policy_doc(
        min_hosts=1, max_hosts=2,
        up={"step": 1, "cooldown_ticks": 0,
            "signals": {"queue_wait_ema_s": 0.1}},
        down={"step": 1, "cooldown_ticks": 0, "stable_ticks": 3,
              "signals": {"queue_wait_ema_s": 0.05}}))
    mon = CapacityMonitor(pol, hosts_live=2)
    mon.observe({"event": "request", "queue_wait_s": 0.2})
    # tripped at max: no up decision, and the calm streak must not accrue
    assert mon.evaluate(tick=0) is None
    # cool the EMA below the calm threshold
    for _ in range(12):
        mon.observe({"event": "request", "queue_wait_s": 0.0})
    assert mon.signal_value("queue_wait_ema_s") < 0.05
    assert mon.evaluate(tick=10) is None     # calm starts counting here
    assert mon.evaluate(tick=12) is None     # held 2 < stable_ticks 3
    dec = mon.evaluate(tick=13)              # held 3 >= 3: fire
    assert (dec["direction"], dec["signal"]) == ("down", CALM_SIGNAL)
    assert (dec["hosts_from"], dec["target_hosts"]) == (2, 1)
    assert dec["value"] == 3.0 and dec["threshold"] == 3.0
    # at min capacity a further down never fires
    for t in (14, 20, 30):
        assert mon.evaluate(tick=t) is None
    # an SLO breach inside the window resets the streak entirely
    mon2 = CapacityMonitor(pol, hosts_live=2)
    for _ in range(12):
        mon2.observe({"event": "request", "queue_wait_s": 0.0})
    assert mon2.evaluate(tick=10) is None
    mon2.observe({"event": "slo", "kind": "x"})
    assert mon2.evaluate(tick=13) is None    # breach in window: no down
    assert mon2.evaluate(tick=10 + pol.window_ticks + 3) is None  # restart
    assert mon2.evaluate(
        tick=10 + pol.window_ticks + 6)["direction"] == "down"


# ---------------------------------------------------------------------------
# replay determinism: the canned fixture, same pins as scripts/lint.sh

def test_replay_decisions_fixture_is_byte_deterministic():
    with open(FIXTURE) as f:
        records = [json.loads(line) for line in f]

    def replay():
        return replay_decisions(records, AutoscalePolicy.load(POLICY),
                                hosts0=2)

    d1, d2 = replay(), replay()
    assert json.dumps(d1) == json.dumps(d2)
    assert [(d["decision"], d["direction"], d["signal"]) for d in d1] == \
        [("d0", "up", "slo_breaches_window"), ("d1", "down", CALM_SIGNAL)]
    assert d1[0]["tick"] == 14 and d1[1]["tick"] == 64
    assert (d1[0]["hosts_from"], d1[0]["target_hosts"]) == (2, 3)
    assert (d1[1]["hosts_from"], d1[1]["target_hosts"]) == (3, 2)


# ---------------------------------------------------------------------------
# the events: schema round-trip, Prometheus series

def test_emit_decision_and_applied_roundtrip_the_ledger_schema(tmp_path):
    led = Ledger(str(tmp_path / "fleet.jsonl"))
    pol = AutoscalePolicy.from_doc(_policy_doc())
    mon = CapacityMonitor(pol, hosts_live=1)
    mon.observe({"event": "request", "queue_wait_s": 0.5})
    dec = mon.evaluate(tick=7)
    emit_decision(led, dec)
    led.emit("applied", decision=dec["decision"], action="expand",
             processes=2, epoch=1, plan_hash="abc123def456", devices=4)
    led.close()
    recs = read_ledger(str(tmp_path / "fleet.jsonl"))
    assert [r["event"] for r in recs] == ["scale_decision", "applied"]
    sd = recs[0]
    for k in ("decision", "direction", "hosts_from", "target_hosts",
              "signal", "value", "threshold", "window_ticks", "bundle"):
        assert sd[k] == dec[k], k
    assert sd["tick"] == 7                       # the extra rides along
    assert recs[1]["plan_hash"] == "abc123def456"
    # the schema refuses an unattributed decision
    led2 = Ledger(str(tmp_path / "bad.jsonl"))
    with pytest.raises(ValueError, match="missing required"):
        led2.emit("scale_decision", direction="up")


def test_autoscale_metrics_series():
    reg = MetricsRegistry()
    sink = metrics_ledger_sink(reg)
    text = reg.render()
    # pre-registered: a steady fleet still scrapes explicit zeros
    assert 'tpu_dist_autoscale_decisions_total{direction="up"} 0' in text
    assert 'tpu_dist_autoscale_decisions_total{direction="down"} 0' in text
    assert "tpu_dist_autoscale_target_hosts 0" in text
    sink({"event": "scale_decision", "decision": "d0", "direction": "up",
          "hosts_from": 2, "target_hosts": 3, "signal": "queue_wait_ema_s",
          "value": 0.2, "threshold": 0.1, "window_ticks": 16,
          "bundle": None})
    sink({"event": "scale_decision", "decision": "d1", "direction": "down",
          "hosts_from": 3, "target_hosts": 2, "signal": CALM_SIGNAL,
          "value": 24.0, "threshold": 24.0, "window_ticks": 16,
          "bundle": None})
    text = reg.render()
    assert 'tpu_dist_autoscale_decisions_total{direction="up"} 1' in text
    assert 'tpu_dist_autoscale_decisions_total{direction="down"} 1' in text
    assert "tpu_dist_autoscale_target_hosts 2" in text


# ---------------------------------------------------------------------------
# the tailer: incremental, torn-line-safe

def test_ledger_tailer_holds_back_torn_lines(tmp_path):
    path = str(tmp_path / "run.jsonl")
    tail = LedgerTailer()
    assert tail.poll([path]) == []               # missing file: no error
    with open(path, "w") as f:
        f.write(json.dumps({"event": "step", "step": 0}) + "\n")
        f.write("not json at all\n")
        f.write('{"event": "step", "st')         # torn mid-write
    recs = tail.poll([path])
    assert [r.get("step") for r in recs] == [0]  # corrupt skipped, torn held
    with open(path, "a") as f:
        f.write('ep": 1}\n')                     # the torn line completes
    assert [r.get("step") for r in tail.poll([path])] == [1]
    assert tail.poll([path]) == []               # nothing new


# ---------------------------------------------------------------------------
# trace_merge: decision + applied markers on the supervisor lane

def _emit_line(f, **rec):
    f.write(json.dumps(rec) + "\n")


def _attempt_ledger(path, t0):
    with open(path, "w") as f:
        _emit_line(f, event="run_start", ts=t0, pid=0, kind="fleet_sim",
                   config={}, mesh=None, devices=["cpu"], process_count=1,
                   attempt=0)
        _emit_line(f, event="step", ts=t0 + 1.0, pid=0, step=0, loss=None,
                   throughput=10.0, unit="tok/s", data_s=0.0,
                   dispatch_s=0.1, device_s=0.4, comm_s=None, mfu=None)
        _emit_line(f, event="run_end", ts=t0 + 2.0, pid=0, steps=1,
                   seconds=2.0, status="ok")


def test_trace_merge_renders_decision_markers(tmp_path):
    base = str(tmp_path / "run.jsonl")
    _attempt_ledger(base, 1000.0)
    with open(str(tmp_path / "run.sup.jsonl"), "w") as f:
        _emit_line(f, event="scale_decision", ts=1000.5, pid=0,
                   decision="d0", direction="up", hosts_from=2,
                   target_hosts=3, signal="queue_depth_ema", value=7.5,
                   threshold=6.0, window_ticks=16, bundle=None, tick=40)
        _emit_line(f, event="scale", ts=1001.0, pid=0, action="expand",
                   processes=3, epoch=1, world_from=2, decision="d0")
        _emit_line(f, event="applied", ts=1001.5, pid=0, decision="d0",
                   action="expand", processes=3, epoch=1,
                   plan_hash="abc123def456", devices=6)
    sys.path.insert(0, ROOT)
    from tools.trace_merge import main as tm_main

    out = str(tmp_path / "trace.json")
    assert tm_main([base, "-o", out]) == 0
    with open(out) as f:
        trace = json.load(f)
    # the existing scale pin is untouched; decisions count separately
    assert trace["otherData"]["scale_events"] == 1
    assert trace["otherData"]["autoscale_events"] == 2
    marks = {e["name"]: e for e in trace["traceEvents"]
             if e.get("ph") == "i"}
    assert "scale:expand" in marks
    assert marks["scale:expand"]["args"]["decision"] == "d0"
    assert marks["decision:up"]["args"]["signal"] == "queue_depth_ema"
    assert marks["decision:up"]["args"]["target_hosts"] == 3
    assert marks["applied:expand"]["args"]["plan_hash"] == "abc123def456"
    # wall order on the one supervisor lane: decision -> scale -> applied
    order = sorted(("decision:up", "scale:expand", "applied:expand"),
                   key=lambda n: marks[n]["ts"])
    assert list(order) == ["decision:up", "scale:expand", "applied:expand"]


# ---------------------------------------------------------------------------
# ledger_report: the decision section

def test_ledger_report_decisions_section():
    sys.path.insert(0, ROOT)
    from tools.ledger_report import decisions_section

    assert decisions_section([{"event": "step", "ts": 1.0}],
                             out=lambda s: None) is None
    records = [
        {"event": "run_start", "ts": 100.0},
        {"event": "scale_decision", "ts": 101.0, "decision": "d0",
         "direction": "up", "hosts_from": 2, "target_hosts": 3,
         "signal": "queue_wait_ema_s", "value": 0.2, "threshold": 0.1,
         "window_ticks": 16, "bundle": "bundles/b1"},
        {"event": "applied", "ts": 102.0, "decision": "d0",
         "action": "expand", "processes": 3, "epoch": 1,
         "plan_hash": "abc123def456"},
    ]
    lines = []
    rows = decisions_section(records, out=lines.append)
    assert len(rows) == 2
    assert rows[0]["decision"] == "d0" and rows[1]["plan_hash"] == \
        "abc123def456"
    text = "\n".join(lines)
    assert "1 decision(s), 1 applied" in text
    assert "d0: up 2 -> 3 host(s)" in text
    assert "bundle bundles/b1" in text
    assert "expand -> 3 process(es) epoch 1" in text


# ---------------------------------------------------------------------------
# the fleet stitcher's decision<->scale<->applied join (hand-built)

def test_fleet_ledger_autoscale_join(tmp_path):
    t0 = 1000.0
    h0 = os.path.join(str(tmp_path), "host0")
    os.makedirs(h0)
    _attempt_ledger(os.path.join(h0, "run.jsonl"), t0)
    with open(os.path.join(h0, "run.sup.jsonl"), "w") as f:
        # d0 paired with its expand + applied (with a plan hash)
        _emit_line(f, event="scale", ts=t0 + 6.0, pid=0, action="expand",
                   processes=3, epoch=1, world_from=2, decision="d0")
        _emit_line(f, event="applied", ts=t0 + 6.5, pid=0, decision="d0",
                   action="expand", processes=3, epoch=1,
                   plan_hash="abc123def456")
        # d1 paired but its retune failed (plan_hash None)
        _emit_line(f, event="scale", ts=t0 + 12.0, pid=0, action="shrink",
                   processes=2, epoch=2, world_from=3, decision="d1")
        _emit_line(f, event="applied", ts=t0 + 12.5, pid=0, decision="d1",
                   action="shrink", processes=2, epoch=2, plan_hash=None)
        # a drain is per-host mechanics: decision-less is FINE
        _emit_line(f, event="scale", ts=t0 + 11.0, pid=0, action="drain",
                   processes=1, epoch=2)
        # an unattributed capacity change is the audit failure
        _emit_line(f, event="scale", ts=t0 + 15.0, pid=0, action="expand",
                   processes=3, epoch=3, world_from=2)
    with open(os.path.join(str(tmp_path), "fleet.jsonl"), "w") as f:
        _emit_line(f, event="scenario", ts=t0, pid=0, name="hand", seed=1,
                   hosts=3, ticks=10, tick_s=0.02)
        _emit_line(f, event="scale_decision", ts=t0 + 5.0, pid=0,
                   decision="d0", direction="up", hosts_from=2,
                   target_hosts=3, signal="queue_depth_ema", value=7.0,
                   threshold=6.0, window_ticks=16, bundle=None, tick=40)
        _emit_line(f, event="scale_decision", ts=t0 + 11.5, pid=0,
                   decision="d1", direction="down", hosts_from=3,
                   target_hosts=2, signal=CALM_SIGNAL, value=24.0,
                   threshold=24.0, window_ticks=16, bundle=None, tick=170)
        _emit_line(f, event="fleet", ts=t0 + 1.0, pid=0, hosts_live=2,
                   goodput_ratio=None, slo_breaches=None, tick=0)
    fleet = FleetLedger.discover(str(tmp_path), warn=lambda m: None)
    auto = fleet.autoscale()
    assert auto is not None
    assert [r["decision"] for r in auto["decisions"]] == ["d0", "d1"]
    d0, d1 = auto["decisions"]
    assert d0["scale_events"] == 1 and d0["lag_s"] == pytest.approx(1.0)
    assert d0["applied"]["plan_hash"] == "abc123def456"
    assert d0["tick"] == 40 and d0["direction"] == "up"
    assert d1["applied"]["plan_hash"] is None
    assert auto["paired"] == 2
    assert auto["applied_with_plan_hash"] == 1
    # only the decision-less EXPAND counts — the drain never needs one
    assert auto["unattributed_scales"] == 1
    assert auto["shed_lost"] == 0
    report = fleet.report()
    assert report["autoscale"]["paired"] == 2
    # the hosts-live timeline carries the fleet tick for lag math
    assert report["hosts_live"][0]["tick"] == 0
    json.dumps(report)      # --json contract: serializable as-is
    # a decision-free fleet reports no autoscale section at all
    assert FleetLedger({0: []}, []).autoscale() is None


# ---------------------------------------------------------------------------
# the supervisor's applied follow-up: retune at the new world size

def test_supervisor_retune_stamps_applied_with_reproducible_hash(tmp_path):
    from tpu_dist.parallel.consensus import MeshView
    from tpu_dist.parallel.supervisor import Supervisor
    from tpu_dist.plan.tune import tune

    plan_dir = str(tmp_path / "plans")
    sup = Supervisor([sys.executable, "-c", "pass"],
                     ledger=str(tmp_path / "run.jsonl"),
                     retune={"device_kind": "TPU v5 lite",
                             "devices_per_host": 2, "plan_dir": plan_dir})
    view = MeshView(epoch=1, hosts=(0, 1, 2), planned=3)
    sup._maybe_retune(view, "expand", "d0")
    recs = read_ledger(str(tmp_path / "run.sup.jsonl"))
    assert [r["event"] for r in recs] == ["applied"]
    app = recs[0]
    assert app["decision"] == "d0" and app["action"] == "expand"
    assert app["processes"] == 3 and app["epoch"] == 1
    assert app["devices"] == 6
    assert app["plan_hash"]
    # the audit contract: a fresh tune at the same world size reproduces
    # the stamped hash byte-for-byte
    _, results = tune(device_kinds=["TPU v5 lite"],
                      workload={"devices": 6})
    assert results["TPU v5 lite"]["best"]["hash"] == app["plan_hash"]
    # and the plan file landed beside the run, named by epoch
    with open(os.path.join(plan_dir, "plan_epoch1.json")) as f:
        assert app["plan_hash"] in f.read()


# ---------------------------------------------------------------------------
# bench_track: the reaction-lag gate (lower is better, abstains pre-history)

def test_bench_track_gates_autoscale_lag(tmp_path):
    sys.path.insert(0, ROOT)
    from tools.bench_track import load_points, track

    def _headline(name, **fleet):
        doc = {"metric": "fleet_sim_goodput", "value": 0.3,
               "unit": "ratio",
               "fleet": {"goodput_ratio": 0.3, "hosts": 3, **fleet}}
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    # pre-autoscale history abstains: no field, no judgment
    pts = load_points([_headline("old.json"),
                       _headline("new.json", autoscale_lag_ticks=8)])
    m = track(pts, threshold_pct=5.0)["metrics"]["fleet_sim_goodput"]
    assert m["autoscale_lag_latest"] == 8
    assert m["autoscale_lag_best_prior"] is None
    assert not m["autoscale_lag_regressed"]
    # a real regression against the trailing best fails the gate
    pts = load_points([_headline("a.json", autoscale_lag_ticks=4),
                       _headline("b.json", autoscale_lag_ticks=8)])
    rep = track(pts, threshold_pct=5.0)
    assert rep["metrics"]["fleet_sim_goodput"]["autoscale_lag_regressed"]
    assert not rep["ok"]
    # a zero-lag best abstains (relative regression is undefined at 0)
    pts = load_points([_headline("z.json", autoscale_lag_ticks=0),
                       _headline("y.json", autoscale_lag_ticks=8)])
    m = track(pts, threshold_pct=5.0)["metrics"]["fleet_sim_goodput"]
    assert not m["autoscale_lag_regressed"]


# ---------------------------------------------------------------------------
# scenario grammar: the autoscale block

def test_scenario_autoscale_block_validation():
    def _doc(**auto):
        return {"name": "t", "seed": 3, "hosts": 3, "ticks": 40,
                "traffic": {"base_rate": 0.2}, "autoscale": auto}

    with pytest.raises(ValueError, match="needs a 'policy'"):
        parse_scenario(_doc(policy=""))
    with pytest.raises(ValueError, match="out of range"):
        parse_scenario(_doc(policy="p.json", standby_hosts=[7]))
    with pytest.raises(ValueError, match="cannot be standby"):
        parse_scenario(_doc(policy="p.json", standby_hosts=[0]))
    with pytest.raises(ValueError, match="duplicate"):
        parse_scenario(_doc(policy="p.json", standby_hosts=[2, 2]))
    sc = load_scenario(SCENARIO)
    assert sc.standby_hosts() == [2]
    assert sc.autoscale["policy"] == "scripts/autoscale_policy.json"
    # the burst that drives the acceptance scale-up is on the schedule
    assert any(ev["type"] == "burst" for ev in sc.events)


# ---------------------------------------------------------------------------
# ACCEPTANCE: the checked-in autoscale scenario end to end (CPU workers)

def test_fleet_autoscale_scenario_acceptance(tmp_path):
    """ISSUE 20 acceptance: 3 virtual hosts under
    ``scripts/fleet_autoscale.json`` — host 2 parked standby, a diurnal
    sinusoid with an overload burst at tick 40 — and every assertion read
    from ``tools/fleet_report.py --json``:

    * hosts-live FOLLOWS traffic: a scale-up decision within the pinned
      lag of the burst (capacity peaks at 3), then a scale-down after
      sustained calm (back to 2);
    * the audit pairing: every capacity change carries a decision id
      (``unattributed_scales == 0``) and every decision produced exactly
      one scale event (``paired == decisions``);
    * zero shed requests lost: drained hosts hand their queue to a
      survivor, which re-admits under ``readmit`` spans in the SAME
      trace;
    * the applied follow-up's plan hash equals a byte-deterministic
      fresh run of the tuner at the new world size;
    * goodput holds above the pinned floor despite two rescales.

    Decision TICKS are wall-timing dependent (workers run behind the
    schedule under compile pressure), so the pins are ranges, never
    exact tick equality — the exact-replay pins live in the lint gate's
    fixture, not here.
    """
    from tpu_dist.plan.tune import tune
    from tpu_dist.sim.runner import FleetSim

    out_dir = str(tmp_path / "fleet")
    sc = load_scenario(SCENARIO)
    burst0 = min(ev["tick"] for ev in sc.events if ev["type"] == "burst")
    report_inline = FleetSim(SCENARIO, out_dir).run()
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fleet_report.py"),
         out_dir, "--json"], capture_output=True, text=True, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)

    # -- the decision ledger: up under the burst, down after calm -------
    auto = report["autoscale"]
    assert auto is not None
    rows = auto["decisions"]
    ups = [r for r in rows if r["direction"] == "up"]
    downs = [r for r in rows if r["direction"] == "down"]
    assert ups and downs, rows
    assert rows[0]["direction"] == "up"
    # reaction lag: the first up decision lands within the pinned window
    # of burst onset (the burst lasts 24 ticks; 64 bounds compile skew)
    assert burst0 <= ups[0]["tick"] <= burst0 + 64, ups[0]
    assert (ups[0]["hosts_from"], ups[0]["target_hosts"]) == (2, 3)
    assert ups[0]["signal"] in SIGNAL_NAMES
    assert downs[0]["signal"] == CALM_SIGNAL
    assert (downs[0]["hosts_from"], downs[0]["target_hosts"]) == (3, 2)
    assert downs[0]["tick"] > ups[0]["tick"]

    # -- the audit pairing: no capacity change without a decision -------
    assert auto["paired"] == len(rows)
    assert auto["unattributed_scales"] == 0
    assert auto["applied_with_plan_hash"] == len(rows)
    for r in rows:
        assert r["scale_events"] == 1, r
        assert r["lag_s"] is not None and r["lag_s"] >= 0
        assert r["applied"]["decision"] == r["decision"]

    # -- the elasticity story mirrors the decisions, stamped -----------
    membership = [e for e in report["elasticity"]
                  if e["action"] in ("shrink", "expand")]
    assert [e["action"] for e in membership] == ["expand", "shrink"]
    assert membership[0]["decision"] == ups[0]["decision"]
    assert membership[0]["processes"] == 3
    assert membership[1]["decision"] == downs[0]["decision"]
    assert membership[1]["processes"] == 2
    # hosts-live follows: starts at 2 (standby parked), peaks at 3
    live = [s["hosts_live"] for s in report["hosts_live"]
            if s["hosts_live"] is not None]
    assert live[0] == 2 and max(live) == 3

    # -- zero shed requests lost: handoff + readmit close every trace --
    assert auto["shed_lost"] == 0
    traces = report["traces"]
    readmitted = [t for t in traces.values() if t["readmits"]]
    assert readmitted, "rescales never exercised the readmit path"
    for t in readmitted:
        assert t["completed"], t
    # queued-then-shed requests re-admit under the SAME trace: the shed
    # and readmit spans bind two attempts into one story
    shed_traces = [t for t in traces.values() if t["sheds"]]
    assert shed_traces, "the rescale drains left no queued work"
    for t in shed_traces:
        assert t["readmits"] > 0 and t["completed"], t
    # and the drained standby's undone arrivals really crossed hosts:
    # requests PLANNED for its rid band completed on a survivor
    drained = sc.standby_hosts()[0]
    crossed = [t for t in readmitted
               if t["rid"] // RID_STRIDE == drained]
    assert crossed, readmitted
    for t in crossed:
        assert drained not in t["hosts"], t

    # -- the applied plan hash reproduces under a fresh tune -----------
    worker_devices = sc.worker_devices
    for r in rows:
        app = r["applied"]
        _, results = tune(device_kinds=["TPU v5 lite"],
                          workload={"devices":
                                    app["processes"] * worker_devices})
        assert results["TPU v5 lite"]["best"]["hash"] == \
            app["plan_hash"], r
        plan_path = os.path.join(out_dir, "plans",
                                 f"plan_epoch{app['epoch']}.json")
        assert os.path.exists(plan_path), plan_path

    # -- goodput holds above the floor; the headline carries the loop --
    assert report["fleet"]["goodput_ratio"] >= 0.05
    assert report["slo_breaches"] <= 12
    with open(os.path.join(out_dir, "headline.json")) as f:
        headline = json.load(f)
    assert headline["fleet"]["autoscale_decisions"] == len(rows)
    lag = headline["fleet"]["autoscale_lag_ticks"]
    assert lag is not None and 0 <= lag <= 64
    assert report_inline["autoscale"]["paired"] == auto["paired"]
