"""Fused int8 Pallas matmul (ops.pallas_quant) vs the reference math.

The kernel's contract: identical numerics to ``ops.quant.quant_einsum``'s
dense path — same per-row activation scales, per-channel weight scales,
round/clip convention and int32 accumulation — with the whole
quantize/dot/dequant ladder fused into one kernel (no int8/int32 HBM
intermediates). Interpret mode keeps every test CPU-cheap; the dispatch
seam (``ops.quant.set_fused_quant``) is pinned so ``quant_matmul`` and the
engines ride the same switch the bench's BENCH_FUSED_QUANT knob flips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.ops.pallas_quant import fused_quant_matmul
from tpu_dist.ops.quant import (_dense_spec, fused_quant_active,
                                quant_einsum, quant_matmul, set_fused_quant)


def _ref(x, w):
    return quant_einsum(_dense_spec(x.ndim), x, w)


def _xw(xs, ws, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.normal(size=xs) * 2.0, dtype),
            jnp.asarray(rng.normal(size=ws), dtype))


@pytest.mark.parametrize("xs,ws", [
    ((8, 16), (16, 8)),          # single tile, sub-block
    ((130, 48), (48, 136)),      # both output dims pad to the block grid
    ((3, 5, 32), (32, 64)),      # leading batch dims fold like the models'
])
def test_fused_forward_matches_reference(xs, ws):
    x, w = _xw(xs, ws)
    got = fused_quant_matmul(x, w, interpret=True)
    want = _ref(x, w)
    assert got.shape == want.shape and got.dtype == want.dtype
    # same scales, same round/clip, int32 accumulation, fp32 dequant:
    # parity is bit-level up to fp32 summation order
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_fused_forward_bf16_io():
    """bf16 operands quantize from their fp32 upcast and the output rounds
    once at the store — exactly the reference path's dtype contract."""
    x, w = _xw((24, 32), (32, 48), seed=1, dtype=jnp.bfloat16)
    got = fused_quant_matmul(x, w, interpret=True)
    want = _ref(x, w)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_fused_backward_is_ste():
    """custom_vjp backward = vjp of the FP matmul on the unquantized
    operands (the quant_einsum STE contract): swapping the kernel in
    changes no training semantics."""
    x, w = _xw((10, 16), (16, 12), seed=2)

    def loss(fn):
        return lambda a, b: jnp.sum(fn(a, b) ** 2)

    gx, gw = jax.grad(loss(lambda a, b: fused_quant_matmul(
        a, b, interpret=True)), argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss(_ref), argnums=(0, 1))(x, w)
    # dot-vs-einsum vjp: fp32 summation order differs by a few ulp
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=1e-5, atol=1e-5)


def test_quant_matmul_dispatch_seam():
    """set_fused_quant routes quant_matmul(mode='int8') through the kernel
    (numerics unchanged), and the auto state keeps CPU runs on the cheap
    XLA path — the engines' `fused` step-record flag reads this switch."""
    x, w = _xw((9, 16), (16, 8), seed=3)
    try:
        set_fused_quant(False)
        assert not fused_quant_active()
        want = quant_matmul(x, w, "int8")
        set_fused_quant(True)
        assert fused_quant_active()
        got = quant_matmul(x, w, "int8")  # interpret auto-selected off-TPU
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)
    finally:
        set_fused_quant(None)
    assert fused_quant_active() == (jax.default_backend() == "tpu")


def test_fused_all_zero_rows_and_padding():
    """All-zero activation rows hit the EPS scale floor and produce exact
    zeros (also the padded-row story: the pad quantizes to q=0 and is
    sliced away, so ragged shapes cannot leak garbage)."""
    x = jnp.zeros((5, 16), jnp.float32).at[0].set(1.0)
    _, w = _xw((5, 16), (16, 8), seed=4)
    got = fused_quant_matmul(x, w, interpret=True)
    want = _ref(x, w)
    assert bool(jnp.all(got[1:] == 0.0))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
