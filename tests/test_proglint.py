"""proglint: the program-level auditor (PR 18 tentpole).

Injected-hazard coverage: every check trips on a program built to carry
its hazard and stays silent on the clean control — plus the audit-pass
modes (none/record/halt), the reason-required waiver grammar, the
ledger/metrics/report integration, and THE tier-1 pin: the tuner's whole
candidate space traces clean (0 unwaivered findings) byte-deterministically.
"""

import json
import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist._compat import shard_map
from tpu_dist.analysis.proglint import (AuditError, Finding,
                                        RecompileSentry, apply_waivers,
                                        audit_jaxpr, audit_tune_space,
                                        collective_signature,
                                        donation_aliased,
                                        mesh_axis_authority, parse_waivers,
                                        to_sarif, unwaivered)
from tpu_dist.plan import compile as plan_compile


@pytest.fixture(autouse=True)
def _audit_off():
    """Every test leaves the process-global audit switch disarmed."""
    yield
    plan_compile.set_audit("none")


def _mesh(axis: str, n: int = 8) -> Mesh:
    return Mesh(np.array(jax.devices()[:n]), (axis,))


def _psum_program(axis: str):
    """A shard_map'd all-reduce over ``axis`` — the mesh may well declare
    the axis (shard_map requires it); the AUTHORITY may not (PL001)."""
    def step(x):
        return jax.lax.psum(x, axis)
    return shard_map(step, mesh=_mesh(axis), in_specs=P(axis),
                     out_specs=P())


class _Led:
    """Ledger stub: records emits, keeps the test free of file I/O."""

    def __init__(self):
        self.events = []

    def emit(self, event, **fields):
        self.events.append({"event": event, **fields})

    def audits(self):
        return [e for e in self.events if e["event"] == "audit"]


# ------------------------------------------------------- waiver grammar
def test_waiver_grammar_parses_reasons_and_flags_debt():
    waivers, meta = parse_waivers(
        "# comment line\n"
        "\n"
        "PL003 serve_* -- bucket shardings differ by design\n"
        "PL001 train_step   # no reason at all\n"
        "PL004 -- reason but no program glob\n"
        "DL003 x -- wrong namespace\n", origin="w.txt")
    assert len(waivers) == 1
    w = waivers[0]
    assert (w.check, w.pattern) == ("PL003", "serve_*")
    assert w.reason == "bucket shardings differ by design"
    # every malformed line is a PL000 finding, never silently honored
    assert [m.check for m in meta] == ["PL000"] * 3
    assert any("no reason" in m.message for m in meta)
    assert any("unparseable" in m.message for m in meta)


def test_apply_waivers_glob_match_and_unwaivered_filter():
    waivers, _ = parse_waivers("PL001 serve_* -- draft axis is synthetic\n")
    fs = [Finding("PL001", "serve_tick", "x"),
          Finding("PL001", "train_step", "x"),
          Finding("PL002", "serve_tick", "x")]   # other check: no match
    out = apply_waivers(fs, waivers)
    assert [f.waived for f in out] == [True, False, False]
    assert out[0].reason == "draft axis is synthetic"
    assert [f.program for f in unwaivered(out)] == ["train_step",
                                                    "serve_tick"]
    assert "[waived:" in out[0].render()


# ------------------------------------------- the jaxpr/HLO checks trip
def test_pl001_unknown_collective_axis_trips_and_control_is_clean():
    x = jnp.arange(8.0)
    bad = jax.make_jaxpr(_psum_program("batch"))(x)   # torch habit axis
    fs = audit_jaxpr("p", bad)
    assert [f.check for f in fs] == ["PL001"]
    assert "'batch'" in fs[0].message
    assert "batch" not in mesh_axis_authority()
    good = jax.make_jaxpr(_psum_program("data"))(x)
    assert audit_jaxpr("p", good) == []


def test_pl001_learns_sp_serving_axis():
    """Satellite of PR 19: the 'sp' serving-sequence-parallel axis joined
    parallel/mesh.py, and the reflection authority picked it up with zero
    proglint changes — the sharded-pool gather's psum over 'sp' audits
    clean while a typo'd spelling still trips."""
    assert "sp" in mesh_axis_authority()
    x = jnp.arange(8.0)
    good = jax.make_jaxpr(_psum_program("sp"))(x)
    assert audit_jaxpr("sp_gather", good) == []
    bad = jax.make_jaxpr(_psum_program("spd"))(x)
    fs = audit_jaxpr("sp_gather", bad)
    assert [f.check for f in fs] == ["PL001"]
    assert "'spd'" in fs[0].message


def test_pl002_asymmetric_cond_psum_order_trips_proglint_and_dl201(
        tmp_path):
    """THE acceptance hazard: a cond whose arms issue psum/pmax in
    opposite order is flagged by BOTH halves — PL002 on the traced jaxpr
    and DL201 on the equivalent source."""
    def step(x):
        def hot(v):
            return jax.lax.pmax(jax.lax.psum(v, "data"), "data")

        def cold(v):
            return jax.lax.psum(jax.lax.pmax(v, "data"), "data")
        return jax.lax.cond(  # distlint: disable=DL201 -- test: the injected hazard under test
            x[0] > 0, hot, cold, x)

    f = shard_map(step, mesh=_mesh("data"), in_specs=P("data"),
                  out_specs=P(), check_vma=False)
    closed = jax.make_jaxpr(f)(jnp.arange(8.0))
    fs = audit_jaxpr("p", closed)
    assert [x.check for x in fs] == ["PL002"]
    assert "divergent collective sequences" in fs[0].message
    # the source twin through distlint's DL201 prover
    from tools.distlint import lint_files
    p = tmp_path / "twin.py"
    p.write_text(
        "import jax\n"
        "def step(pred, x):\n"
        "    def hot(v):\n"
        "        v = jax.lax.psum(v, 'data')\n"
        "        return jax.lax.pmax(v, 'data')\n"
        "    def cold(v):\n"
        "        v = jax.lax.pmax(v, 'data')\n"
        "        return jax.lax.psum(v, 'data')\n"
        "    return jax.lax.cond(pred, hot, cold, x)\n")
    res = lint_files([str(p)], select=["DL201"])
    assert len(res.findings) == 1, [x.render() for x in res.findings]
    assert res.findings[0].rule == "DL201"


def test_pl002_symmetric_cond_and_while_are_exempt():
    def step(x):
        body = lambda v: jax.lax.psum(v, "data")          # noqa: E731
        y = jax.lax.cond(x[0] > 0, body, body, x)
        # while: ONE body, same trip count on every device — exempt
        return jax.lax.while_loop(lambda c: c[1] < 3,
                                  lambda c: (jax.lax.psum(c[0], "data"),
                                             c[1] + 1), (y, 0))[0]

    f = shard_map(step, mesh=_mesh("data"), in_specs=P("data"),
                  out_specs=P(), check_vma=False)
    assert audit_jaxpr("p", jax.make_jaxpr(f)(jnp.arange(8.0))) == []


def test_pl003_sharding_mismatch_drops_donation_and_is_flagged():
    """The silent HBM doubler: XLA drops donate_argnums on a sharding
    mismatch with only a warning; the compiled module's header is the
    proof (input_output_alias present iff honored)."""
    mesh = _mesh("data")
    sh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    x = jnp.arange(8.0)

    def f(v):
        return v * 2.0

    honored = jax.jit(f, donate_argnums=(0,), in_shardings=sh,
                      out_shardings=sh)
    dropped = jax.jit(f, donate_argnums=(0,), in_shardings=sh,
                      out_shardings=rep)   # replicated out: cannot alias
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")    # "donated buffers not usable"
        hlo_ok = honored.lower(x).compile().as_text()
        hlo_bad = dropped.lower(x).compile().as_text()
    assert donation_aliased(hlo_ok) and not donation_aliased(hlo_bad)
    assert audit_jaxpr("p", jax.make_jaxpr(honored)(x), hlo=hlo_ok) == []
    fs = audit_jaxpr("p", jax.make_jaxpr(dropped)(x), hlo=hlo_bad)
    assert [f_.check for f_ in fs] == ["PL003"]
    assert "double-buffered" in fs[0].message
    # no donation declared: silence regardless of the header
    plain = jax.jit(f, in_shardings=sh, out_shardings=rep)
    assert audit_jaxpr("p", jax.make_jaxpr(plain)(x), hlo=hlo_bad) == []


def test_pl004_f32_leak_in_bf16_program_and_exemptions():
    a32 = jnp.ones((4, 4), jnp.float32)
    a16 = jnp.ones((4, 4), jnp.bfloat16)

    def mm(a, b):
        return a @ b

    leak = jax.make_jaxpr(mm)(a32, a32)
    fs = audit_jaxpr("p", leak, precision="bf16")
    assert [f.check for f in fs] == ["PL004"]
    assert "dot_general" in fs[0].message and "f32" in fs[0].message
    # fp32 program: f32 compute is the declared contract
    assert audit_jaxpr("p", leak, precision="fp32") == []
    # bf16_params (master-weights style) KEEPS f32 compute on purpose
    assert audit_jaxpr("p", leak, precision="bf16_params") == []
    # actual bf16 compute in a bf16 program: clean
    assert audit_jaxpr("p", jax.make_jaxpr(mm)(a16, a16),
                       precision="bf16") == []


def test_pl005_sentry_latches_one_finding_per_program():
    sentry = RecompileSentry()
    f = jax.jit(lambda x: x * 2.0)
    sentry.register("vary", f, allowed=1)
    f(jnp.ones(2))
    assert sentry.check() == []            # one shape: within budget
    f(jnp.ones(3))
    f(jnp.ones(4))
    fs = sentry.check()
    assert [x.check for x in fs] == ["PL005"]
    assert "3 entries" in fs[0].message
    assert sentry.check() == []            # latched: exactly one finding
    # allowed>1 (serve prefill's bucket specialization) tolerates buckets
    sentry2 = RecompileSentry()
    g = jax.jit(lambda x: x + 1.0)
    sentry2.register("prefill", g, allowed=3)
    for n in (2, 3, 4):
        g(jnp.ones(n))
    assert sentry2.check() == []


# ------------------------------------------------ the audit pass (knob)
def test_set_audit_rejects_unknown_mode():
    with pytest.raises(ValueError):
        plan_compile.set_audit("loud")


def test_audit_program_record_emits_one_event_halt_raises():
    led = _Led()
    plan_compile.set_audit("record", led)
    x = jnp.arange(8.0)
    fs = plan_compile.audit_program("bad_step", _psum_program("batch"), x)
    assert [f.check for f in fs] == ["PL001"]
    (ev,) = led.audits()                   # exactly one event per program
    assert ev["program"] == "bad_step" and ev["mode"] == "record"
    assert ev["findings"] == 1 and ev["waived"] == 0
    assert ev["detail"][0]["check"] == "PL001"
    # clean program: still exactly one event, zero findings
    plan_compile.audit_program("good_step", _psum_program("data"), x)
    assert [e["findings"] for e in led.audits()] == [1, 0]
    # halt: same checks, but unwaivered findings are fatal
    plan_compile.set_audit("halt", led)
    with pytest.raises(AuditError, match="PL001"):
        plan_compile.audit_program("bad_step", _psum_program("batch"), x)
    # none: the pass is a no-op and emits nothing
    plan_compile.set_audit("none", led)
    n = len(led.events)
    assert plan_compile.audit_program("bad_step",
                                      _psum_program("batch"), x) == []
    assert len(led.events) == n


def test_check_audit_sentry_record_once_then_halt_raises():
    led = _Led()
    plan_compile.set_audit("record", led)
    f = jax.jit(lambda x: x + 1.0)
    plan_compile.register_audit_program("vary", f)
    f(jnp.ones(2))
    f(jnp.ones(3))
    plan_compile.check_audit_sentry()
    plan_compile.check_audit_sentry()      # latched: no second event
    (ev,) = led.audits()
    assert ev["program"] == "vary" and ev["findings"] == 1
    assert ev["detail"][0]["check"] == "PL005"
    # halt arms a FRESH sentry; the same shape-varying dispatch is fatal
    plan_compile.set_audit("halt", led)
    g = jax.jit(lambda x: x * 3.0)
    plan_compile.register_audit_program("vary2", g)
    g(jnp.ones(2))
    g(jnp.ones(3))
    with pytest.raises(AuditError, match="PL005"):
        plan_compile.check_audit_sentry()


# ------------------------------------- ledger / metrics / report wiring
def test_audit_events_feed_metrics_and_report_sections():
    from tpu_dist.obs.metrics import MetricsRegistry, metrics_ledger_sink
    reg = MetricsRegistry()
    sink = metrics_ledger_sink(reg)
    # pre-registered: a clean run still scrapes zeros for every check
    assert 'tpu_dist_audit_findings_total{check="PL003"} 0' in reg.render()
    records = [
        {"event": "audit", "program": "train_step", "mode": "record",
         "findings": 1, "waived": 1, "detail": [
             {"check": "PL003", "program": "train_step", "message": "m",
              "waived": False, "reason": ""},
             {"check": "PL001", "program": "train_step", "message": "m",
              "waived": True, "reason": "r"}]},
        {"event": "audit", "program": "serve_tick", "mode": "record",
         "findings": 0, "waived": 0, "detail": None},
    ]
    for r in records:
        sink(r)
    text = reg.render()
    assert 'tpu_dist_audit_findings_total{check="PL003"} 1' in text
    # waived detail does NOT count
    assert 'tpu_dist_audit_findings_total{check="PL001"} 0' in text
    from tools.ledger_report import audit_section
    lines = []
    sec = audit_section(records, out=lines.append)
    assert sec["mode"] == "record" and len(sec["programs"]) == 2
    assert sec["findings"] == 1 and sec["waived"] == 1
    assert sec["programs"]["train_step"]["checks"] == ["PL001", "PL003"]
    assert any("train_step" in ln and "PL003" in ln for ln in lines)
    # no audit events: the section stays out of the summary entirely
    assert audit_section([{"event": "step"}], out=lines.append) is None


def test_proglint_sarif_document_shape():
    fs = [Finding("PL003", "train_step", "dropped"),
          Finding("PL001", "serve_tick", "bad axis", waived=True,
                  reason="synthetic axis")]
    doc = to_sarif(fs)
    assert doc["version"] == "2.1.0"
    (run,) = doc["runs"]
    assert run["tool"]["driver"]["name"] == "proglint"
    ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert ids == sorted(ids) and "PL000" in ids and "PL005" in ids
    r_bad, r_waived = run["results"]
    assert r_bad["level"] == "error"
    assert r_waived["level"] == "note"
    assert "[waived: synthetic axis]" in r_waived["message"]["text"]
    uri = r_bad["locations"][0]["physicalLocation"]["artifactLocation"]
    assert uri["uri"] == "programs/train_step"


# --------------------------------------------- THE tier-1 pins (accept)
def test_tune_space_audits_clean_and_byte_deterministic():
    """Satellite 1's pin, the proglint twin of test_tree_is_clean: every
    structurally-distinct program in the tuner's full candidate space
    traces clean — 0 unwaivered findings — and the canonical report is
    byte-identical across runs (CI artifact diffing depends on it)."""
    r1 = audit_tune_space()
    assert r1["unwaivered"] == 0, r1["findings"]
    assert r1["plans"] == 72 and r1["programs"] == 8
    assert len(r1["program_names"]) == r1["programs"]
    r2 = audit_tune_space()
    assert (json.dumps(r1, sort_keys=True)
            == json.dumps(r2, sort_keys=True))


def test_lm_smoke_audit_record_exactly_one_event_per_program(tmp_path):
    """Acceptance: audit=record on the CPU LM smoke emits exactly one
    clean audit event per program (compile-time pass + drain-boundary
    counter read — the hot path never sees the auditor)."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    path = str(tmp_path / "lm.jsonl")
    cfg = LMConfig(epochs=1, batch_size=8, seq_len=32, vocab_size=64,
                   num_layers=1, d_model=32, num_heads=2,
                   synth_tokens=2048, print_freq=4, seed=0,
                   audit="record", ledger_path=path)
    LMTrainer(cfg).fit()
    records = [json.loads(ln) for ln in open(path)]
    audits = [r for r in records if r["event"] == "audit"]
    assert len(audits) == 1, audits         # one program: train_step
    (ev,) = audits
    assert ev["program"] == "train_step" and ev["mode"] == "record"
    assert ev["findings"] == 0              # the shipped program is clean
    # the fixed-shape step never trips the sentry: no PL005 events
    assert all((r.get("detail") or [{}])[0].get("check") != "PL005"
               for r in audits)
