"""Chunked vocab cross-entropy (ops.fused_xent) == the full-logits loss.

The chunked path exists so the (B, L, V) fp32 logits never materialize; these
tests pin that it is the SAME objective — value, metrics, and gradients wrt
features and head weight — including ragged row counts that need padding, the
bf16 compute path, and the end-to-end LMTrainer flag in jit and sp modes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.engine.lm_steps import lm_loss_and_metrics
from tpu_dist.ops.fused_xent import chunked_softmax_xent


def _case(b=2, l=24, d=16, v=97, seed=0, mask_frac=0.3):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(b, l, d), jnp.float32)
    w = jnp.asarray(rng.randn(d, v) / np.sqrt(d), jnp.float32)
    t = jnp.asarray(rng.randint(0, v, (b, l)), jnp.int32)
    m = jnp.asarray(rng.rand(b, l) > mask_frac, jnp.float32)
    return x, w, t, m


def _full(x, w, t, m):
    logits = (x @ w).astype(jnp.float32)
    return lm_loss_and_metrics(logits, t, m)


@pytest.mark.parametrize("chunk", [1, 7, 16, 48, 4096])
def test_forward_matches_full(chunk):
    """Loss sum and correct1 equal the full-logits reference for chunk sizes
    that divide, straddle, and exceed the row count (B*L=48)."""
    x, w, t, m = _case()
    loss, correct = chunked_softmax_xent(x, w, t, m, chunk)
    loss_ref, metrics_ref = _full(x, w, t, m)
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    np.testing.assert_allclose(float(correct), float(metrics_ref["correct1"]),
                               rtol=0)


def test_gradients_match_full():
    """d(mean loss)/dx and /dw equal the full-logits path to fp32 tolerance —
    the custom_vjp recompute is the same math, not an approximation."""
    x, w, t, m = _case(seed=1)
    count = jnp.sum(m)

    def loss_chunked(x, w):
        loss, _ = chunked_softmax_xent(x, w, t, m, 13)
        return loss / count

    def loss_full(x, w):
        loss, _ = _full(x, w, t, m)
        return loss / count

    gx_c, gw_c = jax.grad(loss_chunked, argnums=(0, 1))(x, w)
    gx_f, gw_f = jax.grad(loss_full, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_c), np.asarray(gx_f),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw_c), np.asarray(gw_f),
                               rtol=1e-4, atol=1e-6)


def test_masked_rows_contribute_nothing():
    """A fully-masked row changes neither the loss nor any gradient — padding
    rows (sampler wrap, chunk pad) are inert."""
    x, w, t, m = _case(seed=2, mask_frac=0.0)
    m = m.at[1, :].set(0.0)
    x_wild = x.at[1].set(1e3)  # garbage in the masked row

    def loss(x):
        return chunked_softmax_xent(x, w, t, m, 16)[0]

    np.testing.assert_allclose(float(loss(x)), float(loss(x_wild)), rtol=1e-6)
    g = jax.grad(loss)(x_wild)
    assert float(jnp.max(jnp.abs(g[1]))) == 0.0


def test_bf16_compute_close_to_fp32():
    """The bf16 head matmul (fp32 accumulation) stays within bf16 rounding of
    the fp32 loss — the policy the LM bf16 precision mode uses."""
    x, w, t, m = _case(seed=3)
    loss16, _ = chunked_softmax_xent(x, w, t, m, 16, jnp.bfloat16)
    loss32, _ = _full(x, w, t, m)
    np.testing.assert_allclose(float(loss16), float(loss32), rtol=2e-2)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_lm_trainer_loss_chunk_matches(tmp_path):
    """--loss-chunk N trains to the SAME parameters as the full-logits path
    (fp32, same seed) in the jit mode, and sp with loss_chunk agrees with
    dp to the usual cross-mode tolerance."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    tiny = dict(batch_size=8, seq_len=32, d_model=32, num_layers=2,
                num_heads=2, vocab_size=64, synth_tokens=3000, seed=3,
                print_freq=100, epochs=1, lr=1e-2, data_placement="host")

    def vec(tr):
        return np.concatenate([np.asarray(x, np.float32).ravel()
                               for x in jax.tree_util.tree_leaves(
                                   jax.device_get(tr.state.params))])

    tr_full = LMTrainer(LMConfig(**tiny)); tr_full.fit()
    tr_chunk = LMTrainer(LMConfig(loss_chunk=40, **tiny)); tr_chunk.fit()
    np.testing.assert_allclose(vec(tr_chunk), vec(tr_full),
                               rtol=1e-4, atol=1e-5)

    sp = LMTrainer(LMConfig(mesh_shape=(2, 4), mesh_axes=("data", "seq"),
                            loss_chunk=16, **tiny))
    sp.fit()
    np.testing.assert_allclose(vec(sp), vec(tr_full), rtol=2e-3, atol=1e-4)


def test_lm_trainer_loss_chunk_eval_exact(tmp_path):
    """Chunked eval reports the same perplexity metrics as the full path."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    tiny = dict(batch_size=8, seq_len=32, d_model=32, num_layers=2,
                num_heads=2, vocab_size=64, synth_tokens=3000, seed=3,
                print_freq=100, epochs=1, lr=1e-2, data_placement="host",
                evaluate=True)
    loss_f, ppl_f, acc_f = LMTrainer(LMConfig(**tiny)).validate()
    loss_c, ppl_c, acc_c = LMTrainer(LMConfig(loss_chunk=24, **tiny)).validate()
    np.testing.assert_allclose(loss_c, loss_f, rtol=1e-5)
    np.testing.assert_allclose(acc_c, acc_f, rtol=1e-6)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_loss_chunk_under_tensor_parallel_matches_dp():
    """The chunked CE under Megatron TP: the head kernel arrives 'model'-
    sharded and GSPMD partitions the chunked scan's matmul + logsumexp —
    one tp+chunk step equals the dp full-logits step per-leaf."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dist.engine.lm_steps import (make_lm_batches,
                                          make_lm_train_step)
    from tpu_dist.engine.state import TrainState
    from tpu_dist.models.transformer import tiny_lm
    from tpu_dist.ops import make_optimizer
    from tpu_dist.parallel.mesh import make_mesh, replicated
    from tpu_dist.parallel.tp import shard_lm_params

    V, L, B = 64, 32, 8
    rng_np = np.random.RandomState(1)
    tokens = rng_np.randint(0, V, (B, L + 1)).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    model = tiny_lm(vocab_size=V, max_len=L)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.01, 0.9, 0.0, steps_per_epoch=100)
    key = jax.random.PRNGKey(1)

    mesh_dp = make_mesh((8,), ("data",))
    st = jax.device_put(TrainState.create(params, {}, tx),
                        replicated(mesh_dp))
    dp_step = make_lm_train_step(model, tx, mesh_dp, donate=False)
    sh = NamedSharding(mesh_dp, P("data"))
    st_dp, _ = dp_step(st, jax.device_put(inputs, sh),
                       jax.device_put(targets, sh), key)

    mesh_tp = make_mesh((4, 2), ("data", "model"))
    st2 = TrainState.create(params, {}, tx)
    st2 = TrainState(
        step=jax.device_put(st2.step, NamedSharding(mesh_tp, P())),
        params=shard_lm_params(mesh_tp, st2.params), batch_stats={},
        opt_state=jax.device_put(st2.opt_state,
                                 NamedSharding(mesh_tp, P())),
        loss_scale=None)
    tp_step = make_lm_train_step(model, tx, mesh_tp, donate=False,
                                 loss_chunk=16)
    sh_tp = NamedSharding(mesh_tp, P("data"))
    st_tp, _ = tp_step(st2, jax.device_put(inputs, sh_tp),
                       jax.device_put(targets, sh_tp), key)

    flat_dp = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
               jax.tree_util.tree_flatten_with_path(
                   jax.device_get(st_dp.params))[0]}
    flat_tp = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
               jax.tree_util.tree_flatten_with_path(
                   jax.device_get(st_tp.params))[0]}
    for k in flat_dp:
        np.testing.assert_allclose(flat_tp[k], flat_dp[k],
                                   rtol=2e-4, atol=1e-5, err_msg=k)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_lm_trainer_pp_loss_chunk_matches(tmp_path):
    """--loss-chunk in the gpipe pipeline (the last-stage chunked head,
    round 4) trains to the same parameters as the pp full-logits path."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    def vec(tr):
        return np.concatenate([np.asarray(x, np.float32).ravel()
                               for x in jax.tree_util.tree_leaves(
                                   jax.device_get(tr.state.params))])

    tiny = dict(mesh_shape=(2, 4), mesh_axes=("data", "stage"),
                pp_microbatches=2, batch_size=8, seq_len=32, d_model=32,
                num_layers=4, num_heads=2, vocab_size=64, synth_tokens=3000,
                seed=3, print_freq=100, epochs=1, lr=1e-2,
                data_placement="host")
    tr_full = LMTrainer(LMConfig(**tiny)); tr_full.fit()
    tr_chunk = LMTrainer(LMConfig(loss_chunk=40, **tiny)); tr_chunk.fit()
    np.testing.assert_allclose(vec(tr_chunk), vec(tr_full),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_loss_chunk_under_fsdp_matches_dp():
    """Chunked CE under ZeRO-3 (fsdp) placement: the head kernel arrives
    parameter-sharded over 'data' and GSPMD gathers it per chunk — one
    fsdp+chunk step equals the replicated dp full-logits step per-leaf."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dist.engine.lm_steps import (make_lm_batches,
                                          make_lm_train_step)
    from tpu_dist.engine.state import TrainState
    from tpu_dist.models.transformer import tiny_lm
    from tpu_dist.ops import make_optimizer
    from tpu_dist.parallel.fsdp import shard_state_fsdp
    from tpu_dist.parallel.mesh import make_mesh, replicated

    V, L, B = 64, 32, 8
    rng_np = np.random.RandomState(2)
    tokens = rng_np.randint(0, V, (B, L + 1)).astype(np.int32)
    inputs, targets = make_lm_batches(tokens)
    model = tiny_lm(vocab_size=V, max_len=L)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, L), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.01, 0.9, 0.0, steps_per_epoch=100)
    key = jax.random.PRNGKey(1)
    mesh = make_mesh((8,), ("data",))
    sh = NamedSharding(mesh, P("data"))

    st = jax.device_put(TrainState.create(params, {}, tx), replicated(mesh))
    dp_step = make_lm_train_step(model, tx, mesh, donate=False)
    st_dp, _ = dp_step(st, jax.device_put(inputs, sh),
                       jax.device_put(targets, sh), key)

    st_f = shard_state_fsdp(mesh, TrainState.create(params, {}, tx),
                            min_size=256)
    f_step = make_lm_train_step(model, tx, mesh, donate=False,
                                loss_chunk=16)
    st_fs, _ = f_step(st_f, jax.device_put(inputs, sh),
                      jax.device_put(targets, sh), key)

    flat_dp = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
               jax.tree_util.tree_flatten_with_path(
                   jax.device_get(st_dp.params))[0]}
    flat_f = {jax.tree_util.keystr(p): np.asarray(v) for p, v in
              jax.tree_util.tree_flatten_with_path(
                  jax.device_get(st_fs.params))[0]}
    for k in flat_dp:
        np.testing.assert_allclose(flat_f[k], flat_dp[k],
                                   rtol=2e-4, atol=1e-5, err_msg=k)
