"""Native batch-gather library (csrc/gather.cpp) vs numpy fallback."""

import numpy as np

from tpu_dist import _native


def test_native_builds_and_loads():
    # g++ is part of the supported toolchain; the build must succeed here
    assert _native.available()


def test_gather_matches_numpy():
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (100, 8, 8, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, (100,)).astype(np.int32)
    idx = rng.integers(0, 100, (32,))
    gi, gl = _native.gather_batch(images, labels, idx)
    np.testing.assert_array_equal(gi, images[idx])
    np.testing.assert_array_equal(gl, labels[idx])


def test_gather_noncontiguous_falls_back():
    images = np.zeros((10, 4, 4, 3), np.uint8)[:, ::2]  # non-contiguous
    labels = np.arange(10, dtype=np.int32)
    idx = np.array([1, 3])
    gi, gl = _native.gather_batch(images, labels, idx)
    np.testing.assert_array_equal(gl, labels[idx])
    assert gi.shape == (2, 2, 4, 3)
