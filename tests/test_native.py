"""Native batch-gather library (csrc/gather.cpp) vs numpy fallback."""

import numpy as np
import pytest

from tpu_dist import _native


def test_native_builds_and_loads():
    # g++ is part of the supported toolchain; the build must succeed here
    assert _native.available()


def test_gather_matches_numpy():
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (100, 8, 8, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, (100,)).astype(np.int32)
    idx = rng.integers(0, 100, (32,))
    gi, gl = _native.gather_batch(images, labels, idx)
    np.testing.assert_array_equal(gi, images[idx])
    np.testing.assert_array_equal(gl, labels[idx])


def test_gather_noncontiguous_falls_back():
    images = np.zeros((10, 4, 4, 3), np.uint8)[:, ::2]  # non-contiguous
    labels = np.arange(10, dtype=np.int32)
    idx = np.array([1, 3])
    gi, gl = _native.gather_batch(images, labels, idx)
    np.testing.assert_array_equal(gl, labels[idx])
    assert gi.shape == (2, 2, 4, 3)


def _jpeg_bytes(h, w, smooth=True, quality=95):
    import io
    from PIL import Image
    if smooth:
        yy, xx = np.mgrid[0:h, 0:w]
        arr = np.stack([(xx * 255 // max(w, 1)), (yy * 255 // max(h, 1)),
                        ((xx + yy) * 255 // (h + w))], -1).astype(np.uint8)
    else:
        arr = np.random.default_rng(0).integers(0, 255, (h, w, 3), np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, "JPEG", quality=quality)
    return buf.getvalue(), arr


def test_decode_jpeg_matches_pil_framing():
    """Native decode (csrc/decode.cpp) frames identically to the PIL path
    (short side -> size*256//224, center crop) and agrees within ~1 gray
    level on a smooth image (resampling kernels differ by design)."""
    pytest.importorskip("PIL")
    if not _native.decode_available():
        pytest.skip("built without libjpeg")
    from PIL import Image
    import io
    data, _ = _jpeg_bytes(375, 500)
    out = _native.decode_jpeg(data, 224)
    assert out is not None and out.shape == (224, 224, 3)

    im = Image.open(io.BytesIO(data)).convert("RGB")
    w, h = im.size
    scale = 256 / min(w, h)
    im = im.resize((max(1, round(w * scale)), max(1, round(h * scale))))
    ref = np.asarray(im, np.uint8)
    top = (ref.shape[0] - 224) // 2
    left = (ref.shape[1] - 224) // 2
    ref = ref[top:top + 224, left:left + 224]
    diff = np.abs(out.astype(int) - ref.astype(int))
    assert diff.mean() < 1.0 and np.percentile(diff, 99) <= 3


def test_decode_jpeg_dct_scaled_large_source():
    """A source >2x the target exercises the DCT-scaling branch; output is
    still framed and smooth-close to the PIL reference."""
    pytest.importorskip("PIL")
    if not _native.decode_available():
        pytest.skip("built without libjpeg")
    data, _ = _jpeg_bytes(1200, 1600)
    out = _native.decode_jpeg(data, 224)
    assert out is not None and out.shape == (224, 224, 3)
    assert int(out.max()) > 100  # pixels actually landed


def test_decode_jpeg_garbage_returns_none():
    if not _native.decode_available():
        pytest.skip("built without libjpeg")
    assert _native.decode_jpeg(b"not a jpeg at all", 224) is None


def test_imagefolder_native_and_pil_agree(tmp_path):
    """The ImageFolder batch is framing-identical under both decoders."""
    pytest.importorskip("PIL")
    if not _native.decode_available():
        pytest.skip("built without libjpeg")
    from PIL import Image
    from tpu_dist.data.imagefolder import ImageFolderDataset
    split = tmp_path / "train" / "class0"
    split.mkdir(parents=True)
    for i in range(4):
        data, _ = _jpeg_bytes(300 + 10 * i, 400)
        (split / f"img{i}.jpg").write_bytes(data)
    ds = ImageFolderDataset(str(tmp_path / "train"), size=224, workers=2)
    idx = np.arange(4)
    native_imgs, labels = ds.get_batch(idx)
    with _native.numpy_fallback():
        pil_imgs, labels2 = ds.get_batch(idx)
    assert native_imgs.shape == pil_imgs.shape == (4, 224, 224, 3)
    np.testing.assert_array_equal(labels, labels2)
    diff = np.abs(native_imgs.astype(int) - pil_imgs.astype(int))
    assert diff.mean() < 2.0
