"""Optimizer/schedule numerics vs the reference recipe (C19 + torch SGD parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.ops.optim import (lm_lr_schedule, make_optimizer,
                                step_decay_schedule)


def test_lm_schedule_warmup_then_constant():
    sched = lm_lr_schedule(0.1, "constant", warmup_steps=4)
    # linear ramp: steps 0..3 apply 0.025, 0.05, 0.075, 0.1; then flat
    np.testing.assert_allclose([float(sched(s)) for s in range(6)],
                               [0.025, 0.05, 0.075, 0.1, 0.1, 0.1],
                               rtol=1e-6)


def test_lm_schedule_cosine_endpoints_and_floor():
    sched = lm_lr_schedule(0.2, "cosine", warmup_steps=10, total_steps=110,
                           min_frac=0.1)
    assert float(sched(10)) == pytest.approx(0.2)          # post-warmup peak
    assert float(sched(60)) == pytest.approx(0.2 * 0.55)   # halfway point
    assert float(sched(110)) == pytest.approx(0.02)        # floor reached
    assert float(sched(500)) == pytest.approx(0.02)        # flat after
    assert lm_lr_schedule(0.2, "cosine", warmup_steps=0,
                          total_steps=100)(100) == pytest.approx(0.0)


def test_lm_schedule_step_matches_reference_rule():
    sched = lm_lr_schedule(0.1, "step", steps_per_epoch=10, step_epochs=30)
    ref = step_decay_schedule(0.1, steps_per_epoch=10, step_epochs=30)
    for s in (0, 10 * 29, 10 * 30, 10 * 60):
        assert float(sched(s)) == pytest.approx(float(ref(s)))


def test_lm_schedule_rejects_bad_kind_and_horizon():
    with pytest.raises(ValueError, match="unknown lr schedule"):
        lm_lr_schedule(0.1, "linear")
    with pytest.raises(ValueError, match="cosine needs"):
        lm_lr_schedule(0.1, "cosine", warmup_steps=10, total_steps=10)


def test_step_decay_matches_reference_rule():
    # lr = 0.1 * 0.1^(epoch//30), reference 1.dataparallel.py:332-336
    sched = step_decay_schedule(0.1, steps_per_epoch=10, step_epochs=30)
    assert float(sched(0)) == pytest.approx(0.1)
    assert float(sched(10 * 29)) == pytest.approx(0.1)
    assert float(sched(10 * 30)) == pytest.approx(0.01)
    assert float(sched(10 * 60)) == pytest.approx(0.001)


def test_sgd_update_matches_torch_sgd():
    """Bitwise-recipe parity with torch.optim.SGD(momentum, weight_decay)."""
    torch = pytest.importorskip("torch")

    w0 = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)
    g = np.random.default_rng(1).normal(size=(5, 3)).astype(np.float32)

    # torch side: two steps with constant grad
    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.SGD([tw], lr=0.1, momentum=0.9, weight_decay=1e-4)
    for _ in range(2):
        opt.zero_grad()
        tw.grad = torch.tensor(g.copy())
        opt.step()

    # ours
    tx = make_optimizer(0.1, 0.9, 1e-4, steps_per_epoch=1000)
    params = {"w": jnp.asarray(w0)}
    opt_state = tx.init(params)
    for _ in range(2):
        updates, opt_state = tx.update({"w": jnp.asarray(g)}, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)

    np.testing.assert_allclose(np.asarray(params["w"]), tw.detach().numpy(),
                               rtol=1e-5, atol=1e-6)


def test_no_weight_decay_skips_decay_term():
    g = {"w": jnp.ones((4,))}
    params = {"w": jnp.full((4,), 100.0)}
    tx = make_optimizer(0.1, 0.0, 0.0, steps_per_epoch=10)
    u, _ = tx.update(g, tx.init(params), params)
    # without wd the update ignores the (huge) param values entirely
    np.testing.assert_allclose(np.asarray(u["w"]), -0.1 * np.ones(4), rtol=1e-6)


def test_adamw_update_matches_torch_adamw():
    """kind='adamw' reproduces torch.optim.AdamW (decoupled wd) step for
    step at matching hyperparameters."""
    torch = pytest.importorskip("torch")

    w0 = np.random.default_rng(2).normal(size=(5, 3)).astype(np.float32)
    g = np.random.default_rng(3).normal(size=(5, 3)).astype(np.float32)

    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.AdamW([tw], lr=0.01, betas=(0.9, 0.95), eps=1e-8,
                            weight_decay=0.1)
    for _ in range(3):
        opt.zero_grad()
        tw.grad = torch.tensor(g.copy())
        opt.step()

    tx = make_optimizer(0.01, weight_decay=0.1, kind="adamw",
                        b1=0.9, b2=0.95, eps=1e-8,
                        schedule=lambda s: 0.01)
    params = {"w": jnp.asarray(w0)}
    opt_state = tx.init(params)
    for _ in range(3):
        updates, opt_state = tx.update({"w": jnp.asarray(g)}, opt_state,
                                       params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)

    np.testing.assert_allclose(np.asarray(params["w"]), tw.detach().numpy(),
                               rtol=1e-5, atol=1e-6)


def test_unknown_optimizer_kind_raises():
    with pytest.raises(ValueError, match="sgd|adamw"):
        make_optimizer(0.1, kind="rmsprop")


def test_grad_clip_by_global_norm():
    """grad_clip: raw grads scale to the clip norm BEFORE momentum/adam
    statistics (torch clip_grad_norm_ placement); small grads untouched."""
    g = {"a": jnp.full((3,), 3.0), "b": jnp.full((4,), 4.0)}
    # global norm = sqrt(9*3 + 16*4) = sqrt(91) > 1
    params = jax.tree.map(jnp.zeros_like, g)
    tx = make_optimizer(1.0, momentum=0.0, weight_decay=0.0,
                        schedule=lambda s: 1.0, grad_clip=1.0)
    u, _ = tx.update(g, tx.init(params), params)
    gn = float(np.sqrt(sum(float(jnp.sum(x * x))
                           for x in jax.tree.leaves(u))))
    np.testing.assert_allclose(gn, 1.0, rtol=1e-6)  # clipped to the norm

    tiny = jax.tree.map(lambda x: x * 1e-3, g)
    u2, _ = tx.update(tiny, tx.init(params), params)
    for k in g:
        np.testing.assert_allclose(np.asarray(u2[k]), -np.asarray(tiny[k]),
                                   rtol=1e-6)  # under the norm: untouched

    # adamw variant accepts the knob and still steps
    tx2 = make_optimizer(1e-3, kind="adamw", weight_decay=0.0,
                         schedule=lambda s: 1e-3, grad_clip=1.0)
    u3, _ = tx2.update(g, tx2.init(params), params)
    assert all(np.all(np.isfinite(x)) for x in jax.tree.leaves(u3))


def test_grad_clip_zero_keeps_adamw_state_structure():
    """grad_clip=0 must leave the adamw opt_state pytree IDENTICAL to the
    pre-clip-feature structure (resume of older checkpoints)."""
    import optax
    params = {"w": jnp.ones((3,))}
    st_plain = optax.adamw(lambda s: 1e-3).init(params)
    st_ours = make_optimizer(1e-3, kind="adamw",
                             schedule=lambda s: 1e-3).init(params)
    assert (jax.tree_util.tree_structure(st_ours)
            == jax.tree_util.tree_structure(st_plain))


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_grad_clip_pp_matches_dp(schedule):
    """--grad-clip under pipeline parallelism (round 5 — was rejected in
    round 4): block grads are stage-LOCAL inside the pp shard_map, so the
    pp steps clip by a cross-stage psum'd global norm
    (parallel.pp._clip_pp_grads) instead of optax's per-device clip —
    pp+clip must train identically to dp+clip under both schedules, which
    also proves the replicated embed/head update stays synchronized."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    # lr/clip chosen so the clip actually TRIGGERS (raw grad norm at init
    # far exceeds 0.05 at this geometry) — an untriggered clip would pass
    # this test with an identity scale
    kw = dict(batch_size=8, seq_len=32, d_model=32, num_layers=4,
              num_heads=2, vocab_size=64, synth_tokens=2000, seed=3,
              epochs=1, lr=3e-2, grad_clip=0.05, print_freq=100,
              data_placement="host")

    def vec(tr):
        from tpu_dist.parallel.pp import unstack_pipeline_params
        params = jax.device_get(tr.state.params)
        if "blocks" in params:
            params = unstack_pipeline_params(params)
        flat = {jax.tree_util.keystr(p): np.asarray(v, np.float32) for p, v
                in jax.tree_util.tree_flatten_with_path(params)[0]}
        return np.concatenate([flat[k].ravel() for k in sorted(flat)])

    dp = LMTrainer(LMConfig(**kw)); dp.fit()
    pp = LMTrainer(LMConfig(mesh_shape=(2, 4), mesh_axes=("data", "stage"),
                            pp_microbatches=2, pp_schedule=schedule, **kw))
    pp.fit()
    np.testing.assert_allclose(vec(pp), vec(dp), rtol=2e-3, atol=1e-4)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_grad_clip_sp_matches_dp():
    """--grad-clip under sequence parallelism: sp grads are pmean'd to the
    FULL gradient before the update runs, so every device clips by the same
    true global norm — sp+clip trains identically to dp+clip (unlike pp,
    which is rejected)."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    kw = dict(batch_size=8, seq_len=32, d_model=32, num_layers=2,
              num_heads=2, vocab_size=64, synth_tokens=2000, seed=3,
              epochs=1, lr=3e-2, grad_clip=0.5, print_freq=100,
              data_placement="host")

    def vec(tr):
        return np.concatenate([np.asarray(x, np.float32).ravel()
                               for x in jax.tree_util.tree_leaves(
                                   jax.device_get(tr.state.params))])

    dp = LMTrainer(LMConfig(**kw)); dp.fit()
    sp = LMTrainer(LMConfig(mesh_shape=(2, 4), mesh_axes=("data", "seq"),
                            **kw))
    sp.fit()
    np.testing.assert_allclose(vec(sp), vec(dp), rtol=2e-3, atol=1e-4)
