"""Convergence regression bound (the north-star metric's fast guard).

BASELINE.md records steps-to-90% val top-1 for the TPU configs (measured
with tools/convergence.py). This test pins the cheap CPU-sized version of
the same property: if the engine's math, sampler, LR schedule, or metric
reduction regress, the model stops clearing the threshold within the
recorded step budget. Bound = recorded steps + margin, per SURVEY.md §4
(the reference's only QA was convergence — README_EN.md:10).
"""

import jax
import pytest

from tpu_dist.configs import TrainConfig
from tpu_dist.engine import Trainer

# recorded on the 8-virtual-device CPU mesh: lenet/synthetic-mnist clears
# 90% val top-1 in ONE epoch (32 steps) for every engine flavor; bound 2
# epochs = 64 steps for margin.
RECORDED_STEPS = 32
BOUND_STEPS = 64


def _converges(variant, precision, tmp, k=1):
    cfg = TrainConfig(
        arch="lenet", dataset="synthetic-mnist", variant=variant,
        precision=precision, batch_size=64, synth_train_size=2048,
        synth_val_size=512, seed=0, epochs=2, print_freq=10 ** 9,
        steps_per_dispatch=k, checkpoint_dir=tmp)
    tr = Trainer(cfg)
    for epoch in range(cfg.epochs):
        tr.train_epoch(epoch)
        acc = tr.validate(epoch)
        # distlint: disable=DL002 -- CPU test: epoch-boundary read of the step counter
        steps = int(jax.device_get(tr.state.step))
        if acc >= 0.90:
            return steps
    raise AssertionError(
        f"{variant}/{precision}: {acc * 100:.1f}% after {steps} steps "
        f"(bound {BOUND_STEPS})")


def test_jit_fp32_converges_within_bound(tmp_path):
    assert _converges("jit", "fp32", str(tmp_path)) <= BOUND_STEPS


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_jit_bf16_converges_within_bound(tmp_path):
    assert _converges("jit", "bf16", str(tmp_path)) <= BOUND_STEPS


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_shard_map_converges_within_bound(tmp_path):
    assert _converges("shard_map", "fp32", str(tmp_path)) <= BOUND_STEPS


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_windowed_dispatch_converges_within_bound(tmp_path):
    assert _converges("jit", "bf16", str(tmp_path), k=8) <= BOUND_STEPS
