"""Device telemetry utilities (reference statistics.sh analog, C22)."""

import csv
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist.utils.telemetry import (CSV_HEADER, device_memory_stats,
                                      peak_hbm_bytes, program_hbm_bytes,
                                      start_hbm_sampler)


def test_device_memory_stats_never_raises():
    """CPU/virtual backends expose no counters; the API degrades to {}."""
    s = device_memory_stats()
    assert isinstance(s, dict)
    assert peak_hbm_bytes() is None or peak_hbm_bytes() > 0


def test_program_hbm_bytes_from_compiled_program():
    """XLA's static memory analysis works on EVERY backend (the tunneled
    TPU returns no allocator counters — BASELINE.md round-5 note), so the
    epoch-CSV peak column is never empty on a jitted engine step."""
    @jax.jit
    def f(x):
        return (x @ x.T).sum()

    x = jnp.ones((64, 64), jnp.float32)
    f(x).block_until_ready()
    n = program_hbm_bytes(f, x)
    assert n is not None and n >= x.size * 4  # at least the argument bytes


def test_program_hbm_bytes_returns_none_on_non_jitted():
    assert program_hbm_bytes(lambda x: x, jnp.ones(())) is None


def test_hbm_sampler_writes_schema_and_rows(tmp_path):
    path = os.path.join(str(tmp_path), "tele.csv")
    stop = start_hbm_sampler(path, interval_s=0.05)
    time.sleep(0.3)
    stop()
    with open(path) as f:
        rows = list(csv.reader(f))
    assert rows[0] == CSV_HEADER.split(",")
    assert len(rows) >= 3          # several 50ms samples in 300ms
    assert float(rows[1][0]) > 0   # ts column
    import sys as _sys
    if _sys.platform == "linux":   # /proc-backed; empty elsewhere by design
        assert rows[1][4] != ""    # host RSS
    # stop() is idempotent-safe to the file: no rows after close
    n = len(rows)
    time.sleep(0.1)
    with open(path) as f:
        assert len(list(csv.reader(f))) == n
