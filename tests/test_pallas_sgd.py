"""Pallas fused SGD kernel: exact torch-SGD numerics (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.ops.pallas_sgd import FusedSGD, fused_sgd_leaf


@pytest.mark.parametrize("shape", [(7,), (130,), (3, 3, 16, 32)])
def test_fused_leaf_matches_reference_math(shape):
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.normal(size=shape), jnp.float32)
    g = jnp.asarray(rng.normal(size=shape), jnp.float32)
    m = jnp.asarray(rng.normal(size=shape), jnp.float32)
    p2, m2 = fused_sgd_leaf(p, g, m, 0.1, 0.9, 1e-4, interpret=True)
    g_ref = g + 1e-4 * p
    m_ref = 0.9 * m + g_ref
    p_ref = p - 0.1 * m_ref
    np.testing.assert_allclose(np.asarray(p2), np.asarray(p_ref),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(m2), np.asarray(m_ref),
                               rtol=1e-6, atol=1e-7)


def test_fused_bf16_params_fp32_momentum():
    p = jnp.ones((256,), jnp.bfloat16)
    g = jnp.full((256,), 0.5, jnp.float32)
    m = jnp.zeros((256,), jnp.float32)
    p2, m2 = fused_sgd_leaf(p, g, m, 0.1, 0.9, 0.0, interpret=True)
    assert p2.dtype == jnp.bfloat16
    assert m2.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(m2), 0.5)


def test_fused_sgd_matches_optax_over_tree():
    from tpu_dist.ops.optim import make_optimizer

    rng = np.random.default_rng(1)
    params = {"a": jnp.asarray(rng.normal(size=(64, 32)), jnp.float32),
              "b": {"w": jnp.asarray(rng.normal(size=(130,)), jnp.float32)}}
    grads = jax.tree.map(lambda p: jnp.asarray(
        rng.normal(size=p.shape), jnp.float32), params)

    sched = lambda step: 0.05
    fused = FusedSGD(sched, momentum=0.9, weight_decay=1e-4, interpret=True)
    fstate = fused.init(params)
    fp, fstate = fused.apply(params, grads, fstate, jnp.int32(0))
    fp, fstate = fused.apply(fp, grads, fstate, jnp.int32(1))

    tx = make_optimizer(0.05, 0.9, 1e-4, steps_per_epoch=10 ** 6)
    op = params
    ostate = tx.init(op)
    for _ in range(2):
        updates, ostate = tx.update(grads, ostate, op)
        op = jax.tree.map(lambda p, u: p + u, op, updates)

    for k1, k2 in zip(jax.tree.leaves(fp), jax.tree.leaves(op)):
        np.testing.assert_allclose(np.asarray(k1), np.asarray(k2),
                                   rtol=1e-5, atol=1e-6)


def test_fused_sgd_clip_matches_optax_chain():
    """clip_norm > 0 reproduces optax clip_by_global_norm -> sgd exactly:
    the clip scale is computed once per step over the whole tree and fused
    into the kernel's update sweep (ops.pallas_sgd.clip_scale). Large grads
    force the clip branch; the final tiny-grad step checks identity."""
    from tpu_dist.ops.optim import make_optimizer

    rng = np.random.default_rng(3)
    params = {"a": jnp.asarray(rng.normal(size=(40, 16)), jnp.float32),
              "b": {"w": jnp.asarray(rng.normal(size=(130,)), jnp.float32)}}
    clip = 0.25
    fused = FusedSGD(lambda s: 0.05, momentum=0.9, weight_decay=1e-4,
                     clip_norm=clip, interpret=True)
    tx = make_optimizer(0.05, 0.9, 1e-4, steps_per_epoch=10 ** 6,
                        grad_clip=clip)
    fp, fstate = params, fused.init(params)
    op, ostate = params, tx.init(params)
    for step, mag in enumerate((4.0, 1e-3)):   # clip branch, then identity
        grads = jax.tree.map(lambda p: jnp.asarray(
            mag * rng.normal(size=p.shape), jnp.float32), params)
        fp, fstate = fused.apply(fp, grads, fstate, jnp.int32(step))
        updates, ostate = tx.update(grads, ostate, op)
        op = jax.tree.map(lambda p, u: p + u, op, updates)
        for k1, k2 in zip(jax.tree.leaves(fp), jax.tree.leaves(op)):
            np.testing.assert_allclose(np.asarray(k1), np.asarray(k2),
                                       rtol=1e-5, atol=1e-6)


def test_engine_with_fused_sgd_converges():
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg = TrainConfig(dataset="synthetic-mnist", arch="lenet", epochs=1,
                      batch_size=64, synth_train_size=256, synth_val_size=64,
                      seed=1, print_freq=100, optimizer="fused_sgd",
                      checkpoint_dir="/tmp/ck_fused")
    best = Trainer(cfg).fit()
    assert best > 0.3
