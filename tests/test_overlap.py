"""Comm/compute overlap subsystem (parallel.overlap): ring collective
matmul, ring allreduce, bucketed gradient sync, and their engine plumbing.

In-budget tests keep models tiny (2 layers, d32) and assert EXACT-shape /
allclose parity of the decomposed collectives against their fused
references on the virtual 8-device mesh; full trainer-level dp x tp ring
parity, the ring x int8 composition, and the ViT ring Trainer run are
marked slow (each carries multi-program XLA compiles), as are the
model-level forward-parity and engine-step-parity checks — the same
decompositions are pinned in-budget at the function level, keeping this
file's tier-1 footprint to a few seconds."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dist._compat import shard_map
from tpu_dist.parallel.collectives import ring_allreduce
from tpu_dist.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from tpu_dist.parallel.overlap import (bucketed_grad_sync, grad_buckets,
                                       ring_allgather_matmul,
                                       ring_matmul_reduce_scatter,
                                       validate_tp_impl)


def _model_mesh(n):
    return make_mesh((n,), (MODEL_AXIS,), devices=jax.devices()[:n])


# ------------------------------------------------------- ring allreduce
def test_ring_allreduce_matches_psum():
    """Chunked two-pass ppermute ring == fused psum, including a length
    that does not divide the axis size (internal padding)."""
    mesh = make_mesh()
    for shape in ((13,), (4, 5), (8, 16)):
        x = jnp.asarray(np.random.default_rng(0).normal(size=(8,) + shape),
                        jnp.float32)

        def run(f):
            g = shard_map(lambda v: f(v[0])[None], mesh=mesh,
                          in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS),
                          check_vma=False)
            return np.asarray(jax.jit(g)(x))

        ring = run(lambda v: ring_allreduce(v, DATA_AXIS, 8))
        fused = run(lambda v: jax.lax.psum(v, DATA_AXIS))
        np.testing.assert_allclose(ring, fused, rtol=1e-6, atol=1e-6)


# ------------------------------------------------- ring collective matmul
def test_ring_collective_matmul_matches_einsum():
    """AG-matmul and matmul-RS return EXACTLY the shapes of the fused
    einsums they decompose, with values allclose — and the quantized
    matmul rides the same ring within int8 tolerance."""
    n, b, L, D, F = 4, 2, 16, 12, 24
    mesh = _model_mesh(n)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(b, L, D)), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(D, F)) * 0.2, jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(F, D)) * 0.2, jnp.float32)

    def pair(xs, a, c):
        h = ring_allgather_matmul(xs, a, MODEL_AXIS)
        assert h.shape == (b, L, F // n)      # exact shape of x@a's shard
        out = ring_matmul_reduce_scatter(h, c, MODEL_AXIS)
        assert out.shape == (b, L // n, D)    # exact shape of (x@a)@c's shard
        return h, out

    f = jax.jit(shard_map(
        pair, mesh=mesh,
        in_specs=(P(None, MODEL_AXIS, None), P(None, MODEL_AXIS),
                  P(MODEL_AXIS, None)),
        out_specs=(P(None, None, MODEL_AXIS), P(None, MODEL_AXIS, None)),
        check_vma=False))
    h, out = f(x, w1, w2)
    ref_h = jnp.einsum("bld,df->blf", x, w1)
    assert h.shape == ref_h.shape and out.shape == x.shape
    np.testing.assert_allclose(np.asarray(h), np.asarray(ref_h),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.einsum("blf,fd->bld",
                                                     ref_h, w2)),
                               rtol=1e-5, atol=1e-5)

    # int8 composition: the per-chunk quantized matmul (ops.quant) — scales
    # are per activation row / per weight channel, so chunking the sequence
    # preserves them; parity with the fused quant einsum is loss-of-
    # precision-level, not bitwise (accumulation order)
    from tpu_dist.ops.quant import quant_matmul

    mm8 = lambda a, c: quant_matmul(a, c, "int8")
    f8 = jax.jit(shard_map(
        lambda xs, a: ring_allgather_matmul(xs, a, MODEL_AXIS, matmul=mm8),
        mesh=mesh, in_specs=(P(None, MODEL_AXIS, None), P(None, MODEL_AXIS)),
        out_specs=P(None, None, MODEL_AXIS), check_vma=False))
    ref8 = quant_matmul(x, w1, "int8")
    np.testing.assert_allclose(np.asarray(f8(x, w1)), np.asarray(ref8),
                               rtol=5e-2, atol=5e-2)


# --------------------------------------------------- bucketed grad sync
def test_grad_buckets_rules():
    """Size-targeted grouping: consecutive fill, oversized leaf alone,
    dtype change closes a bucket."""
    mk = lambda size, dt=jnp.float32: jnp.zeros((size,), dt)
    leaves = [mk(100), mk(100), mk(10_000), mk(50), mk(50, jnp.bfloat16)]
    groups = grad_buckets(leaves, bucket_bytes=1000)
    assert groups == [[0, 1], [2], [3], [4]]
    assert grad_buckets([mk(10)], 1.0) == [[0]]  # oversized still buckets


def test_bucketed_grad_sync_matches_monolithic():
    """The decomposed bucket reduce-scatter+all-gather sync == per-leaf
    pmean, across ragged shapes, several buckets, and both impls."""
    mesh = make_mesh()
    rng = np.random.default_rng(1)
    tree = {"a": rng.normal(size=(8, 37)), "b": rng.normal(size=(8, 3, 5)),
            "c": rng.normal(size=(8, 501)), "d": rng.normal(size=(8, 2))}
    tree = jax.tree.map(lambda v: jnp.asarray(v, jnp.float32), tree)

    def run(f):
        g = shard_map(
            lambda t: jax.tree.map(lambda v: v[None],
                                   f(jax.tree.map(lambda u: u[0], t))),
            mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS),
            check_vma=False)
        out = jax.jit(g)(tree)
        return {k: np.asarray(v)[0] for k, v in out.items()}

    mono = run(lambda t: jax.tree.map(
        lambda g: jax.lax.pmean(g, DATA_AXIS), t))
    for impl in ("rs_ag", "ring"):
        buck = run(lambda t: bucketed_grad_sync(
            t, DATA_AXIS, bucket_mb=0.001, mean=True, axis_size=8,
            impl=impl))
        for k in mono:
            np.testing.assert_allclose(buck[k], mono[k], rtol=1e-5,
                                       atol=1e-6, err_msg=f"{impl}:{k}")


# ------------------------------------------------- model-level ring parity
def _tiny_lm(**kw):
    from tpu_dist.models.transformer import tiny_lm
    return tiny_lm(vocab_size=64, num_layers=2, d_model=32, num_heads=4,
                   max_len=32, **kw)


@pytest.mark.slow
def test_ring_lm_forward_parity():
    """tp_impl='ring' TransformerLM == the plain model, from the SAME
    params (the trees are identical by construction): logits assembled
    from the per-device seq chunks match the fused forward."""
    n = 4
    mesh = _model_mesh(n)
    model = _tiny_lm()
    tokens = np.random.default_rng(0).integers(0, 64, (2, 16)).astype(
        np.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens,
                        train=False)["params"]
    ref = model.apply({"params": params}, tokens, train=False)
    ring = model.clone(tp_impl="ring")
    f = jax.jit(shard_map(
        lambda p, t: ring.apply({"params": p}, t, train=False),
        mesh=mesh, in_specs=(P(), P()),
        out_specs=P(None, MODEL_AXIS, None), check_vma=False))
    out = f(params, tokens)
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_vit_ring_forward_parity():
    """ViT maps tp_impl='ring' onto the full-token ring_ar flavor (the
    [CLS] token forbids an even sequence split): logits match the plain
    model from the same params."""
    from tpu_dist.models.vit import ViT

    n = 4
    mesh = _model_mesh(n)
    model = ViT(num_classes=5, patch_size=4, num_layers=2, d_model=32,
                num_heads=4)
    x = np.random.default_rng(0).normal(size=(2, 8, 8, 3)).astype(
        np.float32)
    params = model.init({"params": jax.random.PRNGKey(0)}, x,
                        train=False)["params"]
    ref = model.apply({"params": params}, x, train=False)
    ring = model.clone(tp_impl="ring")
    f = jax.jit(shard_map(
        lambda p, t: ring.apply({"params": p}, t, train=False),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False))
    np.testing.assert_allclose(np.asarray(f(params, x)), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ------------------------------------------------------ engine step parity
@pytest.mark.slow
def test_lm_bucketed_step_matches_jit_dp():
    """One optimizer step through the explicit bucketed-sync dp step ==
    the jit/GSPMD dp step (loss equal, updated params allclose)."""
    from tpu_dist.engine.lm_steps import (make_lm_shard_map_train_step,
                                          make_lm_train_step)
    from tpu_dist.engine.state import TrainState
    from tpu_dist.ops import make_optimizer
    from tpu_dist.parallel.mesh import replicated

    mesh = make_mesh()
    model = _tiny_lm()
    rows = np.random.default_rng(0).integers(0, 64, (8, 17)).astype(
        np.int32)
    inputs, targets = rows[:, :-1], rows[:, 1:]
    params = model.init({"params": jax.random.PRNGKey(0)}, inputs,
                        train=False)["params"]
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=100)
    state = jax.device_put(TrainState.create(params, {}, tx),
                           replicated(mesh))
    key = jax.random.PRNGKey(1)
    st_jit, m_jit = make_lm_train_step(model, tx, mesh, donate=False)(
        state, inputs, targets, key)
    st_b, m_b = make_lm_shard_map_train_step(
        model, tx, mesh, grad_bucket_mb=0.0005, donate=False)(
        state, inputs, targets, key)
    assert float(m_jit["loss_sum"]) == pytest.approx(
        float(m_b["loss_sum"]), rel=1e-6)
    for a, b in zip(jax.tree.leaves(st_jit.params),
                    jax.tree.leaves(st_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


# ----------------------------------------------------------- knob gating
def test_overlap_knob_validation():
    from tpu_dist.configs import LMConfig, TrainConfig
    from tpu_dist.engine import Trainer
    from tpu_dist.engine.lm_loop import LMTrainer

    with pytest.raises(ValueError, match="tp_impl"):
        validate_tp_impl("nccl")
    lm = dict(synth_tokens=2000, vocab_size=64, seq_len=32, num_layers=1,
              d_model=32, num_heads=4, batch_size=8, epochs=1, seed=0)
    with pytest.raises(ValueError, match="seq_len"):
        LMTrainer(LMConfig(mesh_shape=(2, 4), mesh_axes=("data", "model"),
                           tp_impl="ring", **{**lm, "seq_len": 30}))
    with pytest.raises(ValueError, match="pure-dp"):
        LMTrainer(LMConfig(fsdp=True, grad_bucket_mb=25.0, **lm))
    img = dict(dataset="synthetic-mnist", arch="lenet", epochs=1,
               batch_size=16, synth_train_size=32, synth_val_size=16)
    with pytest.raises(ValueError, match="shard_map"):
        Trainer(TrainConfig(grad_bucket_mb=25.0, **img))
    with pytest.raises(ValueError, match="vit"):
        Trainer(TrainConfig(variant="shard_map", tp_impl="ring", **img))
    with pytest.raises(ValueError, match="num_heads"):
        # vit_tiny's 3 heads cannot split over a 2-wide model axis
        Trainer(TrainConfig(variant="shard_map", tp_impl="ring",
                            mesh_shape=(4, 2), mesh_axes=("data", "model"),
                            dataset="synthetic-cifar10", arch="vit_tiny",
                            epochs=1, batch_size=16, synth_train_size=32,
                            synth_val_size=16))


# ------------------------------------------------------------ comm bench
def test_comm_bench_cli(tmp_path):
    """tools/comm_bench.py runs green at tiny sizes and its ledger step
    records carry a MEASURED comm phase."""
    from tools.comm_bench import main
    from tpu_dist.obs import read_ledger

    path = str(tmp_path / "comm.jsonl")
    rc = main(["--sizes-mb", "0.01", "--dims", "16,16,32", "--iters", "1",
               "--bucket-mb", "0.005", "--ledger", path])
    assert rc == 0
    steps = [r for r in read_ledger(path) if r["event"] == "step"]
    assert steps and all(r["comm_s"] is not None and r["comm_s"] > 0
                         for r in steps)
    assert any(r["label"].startswith("matmul") for r in steps)


# ----------------------------------------------------------------- slow
@pytest.mark.slow
def test_ring_tp_trainer_loss_parity_vs_gspmd():
    """Full dp x tp train parity at the trainer level: tp_impl='ring' and
    the GSPMD TP engine reach the SAME val loss from the same seed (the
    acceptance bar: losses allclose on a multi-device CPU mesh)."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    base = dict(synth_tokens=8000, vocab_size=64, seq_len=32, num_layers=2,
                d_model=32, num_heads=4, batch_size=8, epochs=1, seed=0,
                lr=0.05, print_freq=100,
                mesh_shape=(2, 4), mesh_axes=("data", "model"))
    t_gspmd = LMTrainer(LMConfig(**base))
    t_gspmd.train_epoch(0)
    loss_gspmd = t_gspmd.validate(0)[0]
    t_ring = LMTrainer(LMConfig(tp_impl="ring", **base))
    assert t_ring.mode == "tp-ring"
    t_ring.train_epoch(0)
    loss_ring = t_ring.validate(0)[0]
    assert loss_ring == pytest.approx(loss_gspmd, rel=1e-4)


@pytest.mark.slow
def test_ring_int8_quant_composition():
    """quant='int8' rides the ring: the QuantDense int8 matmul runs inside
    the collective matmul chunks. Scales are per-shard (finer than GSPMD's
    global per-row amax), so parity with the GSPMD int8 path is loss-level,
    and both track the fp loss closely at init."""
    from tpu_dist.engine.lm_steps import (make_lm_train_step,
                                          make_lm_tp_ring_train_step)
    from tpu_dist.engine.state import TrainState
    from tpu_dist.ops import make_optimizer
    from tpu_dist.parallel.mesh import replicated
    from tpu_dist.parallel.tp import shard_lm_params

    mesh = make_mesh((2, 4), ("data", "model"))
    model = _tiny_lm(quant="int8")
    rows = np.random.default_rng(0).integers(0, 64, (8, 17)).astype(
        np.int32)
    inputs, targets = rows[:, :-1], rows[:, 1:]
    params = model.init({"params": jax.random.PRNGKey(0)}, inputs,
                        train=False)["params"]
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=100)
    key = jax.random.PRNGKey(1)

    from tpu_dist.engine.state import TrainState as TS
    tp_state = TS.create(params, {}, tx)
    tp_state = TS(step=jax.device_put(tp_state.step,
                                      NamedSharding(mesh, P())),
                  params=shard_lm_params(mesh, tp_state.params),
                  batch_stats={},
                  opt_state=jax.device_put(tp_state.opt_state,
                                           NamedSharding(mesh, P())),
                  loss_scale=None)
    gspmd_step = make_lm_train_step(model, tx, mesh, donate=False)
    ring_state = jax.device_put(TrainState.create(params, {}, tx),
                                replicated(mesh))
    ring_step = make_lm_tp_ring_train_step(
        model.clone(tp_impl="ring"), tx, mesh, donate=False)
    losses = {"gspmd": [], "ring": []}
    for _ in range(3):
        tp_state, m1 = gspmd_step(tp_state, inputs, targets, key)
        ring_state, m2 = ring_step(ring_state, inputs, targets, key)
        losses["gspmd"].append(float(m1["loss_sum"]) / float(m1["count"]))
        losses["ring"].append(float(m2["loss_sum"]) / float(m2["count"]))
    np.testing.assert_allclose(losses["ring"], losses["gspmd"],
                               rtol=5e-2)
    assert losses["ring"][-1] < losses["ring"][0]  # it trains


@pytest.mark.slow
def test_vit_ring_trainer_matches_replicated():
    """The image engine's --tp-impl ring (ViT, variant shard_map, model
    mesh axis) matches the model-axis-replicated run batch for batch."""
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    base = dict(dataset="synthetic-cifar10", arch="vit_cifar", epochs=1,
                batch_size=64, synth_train_size=128, synth_val_size=64,
                seed=3, print_freq=100, lr=0.01, variant="shard_map",
                mesh_shape=(4, 2), mesh_axes=("data", "model"))
    ring = Trainer(TrainConfig(tp_impl="ring", **base)).train_epoch(0)
    repl = Trainer(TrainConfig(**base)).train_epoch(0)
    assert ring["loss"] == pytest.approx(repl["loss"], rel=1e-3)
