"""Determinism policy (reference C24: --seed + cudnn.deterministic).

On TPU determinism is the default given fixed PRNG keys: same --seed must
reproduce the run bit-for-bit (the reference could only best-effort this via
cudnn flags with a documented perf warning, 1.dataparallel.py:78-86).
"""

import jax
import pytest
import numpy as np

from tpu_dist.configs import TrainConfig
from tpu_dist.engine import Trainer


def _run(seed, ckpt_dir):
    cfg = TrainConfig(dataset="synthetic-mnist", arch="lenet", epochs=1,
                      batch_size=64, synth_train_size=256, synth_val_size=64,
                      seed=seed, print_freq=100, checkpoint_dir=ckpt_dir)
    tr = Trainer(cfg)
    best = tr.fit()
    flat = np.concatenate([np.asarray(x).ravel()
                           for x in jax.tree.leaves(tr.state.params)])
    return best, flat


def test_same_seed_reproduces_bitwise(tmp_path):
    b1, p1 = _run(123, str(tmp_path / "a"))
    b2, p2 = _run(123, str(tmp_path / "b"))
    assert b1 == b2
    np.testing.assert_array_equal(p1, p2)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_different_seed_differs(tmp_path):
    _, p1 = _run(123, str(tmp_path / "a"))
    _, p2 = _run(124, str(tmp_path / "b"))
    assert not np.array_equal(p1, p2)


def test_epoch_reshuffle_changes_batches():
    # set_epoch semantics: epoch 0 and epoch 1 visit data in different order
    from tpu_dist.data.sampler import DistributedSampler

    s = DistributedSampler(256, 1, 0, shuffle=True, seed=5, batch_size=32)
    s.set_epoch(0)
    e0 = s.indices().copy()
    s.set_epoch(1)
    assert not np.array_equal(e0, s.indices())
