"""Request observatory (round 17): obs.reqtrace span ids, the span
pipeline through engine.serve, and the reading side
(tools/request_report) over a canned two-host fixture.

The pins that matter:

* span ids are DETERMINISTIC and host-independent: two hosts that never
  exchanged a byte mint the same trace_id for the same (namespace, rid),
  so cross-host stitching is id equality, no coordination;
* the canned fixture (tests/fixtures/reqtrace: rid 4 completed on host
  0; rid 5 shed on host 0 under drain, re-admitted and completed on host
  1) reproduces EXACT attribution numbers — per-request queue/prefill/
  decode seconds, residue 0, coverage 1.0 — and stitches rid 5 into ONE
  trace spanning both hosts;
* every ``slo`` breach resolves to >= 1 concrete exemplar trace, worst
  offender first (the shed request outranks the completed one);
* the report is byte-deterministic: same ledger bytes -> same report
  bytes, twice (scripts/lint.sh gates on the same invariant, jax-free);
* the LIVE engine (engine.serve under a virtual clock) emits spans that
  tile admit->finish: queue+prefill meet at first token, decode windows
  meet at finish, so the sum-check holds with residue ~ 0 by
  construction, and a drain shed emits the orphan ``shed`` span.
"""

import itertools
import json
import os
import subprocess
import sys

import pytest

from tpu_dist.obs import reqtrace
from tpu_dist.obs.ledger import Ledger
from tpu_dist.sim.fleet import FleetLedger
from tools.request_report import (requests_summary, slowest_traces,
                                  waterfall_lines)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(ROOT, "tests", "fixtures", "reqtrace")


# ------------------------------------------------------------- span ids
def test_trace_id_is_host_independent_and_deterministic():
    a = reqtrace.trace_id("ci", 5)
    b = reqtrace.trace_id("ci", 5)
    assert a == b and len(a) == 16
    assert reqtrace.trace_id("ci", 4) != a          # rid separates
    assert reqtrace.trace_id("prod", 5) != a        # namespace separates


def test_root_and_child_ids_separate_attempts_and_names():
    tid = reqtrace.trace_id("ci", 5)
    r0 = reqtrace.root_span_id(tid, "ci-h0", 0)
    r1 = reqtrace.root_span_id(tid, "ci-h1", 0)
    assert r0 != r1                                  # per host-attempt view
    assert reqtrace.root_span_id(tid, "ci-h0", 1) != r0
    k0 = reqtrace.child_span_id(r0, "decode", 0)
    k1 = reqtrace.child_span_id(r0, "decode", 1)
    assert k0 != k1 and k0 != reqtrace.child_span_id(r0, "queue", 0)


def test_tracer_advances_per_name_counters_and_stamps_attrs():
    cap = []
    led = Ledger(None, sinks=(cap.append,))
    tr = reqtrace.RequestTracer(led, job_id="j", attempt=2, host=3,
                                trace_ns="ns")
    tid, root, parent = tr.root_ids(7)
    assert tid == reqtrace.trace_id("ns", 7) and parent is None
    _, s0, p0 = tr.ids(7, "decode")
    _, s1, p1 = tr.ids(7, "decode")
    assert p0 == p1 == root                          # children hang off root
    assert s0 == reqtrace.child_span_id(root, "decode", 0)
    assert s1 == reqtrace.child_span_id(root, "decode", 1)
    assert tr.attrs() == {"job_id": "j", "attempt": 2, "host": 3}
    # standalone serving: no host stamp
    assert "host" not in reqtrace.RequestTracer(led, job_id="j").attrs()


# -------------------------------------------- the canned two-host fixture
def _fixture_records():
    return FleetLedger.discover(FIX).merged()


def test_fixture_stitches_rid5_into_one_cross_host_trace():
    traces = reqtrace.traces(_fixture_records())
    assert len(traces) == 2
    t4 = traces[reqtrace.trace_id("ci", 4)]
    t5 = traces[reqtrace.trace_id("ci", 5)]
    assert t4["hosts"] == [0] and t4["rid"] == 4
    # ONE trace for rid 5: the shed attempt on host 0 and the completed
    # re-admission on host 1 share the id two processes derived alone
    assert t5["hosts"] == [0, 1] and t5["rid"] == 5
    assert [r["job_id"] for r in t5["roots"]] == ["ci-h1"]
    names = sorted(s["name"] for s in t5["spans"])
    assert names == ["cow_fork", "decode", "prefill", "prefix_hit",
                     "queue", "readmit", "request", "shed"]
    # the tree: every completed-side child hangs off host 1's root
    kids = reqtrace.children_of(t5)
    root = t5["roots"][0]["span_id"]
    assert {s["name"] for s in kids[root]} == {
        "queue", "prefill", "decode", "readmit", "prefix_hit", "cow_fork"}
    # walk() yields the root first, then its children one level down
    depths = {s["name"]: d for d, s in reqtrace.walk(t5)}
    assert depths["request"] == 0 and depths["decode"] == 1


def test_fixture_attribution_numbers_exact():
    summary = requests_summary(_fixture_records())
    assert summary["traces"] == 2
    assert summary["completed_requests"] == 2
    assert summary["cross_host_traces"] == 1
    assert summary["sheds"] == 1 and summary["readmits"] == 1
    r4, r5 = summary["per_request"]
    assert (r4["rid"], r4["latency_s"], r4["queue_s"], r4["prefill_s"],
            r4["decode_s"], r4["residue_s"]) == (4, 1.0, 0.2, 0.3, 0.5, 0.0)
    assert r4["tpot_s"] == 0.0625 and r4["sum_check_ok"]
    assert (r5["rid"], r5["latency_s"], r5["queue_s"], r5["prefill_s"],
            r5["decode_s"], r5["residue_s"]) == (5, 2.0, 0.3, 0.6, 1.1, 0.0)
    assert r5["tpot_s"] == 0.06875 and r5["sum_check_ok"]
    ta = summary["tail_attribution"]
    assert ta["coverage"] == 1.0
    assert ta["sum_check"] == {"ok": True, "requests": 2, "failed": [],
                               "max_residue_s": 0.0, "tolerance_s": 1e-4}
    assert ta["shares"]["queue"]["seconds"] == 0.5
    assert ta["shares"]["prefill"]["seconds"] == 0.9
    assert ta["shares"]["decode"]["seconds"] == 1.6
    assert ta["shares"]["residue"]["seconds"] == 0.0
    # the percentile IS a concrete request: p50 TTFT names rid 4's split,
    # p99 names rid 5's
    assert ta["ttft"]["p50"]["rid"] == 4
    assert ta["ttft"]["p50"]["queue_s"] == 0.2
    assert ta["ttft"]["p99"]["rid"] == 5
    assert ta["ttft"]["p99"]["prefill_s"] == 0.6


def test_fixture_every_slo_breach_has_exemplars_worst_first():
    records = _fixture_records()
    summary = requests_summary(records)
    assert len(summary["slo_exemplars"]) == 1
    breach = summary["slo_exemplars"][0]
    assert breach["kind"] == "queue_wait" and breach["host"] == 0
    assert len(breach["exemplars"]) >= 1
    # worst offender first: the 1.4s shed outranks the 0.2s completion
    assert [e["kind"] for e in breach["exemplars"]] == ["shed", "request"]
    assert breach["exemplars"][0]["rid"] == 5
    assert breach["exemplars"][0]["score_s"] == 1.4


def test_fixture_report_is_byte_deterministic():
    def build():
        records = FleetLedger.discover(FIX).merged()
        summary = requests_summary(records)
        lines = []
        from tools.request_report import render
        render(summary, records, out=lines.append, waterfalls=5)
        return json.dumps(summary, default=str), "\n".join(lines)

    assert build() == build()


def test_fixture_waterfall_shows_cross_host_story():
    traces = reqtrace.traces(_fixture_records())
    slow = slowest_traces(traces, 2)
    assert [t["rid"] for t in slow] == [5, 4]        # slowest first
    lines = "\n".join(waterfall_lines(slow[0]))
    assert "hosts=[0,1]" in lines
    assert "no root: attempt never completed it" in lines  # host 0's shed
    assert "ticks=16 tokens=16" in lines             # the decode window


# ------------------------------------------------ reading-side plumbing
def test_ledger_report_renders_requests_section():
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "ledger_report.py"),
         os.path.join(FIX, "host1", "run.jsonl"), "--json"],
        capture_output=True, text=True, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr
    req = json.loads(proc.stdout)["requests"]
    assert req["traces"] == 1 and req["completed_requests"] == 1
    assert req["tail_attribution"]["coverage"] == 1.0


def test_trace_merge_gives_each_request_its_own_lane(tmp_path):
    out = str(tmp_path / "trace.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_merge.py"),
         os.path.join(FIX, "host1", "run.jsonl"), "-o", out,
         "--no-discover"], capture_output=True, text=True, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr
    with open(out) as f:
        events = json.load(f)["traceEvents"]
    lanes = [e for e in events if e["ph"] == "M"
             and e["args"].get("name") == "request r5"]
    assert len(lanes) == 1
    spans = [e for e in events if e["ph"] == "X"
             and e["tid"] == lanes[0]["tid"]]
    assert {e["name"] for e in spans} >= {"queue", "prefill", "decode",
                                          "request"}
    dec = next(e for e in spans if e["name"] == "decode")
    assert dec["dur"] == pytest.approx(1.1e6)        # engine seconds -> us
    assert dec["args"]["trace_id"] == reqtrace.trace_id("ci", 5)


def test_metrics_sink_observes_request_ttft_histogram():
    from tpu_dist.obs.metrics import MetricsRegistry, metrics_ledger_sink

    reg = MetricsRegistry()
    sink = metrics_ledger_sink(reg)
    # only root spans carry ttft_s; child spans must not observe
    sink({"event": "span", "name": "decode", "rid": 1, "ts": 1.0})
    sink({"event": "span", "name": "request", "rid": 1, "ttft_s": 0.5,
          "ts": 1.0})
    text = reg.render()
    assert "tpu_dist_request_ttft_seconds_count 1" in text
    assert "tpu_dist_request_ttft_seconds_sum 0.5" in text


# ------------------------------------------- the live engine (jax, tiny)
def test_serve_spans_tile_admit_to_finish_and_drain_sheds():
    """The whole writing side at once, no fixture: a tiny engine under a
    virtual clock completes requests (queue+prefill+decode spans tile
    admit->finish exactly — residue 0, coverage 1.0) and a drain sheds
    the queued stragglers as orphan ``shed`` spans."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dist.engine.serve import (DecodeRequest, ServeConfig,
                                       ServeEngine)
    from tpu_dist.models.transformer import tiny_lm

    L = 32
    lm = tiny_lm(vocab_size=64, num_layers=1, d_model=32, num_heads=2,
                 max_len=L)
    params = lm.init({"params": jax.random.PRNGKey(0)},
                     jnp.zeros((1, L), jnp.int32), train=False)["params"]
    cap = []
    led = Ledger(None, sinks=(cap.append,))
    clock = itertools.count()
    eng = ServeEngine(lm, params, ServeConfig(
        max_slots=2, page_size=4, num_pages=16, trace_window_ticks=4),
        ledger=led, now_fn=lambda: float(next(clock)))
    for i in range(3):
        assert eng.submit(DecodeRequest(i, np.array([1, 2, 3], np.int32),
                                        6))
    for _ in range(100):
        eng.step()
        if eng.completed == 3 and not eng.queue:
            break
    assert eng.completed == 3
    # one more queued request, then drain: it must shed with a span
    assert eng.submit(DecodeRequest(9, np.array([1], np.int32), 4))
    eng.drain(reason="sigterm")
    summary = requests_summary(cap)
    assert summary["completed_requests"] == 3
    ta = summary["tail_attribution"]
    assert ta["sum_check"]["ok"], ta["sum_check"]
    assert ta["coverage"] == 1.0
    assert summary["sheds"] == 1
    shed = next(s for t in reqtrace.traces(cap).values()
                for s in t["spans"] if s["name"] == "shed")
    assert shed["rid"] == 9 and shed["reason"] == "shed"
    # decode windows tile first token -> finish with shared boundaries
    for tr in reqtrace.traces(cap).values():
        decs = sorted((s for s in tr["spans"] if s["name"] == "decode"),
                      key=lambda s: s["start"])
        for a, b in zip(decs, decs[1:]):
            assert a["end"] == b["start"]
