"""Checkpoint save + REAL resume (C20 — the reference has no load path)."""

import os

import jax
import numpy as np

from tpu_dist.engine import checkpoint as ckpt
from tpu_dist.engine.state import TrainState, init_model
from tpu_dist.models import create_model
from tpu_dist.ops import make_optimizer


def _state():
    model = create_model("lenet")
    params, stats = init_model(model, jax.random.PRNGKey(0), (2, 28, 28, 1))
    tx = make_optimizer(0.1, 0.9, 1e-4, steps_per_epoch=10)
    return TrainState.create(params, stats, tx)


def test_save_load_roundtrip(tmp_path):
    state = _state()
    path = ckpt.save_checkpoint(str(tmp_path), state, epoch=3, best_acc1=0.5,
                                arch="lenet", is_best=True)
    assert path is not None and os.path.exists(path)
    # best copy, reference model_best convention (1.dataparallel.py:287-288)
    assert os.path.exists(os.path.join(str(tmp_path), "lenet-model_best.msgpack"))

    template = _state()
    restored, meta = ckpt.load_checkpoint(path, template)
    assert meta["epoch"] == 3
    assert meta["best_acc1"] == 0.5
    a = jax.tree.leaves(state.params)
    b = jax.tree.leaves(restored.params)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_resume_continues_from_epoch(tmp_path):
    """End-to-end: train 1 epoch, checkpoint, resume -> start_epoch advanced."""
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg = TrainConfig(dataset="synthetic-mnist", arch="lenet", epochs=1,
                      batch_size=64, synth_train_size=256, synth_val_size=64,
                      seed=1, print_freq=100, checkpoint_dir=str(tmp_path))
    Trainer(cfg).fit()
    ck = os.path.join(str(tmp_path), "lenet-checkpoint.msgpack")
    assert os.path.exists(ck)

    cfg2 = TrainConfig(dataset="synthetic-mnist", arch="lenet", epochs=2,
                       batch_size=64, synth_train_size=256, synth_val_size=64,
                       seed=1, print_freq=100, checkpoint_dir=str(tmp_path),
                       resume=ck)
    tr = Trainer(cfg2)
    assert tr.start_epoch == 1
    assert tr.best_acc1 > 0.0
    assert int(jax.device_get(tr.state.step)) > 0


def test_interrupt_saves_resumable_checkpoint(tmp_path, monkeypatch):
    """Ctrl-C mid-training leaves a checkpoint (reference lost the run)."""
    import pytest
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg = TrainConfig(dataset="synthetic-mnist", arch="lenet", epochs=3,
                      batch_size=64, synth_train_size=128, synth_val_size=64,
                      seed=1, print_freq=100, checkpoint_dir=str(tmp_path))
    tr = Trainer(cfg)
    monkeypatch.setattr(tr, "train_epoch",
                        lambda epoch: (_ for _ in ()).throw(KeyboardInterrupt))
    with pytest.raises(KeyboardInterrupt):
        tr.fit()
    assert os.path.exists(os.path.join(str(tmp_path),
                                       "lenet-checkpoint.msgpack"))


def _lm_state():
    import jax.numpy as jnp
    from tpu_dist.models.transformer import tiny_lm

    lm = tiny_lm(vocab_size=64, num_layers=2, d_model=64, num_heads=4,
                 max_len=32)
    params = lm.init({"params": jax.random.PRNGKey(0)},
                     jnp.zeros((1, 32), jnp.int32), train=False)["params"]
    tx = make_optimizer(0.01, 0.9, 0.0, steps_per_epoch=10)
    return TrainState.create(params, {}, tx)


def _assert_states_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb) > 0
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(jax.device_get(x)),
                                      np.asarray(jax.device_get(y)))


def test_fsdp_sharded_state_roundtrip(tmp_path):
    """ZeRO-3-placed TrainState saves as the full global state and restores."""
    from tpu_dist.parallel.fsdp import shard_state_fsdp
    from tpu_dist.parallel.mesh import make_mesh

    mesh = make_mesh((8,), ("data",))
    ref = _lm_state()
    sharded = shard_state_fsdp(mesh, ref, min_size=256)
    path = ckpt.save_checkpoint(str(tmp_path), sharded, epoch=1, best_acc1=0.0,
                                arch="lm", is_best=False)
    restored, _ = ckpt.load_checkpoint(path, _lm_state())
    _assert_states_equal(ref.params, restored.params)
    _assert_states_equal(ref.opt_state, restored.opt_state)
    # and the restored host state re-places cleanly
    shard_state_fsdp(mesh, restored, min_size=256)


def test_tp_sharded_state_roundtrip(tmp_path):
    """Megatron-sharded params save as the full global state and restore."""
    from tpu_dist.parallel.mesh import make_mesh
    from tpu_dist.parallel.tp import shard_lm_params

    mesh = make_mesh((4, 2), ("data", "model"))
    ref = _lm_state()
    sharded = TrainState(step=ref.step,
                         params=shard_lm_params(mesh, ref.params),
                         batch_stats={}, opt_state=ref.opt_state,
                         loss_scale=None)
    path = ckpt.save_checkpoint(str(tmp_path), sharded, epoch=1, best_acc1=0.0,
                                arch="lm", is_best=False)
    restored, _ = ckpt.load_checkpoint(path, _lm_state())
    _assert_states_equal(ref.params, restored.params)


def test_mid_epoch_resume_rejects_changed_geometry(tmp_path):
    """A mid-epoch checkpoint + different --batch-size must fail loudly, not
    silently double-apply/skip batches (ADVICE r1 medium)."""
    import pytest
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    kw = dict(dataset="synthetic-mnist", arch="lenet", epochs=1,
              synth_train_size=256, synth_val_size=64, seed=3, print_freq=100,
              checkpoint_dir=str(tmp_path))
    tr = Trainer(TrainConfig(batch_size=64, **kw))
    real_step = tr.train_step

    def limited(*a, **k):
        if limited.n == 2:
            raise KeyboardInterrupt
        limited.n += 1
        return real_step(*a, **k)

    limited.n = 0
    tr.train_step = limited
    with pytest.raises(KeyboardInterrupt):
        tr.fit()
    ck = os.path.join(str(tmp_path), "lenet-checkpoint.msgpack")
    with pytest.raises(ValueError, match="geometry"):
        Trainer(TrainConfig(batch_size=32, resume=ck, **kw))
    # same geometry still resumes fine
    assert Trainer(TrainConfig(batch_size=64, resume=ck,
                               **kw))._skip_batches == 2


def test_mid_epoch_resume_is_step_exact(tmp_path):
    """Interrupt mid-epoch, resume -> final params == uninterrupted run."""
    import pytest
    import numpy as np
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    kw = dict(dataset="synthetic-mnist", arch="lenet", epochs=1,
              batch_size=64, synth_train_size=512, synth_val_size=64,
              seed=7, print_freq=100)

    # uninterrupted baseline
    tr_full = Trainer(TrainConfig(checkpoint_dir=str(tmp_path / "full"), **kw))
    tr_full.fit()

    # interrupted run: stop after 3 of 8 batches via a limited step wrapper
    tr_int = Trainer(TrainConfig(checkpoint_dir=str(tmp_path / "int"), **kw))
    real_step = tr_int.train_step
    calls = {"n": 0}

    def limited(*a, **k):
        if calls["n"] == 3:
            raise KeyboardInterrupt
        calls["n"] += 1
        return real_step(*a, **k)

    tr_int.train_step = limited
    with pytest.raises(KeyboardInterrupt):
        tr_int.fit()

    ck = os.path.join(str(tmp_path / "int"), "lenet-checkpoint.msgpack")
    tr_res = Trainer(TrainConfig(checkpoint_dir=str(tmp_path / "res"),
                                 resume=ck, **kw))
    assert tr_res.start_epoch == 0
    assert tr_res._skip_batches == 3
    tr_res.fit()

    fa = np.concatenate([np.asarray(x).ravel()
                         for x in jax.tree.leaves(tr_full.state.params)])
    fb = np.concatenate([np.asarray(x).ravel()
                         for x in jax.tree.leaves(tr_res.state.params)])
    np.testing.assert_array_equal(fa, fb)


def test_async_save_roundtrip(tmp_path):
    """async_write defers serialization/IO; after wait_for_async_save the
    file is complete, loadable, and identical to a sync save."""
    state = _state()
    p_async = ckpt.save_checkpoint(str(tmp_path / "a"), state, epoch=2,
                                   best_acc1=0.25, arch="lenet",
                                   is_best=True, async_write=True)
    ckpt.wait_for_async_save()
    p_sync = ckpt.save_checkpoint(str(tmp_path / "b"), state, epoch=2,
                                  best_acc1=0.25, arch="lenet", is_best=True)
    ra, ma = ckpt.load_checkpoint(p_async, _state())
    rs, ms = ckpt.load_checkpoint(p_sync, _state())
    assert ma == ms and ma["epoch"] == 2
    for a, b in zip(jax.tree.leaves(ra.params), jax.tree.leaves(rs.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # best copies exist for both
    assert os.path.exists(os.path.join(str(tmp_path / "a"),
                                       "lenet-model_best.msgpack"))


def test_async_then_sync_save_ordering(tmp_path):
    """A sync save right after an async one joins the writer first — the
    final file on disk is the SECOND state, never a torn mix."""
    s1, s2 = _state(), _state()
    s2 = s2.replace(step=s2.step + 7)
    d = str(tmp_path)
    ckpt.save_checkpoint(d, s1, 1, 0.0, "lenet", False, async_write=True)
    ckpt.save_checkpoint(d, s2, 2, 0.0, "lenet", False)
    _, meta = ckpt.load_checkpoint(os.path.join(d, "lenet-checkpoint.msgpack"),
                                   _state())
    assert meta["epoch"] == 2 and meta["step"] == 7


def test_async_save_error_surfaces(tmp_path):
    """A failing background write raises at the next wait/save, not never."""
    import pytest
    state = _state()
    target = str(tmp_path / "d")
    ckpt.save_checkpoint(target, state, 1, 0.0, "lenet", False,
                         async_write=True)
    ckpt.wait_for_async_save()  # first write fine
    # squat a DIRECTORY on the tmp filename: the writer's open() must fail
    # (root ignores permission bits, so chmod tricks don't work here)
    tmp_name = os.path.join(target, "lenet-checkpoint.msgpack.tmp")
    os.makedirs(tmp_name)
    try:
        ckpt.save_checkpoint(target, state, 2, 0.0, "lenet", False,
                             async_write=True)
        with pytest.raises(RuntimeError, match="async checkpoint write"):
            ckpt.wait_for_async_save()
    finally:
        os.rmdir(tmp_name)


def test_pretrained_warm_start_loads_params(tmp_path):
    """--pretrained PATH grafts checkpoint params onto a fresh trainer
    (reference 1.dataparallel.py:97-102's capability, local-file form):
    params match the donor, optimizer state and step are FRESH."""
    import numpy as np

    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    kw = dict(dataset="synthetic-mnist", arch="lenet", epochs=1,
              batch_size=64, synth_train_size=256, synth_val_size=64,
              seed=1, print_freq=100)
    Trainer(TrainConfig(checkpoint_dir=str(tmp_path), **kw)).fit()
    ck = os.path.join(str(tmp_path), "lenet-checkpoint.msgpack")

    tr = Trainer(TrainConfig(pretrained=ck, **kw))
    from tpu_dist.engine.checkpoint import load_warmstart
    donor_params, donor_stats, _ = load_warmstart(ck)
    got = jax.device_get(tr.state.params)
    from flax import traverse_util
    flat_got = traverse_util.flatten_dict(got)
    flat_donor = traverse_util.flatten_dict(donor_params)
    assert set(flat_got) == set(flat_donor)
    for k, a in flat_got.items():
        np.testing.assert_array_equal(np.asarray(a),
                                      np.asarray(flat_donor[k]), err_msg=str(k))
    assert int(jax.device_get(tr.state.step)) == 0  # fresh trajectory


def test_graft_params_keeps_fresh_head_on_shape_mismatch():
    """Donor at 10 classes, target at 3: every tensor grafts except the
    classifier head, which keeps its fresh init (the fine-tune contract)."""
    import numpy as np

    from tpu_dist.engine.checkpoint import graft_params

    fresh = {"conv1": {"kernel": np.zeros((3, 3, 3, 8), np.float32)},
             "fc": {"kernel": np.zeros((8, 3), np.float32),
                    "bias": np.zeros((3,), np.float32)}}
    donor = {"conv1": {"kernel": np.ones((3, 3, 3, 8), np.float32)},
             "fc": {"kernel": np.ones((8, 10), np.float32),
                    "bias": np.ones((10,), np.float32)},
             "extra": {"kernel": np.ones((4,), np.float32)}}
    out, n, skipped = graft_params(fresh, donor)
    assert n == 1
    np.testing.assert_array_equal(out["conv1"]["kernel"],
                                  donor["conv1"]["kernel"])
    np.testing.assert_array_equal(out["fc"]["kernel"], fresh["fc"]["kernel"])
    assert sorted(skipped) == ["fc/bias", "fc/kernel"]


def test_pretrained_missing_file_errors(tmp_path):
    import pytest

    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    with pytest.raises(FileNotFoundError, match="pretrained"):
        Trainer(TrainConfig(dataset="synthetic-mnist", arch="lenet",
                            batch_size=64, synth_train_size=64,
                            synth_val_size=64,
                            pretrained=str(tmp_path / "nope.msgpack")))


def test_pretrained_warm_start_lm(tmp_path):
    """LMTrainer --pretrained: params graft, fresh trajectory."""
    import numpy as np

    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    kw = dict(vocab_size=64, seq_len=32, d_model=32,
              num_layers=1, num_heads=2, batch_size=16, epochs=1,
              synth_tokens=2048, seed=0, print_freq=100)
    LMTrainer(LMConfig(checkpoint_dir=str(tmp_path), **kw)).fit()
    ck = os.path.join(str(tmp_path), "lm-checkpoint.msgpack")
    assert os.path.exists(ck)

    tr = LMTrainer(LMConfig(pretrained=ck, **kw))
    from tpu_dist.engine.checkpoint import load_warmstart
    donor, _, _ = load_warmstart(ck)
    got = jax.device_get(tr.state.params)
    np.testing.assert_array_equal(
        np.asarray(got["tok_emb"]["embedding"]),
        np.asarray(donor["tok_emb"]["embedding"]))
    assert int(jax.device_get(tr.state.step)) == 0
