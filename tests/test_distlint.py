"""distlint: rule fixtures, suppressions, JSON output, and the tier-1
clean-tree pin.

Every rule must flag its bad fixture (tests/fixtures/distlint/dlNNN_bad.py)
and stay silent on the good twin — a rule that cannot fire is worse than no
rule, because it pins a false "clean". The fixtures directory is excluded
from directory walks (distlint SKIP_DIRS), so the clean-tree sweep below
never sees the deliberate violations; fixtures are linted by explicit file
path only.

No jax import anywhere in this file: distlint is stdlib-only by contract,
and this suite must stay cheap inside the tier-1 budget.
"""

import json
import os
import subprocess
import sys

import pytest

from tools.distlint import RULES, lint_files, load_mesh_axes
from tools.distlint.core import (REPO_ROOT, load_callgraph,
                                 parse_suppressions)
from tools.distlint.report import (collect_debt, severity_of, to_sarif)
from tools.distlint.__main__ import main as distlint_main

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "distlint")
RULE_IDS = [r.id for r in RULES]

SURFACE = ["tpu_dist", "tools", "tests", "scripts", "bench.py"]
_FULL: list = []   # memoized full-surface lint (the most expensive call
#                    here — the pin test and the debt test share one run)


def _full_lint():
    if not _FULL:
        _FULL.append(lint_files(SURFACE))
    return _FULL[0]

# every rule must produce EXACTLY this many findings on its bad fixture —
# an extra finding is a false positive creeping into the rule, a missing
# one is a detection regression; both should fail loudly here
EXPECTED_BAD_COUNTS = {"DL001": 2, "DL002": 3, "DL003": 3,
                       "DL004": 4, "DL005": 3, "DL006": 19, "DL007": 2,
                       "DL008": 2,
                       "DL101": 1, "DL102": 2, "DL103": 2, "DL104": 3,
                       "DL201": 4}


def lint_fixture(name: str, rule_id: str):
    return lint_files([os.path.join(FIXTURES, name)], select=[rule_id])


# ------------------------------------------------------------ rule pairs
@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_fires_on_bad_fixture(rule_id):
    res = lint_fixture(f"dl{rule_id[2:]}_bad.py", rule_id)
    assert len(res.findings) == EXPECTED_BAD_COUNTS[rule_id], \
        [f.render() for f in res.findings]
    for f in res.findings:
        assert f.rule == rule_id
        assert f.line > 0 and f.message
        assert f.path.endswith(f"dl{rule_id[2:]}_bad.py")


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_silent_on_good_fixture(rule_id):
    res = lint_fixture(f"dl{rule_id[2:]}_good.py", rule_id)
    assert res.findings == [], [f.render() for f in res.findings]


def test_rules_have_distinct_ids_and_docs():
    assert len(RULE_IDS) == len(set(RULE_IDS)) >= 13
    for r in RULES:
        assert r.title and r.rationale
        assert getattr(r, "severity", None) in ("error", "warn")


# ----------------------------------------------------------- suppression
def _write(tmp_path, text):
    p = tmp_path / "snippet.py"
    p.write_text(text)
    return str(p)


BAD_LOOP = ("import jax\n"
            "def train_epoch(it, step, state):\n"
            "    for b in it:\n"
            "        state, m = step(state, b)\n"
            "        jax.device_get(m){}\n"
            "    return state\n")


def test_trailing_suppression_with_reason(tmp_path):
    path = _write(tmp_path, BAD_LOOP.format(
        "  # distlint: disable=DL002 -- test: deliberate sync"))
    res = lint_files([path], select=["DL002"])
    assert res.findings == []
    ((finding, sup),) = res.suppressed
    assert finding.rule == "DL002" and sup.reason == "test: deliberate sync"


def test_standalone_suppression_applies_to_next_line(tmp_path):
    lines = BAD_LOOP.format("").splitlines()
    lines.insert(4, "        # distlint: disable=DL002 -- test: deliberate "
                    "sync on next line")
    path = _write(tmp_path, "\n".join(lines) + "\n")
    res = lint_files([path], select=["DL002"])
    assert res.findings == []
    assert len(res.suppressed) == 1


def test_suppression_without_reason_is_itself_a_finding(tmp_path):
    path = _write(tmp_path, BAD_LOOP.format(
        "  # distlint: disable=DL002"))
    res = lint_files([path], select=["DL002"])
    rules = sorted(f.rule for f in res.findings)
    # the reasonless disable does NOT suppress, and is flagged as DL000
    assert rules == ["DL000", "DL002"], [f.render() for f in res.findings]
    assert "reason" in next(f for f in res.findings
                            if f.rule == "DL000").message


def test_suppression_for_other_rule_does_not_apply(tmp_path):
    path = _write(tmp_path, BAD_LOOP.format(
        "  # distlint: disable=DL001 -- wrong rule id"))
    res = lint_files([path], select=["DL002"])
    assert [f.rule for f in res.findings] == ["DL002"]
    assert res.suppressed == []


def test_multi_rule_suppression_parses():
    sups, malformed = parse_suppressions(
        "x = 1  # distlint: disable=DL001,DL005 -- both rules, one reason\n")
    assert malformed == []
    assert sups[0].rules == ("DL001", "DL005")
    assert sups[0].line == 1


def test_prose_mentioning_distlint_is_not_a_directive():
    sups, malformed = parse_suppressions(
        "# this comment mentions distlint casually, not as a directive\n"
        "x = 1\n")
    assert sups == [] and malformed == []


def test_unparseable_file_is_reported_not_crashed(tmp_path):
    path = _write(tmp_path, "def broken(:\n")
    res = lint_files([path])
    assert [f.rule for f in res.findings] == ["DL000"]
    assert "unparseable" in res.findings[0].message


# ------------------------------------------------------------ CLI + JSON
def test_cli_json_round_trip(tmp_path, capsys):
    bad = os.path.join(FIXTURES, "dl003_bad.py")
    rc = distlint_main(["--json", "--select", "DL003", bad])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    api = lint_files([bad], select=["DL003"])
    assert payload["findings"] == [f.to_json() for f in api.findings]
    assert payload["files_checked"] == 1
    for f in payload["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message"}


def test_cli_exit_codes(capsys):
    assert distlint_main(["--select", "DL001",
                          os.path.join(FIXTURES, "dl001_good.py")]) == 0
    assert distlint_main(["--select", "DL001",
                          os.path.join(FIXTURES, "dl001_bad.py")]) == 1
    assert distlint_main(["--select", "DL999", "tools"]) == 2
    assert distlint_main(["--list-rules"]) == 0
    capsys.readouterr()


def test_cli_module_entry_point():
    """`python -m tools.distlint` works from the repo root (no jax)."""
    out = subprocess.run(
        [sys.executable, "-m", "tools.distlint", "--list-rules"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    for rid in RULE_IDS:
        assert rid in out.stdout


# --------------------------------------------- review-found regressions
def test_dl004_factory_host_side_build_code_is_not_flagged(tmp_path):
    """jit(make_step(...)) traces what the factory RETURNS; the factory's
    own body is host-side build code and may print/time freely."""
    path = _write(tmp_path, (
        "import time\n"
        "import jax\n"
        "def make_step(cfg):\n"
        "    print('building', cfg)\n"          # host side: legal
        "    t0 = time.time()\n"                # host side: legal
        "    def step(state, batch):\n"
        "        print('stepping')\n"           # traced: flagged
        "        return state\n"
        "    return step\n"
        "train = jax.jit(make_step(1))\n"))
    res = lint_files([path], select=["DL004"])
    assert len(res.findings) == 1, [f.render() for f in res.findings]
    assert res.findings[0].line == 7


def test_dl001_function_defined_under_guard_is_not_flagged(tmp_path):
    """A function merely DEFINED under a divergent guard may be called on
    every host — only calls executing under the guard are hazards."""
    path = _write(tmp_path, (
        "import jax\n"
        "def setup():\n"
        "    if jax.process_index() == 0:\n"
        "        def helper(x):\n"
        "            return jax.lax.psum(x, 'data')\n"
        "        return helper\n"
        "    return None\n"))
    res = lint_files([path], select=["DL001"])
    assert res.findings == [], [f.render() for f in res.findings]


def test_dl001_guarded_return_inside_with_block_propagates(tmp_path):
    """A process_index-guarded early return inside a with/try block makes
    the code after that block host-divergent too."""
    path = _write(tmp_path, (
        "import jax\n"
        "def save(state, sharding, batch, f):\n"
        "    with open(f) as fh:\n"
        "        if jax.process_index() != 0:\n"
        "            return None\n"
        "    from tpu_dist.data import assemble_global\n"
        "    return assemble_global(sharding, batch)\n"))
    res = lint_files([path], select=["DL001"])
    assert [f.rule for f in res.findings] == ["DL001"]


def test_dl003_axis_index_first_positional_arg(tmp_path):
    path = _write(tmp_path, (
        "import jax\n"
        "def idx():\n"
        "    good = jax.lax.axis_index('data')\n"
        "    return good + jax.lax.axis_index('modle')\n"))
    res = lint_files([path], select=["DL003"])
    assert len(res.findings) == 1 and "modle" in res.findings[0].message


def test_dl005_stdlib_rng_through_alias_and_from_import(tmp_path):
    path = _write(tmp_path, (
        "import random as rnd\n"
        "from random import randint\n"
        "def draw():\n"
        "    return rnd.random() + randint(0, 3)\n"))
    res = lint_files([path], select=["DL005"])
    assert len(res.findings) == 2, [f.render() for f in res.findings]


def test_shim_check_file_honors_dl006_suppressions(tmp_path):
    from tools.check_ledger_schema import check_file, load_schema
    p = tmp_path / "emits.py"
    p.write_text(
        "ledger.emit('bogus', x=1)  "
        "# distlint: disable=DL006 -- test: deliberately undeclared\n"
        "ledger.emit('also_bogus', x=1)\n")
    out = check_file(str(p), load_schema(), "emits.py")
    assert len(out) == 1 and "also_bogus" in out[0]


def test_trailing_suppression_on_multiline_statement(tmp_path):
    """A formatter may wrap the flagged call across lines, leaving the
    trailing comment on a continuation line; the suppression must still
    cover the whole statement (findings anchor to the first line)."""
    path = _write(tmp_path, (
        "import jax\n"
        "def train_epoch(it, step, state):\n"
        "    for b in it:\n"
        "        state, m = step(state, b)\n"
        "        jax.device_get(\n"
        "            m)  # distlint: disable=DL002 -- test: deliberate sync\n"
        "    return state\n"))
    res = lint_files([path], select=["DL002"])
    assert res.findings == [] and len(res.suppressed) == 1


def test_dl002_closure_seam_pair():
    """The old false negative (satellite of PR 8): a .item() inside a
    nested def called from the hot loop escaped the lexical scan; the
    reachability pass flags it, and the queue-then-drain twin stays
    silent."""
    bad = lint_files([os.path.join(FIXTURES, "dl002_closure_bad.py")],
                     select=["DL002"])
    assert len(bad.findings) == 1, [f.render() for f in bad.findings]
    assert ".item()" in bad.findings[0].message
    assert "reachable" in bad.findings[0].message
    good = lint_files([os.path.join(FIXTURES, "dl002_closure_good.py")],
                      select=["DL002"])
    assert good.findings == [], [f.render() for f in good.findings]


def test_dl201_branch_order_pair():
    """PR 18's source-level MPI-matching prover: cond/switch branches
    whose ordered collective sequences diverge are flagged (helper refs
    resolve through the call graph, lambdas and partial() heads inline),
    while identical sequences, collective-free branches (the pp.py
    gating shape), the padded-zero-operand fix, and dynamically built
    branch lists all stay silent."""
    bad = lint_files([os.path.join(FIXTURES, "dl201_bad.py")],
                     select=["DL201"])
    assert len(bad.findings) == 4, [f.render() for f in bad.findings]
    msgs = [f.message for f in bad.findings]
    # the asymmetric-order shape renders BOTH sequences, in order
    assert any("psum(data) -> pmax(data)" in m
               and "pmax(data) -> psum(data)" in m for m in msgs), msgs
    # the one-armed shape names the silent arm explicitly
    assert any("[no collectives]" in m for m in msgs)
    good = lint_files([os.path.join(FIXTURES, "dl201_good.py")],
                      select=["DL201"])
    assert good.findings == [], [f.render() for f in good.findings]
    # the real pipeline engine leans on per-device lax.cond gating with
    # collectives hoisted OUTSIDE the cond — it must stay clean
    shipped = lint_files([os.path.join("tpu_dist", "parallel", "pp.py")],
                         select=["DL201"])
    assert shipped.findings == [], [f.render() for f in shipped.findings]


def test_dl003_serve_era_spellings_pair():
    """Satellite of PR 18: the axis authority extends to the serving /
    spec-decode spellings added since PR 8 — mesh.shape["axis"] string
    subscripts and axis_size() first-positional axis names — while int
    array-.shape subscripts and dynamic keys stay silent."""
    bad = lint_files([os.path.join(FIXTURES, "dl003_serve_bad.py")],
                     select=["DL003"])
    assert len(bad.findings) == 2, [f.render() for f in bad.findings]
    assert any("mesh.shape[...]" in f.message and "modle" in f.message
               for f in bad.findings)
    assert any("axis_size()" in f.message and "dataa" in f.message
               for f in bad.findings)
    good = lint_files([os.path.join(FIXTURES, "dl003_serve_good.py")],
                      select=["DL003"])
    assert good.findings == [], [f.render() for f in good.findings]


def test_dl003_sp_axis_spellings_pair():
    """Satellite of PR 19: the 'sp' serving-sequence-parallel axis joined
    the parallel/mesh.py authority, so the sharded-pool call-site shapes
    (gather psum, axis_index ownership tests, mesh.shape sizing, arena
    PartitionSpec) lint clean when spelled 'sp' and fire on every typo."""
    bad = lint_files([os.path.join(FIXTURES, "dl003_sp_bad.py")],
                     select=["DL003"])
    assert len(bad.findings) == 4, [f.render() for f in bad.findings]
    for typo in ("spp", "sp_serve", "sq", "spd"):
        assert any(typo in f.message for f in bad.findings), typo
    good = lint_files([os.path.join(FIXTURES, "dl003_sp_good.py")],
                      select=["DL003"])
    assert good.findings == [], [f.render() for f in good.findings]


def test_dl101_pr5_ledger_sigterm_regression():
    """THE acceptance fixture: the PR-5 plain-Lock-in-SIGTERM-handler
    deadlock shape is flagged, and the shipped RLock fix shape is not —
    both as fixtures and in the real tree (obs/ledger.py)."""
    bad = lint_files([os.path.join(FIXTURES, "dl101_bad.py")],
                     select=["DL101"])
    assert len(bad.findings) == 1, [f.render() for f in bad.findings]
    assert "RLock" in bad.findings[0].message
    good = lint_files([os.path.join(FIXTURES, "dl101_good.py")],
                      select=["DL101"])
    assert good.findings == [], [f.render() for f in good.findings]
    shipped = lint_files([os.path.join("tpu_dist", "obs", "ledger.py"),
                          os.path.join("tpu_dist", "obs", "goodput.py"),
                          os.path.join("tpu_dist", "obs", "metrics.py")],
                         select=["DL101"])
    assert shipped.findings == [], [f.render() for f in shipped.findings]


# ------------------------------------------------------------- call graph
def test_callgraph_typed_attribute_resolution():
    """RunObs.__init__ assigns self.goodput = GoodputMonitor(...), so the
    SIGTERM handler's run_end -> self.goodput.emit_goodput chain resolves
    precisely — the edge the PR-5-class deadlock detection rides."""
    g = load_callgraph()
    hr = g.handler_reachable()
    assert "tpu_dist/obs/__init__.py::RunObs.run_end" in hr
    assert "tpu_dist/obs/goodput.py::GoodputMonitor.emit_goodput" in hr
    assert "tpu_dist/obs/ledger.py::Ledger.emit" in hr
    # watchdog pause/resume are NOT on the handler path: precision check
    assert "tpu_dist/obs/watchdog.py::Watchdog.pause" not in hr


def test_callgraph_jit_factory_fixpoint():
    """Step builders returning jax.jit(...) products are factories, so
    self.train_step = make_train_step(...) resolves to a traced handle
    and the engines' loops derive as hot without any hard-coded list."""
    g = load_callgraph()
    assert "tpu_dist/engine/steps.py::make_train_step" in g._jit_factories()
    rt = g.reaches_traced()
    for fn in ("train_epoch", "_train_epoch_windowed", "_fit_epochs",
               "validate"):
        assert f"tpu_dist/engine/loop.py::Trainer.{fn}" in rt, fn


def test_callgraph_alias_and_import_resolution(tmp_path):
    """import-alias and from-import heads resolve; an out-of-surface file
    is added for the query and removed afterwards (isolation)."""
    p = tmp_path / "snippet.py"
    p.write_text(
        "from tpu_dist.engine.checkpoint import save_checkpoint\n"
        "import tpu_dist.engine.checkpoint as ck\n"
        "def a():\n"
        "    save_checkpoint('d', None, 0, 0.0, 'x', False)\n"
        "def b():\n"
        "    ck.wait_for_async_save()\n")
    g = load_callgraph()
    import ast
    rel = os.path.relpath(str(p), g.root).replace(os.sep, "/")
    added = g.ensure_file(rel, tree=ast.parse(p.read_text()))
    try:
        node_a = g.funcs[f"{rel}::a"]
        targets, _ = g.resolve(node_a, "save_checkpoint")
        assert targets == (
            "tpu_dist/engine/checkpoint.py::save_checkpoint",)
        node_b = g.funcs[f"{rel}::b"]
        targets, _ = g.resolve(node_b, "ck.wait_for_async_save")
        assert targets == (
            "tpu_dist/engine/checkpoint.py::wait_for_async_save",)
    finally:
        if added:
            g.remove_file(rel)
    assert f"{rel}::a" not in g.funcs   # isolation: no leak into the graph


def test_fallback_never_resolves_into_overlay_files():
    """Order independence: by-name fallback from a BASE file must not
    land in a fixture overlay's methods, or a fixture's finding count
    would depend on which edges were cached first (review-found bug: the
    untyped `self._ledger.emit` fallback linked GoodputMonitor into the
    DL101 fixture's Recorder.emit, doubling its findings when the
    fixture was linted in a fresh process)."""
    fix = os.path.join(FIXTURES, "dl101_bad.py")
    first = lint_files([fix], select=["DL101"])
    lint_files(["tpu_dist/obs"])          # populate base edge caches
    again = lint_files([fix], select=["DL101"])
    assert len(first.findings) == len(again.findings) == 1, (
        [f.render() for f in first.findings],
        [f.render() for f in again.findings])


def test_self_referential_local_assignment_does_not_recurse(tmp_path):
    """Review-found crash: `x = x()` (or a=b(); b=a()) made resolve()/
    _resolve_bare() mutually recurse without bound, killing the whole
    lint run with RecursionError via DL002's edge computation."""
    p = tmp_path / "selfref.py"
    p.write_text(
        "import jax\n"
        "step = jax.jit(lambda s: s)\n"
        "def weird():\n"
        "    x = x()\n"
        "    a = b()\n"
        "    b = a()\n"
        "    for _ in range(3):\n"
        "        step(x)\n"
        "        a()\n")
    res = lint_files([str(p)], select=["DL002"])   # must not crash
    assert isinstance(res.findings, list)


def test_remove_file_clears_class_attr_tables(tmp_path):
    """Review-found leak: the attr tables key on ((rel, cls), attr), so
    the old `k[0] == rel` filter never matched and overlay lock/type
    entries survived removal — stale DL101 classifications on re-lint."""
    import ast
    p = tmp_path / "locky.py"
    p.write_text(
        "import threading\n"
        "class R:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.helper = R()\n")
    g = load_callgraph()
    rel = os.path.relpath(str(p), g.root).replace(os.sep, "/")
    added = g.ensure_file(rel, tree=ast.parse(p.read_text()))
    assert added
    assert any(k[0][0] == rel for k in g.lock_attrs)
    g.remove_file(rel)
    assert not any(k[0][0] == rel for k in g.lock_attrs)
    assert not any(k[0][0] == rel for k in g.attr_types)
    assert not any(k[0][0] == rel for k in g.attr_assign_calls)


def test_dl101_class_attribute_lock_form(tmp_path):
    """Review-found blind spot: `_lock = threading.Lock()` declared in
    the CLASS BODY (not __init__) was recorded as a module-local
    variable, so DL101 went silent on that spelling of the exact PR-5
    deadlock shape."""
    with open(os.path.join(FIXTURES, "dl101_bad.py")) as f:
        src = f.read()
    lines = src.replace(
        "self._lock = threading.Lock()", "pass").splitlines()
    at = next(i for i, l in enumerate(lines) if l.startswith("class "))
    lines.insert(at + 1, "    _lock = threading.Lock()   # class-attr form")
    p = tmp_path / "cls_lock_bad.py"
    p.write_text("\n".join(lines) + "\n")
    res = lint_files([str(p)], select=["DL101"])
    assert len(res.findings) == 1, [f.render() for f in res.findings]
    assert "RLock" in res.findings[0].message


def test_ensure_file_reindexes_changed_source(tmp_path):
    """Review-found staleness: the process-cached graph ignored the
    fresh tree when a rel was already indexed, so a same-process re-lint
    of a file that changed on disk served facts — and finding line
    numbers — from the old parse."""
    import ast
    g = load_callgraph()
    p = tmp_path / "w.py"
    src1 = ("import threading\n"
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n")
    rel = os.path.relpath(str(p), g.root).replace(os.sep, "/")
    added = g.ensure_file(rel, tree=ast.parse(src1), src=src1)
    assert added
    try:
        assert g.lock_attrs.get(((rel, "R"), "_lock")) == "Lock"
        src2 = src1.replace("threading.Lock()", "threading.RLock()")
        # same rel, changed content: re-indexed in place, still an
        # overlay owned by the original caller (returns False)
        assert g.ensure_file(rel, tree=ast.parse(src2), src=src2) is False
        assert g.lock_attrs.get(((rel, "R"), "_lock")) == "RLock"
        assert rel in g.overlay_files
        # unchanged content: cheap no-op, no version bump
        v = g._version
        g.ensure_file(rel, tree=ast.parse(src2), src=src2)
        assert g._version == v
    finally:
        g.remove_file(rel)
    assert not any(k[0][0] == rel for k in g.lock_attrs)


def test_callgraph_cycle_tolerance(tmp_path):
    """Mutually recursive functions must not hang reachability."""
    p = tmp_path / "cyc.py"
    p.write_text(
        "import signal\n"
        "def ping():\n"
        "    pong()\n"
        "def pong():\n"
        "    ping()\n"
        "def handler(s, f):\n"
        "    ping()\n"
        "signal.signal(signal.SIGTERM, handler)\n")
    g = load_callgraph()
    import ast
    rel = os.path.relpath(str(p), g.root).replace(os.sep, "/")
    added = g.ensure_file(rel, tree=ast.parse(p.read_text()))
    try:
        reach = g.reachable_from([f"{rel}::handler"])
        assert {f"{rel}::handler", f"{rel}::ping", f"{rel}::pong"} <= reach
    finally:
        if added:
            g.remove_file(rel)


def test_dl001_tensor_rank_comparison_is_not_divergent(tmp_path):
    path = _write(tmp_path, (
        "import jax\n"
        "def reduce_if_matrix(t, x):\n"
        "    if t.rank == 2:\n"                    # tensor rank, not process
        "        return jax.lax.psum(x, 'data')\n"
        "    return x\n"
        "def main_only(rank, sharding, batch):\n"
        "    from tpu_dist.data import assemble_global\n"
        "    if rank == 0:\n"                      # bare rank: process guard
        "        return assemble_global(sharding, batch)\n"))
    res = lint_files([path], select=["DL001"])
    assert len(res.findings) == 1 and res.findings[0].line == 9


# ------------------------------------------------------- tree invariants
def test_mesh_axes_authority_loaded():
    axes = load_mesh_axes()
    assert {"data", "fsdp", "model", "seq", "stage", "expert"} <= axes


def test_tree_is_clean():
    """THE tier-1 pin: zero unsuppressed findings across the FULL
    acceptance surface — tpu_dist, tools (the linter lints itself),
    tests, scripts, bench.py — with ALL rules (old + DL007 + DL1xx), and
    every suppression carries a reason."""
    res = _full_lint()
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    for finding, sup in res.suppressed:
        assert sup.reason.strip(), finding.render()


# ------------------------------------------------- SARIF / severity / debt
def test_sarif_minimal_schema_shape():
    """`--format sarif` emits valid minimal SARIF 2.1.0: version, one
    run, the rule catalog as tool metadata, results with 1-based
    regions."""
    res = lint_files([os.path.join(FIXTURES, "dl003_bad.py")],
                     select=["DL003"])
    doc = to_sarif(res)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-2.1.0.json")
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "distlint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert set(RULE_IDS) | {"DL000"} <= rule_ids
    assert len(run["results"]) == len(res.findings) == 3
    for r in run["results"]:
        assert r["ruleId"] == "DL003"
        assert r["level"] == "error"
        assert r["message"]["text"]
        region = r["locations"][0]["physicalLocation"]["region"]
        assert region["startLine"] >= 1 and region["startColumn"] >= 1
        uri = r["locations"][0]["physicalLocation"]["artifactLocation"]
        assert uri["uri"].endswith("dl003_bad.py")


def test_sarif_golden_snapshot():
    """Byte-level SARIF pin (satellite of PR 18): the structural checks
    above can't catch a field rename or an ordering regression that
    still satisfies the schema — CI dashboards parse these artifacts, so
    the exact serialization is contract. Regenerate deliberately with:
    python -c "import json,os; from tools.distlint import lint_files; \\
    from tools.distlint.report import to_sarif; print(json.dumps(
    to_sarif(lint_files([os.path.join('tests','fixtures','distlint',
    'dl003_bad.py')], select=['DL003'])), indent=2, sort_keys=True))"
    """
    res = lint_files([os.path.join(FIXTURES, "dl003_bad.py")],
                     select=["DL003"])
    got = json.dumps(to_sarif(res), indent=2, sort_keys=True) + "\n"
    with open(os.path.join(FIXTURES, "golden_dl003.sarif.json")) as f:
        want = f.read()
    assert got == want, ("SARIF serialization drifted from the golden "
                         "snapshot — if intentional, regenerate "
                         "tests/fixtures/distlint/golden_dl003.sarif.json")


def test_sarif_cli_and_artifact(tmp_path, capsys):
    out_file = str(tmp_path / "distlint.sarif")
    rc = distlint_main(["--format", "sarif", "--sarif-out", out_file,
                        "--select", "DL001",
                        os.path.join(FIXTURES, "dl001_bad.py")])
    assert rc == 1   # error-tier findings still gate
    stdout_doc = json.loads(capsys.readouterr().out)
    with open(out_file) as f:
        file_doc = json.load(f)
    assert stdout_doc == file_doc
    assert len(file_doc["runs"][0]["results"]) == 2


def test_severity_tiers_gate_errors_only(capsys):
    """warn-tier findings (DL102/DL103) print but exit 0; error-tier
    exits 1 — the contract scripts/lint.sh gates on."""
    assert severity_of("DL101") == "error"
    assert severity_of("DL102") == "warn"
    assert severity_of("DL103") == "warn"
    assert severity_of("DL000") == "error"
    rc_warn = distlint_main(["--select", "DL103",
                             os.path.join(FIXTURES, "dl103_bad.py")])
    out = capsys.readouterr().out
    assert rc_warn == 0
    assert "0 error(s), 2 warning(s)" in out
    rc_err = distlint_main(["--select", "DL101",
                            os.path.join(FIXTURES, "dl101_bad.py")])
    capsys.readouterr()
    assert rc_err == 1


def test_debt_inventory(tmp_path, capsys):
    """--debt inventories suppressions: per-rule counts, reasons, and
    staleness (a pin matching no finding is deletable debt)."""
    p = tmp_path / "pinned.py"
    p.write_text(
        "import jax\n"
        "train_step = jax.jit(lambda s, b: s)\n"
        "def train_epoch(it, state):\n"
        "    for b in it:\n"
        "        state, m = train_step(state, b)\n"
        "        jax.device_get(m)  "
        "# distlint: disable=DL002 -- test: deliberate sync\n"
        "    return state\n"
        "x = 1  # distlint: disable=DL005 -- stale: nothing to suppress\n")
    res = lint_files([str(p)])
    debt = collect_debt([str(p)], root=REPO_ROOT, result=res)
    assert debt["by_rule"] == {"DL002": 1, "DL005": 1}
    by_line = {e["line"]: e for e in debt["entries"]}
    active = by_line[6]
    stale = by_line[8]
    assert active["stale"] is False
    assert active["reason"] == "test: deliberate sync"
    assert stale["stale"] is True
    assert debt["stale"] == [stale]
    # CLI: advisory (exit 0) in both formats
    rc = distlint_main(["--debt", str(p)])
    out = capsys.readouterr().out
    assert rc == 0 and "2 suppression(s)" in out and "STALE" in out
    rc = distlint_main(["--debt", "--format", "json", str(p)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["by_rule"] == {"DL002": 1, "DL005": 1}


def test_debt_real_tree_has_no_stale_pins():
    """Every suppression in the tree matches a live finding — a pin that
    suppresses nothing is debt to delete, caught here not in review."""
    res = _full_lint()
    debt = collect_debt(SURFACE, root=REPO_ROOT, result=res,
                        with_ages=False)   # counts/staleness only: cheap
    assert debt["entries"], "expected the tree's reasoned pins"
    stale = [f"{e['path']}:{e['line']}" for e in debt["stale"]]
    assert not stale, f"stale suppressions (nothing to suppress): {stale}"


def test_dl007_rebind_and_branch_shapes(tmp_path):
    p = tmp_path / "donate.py"
    p.write_text(
        "import jax\n"
        "f = jax.jit(lambda s, b: s, donate_argnums=(0,))\n"
        "def good(state, batches):\n"
        "    for b in batches:\n"
        "        state = f(state, b)\n"       # rebind every iteration
        "    return state\n"
        "def bad(state, b):\n"
        "    out = f(state, b)\n"
        "    return out, state.step\n")       # reads the donated buffer
    res = lint_files([str(p)], select=["DL007"])
    assert len(res.findings) == 1, [x.render() for x in res.findings]
    assert res.findings[0].line == 9


def test_dl007_multiline_call_and_same_line_read(tmp_path):
    """Ordering is positional, not line-based: args on continuation
    lines of a multi-line donating call are NOT post-donation reads,
    while a same-line read past the closing paren IS."""
    p = tmp_path / "donate_pos.py"
    p.write_text(
        "import jax\n"
        "f: object = jax.jit(lambda s, b: s, donate_argnums=(0,))\n"
        "def ok(state, batch):\n"
        "    out = f(\n"
        "        state,\n"                    # inside the call span
        "        batch)\n"
        "    return out\n"
        "def bad(state, b):\n"
        "    return f(state, b), state.step\n")   # read after the paren
    res = lint_files([str(p)], select=["DL007"])
    assert len(res.findings) == 1, [x.render() for x in res.findings]
    assert res.findings[0].line == 9


def test_dl101_annotated_lock_attr(tmp_path):
    """`self._lock: threading.Lock = threading.Lock()` (AnnAssign) feeds
    lock_attrs exactly like the plain assign — the deadlock gate must
    not disappear when someone adds type annotations."""
    p = tmp_path / "ann_lock.py"
    p.write_text(
        "import signal\n"
        "import threading\n"
        "class Recorder:\n"
        "    def __init__(self):\n"
        "        self._lock: threading.Lock = threading.Lock()\n"
        "        self._rows: list = []\n"
        "        signal.signal(signal.SIGTERM, self._on_sigterm)\n"
        "    def emit(self, row):\n"
        "        with self._lock:\n"
        "            self._rows.append(row)\n"
        "    def finalize(self):\n"
        "        with self._lock:\n"
        "            self._rows.append('end')\n"
        "    def _on_sigterm(self, signum, frame):\n"
        "        self.finalize()\n")
    res = lint_files([str(p)], select=["DL101"])
    assert len(res.findings) == 1, [x.render() for x in res.findings]
    assert "RLock" in res.findings[0].message


def test_cli_debt_with_sarif_out_and_json_purity(tmp_path, capsys):
    """--sarif-out writes its artifact even under --debt, and --with-debt
    keeps machine-readable stdout clean (debt goes to stderr)."""
    out_file = str(tmp_path / "debt.sarif")
    rc = distlint_main(["--debt", "--sarif-out", out_file,
                        "--select", "DL001",
                        os.path.join(FIXTURES, "dl001_bad.py")])
    capsys.readouterr()
    assert rc == 0
    with open(out_file) as f:
        assert json.load(f)["version"] == "2.1.0"
    rc = distlint_main(["--format", "json", "--with-debt",
                        "--select", "DL001",
                        os.path.join(FIXTURES, "dl001_bad.py")])
    cap = capsys.readouterr()
    assert rc == 1
    assert json.loads(cap.out)["errors"] == 2   # stdout: pure JSON
    assert "distlint debt:" in cap.err


def test_dl002_module_level_hot_loop(tmp_path):
    """A top-level step loop is hot (the `<module>` pseudo-node joins
    the lexical scan AND seeds reachability for helpers it calls)."""
    p = tmp_path / "modloop.py"
    p.write_text(
        "import jax\n"
        "step = jax.jit(lambda s, b: s)\n"
        "def log(m):\n"
        "    return m['loss'].item()\n"       # reachable from the loop
        "state = 0\n"
        "for b in range(3):\n"
        "    state, m = step(state, b)\n"
        "    log(m)\n")
    res = lint_files([str(p)], select=["DL002"])
    assert [f.line for f in res.findings] == [4], \
        [x.render() for x in res.findings]


def test_cli_debt_select_does_not_mislabel_stale(capsys):
    """Staleness is only decidable against a full-rule result: under
    --select, live pins for unselected rules must NOT be called stale."""
    rc = distlint_main(["--debt", "--select", "DL001", "tpu_dist"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "STALE" not in out
    assert "distlint debt:" in out


def test_sarif_relative_uris_without_baseid_declaration():
    """Repo-relative artifact URIs with SRCROOT left undeclared (no
    originalUriBaseIds) — consumers resolve against their own checkout;
    declaring file:/// would point results at filesystem root."""
    res = lint_files([os.path.join(FIXTURES, "dl001_bad.py")],
                     select=["DL001"])
    run = to_sarif(res)["runs"][0]
    assert "originalUriBaseIds" not in run
    for r in run["results"]:
        loc = r["locations"][0]["physicalLocation"]["artifactLocation"]
        assert not loc["uri"].startswith("/")


def test_dl104_handler_body_in_file_not_mentioning_signal(tmp_path):
    """A handler whose body lives in an in-surface file that never says
    'signal' (installed from a sibling file) is still body-scanned — the
    text gate defers to the cross-file handler root set. (Out-of-surface
    files overlay one at a time by design, so the pair sits in a tmp
    project surface.)"""
    pkg = tmp_path / "tpu_dist"
    pkg.mkdir()
    (pkg / "handlers.py").write_text(
        "import logging\n"
        "def on_term(signum, frame):\n"
        "    logging.error('terminating')\n")
    (pkg / "installer.py").write_text(
        "import signal\n"
        "from tpu_dist import handlers\n"
        "def install():\n"
        "    signal.signal(signal.SIGTERM, handlers.on_term)\n")
    res = lint_files([str(pkg)], root=str(tmp_path), select=["DL104"])
    msgs = [f.render() for f in res.findings]
    assert any("logging call" in m and "handlers.py" in m for m in msgs), msgs
