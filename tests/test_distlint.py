"""distlint: rule fixtures, suppressions, JSON output, and the tier-1
clean-tree pin.

Every rule must flag its bad fixture (tests/fixtures/distlint/dlNNN_bad.py)
and stay silent on the good twin — a rule that cannot fire is worse than no
rule, because it pins a false "clean". The fixtures directory is excluded
from directory walks (distlint SKIP_DIRS), so the clean-tree sweep below
never sees the deliberate violations; fixtures are linted by explicit file
path only.

No jax import anywhere in this file: distlint is stdlib-only by contract,
and this suite must stay cheap inside the tier-1 budget.
"""

import json
import os
import subprocess
import sys

import pytest

from tools.distlint import RULES, lint_files, load_mesh_axes
from tools.distlint.core import REPO_ROOT, parse_suppressions
from tools.distlint.__main__ import main as distlint_main

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "distlint")
RULE_IDS = [r.id for r in RULES]

# every rule must produce EXACTLY this many findings on its bad fixture —
# an extra finding is a false positive creeping into the rule, a missing
# one is a detection regression; both should fail loudly here
EXPECTED_BAD_COUNTS = {"DL001": 2, "DL002": 3, "DL003": 3,
                       "DL004": 4, "DL005": 3, "DL006": 4}


def lint_fixture(name: str, rule_id: str):
    return lint_files([os.path.join(FIXTURES, name)], select=[rule_id])


# ------------------------------------------------------------ rule pairs
@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_fires_on_bad_fixture(rule_id):
    res = lint_fixture(f"dl{rule_id[2:]}_bad.py", rule_id)
    assert len(res.findings) == EXPECTED_BAD_COUNTS[rule_id], \
        [f.render() for f in res.findings]
    for f in res.findings:
        assert f.rule == rule_id
        assert f.line > 0 and f.message
        assert f.path.endswith(f"dl{rule_id[2:]}_bad.py")


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_silent_on_good_fixture(rule_id):
    res = lint_fixture(f"dl{rule_id[2:]}_good.py", rule_id)
    assert res.findings == [], [f.render() for f in res.findings]


def test_rules_have_distinct_ids_and_docs():
    assert len(RULE_IDS) == len(set(RULE_IDS)) >= 6
    for r in RULES:
        assert r.title and r.rationale


# ----------------------------------------------------------- suppression
def _write(tmp_path, text):
    p = tmp_path / "snippet.py"
    p.write_text(text)
    return str(p)


BAD_LOOP = ("import jax\n"
            "def train_epoch(it, step, state):\n"
            "    for b in it:\n"
            "        state, m = step(state, b)\n"
            "        jax.device_get(m){}\n"
            "    return state\n")


def test_trailing_suppression_with_reason(tmp_path):
    path = _write(tmp_path, BAD_LOOP.format(
        "  # distlint: disable=DL002 -- test: deliberate sync"))
    res = lint_files([path], select=["DL002"])
    assert res.findings == []
    ((finding, sup),) = res.suppressed
    assert finding.rule == "DL002" and sup.reason == "test: deliberate sync"


def test_standalone_suppression_applies_to_next_line(tmp_path):
    lines = BAD_LOOP.format("").splitlines()
    lines.insert(4, "        # distlint: disable=DL002 -- test: deliberate "
                    "sync on next line")
    path = _write(tmp_path, "\n".join(lines) + "\n")
    res = lint_files([path], select=["DL002"])
    assert res.findings == []
    assert len(res.suppressed) == 1


def test_suppression_without_reason_is_itself_a_finding(tmp_path):
    path = _write(tmp_path, BAD_LOOP.format(
        "  # distlint: disable=DL002"))
    res = lint_files([path], select=["DL002"])
    rules = sorted(f.rule for f in res.findings)
    # the reasonless disable does NOT suppress, and is flagged as DL000
    assert rules == ["DL000", "DL002"], [f.render() for f in res.findings]
    assert "reason" in next(f for f in res.findings
                            if f.rule == "DL000").message


def test_suppression_for_other_rule_does_not_apply(tmp_path):
    path = _write(tmp_path, BAD_LOOP.format(
        "  # distlint: disable=DL001 -- wrong rule id"))
    res = lint_files([path], select=["DL002"])
    assert [f.rule for f in res.findings] == ["DL002"]
    assert res.suppressed == []


def test_multi_rule_suppression_parses():
    sups, malformed = parse_suppressions(
        "x = 1  # distlint: disable=DL001,DL005 -- both rules, one reason\n")
    assert malformed == []
    assert sups[0].rules == ("DL001", "DL005")
    assert sups[0].line == 1


def test_prose_mentioning_distlint_is_not_a_directive():
    sups, malformed = parse_suppressions(
        "# this comment mentions distlint casually, not as a directive\n"
        "x = 1\n")
    assert sups == [] and malformed == []


def test_unparseable_file_is_reported_not_crashed(tmp_path):
    path = _write(tmp_path, "def broken(:\n")
    res = lint_files([path])
    assert [f.rule for f in res.findings] == ["DL000"]
    assert "unparseable" in res.findings[0].message


# ------------------------------------------------------------ CLI + JSON
def test_cli_json_round_trip(tmp_path, capsys):
    bad = os.path.join(FIXTURES, "dl003_bad.py")
    rc = distlint_main(["--json", "--select", "DL003", bad])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    api = lint_files([bad], select=["DL003"])
    assert payload["findings"] == [f.to_json() for f in api.findings]
    assert payload["files_checked"] == 1
    for f in payload["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message"}


def test_cli_exit_codes(capsys):
    assert distlint_main(["--select", "DL001",
                          os.path.join(FIXTURES, "dl001_good.py")]) == 0
    assert distlint_main(["--select", "DL001",
                          os.path.join(FIXTURES, "dl001_bad.py")]) == 1
    assert distlint_main(["--select", "DL999", "tools"]) == 2
    assert distlint_main(["--list-rules"]) == 0
    capsys.readouterr()


def test_cli_module_entry_point():
    """`python -m tools.distlint` works from the repo root (no jax)."""
    out = subprocess.run(
        [sys.executable, "-m", "tools.distlint", "--list-rules"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    for rid in RULE_IDS:
        assert rid in out.stdout


# --------------------------------------------- review-found regressions
def test_dl004_factory_host_side_build_code_is_not_flagged(tmp_path):
    """jit(make_step(...)) traces what the factory RETURNS; the factory's
    own body is host-side build code and may print/time freely."""
    path = _write(tmp_path, (
        "import time\n"
        "import jax\n"
        "def make_step(cfg):\n"
        "    print('building', cfg)\n"          # host side: legal
        "    t0 = time.time()\n"                # host side: legal
        "    def step(state, batch):\n"
        "        print('stepping')\n"           # traced: flagged
        "        return state\n"
        "    return step\n"
        "train = jax.jit(make_step(1))\n"))
    res = lint_files([path], select=["DL004"])
    assert len(res.findings) == 1, [f.render() for f in res.findings]
    assert res.findings[0].line == 7


def test_dl001_function_defined_under_guard_is_not_flagged(tmp_path):
    """A function merely DEFINED under a divergent guard may be called on
    every host — only calls executing under the guard are hazards."""
    path = _write(tmp_path, (
        "import jax\n"
        "def setup():\n"
        "    if jax.process_index() == 0:\n"
        "        def helper(x):\n"
        "            return jax.lax.psum(x, 'data')\n"
        "        return helper\n"
        "    return None\n"))
    res = lint_files([path], select=["DL001"])
    assert res.findings == [], [f.render() for f in res.findings]


def test_dl001_guarded_return_inside_with_block_propagates(tmp_path):
    """A process_index-guarded early return inside a with/try block makes
    the code after that block host-divergent too."""
    path = _write(tmp_path, (
        "import jax\n"
        "def save(state, sharding, batch, f):\n"
        "    with open(f) as fh:\n"
        "        if jax.process_index() != 0:\n"
        "            return None\n"
        "    from tpu_dist.data import assemble_global\n"
        "    return assemble_global(sharding, batch)\n"))
    res = lint_files([path], select=["DL001"])
    assert [f.rule for f in res.findings] == ["DL001"]


def test_dl003_axis_index_first_positional_arg(tmp_path):
    path = _write(tmp_path, (
        "import jax\n"
        "def idx():\n"
        "    good = jax.lax.axis_index('data')\n"
        "    return good + jax.lax.axis_index('modle')\n"))
    res = lint_files([path], select=["DL003"])
    assert len(res.findings) == 1 and "modle" in res.findings[0].message


def test_dl005_stdlib_rng_through_alias_and_from_import(tmp_path):
    path = _write(tmp_path, (
        "import random as rnd\n"
        "from random import randint\n"
        "def draw():\n"
        "    return rnd.random() + randint(0, 3)\n"))
    res = lint_files([path], select=["DL005"])
    assert len(res.findings) == 2, [f.render() for f in res.findings]


def test_shim_check_file_honors_dl006_suppressions(tmp_path):
    from tools.check_ledger_schema import check_file, load_schema
    p = tmp_path / "emits.py"
    p.write_text(
        "ledger.emit('bogus', x=1)  "
        "# distlint: disable=DL006 -- test: deliberately undeclared\n"
        "ledger.emit('also_bogus', x=1)\n")
    out = check_file(str(p), load_schema(), "emits.py")
    assert len(out) == 1 and "also_bogus" in out[0]


def test_trailing_suppression_on_multiline_statement(tmp_path):
    """A formatter may wrap the flagged call across lines, leaving the
    trailing comment on a continuation line; the suppression must still
    cover the whole statement (findings anchor to the first line)."""
    path = _write(tmp_path, (
        "import jax\n"
        "def train_epoch(it, step, state):\n"
        "    for b in it:\n"
        "        state, m = step(state, b)\n"
        "        jax.device_get(\n"
        "            m)  # distlint: disable=DL002 -- test: deliberate sync\n"
        "    return state\n"))
    res = lint_files([path], select=["DL002"])
    assert res.findings == [] and len(res.suppressed) == 1


def test_dl002_hot_func_names_all_exist_in_tree():
    """Every name the hot-path regex matches must actually occur as a
    function in the tree — a dead alternative gives false assurance that
    a surface is linted when nothing matches it."""
    import ast as ast_mod
    from tools.distlint.rules import HotLoopHostSync
    names = set()
    for d in ("tpu_dist",):
        for root, _, files in os.walk(os.path.join(REPO_ROOT, d)):
            for f in files:
                if not f.endswith(".py"):
                    continue
                with open(os.path.join(root, f)) as fh:
                    tree = ast_mod.parse(fh.read())
                names |= {n.name for n in ast_mod.walk(tree)
                          if isinstance(n, ast_mod.FunctionDef)}
    pattern = HotLoopHostSync.HOT_FUNC_RE.pattern
    alternatives = pattern.strip("^$()").split("|")
    for alt in alternatives:
        assert alt in names, f"HOT_FUNC_RE lists {alt!r}: no such function"


def test_dl001_tensor_rank_comparison_is_not_divergent(tmp_path):
    path = _write(tmp_path, (
        "import jax\n"
        "def reduce_if_matrix(t, x):\n"
        "    if t.rank == 2:\n"                    # tensor rank, not process
        "        return jax.lax.psum(x, 'data')\n"
        "    return x\n"
        "def main_only(rank, sharding, batch):\n"
        "    from tpu_dist.data import assemble_global\n"
        "    if rank == 0:\n"                      # bare rank: process guard
        "        return assemble_global(sharding, batch)\n"))
    res = lint_files([path], select=["DL001"])
    assert len(res.findings) == 1 and res.findings[0].line == 9


# ------------------------------------------------------- tree invariants
def test_mesh_axes_authority_loaded():
    axes = load_mesh_axes()
    assert {"data", "fsdp", "model", "seq", "stage", "expert"} <= axes


def test_tree_is_clean():
    """THE tier-1 pin: zero unsuppressed findings across the acceptance
    surface (tpu_dist, tools, bench.py — all rules) plus tests/scripts
    for the ledger-schema rule, and every suppression carries a reason."""
    res = lint_files(["tpu_dist", "tools", "bench.py"])
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    for finding, sup in res.suppressed:
        assert sup.reason.strip(), finding.render()
    res6 = lint_files(["tests", "scripts"], select=["DL006"])
    assert res6.findings == [], "\n".join(f.render() for f in res6.findings)
