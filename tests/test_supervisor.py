"""Self-healing (round 10): elastic supervisor + deterministic faults.

Every failure class the supervisor claims to remediate is PRODUCED here on
demand — policy math and classification as pure units, each restart path
against a stdlib-only fake child (sub-second per attempt), checkpoint
blast-radius hardening against real containers, and one chaos acceptance
smoke where a supervised LM run survives an injected hard kill mid-epoch
with no manual intervention (ISSUE 10 acceptance). The full elastic-shrink
variant (rendezvous loss -> degraded dp-only relaunch of a real script) is
slow-marked.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from types import SimpleNamespace

import pytest

from tpu_dist.obs import faults
from tpu_dist.obs.goodput import discover_attempt_paths, job_accounting, \
    split_attempts
from tpu_dist.obs.health import HealthError
from tpu_dist.obs.ledger import read_ledger
from tpu_dist.parallel.launch import LaunchInfo, rendezvous_with_retry
from tpu_dist.parallel.supervisor import (CrashLoopError, RestartPolicy,
                                          Supervisor, classify_attempt,
                                          compute_backoff, degraded_env,
                                          latest_checkpoint, run_supervised)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fault_state(monkeypatch):
    """Fault plans are process-global; tests must not leak them."""
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


# ---------------------------------------------------------------------------
# policy math + classification (pure, no processes — lint.sh runs the same
# surface without jax as a CI gate)

def test_backoff_is_exponential_and_capped():
    pol = RestartPolicy(backoff_base_s=1.0, backoff_max_s=8.0)
    assert compute_backoff(0, pol) == 0.0
    assert [compute_backoff(n, pol) for n in (1, 2, 3, 4, 10)] == \
        [1.0, 2.0, 4.0, 8.0, 8.0]


def _end(status=None, error=None):
    return {"event": "run_end", "steps": 3, "seconds": 1.0,
            "status": status, "error": error}


@pytest.mark.parametrize("records,rc,killed,stderr,want", [
    ([_end("ok")], 0, False, "", "clean"),
    ([_end("ok")], None, False, "", "clean"),              # report-side view
    ([_end("crashed", "HealthError: val_loss spike z=9.1")], 1, False, "",
     "health_halt"),
    ([_end("crashed", "SIGTERM")], 143, False, "", "preemption"),
    ([], -signal.SIGTERM, False, "", "preemption"),
    ([], 1, False, "rendezvous failed: could not reach coordinator",
     "rendezvous"),
    ([], 1, False, "grpc DEADLINE_EXCEEDED", "rendezvous"),
    ([{"event": "stall", "idle_s": 9.0}], -9, True, "", "stall"),
    # died mid-stall without our kill (OOM killer / operator)
    ([{"event": "stall", "idle_s": 9.0}], -9, False, "", "stall"),
    ([], 13, False, "", "crash"),
])
def test_classify_attempt_failure_classes(records, rc, killed, stderr, want):
    assert classify_attempt(records, rc, killed, stderr) == want


def test_classify_stall_kill_beats_run_end():
    # our own SIGKILL after a confirmed stall wins over any ledger story
    assert classify_attempt([_end("ok")], -9, True, "") == "stall"


def test_degraded_env_shrinks_and_marks():
    env, survivors = degraded_env({"TPU_DIST_NUM_PROCESSES": "4"}, lost=1)
    assert survivors == 3
    assert env["TPU_DIST_NUM_PROCESSES"] == "3"
    assert env["TPU_DIST_DEGRADED"] == "1"
    # floor at one survivor; a single-process env is never marked degraded
    env, survivors = degraded_env({"TPU_DIST_NUM_PROCESSES": "1"}, lost=1)
    assert survivors == 1 and "TPU_DIST_DEGRADED" not in env


# ---------------------------------------------------------------------------
# fault-spec grammar + matching (obs.faults)

def test_fault_spec_grammar_roundtrip():
    plan = faults.FaultPlan.parse(
        "hard_exit@step=10,attempt=0,code=7; nan_batch@step=3;"
        "rendezvous_fail@times=2")
    assert plan.sites() == {"hard_exit", "nan_batch", "rendezvous_fail"}
    hard = plan.faults[0]
    assert hard.when == {"step": 10, "attempt": 0}
    assert hard.args == {"code": 7.0}
    assert plan.faults[2].times == 2


@pytest.mark.parametrize("spec", [
    "explode@step=1",          # unknown site
    "hard_exit@step",          # malformed condition
    "hard_exit@step=ten",      # non-numeric value
])
def test_fault_spec_rejects_bad_entries(spec):
    with pytest.raises(ValueError):
        faults.FaultPlan.parse(spec)


def test_fault_matching_step_attempt_times():
    plan = faults.FaultPlan.parse("nan_batch@step=5,attempt=1,times=2")
    # wrong attempt never fires, whatever the step
    assert plan.fire("nan_batch", step=9, attempt=0) is None
    # step is ">= N at first opportunity" (window dispatch may skip N)
    assert plan.fire("nan_batch", step=4, attempt=1) is None
    assert plan.fire("nan_batch", step=6, attempt=1) is not None
    assert plan.fire("nan_batch", step=7, attempt=1) is not None  # times=2
    assert plan.fire("nan_batch", step=8, attempt=1) is None      # spent


def test_fault_env_var_installs_lazily(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "nan_batch@step=0")
    monkeypatch.setenv("TPU_DIST_ATTEMPT", "2")  # supervisor's child export
    faults._reset_for_tests()
    assert set(faults.fire_step(0)) == {"nan_batch"}
    assert faults._context["attempt"] == 2


# ---------------------------------------------------------------------------
# latest_checkpoint: the supervisor's jax-free resume pointer

def test_latest_checkpoint_prefers_pointer_then_mtime(tmp_path):
    d = str(tmp_path)
    assert latest_checkpoint(d) is None
    old = os.path.join(d, "lm-checkpoint.r10.msgpack")
    new = os.path.join(d, "lm-checkpoint.msgpack")
    for p in (old, new):
        with open(p, "wb") as f:
            f.write(b"x")
    os.utime(old, (1, 1))
    # no pointer yet: newest msgpack by mtime
    assert latest_checkpoint(d) == new
    with open(os.path.join(d, "lm-checkpoint.index.json"), "w") as f:
        json.dump({"newest": "lm-checkpoint.r10.msgpack"}, f)
    # pointer wins (it only ever names a fully-committed container)
    assert latest_checkpoint(d) == old
    # a pointer naming a missing file is ignored, not trusted
    with open(os.path.join(d, "lm-checkpoint.index.json"), "w") as f:
        json.dump({"newest": "gone.msgpack"}, f)
    assert latest_checkpoint(d) == new


def test_latest_checkpoint_multi_arch_newest_pointer_wins(tmp_path):
    # a dir that ever held another arch's checkpoints: the NEWEST pointer
    # is the resume target, not the alphabetically-first one (resuming an
    # LM run from a stale lenet container would crash-loop on geometry)
    d = str(tmp_path)
    for arch, age in (("lenet", 1), ("lm", 2)):
        ck = os.path.join(d, f"{arch}-checkpoint.msgpack")
        with open(ck, "wb") as f:
            f.write(b"x")
        idx = os.path.join(d, f"{arch}-checkpoint.index.json")
        with open(idx, "w") as f:
            json.dump({"newest": f"{arch}-checkpoint.msgpack"}, f)
        os.utime(idx, (age, age))
    assert latest_checkpoint(d).endswith("lm-checkpoint.msgpack")


# ---------------------------------------------------------------------------
# rendezvous retry (parallel.launch hardening)

_INFO = LaunchInfo("10.0.0.1:8476", 2, 0, "env")


def test_rendezvous_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("boom")

    waits = []
    used = rendezvous_with_retry(flaky, _INFO, retries=5, timeout_s=60,
                                 backoff_s=0.5, sleep=waits.append)
    assert used == 3 and len(calls) == 3
    assert waits == [0.5, 1.0]  # exponential


def test_rendezvous_retry_exhaustion_names_the_coordinator():
    def dead():
        raise ConnectionError("connection refused")

    with pytest.raises(RuntimeError) as ei:
        rendezvous_with_retry(dead, _INFO, retries=3, timeout_s=60,
                              backoff_s=0.0, sleep=lambda s: None)
    msg = str(ei.value)
    assert "10.0.0.1:8476" in msg and "env method" in msg
    assert "3 attempt(s)" in msg and "connection refused" in msg


def test_rendezvous_retry_respects_total_deadline():
    waits = []
    with pytest.raises(RuntimeError) as ei:
        rendezvous_with_retry(lambda: (_ for _ in ()).throw(OSError("x")),
                              _INFO, retries=100, timeout_s=5.0,
                              backoff_s=4.0, sleep=waits.append)
    # first wait (4s) fits the 5s deadline; the second (8s) would not
    assert waits == [4.0]
    assert "2 attempt(s)" in str(ei.value)


def test_rendezvous_fault_site_fails_first_k_attempts():
    faults.install("rendezvous_fail@times=2")
    calls = []
    used = rendezvous_with_retry(lambda: calls.append(1), _INFO, retries=5,
                                 timeout_s=60, backoff_s=0.0,
                                 sleep=lambda s: None)
    assert used == 3 and len(calls) == 1  # two injected failures, then in


# ---------------------------------------------------------------------------
# the supervisor policy loop against a stdlib-only fake child: each failure
# class produced for real (subprocess, ledger tail, exit codes), seconds not
# minutes because the child fakes the *training*, never the failure

_CHILD = r"""
import json, os, signal, sys, time

def emit(f, event, **kw):
    f.write(json.dumps({"event": event, "ts": time.time(), **kw}) + "\n")
    f.flush()

argv = sys.argv[1:]
base = argv[argv.index("--ledger-base") + 1]
behaviors = json.loads(argv[argv.index("--behaviors") + 1])
attempt = int(os.environ.get("TPU_DIST_ATTEMPT", "0"))
b = behaviors[min(attempt, len(behaviors) - 1)]
root, ext = os.path.splitext(base)
path = base if attempt == 0 else f"{root}.a{attempt}{ext}"
with open(path, "a") as f:
    emit(f, "run_start", attempt=attempt)
    if b == "dead":
        sys.exit(3)  # dies before its first step (crash-loop fodder)
    if b == "rdzv":
        print("rendezvous failed: could not reach coordinator",
              file=sys.stderr, flush=True)
        sys.exit(1)
    if b == "shrunk_clean":
        ok = (os.environ.get("TPU_DIST_NUM_PROCESSES") == "1"
              and os.environ.get("TPU_DIST_DEGRADED") == "1"
              and "--mesh-shape" in argv)
        if not ok:
            sys.exit(9)
    emit(f, "step", step=0)
    if b == "faultloop":
        sys.path.insert(0, {root_repo!r})
        from tpu_dist.obs import faults
        for step in range(1, 6):
            faults.fire_step(step)
            emit(f, "step", step=step)
    if b == "halt":
        emit(f, "run_end", steps=1, seconds=0.1, status="crashed",
             error="HealthError: val_loss spike z=9.1")
        sys.exit(2)
    if b == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(30)
    if b == "hang":
        emit(f, "stall", idle_s=9.0, threshold_s=1.0, stacks="")
        time.sleep(60)
    emit(f, "run_end", steps=1, seconds=0.1, status="ok")
"""


@pytest.fixture
def fake_child(tmp_path):
    """A supervised 'training command' factory: behaviors[n] scripts the
    n-th attempt (stdlib-only child — ~50ms per attempt)."""
    script = tmp_path / "child.py"
    script.write_text(_CHILD.replace("{root_repo!r}", repr(ROOT)))
    ledger = str(tmp_path / "run.jsonl")

    def make(behaviors, env=None, **policy_kw):
        kw = dict(max_restarts=3, backoff_base_s=0.01, backoff_max_s=0.02,
                  stall_timeout_s=10.0, stall_grace_s=0.3, crash_loop_k=3)
        kw.update(policy_kw)
        child_env = dict(os.environ)
        child_env.update(env or {})
        return Supervisor(
            [sys.executable, str(script), "--ledger-base", ledger,
             "--behaviors", json.dumps(behaviors)],
            ledger=ledger, policy=RestartPolicy(**kw), env=child_env,
            forward_flags=False, poll_s=0.05), ledger

    return make


def test_supervisor_clean_run_is_one_attempt(fake_child):
    sup, _ = fake_child(["clean"])
    res = sup.run()
    assert res.ok and res.status == "clean"
    assert [a.failure_class for a in res.attempts] == ["clean"]


def test_supervisor_restarts_after_fault_injected_exit(fake_child):
    # the real obs.faults plumbing inside the child: hard_exit at step 3 of
    # attempt 0 (os._exit — no run_end, SIGKILL-class death), attempt 1
    # runs the same loop to completion because the spec is attempt-gated
    sup, ledger = fake_child(
        ["faultloop", "faultloop"],
        env={"TPU_DIST_FAULTS": "hard_exit@step=3,attempt=0,code=13"})
    res = sup.run()
    assert res.ok
    assert [a.failure_class for a in res.attempts] == ["crash", "clean"]
    assert res.attempts[0].returncode == 13
    assert res.attempts[0].steps == 3  # steps 0..2 landed before the kill
    assert res.attempts[1].ledger.endswith(".a1.jsonl")


def test_supervisor_health_halt_classified_and_restarted(fake_child):
    sup, _ = fake_child(["halt", "clean"])
    res = sup.run()
    assert res.ok
    assert [a.failure_class for a in res.attempts] == ["health_halt", "clean"]


def test_supervisor_preemption_classified(fake_child):
    sup, _ = fake_child(["sigterm", "clean"])
    res = sup.run()
    assert res.ok
    assert [a.failure_class for a in res.attempts] == ["preemption", "clean"]


def test_supervisor_kills_confirmed_stall_and_restarts(fake_child):
    # the child's own watchdog 'stall' event with no progress after it:
    # SIGKILL after stall_grace_s, restart, clean finish — well under the
    # stall_timeout_s idle path
    t0 = time.monotonic()
    sup, _ = fake_child(["hang", "clean"])
    res = sup.run()
    assert res.ok
    assert [a.failure_class for a in res.attempts] == ["stall", "clean"]
    assert res.attempts[0].returncode in (-signal.SIGKILL, 137)
    assert time.monotonic() - t0 < 10.0  # grace path, not the 60s sleep


def test_supervisor_crash_loop_cutoff(fake_child):
    # K consecutive pre-first-step deaths stop the supervisor with a
    # diagnosis instead of burning max_restarts (ISSUE 10 acceptance)
    sup, _ = fake_child(["dead"], max_restarts=10, crash_loop_k=3)
    res = sup.run()
    assert res.status == "crash_loop" and not res.ok
    assert len(res.attempts) == 3
    assert all(a.steps == 0 for a in res.attempts)


def test_supervisor_rendezvous_loss_shrinks_mesh(fake_child):
    # confirmed host loss = TWO consecutive rendezvous-class failures
    # (the first full-size retry rides out a transient coordinator
    # outage); then the relaunch env drops to the survivors, is marked
    # degraded, and carries the dp-only mesh reset flags — the child
    # itself verifies all three (exits 9 otherwise). forward_flags on:
    # the degraded flags ride the same append path as --resume
    sup, ledger = fake_child(["rdzv", "rdzv", "shrunk_clean"],
                             env={"TPU_DIST_NUM_PROCESSES": "2"})
    sup.forward_flags = True
    res = sup.run()
    assert res.ok
    assert [a.failure_class for a in res.attempts] == \
        ["rendezvous", "rendezvous", "clean"]
    assert sup.degraded
    assert sup.env["TPU_DIST_NUM_PROCESSES"] == "1"


def test_supervisor_single_rendezvous_failure_keeps_full_mesh(fake_child):
    # a transient outage (one rendezvous failure, then in) must NOT cost
    # a host: the first retry is full-size and undegraded
    sup, _ = fake_child(["rdzv", "clean"],
                        env={"TPU_DIST_NUM_PROCESSES": "2"})
    res = sup.run()
    assert res.ok
    assert [a.failure_class for a in res.attempts] == ["rendezvous", "clean"]
    assert not sup.degraded
    assert sup.env["TPU_DIST_NUM_PROCESSES"] == "2"


def test_supervisor_death_never_orphans_the_child(fake_child):
    # a dying supervisor (scheduler SIGTERM -> SystemExit, or any internal
    # error unwinding run()) must take the live child down with it — an
    # orphaned trainer would race its own requeue on the same ledger and
    # checkpoint dir
    sup, _ = fake_child(["hang"])
    pids = []
    real_popen = subprocess.Popen

    def spying_popen(*a, **kw):
        proc = real_popen(*a, **kw)
        pids.append(proc.pid)
        return proc

    calls = []

    def dying_sleep(s):
        if len(calls) >= 3:  # child is up and hanging; now "get killed"
            raise SystemExit(143)
        calls.append(s)
        time.sleep(s)

    sup._sleep = dying_sleep
    subprocess.Popen = spying_popen
    try:
        with pytest.raises(SystemExit):
            sup.run()
    finally:
        subprocess.Popen = real_popen
    assert pids
    # _run_child's finally terminated AND reaped the child synchronously
    # before the exception propagated — the pid must be gone already
    with pytest.raises(OSError):
        os.kill(pids[0], 0)


def test_supervise_cli_end_to_end(fake_child, tmp_path):
    # the actual CLI surface: python -m tpu_dist.supervise -- <cmd>
    _, ledger = fake_child(["clean"])
    child = str(tmp_path / "child.py")
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_dist.supervise", "--ledger", ledger,
         "--no-forward-flags", "--backoff-s", "0.01", "--",
         sys.executable, child, "--ledger-base", ledger,
         "--behaviors", '["clean"]'],
        capture_output=True, text=True, cwd=ROOT, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "clean: 1 attempt(s) a0=clean" in proc.stderr


# ---------------------------------------------------------------------------
# in-process flavor: run_supervised (the engines' max_restarts opt-in)

@dataclasses.dataclass
class _Cfg:
    resume: str = ""
    checkpoint_dir: str = ""
    ledger_path: str = "run.jsonl"
    attempt: int = 0
    max_restarts: int = 2
    restart_backoff_s: float = 0.0
    crash_loop_k: int = 3


class _Trainer:
    """Scripted in-process trainer: outcomes[n] is attempt n's fate."""

    def __init__(self, outcomes):
        self.outcomes = list(outcomes)
        self.cfgs = []

    def __call__(self, cfg):  # the make_trainer factory
        self.cfgs.append(cfg)
        fate = self.outcomes[min(len(self.cfgs) - 1, len(self.outcomes) - 1)]
        steps = 0 if fate == "dead" else 5
        t = SimpleNamespace(obs=SimpleNamespace(steps=steps))
        if fate == "halt":
            def fit():
                raise HealthError("val_loss spike z=9.1")
        elif fate in ("crash", "dead"):
            def fit():
                raise ValueError("boom")
        else:
            def fit():
                return 42.0
        t.fit = fit
        return t


def test_run_supervised_halt_restarts_from_newest_checkpoint(tmp_path):
    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / "lm-checkpoint.msgpack").write_bytes(b"x")
    (ck / "lm-checkpoint.index.json").write_text(
        json.dumps({"newest": "lm-checkpoint.msgpack"}))
    factory = _Trainer(["halt", "clean"])
    cfg = _Cfg(checkpoint_dir=str(ck))
    assert run_supervised(factory, cfg, sleep=lambda s: None) == 42.0
    assert len(factory.cfgs) == 2
    # attempt 0 keeps the caller's resume; the restart points at the
    # newest valid checkpoint with auto attempt lineage
    assert factory.cfgs[0].resume == ""
    assert factory.cfgs[1].resume == str(ck / "lm-checkpoint.msgpack")
    assert all(c.attempt == -1 for c in factory.cfgs)  # ledger_path set


def test_run_supervised_ctor_failure_is_a_policied_attempt():
    # an OOM/FS blip while REBUILDING the trainer is a classifiable
    # pre-first-step death (backoff + crash-loop counting), not an abort
    # of the whole supervised run
    calls = []

    def factory(run_cfg):
        calls.append(run_cfg)
        if len(calls) == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED during init")
        t = SimpleNamespace(obs=SimpleNamespace(steps=5))
        t.fit = lambda: 7.0
        return t

    assert run_supervised(factory, _Cfg(), sleep=lambda s: None) == 7.0
    assert len(calls) == 2


def test_run_supervised_exhaustion_reraises():
    factory = _Trainer(["crash"])
    with pytest.raises(ValueError, match="boom"):
        run_supervised(factory, _Cfg(max_restarts=1), sleep=lambda s: None)
    assert len(factory.cfgs) == 2  # 1 restart = 2 attempts


def test_run_supervised_crash_loop_raises_diagnosis():
    factory = _Trainer(["dead"])
    with pytest.raises(CrashLoopError, match="first step"):
        run_supervised(factory, _Cfg(max_restarts=10, crash_loop_k=2),
                       sleep=lambda s: None)
    assert len(factory.cfgs) == 2  # cut off by K, not max_restarts


# ---------------------------------------------------------------------------
# checkpoint blast radius: keep-last-K retention, CRC fallback, ENOSPC

def _img_state():
    import jax

    from tpu_dist.engine.state import TrainState, init_model
    from tpu_dist.models import create_model
    from tpu_dist.ops import make_optimizer

    model = create_model("lenet")
    params, stats = init_model(model, jax.random.PRNGKey(0), (2, 28, 28, 1))
    tx = make_optimizer(0.1, 0.9, 1e-4, steps_per_epoch=10)
    return TrainState.create(params, stats, tx)


def test_keep_retention_and_pointer(tmp_path):
    from tpu_dist.engine import checkpoint as ckpt

    d = str(tmp_path)
    state = _img_state()
    for step in (10, 20, 30):
        ckpt.save_checkpoint(d, state.replace(step=step), epoch=step // 10,
                             best_acc1=0.0, arch="lenet", is_best=False,
                             keep=2)
    main = os.path.join(d, "lenet-checkpoint.msgpack")
    assert ckpt.retained_checkpoints(main) == [
        os.path.join(d, "lenet-checkpoint.r30.msgpack"),
        os.path.join(d, "lenet-checkpoint.r20.msgpack")]  # r10 pruned
    with open(os.path.join(d, "lenet-checkpoint.index.json")) as f:
        index = json.load(f)
    assert index["newest"] == "lenet-checkpoint.msgpack"
    assert index["step"] == 30
    assert latest_checkpoint(d) == main  # the supervisor's resume target


def test_corrupt_newest_falls_back_to_retained(tmp_path, capsys):
    # ISSUE 10 acceptance: truncating the newest checkpoint makes the next
    # resume fall back to the previous retained checkpoint, loudly
    from tpu_dist.engine import checkpoint as ckpt

    d = str(tmp_path)
    state = _img_state()
    for step in (10, 20):
        ckpt.save_checkpoint(d, state.replace(step=step), epoch=step // 10,
                             best_acc1=0.0, arch="lenet", is_best=False,
                             keep=2)
    main = os.path.join(d, "lenet-checkpoint.msgpack")
    with open(main, "r+b") as f:  # torn write: half the container
        f.truncate(os.path.getsize(main) // 2)
    restored, meta = ckpt.load_checkpoint(main, _img_state())
    # the r20 retained sibling is a hard link to the truncated newest, so
    # the first INTACT fallback is r10 — a few steps lost, run saved
    assert meta["step"] == 10
    err = capsys.readouterr().err
    assert "corrupt" in err and "RETAINED" in err
    assert int(restored.step) == 10


def test_corrupt_with_no_fallback_raises(tmp_path):
    from tpu_dist.engine import checkpoint as ckpt

    d = str(tmp_path)
    ckpt.save_checkpoint(d, _img_state(), epoch=1, best_acc1=0.0,
                         arch="lenet", is_best=False)  # keep=0: no siblings
    main = os.path.join(d, "lenet-checkpoint.msgpack")
    with open(main, "r+b") as f:
        f.truncate(os.path.getsize(main) - 7)
    with pytest.raises(ckpt.CheckpointCorruptError, match="no intact"):
        ckpt.load_checkpoint(main, _img_state())


def test_structure_mismatch_never_falls_back(tmp_path):
    # every retained sibling shares the structure — falling back would
    # silently resume an incompatible run; the error names the real cause
    from tpu_dist.engine import checkpoint as ckpt
    from tpu_dist.engine.state import TrainState
    from tpu_dist.ops import make_optimizer

    d = str(tmp_path)
    for step in (10, 20):
        ckpt.save_checkpoint(d, _img_state().replace(step=step),
                             epoch=step // 10, best_acc1=0.0, arch="lenet",
                             is_best=False, keep=2)
    import jax.numpy as jnp
    other = TrainState.create(
        {"w": jnp.zeros((3,))}, {},
        make_optimizer(0.1, 0.9, 0.0, steps_per_epoch=10))
    with pytest.raises(ValueError, match="structure"):
        ckpt.load_checkpoint(os.path.join(d, "lenet-checkpoint.msgpack"),
                             other)


def test_enospc_fault_leaves_previous_checkpoint_valid(tmp_path):
    # injected full disk on the SECOND write: the pointer and container on
    # disk stay the first, fully-committed state — exactly what the
    # supervisor's restart will resume from
    from tpu_dist.engine import checkpoint as ckpt

    d = str(tmp_path)
    state = _img_state()
    ckpt.save_checkpoint(d, state.replace(step=10), epoch=1, best_acc1=0.0,
                         arch="lenet", is_best=False, keep=2)
    faults.install("ckpt_enospc")
    with pytest.raises(OSError) as ei:
        ckpt.save_checkpoint(d, state.replace(step=20), epoch=2,
                             best_acc1=0.0, arch="lenet", is_best=False,
                             keep=2)
    import errno
    assert ei.value.errno == errno.ENOSPC
    faults._reset_for_tests()
    main = latest_checkpoint(d)
    restored, meta = ckpt.load_checkpoint(main, _img_state())
    assert meta["step"] == 10  # the ENOSPC'd write never advanced anything


def test_async_enospc_surfaces_on_wait(tmp_path):
    from tpu_dist.engine import checkpoint as ckpt

    d = str(tmp_path)
    faults.install("ckpt_enospc")
    ckpt.save_checkpoint(d, _img_state(), epoch=1, best_acc1=0.0,
                         arch="lenet", is_best=False, async_write=True)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        ckpt.wait_for_async_save(d)


def test_async_writers_are_per_dir(tmp_path):
    # the round-10 fix: two checkpoint dirs no longer share one writer
    # slot — dir B's wait neither joins nor steals dir A's error
    import threading

    from tpu_dist.engine import checkpoint as ckpt

    da, db = str(tmp_path / "a"), str(tmp_path / "b")
    gate = threading.Event()
    state = _img_state()
    orig = ckpt._write

    def slow_write(ckpt_dir, *a, **kw):
        if os.path.abspath(ckpt_dir) == os.path.abspath(da):
            gate.wait(timeout=30)
        return orig(ckpt_dir, *a, **kw)

    ckpt._write, _saved = slow_write, orig
    try:
        ckpt.save_checkpoint(da, state, 1, 0.0, "lenet", False,
                             async_write=True)
        t0 = time.monotonic()
        ckpt.save_checkpoint(db, state, 1, 0.0, "lenet", False,
                             async_write=True)
        ckpt.wait_for_async_save(db)  # must NOT block on dir A's writer
        assert time.monotonic() - t0 < 5.0
        assert os.path.exists(os.path.join(db, "lenet-checkpoint.msgpack"))
    finally:
        gate.set()
        ckpt._write = _saved
        ckpt.wait_for_async_save()


# ---------------------------------------------------------------------------
# chaos acceptance smoke (ISSUE 10): a supervised LM run survives an
# injected hard kill mid-epoch — auto-restart via attempt lineage, resume
# from the last good checkpoint, clean finish, stitched-ledger evidence —
# with no manual intervention anywhere

def _script_env(**extra):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("TPU_DIST") and k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


_LM_TINY = ["--epochs", "2", "--batch-size", "4", "--seq-len", "32",
            "--d-model", "32", "--num-layers", "1", "--num-heads", "2",
            "--vocab-size", "64", "--synth-tokens", "2000",
            "--print-freq", "1"]


@pytest.mark.slow  # tier-1 budget offset (round 13): same supervised-LM
# restart shape as the IN-budget round-13 acceptance
# (tests/test_elastic.py::test_preempt_deadline_snapshot_resumes_exact_step
# — two real attempts, checkpoint resume, stitched report), and the
# hard-kill class itself keeps its cheap in-budget twin
# (test_supervisor_restarts_after_fault_injected_exit)
def test_chaos_smoke_supervised_lm_survives_hard_kill(tmp_path):
    ledger = str(tmp_path / "run.jsonl")
    # 15 steps/epoch; the epoch-1 checkpoint exists when step 20 dies
    sup = Supervisor(
        [sys.executable, os.path.join(ROOT, "scripts", "8.lm_longcontext.py"),
         *_LM_TINY],
        ledger=ledger, ckpt_dir=str(tmp_path / "ck"),
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.05,
                             stall_timeout_s=300.0),
        env=_script_env(TPU_DIST_FAULTS="hard_exit@step=20,attempt=0"),
        poll_s=0.1)
    res = sup.run()
    assert res.ok, [(a.failure_class, a.returncode) for a in res.attempts]
    assert [a.failure_class for a in res.attempts] == ["crash", "clean"]
    assert res.attempts[0].steps >= 15  # died mid-epoch 2, after the ckpt

    records = []
    for p in discover_attempt_paths(ledger):
        records += read_ledger(p, validate=False, strict=False)
    # the injection is on the record, distinguishable from organic failure
    fault_events = [r for r in records if r.get("event") == "fault"]
    assert [f["site"] for f in fault_events] == ["hard_exit"]
    # attempt lineage: two run_starts, the restart resumed from the newest
    # valid checkpoint the supervisor found via the pointer file
    starts = [r for r in records if r.get("event") == "run_start"]
    assert [s["attempt"] for s in starts] == [0, 1]
    assert starts[1]["config"]["resume"].endswith("lm-checkpoint.msgpack")
    # stitched goodput charges the crash->restart window as restart_gap
    acc = job_accounting(split_attempts(records))
    assert acc["categories"]["restart_gap"] > 0
    # and the final report classifies the failure, injected vs organic
    sys.path.insert(0, ROOT)
    from tools.ledger_report import restarts_section
    lines = []
    rep = restarts_section(records, out=lines.append)
    assert rep["attempts"][0]["class"] == "crash"
    assert rep["attempts"][0]["injected"] == ["hard_exit"]
    assert rep["attempts"][1]["class"] == "clean"
    assert rep["injected_faults"] == 1 and rep["organic_failures"] == 0
    assert not rep["crash_loop"]


@pytest.mark.slow  # tier-1 budget: full elastic-shrink variant; the cheap
# fake-child twin (test_supervisor_rendezvous_loss_shrinks_mesh) stays in
def test_elastic_shrink_after_rendezvous_loss_real_script(tmp_path):
    # a 2-process job whose coordinator never comes back: attempts 0+1
    # exhaust the rendezvous retries (injected), the supervisor re-forms
    # the mesh dp-only on the 1 survivor, and the degraded relaunch
    # completes a real single-process distributed init + training run
    import socket

    from tpu_dist._compat import CPU_MULTIPROCESS
    if not CPU_MULTIPROCESS:
        pytest.skip("this jax's CPU backend refuses multi-process runs "
                    "before rendezvous (_compat.CPU_MULTIPROCESS), so the "
                    "2-process launch dies as 'crash', not 'rendezvous'; "
                    "the shrink policy is covered by the fake-child twin")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ledger = str(tmp_path / "run.jsonl")
    sup = Supervisor(
        [sys.executable, os.path.join(ROOT, "scripts", "8.lm_longcontext.py"),
         "--epochs", "1", "--batch-size", "4", "--seq-len", "32",
         "--d-model", "32", "--num-layers", "1", "--num-heads", "2",
         "--vocab-size", "64", "--synth-tokens", "1000",
         "--print-freq", "1"],
        ledger=ledger, ckpt_dir=str(tmp_path / "ck"),
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.05,
                             stall_timeout_s=300.0),
        env=_script_env(
            TPU_DIST_COORDINATOR=f"127.0.0.1:{port}",
            TPU_DIST_NUM_PROCESSES="2", TPU_DIST_PROCESS_ID="0",
            TPU_DIST_RENDEZVOUS_RETRIES="2",
            TPU_DIST_RENDEZVOUS_BACKOFF_S="0.05",
            # attempts 0 AND 1 exhaust their retries (host loss needs two
            # consecutive rendezvous failures before the mesh shrinks);
            # attempt 2 runs fault-free on the 1 survivor
            TPU_DIST_FAULTS="rendezvous_fail@attempt=0,times=2;"
                            "rendezvous_fail@attempt=1,times=2"),
        poll_s=0.1)
    res = sup.run()
    assert res.ok, [(a.failure_class, a.returncode) for a in res.attempts]
    assert [a.failure_class for a in res.attempts] == \
        ["rendezvous", "rendezvous", "clean"]
    assert sup.degraded
    assert sup.env["TPU_DIST_NUM_PROCESSES"] == "1"
    # the degraded attempt ran with the mesh reset to dp-only over the
    # survivors (mesh_shape cleared by the relaunch flags)
    recs = read_ledger(res.attempts[1].ledger, validate=False, strict=False)
    start = next(r for r in recs if r.get("event") == "run_start")
    assert start["config"]["mesh_shape"] is None
    assert list(start["config"]["mesh_axes"]) == ["data"]
