"""LMTrainer: the LM family through the SHARED loop machinery (VERDICT r2 #1).

Mirrors test_engine.py's Trainer coverage for tokens: windowed HBM-resident
path == per-batch path, mid-epoch step-exact resume, exact padded eval, and
cross-mode agreement (dp == sp == pp over a full epoch, not just one step).
"""

import os

import jax
import numpy as np
import pytest

from tpu_dist.configs import LMConfig
from tpu_dist.engine.lm_loop import LMTrainer

TINY = dict(batch_size=8, seq_len=32, d_model=32, num_layers=2, num_heads=2,
            vocab_size=64, synth_tokens=3000, seed=3, print_freq=100,
            epochs=1, lr=1e-2)


def _params_vec(trainer, unstack_pp=False):
    params = jax.device_get(trainer.state.params)
    if unstack_pp:
        from tpu_dist.parallel.pp import unstack_pipeline_params
        params = unstack_pipeline_params(params)
    return np.concatenate([np.asarray(x, np.float32).ravel()
                           for x in jax.tree_util.tree_leaves(params)]), params


def _run(cfg):
    tr = LMTrainer(cfg)
    tr.fit()
    return tr


@pytest.mark.slow  # tier-1 budget (PR 14): the windowed-vs-per-batch
# parity stays pinned in-budget by
# test_lm_shard_mode_windowed_matches_per_batch (same parity, run under
# the sharded step builders this bare-jit twin is a subset of)
def test_lm_windowed_matches_per_batch(tmp_path):
    """steps_per_dispatch=4 + HBM-resident rows == the per-batch loop,
    parameter for parameter (same rng fold per optimizer step)."""
    tr1 = _run(LMConfig(data_placement="host", **TINY))
    tr4 = _run(LMConfig(steps_per_dispatch=4, **TINY))
    assert tr1.device_data is False and tr4.device_data is True
    assert (int(jax.device_get(tr1.state.step))
            == int(jax.device_get(tr4.state.step)) > 0)
    p1, _ = _params_vec(tr1)
    p4, _ = _params_vec(tr4)
    np.testing.assert_allclose(p1, p4, rtol=1e-5, atol=1e-7)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_lm_modes_agree_over_epoch(tmp_path):
    """dp == tp == sp == pp at the end of a FULL epoch over the corpus —
    the round-2 tests only checked single steps on a fixed batch."""
    dp = _run(LMConfig(**TINY))
    p_dp, _ = _params_vec(dp)
    tp = _run(LMConfig(mesh_shape=(4, 2), mesh_axes=("data", "model"), **TINY))
    p_tp, _ = _params_vec(tp)
    sp = _run(LMConfig(mesh_shape=(2, 4), mesh_axes=("data", "seq"), **TINY))
    p_sp, _ = _params_vec(sp)
    pp = _run(LMConfig(mesh_shape=(4, 2), mesh_axes=("data", "stage"),
                       pp_microbatches=2, **TINY))
    _, pp_params = _params_vec(pp, unstack_pp=True)
    np.testing.assert_allclose(p_tp, p_dp, rtol=2e-4, atol=2e-6)
    np.testing.assert_allclose(p_sp, p_dp, rtol=2e-4, atol=2e-6)
    # pp's stacked tree flattens in a different leaf order: compare per-path
    # against dp (ADVICE r3: sorted magnitudes would also pass on permuted
    # or sign-flipped leaves)
    _, dp_params = _params_vec(dp)
    flat_dp = {jax.tree_util.keystr(p): v for p, v in
               jax.tree_util.tree_leaves_with_path(dp_params)}
    flat_pp = {jax.tree_util.keystr(p): v for p, v in
               jax.tree_util.tree_leaves_with_path(pp_params)}
    assert flat_dp.keys() == flat_pp.keys()
    for path in flat_dp:
        np.testing.assert_allclose(
            np.asarray(flat_pp[path]), np.asarray(flat_dp[path]),
            rtol=2e-4, atol=2e-6, err_msg=path)


@pytest.mark.parametrize("mesh_kw", [
    dict(mesh_shape=(2, 4), mesh_axes=("data", "seq")),
    dict(mesh_shape=(4, 2), mesh_axes=("data", "stage"), pp_microbatches=2),
    dict(mesh_shape=(4, 2), mesh_axes=("data", "stage"), pp_microbatches=2,
         pp_schedule="1f1b"),
])
@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_lm_shard_mode_windowed_matches_per_batch(mesh_kw):
    """VERDICT r3 #3: sp and pp get the K-steps-per-dispatch HBM-resident
    window path (lax.scan over index windows INSIDE the shard_map program);
    it must equal the per-batch host-fed path parameter for parameter, and
    its one-dispatch eval must reproduce the per-batch perplexity."""
    tr1 = _run(LMConfig(data_placement="host", **mesh_kw, **TINY))
    tr4 = _run(LMConfig(steps_per_dispatch=4, **mesh_kw, **TINY))
    assert tr1.device_data is False and tr4.device_data is True
    assert (int(jax.device_get(tr1.state.step))
            == int(jax.device_get(tr4.state.step)) > 0)
    unstack = "stage" in mesh_kw["mesh_axes"]
    p1, _ = _params_vec(tr1, unstack_pp=unstack)
    p4, _ = _params_vec(tr4, unstack_pp=unstack)
    np.testing.assert_allclose(p1, p4, rtol=1e-5, atol=1e-7)
    assert tr4.best_ppl == pytest.approx(tr1.best_ppl, rel=1e-4)


@pytest.mark.slow  # tier-1 budget (PR 7): 22s parity twin; grad-accum stays covered in-budget by the image-side test_trainer_grad_accum_wiring
def test_lm_grad_accum_matches_full_batch():
    """--grad-accum-steps N: N sequential microbatches averaging into ONE
    update must equal the full-batch step (dropout-free model), and the
    optimizer step count must be identical."""
    kw = dict(data_placement="host", **{**TINY, "batch_size": 16})
    tr1 = _run(LMConfig(**kw))
    tr2 = _run(LMConfig(grad_accum_steps=2, **kw))
    assert (int(jax.device_get(tr1.state.step))
            == int(jax.device_get(tr2.state.step)) > 0)
    p1, _ = _params_vec(tr1)
    p2, _ = _params_vec(tr2)
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-7)

    with pytest.raises(ValueError, match="mutually exclusive"):
        LMTrainer(LMConfig(grad_accum_steps=2, steps_per_dispatch=2, **TINY))
    with pytest.raises(ValueError, match="jit"):
        LMTrainer(LMConfig(grad_accum_steps=2, mesh_shape=(2, 4),
                           mesh_axes=("data", "seq"), **TINY))


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_lm_mid_epoch_resume_step_exact(tmp_path):
    """Interrupt between windows, resume -> same params as uninterrupted."""
    kw = dict(steps_per_dispatch=2, checkpoint_dir=str(tmp_path / "full"),
              **TINY)
    tr_full = _run(LMConfig(**kw))
    p_full, _ = _params_vec(tr_full)

    tr_int = LMTrainer(LMConfig(**{**kw, "checkpoint_dir":
                                   str(tmp_path / "int")}))
    real = tr_int.window_step
    calls = {"n": 0}

    def limited(*a, **k):
        if calls["n"] == 2:
            raise KeyboardInterrupt
        calls["n"] += 1
        return real(*a, **k)

    tr_int.window_step = limited
    with pytest.raises(KeyboardInterrupt):
        tr_int.fit()

    ck = os.path.join(str(tmp_path / "int"), "lm-checkpoint.msgpack")
    tr_res = LMTrainer(LMConfig(**{**kw, "checkpoint_dir":
                                   str(tmp_path / "res"), "resume": ck}))
    assert tr_res._skip_batches == 4  # 2 windows x K=2
    tr_res.fit()
    p_res, _ = _params_vec(tr_res)
    np.testing.assert_allclose(p_full, p_res, rtol=1e-5, atol=1e-7)


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_lm_lr_schedule_survives_resume(tmp_path):
    """Warmup+cosine LR trajectory continues exactly across a --resume
    boundary (VERDICT r3 #2): interrupt mid-schedule, resume, and the final
    params must match an uninterrupted run — which can only happen if every
    post-resume update applied the same LR as the unbroken trajectory."""
    kw = dict(steps_per_dispatch=2, lr_schedule="cosine", warmup_steps=3,
              lr_decay_steps=12, checkpoint_dir=str(tmp_path / "full"),
              **TINY)
    tr_full = _run(LMConfig(**kw))
    # the schedule is genuinely non-constant over the run (not vacuous)
    lrs = [float(np.asarray(tr_full.lr_schedule(s))) for s in range(8)]
    assert lrs[0] < lrs[2] <= lrs[3] > lrs[7]
    p_full, _ = _params_vec(tr_full)

    tr_int = LMTrainer(LMConfig(**{**kw, "checkpoint_dir":
                                   str(tmp_path / "int")}))
    real = tr_int.window_step
    calls = {"n": 0}

    def limited(*a, **k):
        if calls["n"] == 2:
            raise KeyboardInterrupt
        calls["n"] += 1
        return real(*a, **k)

    tr_int.window_step = limited
    with pytest.raises(KeyboardInterrupt):
        tr_int.fit()
    ck = os.path.join(str(tmp_path / "int"), "lm-checkpoint.msgpack")
    tr_res = LMTrainer(LMConfig(**{**kw, "checkpoint_dir":
                                   str(tmp_path / "res"), "resume": ck}))
    tr_res.fit()
    p_res, _ = _params_vec(tr_res)
    np.testing.assert_allclose(p_full, p_res, rtol=1e-5, atol=1e-7)


def test_lm_resume_geometry_mismatch_fails_before_load(tmp_path):
    cfg = LMConfig(checkpoint_dir=str(tmp_path), **TINY)
    _run(cfg)
    ck = os.path.join(str(tmp_path), "lm-checkpoint.msgpack")
    bad = {**TINY, "d_model": 64}
    with pytest.raises(ValueError, match="geometry"):
        LMTrainer(LMConfig(resume=ck, **bad))


@pytest.mark.slow  # tier-1 budget (PR 18): ~6s composite whose pieces stay
# covered in-budget — metric-sum exactness by
# test_lm.py::test_lm_eval_step_exact_metrics, wrap-padding mask math by
# test_engine.py::test_eval_step_counts_mask_padding and
# test_sampler.py's validity masks
def test_lm_eval_exact_under_padding():
    """Held-out ppl masks sampler wrap-padding: indexed one-dispatch eval ==
    a hand-rolled forward over exactly the real val rows."""
    import jax.numpy as jnp

    from tpu_dist.engine.lm_steps import lm_loss_and_metrics, make_lm_batches

    cfg = LMConfig(steps_per_dispatch=2, **{**TINY, "val_frac": 0.21})
    tr = LMTrainer(cfg)
    assert tr._val_rows_dev is not None
    n_val = len(tr.val_ds)
    assert n_val % cfg.batch_size != 0  # padding actually exercised
    tr.train_epoch(0)
    loss, ppl, acc = tr.validate(0)

    rows = tr.val_ds.rows_array()
    inputs, targets = make_lm_batches(rows)
    logits = tr.model.apply({"params": jax.device_get(tr.state.params)},
                            jnp.asarray(inputs), train=False)
    _, ref = lm_loss_and_metrics(logits, jnp.asarray(targets),
                                 jnp.ones(targets.shape, jnp.float32))
    ref_loss = float(ref["loss_sum"]) / float(ref["count"])
    assert float(ref["count"]) == n_val * cfg.seq_len
    assert loss == pytest.approx(ref_loss, rel=1e-5)


def test_lm_learns_on_corpus():
    """Perplexity north star: two epochs on the affine corpus must collapse
    ppl far below the uniform baseline (vocab 64 -> 64.0)."""
    cfg = LMConfig(steps_per_dispatch=4, **{**TINY, "epochs": 2,
                                            "lr": 3e-2, "num_layers": 1})
    tr = _run(cfg)
    assert tr.best_ppl < 20.0


@pytest.mark.slow  # tier-1 budget (PR 19): two full trainer builds (13s) for
# the max_steps cap; max_steps-capped LMTrainer runs stay exercised
# in-budget by test_lm_trainer_accepts_emitted_plan_file (max_steps=2) and
# test_moe.py's MFU/router-mass runs (max_steps=2/3)
def test_lm_max_steps_caps_run():
    cfg = LMConfig(max_steps=3, **TINY)
    tr = _run(cfg)
    assert int(jax.device_get(tr.state.step)) == 3
    # windowed path: K-step dispatches are atomic, so the window list must
    # be clipped to the budget — max_steps NOT divisible by K stays exact
    cfg = LMConfig(max_steps=3, steps_per_dispatch=2, **TINY)
    tr = _run(cfg)
    assert int(jax.device_get(tr.state.step)) == 3


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_lm_adamw_trains_and_resumes(tmp_path):
    """--optimizer adamw: a checkpoint/resume boundary after epoch 1
    continues the EXACT 2-epoch trajectory (the mu/nu moments ride in the
    generic optax state the checkpoint already round-trips)."""
    kw = dict(TINY, lr=3e-3, optimizer="adamw")
    base = {k: v for k, v in kw.items() if k != "epochs"}

    full = _run(LMConfig(epochs=2, **base))
    v_full, _ = _params_vec(full)

    _run(LMConfig(checkpoint_dir=str(tmp_path / "ck"), epochs=1, **base))
    res = _run(LMConfig(
        resume=str(tmp_path / "ck" / "lm-checkpoint.msgpack"),
        epochs=2, **base))
    v_res, _ = _params_vec(res)
    np.testing.assert_allclose(v_res, v_full, rtol=1e-6, atol=1e-7)
