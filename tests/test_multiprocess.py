"""True multi-process jax.distributed execution (VERDICT r1 missing #2).

The reference's identity is multi-process distributed training
(reference 2.distributed.py:98 env:// rendezvous,
3.multiprocessing_distributed.py:84,102 mp.spawn + loopback tcp://). Every
other test in this suite emulates distribution with 8 virtual devices in ONE
process; these tests actually spawn separate OS processes that rendezvous via
``jax.distributed`` over loopback TCP — the first-ever execution of
``launch.initialize``'s distributed path and of ``prefetch_to_device``'s
``make_array_from_process_local_data`` branch (the multi-controller pitfall
where a bare device_put would silently drop the other process's shard).

Check: a 2-process x 2-device run must produce the SAME trained parameters as
a 1-process x 4-device run on the identical global workload (same global
batch content, same seed) — distribution must be invisible to the math.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from tpu_dist._compat import CPU_MULTIPROCESS

pytestmark = pytest.mark.skipif(
    not CPU_MULTIPROCESS,
    reason="this jax's CPU backend has no multi-process computations "
           "(_compat.CPU_MULTIPROCESS); the spawned workers would all "
           "die with INVALID_ARGUMENT at the first collective")

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(ROOT, "tests", "mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(outdir: str, nprocs: int, local_devices: int) -> dict:
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("TPU_DIST") and k != "XLA_FLAGS"}
    env.update(JAX_PLATFORMS="cpu",
               TPU_DIST_TEST_OUT=outdir,
               TPU_DIST_LOCAL_DEVICES=str(local_devices),
               TPU_DIST_EXPECT_PROCS=str(nprocs))
    return env


def run_workers(tmp, tag: str, nprocs: int, local_devices: int,
                timeout: int = 420, worker: str = WORKER,
                extra_env: dict = None) -> str:
    outdir = os.path.join(tmp, tag)
    os.makedirs(outdir, exist_ok=True)
    base = _worker_env(outdir, nprocs, local_devices)
    base.update(extra_env or {})
    procs = []
    port = _free_port()
    for rank in range(nprocs):
        env = dict(base)
        if nprocs > 1:  # env:// rendezvous (reference 2.distributed.py:98)
            env.update(TPU_DIST_COORDINATOR=f"127.0.0.1:{port}",
                       TPU_DIST_NUM_PROCESSES=str(nprocs),
                       TPU_DIST_PROCESS_ID=str(rank))
        log = open(os.path.join(outdir, f"worker-{rank}.log"), "w")
        procs.append((rank, log, subprocess.Popen(
            [sys.executable, worker], env=env, cwd=ROOT,
            stdout=log, stderr=subprocess.STDOUT)))
    failed = []
    for rank, log, p in procs:
        try:
            rc = p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            rc = -9
        log.close()
        if rc != 0:
            with open(os.path.join(outdir, f"worker-{rank}.log")) as f:
                failed.append(f"worker {rank} rc={rc}\n{f.read()[-2000:]}")
    assert not failed, "\n".join(failed)
    return outdir


def _load(outdir: str):
    with open(os.path.join(outdir, "result.json")) as f:
        result = json.load(f)
    with np.load(os.path.join(outdir, "params.npz")) as z:
        params = {k: z[k] for k in z.files}
    return result, params


@pytest.fixture(scope="module")
def runs(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp("mp"))
    single = run_workers(tmp, "single", nprocs=1, local_devices=4)
    multi = run_workers(tmp, "multi", nprocs=2, local_devices=2)
    return _load(single), _load(multi)


def test_multiprocess_rendezvous(runs):
    (res1, _), (res2, _) = runs
    assert res1["process_count"] == 1 and res1["method"] == "local"
    assert res2["process_count"] == 2 and res2["method"] == "env"
    # both completed the same number of optimizer steps
    assert res1["step"] == res2["step"] > 0


def test_multiprocess_params_match_single_process(runs):
    """2 procs x 2 devices == 1 proc x 4 devices, parameter-for-parameter."""
    (_, p1), (_, p2) = runs
    assert p1.keys() == p2.keys() and len(p1) > 0
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=2e-4, atol=2e-5,
                                   err_msg=f"leaf {k}")


def test_multiprocess_metrics_match(runs):
    (res1, _), (res2, _) = runs
    # distributed eval (psum'd metric sums, padding masked) must agree too
    assert res1["best_acc1"] == pytest.approx(res2["best_acc1"], abs=1e-3)


def test_multiprocess_windowed_device_data_matches(runs, tmp_path):
    """steps_per_dispatch>1 with the HBM-resident indexed data path across 2
    REAL processes == the single-process per-batch run: exercises
    make_array_from_process_local_data on (K,B) index windows (each process
    contributes only its sampler shard's indices)."""
    windowed = run_workers(str(tmp_path), "windowed", nprocs=2,
                           local_devices=2,
                           extra_env={"TPU_DIST_TEST_K": "2"})
    (_, p1), _ = runs  # the fixture's single-process per-batch run
    _, p2 = _load(windowed)
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=2e-4, atol=2e-5,
                                   err_msg=f"leaf {k}")


def test_multiprocess_lm_params_match_single_process(tmp_path):
    """The LM engine across 2 REAL processes == 1 process (VERDICT r2 #1's
    bit-match requirement): same corpus, same sampler rows, same final
    parameters — including the HBM-resident windowed path, whose (K, B)
    index windows cross make_array_from_process_local_data."""
    worker = os.path.join(ROOT, "tests", "mp_lm_worker.py")
    single = run_workers(str(tmp_path), "lm-single", nprocs=1,
                         local_devices=4, worker=worker)
    multi = run_workers(str(tmp_path), "lm-multi", nprocs=2,
                        local_devices=2, worker=worker,
                        extra_env={"TPU_DIST_TEST_K": "2"})
    (res1, p1), (res2, p2) = _load(single), _load(multi)
    assert res1["process_count"] == 1 and res2["process_count"] == 2
    assert res2["method"] == "env"
    assert res1["step"] == res2["step"] > 0
    assert p1.keys() == p2.keys() and len(p1) > 0
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=2e-4, atol=2e-5,
                                   err_msg=f"leaf {k}")
    assert res1["best_ppl"] == pytest.approx(res2["best_ppl"], rel=1e-3)


def test_multiprocess_lm_loss_chunk_matches_full(tmp_path):
    """--loss-chunk (round 4, chunked vocab CE) across 2 REAL processes
    trains to the same parameters as the 2-process full-logits run — the
    chunked custom_vjp is process-topology-invariant."""
    worker = os.path.join(ROOT, "tests", "mp_lm_worker.py")
    full = run_workers(str(tmp_path), "lm-full", nprocs=2,
                       local_devices=2, worker=worker)
    chunk = run_workers(str(tmp_path), "lm-chunk", nprocs=2,
                        local_devices=2, worker=worker,
                        extra_env={"TPU_DIST_TEST_LOSS_CHUNK": "40"})
    (res1, p1), (res2, p2) = _load(full), _load(chunk)
    assert res1["process_count"] == res2["process_count"] == 2
    assert p1.keys() == p2.keys() and len(p1) > 0
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=2e-4, atol=2e-5,
                                   err_msg=f"leaf {k}")


@pytest.mark.parametrize("mode", ["tp", "sp", "pp", "ep"])
def test_multiprocess_model_parallel_matches_single(tmp_path, mode):
    """TP / SP / PP / EP train steps with the MODEL axis spanning 2 REAL
    processes == the same mesh in one process (VERDICT r2 weak #4 — the
    last untested distribution regime): Megatron collectives, the ring
    ppermute, the pipeline stage hop, and the MoE expert dispatch each
    cross a jax.distributed process boundary."""
    worker = os.path.join(ROOT, "tests", "mp_modes_worker.py")
    env = {"TPU_DIST_TEST_MPMODE": mode}
    single = run_workers(str(tmp_path), f"{mode}-single", nprocs=1,
                         local_devices=4, worker=worker, extra_env=env)
    multi = run_workers(str(tmp_path), f"{mode}-multi", nprocs=2,
                        local_devices=2, worker=worker, extra_env=env)
    (res1, p1), (res2, p2) = _load(single), _load(multi)
    assert res1["process_count"] == 1 and res2["process_count"] == 2
    assert res1["step"] == res2["step"] == 3
    assert res1["loss_sum"] == pytest.approx(res2["loss_sum"], rel=1e-4)
    assert p1.keys() == p2.keys() and len(p1) > 0
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=2e-4, atol=2e-5,
                                   err_msg=f"{mode} leaf {k}")


def test_multiprocess_shard_map_engine_matches_single(tmp_path):
    """The explicit-collective (horovod-equivalent) image engine across 2
    real processes == single process — the shard_map psum path over a real
    boundary, with bf16 gradient compression on."""
    env = {"TPU_DIST_TEST_VARIANT": "shard_map",
           "TPU_DIST_TEST_COMPRESSION": "bf16"}
    single = run_workers(str(tmp_path), "sm-single", nprocs=1,
                         local_devices=4, extra_env=env)
    multi = run_workers(str(tmp_path), "sm-multi", nprocs=2,
                        local_devices=2, extra_env=env)
    (_, p1), (_, p2) = _load(single), _load(multi)
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=2e-4, atol=2e-5,
                                   err_msg=f"leaf {k}")


def test_multiprocess_sharded_checkpoint(tmp_path):
    """FSDP leaves sharded ACROSS processes (non-addressable) save and
    restore bit-exactly — the collective process_allgather path."""
    worker = os.path.join(ROOT, "tests", "mp_ckpt_worker.py")
    outdir = run_workers(str(tmp_path), "ckpt", nprocs=2, local_devices=2,
                         worker=worker)
    with open(os.path.join(outdir, "ckpt_result.json")) as f:
        res = json.load(f)
    assert res["nonaddressable_leaves"] > 0
    assert res["meta_epoch"] == 1
    assert res["ok"], res
