"""One worker of a loopback model-parallel run (test_multiprocess, VERDICT r2
weak #4: 'model-parallel axes have never crossed a real process boundary').

The mesh puts the MODEL-parallel axis FIRST, so in the 2-process run that
axis spans the two processes: Megatron TP collectives, the ring-attention
ppermute, the pipeline stage hop, and the MoE expert dispatch each cross a
real jax.distributed boundary — the regime single-process virtual meshes
cannot reach. Data is fed with jax.make_array_from_callback (each process
materializes only its addressable shards from the same deterministic global
batch), and final params are gathered with the collective
checkpoint.gather_to_host path (cross-process param shards for tp/pp/ep).

Env: TPU_DIST_TEST_MPMODE = tp | sp | pp | ep.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    out = os.environ["TPU_DIST_TEST_OUT"]
    mode = os.environ.get("TPU_DIST_TEST_MPMODE", "tp")
    local_devices = int(os.environ.get("TPU_DIST_LOCAL_DEVICES", "2"))

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist._compat import set_cpu_device_count
    set_cpu_device_count(local_devices)

    from tpu_dist.parallel import launch

    info = launch.initialize()
    expected = int(os.environ.get("TPU_DIST_EXPECT_PROCS", "1"))
    assert jax.process_count() == expected, (jax.process_count(), expected)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dist.engine.checkpoint import gather_to_host
    from tpu_dist.engine.lm_steps import (make_lm_batches,
                                          make_lm_sp_train_step,
                                          make_lm_train_step)
    from tpu_dist.engine.state import TrainState
    from tpu_dist.models.transformer import tiny_lm
    from tpu_dist.ops import make_optimizer
    from tpu_dist.parallel.mesh import make_mesh, replicated

    V, L, B, STEPS = 64, 32, 4, 3
    axis = {"tp": "model", "sp": "seq", "pp": "stage", "ep": "expert"}[mode]
    # model axis FIRST: it spans processes in the 2-proc x 2-device run
    mesh = make_mesh((2, 2), (axis, "data"))

    lm_kw = dict(vocab_size=V, num_layers=2, d_model=32, num_heads=4,
                 max_len=L)
    tx = make_optimizer(0.05, 0.9, 0.0, steps_per_epoch=100)
    if mode == "ep":
        from tpu_dist.models.moe import MoETransformerLM
        from tpu_dist.parallel.ep import shard_state_ep

        model = MoETransformerLM(num_experts=2, **lm_kw)
        params = model.init({"params": jax.random.PRNGKey(0)},
                            jnp.zeros((1, L), jnp.int32),
                            train=False)["params"]
        state = shard_state_ep(mesh, TrainState.create(params, {}, tx))
        step = make_lm_train_step(model, tx, mesh, donate=False)
        data_spec = P("data")
    else:
        model = tiny_lm(**lm_kw)
        params = model.init({"params": jax.random.PRNGKey(0)},
                            jnp.zeros((1, L), jnp.int32),
                            train=False)["params"]
        if mode == "tp":
            from tpu_dist.parallel.tp import shard_lm_params

            st = TrainState.create(params, {}, tx)
            state = TrainState(
                step=jax.device_put(st.step, NamedSharding(mesh, P())),
                params=shard_lm_params(mesh, st.params), batch_stats={},
                opt_state=jax.device_put(st.opt_state,
                                         NamedSharding(mesh, P())),
                loss_scale=None)
            step = make_lm_train_step(model, tx, mesh, donate=False)
            data_spec = P("data")
        elif mode == "sp":
            from functools import partial

            state = jax.device_put(TrainState.create(params, {}, tx),
                                   replicated(mesh))
            step = make_lm_sp_train_step(partial(tiny_lm, **lm_kw), tx,
                                         mesh, donate=False)
            data_spec = P("data", "seq")
        else:  # pp
            from tpu_dist.parallel.pp import (make_lm_pp_train_step,
                                              shard_state_pp,
                                              stack_pipeline_params)

            params = stack_pipeline_params(params, 2)
            state = shard_state_pp(mesh, TrainState.create(params, {}, tx))
            step = make_lm_pp_train_step(model, tx, mesh,
                                         num_microbatches=2, donate=False)
            data_spec = P("data", None)

    # same deterministic global batch in every run; each process materializes
    # only its addressable shards via the callback
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, V, (B, L + 1)).astype(np.int32)
    inputs_np, targets_np = make_lm_batches(tokens)
    sh = NamedSharding(mesh, data_spec)

    def put(arr):
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx: arr[idx])

    inputs, targets = put(np.ascontiguousarray(inputs_np)), \
        put(np.ascontiguousarray(targets_np))
    key = jax.random.PRNGKey(1)
    for _ in range(STEPS):
        state, metrics = step(state, inputs, targets, key)
    loss_sum = float(jax.device_get(metrics["loss_sum"]))

    # collective for cross-process shards — every process must call
    host_params = gather_to_host(state.params)
    if jax.process_index() == 0:
        leaves = jax.tree_util.tree_leaves(host_params)
        np.savez(os.path.join(out, "params.npz"),
                 **{f"p{i}": np.asarray(x, np.float32)
                    for i, x in enumerate(leaves)})
        with open(os.path.join(out, "result.json"), "w") as f:
            json.dump({"mode": mode, "loss_sum": loss_sum,
                       "process_count": jax.process_count(),
                       "method": info.method,
                       "step": int(np.asarray(jax.device_get(state.step)))},
                      f)


if __name__ == "__main__":
    main()
