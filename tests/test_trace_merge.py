"""Cross-process trace merge (tools/trace_merge.py) — no jax needed.

Covers: two synthetic .pN ledgers merging into one valid Chrome-trace
JSON with a lane per process (phase slices, comm overlay, alert instants,
skew/hbm counters, metadata names), per-process clock normalization to
run_start, CLI sibling discovery + output file, and the crash-tolerance
satellite: a truncated trailing JSONL line is skipped with a warning by
both trace_merge and ledger_report instead of raising.
"""

import json

import pytest

from tpu_dist.obs.ledger import read_ledger
from tools.trace_merge import discover_ledgers, main, merge_ledgers


def _write_ledger(path, pid, t0):
    """Hand-write a schema-conformant ledger with DETERMINISTIC timestamps
    (Ledger.emit stamps wall time; the merge math needs fixed numbers)."""
    recs = [
        {"event": "run_start", "ts": t0, "pid": pid, "kind": "lm",
         "config": {}, "mesh": {"data": 2}, "devices": ["cpu"],
         "process_count": 2},
        {"event": "compile", "ts": t0 + 1.0, "pid": pid,
         "program": "train_step"},
        {"event": "step", "ts": t0 + 2.0, "pid": pid, "step": 0, "loss": 2.0,
         "throughput": 1000.0, "unit": "tok/s", "data_s": 0.1,
         "dispatch_s": 0.2, "device_s": 0.5, "comm_s": 0.2, "mfu": 0.5,
         "steps_in_dispatch": 1},
        {"event": "skew", "ts": t0 + 2.5, "pid": pid, "step": 0,
         "p50_s": 0.1, "p99_s": 0.2, "spread_s": 0.01 * (pid + 1),
         "straggler": 1},
        {"event": "health", "ts": t0 + 2.6, "pid": pid, "step": 1,
         "kind": "nonfinite", "policy": "skip", "action": "skip",
         "value": 3.0},
        {"event": "stall", "ts": t0 + 2.7, "pid": pid, "idle_s": 9.0,
         "threshold_s": 5.0, "stacks": "..."},
        {"event": "hbm", "ts": t0 + 2.8, "pid": pid, "bytes_in_use": 1024},
        {"event": "eval", "ts": t0 + 3.0, "pid": pid, "epoch": 0,
         "loss": 1.5},
        {"event": "epoch", "ts": t0 + 3.5, "pid": pid, "epoch": 0,
         "start_ts": t0 + 1.0, "seconds": 2.5, "throughput": 900.0,
         "unit": "tok/s", "loss": 1.8},
        {"event": "run_end", "ts": t0 + 4.0, "pid": pid, "steps": 1,
         "seconds": 4.0, "status": "ok"},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return recs


def test_merge_two_process_ledgers(tmp_path):
    base = str(tmp_path / "run.jsonl")
    sib = str(tmp_path / "run.p1.jsonl")
    # process clocks deliberately offset by 100s: normalization per
    # run_start must line both lanes up near t=0
    _write_ledger(base, 0, t0=1000.0)
    _write_ledger(sib, 1, t0=1100.0)

    assert discover_ledgers(base) == [base, sib]
    trace = merge_ledgers([base, sib])
    txt = json.dumps(trace)       # valid JSON end to end
    trace = json.loads(txt)
    ev = trace["traceEvents"]
    assert trace["otherData"]["processes"] == 2
    pids = {e["pid"] for e in ev}
    assert pids == {0, 1}

    for pid in (0, 1):
        lane = [e for e in ev if e["pid"] == pid]
        names = {e["name"] for e in lane}
        # phase slices, overlays, instants, counters, metadata all present
        assert {"data", "dispatch", "device", "comm"} <= names
        assert "STALL" in names and "health:nonfinite" in names
        assert "skew spread (ms)" in names and "hbm bytes" in names
        assert any(e["ph"] == "M" and e["name"] == "process_name"
                   for e in lane)
        # clock normalized to the process's OWN run_start: everything in
        # the first handful of seconds, never at the 100s wall offset
        times = [e["ts"] for e in lane if "ts" in e]
        assert min(times) >= 0 and max(times) < 10e6
        # the step's three slices are back-to-back and end at the emit ts
        dev = [e for e in lane if e["name"] == "device"][0]
        assert dev["ts"] + dev["dur"] == pytest.approx(2.0e6, abs=1)
        comm = [e for e in lane if e["name"] == "comm"][0]
        assert comm["ts"] == pytest.approx(dev["ts"])
        ep = [e for e in lane if e["name"] == "epoch 0"][0]
        assert ep["dur"] == pytest.approx(2.5e6)


def test_cli_discovers_siblings_and_writes_trace(tmp_path, capsys):
    base = str(tmp_path / "run.jsonl")
    _write_ledger(base, 0, t0=0.0)
    _write_ledger(str(tmp_path / "run.p1.jsonl"), 1, t0=0.0)
    out = str(tmp_path / "merged.json")
    assert main([base, "-o", out]) == 0
    assert "2 process lane(s)" in capsys.readouterr().out
    with open(out) as f:
        trace = json.load(f)
    assert {e["pid"] for e in trace["traceEvents"]} == {0, 1}


def test_truncated_trailing_line_is_skipped_with_warning(tmp_path, capsys):
    """The crash satellite: a writer killed mid-write leaves a torn final
    line; the tolerant readers (trace_merge, ledger_report) must keep
    every intact record and warn instead of raising."""
    base = str(tmp_path / "run.jsonl")
    recs = _write_ledger(base, 0, t0=0.0)
    with open(base, "a") as f:
        f.write('{"event": "step", "ts": 99.0, "pid": 0, "loss"')  # torn
    with pytest.raises(Exception):
        read_ledger(base)  # strict default still raises (engine contract)
    kept = read_ledger(base, strict=False)
    assert len(kept) == len(recs)
    assert "skipping corrupt/truncated" in capsys.readouterr().err

    trace = merge_ledgers([base])
    assert trace["otherData"]["processes"] == 1
    # ledger_report's CLI path reads tolerantly too and renders health
    from tools.ledger_report import main as report_main, summarize

    lines = []
    counts = summarize(kept, out=lines.append)
    assert counts["health"] == 1
    assert any("HEALTH TRIPS: 1" in ln for ln in lines)
    assert report_main([base]) == 0
    capsys.readouterr()


def test_unknown_future_event_skipped_not_fatal(tmp_path):
    """A ledger written by a NEWER tpu_dist (an event this tree does not
    declare) merges with a warning — operators debug across versions."""
    base = str(tmp_path / "run.jsonl")
    _write_ledger(base, 0, t0=0.0)
    with open(base, "a") as f:
        f.write(json.dumps({"event": "from_the_future", "ts": 5.0,
                            "pid": 0}) + "\n")
    trace = merge_ledgers([base])
    assert trace["otherData"]["processes"] == 1
