"""Dataset/loader/pipeline tests (reference C4/C13 equivalents)."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist.data import (DataLoader, DistributedSampler, load_dataset,
                           make_transform, prefetch_to_device)
from tpu_dist.data.datasets import CIFAR10_MEAN, CIFAR10_STD


def test_synthetic_deterministic_and_learnable_split():
    tr1, va1 = load_dataset("synthetic-cifar10", "/nonexistent", 256, 64, seed=7)
    tr2, va2 = load_dataset("synthetic-cifar10", "/nonexistent", 256, 64, seed=7)
    np.testing.assert_array_equal(tr1.images, tr2.images)
    # train and val must share class structure (same prototypes, diff samples)
    assert not np.array_equal(tr1.images[:64], va1.images)
    assert tr1.images.shape == (256, 32, 32, 3)
    assert tr1.images.dtype == np.uint8


def test_loader_yields_full_uint8_batches():
    tr, _ = load_dataset("synthetic-mnist", "/nonexistent", 100, 10, seed=3)
    sampler = DistributedSampler(len(tr), 2, 0, shuffle=True, batch_size=16)
    loader = DataLoader(tr, sampler, 16)
    batches = list(loader)
    assert len(batches) == len(loader)
    for imgs, labels in batches:
        assert imgs.shape == (16, 28, 28, 1)
        assert imgs.dtype == np.uint8
        assert labels.shape == (16,)


def test_transform_matches_totensor_normalize():
    # ToTensor (/255) + Normalize(mean, std), reference 2.distributed.py:127-136
    img = np.full((1, 2, 2, 3), 128, np.uint8)
    t = make_transform(CIFAR10_MEAN, CIFAR10_STD)
    out = np.asarray(t(jnp.asarray(img)))
    expected = (128 / 255.0 - CIFAR10_MEAN) / CIFAR10_STD
    np.testing.assert_allclose(out[0, 0, 0], expected, rtol=1e-5)


def test_augmented_transform_preserves_shape_and_is_random():
    t = make_transform(np.zeros(3, np.float32), np.ones(3, np.float32),
                       augment=True, max_shift=2)
    img = np.random.default_rng(0).integers(0, 255, (4, 8, 8, 3)).astype(np.uint8)
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    o1 = np.asarray(t(jnp.asarray(img), k1))
    o2 = np.asarray(t(jnp.asarray(img), k2))
    assert o1.shape == img.shape
    assert not np.array_equal(o1, o2)


def test_prefetch_to_device_preserves_order():
    batches = [(np.full((2, 2), i, np.uint8), np.array([i, i])) for i in range(5)]
    out = list(prefetch_to_device(iter(batches), None, size=3))
    assert len(out) == 5
    for i, (imgs, labels) in enumerate(out):
        assert int(np.asarray(imgs)[0, 0]) == i


def test_stream_prefetch_passes_none_and_exception_items():
    """Tagged control envelopes (ADVICE r3): a producer may legitimately
    yield None or exception INSTANCES as items — neither truncates the
    stream nor raises — while a raising producer still propagates."""
    from tpu_dist.data.loader import stream_prefetch

    items = [1, None, ValueError("payload, not control"), 4]
    out = list(stream_prefetch(iter(items)))
    assert out[0] == 1 and out[1] is None and out[3] == 4
    assert isinstance(out[2], ValueError)

    def boom():
        yield 1
        raise RuntimeError("assembly failed")

    got = []
    try:
        for x in stream_prefetch(boom()):
            got.append(x)
        raised = False
    except RuntimeError:
        raised = True
    assert raised and got == [1]


def test_token_bin_size_alignment_checked(tmp_path):
    """A .bin whose byte size is not a whole number of tokens for the
    configured dtype fails loudly instead of yielding garbage ids."""
    import os

    import pytest

    from tpu_dist.data.tokens import _load_stream

    p = tmp_path / "odd.bin"
    p.write_bytes(b"\x01\x02\x03")  # 3 bytes: not divisible by uint16
    with pytest.raises(ValueError, match="whole number"):
        _load_stream(str(p))
    os.environ["TPU_DIST_TOKEN_DTYPE"] = "uint32"
    try:
        q = tmp_path / "ok16.bin"
        q.write_bytes(np.arange(6, dtype=np.uint16).tobytes())  # 12 bytes
        arr, _ = _load_stream(str(q))  # 4-aligned: loads as uint32
        assert arr.dtype == np.uint32
    finally:
        del os.environ["TPU_DIST_TOKEN_DTYPE"]


def test_loader_propagates_worker_errors():
    class Bad:
        def get_batch(self, idx):
            raise RuntimeError("decode failed")

    sampler = DistributedSampler(32, 1, 0, batch_size=8)
    loader = DataLoader(Bad(), sampler, 8)
    try:
        list(loader)
        raised = False
    except RuntimeError:
        raised = True
    assert raised
