"""Dataset/loader/pipeline tests (reference C4/C13 equivalents)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist.data import (DataLoader, DistributedSampler, load_dataset,
                           make_transform, prefetch_to_device)
from tpu_dist.data.datasets import CIFAR10_MEAN, CIFAR10_STD


def test_synthetic_deterministic_and_learnable_split():
    tr1, va1 = load_dataset("synthetic-cifar10", "/nonexistent", 256, 64, seed=7)
    tr2, va2 = load_dataset("synthetic-cifar10", "/nonexistent", 256, 64, seed=7)
    np.testing.assert_array_equal(tr1.images, tr2.images)
    # train and val must share class structure (same prototypes, diff samples)
    assert not np.array_equal(tr1.images[:64], va1.images)
    assert tr1.images.shape == (256, 32, 32, 3)
    assert tr1.images.dtype == np.uint8


def test_loader_yields_full_uint8_batches():
    tr, _ = load_dataset("synthetic-mnist", "/nonexistent", 100, 10, seed=3)
    sampler = DistributedSampler(len(tr), 2, 0, shuffle=True, batch_size=16)
    loader = DataLoader(tr, sampler, 16)
    batches = list(loader)
    assert len(batches) == len(loader)
    for imgs, labels in batches:
        assert imgs.shape == (16, 28, 28, 1)
        assert imgs.dtype == np.uint8
        assert labels.shape == (16,)


def test_transform_matches_totensor_normalize():
    # ToTensor (/255) + Normalize(mean, std), reference 2.distributed.py:127-136
    img = np.full((1, 2, 2, 3), 128, np.uint8)
    t = make_transform(CIFAR10_MEAN, CIFAR10_STD)
    out = np.asarray(t(jnp.asarray(img)))
    expected = (128 / 255.0 - CIFAR10_MEAN) / CIFAR10_STD
    np.testing.assert_allclose(out[0, 0, 0], expected, rtol=1e-5)


def test_augmented_transform_preserves_shape_and_is_random():
    t = make_transform(np.zeros(3, np.float32), np.ones(3, np.float32),
                       augment=True, max_shift=2)
    img = np.random.default_rng(0).integers(0, 255, (4, 8, 8, 3)).astype(np.uint8)
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    o1 = np.asarray(t(jnp.asarray(img), k1))
    o2 = np.asarray(t(jnp.asarray(img), k2))
    assert o1.shape == img.shape
    assert not np.array_equal(o1, o2)


def test_prefetch_to_device_preserves_order():
    batches = [(np.full((2, 2), i, np.uint8), np.array([i, i])) for i in range(5)]
    out = list(prefetch_to_device(iter(batches), None, size=3))
    assert len(out) == 5
    for i, (imgs, labels) in enumerate(out):
        assert int(np.asarray(imgs)[0, 0]) == i


def test_device_prefetcher_order_stats_and_clean_shutdown():
    """DevicePrefetcher (the round-9 double-buffered upload pipeline):
    batches arrive in order, the overlap ledger counts them, and
    exhaustion JOINS the producer thread (DL103's clean path, not just
    the daemon backstop)."""
    from tpu_dist.data.loader import DevicePrefetcher

    batches = [np.full((4,), i, np.int32) for i in range(7)]
    pf = DevicePrefetcher(iter(batches), depth=2)
    out = list(pf)
    assert [int(np.asarray(b)[0]) for b in out] == list(range(7))
    st = pf.stats()
    assert st["batches"] == 7 and st["put_s"] >= 0.0
    assert st["overlap_efficiency"] is None or 0.0 <= st["overlap_efficiency"] <= 1.0
    assert not pf._thread.is_alive()


def test_device_prefetcher_abandonment_stops_producer():
    """Breaking out of the consuming loop (generator close) must stop and
    join the producer — an epoch cut short never leaves an upload thread
    feeding a dead consumer."""
    from tpu_dist.data.loader import DevicePrefetcher

    def endless():
        i = 0
        while True:
            yield np.full((2,), i, np.int32)
            i += 1

    pf = DevicePrefetcher(endless(), depth=2)
    it = iter(pf)
    assert int(np.asarray(next(it))[0]) == 0
    assert int(np.asarray(next(it))[0]) == 1
    it.close()                      # consumer abandons mid-stream
    assert not pf._thread.is_alive()


def test_device_prefetcher_error_propagates_and_joins():
    from tpu_dist.data.loader import DevicePrefetcher

    def boom():
        yield np.zeros((2,), np.int32)
        raise RuntimeError("assembly failed")

    pf = DevicePrefetcher(boom())
    it = iter(pf)
    next(it)
    with pytest.raises(RuntimeError, match="assembly failed"):
        next(it)
    assert not pf._thread.is_alive()


def test_device_prefetcher_composes_with_sampler_epochs():
    """One prefetcher per epoch over the loader's stream: every epoch
    yields exactly len(loader) batches, set_epoch reshuffles between them
    (different batch content), and the same-epoch replay is bit-identical
    — the sampler/epoch logic needs no special casing in the prefetcher."""
    from tpu_dist.data.loader import DevicePrefetcher

    tr, _ = load_dataset("synthetic-mnist", "/nonexistent", 64, 10, seed=5)
    sampler = DistributedSampler(len(tr), 1, 0, shuffle=True, batch_size=16)
    loader = DataLoader(tr, sampler, 16)

    def epoch_batches(epoch):
        sampler.set_epoch(epoch)
        pf = DevicePrefetcher(iter(loader), depth=2)
        out = [np.asarray(imgs) for imgs, _ in pf]
        assert not pf._thread.is_alive()
        return out

    e0, e1, e0_again = (epoch_batches(0), epoch_batches(1),
                        epoch_batches(0))
    assert len(e0) == len(e1) == len(loader)
    assert any(not np.array_equal(a, b) for a, b in zip(e0, e1))
    assert all(np.array_equal(a, b) for a, b in zip(e0, e0_again))


def test_stream_prefetch_passes_none_and_exception_items():
    """Tagged control envelopes (ADVICE r3): a producer may legitimately
    yield None or exception INSTANCES as items — neither truncates the
    stream nor raises — while a raising producer still propagates."""
    from tpu_dist.data.loader import stream_prefetch

    items = [1, None, ValueError("payload, not control"), 4]
    out = list(stream_prefetch(iter(items)))
    assert out[0] == 1 and out[1] is None and out[3] == 4
    assert isinstance(out[2], ValueError)

    def boom():
        yield 1
        raise RuntimeError("assembly failed")

    got = []
    try:
        for x in stream_prefetch(boom()):
            got.append(x)
        raised = False
    except RuntimeError:
        raised = True
    assert raised and got == [1]


def test_token_bin_size_alignment_checked(tmp_path):
    """A .bin whose byte size is not a whole number of tokens for the
    configured dtype fails loudly instead of yielding garbage ids."""
    import os

    import pytest

    from tpu_dist.data.tokens import _load_stream

    p = tmp_path / "odd.bin"
    p.write_bytes(b"\x01\x02\x03")  # 3 bytes: not divisible by uint16
    with pytest.raises(ValueError, match="whole number"):
        _load_stream(str(p))
    os.environ["TPU_DIST_TOKEN_DTYPE"] = "uint32"
    try:
        q = tmp_path / "ok16.bin"
        q.write_bytes(np.arange(6, dtype=np.uint16).tobytes())  # 12 bytes
        arr, _ = _load_stream(str(q))  # 4-aligned: loads as uint32
        assert arr.dtype == np.uint32
    finally:
        del os.environ["TPU_DIST_TOKEN_DTYPE"]


def test_loader_propagates_worker_errors():
    class Bad:
        def get_batch(self, idx):
            raise RuntimeError("decode failed")

    sampler = DistributedSampler(32, 1, 0, batch_size=8)
    loader = DataLoader(Bad(), sampler, 8)
    try:
        list(loader)
        raised = False
    except RuntimeError:
        raised = True
    assert raised
