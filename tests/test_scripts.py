"""Cookbook smoke tests (VERDICT r1 #9): every scripts/N.py entrypoint runs.

The suite otherwise tests the library; these run the actual CLI surface the
README advertises — parser, per-variant defaults, launch.initialize, Trainer
wiring — for one tiny synthetic epoch each, in a subprocess on CPU (the same
scripts run unchanged on TPU; see .claude/skills/verify for the TPU drive).
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(ROOT, "scripts")

TINY = ["--epochs", "1", "--batch-size", "32", "--arch", "lenet",
        "--dataset", "synthetic-mnist", "--synth-train-size", "96",
        "--synth-val-size", "32", "--workers", "1", "--print-freq", "100"]


def run_script(tmp, name, args, env_extra=None, timeout=300):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("TPU_DIST") and k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, name), *args],
        env=env, cwd=str(tmp), capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"{name} rc={proc.returncode}\nstdout:\n{proc.stdout[-2000:]}\n"
        f"stderr:\n{proc.stderr[-2000:]}")
    return proc.stdout


def ck(tmp):
    return ["--checkpoint-dir", os.path.join(str(tmp), "ck")]


def test_script_1_dataparallel(tmp_path):
    out = run_script(tmp_path, "1.dataparallel.py", TINY + ck(tmp_path))
    assert "best_acc1" in out
    assert os.path.exists(tmp_path / "dataparallel.csv")  # C21 CSV default


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_script_2_distributed(tmp_path):
    out = run_script(tmp_path, "2.distributed.py", TINY + ck(tmp_path))
    assert "rendezvous=local" in out and "best_acc1" in out


def test_script_3_spawn_two_processes(tmp_path):
    from tpu_dist._compat import CPU_MULTIPROCESS
    if not CPU_MULTIPROCESS:
        pytest.skip("this jax's CPU backend has no multi-process "
                    "computations (_compat.CPU_MULTIPROCESS)")
    out = run_script(tmp_path, "3.multiprocessing_spawn.py",
                     TINY + ck(tmp_path),
                     env_extra={"TPU_DIST_NPROCS_SPAWN": "2"})
    assert "best_acc1" in out


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_script_4_bf16(tmp_path):
    out = run_script(tmp_path, "4.bf16_distributed.py", TINY + ck(tmp_path))
    assert "best_acc1" in out


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_script_5_allreduce(tmp_path):
    out = run_script(tmp_path, "5.allreduce_distributed.py",
                     TINY + ck(tmp_path))
    assert "best_acc1" in out


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_script_5_2_mnist(tmp_path):
    out = run_script(tmp_path, "5.2.mnist.py", TINY + ck(tmp_path))
    assert "best_acc1" in out


def test_script_6_slurm_fallback_local(tmp_path):
    # no SLURM env -> local single-process; dataset overridden to synthetic
    out = run_script(tmp_path, "6.distributed_slurm.py", TINY + ck(tmp_path))
    assert "best_acc1" in out
    assert os.path.exists(tmp_path / "distributed.csv")


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_script_7_flagship_windowed(tmp_path):
    # keep the flagship's windowed dispatch path (K>1) but shrink the model
    out = run_script(tmp_path, "7.jax_tpu.py",
                     TINY + ck(tmp_path) + ["--steps-per-dispatch", "2"])
    assert "best_acc1" in out
    assert os.path.exists(tmp_path / "jax_tpu.csv")


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_script_8_lm(tmp_path):
    out = run_script(tmp_path, "8.lm_longcontext.py",
                     ["--steps", "3", "--batch-size", "4", "--seq-len", "32",
                      "--d-model", "32", "--num-layers", "1", "--num-heads",
                      "2", "--print-freq", "1", "--synth-tokens", "2000",
                      "--vocab-size", "64", "--generate", "8",
                      "--checkpoint-dir", os.path.join(str(tmp_path), "ck")])
    assert "corpus=synth-affine-train" in out  # real corpus, not fixed batch
    assert "throughput" in out
    assert "ppl" in out            # held-out perplexity surface
    assert "affine rule" in out    # --generate surface


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_script_8_lm_pipeline_mode(tmp_path):
    out = run_script(tmp_path, "8.lm_longcontext.py",
                     ["--mesh", "data=2,stage=2", "--steps", "3",
                      "--batch-size", "4", "--seq-len", "32", "--d-model",
                      "32", "--num-layers", "2", "--num-heads", "2",
                      "--print-freq", "1", "--pp-microbatches", "2",
                      "--synth-tokens", "2000", "--vocab-size", "64"],
                     env_extra={"XLA_FLAGS":
                                "--xla_force_host_platform_device_count=4"})
    assert "mode=pp-gpipe" in out and "throughput" in out and "ppl" in out


def test_script_evaluate_flag(tmp_path):
    # reference -e/--evaluate path (C1): eval-only run, no training
    out = run_script(tmp_path, "5.2.mnist.py",
                     TINY + ck(tmp_path) + ["--evaluate"])
    assert "best_acc1" in out


@pytest.mark.slow  # tier-1 budget (PR 3): heavy; covered by cheaper siblings in-budget
def test_tool_lm_convergence(tmp_path):
    out = run_script(tmp_path, "../tools/lm_convergence.py",
                     ["--synth-tokens", "60000", "--batch-size", "16",
                      "--seq-len", "128", "--d-model", "64", "--threshold",
                      "20", "--max-epochs", "4", "--vocab-size", "128"])
    assert "steps_to_ppl_20" in out


def test_tool_data_rate(tmp_path):
    out = run_script(tmp_path, "../tools/data_rate.py",
                     ["--images", "32", "--size", "64", "--batch", "16",
                      "--seconds", "0.5", "--prefetch-batches", "4",
                      "--prefetch-mb", "1", "--step-ms", "5",
                      "--root", os.path.join(str(tmp_path), "ifolder")])
    assert "host_data_path_images_per_sec" in out
    # the round-9 DevicePrefetcher overlap probe rides the same JSON
    assert "overlap_efficiency" in out and "inline_copy_s" in out


@pytest.mark.slow  # tier-1 budget (PR 7): 14s end-to-end sampler run; the sampler/peak-HBM mechanics stay covered by test_telemetry.py units
def test_telemetry_csv_and_peak_hbm_column(tmp_path):
    """--telemetry-csv samples the 500ms device/host CSV (reference
    statistics.sh analog, C22) and the per-epoch CSV carries the peak-HBM
    column (VERDICT r4 #5; empty value on CPU, where the backend exposes no
    memory counters — the COLUMN must still exist)."""
    import csv as csv_mod

    tele = os.path.join(str(tmp_path), "tele.csv")
    run_script(tmp_path, "1.dataparallel.py",
               TINY + ck(tmp_path) + ["--telemetry-csv", tele])
    with open(tele) as f:
        rows = list(csv_mod.reader(f))
    assert rows[0] == ["ts", "hbm_bytes_in_use", "hbm_peak_bytes",
                       "hbm_bytes_limit", "host_rss_kb"]
    assert len(rows) >= 2          # ran long enough for >= 1 sample
    assert float(rows[1][0]) > 0   # ts
    assert rows[1][4] != ""        # host RSS always present on linux

    with open(tmp_path / "dataparallel.csv") as f:
        epoch_rows = list(csv_mod.reader(f))
    assert len(epoch_rows[0]) == 4  # start, secs, img/s, peak_hbm
