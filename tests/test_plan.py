"""Plan IR + compiler + auto-tuner (tpu_dist.plan, round 15).

Three layers, cheapest first:

* **no-jax units** — the Plan IR (round-trip, hash determinism,
  validation, the mesh-axis authority pin) and the tuner (exact expected
  winner over the checked-in canned measurement file, byte-determinism,
  trial-specificity) exercise modules that must import under the
  scripts/lint.sh jax blocker;
* **CPU parity** — ``compile_train_step(plan)`` built DIRECTLY from a
  Plan matches every legacy ``make_*`` builder's loss/param trajectory
  bit-for-bit (the builders are shims over the compiler now; these pin
  the plan-field -> builder-argument mapping);
* **engine acceptance** — both engines accept an emitted plan file via
  the new ``plan`` config knob, stamp it into run_start + a ``plan``
  ledger event, and ledger_report renders it.
"""

from __future__ import annotations

import ast
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_dist.plan.ir import (DEFAULT_OPT_BLOCK_ROWS, DEFAULT_QUANT_BLOCK,
                              KNOWN_AXES, Plan, PlanError,
                              apply_plan_to_config, load_plan_file,
                              plan_for_device, plan_hash, plan_knob_summary)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TUNE_CI = os.path.join(REPO, "scripts", "tune_ci.json")


@pytest.fixture
def clean_plan_globals():
    """Restore the plan-owned trace-time globals (fused switch, Pallas
    blocks) after a test that activates a plan."""
    yield
    from tpu_dist.ops import pallas_quant, pallas_sgd
    from tpu_dist.ops.quant import set_fused_quant

    set_fused_quant(None)
    pallas_quant.set_quant_blocks()
    pallas_sgd.set_block_rows()


# ---- IR units (no jax in the modules under test) --------------------------

def test_plan_roundtrip_and_hash_determinism():
    p = Plan(engine="lm", quant="int8", sync="explicit",
             grad_bucket_mb=25.0, window="indexed", steps_per_dispatch=16,
             quant_block=(256, 128, 0), opt_block_rows=1024).validate()
    q = Plan.from_json(p.to_json())
    assert q == p and hash(q) == hash(p)
    assert plan_hash(p) == plan_hash(q)
    # canonical JSON: key order in the input dict must not matter
    d = p.to_dict()
    shuffled = dict(sorted(d.items(), reverse=True))
    assert plan_hash(Plan.from_dict(shuffled)) == plan_hash(p)
    # any knob change moves the hash
    assert plan_hash(Plan(engine="lm", quant="int8", fused_quant="on")) \
        != plan_hash(Plan(engine="lm", quant="int8"))


def test_plan_validation_rejects_illegal_combinations():
    bad = [
        dict(engine="lm", quant="int4"),
        dict(engine="lm", tp_impl="ring"),                 # needs tp+explicit
        dict(engine="lm", grad_bucket_mb=25.0),            # needs explicit
        dict(engine="lm", layout="sp"),                    # needs explicit
        dict(engine="lm", layout="tp", sync="explicit"),   # tp+explicit=ring
        dict(engine="lm", grad_accum_steps=2, steps_per_dispatch=4,
             window="indexed"),
        dict(engine="lm", adasum=True, sync="explicit"),   # image knob
        dict(engine="lm", window="stacked"),               # image window
        dict(engine="image", layout="sp", sync="explicit"),
        dict(engine="image", loss_chunk=64),
        dict(engine="image", window="indexed", sync="explicit"),
        dict(engine="lm", quant_block=(100, 128, 0)),      # bm % 8
        dict(engine="lm", quant_block=(128, 64, 0)),       # bn % 128
        dict(engine="lm", quant_block=(128, 128, 64)),     # bk % 128
        dict(engine="lm", opt_block_rows=100),
    ]
    for kw in bad:
        with pytest.raises(PlanError):
            Plan(**kw).validate()
    # the image explicit step MAY bucket while ring-pmean'ing over 'model'
    Plan(engine="image", sync="explicit", layout="tp", tp_impl="ring",
         grad_bucket_mb=25.0).validate()


def test_plan_mesh_validation():
    p = Plan(engine="lm", layout="tp", sync="explicit", tp_impl="ring")
    p.validate_against_mesh({"data": 4, "model": 2})
    with pytest.raises(PlanError):
        p.validate_against_mesh({"data": 8})          # no model axis
    with pytest.raises(PlanError):
        Plan(engine="lm").validate_against_mesh({"batch": 8})  # unknown axis


def test_known_axes_matches_mesh_authority():
    """plan.ir mirrors the parallel/mesh.py *_AXIS authority jax-free; an
    axis added there MUST land here too (same AST pin distlint DL003
    uses — neither module imports the other)."""
    tree = ast.parse(open(os.path.join(
        REPO, "tpu_dist", "parallel", "mesh.py")).read())
    axes = [n.value.value for n in ast.walk(tree)
            if isinstance(n, ast.Assign) and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Name)
            and n.targets[0].id.endswith("_AXIS")
            and isinstance(n.value, ast.Constant)]
    assert tuple(axes) == KNOWN_AXES


def test_load_plan_file_and_device_selection(tmp_path):
    full = Plan(engine="lm", quant="int8").to_dict()
    doc = {"version": 1, "plans": {"v5 lite": full,
                                   "default": Plan(engine="lm").to_dict()}}
    path = tmp_path / "plans.json"
    path.write_text(json.dumps(doc))
    plans = load_plan_file(str(path))
    # substring match (the PEAK table convention), then the default
    assert plan_for_device(plans, "TPU v5 lite").quant == "int8"
    assert plan_for_device(plans, "cpu").quant == "none"
    del plans["default"]
    with pytest.raises(PlanError):
        plan_for_device(plans, "cpu")
    # a bare single-plan file keys as 'default'
    path.write_text(json.dumps(full))
    assert plan_for_device(load_plan_file(str(path)), "anything") \
        == Plan.from_dict(full)
    # unknown fields refuse loudly (typo'd knob files must not no-op)
    path.write_text(json.dumps({**full, "qant": "int8"}))
    with pytest.raises(PlanError):
        load_plan_file(str(path))


def test_apply_plan_to_config_both_engines():
    from tpu_dist.configs import LMConfig, TrainConfig

    p = Plan(engine="lm", quant="int8", sync="explicit",
             grad_bucket_mb=25.0, window="indexed", steps_per_dispatch=16,
             loss_chunk=128, health="skip")
    cfg = apply_plan_to_config(LMConfig(seq_len=64), p)
    assert (cfg.quant, cfg.grad_bucket_mb, cfg.steps_per_dispatch,
            cfg.loss_chunk, cfg.health, cfg.data_placement) == \
        ("int8", 25.0, 16, 128, "skip", "device")
    assert cfg.seq_len == 64            # non-plan fields untouched
    ip = Plan(engine="image", sync="explicit", grad_compression="bf16",
              predivide_factor=2.0)
    icfg = apply_plan_to_config(TrainConfig(), ip)
    assert icfg.variant == "shard_map"
    assert icfg.grad_compression == "bf16"
    assert icfg.gradient_predivide_factor == 2.0
    assert apply_plan_to_config(
        TrainConfig(), Plan(engine="image")).variant == "jit"
    with pytest.raises(PlanError):
        apply_plan_to_config(TrainConfig(), p)      # lm plan, image config


def test_plan_knob_summary_is_the_non_default_diff():
    assert plan_knob_summary(Plan(engine="lm")) == {}
    s = plan_knob_summary(Plan(engine="lm", quant="int8",
                               quant_block=(256, 128, 0)))
    assert s == {"quant": "int8", "quant_block": [256, 128, 0]}


# ---- tuner (no jax in the modules under test) -----------------------------

def test_tuner_exact_winner_over_canned_measurements():
    """The checked-in scripts/tune_ci.json names its winner exactly: the
    measured-refinement trial (int8 + bucket 25 + 16-step indexed window +
    256x128 tiles + 1024-row optimizer blocks) must beat every analytic
    candidate."""
    from tpu_dist.plan.tune import tune

    text, results = tune(measurement_files=[TUNE_CI])
    res = results["TPU v5 lite"]
    best = res["best"]
    assert best["measured"] and best["step_s"] == pytest.approx(0.0021)
    knobs = plan_knob_summary(best["plan"])
    assert knobs == {"sync": "explicit", "quant": "int8",
                     "grad_bucket_mb": 25.0, "window": "indexed",
                     "steps_per_dispatch": 16,
                     "quant_block": [256, 128, 0], "opt_block_rows": 1024}
    # the emitted file round-trips through the config-knob loader
    doc = json.loads(text)
    sel = Plan.from_dict(doc["plans"]["TPU v5 lite"])
    assert plan_hash(sel) == best["hash"]
    # peaks resolved from the real tables (v5e), not the nominal fallback
    assert not res["peaks"]["nominal"]
    assert res["peaks"]["tflops"] == pytest.approx(197.0)


def test_tuner_is_byte_deterministic():
    from tpu_dist.plan.tune import tune

    t1, _ = tune(measurement_files=[TUNE_CI])
    t2, _ = tune(measurement_files=[TUNE_CI])
    assert t1 == t2


def test_tuner_without_measurements_still_ranks():
    """No comm_bench file: pure analytic roofline — int8+fused beats fp
    on a compute-bound workload, and the result stays deterministic."""
    from tpu_dist.plan.tune import search

    r1 = search(device_kind="TPU v4")
    r2 = search(device_kind="TPU v4")
    assert [c["hash"] for c in r1["ranked"]] == \
        [c["hash"] for c in r2["ranked"]]
    assert r1["best"]["plan"].quant == "int8"
    assert r1["best"]["plan"].fused_quant == "auto"   # auto = fused on TPU


def test_trial_specificity_and_hash_keying():
    from tpu_dist.plan.tune import trial_step_seconds

    plan = Plan(engine="lm", quant="int8", grad_bucket_mb=25.0,
                sync="explicit")
    trials = [
        {"knobs": {"quant": "int8"}, "step_s": 0.5},
        {"knobs": {"quant": "int8", "grad_bucket_mb": 25.0},
         "step_s": 0.25},                       # more specific: wins
        {"knobs": {"quant": "none"}, "step_s": 0.1},   # does not match
    ]
    assert trial_step_seconds(trials, plan, {}) == 0.25
    trials.append({"plan_hash": plan_hash(plan), "knobs": {},
                   "step_s": 0.125})            # exact hash: beats subsets
    assert trial_step_seconds(trials, plan, {}) == 0.125


def test_comm_estimates_scale_to_workload_bytes():
    from tpu_dist.plan.tune import comm_estimates, normalize_workload

    meas = {"results": [
        {"bench": "grad_sync", "bytes": 1e8, "bucketed_s": 0.01,
         "monolithic_s": 0.02},
        {"bench": "grad_sync", "bytes": 1e9, "bucketed_s": 0.1,
         "monolithic_s": 0.2}]}
    w = normalize_workload({"n_params": 50e6})   # 2e8 grad bytes
    est = comm_estimates(meas, w)
    # nearest row (1e8) scaled linearly to 2e8 bytes
    assert est["sync_bucketed_s"] == pytest.approx(0.02)
    assert est["sync_monolithic_s"] == pytest.approx(0.04)
    assert comm_estimates(None, w) == {}


def test_tools_tune_cli_deterministic_and_ledger(tmp_path):
    """python -m tools.tune over the canned file: byte-identical plan
    JSON across two runs (the acceptance criterion) + a schema-valid
    `tune` ledger event."""
    env = dict(os.environ, PYTHONPATH=REPO)
    led = tmp_path / "tune.jsonl"
    outs = []
    for i in range(2):
        r = subprocess.run(
            [sys.executable, "-m", "tools.tune", "--comm-bench", TUNE_CI,
             "--json"] + (["--ledger", str(led)] if i == 0 else []),
            capture_output=True, text=True, cwd=REPO, env=env, timeout=120)
        assert r.returncode == 0, r.stderr
        outs.append(r.stdout)
    assert outs[0] == outs[1] and outs[0].strip()
    from tpu_dist.obs.ledger import read_ledger

    tunes = [r for r in read_ledger(str(led)) if r["event"] == "tune"]
    assert len(tunes) == 1
    doc = json.loads(outs[0])
    assert tunes[0]["best_hash"] == doc["plans"]["TPU v5 lite"]["hash"]
    assert tunes[0]["candidates"] > 10 and tunes[0]["measured"] is True


# ---- every maker's plan mapping, pinned exactly (no compiles) -------------

def test_all_makers_construct_expected_plans(monkeypatch):
    """Intercept the compiler entry and pin the EXACT Plan every legacy
    ``make_*`` builder constructs — complete shim coverage in
    milliseconds; the runtime parity tests below then prove the lowering
    itself on one representative per mode."""
    import tpu_dist.plan.compile as pc
    from tpu_dist.engine import lm_steps, steps

    captured = {}

    def fake_train(plan, binds):
        captured["plan"], captured["binds"] = plan, binds
        return "train-stub"

    def fake_eval(plan, binds):
        captured["plan"], captured["binds"] = plan, binds
        return "eval-stub"

    monkeypatch.setattr(pc, "compile_train_step", fake_train)
    monkeypatch.setattr(pc, "compile_eval_step", fake_eval)
    MESH, MODEL, TX, TR = object(), object(), object(), object()

    def check(fn, args, kwargs, expect, want="train-stub"):
        captured.clear()
        assert fn(*args, **kwargs) == want
        assert captured["plan"] == expect, fn.__name__
        assert captured["binds"].mesh is MESH

    img = dict(engine="image")
    check(steps.make_train_step, (MODEL, TX, TR, MESH),
          dict(health="skip"), Plan(**img, health="skip"))
    check(steps.make_multi_train_step, (MODEL, TX, TR, MESH), {},
          Plan(**img, window="stacked"))
    check(steps.make_indexed_multi_train_step,
          (MODEL, TX, TR, MESH, (8, 8, 1)), dict(donate=False),
          Plan(**img, window="indexed", donate=False))
    check(steps.make_grad_accum_train_step, (MODEL, TX, TR, MESH), {},
          Plan(**img, grad_accum_steps=2))
    check(steps.make_shard_map_train_step, (MODEL, TX, TR, MESH),
          dict(grad_compression="bf16", predivide_factor=2.0,
               grad_bucket_mb=25.0),
          Plan(**img, sync="explicit", grad_compression="bf16",
               predivide_factor=2.0, grad_bucket_mb=25.0))
    check(steps.make_shard_map_train_step, (MODEL, TX, TR, MESH),
          dict(model_axis="model"),
          Plan(**img, sync="explicit", layout="tp", tp_impl="ring"))
    check(steps.make_eval_step, (MODEL, TR, MESH), {}, Plan(**img),
          want="eval-stub")
    check(steps.make_indexed_eval_step, (MODEL, TR, MESH, (8, 8, 1)), {},
          Plan(**img, window="indexed"), want="eval-stub")

    lm = dict(engine="lm")
    check(lm_steps.make_lm_train_step, (MODEL, TX, MESH),
          dict(aux_weight=0.5, loss_chunk=64),
          Plan(**lm, aux_weight=0.5, loss_chunk=64))
    check(lm_steps.make_lm_grad_accum_train_step, (MODEL, TX, MESH), {},
          Plan(**lm, grad_accum_steps=2))
    check(lm_steps.make_lm_shard_map_train_step, (MODEL, TX, MESH), {},
          Plan(**lm, sync="explicit", grad_bucket_mb=25.0))
    check(lm_steps.make_lm_tp_ring_train_step, (MODEL, TX, MESH), {},
          Plan(**lm, sync="explicit", layout="tp", tp_impl="ring"))
    check(lm_steps.make_lm_explicit_indexed_multi_train_step,
          (MODEL, MESH), {},
          Plan(**lm, sync="explicit", window="indexed",
               steps_per_dispatch=2))
    check(lm_steps.make_lm_indexed_multi_train_step, (MODEL, TX, MESH),
          dict(health="halt"),
          Plan(**lm, window="indexed", steps_per_dispatch=2,
               health="halt"))
    check(lm_steps.make_lm_eval_step, (MODEL, MESH), dict(loss_chunk=32),
          Plan(**lm, loss_chunk=32), want="eval-stub")
    check(lm_steps.make_lm_indexed_eval_step, (MODEL, MESH), {},
          Plan(**lm, window="indexed", steps_per_dispatch=2),
          want="eval-stub")
    sp = dict(engine="lm", layout="sp", sync="explicit")
    check(lm_steps.make_lm_sp_train_step, (MODEL, TX, MESH), {},
          Plan(**sp))
    check(lm_steps.make_lm_sp_indexed_multi_train_step,
          (MODEL, TX, MESH), {},
          Plan(**sp, window="indexed", steps_per_dispatch=2))
    check(lm_steps.make_lm_sp_eval_step, (MODEL, MESH), {}, Plan(**sp),
          want="eval-stub")
    check(lm_steps.make_lm_sp_indexed_eval_step, (MODEL, MESH), {},
          Plan(**sp, window="indexed", steps_per_dispatch=2),
          want="eval-stub")


# ---- CPU loss parity: every mode through the ONE compiler -----------------
# The capture test above pins bit-for-bit equivalence with the legacy
# builders structurally (a maker IS compile_train_step of its pinned plan
# — there is no other code path); the tests below prove the LOWERINGS
# themselves: every mode (jit, shard_map/bucketed, windowed, ring, sp,
# × quant) trains through compile(plan) and the flavors agree on the
# loss trajectory. Sub-meshes (4 of the 8 virtual devices) keep the SPMD
# compiles cheap — tier-1 budget.

def _leaves_close(a, b, rtol=1e-5, atol=1e-6):
    import jax

    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x, np.float64),
                                   np.asarray(y, np.float64),
                                   rtol=rtol, atol=atol)


def _lm_fixture(quant="none"):
    import jax
    import jax.numpy as jnp

    from tpu_dist.engine.state import TrainState
    from tpu_dist.models.transformer import tiny_lm
    from tpu_dist.ops import make_optimizer

    V, L, D = 32, 16, 32
    model = tiny_lm(vocab_size=V, num_layers=1, d_model=D, num_heads=4,
                    max_len=L, quant=quant)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng},
                        np.zeros((1, L), np.int32), train=False)["params"]
    tx = make_optimizer(0.01, 0.9, 0.0)
    rows = np.random.RandomState(0).randint(0, V, (8, L + 1)).astype(
        np.int32)

    def fresh():
        return TrainState.create(jax.tree.map(jnp.copy, params), {}, tx)

    return model, tx, rows, fresh, rng


def _plan_step(plan, **binds_kw):
    from tpu_dist.plan.compile import Bindings, compile_train_step

    return compile_train_step(plan, Bindings(**binds_kw))


def test_lm_plan_loss_parity_across_modes(clean_plan_globals):
    """jit / bucketed-shard_map / indexed-window / ring / sp / int8 all
    lower through the one compiler and agree: the dp flavors match the
    jit baseline's loss trajectory, the window matches K sequential
    steps, and int8 tracks the fp loss (op-level tracking is pinned in
    test_quant)."""
    import jax

    from tpu_dist.models.transformer import tiny_lm
    from tpu_dist.parallel.mesh import make_mesh

    model, tx, rows, fresh, rng = _lm_fixture()
    devs = jax.devices()[:4]
    mesh = make_mesh((4,), ("data",), devices=devs)
    rows_b = np.random.RandomState(1).randint(
        0, 32, rows.shape).astype(np.int32)
    batch_a = (rows[:, :-1], rows[:, 1:])
    batch_b = (rows_b[:, :-1], rows_b[:, 1:])
    inp, tgt = batch_a
    binds = dict(mesh=mesh, model=model, tx=tx)

    # baseline: the gspmd jit template, 2 sequential steps
    jit_step = _plan_step(Plan(engine="lm"), **binds)
    s = fresh()
    s, m1 = jit_step(s, *batch_a, rng)
    s, m2 = jit_step(s, *batch_b, rng)
    base_losses = (float(m1["loss_sum"]), float(m2["loss_sum"]))
    base_params = s.params

    # explicit bucketed dp: same math, different (explicit) collectives
    bstep = _plan_step(Plan(engine="lm", sync="explicit",
                            grad_bucket_mb=25.0), **binds)
    s = fresh()
    s, bm = bstep(s, *batch_a, rng)
    assert float(bm["loss_sum"]) == pytest.approx(base_losses[0], rel=1e-5)
    s, bm2 = bstep(s, *batch_b, rng)
    assert float(bm2["loss_sum"]) == pytest.approx(base_losses[1],
                                                   rel=1e-4)
    _leaves_close(s.params, base_params, rtol=1e-4)

    # indexed window: one 2-step dispatch over the HBM-resident row
    # matrix == the 2 sequential jit steps (identical math incl. the
    # per-step rng fold; window step i gathers the rows whose device-side
    # shift reproduces batch i exactly)
    wstep = _plan_step(Plan(engine="lm", window="indexed",
                            steps_per_dispatch=2), **binds)
    rows16 = jax.device_put(np.concatenate([rows, rows_b]))
    idx = np.arange(16, dtype=np.int32).reshape(2, 8)
    s = fresh()
    s, wm = wstep(s, rows16, idx, rng)
    assert float(wm["loss_sum"]) == pytest.approx(
        base_losses[0] + base_losses[1], rel=1e-6)
    _leaves_close(s.params, base_params, rtol=1e-6)

    # ring TP over (2, 2): fp loss parity with the jit dp baseline
    mesh_ring = make_mesh((2, 2), ("data", "model"), devices=devs)
    ring_step = _plan_step(
        Plan(engine="lm", sync="explicit", layout="tp", tp_impl="ring"),
        mesh=mesh_ring, model=model.clone(tp_impl="ring"), tx=tx)
    s = fresh()
    s, rm = ring_step(s, inp, tgt, rng)
    assert float(rm["loss_sum"]) == pytest.approx(base_losses[0], rel=2e-4)

    # sp over (2, 2): ring attention, psum'd sums == the global sums
    from functools import partial

    ctor = partial(tiny_lm, vocab_size=32, num_layers=1, d_model=32,
                   num_heads=4, max_len=16)
    mesh_sp = make_mesh((2, 2), ("data", "seq"), devices=devs)
    sp_step = _plan_step(Plan(engine="lm", layout="sp", sync="explicit"),
                         mesh=mesh_sp, model_ctor=ctor, tx=tx)
    s = fresh()
    s, sm = sp_step(s, inp, tgt, rng)
    assert float(sm["loss_sum"]) == pytest.approx(base_losses[0], rel=2e-4)
    assert float(sm["count"]) == float(m1["count"])

    # int8: the same jit template with quantized matmuls tracks fp
    qmodel, qtx, _, qfresh, _ = _lm_fixture(quant="int8")
    qstep = _plan_step(Plan(engine="lm", quant="int8"),
                       mesh=mesh, model=qmodel, tx=qtx)
    s, qm = qstep(qfresh(), inp, tgt, rng)
    assert float(qm["loss_sum"]) == pytest.approx(base_losses[0], rel=0.05)


def test_image_plan_loss_parity_across_modes():
    import jax
    import jax.numpy as jnp
    import optax

    from tpu_dist.engine.state import TrainState, init_model
    from tpu_dist.parallel.mesh import make_mesh
    from tpu_dist.plan.compile import Bindings

    import flax.linen as nn

    class _MLP(nn.Module):
        """BN- and dropout-free: the jit and shard_map flavors are then
        bit-comparable (per-replica BN stats and per-device rng folds are
        the two DESIGNED divergences — test_engine pins them)."""

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            return nn.Dense(10)(nn.relu(nn.Dense(32)(x)))

    devs = jax.devices()[:4]
    mesh = make_mesh((4,), ("data",), devices=devs)
    model = _MLP()
    rng = jax.random.PRNGKey(0)
    params, bs = init_model(model, rng, (2, 28, 28, 1))
    tx = optax.sgd(0.1)
    transform = lambda x, r: x.astype(jnp.float32) / 255.0

    def fresh():
        return TrainState.create(jax.tree.map(jnp.copy, params),
                                 jax.tree.map(jnp.copy, bs), tx)

    imgs = np.random.RandomState(0).randint(
        0, 255, (8, 28, 28, 1)).astype(np.uint8)
    lbls = (np.arange(8) % 10).astype(np.int32)
    binds = dict(mesh=mesh, model=model, tx=tx, transform=transform)
    jit_step = _plan_step(Plan(engine="image"), **binds)
    s = fresh()
    s, m1 = jit_step(s, imgs, lbls, rng)
    s, m2 = jit_step(s, imgs[::-1], lbls[::-1], rng)

    # explicit shard_map flavor: LeNet is BN-free, so updates are
    # bit-comparable with the jit flavor (the steps.py contract)
    sm_step = _plan_step(Plan(engine="image", sync="explicit"), **binds)
    t = fresh()
    t, n1 = sm_step(t, imgs, lbls, rng)
    assert float(n1["loss_sum"]) == pytest.approx(float(m1["loss_sum"]),
                                                  rel=1e-5)
    t, n2 = sm_step(t, imgs[::-1], lbls[::-1], rng)
    _leaves_close(t.params, s.params, rtol=1e-4)

    # stacked window: one 2-step dispatch == the 2 sequential jit steps
    # (identical rng folds — the make_multi_train_step contract)
    w_step = _plan_step(Plan(engine="image", window="stacked",
                             steps_per_dispatch=2), **binds)
    w = fresh()
    w, wm = w_step(w, np.stack([imgs, imgs[::-1]]),
                   np.stack([lbls, lbls[::-1]]), rng)
    assert float(wm["loss_sum"]) == pytest.approx(
        float(m1["loss_sum"]) + float(m2["loss_sum"]), rel=1e-6)
    _leaves_close(w.params, s.params, rtol=1e-6)

    # eval lowering via the public lazy pair (compile_plan/CompiledPlan —
    # same lowering as compile_eval_step, built on first access, cached)
    from tpu_dist.plan.compile import compile_plan

    cp = compile_plan(Plan(engine="image"),
                      Bindings(mesh=mesh, model=model,
                               eval_transform=transform))
    ev = cp.eval_step
    assert cp.eval_step is ev          # lazy + cached
    out = ev(params, bs, imgs, lbls, np.ones(8, np.float32))
    logits = model.apply({"params": params, "batch_stats": bs},
                         transform(imgs, None), train=False)
    top1 = float(np.sum(np.argmax(np.asarray(logits), -1) == lbls))
    assert float(out["correct1"]) == top1
    assert float(out["count"]) == 8.0


def test_fused_quant_plan_blocks_are_bit_identical(clean_plan_globals):
    """activate_plan flips the fused kernel + block sizes; any legal
    (bm, bn, bk) produces bit-identical fused matmuls (the bk chunking is
    exact int32 accumulation)."""
    import jax.numpy as jnp

    from tpu_dist.ops import pallas_quant as pq
    from tpu_dist.ops.quant import fused_quant_active
    from tpu_dist.plan.compile import activate_plan

    x = jnp.asarray(np.random.RandomState(0).normal(size=(24, 256)),
                    jnp.float32)
    w = jnp.asarray(np.random.RandomState(1).normal(size=(256, 192)),
                    jnp.float32)
    activate_plan(Plan(engine="lm", quant="int8", fused_quant="on"))
    assert fused_quant_active()
    assert pq.quant_blocks() == DEFAULT_QUANT_BLOCK
    ref = np.asarray(pq.fused_quant_matmul(x, w))
    activate_plan(Plan(engine="lm", quant="int8", fused_quant="on",
                       quant_block=(64, 256, 128), opt_block_rows=256))
    assert pq.quant_blocks() == (64, 256, 128)
    from tpu_dist.ops.pallas_sgd import block_rows

    assert block_rows() == 256
    assert np.array_equal(ref, np.asarray(pq.fused_quant_matmul(x, w)))
    # review regression (PR 15): a RAGGED out-features dim (128 < n <
    # blk_n, n % 128 != 0) under a widened bn tile must lane-round, not
    # hand Mosaic a ragged (k, 200) block — and stay bit-identical
    w200 = jnp.asarray(np.random.RandomState(2).normal(size=(256, 200)),
                       jnp.float32)
    activate_plan(Plan(engine="lm", quant="int8", fused_quant="on"))
    ref200 = np.asarray(pq.fused_quant_matmul(x, w200))
    activate_plan(Plan(engine="lm", quant="int8", fused_quant="on",
                       quant_block=(128, 256, 0)))
    assert np.array_equal(ref200, np.asarray(pq.fused_quant_matmul(x, w200)))
    activate_plan(Plan(engine="lm", fused_quant="off"))
    assert not fused_quant_active()


# ---- engine acceptance: the config `plan` knob ----------------------------

def test_lm_trainer_accepts_emitted_plan_file(tmp_path, clean_plan_globals):
    """ACCEPTANCE: tools/tune.py's emitted plan file drives a real LM run
    through the config knob — knobs applied, run_start stamped, a `plan`
    event emitted, ledger_report renders it."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer
    from tpu_dist.obs.ledger import read_ledger
    from tpu_dist.plan.tune import tune

    text, results = tune(measurement_files=[TUNE_CI])
    best_hash = results["TPU v5 lite"]["best"]["hash"]
    doc = json.loads(text)
    # retarget the emitted per-device entry at this machine's device kind
    plan_doc = {"version": 1,
                "plans": {"default": doc["plans"]["TPU v5 lite"]}}
    path = tmp_path / "plans.json"
    path.write_text(json.dumps(plan_doc))
    ledger = tmp_path / "run.jsonl"
    cfg = LMConfig(seq_len=32, vocab_size=64, num_layers=1, d_model=32,
                   num_heads=4, batch_size=16, synth_tokens=6000, epochs=1,
                   max_steps=2, ledger_path=str(ledger), watchdog_factor=0,
                   plan=str(path))
    t = LMTrainer(cfg)
    # the plan's knobs landed in the config before the engine built steps
    assert t.cfg.quant == "int8" and t.cfg.grad_bucket_mb == 25.0
    assert t.cfg.steps_per_dispatch == 16 and t.mode == "dp-bucketed"
    t.fit()
    recs = read_ledger(str(ledger))
    start = [r for r in recs if r["event"] == "run_start"][0]
    assert start["plan_hash"] == best_hash
    assert start["plan_source"] == str(path)
    plan_events = [r for r in recs if r["event"] == "plan"]
    assert len(plan_events) == 1
    assert plan_events[0]["plan_hash"] == best_hash
    assert plan_events[0]["knobs"]["quant"] == "int8"
    # ledger_report renders + returns the plan section
    from tools.ledger_report import summarize

    summary = summarize(recs, out=lambda s: None)
    assert summary["plan"]["plan_hash"] == best_hash
    assert summary["run"]["plan_hash"] == best_hash


def test_image_trainer_accepts_plan_and_auto(tmp_path, clean_plan_globals):
    """The image engine takes a plan file (variant flip to shard_map) and
    the 'auto' knob (analytic search, pruned to what the config runs)."""
    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine.loop import Trainer

    plan = Plan(engine="image", sync="explicit", grad_bucket_mb=25.0)
    path = tmp_path / "plan.json"
    path.write_text(plan.to_json())
    ledger = tmp_path / "img.jsonl"
    cfg = TrainConfig(dataset="synthetic", arch="lenet", batch_size=64,
                      synth_train_size=128, synth_val_size=64, epochs=1,
                      watchdog_factor=0, plan=str(path),
                      ledger_path=str(ledger))
    t = Trainer(cfg)
    assert t.cfg.variant == "shard_map"
    assert t.cfg.grad_bucket_mb == 25.0
    assert t._plan_info["hash"] == plan_hash(plan)
    t.fit()
    from tpu_dist.obs.ledger import read_ledger

    recs = read_ledger(str(ledger))
    assert [r for r in recs if r["event"] == "run_start"][0]["plan_hash"] \
        == plan_hash(plan)
    assert [r for r in recs if r["event"] == "plan"]
    # 'auto' must never break a working config: quant stays off for a
    # conv arch, and the resolved plan passes the engine's own validation
    cfg2 = TrainConfig(dataset="synthetic", arch="lenet", batch_size=64,
                       synth_train_size=256, synth_val_size=64, epochs=1,
                       watchdog_factor=0, plan="auto")
    t2 = Trainer(cfg2)
    assert t2._plan_info["source"] == "auto"
    assert t2.cfg.quant == "none"


def test_auto_plan_carries_unsearched_config_knobs(clean_plan_globals):
    """Review regression (PR 15): 'auto' tunes only what it searches —
    precision/grad accumulation/chunked CE/health stay the config's
    choice instead of being reset to Plan defaults."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.plan.compile import resolve_config_plan

    cfg = LMConfig(plan="auto", precision="bf16", grad_accum_steps=4,
                   loss_chunk=1024, health="skip", seq_len=32,
                   vocab_size=64, num_layers=1, d_model=32)
    out, info = resolve_config_plan(cfg)
    assert info is not None and info["source"] == "auto"
    assert out.precision == "bf16"
    assert out.grad_accum_steps == 4
    assert out.loss_chunk == 1024
    assert out.health == "skip"
    # accumulation legally excludes windowed/bucketed candidates, so the
    # chosen plan must not have flipped those on either
    assert out.steps_per_dispatch == 1 and out.grad_bucket_mb == 0.0


def test_block_env_seeds_are_validated():
    """Review regression (PR 15): the TPU_DIST_QUANT_BLOCKS /
    TPU_DIST_OPT_BLOCK_ROWS env seeds ride the validated setters (the ONE
    legality rule in plan.ir) — malformed values fail loudly at import,
    not as a Mosaic tiling abort at first trace."""
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import tpu_dist.ops.pallas_quant")
    env = dict(os.environ, PYTHONPATH=REPO,
               TPU_DIST_QUANT_BLOCKS="100,128,0")
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode != 0 and "bm=100" in r.stderr
    env["TPU_DIST_QUANT_BLOCKS"] = "256"          # wrong arity
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode != 0 and "expected 'bm,bn,bk'" in r.stderr
    code2 = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
             "import tpu_dist.ops.pallas_sgd")
    env2 = dict(os.environ, PYTHONPATH=REPO,
                TPU_DIST_OPT_BLOCK_ROWS="100")
    r = subprocess.run([sys.executable, "-c", code2], env=env2, cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode != 0 and "opt_block_rows=100" in r.stderr


def test_resolve_config_plan_none_is_noop():
    from tpu_dist.configs import LMConfig
    from tpu_dist.plan.compile import resolve_config_plan

    cfg = LMConfig()
    out, info = resolve_config_plan(cfg)
    assert out is cfg and info is None
    out, info = resolve_config_plan(LMConfig(plan="none"))
    assert info is None
