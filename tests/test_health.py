"""Numerical-health sentry (obs.health): fused probes, skip/halt policy.

Covers: the probe values and the device-side skip gate at the
_apply_update level (the one funnel every engine flavor shares), the
host-side loss-spike EMA detector, and the acceptance NaN-injection
integration run — a data-driven NaN batch in a real LMTrainer epoch is
skipped with params bit-identical, data+RNG advancing, exactly one
``health`` ledger event, and the run still converging; under ``halt`` the
loop raises and the crash-safe shutdown stamps ``run_end`` as crashed.
"""

import numpy as np
import pytest

from tpu_dist.obs.health import (HealthError, HealthSentry, validate_health)
from tpu_dist.obs.ledger import Ledger, read_ledger

POISON_TOKEN = 3
SEQ_LEN = 32


# ------------------------------------------------------------- unit level
def _tiny_update_rig():
    import jax
    import jax.numpy as jnp
    import optax

    from tpu_dist.engine.state import TrainState
    from tpu_dist.engine.steps import _apply_update

    tx = optax.sgd(0.1)
    params = {"w": jnp.arange(4.0), "b": jnp.float32(1.0)}
    state = TrainState.create(params, {}, tx)

    def run(grads, health):
        return jax.jit(lambda s, g: _apply_update(tx, s, g, {}, {}, health)
                       )(state, grads)

    return state, run


def test_probes_ride_the_metrics_and_skip_gates_the_update():
    import jax
    import jax.numpy as jnp

    state, run = _tiny_update_rig()
    clean = {"w": jnp.ones(4), "b": jnp.float32(2.0)}
    new_state, metrics = run(clean, "record")
    m = jax.device_get(metrics)
    assert m["nonfinite_count"] == 0
    assert m["grad_norm"] == pytest.approx(np.sqrt(4 + 4.0), rel=1e-6)
    assert m["update_norm"] > 0
    assert not np.allclose(jax.device_get(new_state.params)["w"],
                           jax.device_get(state.params)["w"])

    poisoned = {"w": jnp.ones(4).at[1].set(jnp.nan), "b": jnp.float32(2.0)}
    # record: the NaN flows into the params (probes report, nothing gates)
    bad_state, m = run(poisoned, "record")
    m = jax.device_get(m)
    assert m["nonfinite_count"] == 1
    assert np.isnan(jax.device_get(bad_state.params)["w"]).any()
    # skip: params/opt bit-identical, step still advances (data+RNG march)
    skip_state, m = run(poisoned, "skip")
    m = jax.device_get(m)
    assert m["nonfinite_count"] == 1
    before, after = jax.device_get((state.params, skip_state.params))
    assert all(np.array_equal(before[k], after[k]) for k in before)
    assert int(jax.device_get(skip_state.step)) == \
        int(jax.device_get(state.step)) + 1


def test_loss_scale_overflow_is_not_a_health_trip():
    """A dynamic-loss-scale overflow is ROUTINE apex behavior (the finite
    gate reverts the update and halves the scale) — the probes must
    report clean zeros for that step, or health=halt would kill every
    healthy fp16 run at the scale-growth cadence."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpu_dist.engine.state import TrainState
    from tpu_dist.engine.steps import _apply_update
    from tpu_dist.ops.precision import LossScaleState

    tx = optax.sgd(0.1)
    params = {"w": jnp.arange(4.0)}
    state = TrainState.create(params, {}, tx, LossScaleState.create(2.0))
    overflowed = {"w": jnp.ones(4).at[0].set(jnp.inf)}
    new_state, metrics = jax.jit(
        lambda s, g: _apply_update(tx, s, g, {}, {}, "halt"))(
            state, overflowed)
    m = jax.device_get(metrics)
    assert m["nonfinite_count"] == 0 and m["grad_norm"] == 0
    # the ls gate did its own skip: params unchanged, scale halved
    assert np.array_equal(jax.device_get(new_state.params)["w"],
                          jax.device_get(state.params)["w"])
    assert float(jax.device_get(new_state.loss_scale.scale)) == 1.0


def test_validate_health_rejects_unknown_policy():
    validate_health("skip")
    with pytest.raises(ValueError, match="health"):
        validate_health("panic")
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    with pytest.raises(ValueError, match="health"):
        LMTrainer(LMConfig(health="panic"))


def test_sentry_loss_spike_and_halt(tmp_path):
    led = Ledger(str(tmp_path / "h.jsonl"))
    s = HealthSentry(policy="record", spike_z=4.0, ledger=led, warmup=10)
    for i in range(30):
        s.observe(i, 1.0 + 0.01 * (i % 3))
    assert s.trips == 0
    s.observe(30, 50.0)  # ~1000 sigma
    assert s.trips == 1 and s.trips_by_kind == {"loss_spike": 1}
    s.observe(31, 1.0)   # the spike did not poison the EMA baseline
    assert s.trips == 1
    # non-finite loss trips as 'nonfinite' even with zero probe count
    s.observe(32, float("nan"))
    assert s.trips_by_kind.get("nonfinite") == 1
    led.close()
    recs = [r for r in read_ledger(led.path) if r["event"] == "health"]
    assert [r["kind"] for r in recs] == ["loss_spike", "nonfinite"]
    assert recs[0]["action"] == "record" and recs[0]["value"] > 4.0

    halt = HealthSentry(policy="halt", spike_z=4.0, warmup=2)
    for i in range(5):
        halt.observe(i, 1.0)
    with pytest.raises(HealthError, match="loss_spike"):
        halt.observe(5, 100.0)
    with pytest.raises(HealthError, match="nonfinite"):
        halt.observe(6, 1.0, nonfinite=2.0)


# ----------------------------------------------- engine integration (CPU)
class _NaNModel:
    """Delegating model wrapper that poisons the logits of any batch whose
    first row is the constant sentinel token — data-driven NaN injection
    through the real forward/backward, so the step's GRADIENTS go NaN."""

    def __init__(self, inner, token):
        self._inner = inner
        self._token = token

    def apply(self, variables, x, *args, **kwargs):
        import jax.numpy as jnp

        out = self._inner.apply(variables, x, *args, **kwargs)
        poison = jnp.where(jnp.all(x[0] == self._token),
                           jnp.float32(jnp.nan), jnp.float32(0.0))
        if isinstance(out, tuple):
            return out[0] + poison, out[1]
        return out + poison

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _poisoned_trainer(tmp_path, health, poison_batch):
    """Tiny-LM trainer whose epoch-0 batch ``poison_batch`` leads with an
    all-sentinel row (the corpus itself is edited, so the injection is
    data-driven end to end)."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    cfg = LMConfig(epochs=1, batch_size=8, seq_len=SEQ_LEN, vocab_size=64,
                   num_layers=1, d_model=32, num_heads=2,
                   synth_tokens=80 * SEQ_LEN + 1, print_freq=1, seed=0,
                   health=health,
                   ledger_path=str(tmp_path / f"{health}.jsonl"))
    tr = LMTrainer(cfg)
    idx, _ = tr._epoch_indices(tr.train_ds, True, 0)
    row = int(idx[poison_batch][0])
    tr.train_ds.stream[row * SEQ_LEN: (row + 1) * SEQ_LEN + 1] = POISON_TOKEN
    tr.model = _NaNModel(tr.model, POISON_TOKEN)
    tr._build_steps()  # rebuild the jitted steps over the wrapped model
    return tr, cfg


@pytest.mark.slow  # tier-1 budget (PR 9): 24s e2e; skip-gating itself is unit-pinned by test_probes_ride_the_metrics_and_skip_gates_the_update and the run/ledger mechanics by the cheaper halt twin below — offsets the new pallas_quant/prefetcher/int8kv tests
def test_health_skip_nan_injection_lm_run(tmp_path):
    """Acceptance: with health=skip, the NaN-grad step is skipped (params
    bit-identical, data+RNG advance), the run completes with exactly one
    'health' ledger event, and the tiny LM still converges."""
    import jax

    tr, cfg = _poisoned_trainer(tmp_path, "skip", poison_batch=3)
    seen = {}
    orig = tr.train_step

    def spy(state, inputs, targets, rng):
        poisoned = bool(
            (np.asarray(jax.device_get(inputs))[0] == POISON_TOKEN).all())
        if poisoned:
            seen["before"] = jax.device_get(state.params)
            seen["step_before"] = int(jax.device_get(state.step))
        out_state, metrics = orig(state, inputs, targets, rng)
        if poisoned:
            seen["after"] = jax.device_get(out_state.params)
            seen["step_after"] = int(jax.device_get(out_state.step))
        return out_state, metrics

    tr.train_step = spy
    tr.fit()  # completes — the poisoned batch does not kill the run

    assert "before" in seen, "the poisoned batch never reached the step"
    flat_b = jax.tree_util.tree_leaves(seen["before"])
    flat_a = jax.tree_util.tree_leaves(seen["after"])
    assert all(np.array_equal(b, a) for b, a in zip(flat_b, flat_a)), \
        "skip must keep params bit-identical across the NaN step"
    assert seen["step_after"] == seen["step_before"] + 1, \
        "skip must still advance the step counter (data+RNG lockstep)"

    recs = read_ledger(cfg.ledger_path)
    trips = [r for r in recs if r["event"] == "health"]
    assert len(trips) == 1 and trips[0]["kind"] == "nonfinite"
    assert trips[0]["action"] == "skip" and trips[0]["policy"] == "skip"
    steps = [r for r in recs if r["event"] == "step"]
    # the poisoned record carries the trip: NaN loss is None after json-
    # safety, nonfinite_count == 1; every other record is clean
    bad = [r for r in steps if (r.get("nonfinite_count") or 0) > 0]
    assert len(bad) == 1 and bad[0]["loss"] is None
    losses = [r["loss"] for r in steps if r["loss"] is not None
              and not r.get("warm")]
    assert losses[-1] < losses[0], "run should still converge past the skip"
    (end,) = [r for r in recs if r["event"] == "run_end"]
    assert end["status"] == "ok" and end["health_trips"] == 1
    # the epoch averages were not poisoned by the skipped record
    (ep,) = [r for r in recs if r["event"] == "epoch"]
    assert ep["loss"] is not None


def test_health_halt_nan_injection_raises(tmp_path):
    """Acceptance twin: health=halt raises out of the loop at the drain
    that sees the NaN, and the crash-safe shutdown stamps run_end."""
    tr, cfg = _poisoned_trainer(tmp_path, "halt", poison_batch=1)
    with pytest.raises(HealthError, match="nonfinite"):
        tr.fit()
    recs = read_ledger(cfg.ledger_path)
    assert [r for r in recs if r["event"] == "health"][0]["action"] == "halt"
    (end,) = [r for r in recs if r["event"] == "run_end"]
    assert end["status"] == "crashed" and "HealthError" in end["error"]
