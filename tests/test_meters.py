"""Unit tests for meters/accuracy (reference C17/C18 semantics)."""

import jax.numpy as jnp
import numpy as np

from tpu_dist.utils.meters import (MeterBank, accuracy, correct_counts,
                                   topk_accuracy)


def test_meter_bank_weighted_running_avg():
    b = MeterBank(10, [("Loss", ".4f")])
    b.update("Loss", 2.0, n=2)
    b.update("Loss", 4.0, n=2)
    assert b.last("Loss") == 4.0
    assert b.avg("Loss") == 3.0


def test_meter_bank_empty_avg_is_zero():
    b = MeterBank(10, [("Time", "6.3f")])
    assert b.avg("Time") == 0.0


def test_meter_bank_progress_line_format():
    # cookbook-parity line: [i/N] header then "Name last (avg)" cells
    b = MeterBank(100, [("Loss", ".2f")], prefix="Epoch: [3]")
    b.update("Loss", 1.5)
    lines = []
    b.display(7, printer=lines.append)
    assert lines == ["Epoch: [3][  7/100]\tLoss 1.50 (1.50)"]


def test_meter_bank_snapshot_agrees_with_printed_line():
    """snapshot() is THE read both the progress printer and the run ledger
    consume (round-6 obs satellite): the numbers in the rendered line must
    be exactly the snapshot's (last, avg) — line() renders FROM the
    snapshot, so a drift is structurally impossible; this pins it."""
    b = MeterBank(50, [("Loss", ".4f"), ("Time", "6.3f")], prefix="E[0]")
    for v, n in ((2.0, 4), (1.0, 4), (0.5, 8)):
        b.update("Loss", v, n)
        b.update("Time", v / 10, 1)
    snap = b.snapshot()
    assert snap["Loss"]["last"] == 0.5
    assert snap["Loss"]["avg"] == (2.0 * 4 + 1.0 * 4 + 0.5 * 8) / 16
    line = b.line(7)
    # the rendered cells carry the snapshot's numbers, formatted
    assert f"Loss {snap['Loss']['last']:.4f} ({snap['Loss']['avg']:.4f})" \
        in line
    assert f"Time {snap['Time']['last']:6.3f} ({snap['Time']['avg']:6.3f})" \
        in line
    # rendering an explicitly passed snapshot equals the implicit read
    assert b.line(7, snapshot=snap) == line
    # snapshot is a copy: mutating it cannot corrupt the meters
    snap["Loss"]["last"] = 999.0
    assert b.last("Loss") == 0.5


def test_meter_bank_avg_independent_of_update_batching():
    # summing one window at a time must equal per-sample updates
    a = MeterBank(10, [("x", ".2f")])
    for v in (1.0, 2.0, 3.0, 6.0):
        a.update("x", v)
    window = MeterBank(10, [("x", ".2f")])
    window.update("x", (1.0 + 2.0) / 2, n=2)
    window.update("x", (3.0 + 6.0) / 2, n=2)
    assert a.avg("x") == window.avg("x") == 3.0


def test_simplified_accuracy_matches_reference_semantics():
    # reference returns top-1 twice (1.dataparallel.py:339-364, README_EN.md:654)
    logits = jnp.array([[1.0, 2.0, 0.0], [3.0, 0.0, 1.0]])
    target = jnp.array([1, 2])
    a1, a5 = accuracy(logits, target)
    assert float(a1) == 0.5
    assert float(a5) == 0.5


def test_topk_accuracy_percent():
    logits = jnp.array([[0.9, 0.5, 0.1, 0.0, 0.0],
                        [0.1, 0.2, 0.9, 0.0, 0.0]])
    target = jnp.array([1, 0])
    top1, top2 = topk_accuracy(logits, target, topk=(1, 2))
    assert float(top1) == 0.0
    assert float(top2) == 50.0  # sample 0: class 1 is 2nd


def test_correct_counts_are_sums_not_fractions():
    logits = jnp.array([[9.0, 1.0, 0.0],   # pred 0, target 0 -> top1 hit
                        [1.0, 9.0, 0.0],   # pred 1, target 1 -> top1 hit
                        [9.0, 5.0, 0.0]])  # pred 0, target 1 -> top2 only
    target = jnp.array([0, 1, 1])
    c1, c2 = correct_counts(logits, target, topk=(1, 2))
    assert float(c1) == 2.0
    assert float(c2) == 3.0
