"""Unit tests for meters/accuracy (reference C17/C18 semantics)."""

import jax.numpy as jnp
import numpy as np

from tpu_dist.utils.meters import (AverageMeter, ProgressMeter, accuracy,
                                   correct_counts, topk_accuracy)


def test_average_meter_running_avg():
    m = AverageMeter("Loss", ":.4f")
    m.update(2.0, n=2)
    m.update(4.0, n=2)
    assert m.val == 4.0
    assert m.sum == 12.0
    assert m.count == 4
    assert m.avg == 3.0


def test_average_meter_reset():
    m = AverageMeter("x")
    m.update(5.0)
    m.reset()
    assert m.avg == 0.0 and m.count == 0


def test_progress_meter_format():
    m = AverageMeter("Loss", ":.2f")
    m.update(1.5)
    lines = []
    p = ProgressMeter(100, [m], prefix="Epoch: [3]")
    p.display(7, printer=lines.append)
    assert lines == ["Epoch: [3][  7/100]\tLoss 1.50 (1.50)"]


def test_simplified_accuracy_matches_reference_semantics():
    # reference returns top-1 twice (1.dataparallel.py:339-364, README_EN.md:654)
    logits = jnp.array([[1.0, 2.0, 0.0], [3.0, 0.0, 1.0]])
    target = jnp.array([1, 2])
    a1, a5 = accuracy(logits, target)
    assert float(a1) == 0.5
    assert float(a5) == 0.5


def test_topk_accuracy_percent():
    logits = jnp.array([[0.9, 0.5, 0.1, 0.0, 0.0],
                        [0.1, 0.2, 0.9, 0.0, 0.0]])
    target = jnp.array([1, 0])
    top1, top2 = topk_accuracy(logits, target, topk=(1, 2))
    assert float(top1) == 0.0
    assert float(top2) == 50.0  # sample 0: class 1 is 2nd


def test_correct_counts_are_sums_not_fractions():
    logits = jnp.array([[9.0, 1.0, 0.0],   # pred 0, target 0 -> top1 hit
                        [1.0, 9.0, 0.0],   # pred 1, target 1 -> top1 hit
                        [9.0, 5.0, 0.0]])  # pred 0, target 1 -> top2 only
    target = jnp.array([0, 1, 1])
    c1, c2 = correct_counts(logits, target, topk=(1, 2))
    assert float(c1) == 2.0
    assert float(c2) == 3.0
