"""Flight recorder (obs.flightrec): triggered forensic bundles.

Covers: a direct trigger writing a complete bundle (manifest + stacks +
ledger tail) and its ``diagnosis`` ledger event; the ledger-sink
auto-triggers (stall event, health event, skew-straggler spike — and
benign skew staying silent); cooldown/cap rate limiting; bundle-root
derivation from the ledger path; SIGUSR1 through RunObs; and the
acceptance test — an induced stall in a CPU LM engine smoke producing a
bundle with a valid manifest, a ``diagnosis`` event, and a captured
jax.profiler trace window of the steps after the trigger.
"""

import json
import os
import signal
import time

import pytest

from tpu_dist.obs import FlightRecorder, Ledger, read_ledger
from tpu_dist.obs.flightrec import SKEW_SPREAD_MIN_S


def _manifest(bundle):
    with open(os.path.join(bundle, "manifest.json")) as f:
        return json.load(f)


# ------------------------------------------------------------- unit-ish
def test_trigger_writes_bundle_and_diagnosis_event(tmp_path):
    path = str(tmp_path / "run.jsonl")
    led = Ledger(path)
    fr = FlightRecorder(dir=str(tmp_path / "fr"), ledger=led,
                        trace_steps=0)
    led.add_sink(fr.sink)
    for i in range(5):  # ring content leading up to the trigger
        led.emit("hbm", bytes_in_use=i)
    led.emit("step", step=7, loss=1.0, throughput=10.0, unit="tok/s",
             data_s=0.0, dispatch_s=0.0, device_s=0.0, comm_s=None,
             mfu=None)
    bundle = fr.trigger("manual", note="operator asked")
    assert bundle and os.path.isdir(bundle)
    m = _manifest(bundle)
    assert m["reason"] == "manual" and m["note"] == "operator asked"
    assert m["step"] == 7  # last step record seen by the ring
    assert m["trace"]["status"] == "disabled"
    assert "stacks.txt" in m["files"] and "events_tail.jsonl" in m["files"]
    stacks = open(os.path.join(bundle, "stacks.txt")).read()
    assert "--- thread" in stacks
    tail = [json.loads(ln) for ln in
            open(os.path.join(bundle, "events_tail.jsonl"))]
    assert [r["event"] for r in tail].count("hbm") == 5
    assert tail[-1]["event"] == "step"
    led.close()
    (diag,) = [r for r in read_ledger(path) if r["event"] == "diagnosis"]
    assert diag["reason"] == "manual" and diag["bundle"] == bundle
    assert diag["step"] == 7 and diag["trace"] == "disabled"


def test_sink_auto_triggers_on_stall_health_and_skew_spike(tmp_path):
    path = str(tmp_path / "run.jsonl")
    led = Ledger(path)
    fr = FlightRecorder(dir=str(tmp_path / "fr"), ledger=led,
                        trace_steps=0, cooldown_s=0.0)
    led.add_sink(fr.sink)
    led.emit("stall", idle_s=9.0, threshold_s=1.0, stacks="...")
    led.emit("health", step=3, kind="nonfinite", policy="record",
             action="record", value=1.0)
    # benign skew: small spread — must NOT trigger
    led.emit("skew", step=10, p50_s=0.01, p99_s=0.012, spread_s=0.002,
             straggler=0)
    # straggler spike: spread over both bounds
    led.emit("skew", step=20, p50_s=0.05,
             p99_s=SKEW_SPREAD_MIN_S, spread_s=SKEW_SPREAD_MIN_S + 0.1,
             straggler=1)
    led.close()
    assert [os.path.basename(b).split("-")[1] for b in fr.bundles] == \
        ["stall", "health", "skew"]
    diags = [r for r in read_ledger(path) if r["event"] == "diagnosis"]
    assert [d["reason"] for d in diags] == ["stall", "health", "skew"]
    assert "straggler 1" in diags[-1]["note"]


def test_cooldown_and_bundle_cap_rate_limit(tmp_path):
    led = Ledger(None)
    fr = FlightRecorder(dir=str(tmp_path / "fr"), ledger=led,
                        trace_steps=0, cooldown_s=60.0)
    assert fr.trigger("manual") is not None
    assert fr.trigger("manual") is None  # inside the cooldown
    fr2 = FlightRecorder(dir=str(tmp_path / "fr2"), ledger=led,
                         trace_steps=0, cooldown_s=0.0, max_bundles=2)
    assert fr2.trigger("a") and fr2.trigger("b")
    assert fr2.trigger("c") is None  # capped
    led.close()


def test_bundle_root_derives_from_ledger_path(tmp_path):
    path = str(tmp_path / "run.jsonl")
    led = Ledger(path)
    fr = FlightRecorder(ledger=led, trace_steps=0)
    bundle = fr.trigger("manual")
    assert bundle.startswith(path + ".flightrec")
    led.close()
    # pathless ledger: a temp root still captures the bundle
    fr2 = FlightRecorder(ledger=Ledger(None), trace_steps=0)
    b2 = fr2.trigger("manual")
    assert b2 and os.path.isfile(os.path.join(b2, "manifest.json"))
    import shutil

    shutil.rmtree(fr2._dir, ignore_errors=True)


# ------------------------------------------------------------ with jax
def test_sigusr1_captures_bundle_through_runobs(tmp_path):
    """kill -USR1 <pid> is the operator-initiated trigger: RunObs arms
    the handler at run_start, restores the previous one at run_end."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.obs import RunObs

    prev = signal.getsignal(signal.SIGUSR1)
    path = str(tmp_path / "run.jsonl")
    cfg = LMConfig(ledger_path=path, flightrec_trace_steps=0,
                   flightrec_dir=str(tmp_path / "fr"))
    obs = RunObs("lm", cfg, None, unit="tok/s")
    obs.run_start()
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        time.sleep(0.05)  # handler runs on the main thread imminently
    finally:
        obs.run_end()
    assert signal.getsignal(signal.SIGUSR1) == prev  # restored
    recs = read_ledger(path)
    (diag,) = [r for r in recs if r["event"] == "diagnosis"]
    assert diag["reason"] == "sigusr1"
    assert os.path.isfile(os.path.join(diag["bundle"], "manifest.json"))


def _run_stalling_lm(tmp_path, trace_steps: int):
    """A tiny CPU LM run with one injected mid-epoch stall: the watchdog
    fires, its ledger event auto-triggers the flight recorder."""
    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    path = str(tmp_path / "lm.jsonl")
    cfg = LMConfig(epochs=1, batch_size=8, seq_len=32, vocab_size=64,
                   num_layers=1, d_model=32, num_heads=2,
                   synth_tokens=2304, print_freq=1, seed=0,
                   ledger_path=path, watchdog_factor=4.0,
                   flightrec_trace_steps=trace_steps,
                   flightrec_dir=str(tmp_path / "fr"))
    tr = LMTrainer(cfg)
    # shrink the watchdog's floor/poll so the injected stall fires fast
    # (production floor is 5s — too slow for tier-1)
    tr.obs.watchdog.min_timeout_s = 0.25
    tr.obs.watchdog.poll_s = 0.05
    orig_step, calls = tr.train_step, {"n": 0}

    def stalling_step(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 5:  # mid-epoch, after the median is established
            time.sleep(1.2)
        return orig_step(*a, **kw)

    tr.train_step = stalling_step
    tr.fit()
    return read_ledger(path)


def test_induced_stall_in_lm_engine_produces_bundle(tmp_path):
    """ACCEPTANCE: an induced stall in a CPU engine smoke produces a
    flight-recorder bundle with a valid manifest and a ``diagnosis``
    ledger event, and ledger_report renders the diagnosis section.
    trace_steps=0 here: the profiler's one-time ~20s init belongs behind
    the slow marker (test_stall_profiler_window_captured)."""
    recs = _run_stalling_lm(tmp_path, trace_steps=0)
    assert [r for r in recs if r["event"] == "stall"], "watchdog never fired"
    diags = [r for r in recs if r["event"] == "diagnosis"]
    assert diags and diags[0]["reason"] == "stall"
    bundle = diags[0]["bundle"]
    m = _manifest(bundle)
    assert m["reason"] == "stall" and "stacks.txt" in m["files"]
    assert m["step"] is not None
    assert m["trace"]["status"] == "disabled"
    # events_tail holds the run-up to the stall
    tail = [json.loads(ln) for ln in
            open(os.path.join(bundle, "events_tail.jsonl"))]
    assert any(r["event"] == "step" for r in tail)
    # the report tool surfaces the bundle
    from tools.ledger_report import summarize

    lines = []
    summary = summarize(recs, out=lines.append)
    assert summary["diagnosis"] == len(diags)
    assert any("DIAGNOSIS BUNDLES" in ln for ln in lines)
    assert any(bundle in ln for ln in lines)


@pytest.mark.slow
def test_stall_profiler_window_captured(tmp_path):
    """Full-size twin: the profiler window armed at the trigger captures
    the next step records into <bundle>/trace (slow: jax.profiler's
    first start_trace pays a ~20s one-time init on this backend)."""
    recs = _run_stalling_lm(tmp_path, trace_steps=2)
    diags = [r for r in recs if r["event"] == "diagnosis"]
    assert diags and diags[0]["reason"] == "stall"
    m = _manifest(diags[0]["bundle"])
    assert m["trace"]["status"] == "captured", m["trace"]
    trace_dir = os.path.join(diags[0]["bundle"], "trace")
    assert os.path.isdir(trace_dir) and any(os.scandir(trace_dir))
