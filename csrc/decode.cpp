// Native JPEG decode for the ImageFolder host input pipeline.
//
// Role: BASELINE.md records host JPEG DECODE as the binding constraint for
// real-ImageNet streaming in this container (455 img/s threaded PIL vs the
// 2,031 img/s/chip device rate) — the reference leans on torchvision's
// libjpeg-turbo C path for the same job. This is the tpu_dist equivalent:
// libjpeg from a memory buffer, with two wins over the PIL path:
//
//  1. DCT-domain scaling: libjpeg can emit 1/2, 1/4, 1/8-scale pixels
//     straight from the coefficients, so a 1500px photo headed for 224px
//     decodes ~8x fewer pixels before the bilinear pass ever runs.
//  2. The GIL is released for the whole decode (ctypes), so the loader's
//     thread pool decodes genuinely in parallel.
//
// Semantics mirror tpu_dist.data.imagefolder._decode: resize so the SHORT
// side hits pre_short (= size*256//224, the reference's Resize(256) for
// CenterCrop(224)), bilinear, center crop to (size, size, 3) RGB u8. The
// target dims are computed from the ORIGINAL geometry so the result frames
// identically to the PIL path (resampling kernels differ by design).
//
// Builds without libjpeg too (__has_include guard): decode_available()
// reports 0 and Python stays on PIL.

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <cmath>
#include <vector>

// TPU_DIST_NO_JPEG comes from the Makefile when the link probe fails (a
// header with no linkable library); __has_include covers the no-header case.
#if __has_include(<jpeglib.h>) && !defined(TPU_DIST_NO_JPEG)
#define TPU_DIST_HAVE_JPEG 1
#include <csetjmp>
#include <cstdio>
#include <jpeglib.h>
#else
#define TPU_DIST_HAVE_JPEG 0
#endif

namespace {

// Bilinear resize (H, W, 3) u8 -> (out_h, out_w, 3) u8, PIL-style
// half-pixel-centered sampling grid.
void resize_bilinear(const uint8_t* src, int h, int w, uint8_t* dst,
                     int out_h, int out_w) {
    const float sy = (float)h / out_h, sx = (float)w / out_w;
    for (int oy = 0; oy < out_h; ++oy) {
        float fy = (oy + 0.5f) * sy - 0.5f;
        int y0 = (int)std::floor(fy);
        float wy = fy - y0;
        int y1 = y0 + 1;
        if (y0 < 0) y0 = 0;
        if (y1 < 0) y1 = 0;
        if (y0 > h - 1) y0 = h - 1;
        if (y1 > h - 1) y1 = h - 1;
        for (int ox = 0; ox < out_w; ++ox) {
            float fx = (ox + 0.5f) * sx - 0.5f;
            int x0 = (int)std::floor(fx);
            float wx = fx - x0;
            int x1 = x0 + 1;
            if (x0 < 0) x0 = 0;
            if (x1 < 0) x1 = 0;
            if (x0 > w - 1) x0 = w - 1;
            if (x1 > w - 1) x1 = w - 1;
            const uint8_t* p00 = src + (y0 * (int64_t)w + x0) * 3;
            const uint8_t* p01 = src + (y0 * (int64_t)w + x1) * 3;
            const uint8_t* p10 = src + (y1 * (int64_t)w + x0) * 3;
            const uint8_t* p11 = src + (y1 * (int64_t)w + x1) * 3;
            uint8_t* o = dst + (oy * (int64_t)out_w + ox) * 3;
            for (int c = 0; c < 3; ++c) {
                float v = (1 - wy) * ((1 - wx) * p00[c] + wx * p01[c]) +
                          wy * ((1 - wx) * p10[c] + wx * p11[c]);
                o[c] = (uint8_t)(v + 0.5f);
            }
        }
    }
}

#if TPU_DIST_HAVE_JPEG
struct ErrMgr {
    jpeg_error_mgr pub;
    std::jmp_buf jump;
};

void on_error(j_common_ptr cinfo) {
    std::longjmp(((ErrMgr*)cinfo->err)->jump, 1);
}
#endif

}  // namespace

extern "C" {

int decode_available(void) { return TPU_DIST_HAVE_JPEG; }

// Decode JPEG bytes -> resize short side to pre_short (bilinear, target
// dims from the original geometry) -> center crop (size, size, 3) RGB u8
// into out. Returns 0 on success, nonzero on any decode error (caller
// falls back to PIL).
int decode_jpeg_resize_crop(const uint8_t* data, int64_t len, int size,
                            int pre_short, uint8_t* out) {
#if !TPU_DIST_HAVE_JPEG
    (void)data; (void)len; (void)size; (void)pre_short; (void)out;
    return -1;
#else
    // buffers DECLARED BEFORE setjmp: a longjmp from mid-decode lands back
    // here with both vectors still live, so their destructors run on the
    // error return — no leak, no longjmp-over-unwound-objects UB
    std::vector<uint8_t> pixels, resized;
    jpeg_decompress_struct cinfo;
    ErrMgr jerr;
    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = on_error;
    if (setjmp(jerr.jump)) {
        jpeg_destroy_decompress(&cinfo);
        return 1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, data, (unsigned long)len);
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return 2;
    }
    const int w0 = (int)cinfo.image_width, h0 = (int)cinfo.image_height;
    if (w0 <= 0 || h0 <= 0) {
        jpeg_destroy_decompress(&cinfo);
        return 3;
    }
    // target dims from the ORIGINAL geometry (matches the PIL path)
    const double scale = (double)pre_short / (w0 < h0 ? w0 : h0);
    int tw = (int)std::lround(w0 * scale);
    int th = (int)std::lround(h0 * scale);
    if (tw < 1) tw = 1;
    if (th < 1) th = 1;
    // DCT scaling: smallest 1/d (d in 8,4,2,1) still >= the resize target
    cinfo.scale_num = 1;
    cinfo.scale_denom = 1;
    for (int d = 8; d > 1; d /= 2) {
        if (w0 / d >= tw && h0 / d >= th) {
            cinfo.scale_denom = (unsigned)d;
            break;
        }
    }
    cinfo.out_color_space = JCS_RGB;
    // speed knobs: the fast integer DCT and plain (non-fancy) chroma
    // upsampling cost ~1 gray level worst-case vs the accurate paths —
    // noise well below the bilinear resample that follows
    cinfo.dct_method = JDCT_IFAST;
    cinfo.do_fancy_upsampling = FALSE;
    jpeg_start_decompress(&cinfo);
    const int dw = (int)cinfo.output_width, dh = (int)cinfo.output_height;
    pixels.resize((size_t)dw * dh * 3);
    while (cinfo.output_scanline < cinfo.output_height) {
        JSAMPROW row = pixels.data() + (size_t)cinfo.output_scanline * dw * 3;
        jpeg_read_scanlines(&cinfo, &row, 1);
    }
    jpeg_finish_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);

    resized.resize((size_t)tw * th * 3);
    resize_bilinear(pixels.data(), dh, dw, resized.data(), th, tw);
    if (th < size || tw < size) return 4;  // pre_short >= size always holds
    const int top = (th - size) / 2, left = (tw - size) / 2;
    for (int y = 0; y < size; ++y) {
        std::memcpy(out + (size_t)y * size * 3,
                    resized.data() + ((size_t)(top + y) * tw + left) * 3,
                    (size_t)size * 3);
    }
    return 0;
#endif
}

}  // extern "C"
