// Native host-side batch gather for the data loader (tpu_dist.data).
//
// Role: the reference delegated its host->device feeding hot path to native
// code (CUDA-stream prefetcher, reference 4.apex_distributed.py:80-133, and
// torch DataLoader's C++ workers). On TPU the device side is XLA's; the
// host-side gather (assembling a batch from sampled row indices) is this
// library. It releases the GIL implicitly (called via ctypes from the
// producer thread) so batch assembly genuinely overlaps the jitted step even
// on a 1-core host, and memcpy's whole rows instead of numpy fancy-indexing
// element loops.
//
// Build: make -C csrc   (g++ -O3 -march=native -shared -fPIC)

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// Gather rows: out[i,:] = src[idx[i],:], row_bytes bytes per row.
void gather_rows_u8(const uint8_t* src, const int64_t* idx, uint8_t* out,
                    int64_t n_rows, int64_t row_bytes) {
    for (int64_t i = 0; i < n_rows; ++i) {
        std::memcpy(out + i * row_bytes, src + idx[i] * row_bytes,
                    (size_t)row_bytes);
    }
}

// Gather int32 labels: out[i] = src[idx[i]].
void gather_i32(const int32_t* src, const int64_t* idx, int32_t* out,
                int64_t n) {
    for (int64_t i = 0; i < n; ++i) out[i] = src[idx[i]];
}

}  // extern "C"
