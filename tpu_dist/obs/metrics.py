"""Process-local metrics registry + Prometheus scrape endpoint (stdlib only).

The ledger is a flight recorder — perfect for post-mortems, useless for a
live dashboard: an operator watching a 3-day run wants throughput, MFU,
stall and health-trip counters NOW, from a scraper, without tailing JSONL
over ssh. This module is the export half of the obs subsystem:

* :class:`MetricsRegistry` — counters / gauges / histograms with optional
  labels, rendered in the Prometheus text exposition format
  (``render()``). Thread-safe (the watchdog and HBM sampler feed it from
  daemon threads). No jax, no deps — importable on a login host.
* :func:`metrics_ledger_sink` — a ledger sink that maps the typed event
  stream onto the registry, so EVERYTHING that reaches the ledger (step
  records, watchdog stalls, skew samples, health trips, HBM samples,
  decode calls) feeds the scrape for free, from one mechanism. The
  standard series are pre-registered so a scrape always carries the
  stall/health counters even at zero.
* :class:`MetricsServer` / :func:`serve_metrics` — a daemon-thread HTTP
  endpoint serving ``render()`` on every GET (``/metrics`` by
  convention). ``RunObs`` starts one per process when ``metrics_port`` is
  set, at ``metrics_port + process_index`` — the ``.pN`` story, applied
  to ports. A bind failure warns and disables; an exporter must never
  take the run down.

``RunObs.run_end`` snapshots the registry into a ``metrics_snapshot``
ledger event, so the final counter values survive in the flight record
after the endpoint is gone.
"""

from __future__ import annotations

import sys
import threading
from typing import Dict, Iterable, Optional, Tuple

DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0)


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{str(v)}"' for k, v in labels)
    return "{" + body + "}"


class _Metric:
    """One named family; per-label-set children live in ``_series``."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str = ""):
        self.name = name
        self.help = help_text
        self._series: Dict[Tuple[Tuple[str, str], ...], object] = {}
        # RLock, not Lock: RunObs.run_end's SIGTERM path snapshots every
        # metric on the main thread — if the signal lands while that same
        # thread is inside labels()/render() (the ledger-sink fan-out), a
        # plain Lock would self-deadlock (distlint DL101)
        self._lock = threading.RLock()

    def labels(self, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            child = self._series.get(key)
            if child is None:
                child = self._new_child()
                self._series[key] = child
        return child

    def _default(self):
        return self.labels()

    def _new_child(self):
        raise NotImplementedError

    def _render_series(self, out, key, child):
        raise NotImplementedError

    def render(self, out: list) -> None:
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            items = list(self._series.items())
        for key, child in sorted(items):
            self._render_series(out, key, child)

    def snapshot(self) -> dict:
        with self._lock:
            return {_label_str(k) or "": child.value_view()
                    for k, child in self._series.items()}


class _CounterChild:
    def __init__(self):
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v += amount

    @property
    def value(self) -> float:
        return self._v

    def value_view(self):
        return self._v


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value

    def _render_series(self, out, key, child):
        out.append(f"{self.name}{_label_str(key)} {_fmt(child.value)}")


class _GaugeChild(_CounterChild):
    def __init__(self):
        super().__init__()
        self._fn = None

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def set_fn(self, fn) -> None:
        """Computed gauge: ``fn()`` is evaluated at every read (render/
        snapshot) — for values that age between scrapes, like
        ``tpu_dist_last_step_age_s``. ``fn`` must be cheap and safe."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return self._v
        return self._v

    def value_view(self):
        return self.value


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, v: float) -> None:
        self._default().set(v)

    def set_fn(self, fn) -> None:
        self._default().set_fn(fn)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value

    def _render_series(self, out, key, child):
        out.append(f"{self.name}{_label_str(key)} {_fmt(child.value)}")


class _HistogramChild:
    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1 = +Inf
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
            self.counts[-1] += 1

    def value_view(self):
        return {"sum": self.sum, "count": self.count}


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text="", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_text)
        self.buckets = tuple(sorted(buckets))

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def _render_series(self, out, key, child):
        for b, c in zip(child.buckets, child.counts):
            ls = _label_str(key + (("le", _fmt(b)),))
            out.append(f"{self.name}_bucket{ls} {c}")
        ls = _label_str(key + (("le", "+Inf"),))
        out.append(f"{self.name}_bucket{ls} {child.counts[-1]}")
        out.append(f"{self.name}_sum{_label_str(key)} {_fmt(child.sum)}")
        out.append(f"{self.name}_count{_label_str(key)} {child.count}")


class MetricsRegistry:
    """Get-or-create registry of named metrics; ``render()`` is the scrape
    payload, ``snapshot()`` the JSON-safe dump for ``metrics_snapshot``."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        # RLock for the same reason as _Metric._lock: snapshot() runs on
        # the SIGTERM handler path while _get() serves main-thread sinks
        self._lock = threading.RLock()

    def _get(self, cls, name, help_text, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_text, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name!r} already registered as "
                                 f"{type(m).__name__}")
        return m

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get(Counter, name, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get(Gauge, name, help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help_text, buckets=buckets)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        out: list = []
        for m in metrics:
            m.render(out)
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        with self._lock:
            metrics = list(self._metrics.items())
        return {name: m.snapshot() for name, m in metrics}

    def read_value(self, name: str):
        """The unlabeled child's current value, or None when the family
        (or its default child) does not exist — a cheap single-series
        read that never renders the registry (the /healthz path)."""
        with self._lock:
            m = self._metrics.get(name)
        if m is None:
            return None
        with m._lock:
            child = m._series.get(())
        return None if child is None else child.value


# -- the ledger -> registry bridge ----------------------------------------

def metrics_ledger_sink(reg: MetricsRegistry):
    """Build the sink that maps ledger events onto the registry. The
    operator-facing series are pre-registered here so a scrape during a
    healthy run still exposes the zero-valued stall/health counters
    (absence and zero are different answers to "is it hung?")."""
    steps = reg.counter("tpu_dist_steps_total",
                        "optimizer steps recorded in the ledger")
    items = reg.counter("tpu_dist_items_total",
                        "items (images/tokens) trained, global batch")
    throughput = reg.gauge("tpu_dist_step_throughput",
                           "last step record's items/sec (unit label)")
    mfu = reg.gauge("tpu_dist_mfu", "last step record's model FLOP/s "
                    "utilization (0-1)")
    loss = reg.gauge("tpu_dist_loss", "last recorded train loss")
    phase = reg.counter("tpu_dist_phase_seconds_total",
                        "host-measured step phase seconds by phase label")
    step_hist = reg.histogram("tpu_dist_step_seconds",
                              "per-optimizer-step wall seconds")
    stalls = reg.counter("tpu_dist_stalls_total",
                         "watchdog stall dumps fired")
    stall_idle = reg.gauge("tpu_dist_stall_idle_seconds",
                           "idle seconds at the last watchdog stall")
    skew_spread = reg.gauge("tpu_dist_skew_spread_seconds",
                            "last cross-host step-time spread (max-min)")
    straggler = reg.gauge("tpu_dist_straggler_index",
                          "process index of the last skew straggler")
    health = reg.counter("tpu_dist_health_trips_total",
                         "numerical-health trips by kind")
    health.labels(kind="nonfinite")       # pre-register: scrape shows 0
    health.labels(kind="loss_spike")
    # goodput accounting + progress SLOs (obs.goodput): the ratio and the
    # per-category badput seconds track the last 'goodput' event (a
    # snapshot partition, hence gauges); breaches are a counter by kind
    goodput_ratio = reg.gauge("tpu_dist_goodput_ratio",
                              "goodput share of wall-clock (0-1), from "
                              "the last goodput event")
    badput = reg.gauge("tpu_dist_badput_seconds",
                       "badput seconds by category, from the last "
                       "goodput event")
    from tpu_dist.obs.goodput import CATEGORIES
    for c in CATEGORIES:
        badput.labels(category=c)         # pre-register: scrape shows 0
    slo_breaches = reg.counter("tpu_dist_slo_breaches_total",
                               "progress-SLO breaches by kind")
    slo_breaches.labels(kind="steps_per_min")
    slo_breaches.labels(kind="throughput")
    # progress-aware liveness: seconds since the last step record,
    # computed at read time (-1 before the first step) — the /healthz
    # body carries it so an external probe can detect a stalled-but-alive
    # run without parsing the full scrape
    import time as _time

    last_step_ts = [None]
    age = reg.gauge("tpu_dist_last_step_age_s",
                    "seconds since the last step record (-1 before any)")
    age.labels().set_fn(
        lambda: (round(_time.time() - last_step_ts[0], 3)
                 if last_step_ts[0] else -1.0))
    # build_info-style identity gauge (value always 1; the labels are the
    # payload): scrapes from different runs/configs become joinable on
    # run_id/config_hash, Prometheus-standard style. The family is
    # pre-registered here; its one child materializes when run_start
    # carries the labels.
    build_info = reg.gauge("tpu_dist_build_info",
                           "run identity: join metrics across runs on "
                           "these labels (value is always 1)")
    epoch_g = reg.gauge("tpu_dist_epoch", "last completed epoch")
    eval_loss = reg.gauge("tpu_dist_eval_loss", "last held-out eval loss")
    hbm = reg.gauge("tpu_dist_hbm_bytes_in_use", "last HBM sampler reading")
    decode_toks = reg.counter("tpu_dist_decode_tokens_total",
                              "tokens produced by generate() calls")
    # serving (engine.serve): queue/occupancy/pool-pressure gauges track
    # the admit/kv_cache event stream; requests and admission rejections
    # are counters so a dashboard rates them
    serve_queue = reg.gauge("tpu_dist_serve_queue_depth",
                            "decode requests waiting for a slot")
    serve_active = reg.gauge("tpu_dist_serve_active_seqs",
                             "sequences occupying serve slots")
    kv_free = reg.gauge("tpu_dist_kv_pages_free",
                        "free pages in the paged KV pool")
    serve_reqs = reg.counter("tpu_dist_serve_requests_total",
                             "serving requests completed")
    serve_rejects = reg.counter("tpu_dist_serve_rejected_total",
                                "submissions rejected by admission control")
    serve_toks = reg.counter("tpu_dist_serve_tokens_total",
                             "tokens generated by the serving engine")
    # per-request tracing (obs.reqtrace): the root 'request' span carries
    # the measured TTFT, so the histogram is fed by the span stream — the
    # scrape-side face of the request observatory
    req_ttft = reg.histogram("tpu_dist_request_ttft_seconds",
                             "per-request time-to-first-token seconds, "
                             "from root request spans")
    # elastic capacity (parallel.consensus / supervisor `scale` events):
    # the live mesh size and the degraded flag, so a dashboard shows a
    # shrink/re-expansion cycle without parsing ledgers
    mesh_procs = reg.gauge("tpu_dist_mesh_processes",
                           "process count of the current mesh (consensus "
                           "view; from run_start and scale events)")
    degraded_g = reg.gauge("tpu_dist_degraded",
                           "1 while running on a shrunken (degraded) "
                           "mesh, 0 at the planned world size")
    # fleet plane (tpu_dist.sim `fleet` events): the stitched goodput
    # ratio, live-host count and cumulative SLO-breach total of a whole
    # simulated (or real multi-supervisor) fleet — the dashboard view of
    # "handles heavy traffic" as one number per scrape
    fleet_ratio = reg.gauge("tpu_dist_fleet_goodput_ratio",
                            "stitched fleet goodput share of aggregate "
                            "wall (0-1), from the last fleet event")
    fleet_hosts = reg.gauge("tpu_dist_fleet_hosts_live",
                            "virtual hosts with a running child, from "
                            "the last fleet event")
    fleet_breaches = reg.counter("tpu_dist_fleet_slo_breaches_total",
                                 "fleet-wide SLO breaches (monotonic; "
                                 "fed by deltas of the fleet events' "
                                 "cumulative count)")
    # autoscaling (obs.autoscale `scale_decision` events): decisions by
    # direction plus the last decision's target capacity — the dashboard
    # face of the closed capacity loop. Directions pre-registered so a
    # steady fleet still scrapes explicit zeros
    autoscale_decisions = reg.counter(
        "tpu_dist_autoscale_decisions_total",
        "autoscaling decisions emitted, by direction")
    autoscale_decisions.labels(direction="up")
    autoscale_decisions.labels(direction="down")
    autoscale_target = reg.gauge(
        "tpu_dist_autoscale_target_hosts",
        "target host count of the last autoscaling decision")
    # program-audit findings (tpu_dist.analysis.proglint 'audit' events)
    # by check id; pre-registered so a clean run still scrapes zeros
    audit_findings = reg.counter("tpu_dist_audit_findings_total",
                                 "unwaivered program-audit findings "
                                 "(analysis.proglint), by check")
    for c in ("PL001", "PL002", "PL003", "PL004", "PL005"):
        audit_findings.labels(check=c)
    # fleet events carry the CUMULATIVE count; a Prometheus counter must
    # only move forward, so the sink feeds it deltas
    fleet_breach_seen = [0.0]
    # materialize the unlabeled children too — a family with no child
    # renders no sample line, and "0" vs "absent" are different answers
    # to "is it hung?"
    for m in (steps, items, mfu, loss, stalls, stall_idle, skew_spread,
              straggler, epoch_g, eval_loss, hbm, decode_toks, step_hist,
              goodput_ratio, serve_queue, serve_active, kv_free, serve_reqs,
              serve_rejects, serve_toks, req_ttft, mesh_procs, degraded_g,
              fleet_ratio, fleet_hosts, fleet_breaches, autoscale_target):
        m.labels()

    def sink(rec: dict) -> None:
        ev = rec.get("event")
        if ev == "run_start":
            import hashlib
            import json as _json

            cfg = rec.get("config") or {}
            chash = hashlib.sha1(_json.dumps(
                cfg, sort_keys=True, default=str).encode()).hexdigest()[:12]
            build_info.labels(
                run_id=f"{int(rec.get('ts') or 0)}-p{rec.get('pid', 0)}",
                kind=str(rec.get("kind") or ""),
                config_hash=chash,
                jax=str(rec.get("jax_version") or ""),
                quant=str(cfg.get("quant") or "none"),
                tp_impl=str(cfg.get("tp_impl") or "gspmd")).set(1)
            if rec.get("process_count") is not None:
                mesh_procs.set(rec["process_count"])
            degraded_g.set(1.0 if rec.get("degraded") else 0.0)
        elif ev == "step":
            last_step_ts[0] = rec.get("ts") or _time.time()
            n = rec.get("steps_in_dispatch") or 1
            steps.inc(n)
            if rec.get("items"):
                items.inc(rec["items"])
            if rec.get("throughput") is not None:
                throughput.labels(unit=rec.get("unit") or "items/s").set(
                    rec["throughput"])
            if rec.get("mfu") is not None:
                mfu.set(rec["mfu"])
            if rec.get("loss") is not None:
                loss.set(rec["loss"])
            wall = 0.0
            for key, lbl in (("data_s", "data"), ("dispatch_s", "dispatch"),
                             ("device_s", "device"), ("comm_s", "comm")):
                v = rec.get(key)
                if v:
                    phase.labels(phase=lbl).inc(v)
                    if key != "comm_s":  # comm overlaps device_s
                        wall += v
            if wall:
                step_hist.observe(wall / n)
        elif ev == "stall":
            stalls.inc()
            if rec.get("idle_s") is not None:
                stall_idle.set(rec["idle_s"])
        elif ev == "skew":
            if rec.get("spread_s") is not None:
                skew_spread.set(rec["spread_s"])
            if rec.get("straggler") is not None:
                straggler.set(rec["straggler"])
        elif ev == "health":
            health.labels(kind=rec.get("kind") or "unknown").inc()
        elif ev == "epoch":
            if rec.get("epoch") is not None:
                epoch_g.set(rec["epoch"])
        elif ev == "eval":
            if rec.get("loss") is not None:
                eval_loss.set(rec["loss"])
        elif ev == "hbm":
            if rec.get("bytes_in_use") is not None:
                hbm.set(rec["bytes_in_use"])
        elif ev == "decode":
            if rec.get("tokens"):
                decode_toks.inc(rec["tokens"])
        elif ev == "admit":
            if rec.get("queue_depth") is not None:
                serve_queue.set(rec["queue_depth"])
            if rec.get("pages_free") is not None:
                kv_free.set(rec["pages_free"])
            if not rec.get("accepted"):
                serve_rejects.inc()
        elif ev == "request":
            serve_reqs.inc()
            if rec.get("tokens"):
                serve_toks.inc(rec["tokens"])
        elif ev == "span":
            # only the root span carries a request-level TTFT; child spans
            # (queue/prefill/decode windows) are trace detail, not samples
            if (rec.get("name") == "request"
                    and rec.get("ttft_s") is not None):
                req_ttft.observe(rec["ttft_s"])
        elif ev == "kv_cache":
            if rec.get("pages_free") is not None:
                kv_free.set(rec["pages_free"])
            if rec.get("active_seqs") is not None:
                serve_active.set(rec["active_seqs"])
        elif ev == "goodput":
            if rec.get("ratio") is not None:
                goodput_ratio.set(rec["ratio"])
            for c, secs in (rec.get("categories") or {}).items():
                if secs is not None:
                    badput.labels(category=c).set(secs)
        elif ev == "slo":
            slo_breaches.labels(kind=rec.get("kind") or "unknown").inc()
        elif ev == "scale":
            if rec.get("processes") is not None:
                mesh_procs.set(rec["processes"])
            act = rec.get("action")
            if act == "shrink":
                degraded_g.set(1.0)
            elif act == "expand":
                degraded_g.set(0.0)
        elif ev == "scale_decision":
            autoscale_decisions.labels(
                direction=rec.get("direction") or "unknown").inc()
            if rec.get("target_hosts") is not None:
                autoscale_target.set(rec["target_hosts"])
        elif ev == "audit":
            for d in (rec.get("detail") or ()):
                if not d.get("waived"):
                    audit_findings.labels(
                        check=d.get("check") or "unknown").inc()
        elif ev == "fleet":
            if rec.get("hosts_live") is not None:
                fleet_hosts.set(rec["hosts_live"])
            if rec.get("goodput_ratio") is not None:
                fleet_ratio.set(rec["goodput_ratio"])
            v = rec.get("slo_breaches")
            if v is not None and v > fleet_breach_seen[0]:
                fleet_breaches.inc(v - fleet_breach_seen[0])
                fleet_breach_seen[0] = v

    return sink


# -- the scrape endpoint ---------------------------------------------------

class MetricsServer:
    """Daemon-thread HTTP server rendering the registry on every GET."""

    def __init__(self, registry: MetricsRegistry, port: int,
                 host: str = "0.0.0.0"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                path = self.path.split("?")[0]
                if path in ("/healthz", "/livez"):
                    # trivial liveness: the process (and this daemon
                    # thread) is up — no registry render, so a wedged
                    # metrics pipeline can't fail the liveness probe.
                    # /healthz is additionally progress-aware: it carries
                    # seconds since the last step record (one cheap
                    # single-gauge read), so an external probe detects a
                    # stalled-but-alive run without parsing the scrape
                    body = b"ok\n"
                    if path == "/healthz":
                        v = reg.read_value("tpu_dist_last_step_age_s")
                        if isinstance(v, (int, float)):
                            body = f"ok last_step_age_s={v:.3f}\n".encode()
                    ctype = "text/plain; charset=utf-8"
                else:
                    body = reg.render().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="tpu-dist-metrics", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)


def serve_metrics(registry: MetricsRegistry, port: int,
                  host: str = "0.0.0.0") -> Optional[MetricsServer]:
    """Start the endpoint; on bind failure warn and return None — the
    exporter is an accessory, never a reason to lose a run."""
    try:
        return MetricsServer(registry, port, host)
    except OSError as e:
        print(f"tpu_dist metrics endpoint disabled: cannot bind port "
              f"{port} ({e})", file=sys.stderr)
        return None
