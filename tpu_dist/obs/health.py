"""Numerical-health sentry: NaN/Inf grads and loss spikes, caught in-band.

A fleet-scale run dies two ways the PR-2 watchdog cannot see: *numerically*
(one bad batch or an LR spike pushes grads to Inf, the optimizer writes NaN
into the params, and every step after that silently trains garbage) and
*statistically* (loss explodes without ever going non-finite). MegaScale-
style production stacks treat both as first-class signals. Two halves here:

* **Device-side probes** (:func:`probe_update_metrics`) — global grad-norm,
  non-finite-leaf count, and update-norm computed INSIDE the jitted train
  step (``engine.steps._apply_update``, which every engine flavor funnels
  through: jit / shard_map / windowed / bucketed / ring / sp / pp). The
  probes are a few tree-reductions fused into the existing program and ride
  the metrics dict the loops already fetch at drain boundaries — **zero new
  host syncs**. With ``health='skip'`` the step also gates itself: a
  non-finite gradient (or update) keeps params/opt-state/batch-stats
  bit-identical while the step counter still advances, so the data stream
  and the per-step RNG fold stay in multi-host lockstep (every process
  computes the same post-sync gradients, so every process skips together).

* **Host-side sentry** (:class:`HealthSentry`) — consumes the fetched
  probes plus the already-fetched loss at each drain: a non-finite trip
  emits a ``health`` ledger event (and raises :class:`HealthError` under
  ``halt``); a trailing EMA/z-score detector flags loss SPIKES that never
  go non-finite (the silent divergence case). Pure stdlib — the sentry
  runs on numbers the loop already holds.

Policy (``health`` knob in TrainConfig/LMConfig): ``record`` (probes +
events only — the default), ``skip`` (zero the update, keep going),
``halt`` (raise out of the loop; the crash-safe ledger shutdown then stamps
``run_end`` with ``status='crashed'``).
"""

from __future__ import annotations

import math
from typing import Optional

HEALTH_POLICIES = ("record", "skip", "halt")

PROBE_KEYS = ("grad_norm", "nonfinite_count", "update_norm")


def validate_health(policy: str) -> str:
    if policy not in HEALTH_POLICIES:
        raise ValueError(f"unknown health policy {policy!r} "
                         f"({'|'.join(HEALTH_POLICIES)})")
    return policy


class HealthError(RuntimeError):
    """Raised by the sentry under ``health='halt'`` when a trip fires."""


# -- device side (called at trace time from the jitted steps) --------------

def _float_leaves(tree):
    import jax
    import jax.numpy as jnp

    return [l for l in jax.tree.leaves(tree)
            if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)]


def probe_update_metrics(grads, old_params, new_params) -> dict:
    """The fused health probes, as f32 scalars that join the step's metric
    sums: global grad L2 norm, count of grad leaves whose squared-sum is
    non-finite (any Inf/NaN value — or a norm overflow, which the gate
    must catch anyway), and the L2 norm of the proposed parameter update.
    ONE reduction pass per tree: the per-leaf squared sums feed both the
    norm and the non-finite count (a single NaN/Inf poisons its leaf's
    sum), so the whole probe set costs one sum-of-squares sweep over
    grads plus one over the update. Computed from the POST-SYNC gradients
    (every caller reduces grads before ``_apply_update``), so the values
    — and any skip decision derived from them — are identical on every
    device and host. Scalars sum across K-step dispatch windows like
    every other metric; the loops divide by ``steps_in_dispatch`` for the
    per-step view."""
    import jax.numpy as jnp

    def sq_sums(leaves):
        return [jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves]

    g_sq = sq_sums(_float_leaves(grads))
    u_sq = [jnp.sum(jnp.square(n.astype(jnp.float32)
                               - o.astype(jnp.float32)))
            for n, o in zip(_float_leaves(new_params),
                            _float_leaves(old_params))]
    zero = jnp.float32(0.0)
    return {
        "grad_norm": jnp.sqrt(sum(g_sq)) if g_sq else zero,
        "nonfinite_count": (sum((~jnp.isfinite(s)).astype(jnp.float32)
                                for s in g_sq) if g_sq else zero),
        "update_norm": jnp.sqrt(sum(u_sq)) if u_sq else zero,
    }


def probes_ok(probes: dict):
    """Device-side gate for ``health='skip'``: True iff no grad leaf is
    non-finite AND both norms are finite (an overflow that squares to Inf
    is caught by the norm even when no single leaf is Inf yet)."""
    import jax.numpy as jnp

    return ((probes["nonfinite_count"] == 0)
            & jnp.isfinite(probes["grad_norm"])
            & jnp.isfinite(probes["update_norm"]))


# -- host side -------------------------------------------------------------

class HealthSentry:
    """Drain-boundary consumer of the fetched probes + loss.

    ``observe()`` is called by both engines' ``_drain`` once per step
    record (numbers already on host — no sync). Trips:

    * ``nonfinite`` — the record's non-finite-leaf count is > 0, a probe
      norm came back non-finite, or the loss itself is NaN/Inf. Under
      ``skip`` the device already zeroed the update; the event records
      that. Under ``halt`` the sentry raises :class:`HealthError`.
    * ``loss_spike`` — the loss is finite but more than ``spike_z``
      trailing standard deviations above the EMA mean (EMA over the last
      ~``2/alpha`` records, armed after ``warmup`` observations so early
      fast-dropping losses never false-fire). A spike cannot be un-applied,
      so its action is ``record`` unless the policy is ``halt``.

    Every trip emits a ``health`` ledger event (EVENT_SCHEMA), which the
    metrics registry's ledger sink turns into the
    ``tpu_dist_health_trips_total`` counter.
    """

    def __init__(self, policy: str = "record", spike_z: float = 8.0,
                 ledger=None, alpha: float = 0.05, warmup: int = 20):
        self.policy = validate_health(policy)
        self.spike_z = float(spike_z)
        self.ledger = ledger
        self.alpha = alpha
        self.warmup = warmup
        self._mean: Optional[float] = None
        self._var = 0.0
        self._n = 0
        self.trips = 0
        self.trips_by_kind: dict = {}

    def _trip(self, step, kind: str, action: str, value, loss, grad_norm):
        self.trips += 1
        self.trips_by_kind[kind] = self.trips_by_kind.get(kind, 0) + 1
        if self.ledger is not None:
            self.ledger.emit("health", step=step, kind=kind,
                             policy=self.policy, action=action, value=value,
                             loss=loss, grad_norm=grad_norm)
        if action == "halt":
            raise HealthError(
                f"health=halt: {kind} at step {step} (value={value!r}, "
                f"loss={loss!r}, grad_norm={grad_norm!r}) — see the "
                "'health' ledger event")

    def observe(self, step: int, loss, nonfinite=None, grad_norm=None,
                update_norm=None, n_steps: int = 1) -> None:
        """One step record's worth of health signals (window records pass
        their per-step means and the summed non-finite count)."""
        loss = None if loss is None else float(loss)
        loss_bad = loss is not None and not math.isfinite(loss)
        probe_bad = any(v is not None and not math.isfinite(float(v))
                        for v in (grad_norm, update_norm))
        if (nonfinite and float(nonfinite) > 0) or probe_bad or loss_bad:
            self._trip(step, "nonfinite", self.policy,
                       float(nonfinite or 0), loss, grad_norm)
            return  # a non-finite loss must not poison the spike EMA
        if loss is None:
            return
        if self._mean is not None and self._n >= self.warmup \
                and self.spike_z > 0:
            std = math.sqrt(max(self._var, 1e-24))
            z = (loss - self._mean) / std
            if z > self.spike_z:
                self._trip(step, "loss_spike",
                           "halt" if self.policy == "halt" else "record",
                           round(z, 3), loss, grad_norm)
                return  # do not absorb the spike into the baseline
        if self._mean is None:
            self._mean = loss
        else:
            d = loss - self._mean
            self._mean += self.alpha * d
            self._var = (1 - self.alpha) * (self._var + self.alpha * d * d)
        self._n += 1
