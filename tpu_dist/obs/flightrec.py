"""Triggered flight recorder: capture a forensic bundle at the bad moment.

PRs 2/5 built detection — watchdog stalls, health trips, skew stragglers —
but a trip leaves the operator with a stack dump on stderr and a number in
the ledger: no profiler window of the bad steps, no memory profile, no
packaged artifact to attach to an incident. The flight recorder is the
capture half. It is ALWAYS on (a bounded in-memory ring of recent ledger
records costs nothing) and, when triggered, writes one self-contained
bundle directory:

* ``manifest.json``    — reason, step, timestamps, file inventory, trace
  status (the machine-readable index; rewritten when the trace lands);
* ``stacks.txt``       — every Python thread's stack at trigger time;
* ``hbm.json``         — live device memory counters (allocator truth);
* ``memory.prof``      — ``jax.profiler.save_device_memory_profile``
  (pprof; per-buffer attribution for OOM forensics);
* ``events_tail.jsonl``— the ring: the last N ledger records leading up
  to the trigger (what the run was doing);
* ``trace/``           — a ``jax.profiler`` trace of the next K step
  records after the trigger (armed at trigger time, started/stopped on
  the loop thread at drain boundaries — profiler state is global, so a
  daemon-thread trigger must never touch it directly).

Triggers: watchdog ``stall`` events, health-sentry ``health`` trips, skew
samples whose spread marks a straggler spike, progress-SLO ``slo``
breaches (obs.goodput), ``SIGUSR1`` (operator-
initiated, armed by :class:`~tpu_dist.obs.RunObs`), or a direct
:meth:`FlightRecorder.trigger` call. All but the signal arrive through the
run ledger's event stream — the recorder is a ledger sink, the same
one-mechanism wiring the metrics registry uses — so every detector that
can emit an event can produce a bundle without new plumbing. Each bundle
emits a ``diagnosis`` ledger event pointing at its directory; a cooldown
and a bundle cap keep a flapping detector from filling the disk.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, List, Optional

from tpu_dist.obs.ledger import Ledger

# a skew sample is a straggler SPIKE (not routine jitter) when the
# cross-host spread exceeds both bounds
SKEW_SPREAD_FACTOR = 4.0   # x the sample's own p50 step time
SKEW_SPREAD_MIN_S = 0.5    # and an absolute floor


def _skew_is_spike(rec: dict) -> bool:
    spread = rec.get("spread_s")
    p50 = rec.get("p50_s")
    if spread is None:
        return False
    return (spread >= SKEW_SPREAD_MIN_S
            and spread >= SKEW_SPREAD_FACTOR * (p50 or 0.0))


class FlightRecorder:
    """Always-on ring + triggered bundle capture (see module docstring).

    ``dir=''`` derives the bundle root lazily at first trigger: beside the
    ledger file when it has a path, else a fresh temp directory — a
    triggered capture must never be lost to a missing config knob.
    ``trace_steps=0`` disables the profiler window (the rest of the bundle
    still captures); ``profiler_busy`` lets the owner veto the window when
    a ``profile_dir`` session already drives the (global) profiler.
    """

    def __init__(self, dir: str = "", ledger: Optional[Ledger] = None,
                 ring_size: int = 256, trace_steps: int = 3,
                 profiler_busy: Optional[Callable[[], bool]] = None,
                 cooldown_s: float = 60.0, max_bundles: int = 8,
                 process_index: int = 0):
        self._dir = dir or ""
        self.ledger = ledger
        self.trace_steps = max(int(trace_steps), 0)
        self._profiler_busy = profiler_busy or (lambda: False)
        self.cooldown_s = cooldown_s
        self.max_bundles = max_bundles
        self.process_index = process_index
        self.ring: deque = deque(maxlen=ring_size)
        self.bundles: List[str] = []
        # RLock, not Lock: the SIGUSR1 handler runs ON the main thread and
        # calls trigger() — if the signal lands while that same thread is
        # inside sink()/_advance_trace() holding this lock, a plain Lock
        # would self-deadlock (the same hazard Ledger._lock documents)
        self._lock = threading.RLock()
        self._last_trigger: Optional[float] = None
        self._last_step: Optional[int] = None
        self._drop_noted = False   # one cooldown note per window
        self._cap_noted = False    # one cap note per run
        # pending/active profiler window: {"state", "bundle", "manifest",
        # "remaining"} — mutated only under _lock, profiler calls only on
        # the loop thread (step-event sink)
        self._trace: Optional[dict] = None
        self._seq = 0

    # -- the ledger-sink half (auto-triggers + ring + trace advance) ------
    def sink(self, rec: dict) -> None:
        """Registered on the run ledger: every event feeds the ring; the
        detector events trigger a capture; step records drive the armed
        profiler window (they are emitted on the loop thread at drain
        boundaries — the only safe place to touch global profiler state)."""
        ev = rec.get("event")
        with self._lock:
            self.ring.append(rec)
            if ev == "step" and rec.get("step") is not None:
                self._last_step = rec["step"]
        if ev == "step":
            self._advance_trace()
        elif ev == "stall":
            self.trigger("stall", note=f"idle {rec.get('idle_s')}s "
                                       f"(threshold {rec.get('threshold_s')}s)")
        elif ev == "health":
            self.trigger("health", note=f"{rec.get('kind')} at step "
                                        f"{rec.get('step')} -> "
                                        f"{rec.get('action')}")
        elif ev == "skew" and _skew_is_spike(rec):
            self.trigger("skew", note=f"spread {rec.get('spread_s')}s, "
                                      f"straggler {rec.get('straggler')}")
        elif ev == "slo":
            # progress-SLO breach (obs.goodput): the run is alive but not
            # making floor-rate progress — exactly a flight-record moment
            self.trigger("slo", note=f"{rec.get('kind')} "
                                     f"{rec.get('value')} < floor "
                                     f"{rec.get('floor')} at step "
                                     f"{rec.get('step')}")

    # -- capture ----------------------------------------------------------
    def _base_dir(self) -> str:
        if not self._dir:
            if self.ledger is not None and self.ledger.path:
                self._dir = self.ledger.path + ".flightrec"
            else:
                self._dir = tempfile.mkdtemp(prefix="tpu_dist_flightrec.")
        os.makedirs(self._dir, exist_ok=True)
        return self._dir

    def trigger(self, reason: str, note: Optional[str] = None) -> Optional[str]:
        """Capture a bundle NOW (ring tail, stacks, HBM, memory profile,
        manifest), arm the profiler window for the next ``trace_steps``
        step records, and emit the ``diagnosis`` ledger event. Returns the
        bundle directory, or None when rate-limited (cooldown) or capped.
        Safe to call from any thread — the profiler is never touched here.
        """
        import sys

        now = time.monotonic()
        with self._lock:
            if self._last_trigger is not None \
                    and now - self._last_trigger < self.cooldown_s:
                # dropped-but-observable: an operator's kill -USR1 inside
                # the cooldown must not look like a dead recorder — but a
                # flapping detector triggering every step must not flood
                # stderr either, so note only the FIRST drop per window
                if not self._drop_noted:
                    self._drop_noted = True
                    print(f"tpu_dist flightrec: {reason!r} trigger dropped"
                          f" (cooldown {self.cooldown_s:g}s; further drops"
                          " this window are silent)", file=sys.stderr)
                return None
            if len(self.bundles) >= self.max_bundles:
                if not self._cap_noted:
                    self._cap_noted = True
                    print(f"tpu_dist flightrec: {reason!r} trigger dropped"
                          f" (bundle cap {self.max_bundles} reached; no "
                          "further captures this run)", file=sys.stderr)
                return None
            self._drop_noted = False
            self._last_trigger = now
            self._seq += 1
            seq = self._seq
            tail = list(self.ring)
            step = self._last_step
        bundle = os.path.join(
            self._base_dir(),
            f"{seq:03d}-{reason}-p{self.process_index}")
        os.makedirs(bundle, exist_ok=True)
        files = {}
        files["stacks.txt"] = self._write_stacks(bundle)
        files["hbm.json"] = self._write_hbm(bundle)
        files["memory.prof"] = self._write_memory_profile(bundle)
        files["events_tail.jsonl"] = self._write_tail(bundle, tail)
        trace_status = self._arm_trace(bundle)
        manifest = {
            "reason": reason,
            "note": note,
            "step": step,
            "ts": time.time(),
            "process_index": self.process_index,
            "files": {k: v for k, v in files.items() if v},
            "trace": trace_status,
        }
        self._write_manifest(bundle, manifest)
        if trace_status["status"] == "armed":
            with self._lock:
                self._trace = {"state": "armed", "bundle": bundle,
                               "manifest": manifest,
                               "remaining": self.trace_steps}
        with self._lock:
            self.bundles.append(bundle)
        if self.ledger is not None:
            try:
                self.ledger.emit("diagnosis", reason=reason, bundle=bundle,
                                 step=step, note=note,
                                 trace=trace_status["status"])
            except Exception:
                pass  # a capture must never take the run down
        return bundle

    def _write_manifest(self, bundle: str, manifest: dict) -> None:
        try:
            tmp = os.path.join(bundle, "manifest.json.tmp")
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, default=str)
            os.replace(tmp, os.path.join(bundle, "manifest.json"))
        except OSError:
            pass

    def _write_stacks(self, bundle: str) -> Optional[str]:
        from tpu_dist.obs.watchdog import thread_stacks

        try:
            with open(os.path.join(bundle, "stacks.txt"), "w") as f:
                f.write(thread_stacks())
            return "stacks.txt"
        except OSError:
            return None

    def _write_hbm(self, bundle: str) -> Optional[str]:
        try:
            from tpu_dist.utils.telemetry import device_memory_stats

            stats = device_memory_stats()
        except Exception:
            return None
        try:
            with open(os.path.join(bundle, "hbm.json"), "w") as f:
                json.dump(stats, f, indent=1, default=str)
            return "hbm.json"
        except OSError:
            return None

    def _write_memory_profile(self, bundle: str) -> Optional[str]:
        try:  # pprof device-memory profile; backend support varies
            import jax.profiler

            path = os.path.join(bundle, "memory.prof")
            jax.profiler.save_device_memory_profile(path)
            return "memory.prof"
        except Exception:
            return None

    def _write_tail(self, bundle: str, tail: list) -> Optional[str]:
        try:
            with open(os.path.join(bundle, "events_tail.jsonl"), "w") as f:
                for rec in tail:
                    f.write(json.dumps(rec, default=str) + "\n")
            return "events_tail.jsonl"
        except OSError:
            return None

    # -- the profiler window ---------------------------------------------
    def _arm_trace(self, bundle: str) -> dict:
        if self.trace_steps <= 0:
            return {"status": "disabled", "steps": 0}
        if self._profiler_busy():
            return {"status": "skipped",
                    "why": "a profile_dir session owns the profiler"}
        with self._lock:
            if self._trace is not None:
                return {"status": "skipped",
                        "why": "a prior bundle's window is still open"}
        return {"status": "armed", "steps": self.trace_steps,
                "dir": "trace"}

    def _advance_trace(self) -> None:
        """Called on every step record (loop thread): start an armed
        window, count an active one down, stop it when it completes."""
        with self._lock:
            tr = self._trace
            if tr is None:
                return
            state = tr["state"]
        if state == "armed":
            try:
                import jax.profiler

                jax.profiler.start_trace(os.path.join(tr["bundle"], "trace"))
                with self._lock:
                    tr["state"] = "active"
            except Exception as e:
                self._finish_trace(tr, "failed", why=repr(e))
            return
        with self._lock:
            tr["remaining"] -= 1
            done = tr["remaining"] <= 0
        if done:
            self._stop_trace(tr, "captured")

    def _stop_trace(self, tr: dict, status: str, why: Optional[str] = None):
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception as e:
            status, why = "failed", repr(e)
        self._finish_trace(tr, status, why=why)

    def _finish_trace(self, tr: dict, status: str,
                      why: Optional[str] = None) -> None:
        manifest = tr["manifest"]
        manifest["trace"] = {"status": status, "dir": "trace",
                             "steps": self.trace_steps}
        if why:
            manifest["trace"]["why"] = why
        self._write_manifest(tr["bundle"], manifest)
        with self._lock:
            if self._trace is tr:
                self._trace = None

    def close(self) -> None:
        """Finalize a window left open at run end (a stall with no
        subsequent steps — the honest manifest says so)."""
        with self._lock:
            tr = self._trace
        if tr is None:
            return
        if tr["state"] == "active":
            self._stop_trace(tr, "captured",
                             why="truncated: run ended inside the window")
        else:
            self._finish_trace(tr, "not-captured",
                               why="no step completed after the trigger")
