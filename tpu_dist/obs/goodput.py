"""Goodput accounting, restart-aware run lineage, and progress SLOs.

The reference cookbook meters per-step throughput inside one healthy
process; nothing upstream answers the allocation owner's question — "how
much of my wall-clock actually trained the model?" — once compiles, data
stalls, evals, checkpoints, crashes and restarts enter the picture. This
module is the *accounting* half of the obs subsystem (detection lives in
watchdog/health/skew, diagnosis in flightrec/attr, export in metrics):

* :class:`GoodputAccumulator` — time-weighted partition of one attempt's
  wall-clock into **goodput** (productive device step seconds) and the
  badput categories (:data:`CATEGORIES`): startup/compile, data wait,
  dispatch, eval, checkpoint, watchdog stalls, health-skipped steps, and
  drain/idle residue. Pure stdlib, fed one ledger record at a time — the
  same object powers the offline report (replay a file) and the live
  monitor (registered as a ledger sink).
* **Run lineage** — :func:`attempt_path` / :func:`next_attempt_index` /
  :func:`discover_attempt_paths` name and find the per-attempt ledgers of
  one logical job (``run.jsonl``, ``run.a1.jsonl``, ... — the restart
  analog of the multi-process ``.pN`` story), and
  :func:`split_attempts` / :func:`job_accounting` stitch them into one
  timeline with crash→restart gaps charged as ``restart_gap`` badput.
  ``RunObs`` stamps ``job_id``/``attempt`` into ``run_start`` and applies
  the attempt suffix to the ledger path (``attempt=-1`` auto-picks the
  next free index).
* :class:`GoodputMonitor` — host-side ledger sink that (a) emits periodic
  and final ``goodput`` events (feeding the ``tpu_dist_goodput_ratio`` /
  ``tpu_dist_badput_seconds`` gauges through the metrics sink), and (b)
  watches progress SLOs: EMA optimizer steps/min and items/s against
  configured floors, emitting an ``slo`` event at each breach episode —
  which auto-triggers the flight recorder through the ledger-sink path,
  the same zero-new-plumbing wiring every other detector uses.

Accounting conventions (the fixture tests in tests/test_goodput.py pin
these exactly):

* everything between ``run_start`` and the ``compile`` event is
  ``startup`` (init, first data fetch, the compile, the warm execute —
  the engines emit ``compile`` right after the warm dispatch's blocking
  device_get, so the whole warm batch lies inside that gap). The warm
  step record itself is emitted later, at the drain; its span is already
  covered by the gap, so it only charges ``startup`` on streams with NO
  ``compile`` event (hand-built ledgers);
* ``eval``/``ckpt`` events use their ``seconds`` field when the engines
  stamp it (exact), else the gap since the previous loop-ordered event;
* a watchdog ``stall``'s idle seconds are badput, and are deducted from
  the next step record's device/data/dispatch contribution — the stalled
  wait surfaces inside that record's phases, so without the deduction it
  would double-count;
* a ``health`` skip moves the skipped step's device share from goodput to
  ``skipped`` (the device ran, the update was discarded);
* whatever the records cannot explain is ``idle`` (drain residue, python
  overhead); categories + goodput always sum to wall-clock, with any
  over-attribution surfaced as ``overrun_s`` instead of hidden.
"""

from __future__ import annotations

import glob
import os
import re
import threading
import time
from typing import Dict, List, Optional

# badput categories, in report order. "goodput" rides beside them (it is
# the complement, not a badput); "restart_gap" only appears at job level.
CATEGORIES = ("startup", "data_wait", "dispatch", "eval", "ckpt", "stall",
              "skipped", "idle", "restart_gap")

# events whose emission order follows the loop thread: they anchor the
# gap cursor. Daemon-thread events (hbm sampler, watchdog stall, flightrec
# diagnosis) land at arbitrary points and must not shrink an eval/ckpt gap.
_ANCHORS = frozenset({
    "run_start", "compile", "step", "eval", "ckpt", "epoch", "decode",
    "health", "skew", "goodput", "slo", "metrics_snapshot", "run_end"})


# -- run lineage: per-attempt ledger naming --------------------------------

def attempt_path(path: str, attempt: int) -> str:
    """Suffix a ledger path with the attempt ordinal: ``run.jsonl`` ->
    ``run.a2.jsonl`` for attempt 2; attempt 0 keeps the bare path (the
    restart analog of :func:`~tpu_dist.obs.ledger.per_process_path`)."""
    if not path or attempt <= 0:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.a{attempt}{ext}"


def next_attempt_index(path: str, process_index: int = 0) -> int:
    """The next free attempt ordinal for ``path``: 0 when this process's
    bare-attempt file does not exist yet, else 1 + the highest ``.aN`` on
    disk — the ``attempt=-1`` auto mode, so a restarted job never
    clobbers or appends to a previous attempt's ledger.

    Detection probes THIS process's own files (``run.p1.jsonl`` /
    ``run.aN.p1.jsonl`` for process 1), never the shared bare path:
    process 0 creating ``run.jsonl`` first must not make a
    later-starting process 1 of the SAME attempt self-assign attempt 1.
    On multi-host runs without a shared ledger directory, still pass the
    attempt explicitly (the scheduler's restart counter) so all
    processes agree."""
    from tpu_dist.obs.ledger import per_process_path

    if not path:
        return 0
    mine = lambda n: per_process_path(attempt_path(path, n), process_index)
    if not os.path.exists(mine(0)):
        return 0
    root, ext = os.path.splitext(path)
    psuf = f".p{process_index}" if process_index else ""
    highest = 0
    for p in glob.glob(f"{glob.escape(root)}.a*{psuf}{ext}"):
        m = re.fullmatch(re.escape(root) + r"\.a(\d+)"
                         + re.escape(psuf + ext), p)
        if m:
            highest = max(highest, int(m.group(1)))
    return highest + 1


def attempt_ordinal(path: str) -> int:
    """The attempt number a ledger path encodes (``run.a2.jsonl`` -> 2;
    bare -> 0) — label lanes/reports by THIS, not by list position, so a
    lost intermediate attempt ledger never renumbers the rest."""
    root, _ = os.path.splitext(path)
    m = re.search(r"\.a(\d+)$", root)
    return int(m.group(1)) if m else 0


def discover_attempt_paths(path: str) -> List[str]:
    """``run.jsonl`` -> [run.jsonl, run.a1.jsonl, ...] (attempt order).
    Works from any attempt's path — the bare stem is derived first."""
    root, ext = os.path.splitext(path)
    m = re.fullmatch(r"(.*)\.a(\d+)", root)
    if m:
        root = m.group(1)
        path = root + ext
    found = {}
    for p in glob.glob(f"{glob.escape(root)}.a*{ext}"):
        mm = re.fullmatch(re.escape(root) + r"\.a(\d+)" + re.escape(ext), p)
        if mm:
            found[int(mm.group(1))] = p
    out = [path] if os.path.exists(path) or not found else []
    return out + [found[i] for i in sorted(found)]


def sup_sibling_path(path: str) -> str:
    """The supervisor's own scale-event ledger for a job: any attempt
    path -> ``<stem>.sup<ext>`` (``run.a2.jsonl`` -> ``run.sup.jsonl``).
    THE naming rule — the supervisor writes it, and load_job_records /
    tools/trace_merge discover it, through this one function."""
    root, ext = os.path.splitext(path)
    root = re.sub(r"\.a\d+$", "", root)  # any attempt path -> the stem
    return f"{root}.sup{ext}"


def load_job_records(path: str, discover: bool = True,
                     warn=None) -> List[dict]:
    """Read one logical JOB back from disk: the attempt family of ``path``
    (``run.jsonl``, ``run.a1.jsonl``, ... in attempt order) with the
    supervisor's ``<stem>.sup.jsonl`` scale-event sibling APPENDED — never
    ts-interleaved, because a between-attempt ``scale`` record sorted into
    the middle would split a pseudo-attempt into the run_start-boundary
    goodput/restart math (the consumers order scale events by ts
    themselves). ``discover=False`` reads only the given file.

    This is the one job-loading rule: ``tools/ledger_report`` renders a
    single job from it, and :class:`tpu_dist.sim.fleet.FleetLedger` calls
    it once per host — cross-host discovery is per-host job discovery
    plus a directory walk. Lenient by design (``strict=False`` reads,
    unreadable files skipped through ``warn``): crashed hosts are exactly
    the ones a fleet report inspects."""
    import sys

    from tpu_dist.obs.ledger import read_ledger

    warn = warn or (lambda msg: print(msg, file=sys.stderr))
    paths = (discover_attempt_paths(path) or [path]) if discover else [path]
    records: List[dict] = []
    for p in paths:
        try:
            records.extend(read_ledger(p, strict=False))
        except OSError as e:
            warn(f"warning: skipping {p}: {e}")
    if discover:
        sup = sup_sibling_path(paths[0])
        if os.path.exists(sup):
            try:
                records.extend(read_ledger(sup, strict=False))
            except OSError as e:
                warn(f"warning: skipping {sup}: {e}")
    return records


def fleet_accounting(host_jobs: Dict) -> Optional[dict]:
    """Aggregate per-host job partitions (each a :func:`job_accounting`
    dict, keyed by host id) into ONE fleet partition.

    The fleet invariant is inherited, not re-proven: each host's
    categories + goodput sum to its own stitched wall (restart gaps
    included, over-attribution surfaced as overrun), so the fleet sums
    preserve it — ``goodput_s + sum(categories) == aggregate_wall_s`` to
    rounding, with ``sum_check`` carrying the measured ratio so a report
    (and the CI gate) can assert ~100% instead of trusting this comment.
    ``aggregate wall`` is the sum of host walls (N hosts x T seconds = NT
    host-seconds of capacity — the denominator a capacity owner pays
    for), NOT the max span."""
    jobs = {h: j for h, j in host_jobs.items() if j}
    if not jobs:
        return None
    cats = {c: 0.0 for c in CATEGORIES}
    wall = goodput = overrun = 0.0
    opt_steps = 0
    per_host = {}
    for h in sorted(jobs):
        j = jobs[h]
        wall += j["wall_s"]
        goodput += j["goodput_s"]
        overrun += j.get("overrun_s") or 0.0
        opt_steps += j.get("opt_steps") or 0
        for k, v in (j.get("categories") or {}).items():
            cats[k] = cats.get(k, 0.0) + v
        per_host[h] = {"wall_s": j["wall_s"], "goodput_s": j["goodput_s"],
                       "ratio": j.get("ratio"),
                       "attempts": len(j.get("attempts") or ()) or 1}
    explained = goodput + sum(cats.values())
    return {"hosts": len(jobs),
            "aggregate_wall_s": round(wall, 6),
            "goodput_s": round(goodput, 6),
            "goodput_ratio": round(goodput / wall, 6) if wall else None,
            "categories": {k: round(v, 6) for k, v in cats.items()},
            "overrun_s": round(overrun, 6) if overrun > 1e-9 else 0.0,
            "opt_steps": opt_steps,
            "sum_check": round(explained / wall, 6) if wall else None,
            "per_host": per_host}


def split_attempts(records: List[dict]) -> List[List[dict]]:
    """Split one record stream at ``run_start`` boundaries — the shape of
    a stitched multi-attempt read (files concatenated in attempt order)
    AND of a single file a restarted job appended to."""
    out: List[List[dict]] = []
    for rec in records:
        if rec.get("event") == "run_start" or not out:
            out.append([])
        out[-1].append(rec)
    return out


# -- the accumulator -------------------------------------------------------

class GoodputAccumulator:
    """Feed ledger records in order; :meth:`finalize` yields the partition.

    Also usable directly as a ledger sink (``ledger.add_sink(acc.add)``) —
    bench.py does exactly that to put a ``goodput`` block in its headline
    JSON. All fields tolerate schema-legal ``None`` values.
    """

    def __init__(self):
        self.t0: Optional[float] = None
        self.t_end: Optional[float] = None
        self._t_last: Optional[float] = None
        self._prev: Optional[float] = None
        self.cat: Dict[str, float] = {c: 0.0 for c in CATEGORIES
                                      if c != "restart_gap"}
        self.goodput = 0.0
        self.n_opt = 0
        self.status: Optional[str] = None
        self._pending_stall = 0.0
        self._last_dev_per_opt = 0.0
        self._saw_compile = False

    def add(self, rec: dict) -> None:
        ev = rec.get("event")
        ts = rec.get("ts")
        if ts is None:
            return
        if self.t0 is None:
            self.t0 = ts
            if ev == "run_start":
                self._prev = ts
                self._t_last = ts
                return
        gap = max(0.0, ts - self._prev) if self._prev is not None else 0.0
        if ev == "compile":
            self.cat["startup"] += gap
            self._saw_compile = True
        elif ev == "step":
            d = rec.get("data_s") or 0.0
            p = rec.get("dispatch_s") or 0.0
            v = rec.get("device_s") or 0.0
            if rec.get("warm"):
                # with a compile event, the warm span already lies inside
                # the run_start->compile gap charged above (the record is
                # merely EMITTED later, at the drain) — charging it again
                # would double-count the whole compile
                if not self._saw_compile:
                    self.cat["startup"] += d + p + v
            else:
                k = max(int(rec.get("steps_in_dispatch") or 1), 1)
                self._last_dev_per_opt = v / k
                self.n_opt += k
                # a stall's wait resurfaces inside this record's phases —
                # deduct it so stall badput is not double-counted
                for val, key in ((v, None), (d, "data_wait"),
                                 (p, "dispatch")):
                    take = min(self._pending_stall, val)
                    self._pending_stall -= take
                    if key is None:
                        self.goodput += val - take
                    else:
                        self.cat[key] += val - take
        elif ev == "eval":
            secs = rec.get("seconds")
            self.cat["eval"] += secs if secs is not None else gap
        elif ev == "ckpt":
            secs = rec.get("seconds")
            self.cat["ckpt"] += secs if secs is not None else gap
        elif ev == "decode":
            # a generate() call is productive device work
            self.goodput += rec.get("seconds") or 0.0
        elif ev == "stall":
            idle = rec.get("idle_s") or 0.0
            self.cat["stall"] += idle
            self._pending_stall += idle
        elif ev == "health":
            if rec.get("action") == "skip":
                # the device ran the step; the update was discarded
                shift = min(self.goodput, self._last_dev_per_opt)
                self.goodput -= shift
                self.cat["skipped"] += shift
        elif ev == "run_end":
            self.t_end = ts
            self.status = rec.get("status")
        if ev in _ANCHORS:
            self._prev = ts
        self._t_last = (ts if self._t_last is None
                        else max(self._t_last, ts))

    def end_ts(self) -> Optional[float]:
        return self.t_end if self.t_end is not None else self._t_last

    def finalize(self, end_ts: Optional[float] = None) -> Optional[dict]:
        """The partition as a JSON-safe dict (non-destructive — the live
        monitor snapshots mid-run). None until a first record arrived."""
        if self.t0 is None:
            return None
        end = end_ts if end_ts is not None else self.end_ts()
        wall = max((end or self.t0) - self.t0, 0.0)
        known = self.goodput + sum(v for k, v in self.cat.items()
                                   if k != "idle")
        idle = wall - known
        overrun = max(-idle, 0.0)
        cats = {k: round(v, 6) for k, v in self.cat.items() if k != "idle"}
        cats["idle"] = round(max(idle, 0.0), 6)
        return {"wall_s": round(wall, 6),
                "goodput_s": round(self.goodput, 6),
                "ratio": round(self.goodput / wall, 6) if wall else None,
                "categories": cats,
                "overrun_s": round(overrun, 6) if overrun > 1e-9 else 0.0,
                "opt_steps": self.n_opt,
                "status": self.status}


def accounting(records: List[dict],
               end_ts: Optional[float] = None) -> Optional[dict]:
    """One attempt's records -> its goodput partition (pure replay)."""
    acc = GoodputAccumulator()
    for rec in records:
        acc.add(rec)
    return acc.finalize(end_ts=end_ts)


def job_accounting(attempts: List[List[dict]]) -> Optional[dict]:
    """Stitch per-attempt record lists (attempt order) into one job-level
    partition: categories summed across attempts, plus the between-attempt
    ``restart_gap`` badput (attempt k+1's run_start minus attempt k's last
    event — the crash, scheduler requeue and re-init the per-attempt
    ledgers cannot see). Categories + goodput sum to the stitched wall."""
    accs = []
    for recs in attempts:
        acc = GoodputAccumulator()
        for rec in recs:
            acc.add(rec)
        if acc.t0 is not None:
            # label by the STAMPED ordinal, not the list position — a
            # lost intermediate attempt ledger must not renumber the rest
            starts = [r for r in recs if r.get("event") == "run_start"]
            acc.attempt_no = (starts[0].get("attempt")
                              if starts and starts[0].get("attempt")
                              is not None else len(accs))
            accs.append(acc)
    if not accs:
        return None
    cats = {c: 0.0 for c in CATEGORIES}
    goodput = 0.0
    overrun = 0.0
    opt_steps = 0
    per_attempt = []
    prev_end: Optional[float] = None
    for acc in accs:
        part = acc.finalize()
        for k, v in part["categories"].items():
            cats[k] += v
        goodput += part["goodput_s"]
        overrun += part["overrun_s"]
        opt_steps += part["opt_steps"]
        gap = (max(0.0, acc.t0 - prev_end)
               if prev_end is not None else 0.0)
        cats["restart_gap"] += gap
        per_attempt.append({"attempt": acc.attempt_no,
                            "status": part["status"],
                            "wall_s": part["wall_s"],
                            "goodput_s": part["goodput_s"],
                            "opt_steps": part["opt_steps"],
                            "restart_gap_s": round(gap, 6) or 0.0})
        prev_end = acc.end_ts()
    wall = max((accs[-1].end_ts() or accs[0].t0) - accs[0].t0, 0.0)
    return {"wall_s": round(wall, 6),
            "goodput_s": round(goodput, 6),
            "ratio": round(goodput / wall, 6) if wall else None,
            "categories": {k: round(v, 6) for k, v in cats.items()},
            "overrun_s": round(overrun, 6) if overrun > 1e-9 else 0.0,
            "opt_steps": opt_steps,
            "attempts": per_attempt}


# -- the live monitor ------------------------------------------------------

class GoodputMonitor:
    """Ledger sink: live goodput accounting + progress-SLO watch.

    Registered by ``RunObs`` on every run (a few float adds per event).
    Emits ``goodput`` events every ``every_s`` seconds of run time (0 =
    only the final one ``RunObs.run_end`` asks for) and one ``slo`` event
    per breach *episode* (hysteresis: re-arms when the EMA recovers above
    the floor) — both reach the metrics registry and the flight recorder
    through the normal sink fan-out. EMAs ignore warm records and need
    ``min_records`` samples before judging, so a compile can never breach.
    """

    def __init__(self, ledger, every_s: float = 60.0,
                 slo_steps_per_min: float = 0.0,
                 slo_throughput: float = 0.0, unit: str = "items/s",
                 alpha: float = 0.5, min_records: int = 2):
        self._ledger = ledger
        self.acc = GoodputAccumulator()
        self.every_s = max(float(every_s or 0.0), 0.0)
        self.floors = {"steps_per_min": float(slo_steps_per_min or 0.0),
                       "throughput": float(slo_throughput or 0.0)}
        self.unit = unit
        self.alpha = alpha
        self.min_records = min_records
        self.breaches = 0
        self._in_breach = {k: False for k in self.floors}
        self._ema = {k: None for k in self.floors}
        self._samples = 0
        self._last_step_ts: Optional[float] = None
        self._last_emit_ts: Optional[float] = None
        # RLock, not Lock: run_end's SIGTERM path calls emit_goodput() on
        # the main thread — if the signal lands while that same thread is
        # inside sink() holding this lock, a plain Lock would self-deadlock
        # (the exact hazard Ledger._lock documents; distlint DL101)
        self._lock = threading.RLock()

    def sink(self, rec: dict) -> None:
        ev = rec.get("event")
        if ev in ("goodput", "slo"):
            return  # our own (nested) emits
        with self._lock:
            self.acc.add(rec)
            if ev == "run_start":
                self._last_emit_ts = rec.get("ts")
                return
            if ev in ("eval", "ckpt", "epoch"):
                # steps legitimately stop completing across eval/ckpt
                # boundaries — the next step's dt must not read as a
                # steps/min collapse (spurious breach every epoch)
                self._last_step_ts = None
                return
            if ev != "step":
                return
            ts = rec.get("ts") or time.time()
            step = rec.get("step")
            breached = self._observe(rec, ts) if not rec.get("warm") else []
            periodic = (self.every_s > 0
                        and self._last_emit_ts is not None
                        and ts - self._last_emit_ts >= self.every_s)
            if periodic:
                self._last_emit_ts = ts
        # emit OUTSIDE the monitor lock (the nested Ledger.emit re-enters
        # this sink via the fan-out; Ledger's own RLock handles its side)
        for kind, value, floor in breached:
            self._ledger.emit("slo", step=step, kind=kind,
                              value=round(value, 6), floor=floor,
                              unit=self.unit)
        if periodic:
            self.emit_goodput(final=False)

    def _observe(self, rec: dict, ts: float):
        """Update the EMAs from one hot step record; return the breaches
        that just started (kind, ema, floor). Caller holds the lock."""
        out = []
        samples = {}
        if self._last_step_ts is not None and ts > self._last_step_ts:
            k = max(int(rec.get("steps_in_dispatch") or 1), 1)
            samples["steps_per_min"] = k / (ts - self._last_step_ts) * 60.0
        self._last_step_ts = ts
        if rec.get("throughput") is not None:
            samples["throughput"] = float(rec["throughput"])
        if not samples:
            return out
        self._samples += 1
        for kind, v in samples.items():
            prev = self._ema[kind]
            self._ema[kind] = (v if prev is None
                               else self.alpha * v
                               + (1 - self.alpha) * prev)
        if self._samples < self.min_records:
            return out
        for kind, floor in self.floors.items():
            ema = self._ema[kind]
            if floor <= 0 or ema is None:
                continue
            if ema < floor and not self._in_breach[kind]:
                self._in_breach[kind] = True
                self.breaches += 1
                out.append((kind, ema, floor))
            elif ema >= floor and self._in_breach[kind]:
                self._in_breach[kind] = False  # re-arm
        return out

    def emit_goodput(self, final: bool = True) -> Optional[dict]:
        """Emit one ``goodput`` event from the current partition (the
        final one is ``RunObs.run_end``'s, stamped ``final=True``)."""
        with self._lock:
            part = self.acc.finalize(
                end_ts=time.time() if final else None)
            breaches = self.breaches
        if part is None:
            return None
        return self._ledger.emit(
            "goodput", wall_s=part["wall_s"], goodput_s=part["goodput_s"],
            ratio=part["ratio"], categories=part["categories"],
            overrun_s=part["overrun_s"], opt_steps=part["opt_steps"],
            slo_breaches=breaches, final=final)
