"""Deterministic fault injection: make every supervisor path testable.

A remediation layer (parallel.supervisor) is only trustworthy if every
failure class it claims to handle can be *produced on demand* — a restart
policy validated against hope is not validated. This module injects
failures at named sites, deterministically (a spec names the site and the
step/epoch/attempt it fires at), so a hang, a hard kill, a full disk or a
flaky coordinator is a one-line env var away on a CPU dev box:

    TPU_DIST_FAULTS="hard_exit@step=10,attempt=0" python -m tpu_dist.supervise ...

Spec grammar (``TPU_DIST_FAULTS`` env var or the ``faults`` config knob;
entries separated by ``;``)::

    site@key=val[,key=val...]

where *site* is one of :data:`SITES` and the keys split into match
conditions and site arguments:

* ``step=N``   — fire at the first step whose ordinal is >= N (window
  dispatches may never land on N exactly);
* ``epoch=N``  — same, for epoch-scoped sites;
* ``nth=N``    — fire on the N-th *check* of the site (1-based; e.g. the
  2nd checkpoint write). Sites with no condition fire on the first check;
* ``attempt=N``— additionally require restart-attempt ordinal N (so an
  injected crash does not re-fire after the supervisor restarts the run
  and resumes *before* the fault step);
* ``times=K``  — fire up to K times (default 1; rendezvous faults use
  this to fail the first K connection attempts);
* ``secs=S``   — ``hang``: sleep S seconds (default 3600);
  ``preempt_deadline``: the forwarded snapshot deadline (default the
  ``TPU_DIST_PREEMPT_DEADLINE_S`` env, then 30);
* ``code=C``   — ``hard_exit`` only: ``os._exit`` status (default 13).

Sites (:data:`SITES`):

* ``nan_batch``       — step-scoped; the engine poisons the step's numbers
  with NaN (inputs here are integer tokens / uint8 pixels, so the
  injection lands on the param tree: the step's loss/grads go non-finite
  exactly as a NaN batch would make them, and the health sentry trips);
* ``hard_exit``       — step-scoped ``os._exit`` (SIGKILL-class death: no
  atexit, no run_end — the torn-ledger crash the supervisor must classify);
* ``hang``            — step-scoped sleep on the step thread (the
  watchdog-confirmed-stall path: stall event fires, the loop never
  advances, the supervisor SIGKILLs and restarts);
* ``preempt_sigterm`` — step-scoped ``SIGTERM`` to self (the scheduler's
  preemption signal; the crash guard's handler runs, run_end lands);
* ``ckpt_enospc``     — checkpoint-write ``OSError(ENOSPC)`` raised inside
  the container write (engine.checkpoint), before any byte lands;
* ``rendezvous_fail`` — ``launch.initialize`` raises ``ConnectionError``
  instead of calling ``jax.distributed.initialize`` (exercises the retry/
  backoff/deadline path without a real flaky coordinator);
* ``preempt_deadline``— step-scoped; the engine receives the scheduler's
  advance preemption notice (``secs=S`` deadline, default 30) WITHOUT a
  real SIGTERM: the loop finishes the in-flight step, writes the
  coordinated snapshot and exits ``preemption_snapshotted`` — the
  round-13 elastic path, provable on CPU;
* ``host_return``     — consensus-round site (parallel.consensus): a lost
  planned host re-registers (``host=N`` names one, default all missing),
  driving mesh re-expansion deterministically with no real second host.

Every injection emits one ``fault`` ledger event (EVENT_SCHEMA) — reports
must distinguish *injected* failures from organic ones — and prints a
stderr line (the ledger may be the thing being killed). The module is
stdlib-only at import time (the supervisor and lint.sh's no-jax pass both
import it); jax appears only inside :func:`poison_params`.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

SITES = ("nan_batch", "hard_exit", "hang", "preempt_sigterm",
         "ckpt_enospc", "rendezvous_fail", "preempt_deadline",
         "host_return")

# sites the engines check once per optimizer-step loop iteration
STEP_SITES = ("nan_batch", "hard_exit", "hang", "preempt_sigterm",
              "preempt_deadline")

# match conditions vs site arguments (anything not a condition is an arg)
_CONDITIONS = ("step", "epoch", "nth", "attempt")

ENV_VAR = "TPU_DIST_FAULTS"


@dataclass
class Fault:
    """One parsed spec entry: a site plus when/how it fires."""

    site: str
    when: Dict[str, int]
    args: Dict[str, float]
    spec: str
    fired: int = 0

    @property
    def times(self) -> int:
        return int(self.args.get("times", 1))

    def matches(self, nth: int, ctx: Dict) -> bool:
        if self.fired >= self.times:
            return False
        for key, want in self.when.items():
            if key == "nth":
                if nth < want:
                    return False
            elif key == "attempt":
                have = ctx.get("attempt")
                if have is None or int(have) != want:
                    return False
            else:  # step / epoch: first opportunity >= N
                have = ctx.get(key)
                if have is None or int(have) < want:
                    return False
        return True


def _parse_entry(entry: str) -> Fault:
    entry = entry.strip()
    site, _, rest = entry.partition("@")
    site = site.strip()
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r} in {entry!r} "
                         f"(sites: {', '.join(SITES)})")
    when: Dict[str, int] = {}
    args: Dict[str, float] = {}
    if rest:
        for kv in rest.split(","):
            key, sep, val = kv.partition("=")
            key = key.strip()
            if not sep or not val.strip():
                raise ValueError(f"malformed fault condition {kv!r} in "
                                 f"{entry!r} (want key=value)")
            try:
                num = float(val)
            except ValueError:
                raise ValueError(f"non-numeric fault value {kv!r} in "
                                 f"{entry!r}") from None
            if key in _CONDITIONS:
                when[key] = int(num)
            else:
                args[key] = num
    return Fault(site=site, when=when, args=args, spec=entry)


@dataclass
class FaultPlan:
    """The parsed spec: every entry, plus per-site check counters.

    ``fire`` is the one entry point: it matches, records, emits the
    ``fault`` ledger event, and *executes* the process-level sites
    (exit/hang/signal) itself — data-level sites (nan_batch, ckpt_enospc,
    rendezvous_fail) return the Fault so the caller applies the effect.
    """

    faults: List[Fault] = field(default_factory=list)
    seen: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        entries = [e for e in (spec or "").split(";") if e.strip()]
        return cls(faults=[_parse_entry(e) for e in entries])

    def sites(self) -> set:
        return {f.site for f in self.faults}

    def fire(self, site: str, ledger=None, **ctx) -> Optional[Fault]:
        """Check ``site`` against the plan; fire at most one matching fault.

        Returns the fired Fault (data-level sites) or None. Process-level
        sites (hard_exit / hang / preempt_sigterm) act here and — except
        ``hang``, which eventually returns if ``secs`` elapses — do not."""
        nth = self.seen.get(site, 0) + 1
        self.seen[site] = nth
        for f in self.faults:
            if f.site == site and f.matches(nth, ctx):
                f.fired += 1
                self._record(f, ledger, ctx)
                self._act(f)
                return f
        return None

    def _record(self, f: Fault, ledger, ctx: Dict) -> None:
        print(f"[faults] INJECTING {f.spec!r} "
              f"(ctx {dict(ctx)}, firing {f.fired}/{f.times})",
              file=sys.stderr, flush=True)
        led = ledger if ledger is not None else _default_ledger
        if led is not None:
            try:
                led.emit("fault", site=f.site, step=ctx.get("step"),
                         spec=f.spec, attempt=ctx.get("attempt"))
            except Exception:
                pass  # injection must not depend on a healthy ledger

    def _act(self, f: Fault) -> None:
        if f.site == "hard_exit":
            # the SIGKILL-class death: no atexit hooks, no run_end, a
            # possibly-torn ledger line — exactly what a killed host leaves
            os._exit(int(f.args.get("code", 13)))
        elif f.site == "hang":
            time.sleep(float(f.args.get("secs", 3600.0)))
        elif f.site == "preempt_sigterm":
            import signal

            os.kill(os.getpid(), signal.SIGTERM)


# -- process-global plan (crosses the supervisor->child env boundary) -------

_lock = threading.RLock()
_plan: Optional[FaultPlan] = None
_env_loaded = False
_default_ledger = None
_context: Dict[str, int] = {}


def _seed_env_context() -> None:
    """Seed ``attempt`` from TPU_DIST_ATTEMPT (the supervisor exports it
    per child) so attempt-conditioned faults at sites that fire BEFORE
    RunObs exists — rendezvous in launch.initialize — still match.
    RunObs.set_context overwrites with the authoritative value later."""
    val = os.environ.get("TPU_DIST_ATTEMPT", "")
    if val and "attempt" not in _context:
        try:
            _context["attempt"] = int(val)
        except ValueError:
            pass


def install(spec, ledger=None) -> Optional[FaultPlan]:
    """Install a plan from a spec string (or FaultPlan; None/"" clears)."""
    global _plan, _env_loaded, _default_ledger
    with _lock:
        _plan = (spec if isinstance(spec, FaultPlan)
                 else FaultPlan.parse(spec) if spec else None)
        _env_loaded = True  # an explicit install wins over the env var
        if ledger is not None:
            _default_ledger = ledger
        _seed_env_context()
    return _plan


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, lazily parsed from ``TPU_DIST_FAULTS`` once."""
    global _plan, _env_loaded
    with _lock:
        if not _env_loaded:
            _env_loaded = True
            spec = os.environ.get(ENV_VAR, "")
            if spec:
                _plan = FaultPlan.parse(spec)
                _seed_env_context()
        return _plan


def set_ledger(ledger) -> None:
    """Register the run's ledger as the fault-event destination (RunObs
    calls this at run_start so sites without a ledger in hand — the
    checkpoint writer, launch — still record their injections)."""
    global _default_ledger
    _default_ledger = ledger


def set_context(**ctx) -> None:
    """Merge ambient match context (RunObs stamps ``attempt`` here)."""
    _context.update({k: v for k, v in ctx.items() if v is not None})


def fire(site: str, ledger=None, **ctx) -> Optional[Fault]:
    """Module-level convenience: no-op (and cheap) when no plan is set."""
    plan = active_plan()
    if plan is None:
        return None
    merged = {**_context, **ctx}
    return plan.fire(site, ledger=ledger, **merged)


def fire_step(step: int, ledger=None, **ctx) -> Dict[str, Fault]:
    """Check every step-scoped site for this step ordinal; returns the
    data-level effects the caller must apply as ``{site: Fault}``
    (``nan_batch`` and ``preempt_deadline`` — the Fault carries the
    site args, e.g. the injected deadline's ``secs``; the process-level
    sites act inside fire()). ``site in effects`` keeps working as it
    did when this returned a bare set."""
    plan = active_plan()
    if plan is None:
        return {}
    effects: Dict[str, Fault] = {}
    active = plan.sites()
    for site in STEP_SITES:
        fault = (plan.fire(site, ledger=ledger,
                           **{**_context, "step": step, **ctx})
                 if site in active else None)
        if fault is not None and site in ("nan_batch", "preempt_deadline"):
            effects[site] = fault
    return effects


def poison_params(params):
    """NaN-poison the first float leaf of a param tree (the ``nan_batch``
    effect: this run's inputs are integer tokens / uint8 pixels, so the
    numeric fault is injected where the floats live — the step's grads and
    loss go non-finite exactly as a NaN input batch would make them)."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(params)
    for i, leaf in enumerate(leaves):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            leaves[i] = leaf * jnp.float32(float("nan")).astype(leaf.dtype)
            break
    return jax.tree.unflatten(treedef, leaves)


def _reset_for_tests() -> None:
    """Clear all module state (test isolation only)."""
    global _plan, _env_loaded, _default_ledger
    with _lock:
        _plan = None
        _env_loaded = False
        _default_ledger = None
        _context.clear()
