"""Autoscaler: the observability plane drives capacity, auditable.

Rounds 7-15 built every piece of a closed autoscaling loop — the SLO
monitor emits breach events, the fleet simulator replays a diurnal day in
minutes, the supervisor grows/shrinks the mesh via consensus epochs, and
the plan auto-tuner is deterministic and jax-free — but nothing connected
them: capacity was whatever the launcher said. This module is the
connection, built the same way as :mod:`tpu_dist.obs.goodput` and
:mod:`tpu_dist.obs.reqtrace`: stdlib-only, jax-free, a pure function of
the ledger.

* :class:`AutoscalePolicy` — a declarative JSON policy (min/max hosts,
  per-direction step size + cooldown, up-trip thresholds, down-side
  hysteresis). ``scripts/autoscale_policy.json`` is the checked-in
  exemplar; ``scripts/lint.sh`` loads it on a bare host as a CI gate.
* :class:`CapacityMonitor` — a ledger sink/tail-follower maintaining the
  rolling capacity signals (SLO-breach window, queue-wait and queue-depth
  EMAs, free-page watermark, fleet goodput ratio, step-time changepoint)
  and, under the policy, producing ``scale_decision`` records with FULL
  attribution: which signal tripped, its value vs threshold, the window,
  and the newest flight-recorder bundle reference — "why did we scale"
  is answerable from the ledger alone.
* :func:`replay_decisions` — the pure replay: ``(records, policy) ->
  decisions``, byte-deterministic (no wall clock, no randomness; the
  replay clock is the ``tick`` extra on ``fleet`` heartbeats). The lint
  gate builds a canned fixture twice and asserts byte identity.
* :class:`LedgerTailer` — the incremental multi-file reader the fleet
  runner uses to feed live host ledgers into the monitor (complete lines
  only; torn trailing lines are held back, the `_LedgerTail` contract).

The CONSUMPTION side lives where capacity already lives: the fleet
runner (:mod:`tpu_dist.sim.runner`) executes decisions as consensus
``register``/``leave`` membership changes, the supervisor
(:mod:`tpu_dist.parallel.supervisor`) turns the epoch bump into the
shrink/expand rescale it already owns — stamping the pending decision id
onto its ``scale`` event — and re-runs :func:`tpu_dist.plan.tune.tune`
at the new world size, recording the fresh ``plan_hash`` in the
decision's ``applied`` follow-up event. Every scale ACTION therefore
pairs 1:1 with a decision that explains it.
"""

from __future__ import annotations

import json
import os
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Canonical signal evaluation order: attribution must be deterministic,
# so the FIRST tripped signal in this order names the decision. Each
# entry is (name, trip-sense): "high" trips at value >= threshold
# (pressure signals), "low" at value <= threshold (depletion signals).
SIGNALS = (
    ("slo_breaches_window", "high"),   # slo events within window_ticks
    ("queue_wait_ema_s", "high"),      # EMA of request.queue_wait_s
    ("queue_depth_ema", "high"),       # EMA of admit.queue_depth
    ("free_page_frac", "low"),         # kv_cache free/(free+used) watermark
    ("goodput_ratio", "low"),          # last goodput/fleet ratio
    ("step_time_ratio", "high"),       # short-EMA/long-EMA step-wall change
)
SIGNAL_SENSE = dict(SIGNALS)
SIGNAL_NAMES = tuple(name for name, _ in SIGNALS)

# the attribution name of a hysteresis-triggered scale-down: the "signal"
# is sustained calm itself (value = calm ticks, threshold = stable_ticks)
CALM_SIGNAL = "calm_ticks"


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"autoscale policy: {msg}")


@dataclass(frozen=True)
class DirectionPolicy:
    """One direction's knobs: how far to step, how long to hold off
    after ANY decision (cooldown), and — scale-down only — how long
    every down signal must stay calm first (hysteresis)."""

    step: int = 1
    cooldown_ticks: int = 0
    stable_ticks: int = 0                      # down-side hysteresis
    signals: Dict[str, float] = field(default_factory=dict)


@dataclass(frozen=True)
class AutoscalePolicy:
    """The declarative policy (pure data; ``scripts/lint.sh`` loads it
    on a bare host). ``up.signals`` are TRIP thresholds (any one trips a
    scale-up); ``down.signals`` are CALM thresholds (all must hold
    strictly inside their calm side for ``down.stable_ticks`` straight
    evaluations, with zero SLO breaches in the window, before a
    scale-down fires)."""

    min_hosts: int
    max_hosts: int
    up: DirectionPolicy
    down: DirectionPolicy
    window_ticks: int = 16        # the slo-breach counting window
    ema_alpha: float = 0.25       # queue wait/depth EMA smoothing

    @classmethod
    def from_doc(cls, doc: Dict) -> "AutoscalePolicy":
        _require(isinstance(doc, dict), "document must be a JSON mapping")
        for key in ("min_hosts", "max_hosts", "up"):
            _require(key in doc, f"missing required key {key!r}")
        dirs = {}
        for direction in ("up", "down"):
            d = doc.get(direction) or {}
            _require(isinstance(d, dict),
                     f"{direction!r} must be a mapping")
            sigs = d.get("signals") or {}
            for name, thr in sigs.items():
                _require(name in SIGNAL_NAMES,
                         f"unknown signal {name!r} (signals: "
                         f"{list(SIGNAL_NAMES)})")
                _require(isinstance(thr, (int, float)),
                         f"signal {name!r}: threshold must be a number")
            dirs[direction] = DirectionPolicy(
                step=int(d.get("step", 1)),
                cooldown_ticks=int(d.get("cooldown_ticks", 0)),
                stable_ticks=int(d.get("stable_ticks", 0)),
                signals={str(k): float(v) for k, v in sigs.items()})
            _require(dirs[direction].step >= 1,
                     f"{direction}.step must be >= 1")
            _require(dirs[direction].cooldown_ticks >= 0,
                     f"{direction}.cooldown_ticks must be >= 0")
        pol = cls(min_hosts=int(doc["min_hosts"]),
                  max_hosts=int(doc["max_hosts"]),
                  up=dirs["up"], down=dirs["down"],
                  window_ticks=int(doc.get("window_ticks", 16)),
                  ema_alpha=float(doc.get("ema_alpha", 0.25)))
        _require(pol.min_hosts >= 1, "min_hosts must be >= 1")
        _require(pol.max_hosts >= pol.min_hosts,
                 "max_hosts must be >= min_hosts")
        _require(pol.window_ticks >= 1, "window_ticks must be >= 1")
        _require(0.0 < pol.ema_alpha <= 1.0,
                 "ema_alpha must be in (0, 1]")
        _require(bool(pol.up.signals),
                 "up.signals must name at least one trip threshold")
        _require(not pol.down.signals or pol.down.stable_ticks >= 1,
                 "down.signals without down.stable_ticks >= 1 would "
                 "flap — hysteresis is required for scale-down")
        return pol

    @classmethod
    def load(cls, path: str) -> "AutoscalePolicy":
        with open(path) as f:
            return cls.from_doc(json.load(f))

    def to_doc(self) -> Dict:
        return {
            "min_hosts": self.min_hosts, "max_hosts": self.max_hosts,
            "window_ticks": self.window_ticks, "ema_alpha": self.ema_alpha,
            "up": {"step": self.up.step,
                   "cooldown_ticks": self.up.cooldown_ticks,
                   "signals": dict(self.up.signals)},
            "down": {"step": self.down.step,
                     "cooldown_ticks": self.down.cooldown_ticks,
                     "stable_ticks": self.down.stable_ticks,
                     "signals": dict(self.down.signals)}}


class CapacityMonitor:
    """Fold ledger records into rolling capacity signals; evaluate the
    policy into ``scale_decision`` dicts.

    Deterministic BY CONSTRUCTION: no wall clock, no randomness — time is
    the tick the caller passes to :meth:`evaluate` (the fleet runner's
    fleet clock) or, in replay, the ``tick`` extra on ``fleet`` heartbeat
    records. Decision ids are a plain sequence (``d0``, ``d1``, ...), so
    the same records under the same policy always produce byte-identical
    decisions — the property the lint gate pins.
    """

    def __init__(self, policy: AutoscalePolicy, hosts_live: int):
        self.policy = policy
        self.hosts = int(hosts_live)    # current target capacity
        self.tick = 0
        self.decisions: List[dict] = []
        self._seq = 0
        self._queue_wait_ema: Optional[float] = None
        self._queue_depth_ema: Optional[float] = None
        self._free_frac: Optional[float] = None
        self._goodput_ratio: Optional[float] = None
        self._step_short: Optional[float] = None
        self._step_long: Optional[float] = None
        self._slo_ticks: deque = deque()
        self._last_bundle: Optional[str] = None
        self._last_decision_tick: Optional[int] = None
        self._calm_since: Optional[int] = None

    # -- signal folding ---------------------------------------------------
    def _ema(self, prev: Optional[float], x: float) -> float:
        a = self.policy.ema_alpha
        return x if prev is None else prev + a * (x - prev)

    def observe(self, rec: dict) -> None:
        """Fold one ledger record (any host's stream; order within a tick
        is immaterial — signals are EMAs/windows, not sequences)."""
        ev = rec.get("event")
        if ev == "fleet":
            t = rec.get("tick")
            if t is not None:
                self.tick = max(self.tick, int(t))
            if rec.get("goodput_ratio") is not None:
                self._goodput_ratio = float(rec["goodput_ratio"])
        elif ev == "request":
            if rec.get("queue_wait_s") is not None:
                self._queue_wait_ema = self._ema(
                    self._queue_wait_ema, float(rec["queue_wait_s"]))
        elif ev == "admit":
            if rec.get("queue_depth") is not None:
                self._queue_depth_ema = self._ema(
                    self._queue_depth_ema, float(rec["queue_depth"]))
        elif ev == "kv_cache":
            free = rec.get("pages_free")
            used = rec.get("pages_used")
            if free is not None and used is not None and free + used > 0:
                self._free_frac = free / float(free + used)
        elif ev == "slo":
            self._slo_ticks.append(self.tick)
        elif ev == "goodput":
            if rec.get("ratio") is not None:
                self._goodput_ratio = float(rec["ratio"])
        elif ev == "step":
            wall = sum(rec.get(k) or 0.0
                       for k in ("data_s", "dispatch_s", "device_s"))
            n = rec.get("steps_in_dispatch") or 1
            if wall > 0 and n:
                per = wall / n
                # changepoint pair: a fast EMA over a slow one — a
                # sustained step-time regression pushes the ratio > 1
                self._step_short = (per if self._step_short is None else
                                    self._step_short + 0.5 *
                                    (per - self._step_short))
                self._step_long = (per if self._step_long is None else
                                   self._step_long + 0.05 *
                                   (per - self._step_long))
        elif ev == "diagnosis":
            if rec.get("bundle"):
                self._last_bundle = str(rec["bundle"])

    def signal_value(self, name: str) -> Optional[float]:
        """The current value of one named signal (None until its feeding
        events have been observed — an unobserved signal never trips)."""
        if name == "queue_wait_ema_s":
            return self._queue_wait_ema
        if name == "queue_depth_ema":
            return self._queue_depth_ema
        if name == "free_page_frac":
            return self._free_frac
        if name == "goodput_ratio":
            return self._goodput_ratio
        if name == "slo_breaches_window":
            lo = self.tick - self.policy.window_ticks
            while self._slo_ticks and self._slo_ticks[0] < lo:
                self._slo_ticks.popleft()
            return float(len(self._slo_ticks))
        if name == "step_time_ratio":
            if self._step_short is None or not self._step_long:
                return None
            return self._step_short / self._step_long
        raise ValueError(f"unknown autoscale signal {name!r}")

    # -- policy evaluation ------------------------------------------------
    def _cooldown_ok(self, direction: DirectionPolicy) -> bool:
        return (self._last_decision_tick is None
                or self.tick - self._last_decision_tick
                >= direction.cooldown_ticks)

    def _decide(self, direction: str, target: int, signal: str,
                value: float, threshold: float) -> dict:
        dec = {"decision": f"d{self._seq}", "direction": direction,
               "hosts_from": self.hosts, "target_hosts": target,
               "signal": signal, "value": round(float(value), 6),
               "threshold": threshold,
               "window_ticks": self.policy.window_ticks,
               "tick": self.tick, "bundle": self._last_bundle}
        self._seq += 1
        self.hosts = target
        self._last_decision_tick = self.tick
        self._calm_since = None
        self.decisions.append(dec)
        return dec

    def evaluate(self, tick: Optional[int] = None,
                 hosts_live: Optional[int] = None) -> Optional[dict]:
        """One policy evaluation at ``tick`` (defaults to the replay
        clock) against ``hosts_live`` (defaults to the monitor's own
        simulated capacity). Returns the decision dict, or None."""
        pol = self.policy
        if tick is not None:
            self.tick = max(self.tick, int(tick))
        if hosts_live is not None:
            self.hosts = int(hosts_live)
        # scale-UP: first configured tripped signal in canonical order
        for name in SIGNAL_NAMES:
            thr = pol.up.signals.get(name)
            if thr is None:
                continue
            v = self.signal_value(name)
            if v is None:
                continue
            tripped = (v >= thr if SIGNAL_SENSE[name] == "high"
                       else v <= thr)
            if tripped:
                if self.hosts < pol.max_hosts and self._cooldown_ok(pol.up):
                    target = min(self.hosts + pol.up.step, pol.max_hosts)
                    return self._decide("up", target, name, v, thr)
                # pressure exists: a calm streak must not accrue under it
                self._calm_since = None
                return None
        # scale-DOWN: every calm threshold held + zero breaches in window,
        # sustained for stable_ticks straight evaluations (hysteresis)
        if not pol.down.signals:
            return None
        calm = self.signal_value("slo_breaches_window") == 0.0
        for name, thr in pol.down.signals.items():
            v = self.signal_value(name)
            if v is None or not (v < thr if SIGNAL_SENSE[name] == "high"
                                 else v > thr):
                calm = False
                break
        if not calm:
            self._calm_since = None
            return None
        if self._calm_since is None:
            self._calm_since = self.tick
        held = self.tick - self._calm_since
        if (held >= pol.down.stable_ticks and self.hosts > pol.min_hosts
                and self._cooldown_ok(pol.down)):
            target = max(self.hosts - pol.down.step, pol.min_hosts)
            return self._decide("down", target, CALM_SIGNAL,
                                float(held), float(pol.down.stable_ticks))
        return None


def emit_decision(ledger, dec: dict) -> dict:
    """Write one decision as its ``scale_decision`` ledger event (the
    explicit-keyword emit site DL006 verifies against the schema)."""
    return ledger.emit(
        "scale_decision", decision=dec["decision"],
        direction=dec["direction"], hosts_from=dec["hosts_from"],
        target_hosts=dec["target_hosts"], signal=dec["signal"],
        value=dec["value"], threshold=dec["threshold"],
        window_ticks=dec["window_ticks"], bundle=dec["bundle"],
        tick=dec.get("tick"))


def replay_decisions(records: List[dict], policy: AutoscalePolicy,
                     hosts0: int) -> List[dict]:
    """The pure replay: fold ``records`` in order, evaluating the policy
    at every ``fleet`` heartbeat that carries a ``tick`` extra (the
    canned-fixture clock). Capacity evolves from ``hosts0`` by the
    decisions themselves — same records + same policy -> byte-identical
    decision list, which is what makes the CI gate meaningful."""
    mon = CapacityMonitor(policy, hosts_live=hosts0)
    for rec in records:
        mon.observe(rec)
        if rec.get("event") == "fleet" and rec.get("tick") is not None:
            mon.evaluate()
    return list(mon.decisions)


class LedgerTailer:
    """Incremental reader over a GROWING set of JSONL ledger files: each
    :meth:`poll` returns the new complete records across every path, in
    path order (live feeding is not byte-ordered across hosts and does
    not need to be — the monitor's signals are EMAs and windows). Torn
    trailing lines are held back until their newline lands, the
    ``supervisor._LedgerTail`` contract; corrupt lines are skipped."""

    def __init__(self) -> None:
        self._offsets: Dict[str, int] = {}
        self._partials: Dict[str, bytes] = {}

    def poll(self, paths: List[str]) -> List[dict]:
        out: List[dict] = []
        for path in paths:
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(path, 0)
            if size <= offset:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read(size - offset)
            except OSError:
                continue
            self._offsets[path] = size
            data = self._partials.get(path, b"") + chunk
            lines = data.split(b"\n")
            self._partials[path] = lines.pop()
            for line in lines:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue    # torn mid-crash line: skip, not truth
                if isinstance(rec, dict):
                    out.append(rec)
        return out
