"""Static cost attribution: bucket a compiled step's HLO by op category.

The ledger answers "how long did the step take" (PR 2) and "how much of it
was communication" (PR 4) — but not "WHICH op category is eating it". The
XLA cost model's totals (``utils.telemetry.program_stats``) collapse the
whole program into one flops number; an MFU push needs the split: how much
of the model's arithmetic is matmul vs attention, how many bytes move
through collectives of each kind, and how much elementwise/fusion residue
rides along. This module walks the OPTIMIZED (post-fusion) HLO text of the
same executable the telemetry probe already lowered (``program_stats(...,
with_hlo=True)`` — one AOT lower for hbm/flops/attribution together) and
accumulates per-category flop and byte estimates:

* ``matmul``       — ``dot`` / ``convolution`` (and backend matmul
  custom-calls): flops from the contraction dims, exactly;
* ``attention``    — any op whose jax ``op_name`` metadata places it in an
  attention scope (the dots and softmax fusions of the attention block
  report here, not under matmul/fusion — flash-attention custom-calls
  included, though their inner flops are invisible to HLO);
* ``collective:*`` — all-reduce / all-gather / reduce-scatter /
  collective-permute / all-to-all, bytes = operand+result sizes (flops 0);
* ``elementwise``  — un-fused top-level ops (~1 flop per output element);
* ``fusion``       — fusion instructions: HBM bytes from their operand and
  result shapes (inner temporaries live in registers, so inner byte counts
  would be fiction), flops recursed from the fused computation so an
  embedded dot still lands in matmul/attention.

Estimates, not measurements: ``while`` bodies (lax.scan windows) are
counted ONCE like XLA's own cost model, custom-call kernels (Pallas) are
opaque, and elementwise flops are 1/element. The point is the SHARE
structure — which the ledger_report roofline section then compares against
measured ``device_s``/``comm_s``/MFU per step window. Pure stdlib: parsing
imports no jax, so canned HLO text attributes on a login host too.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

# dtype -> bytes per element (HLO shape literals: f32[8,32]{1,0})
_DTYPES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "f8e4m3fn": 1, "f8e4m3fnuz": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2": 1, "f8e5m2fnuz": 1, "f8e3m4": 1, "f8e4m3": 1,
    "token": 0, "opaque": 0,
}
# longest-first alternation so f8e4m3fn wins over f8e4m3; \b guards keep
# attribute text like devices=[1,2] from reading as a shape
_SHAPE_RE = re.compile(
    r"\b(" + "|".join(sorted(_DTYPES, key=len, reverse=True))
    + r")\[([0-9,]*)\]")
_COMP_RE = re.compile(r"^\s*(ENTRY\s+)?%?([\w.\-]+)\s*\(.*->.*\{\s*$")
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.*)$")
_OPCODE_RE = re.compile(r"\s*([A-Za-z][\w\-]*)")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")
_SUBCOMP_RE = re.compile(r"(?:body|condition|true_computation|"
                         r"false_computation)=%?([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_LHS_CDIMS_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_DIM_LABELS_RE = re.compile(r"dim_labels=([0-9a-z?]+)_([0-9a-z?]+)->")
_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')
# attention scopes: named attention modules/kernels, plus the bare einsum
# scopes of the score/value contractions (bqhd,bkhd->bhqk and its
# transpose carry 'bhqk' in the op_name path on every model here)
_ATTN_RE = re.compile(r"attn|attention|flash|bhqk", re.I)
_MATMUL_TARGET_RE = re.compile(r"matmul|dot|conv|gemm", re.I)

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all", "collective-broadcast")
# zero-cost bookkeeping ops (and the -done halves of async pairs: the
# -start instruction carries the shapes once)
_FREE_OPS = {"parameter", "constant", "tuple", "get-tuple-element",
             "bitcast", "after-all", "partition-id", "replica-id",
             "opt-barrier", "domain"}


def _dims(spec: str) -> int:
    n = 1
    for d in spec.split(","):
        if d:
            n *= int(d)
    return n


def _shapes_bytes(segment: str) -> float:
    return sum(_DTYPES[m.group(1)] * _dims(m.group(2))
               for m in _SHAPE_RE.finditer(segment))


def _split_output_shape(rest: str):
    """Split 'SHAPE opcode(...)...' into (shape segment, tail). Tuple
    shapes — '(f32[8]{0}, s32[]{})' — span to the matching paren."""
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            depth += ch == "("
            depth -= ch == ")"
            if depth == 0:
                return rest[:i + 1], rest[i + 1:]
        return rest, ""
    i = rest.find(" ")
    return (rest, "") if i < 0 else (rest[:i], rest[i:])


class _Instr:
    __slots__ = ("opcode", "out_shape", "tail", "op_name", "line")

    def __init__(self, opcode, out_shape, tail, op_name, line):
        self.opcode = opcode
        self.out_shape = out_shape
        self.tail = tail          # everything after the opcode (operands+attrs)
        self.op_name = op_name
        self.line = line


def _parse_computations(hlo_text: str):
    """{computation name: [instructions]}, plus the ENTRY name."""
    comps: Dict[str, List[_Instr]] = {}
    entry = None
    cur: Optional[List[_Instr]] = None
    for raw in hlo_text.splitlines():
        m = _COMP_RE.match(raw)
        if m and "=" not in raw.split("(")[0]:
            name = m.group(2)
            cur = comps.setdefault(name, [])
            if m.group(1):
                entry = name
            continue
        if cur is None:
            continue
        if raw.strip() == "}":
            cur = None
            continue
        mi = _INSTR_RE.match(raw)
        if not mi:
            continue
        rest = mi.group(1)
        # metadata can quote arbitrary jax scope strings — take op_name
        # out first, then drop the block so it can't read as shapes
        mo = _OPNAME_RE.search(rest)
        op_name = mo.group(1) if mo else ""
        rest = re.sub(r"metadata=\{[^}]*\}", "", rest)
        shape_seg, tail = _split_output_shape(rest)
        mop = _OPCODE_RE.match(tail)
        if not mop:
            continue
        cur.append(_Instr(mop.group(1), shape_seg, tail[mop.end():],
                          op_name, rest))
    return comps, entry


def _dot_flops(instr: _Instr) -> float:
    """2 * |output| * K, K = product of the lhs contracting dim sizes
    (operand shapes are inline in optimized HLO call sites)."""
    out = sum(_dims(m.group(2)) for m in _SHAPE_RE.finditer(instr.out_shape))
    operands = [m for m in _SHAPE_RE.finditer(instr.tail)]
    mc = _LHS_CDIMS_RE.search(instr.tail)
    if not operands or mc is None:
        return 2.0 * out
    lhs_dims = [int(d) for d in operands[0].group(2).split(",") if d]
    k = 1
    for i in (int(x) for x in mc.group(1).split(",") if x):
        if i < len(lhs_dims):
            k *= lhs_dims[i]
    return 2.0 * out * k


def _conv_flops(instr: _Instr) -> float:
    """2 * |output| * (kernel spatial x in-channels) — prod(kernel)/C_out,
    with C_out read off the dim_labels 'o' position."""
    out = sum(_dims(m.group(2)) for m in _SHAPE_RE.finditer(instr.out_shape))
    operands = [m for m in _SHAPE_RE.finditer(instr.tail)]
    ml = _DIM_LABELS_RE.search(instr.tail)
    if len(operands) < 2 or ml is None:
        return 2.0 * out
    kernel = [int(d) for d in operands[1].group(2).split(",") if d]
    o_pos = ml.group(2).find("o")
    c_out = kernel[o_pos] if 0 <= o_pos < len(kernel) else 1
    import math
    return 2.0 * out * math.prod(kernel) / max(c_out, 1)


def _categorize(instr: _Instr) -> str:
    op = instr.opcode
    base = op[:-6] if op.endswith("-start") else op
    if base in _COLLECTIVES:
        return "collective:" + base
    if _ATTN_RE.search(instr.op_name):
        return "attention"
    if op in ("dot", "convolution"):
        return "matmul"
    if op == "custom-call":
        mt = _TARGET_RE.search(instr.tail)
        if mt and _MATMUL_TARGET_RE.search(mt.group(1)):
            return "matmul"
        return "custom-call"
    if op == "fusion":
        return "fusion"
    return "elementwise"


def _add(acc: dict, cat: str, flops: float, nbytes: float) -> None:
    b = acc.setdefault(cat, {"flops": 0.0, "bytes": 0.0, "count": 0})
    b["flops"] += flops
    b["bytes"] += nbytes
    b["count"] += 1


def _instr_flops(instr: _Instr) -> float:
    if instr.opcode == "dot":
        return _dot_flops(instr)
    if instr.opcode == "convolution":
        return _conv_flops(instr)
    if instr.opcode.startswith(tuple(_COLLECTIVES)) \
            or instr.opcode == "custom-call":
        return 0.0
    # ~1 flop per output element for everything else
    return float(sum(_dims(m.group(2))
                     for m in _SHAPE_RE.finditer(instr.out_shape)))


def _walk(name: str, comps: dict, acc: dict, fusion_cat: Optional[str],
          visiting: set) -> None:
    """Accumulate one computation's instructions into ``acc``. Inside a
    fusion (``fusion_cat`` set), only FLOPS accumulate — the fusion call
    site already charged the real HBM bytes — and residue inherits the
    fusion's category so an attention-scoped softmax fusion stays under
    attention."""
    if name in visiting or name not in comps:
        return  # unresolvable or (malformed) recursive reference
    visiting = visiting | {name}
    for instr in comps[name]:
        op = instr.opcode
        if op in _FREE_OPS or op.endswith("-done") or op.endswith("-update"):
            continue
        if op == "fusion":
            cat = _categorize(instr) if fusion_cat is None else fusion_cat
            if fusion_cat is None:
                # the fusion boundary is where HBM traffic happens
                _add(acc, cat,
                     0.0, _shapes_bytes(instr.out_shape + instr.tail))
            mc = _CALLS_RE.search(instr.tail)
            if mc:
                _walk(mc.group(1), comps, acc, cat, visiting)
            continue
        if op in ("while", "conditional", "call"):
            # recurse into bodies/branches (counted ONCE, the cost-model
            # convention for scan windows); the call instruction's own
            # tuple shapes would double-count the carried state
            subs = _SUBCOMP_RE.findall(instr.tail) \
                + _CALLS_RE.findall(instr.tail)
            mb = _BRANCHES_RE.search(instr.tail)
            if mb:
                subs += re.findall(r"%?([\w.\-]+)", mb.group(1))
            for sub in subs:
                _walk(sub, comps, acc, fusion_cat, visiting)
            continue
        cat = _categorize(instr)
        if fusion_cat is not None and cat in ("elementwise", "custom-call"):
            cat = fusion_cat  # fusion residue
        nbytes = (0.0 if fusion_cat is not None
                  else _shapes_bytes(instr.out_shape + instr.tail))
        _add(acc, cat, _instr_flops(instr), nbytes)


def cost_buckets(hlo_text: str) -> Dict[str, dict]:
    """{category: {'flops', 'bytes', 'count'}} for one optimized-HLO
    module (``compiled.as_text()`` / ``program_stats(..., with_hlo=True)
    ['hlo']``). Empty dict when the text has no parseable entry."""
    comps, entry = _parse_computations(hlo_text)
    if entry is None:
        # fall back to the largest computation (older printers may not
        # mark ENTRY on partial dumps)
        entry = max(comps, key=lambda k: len(comps[k]), default=None)
    acc: Dict[str, dict] = {}
    if entry is not None:
        _walk(entry, comps, acc, None, set())
    for b in acc.values():
        b["flops"] = round(b["flops"], 3)
        b["bytes"] = round(b["bytes"], 3)
    return acc


def bucket_totals(buckets: Dict[str, dict]) -> dict:
    """{'flops', 'bytes', 'collective_bytes'} rollup of cost_buckets()."""
    return {
        "flops": sum(b["flops"] for b in buckets.values()),
        "bytes": sum(b["bytes"] for b in buckets.values()),
        "collective_bytes": sum(b["bytes"] for c, b in buckets.items()
                                if c.startswith("collective:")),
    }


# -- device peaks (the roofline's denominators) ----------------------------

# HBM bandwidth GB/s per chip by device kind (public spec sheets; the
# compute-peak twin lives in utils.mfu.PEAK_TFLOPS)
PEAK_GBPS = (
    ("v6", 1640.0), ("trillium", 1640.0),
    ("v5p", 2765.0),
    ("v5 lite", 819.0), ("v5e", 819.0), ("v5litepod", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)


def effective_peak_gbps() -> tuple:
    """(peak_gbps, is_nominal): published HBM bandwidth of device 0, or the
    ``TPU_DIST_NOMINAL_PEAK_GBPS`` fallback (default 1.0) that keeps the
    roofline's memory bound non-null on CPU/virtual backends."""
    import os

    import jax

    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, peak in PEAK_GBPS:
        if key in kind:
            return peak, False
    return float(os.environ.get("TPU_DIST_NOMINAL_PEAK_GBPS", "1.0")), True


def emit_cost_model(ledger, program: str, hlo_text: str,
                    xla_flops=None) -> Optional[dict]:
    """Bucket ``hlo_text`` and emit the ``cost_model`` ledger event beside
    the engines' ``compile`` event (same one-lower probe). Returns the
    record, or None when the text yields no buckets (nothing to report).
    ``xla_flops`` carries the cost model's own total for cross-checking
    the attribution (the buckets' matmul flops should dominate it)."""
    buckets = cost_buckets(hlo_text)
    if not buckets:
        return None
    tot = bucket_totals(buckets)
    from tpu_dist.obs import effective_peak_tflops

    peak_tf, tf_nominal = effective_peak_tflops()
    peak_gb, gb_nominal = effective_peak_gbps()
    return ledger.emit(
        "cost_model", program=program, buckets=buckets,
        total_flops=tot["flops"], total_bytes=tot["bytes"],
        collective_bytes=tot["collective_bytes"], xla_flops=xla_flops,
        peak_tflops=peak_tf, peak_gbps=peak_gb,
        peak_is_nominal=tf_nominal or gb_nominal)
