"""tpu_dist.obs — one observability subsystem for every run.

Four pieces, one handle:

* :mod:`~tpu_dist.obs.ledger` — append-only JSONL of typed events (the
  source of truth; the epoch CSV and progress line render FROM it);
* :mod:`~tpu_dist.obs.trace` — host-side step-phase spans that also emit
  ``jax.profiler`` annotations when a trace is active;
* :mod:`~tpu_dist.obs.skew` — cross-host step-time allgather every K steps
  (straggler index, p50/p99/spread);
* :mod:`~tpu_dist.obs.watchdog` — trailing-median hang detector that dumps
  thread stacks + HBM to stderr and the ledger, once per stall.

:class:`RunObs` wires them from a config (``ledger_path`` /
``watchdog_factor`` / ``skew_every`` / ``log_csv`` / ``profile_dir``) so the
image Trainer, the LMTrainer, ``engine.generate`` and ``bench.py`` all feed
the SAME records instead of five bespoke logging stacks. MFU per step is
computed here against the device's bf16 peak; on backends with no published
peak (CPU, virtual) the field stays non-null by normalizing against a
nominal ``TPU_DIST_NOMINAL_PEAK_TFLOPS`` (default 1.0 — i.e. the value
reads as model TFLOP/s) and ``run_start`` carries ``peak_is_nominal`` so
readers can tell the two apart.
"""

from __future__ import annotations

import atexit
import dataclasses
import os
import signal
import sys
import threading
import time
import traceback
from typing import Optional

from tpu_dist.obs import faults
from tpu_dist.obs.attr import bucket_totals, cost_buckets, emit_cost_model
from tpu_dist.obs.flightrec import FlightRecorder
from tpu_dist.obs.goodput import (GoodputAccumulator, GoodputMonitor,
                                  attempt_path, discover_attempt_paths,
                                  job_accounting, next_attempt_index,
                                  split_attempts)
from tpu_dist.obs.health import HealthError, HealthSentry, validate_health
from tpu_dist.obs.ledger import (EVENT_SCHEMA, EpochCsvSink, Ledger,
                                 ProgressSink, per_process_path, phase_totals,
                                 read_ledger)
from tpu_dist.obs.metrics import (MetricsRegistry, metrics_ledger_sink,
                                  serve_metrics)
from tpu_dist.obs.skew import SkewMonitor
from tpu_dist.obs.trace import StepTracer, profile_session, step_annotation
from tpu_dist.obs.watchdog import Watchdog

__all__ = ["EVENT_SCHEMA", "EpochCsvSink", "FlightRecorder",
           "GoodputAccumulator", "GoodputMonitor", "HealthError",
           "HealthSentry", "Ledger", "MetricsRegistry", "ProgressSink",
           "RunObs", "SkewMonitor", "StepTracer", "Watchdog",
           "attempt_path", "bucket_totals", "cost_buckets",
           "discover_attempt_paths", "emit_cost_model", "faults",
           "job_accounting",
           "metrics_ledger_sink", "next_attempt_index", "per_process_path",
           "phase_totals", "profile_session", "read_ledger",
           "serve_metrics", "split_attempts", "step_annotation"]


def effective_peak_tflops() -> tuple:
    """(peak_tflops, is_nominal): the device's published bf16 peak, or the
    nominal fallback that keeps per-step MFU non-null on CPU/virtual
    backends (MFU then reads as model TFLOP/s per chip)."""
    import jax
    from tpu_dist.utils.mfu import peak_tflops_for

    peak = peak_tflops_for(jax.devices()[0])
    if peak:
        return float(peak), False
    return float(os.environ.get("TPU_DIST_NOMINAL_PEAK_TFLOPS", "1.0")), True


class RunObs:
    """Per-run observability handle: ledger + tracer + skew + watchdog.

    Built unconditionally by both engines (a pathless ledger costs nothing),
    so call sites never guard on "is observability on". ``unit`` names the
    throughput unit of this run's step records ("img/s" | "tok/s").
    """

    def __init__(self, kind: str, cfg, mesh=None, unit: str = "items/s",
                 plan_info=None):
        import jax

        self.kind = kind
        self.cfg = cfg
        self.unit = unit
        # resolved step plan (tpu_dist.plan): {'source', 'hash', 'knobs',
        # 'device_kind'} from plan.compile.resolve_config_plan — stamped
        # into run_start and emitted as its own 'plan' event so reports
        # and the tuner's measured-refinement loop can key runs by plan
        self.plan_info = plan_info
        pidx = jax.process_index()
        self.is_main = pidx == 0
        # run lineage (obs.goodput): one logical job = N restart attempts,
        # each writing its OWN ledger (run.jsonl, run.a1.jsonl, ... — the
        # restart analog of the .pN multi-process story) so the attempt
        # tools can stitch the timeline back with restart gaps visible.
        # attempt=-1 auto-picks the next free index from files on disk.
        base_path = getattr(cfg, "ledger_path", "") or ""
        attempt = int(getattr(cfg, "attempt", 0) or 0)
        if attempt < 0:
            # probes THIS process's own prior files, so process 0 creating
            # the bare ledger first never makes a later-starting peer of
            # the same attempt self-assign the next index
            attempt = (next_attempt_index(base_path, pidx)
                       if base_path else 0)
        self.attempt = attempt
        self.job_id = (getattr(cfg, "job_id", "") or
                       (os.path.splitext(os.path.basename(base_path))[0]
                        if base_path else None))
        ledger_path = per_process_path(
            attempt_path(base_path, attempt), pidx)
        self.ledger = Ledger(ledger_path or None, process_index=pidx)
        if getattr(cfg, "log_csv", "") and self.is_main:
            # the legacy per-epoch CSV becomes a VIEW of the epoch event
            self.ledger.add_sink(EpochCsvSink(cfg.log_csv))
        profile_dir = getattr(cfg, "profile_dir", "") or ""
        self.profiling = bool(profile_dir) and self.is_main
        self.profile_dir = profile_dir
        self.tracer = StepTracer(annotate=self.profiling)
        skew_every = getattr(cfg, "skew_every", 0) or 0
        self.skew = (SkewMonitor(skew_every, ledger=self.ledger)
                     if skew_every > 0 else None)
        wd_factor = getattr(cfg, "watchdog_factor", 0.0) or 0.0
        self.watchdog = (Watchdog(wd_factor, ledger=self.ledger)
                         if wd_factor > 0 else None)
        # numerical-health sentry (obs.health): consumes the fused step
        # probes + loss at each drain; skip/halt policy from the config
        self.health = HealthSentry(
            policy=validate_health(getattr(cfg, "health", "record")),
            spike_z=getattr(cfg, "health_spike_z", 8.0) or 0.0,
            ledger=self.ledger)
        # live metrics export (obs.metrics): the registry is fed by a
        # ledger sink — everything emitted (steps, stalls, skew, health,
        # hbm, decode) reaches the scrape through the one event stream
        self.metrics = MetricsRegistry()
        self.ledger.add_sink(metrics_ledger_sink(self.metrics))
        self.metrics_server = None
        metrics_port = getattr(cfg, "metrics_port", 0) or 0
        if metrics_port > 0:
            # .pN story for ports: process i serves metrics_port + i
            self.metrics_server = serve_metrics(self.metrics,
                                                metrics_port + pidx)
        # flight recorder (obs.flightrec): always-on ring of recent events
        # + triggered bundle capture, fed — like the metrics registry — by
        # the one ledger event stream, so watchdog stalls, health trips and
        # skew-straggler spikes all produce a bundle without new plumbing.
        # The profiler-window veto keeps it off the global profiler when a
        # profile_dir session owns it.
        self.flightrec = FlightRecorder(
            dir=getattr(cfg, "flightrec_dir", "") or "",
            ledger=self.ledger,
            trace_steps=getattr(cfg, "flightrec_trace_steps", 3),
            profiler_busy=lambda: self.profiling,
            process_index=pidx)
        self.ledger.add_sink(self.flightrec.sink)
        # goodput accounting + progress-SLO watch (obs.goodput): another
        # ledger sink — periodic 'goodput' partitions and 'slo' breach
        # events ride the same one-event-stream fan-out, so the metrics
        # gauges and the flight recorder see them with no new plumbing
        self.goodput = GoodputMonitor(
            self.ledger,
            every_s=getattr(cfg, "goodput_every_s", 60.0),
            slo_steps_per_min=getattr(cfg, "slo_steps_per_min", 0.0),
            slo_throughput=getattr(cfg, "slo_throughput", 0.0),
            unit=unit)
        self.ledger.add_sink(self.goodput.sink)
        self._prev_sigusr1 = None
        # deterministic fault injection (obs.faults): the config knob wins
        # over TPU_DIST_FAULTS; ledger + attempt context registered at
        # run_start so every injection site (checkpoint writer, launch)
        # can emit its 'fault' event without new plumbing
        if getattr(cfg, "faults", ""):
            faults.install(cfg.faults)
        # supervisor liveness: touch a heartbeat file at each proven-progress
        # beat (parallel.supervisor sets the env var for its children; the
        # ledger tail is the other liveness signal)
        self._hb_path = os.environ.get("TPU_DIST_HEARTBEAT_FILE", "") \
            if self.is_main else ""
        self._hb_last = 0.0
        self.peak_tflops, self.peak_is_nominal = effective_peak_tflops()
        self._mesh_info = (
            {name: int(size) for name, size in mesh.shape.items()}
            if mesh is not None else None)
        self._t0 = time.time()
        self.steps = 0
        self._ended = False
        self._crash_tb: Optional[str] = None
        self._prev_excepthook = None
        self._prev_sigterm = None
        # coordinated preemption (round 13): a loop that can snapshot
        # enables this, and a SIGTERM then REQUESTS a snapshot (flag +
        # deadline) instead of the crash guard's immediate run_end — the
        # loop finishes the in-flight step, checkpoints, and exits with
        # parallel.supervisor.PREEMPT_SNAPSHOT_RC
        self._preempt_enabled = False
        self._preempt_event = threading.Event()
        self.preempt_deadline_s: Optional[float] = None
        self.preempt_source: Optional[str] = None

    # -- coordinated preemption ----------------------------------------
    def enable_preempt_snapshot(self) -> None:
        """Loops with a snapshot path call this before :meth:`run_start`:
        SIGTERM becomes a snapshot REQUEST the loop drains at its next
        step boundary rather than an immediate crash-guard shutdown."""
        self._preempt_enabled = True

    def request_preemption(self, deadline_s: Optional[float] = None,
                           source: str = "sigterm") -> None:
        """Arm the snapshot request (idempotent). ``deadline_s`` defaults
        to the supervisor-forwarded ``TPU_DIST_PREEMPT_DEADLINE_S``."""
        if self._preempt_event.is_set():
            return
        if deadline_s is None:
            try:
                deadline_s = float(
                    os.environ.get("TPU_DIST_PREEMPT_DEADLINE_S", "30"))
            except ValueError:
                deadline_s = 30.0
        self.preempt_deadline_s = deadline_s
        self.preempt_source = source
        self._preempt_event.set()

    def preempt_pending(self) -> bool:
        return self._preempt_event.is_set()

    # -- lifecycle ------------------------------------------------------
    def run_start(self) -> None:
        import jax

        self._t0 = time.time()
        self._ended = False
        faults.set_ledger(self.ledger)
        # fault-gating context: under a supervisor, TPU_DIST_ATTEMPT (its
        # launch counter) is authoritative — the ledger ordinal does not
        # advance across ledgerless deaths (a pre-RunObs rendezvous crash),
        # so gating on it would aim attempt-conditioned faults at the
        # wrong launch. Standalone runs have no env var; the two coincide.
        try:
            fault_attempt = int(
                os.environ.get("TPU_DIST_ATTEMPT", "") or self.attempt)
        except ValueError:
            fault_attempt = self.attempt
        faults.set_context(attempt=fault_attempt)
        try:
            mesh_epoch = int(os.environ.get("TPU_DIST_MESH_EPOCH", "0") or 0)
        except ValueError:
            mesh_epoch = 0
        self.ledger.emit(
            "run_start", kind=self.kind,
            config=dataclasses.asdict(self.cfg)
            if dataclasses.is_dataclass(self.cfg) else dict(self.cfg),
            mesh=self._mesh_info,
            devices=sorted({d.device_kind for d in jax.local_devices()}),
            process_count=jax.process_count(),
            device_count=jax.device_count(),
            peak_tflops=self.peak_tflops,
            peak_is_nominal=self.peak_is_nominal,
            jax_version=jax.__version__,
            job_id=self.job_id, attempt=self.attempt,
            resumed_from=getattr(self.cfg, "resume", "") or None,
            # elastic lineage (parallel.consensus): reports tell a
            # degraded layout and its rendezvous epoch from the planned one
            degraded=os.environ.get("TPU_DIST_DEGRADED") == "1",
            mesh_epoch=mesh_epoch,
            # step-plan identity (tpu_dist.plan): which tuned plan drove
            # this run's step compilation (None = hand-set knobs)
            plan_hash=(self.plan_info or {}).get("hash"),
            plan_source=(self.plan_info or {}).get("source"),
            plan_knobs=(self.plan_info or {}).get("knobs"))
        if self.plan_info:
            self.ledger.emit(
                "plan", source=self.plan_info.get("source"),
                plan_hash=self.plan_info.get("hash"),
                knobs=self.plan_info.get("knobs"),
                device_kind=self.plan_info.get("device_kind"))
        self._arm_crash_guard()

    def run_end(self, status: Optional[str] = None, **extra) -> None:
        """Final rollup + shutdown. Idempotent (the crash guard's atexit
        hook and a loop's ``finally`` may both call it). ``status`` is
        derived from the active exception when not given — the loops call
        this from a ``finally``, where ``sys.exc_info()`` still sees the
        in-flight crash — so an unhandled exception stamps
        ``status="crashed"`` plus a truncated traceback without any
        call-site ceremony. The ledger file is line-buffered, so every
        prior event is already on disk even if this emit never runs."""
        if self._ended:
            return
        self._ended = True
        self._disarm_crash_guard()
        if self.watchdog is not None:
            self.watchdog.stop()
        # finalize a profiler window left open (a stall with no subsequent
        # steps) BEFORE the final emits below land in the ring
        self.flightrec.close()
        if status is None:
            exc = sys.exc_info()[1]
            if exc is None and self._crash_tb is not None:
                status = "crashed"
                extra.setdefault("error", self._crash_tb)
            elif isinstance(exc, KeyboardInterrupt):
                status = "interrupted"
            elif exc is not None:
                status = "crashed"
                extra.setdefault("error", "".join(
                    traceback.format_exception(type(exc), exc,
                                               exc.__traceback__))[-2000:])
            else:
                status = "ok"
        # the final goodput partition (obs.goodput): always one 'goodput'
        # event per attempt, however short the run — the attempt tools and
        # the metrics snapshot below both read it. Exception-guarded: the
        # crash paths (atexit/SIGTERM) reach here too
        try:
            self.goodput.emit_goodput(final=True)
        except Exception:
            pass
        # the registry's final values survive in the flight record after
        # the scrape endpoint is gone
        self.ledger.emit("metrics_snapshot", metrics=self.metrics.snapshot())
        self.ledger.emit("run_end", steps=self.steps,
                         seconds=round(time.time() - self._t0, 3),
                         status=status, health_trips=self.health.trips,
                         **extra)
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        self.ledger.close()

    # -- crash-safe shutdown -------------------------------------------
    # An unhandled exception reaches run_end via the loops' finally (and
    # sys.exc_info stamps it); the guard covers the paths finally cannot:
    # SIGTERM (the scheduler's preemption signal — default handling kills
    # the process with no cleanup) and interpreter exit without run_end
    # (a caller that never wrapped the loop). Armed at run_start, disarmed
    # at run_end; emit is microseconds on a line-buffered file.
    def _arm_crash_guard(self) -> None:
        atexit.register(self._atexit_end)
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        try:
            if threading.current_thread() is threading.main_thread():
                self._prev_sigterm = signal.signal(signal.SIGTERM,
                                                   self._on_sigterm)
        except (ValueError, OSError):  # non-main thread / exotic platform
            self._prev_sigterm = None
        try:
            # operator-initiated diagnosis: kill -USR1 <pid> captures a
            # flight-recorder bundle without touching the run
            if threading.current_thread() is threading.main_thread():
                self._prev_sigusr1 = signal.signal(signal.SIGUSR1,
                                                   self._on_sigusr1)
        except (ValueError, OSError, AttributeError):  # no SIGUSR1 on win
            self._prev_sigusr1 = None

    def _disarm_crash_guard(self) -> None:
        try:
            atexit.unregister(self._atexit_end)
        except Exception:
            pass
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):
                pass
            self._prev_sigterm = None
        if self._prev_sigusr1 is not None:
            try:
                signal.signal(signal.SIGUSR1, self._prev_sigusr1)
            except (ValueError, OSError):
                pass
            self._prev_sigusr1 = None

    def _on_sigusr1(self, signum, frame) -> None:
        self.flightrec.trigger("sigusr1")
        prev = self._prev_sigusr1
        if callable(prev):
            prev(signum, frame)

    def _excepthook(self, exc_type, exc, tb) -> None:
        # record the traceback for the atexit emit, then defer to the
        # previous hook (never swallow the crash report)
        self._crash_tb = "".join(
            traceback.format_exception(exc_type, exc, tb))[-2000:]
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    def _atexit_end(self) -> None:
        if not self._ended:
            self.run_end(status="crashed" if self._crash_tb else "ok",
                         **({"error": self._crash_tb}
                            if self._crash_tb else {}))

    def _on_sigterm(self, signum, frame) -> None:
        if self._preempt_enabled and not self._ended:
            # coordinated path: flag only (signal-safe — no locks, no
            # I/O); the loop finishes the in-flight step, snapshots, and
            # owns the run_end + exit
            self.request_preemption(source="SIGTERM")
            return
        # capture BEFORE run_end: disarming inside it nulls _prev_sigterm,
        # and a previously-installed handler (a preemption checkpoint
        # hook, say) must still be chained
        prev = self._prev_sigterm
        self.run_end(status="crashed", error="SIGTERM")
        if callable(prev):
            prev(signum, frame)
        else:
            raise SystemExit(143)

    # -- per-step -------------------------------------------------------
    def step(self, step: int, loss: Optional[float], n_items: float,
             wall_s: float, data_s: float, dispatch_s: float,
             device_s: float, device_flops: Optional[float] = None,
             steps_in_dispatch: int = 1, warm: bool = False,
             comm_s: Optional[float] = None, **extra) -> dict:
        """Record one optimizer step (or one K-step dispatch window).

        ``n_items`` is the GLOBAL item count of the record (images or
        tokens across all steps in the dispatch); ``device_flops`` is the
        per-device model FLOPs of ONE optimizer step, from which TFLOP/s
        and MFU derive. ``comm_s`` is the communication share of the
        dispatch where the engine can isolate it (explicit bucketed grad
        sync: a standalone-probe estimate; None under fused/GSPMD
        schedules) — it OVERLAPS device_s, see the EVENT_SCHEMA note. ``warm=True`` marks the record that carried the
        XLA compile (its dispatch_s is compile-dominated; ledger_report
        excludes warm records from phase shares and trends, matching the
        loops' own warm-excluded throughput convention). Also feeds the
        skew monitor. The hang watchdog is NOT fed here — step records
        land only at drain boundaries, while the watchdog needs the
        per-iteration cadence (:meth:`heartbeat`); feeding it boundary-
        clustered single-step durations would false-fire on any run whose
        print window exceeds factor x one step.
        """
        wall = max(wall_s, 1e-9)
        throughput = n_items / wall
        tflops = mfu = None
        if device_flops:
            tflops = device_flops * steps_in_dispatch / wall / 1e12
            mfu = tflops / self.peak_tflops
        rec = self.ledger.emit(
            "step", step=step, loss=loss,
            throughput=round(throughput, 1), unit=self.unit,
            data_s=round(data_s, 6), dispatch_s=round(dispatch_s, 6),
            device_s=round(device_s, 6),
            comm_s=round(comm_s, 6) if comm_s is not None else None,
            mfu=float(f"{mfu:.4g}") if mfu is not None else None,
            tflops=float(f"{tflops:.4g}") if tflops is not None else None,
            steps_in_dispatch=steps_in_dispatch, warm=warm,
            items=n_items, **extra)
        self.steps += steps_in_dispatch
        if self.skew is not None:
            self.skew.record(step, wall_s, data_s,
                             n_steps=steps_in_dispatch)
        return rec

    def fire_step_faults(self, step: int) -> dict:
        """Step-scoped fault-injection check (obs.faults), called by the
        loops once per dispatch iteration: the process-level sites
        (hard_exit/hang/preempt_sigterm) act inside, and the returned
        ``{site: Fault}`` mapping names the data-level effects the loop
        must apply itself (``nan_batch``, ``preempt_deadline`` — the
        Fault carries site args like the injected deadline). No-op and
        near-free when no plan is active."""
        return faults.fire_step(step, ledger=self.ledger)

    def heartbeat(self) -> None:
        """Device progress proven (a drain's blocking device_get returned)
        — the watchdog's arming signal. The loops call this at every drain
        sync point; the watchdog derives the duration itself (time since
        the previous beat), so its trailing median tracks the print-window
        cadence being watched — off-boundary iterations only ENQUEUE work
        and prove nothing about the devices (Watchdog.beat)."""
        if self.watchdog is not None:
            self.watchdog.beat()
        # supervisor liveness: proven progress also touches the heartbeat
        # file (parallel.supervisor watches its mtime beside the ledger
        # tail). Throttled and best-effort — liveness reporting must never
        # take the run down, even on a full disk.
        if self._hb_path:
            now = time.time()
            if now - self._hb_last >= 1.0:
                self._hb_last = now
                try:
                    with open(self._hb_path, "w") as f:
                        f.write(f"{now}\n")
                except OSError:
                    pass

    # -- phase transitions ---------------------------------------------
    def pause(self) -> None:
        """Entering a phase where step completions legitimately stop
        (validation, checkpoint gather) — silence the watchdog."""
        if self.watchdog is not None:
            self.watchdog.pause()

    def resume(self) -> None:
        if self.watchdog is not None:
            self.watchdog.resume()
