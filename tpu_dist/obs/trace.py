"""Step-phase tracer: host-side spans that line up with XLA traces.

The loops need to know where a step's wall time went — data wait vs dispatch
vs device block — every step and with ~zero overhead, not only when a
profiler is attached. :class:`StepTracer` accumulates named host-side spans
(``with tracer.span("data"): ...``) into a per-step dict the ledger's
``step`` record carries; when a ``jax.profiler`` trace is active
(``profile_dir`` set), the same spans also emit
``jax.profiler.TraceAnnotation`` so the host phases appear as named regions
on the XLA timeline, and :func:`step_annotation` wraps
``StepTraceAnnotation`` so XLA's per-step grouping matches the ledger's
step numbering.

:func:`profile_session` replaces the two copy-pasted start/stop_trace
blocks the engines grew in round 2: one context manager that starts the
trace on entry and flushes it even on OOM/interrupt — a failing run is
exactly the one worth profiling.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Optional


class StepTracer:
    """Accumulating named spans for one step (or window) of host work.

    Spans nest: a span opened inside another accumulates under the joined
    path (``data`` -> ``data/decode``), and the parent's total includes the
    child's time (wall-clock truth; the report subtracts if it wants
    self-time). ``annotate=True`` additionally wraps each span in
    ``jax.profiler.TraceAnnotation`` so host phases land on the XLA trace.

    One tracer per loop; call :meth:`pop` at each step boundary to collect
    {phase: seconds} and reset. :meth:`add` folds in externally measured
    seconds (the boundary device_get block, timed where it happens).
    """

    def __init__(self, annotate: bool = False):
        self.annotate = annotate
        self._acc: Dict[str, float] = {}
        self._stack = []

    @contextmanager
    def span(self, name: str):
        path = "/".join(self._stack + [name])
        self._stack.append(name)
        ann = None
        if self.annotate:
            import jax.profiler
            ann = jax.profiler.TraceAnnotation(path)
            ann.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if ann is not None:
                ann.__exit__(None, None, None)
            self._stack.pop()
            self._acc[path] = self._acc.get(path, 0.0) + dt

    def add(self, name: str, seconds: float) -> None:
        """Fold externally measured seconds into a phase."""
        self._acc[name] = self._acc.get(name, 0.0) + float(seconds)

    def phases(self) -> Dict[str, float]:
        return dict(self._acc)

    def pop(self) -> Dict[str, float]:
        """Collect the accumulated {phase: seconds} and reset for the next
        step."""
        out, self._acc = self._acc, {}
        return out


@contextmanager
def step_annotation(step_num: int, enabled: bool = True):
    """``jax.profiler.StepTraceAnnotation`` wrapper (no-op when disabled)
    so XLA's per-step trace grouping carries the ledger's step number."""
    if not enabled:
        yield
        return
    import jax.profiler
    with jax.profiler.StepTraceAnnotation("step", step_num=step_num):
        yield


@contextmanager
def profile_session(profile_dir: str, enabled: bool = True):
    """Start a ``jax.profiler`` trace into ``profile_dir`` and STOP IT ON
    EVERY EXIT PATH (normal, OOM, interrupt). The engines' only device
    tracing entry point since the round-6 obs refactor (both previously
    carried their own start/stop_trace try/finally)."""
    if not (profile_dir and enabled):
        yield False
        return
    import jax.profiler
    jax.profiler.start_trace(profile_dir)
    try:
        yield True
    finally:
        jax.profiler.stop_trace()
