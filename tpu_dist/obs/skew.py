"""Cross-host straggler/skew monitor (the multi-host blind spot, closed).

A multi-controller run prints from process 0 only, so a slow host — thermal
throttling, a contended NIC, a dying SSD feeding the loader — is invisible
until it drags the whole pod's step time down (every collective waits for
the straggler). Megatron/PaLM-style production loops publish cross-host
step-time spread for exactly this reason.

Every ``every`` steps, each process contributes its local trailing
step-time and data-wait means to a tiny allgather
(``multihost_utils.process_allgather`` — one jitted collective over a few
floats, noise next to a training step) and records p50/p99/max-minus-min
plus the straggler's process index in its ledger. The exchange is itself a
collective, so EVERY process must call :meth:`record` on every step —
it participates only on the shared ``step % every == 0`` boundaries, which
all processes hit together (same sampler geometry by construction).

Single-process runs degrade gracefully (allgather of one row): the same
code path runs in tests and on one host, spread is 0, straggler is 0.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from tpu_dist.obs.ledger import Ledger


class SkewMonitor:
    """Windowed cross-process step-time skew sampler.

    ``record(step, step_s, data_s, n_steps=...)`` per record (``n_steps``
    is the dispatch-window size in the K-steps-per-dispatch paths); once
    ``every`` optimizer steps have accumulated, the trailing window's
    means are allgathered and a ``skew`` ledger event is emitted. Counting
    accumulated steps — not ``step % every`` — keeps the configured cadence
    under window strides that never land on a multiple of ``every``.
    Returns the stats dict on exchange records, None otherwise.
    """

    def __init__(self, every: int, ledger: Optional[Ledger] = None):
        if every < 1:
            raise ValueError("skew_every must be >= 1")
        self.every = every
        self.ledger = ledger
        self._step_s = []
        self._data_s = []
        self._accum = 0
        self.last_stats: Optional[dict] = None

    def record(self, step: int, step_s: float, data_s: float = 0.0,
               n_steps: int = 1) -> Optional[dict]:
        self._step_s.append(float(step_s))
        self._data_s.append(float(data_s))
        self._accum += n_steps
        # every process sees the same record sequence (shared sampler and
        # window geometry), so this boundary is collective-safe
        if self._accum < self.every:
            return None
        self._accum = 0
        local = np.array([np.mean(self._step_s), np.mean(self._data_s)],
                         np.float32)
        self._step_s.clear()
        self._data_s.clear()
        return self._exchange(step, local)

    def _exchange(self, step: int, local: np.ndarray) -> dict:
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            # (nprocs, 2) — row i is process i's [step_s, data_s] means
            rows = np.asarray(multihost_utils.process_allgather(local))
        else:
            rows = local[None, :]
        step_times = rows[:, 0]
        stats = {
            "step": step,
            "p50_s": float(np.percentile(step_times, 50)),
            "p99_s": float(np.percentile(step_times, 99)),
            "spread_s": float(step_times.max() - step_times.min()),
            "straggler": int(np.argmax(step_times)),
            "straggler_step_s": float(step_times.max()),
            "straggler_data_s": float(rows[int(np.argmax(step_times)), 1]),
            "n_procs": int(rows.shape[0]),
        }
        self.last_stats = stats
        if self.ledger is not None:
            self.ledger.emit(
                "skew", step=stats["step"], p50_s=stats["p50_s"],
                p99_s=stats["p99_s"], spread_s=stats["spread_s"],
                straggler=stats["straggler"],
                straggler_step_s=stats["straggler_step_s"],
                straggler_data_s=stats["straggler_data_s"],
                n_procs=stats["n_procs"])
        return stats
