"""Hang watchdog: stack + HBM dump when a step stops completing.

A hung collective (one host dropped out), a deadlocked loader thread, or a
device queue stuck behind a tunneled controller all present the same way: a
training loop that silently stops printing, forever. The reference cookbook
— and rounds 1-5 of this repo — would sit there until someone killed the
job with zero forensic record.

The watchdog is a daemon thread armed by step completions: the loop calls
:meth:`step_done` after every optimizer-step (or window) dispatch cycle,
which maintains a trailing median of step durations. If no step completes
within ``factor x median`` (floored at ``min_timeout_s`` so fast CPU loops
never false-trigger), it dumps — ONCE per stall — to stderr and the ledger:

* every Python thread's stack (``sys._current_frames``), which catches the
  loader/prefetch/checkpoint threads too;
* live HBM counters (``utils.telemetry.device_memory_stats``);
* the last ledger event (what the run was doing when it stopped).

It never kills the run: a stall that resolves (a slow eval, a network blip)
re-arms on the next ``step_done`` and the run continues with the dump as a
breadcrumb. Loops call :meth:`pause` around phases where step completions
legitimately stop (validation, checkpoint gather) and :meth:`resume` when
stepping resumes. Opt out with ``watchdog_factor=0`` in the config.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import deque
from typing import Optional

from tpu_dist.obs.ledger import Ledger


def thread_stacks() -> str:
    """Formatted stacks of every live Python thread (the dump payload)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sys._current_frames().items():
        parts.append(f"--- thread {names.get(ident, '?')} ({ident}) ---\n"
                     + "".join(traceback.format_stack(frame)))
    return "\n".join(parts)


class Watchdog:
    """Trailing-median hang detector. Thread starts lazily on the first
    :meth:`step_done` (constructing one per Trainer is free until a loop
    actually steps)."""

    def __init__(self, factor: float = 10.0,
                 ledger: Optional[Ledger] = None,
                 min_timeout_s: float = 5.0,
                 poll_s: float = 0.5,
                 stream=None):
        if factor <= 0:
            raise ValueError("watchdog factor must be > 0 (use no watchdog "
                             "instead of factor<=0)")
        self.factor = factor
        self.ledger = ledger
        self.min_timeout_s = min_timeout_s
        self.poll_s = poll_s
        self._stream = stream  # None -> sys.stderr at dump time (testable)
        self._durations = deque(maxlen=64)
        self._last_done: Optional[float] = None
        self._fired_this_stall = False
        self.stall_count = 0
        self._paused = False
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # -- loop-side API --------------------------------------------------
    def step_done(self, seconds: float) -> None:
        """A step (or dispatch window) completed in ``seconds``."""
        with self._lock:
            self._durations.append(float(seconds))
            self._last_done = time.monotonic()
            self._fired_this_stall = False  # stall over; re-arm
            self._paused = False
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tpu-dist-watchdog", daemon=True)
            self._thread.start()

    def beat(self) -> None:
        """Progress proven NOW; duration = time since the previous beat.

        The engines beat at drain sync points (the blocking device_get),
        because under async dispatch that is the only moment the host
        KNOWS the devices advanced — off-boundary iterations merely
        enqueue. Beating there with the full inter-drain duration makes
        the trailing median track the print-window cadence, so a
        long-but-healthy boundary block never trips the threshold while a
        genuine hang (> factor x a normal window) still does. The first
        beat after construction/resume only arms (no duration yet)."""
        now = time.monotonic()
        with self._lock:
            # a beat right after pause() (eval/ckpt just ran) only re-arms:
            # its duration would include the paused phase, not a window
            last = None if self._paused else self._last_done
        if last is None:
            with self._lock:
                self._last_done = now
                self._paused = False
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="tpu-dist-watchdog", daemon=True)
                self._thread.start()
            return
        self.step_done(now - last)

    def pause(self) -> None:
        """Suspend stall detection (validation/checkpoint phases where no
        step completes by design)."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._last_done = time.monotonic()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll_s + 1)

    # -- detector -------------------------------------------------------
    def _threshold_s(self) -> Optional[float]:
        if not self._durations:
            return None
        med = sorted(self._durations)[len(self._durations) // 2]
        return max(self.factor * med, self.min_timeout_s)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                if (self._paused or self._fired_this_stall
                        or self._last_done is None):
                    continue
                thr = self._threshold_s()
                idle = time.monotonic() - self._last_done
                if thr is None or idle < thr:
                    continue
                self._fired_this_stall = True  # once per stall
                self.stall_count += 1
            self._dump(idle, thr)

    def _dump(self, idle_s: float, threshold_s: float) -> None:
        from tpu_dist.utils.telemetry import device_memory_stats

        stacks = thread_stacks()
        try:
            hbm = device_memory_stats()
        except Exception:
            hbm = {}
        last = self.ledger.last if self.ledger is not None else None
        stream = self._stream or sys.stderr
        print(f"\n=== tpu_dist watchdog: NO STEP COMPLETED for "
              f"{idle_s:.1f}s (threshold {threshold_s:.1f}s = "
              f"{self.factor:g} x trailing-median step) ===\n"
              f"last ledger event: {last}\n"
              f"hbm: {hbm or 'n/a'}\n{stacks}\n"
              f"=== end watchdog dump (run NOT killed) ===",
              file=stream, flush=True)
        if self.ledger is not None:
            try:
                self.ledger.emit(
                    "stall", idle_s=round(idle_s, 3),
                    threshold_s=round(threshold_s, 3), stacks=stacks,
                    hbm=hbm or None, last_event=last)
            except Exception:
                pass  # the dump must never take the run down
