"""Run ledger: append-only JSONL of typed run events (the observability spine).

The reference cookbook's only record of a run is whatever scrolled past on
stdout plus a per-epoch CSV clone in every script; tpu_dist's round-1-5
engines reproduced those and then grew ad-hoc extras (bench JSON, MFU
prints, HBM probes) with no machine-readable per-step record. The ledger
replaces all of that as the source of truth: every engine/bench/decode run
appends one JSON object per event to ``ledger_path``, and the legacy
artifacts (epoch CSV, progress line) become *sinks* rendered from ledger
records rather than independently computed values.

Schema discipline: ``EVENT_SCHEMA`` below is a PURE LITERAL (dict of
event-name -> tuple of required field names) so ``tools/check_ledger_schema``
can extract it by AST walk — without importing jax — and statically verify
every ``*.emit("<event>", ...)`` call site in the tree names a declared
event and passes its required fields. Values may be ``None`` (e.g. MFU on a
backend with no cost model); *presence* is what the schema pins, so readers
can always key into a record without guards.

Multi-host: each process writes its OWN file — ``per_process_path`` suffixes
non-main paths with the process index (``run.jsonl`` -> ``run.p1.jsonl``) so
N processes never interleave writes into one file. ``emit`` is
thread-safe (the HBM sampler and the hang watchdog feed the ledger from
daemon threads).
"""

from __future__ import annotations

import csv
import json
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional

# event name -> required fields. PURE LITERAL (tools/check_ledger_schema
# extracts it via ast.literal_eval — no computed values, no imports).
# Required means "key present"; None values are legal where a backend
# cannot supply the number. ``event``/``ts``/``pid`` are stamped by emit().
EVENT_SCHEMA = {
    # run identity: full config + mesh + device kinds, once per run
    "run_start": ("kind", "config", "mesh", "devices", "process_count"),
    # first-dispatch / AOT-probe record (program stats, warm seconds)
    "compile": ("program",),
    # one optimizer step (or one K-step dispatch window: steps_in_dispatch
    # carries the window size) with the full phase breakdown. comm_s is the
    # communication share: unlike the other phases it OVERLAPS device_s
    # (that is the point of parallel.overlap), so it is reported beside the
    # share table, not inside it. None where the engine cannot isolate it
    # (fused GSPMD sync, ring TP interleaving); the explicit bucketed-sync
    # mode stamps a standalone-probe estimate, tools/comm_bench.py measures
    # it exactly (its programs are pure communication). Engines additionally
    # stamp a boolean `fused` extra: whether int8 matmuls rode the fused
    # Pallas kernel (ops.pallas_quant) — ledger_report splits MFU on it.
    "step": ("step", "loss", "throughput", "unit",
             "data_s", "dispatch_s", "device_s", "comm_s", "mfu"),
    # end-of-epoch rollup (the legacy per-epoch CSV row renders from this)
    "epoch": ("epoch", "start_ts", "seconds", "throughput", "unit", "loss"),
    # held-out evaluation
    "eval": ("epoch", "loss"),
    # checkpoint written
    "ckpt": ("epoch", "path", "is_best"),
    # cross-host step-time skew sample (obs.skew every K steps)
    "skew": ("step", "p50_s", "p99_s", "spread_s", "straggler"),
    # hang-watchdog stall dump (obs.watchdog; once per stall)
    "stall": ("idle_s", "threshold_s", "stacks"),
    # periodic HBM sampler row (utils.telemetry feeding the ledger)
    "hbm": ("bytes_in_use",),
    # one generate() call (engine.generate with a ledger passed in)
    "decode": ("tokens", "seconds", "throughput"),
    # serving admission decision (engine.serve): one per submit();
    # accepted=False carries a `reason` extra (queue_full|page_watermark|
    # slo_shedding|too_long|exceeds_pool) — the overload forensics
    "admit": ("rid", "accepted", "queue_depth", "pages_free"),
    # one COMPLETED serving request (engine.serve): the serving-SLO
    # record — timestamps are engine-clock (real seconds by default,
    # virtual units under an injected clock); ttft_s/prompt_len ride as
    # extras
    "request": ("rid", "tokens", "queue_wait_s", "admit_ts",
                "first_token_ts", "finish_ts"),
    # paged KV pool pressure snapshot (engine.serve, periodic + final):
    # shared_pages/cow_copies/prefix_hits track cross-request prefix
    # sharing, spec_emitted/spec_slot_ticks the speculative acceptance
    # trend, sharded_devices the sp-mesh width of the pool (1 when
    # unsharded) and chunks_pending the chunked-prefill backlog (the
    # chunk-queue depth ledger_report trends); high_water_used/slots/
    # tick/chunk_ticks ride as extras
    "kv_cache": ("pages_free", "pages_used", "active_seqs",
                 "shared_pages", "cow_copies", "prefix_hits",
                 "sharded_devices", "chunks_pending"),
    # numerical-health trip (obs.health sentry: non-finite grads/loss or a
    # loss spike); action records what the policy did (record|skip|halt)
    "health": ("step", "kind", "policy", "action", "value"),
    # flight-recorder bundle captured (obs.flightrec): reason names the
    # trigger (stall|health|skew|sigusr1|manual), bundle the directory
    # holding manifest.json + stacks/HBM/ledger-tail/profiler-window
    "diagnosis": ("reason", "bundle", "step"),
    # static cost attribution of one compiled step program (obs.attr):
    # buckets maps category -> {flops, bytes, count}; emitted once at
    # compile time beside the 'compile' event, read back by the
    # ledger_report roofline section
    "cost_model": ("program", "buckets"),
    # final registry dump (obs.metrics) so counter values survive in the
    # flight record after the scrape endpoint is gone
    "metrics_snapshot": ("metrics",),
    # goodput/badput partition snapshot (obs.goodput): categories maps
    # badput category -> seconds (startup/data_wait/dispatch/eval/ckpt/
    # stall/skipped/idle[/restart_gap]); emitted periodically by the
    # GoodputMonitor sink and once at run_end (final=True extra)
    "goodput": ("wall_s", "goodput_s", "ratio", "categories"),
    # progress-SLO breach (obs.goodput): EMA steps/min or items/s fell
    # below the configured floor; auto-triggers the flight recorder
    # through the ledger-sink path like every other detector event
    "slo": ("step", "kind", "value", "floor"),
    # one deterministic fault injection (obs.faults): site names the
    # injection point (nan_batch|hard_exit|hang|preempt_sigterm|
    # ckpt_enospc|rendezvous_fail), spec the matched entry; step/attempt
    # may be None for non-step-scoped sites. Reports use these to keep
    # injected failures distinguishable from organic ones
    "fault": ("site", "step", "spec"),
    # elastic-capacity transition (parallel.supervisor consensus + the
    # engines): action names the transition (shrink|expand|
    # preempt_snapshot|peer_restore|drain), processes the post-transition
    # world size, epoch the consensus/rendezvous epoch (None where no
    # consensus is configured); hosts/step/world_from ride as extras.
    # ledger_report stitches these into the elasticity timeline
    "scale": ("action", "processes", "epoch"),
    # one autoscaling decision (obs.autoscale CapacityMonitor under an
    # AutoscalePolicy): direction (up|down), the capacity transition
    # (hosts_from -> target_hosts), and the FULL attribution — which
    # signal tripped, its value vs threshold, the evaluation window, and
    # the newest flight-recorder bundle reference (None when no diagnosis
    # preceded it) — so "why did we scale" reads from the ledger alone.
    # The fleet tick rides as an extra; the executing scale event stamps
    # the decision id as its own `decision` extra (1:1 pairing)
    "scale_decision": ("decision", "direction", "hosts_from",
                       "target_hosts", "signal", "value", "threshold",
                       "window_ticks", "bundle"),
    # the decision's follow-up (parallel.supervisor after the rescale
    # relaunch): which decision was applied, the executed action
    # (shrink|expand), the post-transition world size and consensus
    # epoch, and the plan_hash of the deterministic plan/tune.py re-run
    # at the new world size (None when no retune is configured) — the
    # PR 15 retune-on-rescale residue, closed and auditable
    "applied": ("decision", "action", "processes", "epoch", "plan_hash"),
    # fleet-simulation identity (tpu_dist.sim.runner): the scenario one
    # fleet run executed — name/seed/hosts/ticks pin the deterministic
    # schedule so a fleet report is self-describing; tick_s/events ride
    # as extras. One per fleet ledger, the fleet analog of run_start
    "scenario": ("name", "seed", "hosts", "ticks"),
    # fleet-plane rollup (tpu_dist.sim.runner, periodic + final=True):
    # hosts_live is the count of virtual hosts with a running child,
    # goodput_ratio the stitched fleet ratio (None on periodic snapshots
    # — the full stitch runs once at the end), slo_breaches the
    # cumulative fleet-wide breach count. Feeds the
    # tpu_dist_fleet_* Prometheus series through the metrics sink
    "fleet": ("hosts_live", "goodput_ratio", "slo_breaches"),
    # one request-lifecycle span (obs.reqtrace): per-request distributed
    # tracing. Ids are DERIVED, not generated — trace_id = H(ns|rid) is
    # host-independent (cross-host traces stitch by equality alone),
    # span_id/parent_id chain H(parent|name|n) under the per-(job_id,
    # attempt) root. start/end are ENGINE-CLOCK seconds (comparable
    # within one process only; emit's wall ``ts`` anchors cross-host
    # placement). name is the lifecycle phase (request|queue|prefill|
    # decode|shed|readmit|prefix_hit|cow_fork); job_id/attempt/host/
    # tenant/reason/bucket/tokens ride as extras
    "span": ("trace_id", "span_id", "parent_id", "name", "rid",
             "start", "end"),
    # resolved step plan (tpu_dist.plan): which tuned/loaded plan drove
    # this run's step compilation — source names the file|'auto', plan_hash
    # the content address (plan.ir.plan_hash), knobs the non-default knob
    # diff; device_kind rides as a field so a report can say which table
    # row the plan was selected for. Emitted once, right after run_start
    "plan": ("source", "plan_hash", "knobs"),
    # one auto-tuner invocation (plan.tune via tools/tune.py --ledger):
    # the search's identity — candidate count and the winning plan hash
    # per device kind; workload/measured extras ride along
    "tune": ("device_kind", "candidates", "best_hash"),
    # one program-audit verdict (tpu_dist.analysis.proglint through
    # plan.compile's audit pass): program names the jitted step/serve
    # program, mode the knob (record|halt), findings the UNWAIVERED
    # finding count (0 = clean); waived and detail (the finding dicts)
    # ride as extras. One event per program at its compile-time pass,
    # plus one latched event per program the recompile sentry catches
    "audit": ("program", "mode", "findings"),
    # run rollup: total steps, wall seconds, best metric in extras;
    # status ("ok"|"crashed"|"interrupted") rides as an extra stamped by
    # RunObs.run_end — the crash-safe shutdown path sets "crashed"
    "run_end": ("steps", "seconds"),
}


def _json_safe(v):
    """Non-finite floats (inf/nan — e.g. best_ppl before any eval) become
    None: json.dumps would otherwise emit the bare tokens Infinity/NaN,
    which are NOT valid JSON and break strict parsers (jq, pandas) on the
    whole line — the machine-readability the ledger exists for."""
    if isinstance(v, float) and not math.isfinite(v):
        return None
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    return v


def per_process_path(path: str, process_index: int) -> str:
    """Suffix non-main output paths with the process index so multi-host
    runs never clobber one file: ``run.jsonl`` -> ``run.p1.jsonl`` for
    process 1; process 0 keeps the bare path (single-host unchanged)."""
    if not path or process_index == 0:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.p{process_index}{ext}"


class Ledger:
    """Append-only JSONL event log with schema validation and sinks.

    ``path=None`` builds a sink-only ledger (no file): the engines always
    carry one so the epoch-CSV sink, watchdog, and skew monitor have a
    single emit() surface whether or not ``ledger_path`` is set.

    Sinks are callables ``sink(record: dict)`` invoked on every emit —
    the legacy renderers (epoch CSV, progress stdout) hang off here, so
    they can never drift from the recorded values.
    """

    def __init__(self, path: Optional[str] = None, process_index: int = 0,
                 sinks: tuple = ()):
        self.path = path or None
        self.process_index = process_index
        self._f = open(path, "a", buffering=1) if path else None
        # RLock, not Lock: the crash guard's SIGTERM handler runs ON the
        # main thread and emits run_end — if the signal lands while that
        # same thread is inside emit(), a plain Lock would self-deadlock
        # on exactly the preemption path the guard exists for. Re-entry
        # writes the inner record as its own complete line (signals fire
        # between bytecodes, never mid-write), so lines stay intact.
        self._lock = threading.RLock()
        self._sinks: List[Callable[[dict], None]] = list(sinks)
        self.last: Optional[dict] = None  # most recent record (watchdog dump)

    def add_sink(self, sink: Callable[[dict], None]) -> None:
        self._sinks.append(sink)

    def emit(self, event: str, **fields) -> dict:
        """Validate + append one typed record; returns the full record."""
        required = EVENT_SCHEMA.get(event)
        if required is None:
            raise ValueError(f"undeclared ledger event {event!r} "
                             f"(EVENT_SCHEMA: {sorted(EVENT_SCHEMA)})")
        missing = [k for k in required if k not in fields]
        if missing:
            raise ValueError(f"ledger event {event!r} missing required "
                             f"fields {missing}")
        rec = _json_safe({"event": event, "ts": time.time(),
                          "pid": self.process_index, **fields})
        with self._lock:
            self.last = rec
            if self._f is not None and not self._f.closed:
                # default=str: config dicts can carry tuples/dtypes — a
                # ledger write must never take the run down
                self._f.write(json.dumps(rec, default=str) + "\n")
            for sink in self._sinks:
                try:
                    sink(rec)
                except Exception:
                    pass  # a renderer must never take the run down
        return rec

    def close(self) -> None:
        with self._lock:
            if self._f is not None and not self._f.closed:
                self._f.flush()
                self._f.close()
            for sink in self._sinks:
                close = getattr(sink, "close", None)
                if close:
                    try:
                        close()
                    except Exception:
                        pass


def read_ledger(path: str, validate: bool = True,
                strict: bool = True) -> List[dict]:
    """Parse a ledger file back into typed records (the round-trip half of
    the schema contract: every line is a declared event carrying its
    required fields).

    ``strict=False`` skips corrupt or truncated lines with a stderr
    warning instead of raising — a process killed mid-``write`` leaves a
    torn trailing line, and crashed runs are exactly the ones operators
    inspect (tools/ledger_report and tools/trace_merge read this way)."""
    import sys

    out = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict):
                    raise ValueError("not a JSON object")
                if validate:
                    ev = rec.get("event")
                    required = EVENT_SCHEMA.get(ev)
                    if required is None:
                        raise ValueError(
                            f"{path}:{line_no}: undeclared event {ev!r}")
                    missing = [k for k in required if k not in rec]
                    if missing:
                        raise ValueError(f"{path}:{line_no}: event {ev!r} "
                                         f"missing {missing}")
            except (json.JSONDecodeError, ValueError):
                if strict:
                    raise
                print(f"warning: {path}:{line_no}: skipping corrupt/"
                      f"truncated ledger line ({line[:60]!r}...)",
                      file=sys.stderr)
                continue
            out.append(rec)
    return out


class EpochCsvSink:
    """Render 'epoch' events into the cookbook-parity per-epoch CSV
    (reference 1.dataparallel.py:187-190 format [wall_start, seconds] +
    the tpu_dist rate and peak-HBM columns). The CSV is now a VIEW of the
    ledger's epoch record — same values, one source."""

    def __init__(self, path: str):
        self._path = path
        self._f = None

    def __call__(self, rec: dict) -> None:
        if rec.get("event") != "epoch":
            return
        if self._f is None:
            self._f = open(self._path, "a+", newline="")
        csv.writer(self._f).writerow(
            [rec["start_ts"], rec["seconds"],
             round(rec["throughput"], 1), rec.get("hbm_bytes") or ""])
        self._f.flush()

    def close(self) -> None:
        if self._f is not None and not self._f.closed:
            self._f.close()


def _fmt(v, spec: str) -> str:
    """Format a maybe-None numeric ledger field ('?' for None — schema
    requires presence, not non-nullness)."""
    return f"{v:{spec}}" if v is not None else "?"


class ProgressSink:
    """Render step/epoch/stall events as one-line text — the stdout
    renderer flavor of the ledger (tools/ledger_report --tail uses it;
    the in-loop progress line stays MeterBank's cookbook-format string,
    fed from the same MeterBank.snapshot() read as the ledger)."""

    def __init__(self, printer: Callable[[str], None] = print,
                 every: int = 1):
        self._print = printer
        self._every = max(every, 1)

    def __call__(self, rec: dict) -> None:
        # every field is formatted None-tolerantly: the schema only pins
        # PRESENCE, and all-None records are legal (ledger.py header)
        ev = rec.get("event")
        if ev == "step":
            if (rec["step"] or 0) % self._every:
                return
            mfu = rec.get("mfu")
            self._print(
                f"step {rec['step']}: loss " + _fmt(rec["loss"], ".4f")
                + f" {_fmt(rec['throughput'], ',.0f')} {rec['unit']}"
                + (f" MFU {mfu * 100:.1f}%" if mfu else "")
                + f" [data {_fmt(rec['data_s'], '.3f')}s dispatch "
                  f"{_fmt(rec['dispatch_s'], '.3f')}s device "
                  f"{_fmt(rec['device_s'], '.3f')}s]")
        elif ev == "epoch":
            self._print(f"epoch {rec['epoch']}: "
                        f"loss {_fmt(rec['loss'], '.4f')} "
                        f"{_fmt(rec['throughput'], ',.0f')} {rec['unit']} "
                        f"({_fmt(rec['seconds'], '.1f')}s)")
        elif ev == "stall":
            self._print(f"STALL: no step for {_fmt(rec['idle_s'], '.1f')}s "
                        f"(threshold {_fmt(rec['threshold_s'], '.1f')}s)")


def phase_totals(records) -> Dict[str, float]:
    """Sum the per-step phase seconds across a record list — the per-phase
    time-share rollup ledger_report and bench publish. ``comm_s`` rides
    along but OVERLAPS device_s (schema note), so share denominators must
    exclude it."""
    tot = {"data_s": 0.0, "dispatch_s": 0.0, "device_s": 0.0, "comm_s": 0.0}
    for rec in records:
        if rec.get("event") != "step":
            continue
        for k in tot:
            tot[k] += rec.get(k) or 0.0
    return tot
