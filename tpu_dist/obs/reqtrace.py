"""Per-request distributed tracing: deterministic spans over the ledger.

Every telemetry layer so far — ledger, metrics, flight recorder, goodput,
fleet observatory — is *aggregate*: ``DecodeRequest.rid`` rides the
``admit``/``request`` events, yet nothing can answer "where did THIS
request's p99 TTFT go — queue, prefill bucket, spec-reject storm, CoW
fork, or shed-readmit?". This module is the missing span model: stdlib
only, jax-free, emitted as the ``span`` ledger event through the normal
sink fan-out (so the metrics bridge, flight recorder and fleet stitcher
all see spans for free).

Identity is DERIVED, never generated — no wall-clock, no randomness:

* ``trace_id = H(trace_ns | rid)``: host-INDEPENDENT on purpose. Two
  fleet hosts never exchange a byte, yet both mint the SAME trace id for
  the same request rid (the namespace is the scenario/job family, not the
  per-host job_id), so a request shed on one host and re-admitted on
  another — today's dropped case, tomorrow's migration — stitches into
  ONE trace by id equality alone (:meth:`sim.fleet.FleetLedger.traces`).
* root span, one per (job_id, attempt) that touched the request:
  ``H(trace_id | job_id | attempt | 'request')``. An attempt that only
  SHED the request never emits its root, but the id is still derivable,
  so orphan children always know their parent.
* child spans: ``H(parent_id | name | n)`` with a deterministic
  per-(parent, name) counter — the n-th decode window of a request has
  the same span id on every replay (the replay-diffable discipline the
  rest of the ledger already follows).

Span ``start``/``end`` are ENGINE-CLOCK seconds (real seconds under the
default clock, virtual units under an injected one) — comparable within
one process only. The ledger's wall ``ts``, stamped at emit time (== span
close), anchors cross-host placement: SLO-exemplar windows
(tools/request_report.py) and Perfetto lanes (tools/trace_merge.py) both
key on it.

Attribution contract (tools/request_report.py): the ``queue``, ``prefill``
and ``decode`` spans of one root TILE the request's admit->finish interval
contiguously, so ``sum(categories) + residue == latency`` holds by
construction (the goodput ``sum_check`` discipline, per request). Detail
spans (``prefix_hit``, ``cow_fork``, ``readmit``, ``shed``) NEST inside
those periods and are excluded from the category sum — they name causes,
they don't add seconds.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

# the root span's name; every other span of a trace is a child of a root
ROOT_NAME = "request"
# span names that sum into the attribution categories (tile the request)
CATEGORIES = ("queue", "prefill", "decode")
# span names that annotate a cause inside a category period (no seconds)
DETAIL_NAMES = ("prefix_hit", "cow_fork", "readmit", "shed")


def _h(text: str) -> str:
    return hashlib.sha1(text.encode()).hexdigest()[:16]


def trace_id(trace_ns: str, rid) -> str:
    """Host-independent request identity: every host of one fleet derives
    the same id from the shared namespace + rid, no coordination."""
    return _h(f"{trace_ns}|{rid}")


def root_span_id(tid: str, job_id: str, attempt: int) -> str:
    """The per-(job, attempt) root: one host-attempt's view of a request.
    Derivable without the root record existing (shed-only attempts)."""
    return _h(f"{tid}|{job_id}|{attempt}|{ROOT_NAME}")


def child_span_id(parent_id: str, name: str, n: int) -> str:
    """The n-th ``name`` child under ``parent_id`` (0-based)."""
    return _h(f"{parent_id}|{name}|{n}")


class RequestTracer:
    """Trace context carried through an engine: the ledger to emit into
    plus the (job_id, attempt, host, trace_ns) identity that pins every
    derived id. Emit sites stay in the instrumented modules (literal
    ``.emit("span", ...)`` calls — the DL006 discipline); the tracer only
    derives ids and the common extras."""

    def __init__(self, ledger, job_id: str, attempt: int = 0,
                 host: Optional[int] = None,
                 trace_ns: Optional[str] = None):
        self.ledger = ledger
        self.job_id = str(job_id)
        self.attempt = int(attempt)
        self.host = host
        # default namespace: the job id — correct for single-host serving;
        # the fleet worker passes the SCENARIO name so per-host job ids
        # (``{scenario}-h{host}``) don't split one request into N traces
        self.trace_ns = str(trace_ns if trace_ns is not None else job_id)
        self._counts: Dict[Tuple[str, str], int] = {}

    def trace_id(self, rid) -> str:
        return trace_id(self.trace_ns, rid)

    def root_id(self, rid) -> str:
        return root_span_id(self.trace_id(rid), self.job_id, self.attempt)

    def root_ids(self, rid) -> Tuple[str, str, None]:
        """(trace_id, span_id, parent_id) for the request root span."""
        return self.trace_id(rid), self.root_id(rid), None

    def ids(self, rid, name: str) -> Tuple[str, str, str]:
        """(trace_id, span_id, parent_id) for the next ``name`` child of
        the request's root, advancing the deterministic counter."""
        tid = self.trace_id(rid)
        parent = root_span_id(tid, self.job_id, self.attempt)
        key = (parent, name)
        n = self._counts.get(key, 0)
        self._counts[key] = n + 1
        return tid, child_span_id(parent, name, n), parent

    def attrs(self) -> dict:
        """The identity extras every span rides: which host-attempt saw
        this slice of the request (host omitted when not in a fleet)."""
        out = {"job_id": self.job_id, "attempt": self.attempt}
        if self.host is not None:
            out["host"] = self.host
        return out


# -- reading spans back ----------------------------------------------------

def spans(records) -> List[dict]:
    """The span records of a ledger, in emit order."""
    return [r for r in records if r.get("event") == "span"]


def traces(records) -> Dict[str, dict]:
    """Group span records into traces: trace_id -> {rid, spans, roots,
    hosts, names}. Deterministic: spans sort by (start, span_id) — engine
    clocks aren't comparable across hosts, but the tie-break id makes the
    order reproducible regardless."""
    out: Dict[str, dict] = {}
    for r in spans(records):
        t = out.setdefault(r["trace_id"], {
            "trace_id": r["trace_id"], "rid": r.get("rid"),
            "spans": [], "roots": [], "hosts": set(), "names": set()})
        t["spans"].append(r)
        t["names"].add(r.get("name"))
        if r.get("host") is not None:
            t["hosts"].add(r["host"])
        if r.get("name") == ROOT_NAME:
            t["roots"].append(r)
    for t in out.values():
        t["spans"].sort(key=lambda s: (float(s.get("start") or 0.0),
                                       str(s.get("span_id"))))
        t["roots"].sort(key=lambda s: (str(s.get("job_id")),
                                       int(s.get("attempt") or 0)))
        t["hosts"] = sorted(t["hosts"])
        t["names"] = sorted(n for n in t["names"] if n)
    return out


def children_of(trace: dict) -> Dict[Optional[str], List[dict]]:
    """parent span_id -> children, in the deterministic span order."""
    by_parent: Dict[Optional[str], List[dict]] = {}
    for s in trace["spans"]:
        by_parent.setdefault(s.get("parent_id"), []).append(s)
    return by_parent


def walk(trace: dict):
    """DFS over the span tree, yielding (depth, span). Roots first (by
    job/attempt), each root's children in span order; orphan children
    (their root was never emitted — shed-only attempts) surface at depth
    1 under a None parent so nothing silently disappears."""
    by_parent = children_of(trace)
    root_ids = {r["span_id"] for r in trace["roots"]}
    for root in trace["roots"]:
        yield 0, root
        for child in by_parent.get(root["span_id"], ()):
            yield 1, child
    for parent, kids in sorted(by_parent.items(),
                               key=lambda kv: str(kv[0])):
        if parent is None or parent in root_ids:
            continue
        for child in kids:
            yield 1, child
