"""jax version bridge (single home for every API the repo needs that moved).

The framework targets current jax (``jax.shard_map`` with ``check_vma``,
``jax_num_cpu_devices``); CI containers sometimes carry an older wheel where
the same features live under different names (``jax.experimental.shard_map``
with ``check_rep``, ``XLA_FLAGS=--xla_force_host_platform_device_count``).
Every call site imports from here so the difference is absorbed ONCE instead
of leaking try/excepts through the engines.
"""

from __future__ import annotations

import os

import jax

try:  # jax >= 0.6: top-level export, varying-manual-axes check is check_vma
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental home, same check named check_rep
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"

# Partial-manual shard_map (manual 'data'/'stage' with a GSPMD *auto*
# 'model' axis — the pp x tp composition) only works on the current-jax
# implementation: the experimental one lowers axis_index to a PartitionId
# the SPMD partitioner rejects, and resharding auto-axis operands inside
# the manual region trips an XLA IsManualSubgroup CHECK (process abort).
# Callers gate the composition on this flag to fail cleanly instead.
PARTIAL_MANUAL_SHARD_MAP = _CHECK_KW == "check_vma"

# True multi-process execution on the CPU backend (jax.distributed over
# loopback with cross-process collectives — the multi-host simulation the
# mp tests spawn) needs the current-jax CPU collectives; the older wheel's
# CPU backend raises "Multiprocess computations aren't implemented".
# Single-process virtual-device meshes are unaffected.
CPU_MULTIPROCESS = _CHECK_KW == "check_vma"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
    """`jax.shard_map` signature (keyword mesh/specs, ``check_vma``,
    ``axis_names`` = the MANUAL axes), executed by whichever implementation
    this jax ships. Older jax spells the manual-axes selection as its
    complement (``auto`` = the GSPMD axes), so translate through the mesh."""
    kwargs[_CHECK_KW] = check_vma
    if _CHECK_KW == "check_rep" and "axis_names" in kwargs:
        # NOTE: the repo's only axis_names caller (_pp_shard_map) refuses
        # old jax first (PARTIAL_MANUAL_SHARD_MAP) because a non-empty
        # 'auto' set aborts in the old SPMD partitioner; this translation
        # is kept for the all-axes-manual case (auto = {}), which old jax
        # runs fine
        manual = kwargs.pop("axis_names")
        kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(manual)
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


def set_cpu_device_count(n: int) -> None:
    """Request ``n`` virtual CPU devices. Must run before the backend
    initializes (conftest / driver entry time). Newer jax has a config
    option; older jax only reads the XLA host-platform flag."""
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        import re
        try:  # flags are parsed once at backend init — writing them later
            # is a silent no-op, so refuse loudly instead (the caller
            # would otherwise die far downstream at "need N devices")
            from jax._src import xla_bridge as _xb
            initialized = bool(getattr(_xb, "_backends", None))
        except Exception:
            initialized = False
        if initialized:
            raise RuntimeError(
                f"set_cpu_device_count({n}): this jax has no "
                "jax_num_cpu_devices option and a backend is already "
                "initialized, so the XLA_FLAGS fallback "
                "(--xla_force_host_platform_device_count) can no longer "
                "take effect. Call set_cpu_device_count before anything "
                "touches jax.devices()/jit.")
        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={n}"
        if "xla_force_host_platform_device_count" in flags:
            # a stale count (e.g. a leftover =2 from a manual run) must be
            # REPLACED, or every mesh sized for n devices fails to build
            flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                           flag, flags)
            os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()
