"""FleetLedger: stitch per-host run ledgers into one fleet plane.

``tools/ledger_report`` answers "what happened to this job" for ONE
host's attempt family; a fleet run (tpu_dist.sim.runner, or any N
supervised hosts writing into one shared directory tree) needs the same
answer across hosts: cross-host discovery, clocks normalized to one
fleet epoch, and the rollups that make "handles heavy traffic" a number
— fleet goodput that provably sums to aggregate wall, the restart-class
histogram, the fleet-wide SLO-breach count, the elasticity timeline, and
per-tenant request percentiles.

Layout contract (what :meth:`FleetLedger.discover` walks)::

    <root>/fleet.jsonl          # the runner's own ledger (scenario/fleet)
    <root>/host0/run.jsonl      # host 0's attempt family + .sup sibling
    <root>/host1/run.jsonl
    ...

Each host is loaded through :func:`tpu_dist.obs.goodput.load_job_records`
— the SAME one job-loading rule ``ledger_report`` uses (attempt family in
order, supervisor sibling appended) — so the fleet plane is the per-host
plane N times plus aggregation, never a second parser. Torn trailing
lines and unreadable files are tolerated per host: one crashed host must
not take down the fleet report that exists to explain it.

Stdlib-only (the supervisor/classify imports are jax-free by
construction): runs on a login host, in CI, anywhere.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, List, Optional

from tpu_dist.obs.goodput import (fleet_accounting, job_accounting,
                                  load_job_records, split_attempts)


def _pctl(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of a sorted list — the repo convention
    (tools/ledger_report._pctl; duplicated here only because tools/ is
    not importable from library code, and pinned equal by the tests)."""
    if not xs:
        return None
    return xs[min(int(round(q / 100.0 * (len(xs) - 1))), len(xs) - 1)]


class FleetLedger:
    """The stitched fleet: ``{host_id: [records...]}`` plus the runner's
    own fleet ledger, with the rollup methods a report needs."""

    def __init__(self, hosts: Dict[int, List[dict]],
                 fleet_records: Optional[List[dict]] = None):
        self.hosts = dict(hosts)
        self.fleet_records = list(fleet_records or ())

    # -- discovery --------------------------------------------------------
    @classmethod
    def discover(cls, root: str, ledger_name: str = "run.jsonl",
                 warn=None) -> "FleetLedger":
        """Walk ``<root>/host<N>/<ledger_name>`` (plus the runner's
        ``<root>/fleet.jsonl``) and load every host's job. A host dir with
        no readable records still earns an (empty) entry — a host that
        died before its first ledger line is a finding, not a KeyError."""
        hosts: Dict[int, List[dict]] = {}
        for d in sorted(glob.glob(os.path.join(glob.escape(root), "host*"))):
            m = re.fullmatch(r"host(\d+)", os.path.basename(d))
            if not m or not os.path.isdir(d):
                continue
            h = int(m.group(1))
            base = os.path.join(d, ledger_name)
            hosts[h] = (load_job_records(base, warn=warn)
                        if os.path.exists(base) else [])
        fleet_path = os.path.join(root, "fleet.jsonl")
        fleet = (load_job_records(fleet_path, discover=False, warn=warn)
                 if os.path.exists(fleet_path) else [])
        return cls(hosts, fleet)

    # -- clock normalization ---------------------------------------------
    def t0(self) -> Optional[float]:
        """The fleet epoch: the earliest timestamp anywhere (run_start
        preferred — a sup sibling's scale event can predate the first
        child's run_start only by supervisor startup noise)."""
        starts = [r["ts"] for recs in self.hosts.values() for r in recs
                  if r.get("event") == "run_start"
                  and r.get("ts") is not None]
        if starts:
            return min(starts)
        everything = [r.get("ts") for recs in self.hosts.values()
                      for r in recs if r.get("ts") is not None]
        everything += [r.get("ts") for r in self.fleet_records
                       if r.get("ts") is not None]
        return min(everything) if everything else None

    def merged(self) -> List[dict]:
        """One clock-normalized fleet stream: every record copied with
        ``host`` stamped and ``t_rel`` (seconds since the fleet epoch)
        attached, host streams appended in host order — NOT
        ts-interleaved, for the same reason the sup sibling is appended
        (run_start boundaries are load-bearing for the per-attempt math);
        time-ordered consumers sort on ``t_rel`` themselves."""
        t0 = self.t0() or 0.0
        out = []
        for h in sorted(self.hosts):
            for r in self.hosts[h]:
                rec = dict(r)
                rec["host"] = h
                if rec.get("ts") is not None:
                    rec["t_rel"] = round(rec["ts"] - t0, 6)
                out.append(rec)
        return out

    # -- rollups ----------------------------------------------------------
    def scenario(self) -> Optional[dict]:
        for r in self.fleet_records:
            if r.get("event") == "scenario":
                return r
        return None

    def accounting(self) -> Optional[dict]:
        """Per-host :func:`job_accounting` aggregated by
        :func:`fleet_accounting`: the goodput half of the fleet report."""
        jobs = {h: job_accounting(split_attempts(recs))
                for h, recs in self.hosts.items() if recs}
        return fleet_accounting(jobs)

    def restart_classes(self) -> Dict[int, List[str]]:
        """Per-host attempt classification, from records alone (the
        report-side mode of ``classify_attempt``) — compared EXACTLY
        against the scenario's own prediction in CI."""
        from tpu_dist.parallel.supervisor import classify_attempt

        out: Dict[int, List[str]] = {}
        for h, recs in self.hosts.items():
            # the sup sibling's scale events ride appended after the last
            # attempt; they are not an attempt and must not classify as one
            own = [r for r in recs if r.get("event") != "scale"]
            out[h] = [classify_attempt(att) for att in split_attempts(own)
                      if att]
        return out

    def restart_histogram(self) -> Dict[str, int]:
        hist: Dict[str, int] = {}
        for classes in self.restart_classes().values():
            for cls in classes:
                hist[cls] = hist.get(cls, 0) + 1
        return hist

    def slo_breaches(self) -> int:
        return sum(1 for recs in self.hosts.values() for r in recs
                   if r.get("event") == "slo")

    def elasticity(self) -> List[dict]:
        """Fleet-wide ``scale`` timeline: every host's scale events (sup
        siblings included — load_job_records appended them) in fleet-clock
        order, each stamped with its host."""
        t0 = self.t0() or 0.0
        rows = []
        for h, recs in self.hosts.items():
            for r in recs:
                if r.get("event") != "scale":
                    continue
                rows.append({"host": h,
                             "t_rel": round((r.get("ts") or t0) - t0, 6),
                             **{k: r.get(k) for k in
                                ("action", "processes", "epoch", "hosts",
                                 "step", "world_from", "shed",
                                 "decision")}})
        rows.sort(key=lambda r: (r["t_rel"], r["host"]))
        return rows

    def autoscale(self) -> Optional[dict]:
        """The decision audit: every ``scale_decision`` the capacity
        monitor emitted (runner fleet ledger), each joined with the scale
        event(s) the supervisor attributed to it (``decision`` stamp) and
        the ``applied`` follow-up carrying the retuned plan hash. The
        acceptance invariant is the pairing: ``unattributed_scales == 0``
        and every decision's ``scale_events == 1`` means capacity never
        moved except under an auditable decision. ``None`` when the run
        had no autoscaling (fixed-capacity fleets stay unchanged)."""
        decisions = [r for r in self.fleet_records
                     if r.get("event") == "scale_decision"]
        if not decisions:
            return None
        t0 = self.t0() or 0.0
        scales = self.elasticity()
        applied = []
        for h, recs in self.hosts.items():
            for r in recs:
                if r.get("event") != "applied":
                    continue
                applied.append({"host": h,
                                "t_rel": round((r.get("ts") or t0) - t0, 6),
                                **{k: r.get(k) for k in
                                   ("decision", "action", "processes",
                                    "epoch", "plan_hash")}})
        rows = []
        for d in decisions:
            did = d.get("decision")
            match = [s for s in scales if s.get("decision") == did]
            app = [a for a in applied if a.get("decision") == did]
            t_rel = round((d.get("ts") or t0) - t0, 6)
            rows.append({
                "decision": did, "t_rel": t_rel, "tick": d.get("tick"),
                **{k: d.get(k) for k in
                   ("direction", "hosts_from", "target_hosts", "signal",
                    "value", "threshold", "window_ticks", "bundle")},
                "scale_events": len(match),
                "lag_s": (round(match[0]["t_rel"] - t_rel, 6)
                          if match else None),
                "applied": app[0] if app else None})
        rows.sort(key=lambda r: (r["t_rel"], r["decision"] or ""))
        traces = self.traces()
        return {
            "decisions": rows,
            "paired": sum(1 for r in rows if r["scale_events"] == 1),
            # only MEMBERSHIP actions need attribution: drains/snapshots
            # are per-host mechanics, not capacity changes
            "unattributed_scales": sum(
                1 for s in scales
                if s.get("action") in ("shrink", "expand")
                and s.get("decision") is None),
            "applied_with_plan_hash": sum(
                1 for r in rows
                if (r["applied"] or {}).get("plan_hash") is not None),
            "shed_lost": sum(1 for tr in traces.values()
                             if tr["sheds"] and not tr["completed"]),
        }

    def per_tenant(self) -> Dict[str, dict]:
        """Per-tenant serving percentiles over the fleet's ``request``
        events (the worker stamps ``tenant`` on each): queue wait and
        TTFT p50/p99, completed counts and generated tokens — the
        many-workloads-one-fleet accounting ROADMAP item 4 asks for."""
        by_tenant: Dict[str, List[dict]] = {}
        for recs in self.hosts.values():
            for r in recs:
                if r.get("event") != "request":
                    continue
                by_tenant.setdefault(str(r.get("tenant") or "?"),
                                     []).append(r)
        out = {}
        for tenant, rs in sorted(by_tenant.items()):
            waits = sorted(r["queue_wait_s"] for r in rs
                           if r.get("queue_wait_s") is not None)
            ttfts = sorted(r["ttft_s"] for r in rs
                           if r.get("ttft_s") is not None)
            out[tenant] = {
                "requests": len(rs),
                "tokens": sum(r.get("tokens") or 0 for r in rs),
                "queue_wait_s": {"p50": _pctl(waits, 50),
                                 "p99": _pctl(waits, 99)},
                "ttft_s": {"p50": _pctl(ttfts, 50), "p99": _pctl(ttfts, 99)}}
        return out

    def traces(self) -> Dict[str, dict]:
        """Cross-host request traces, stitched by trace_id equality alone
        (obs.reqtrace derives host-independent ids, so hosts that never
        exchanged a byte mint the same id for the same rid) — the request
        analog of the goodput stitch. Returns trace_id -> a summary row:
        which hosts touched the request, span/shed/readmit counts, and
        whether ANY host completed it (a root ``request`` span exists).
        The heavy per-trace machinery (waterfalls, attribution, exemplars)
        lives in tools/request_report.py over :meth:`merged`."""
        from tpu_dist.obs import reqtrace

        out = {}
        for tid, tr in sorted(reqtrace.traces(self.merged()).items()):
            names = [s.get("name") for s in tr["spans"]]
            out[tid] = {
                "rid": tr["rid"],
                "hosts": tr["hosts"],
                "spans": len(tr["spans"]),
                "sheds": sum(1 for n in names if n == "shed"),
                "readmits": sum(1 for n in names if n == "readmit"),
                "completed": bool(tr["roots"]),
            }
        return out

    def serving_totals(self) -> dict:
        completed = rejected = 0
        for recs in self.hosts.values():
            for r in recs:
                if r.get("event") == "request":
                    completed += 1
                elif r.get("event") == "admit" and not r.get("accepted"):
                    rejected += 1
        return {"completed": completed, "rejected": rejected}

    def hosts_live_timeline(self) -> List[dict]:
        """The runner's periodic ``fleet`` snapshots (hosts_live over
        fleet time) — the scrape-series view, read back from the ledger."""
        t0 = self.t0() or 0.0
        return [{"t_rel": round((r.get("ts") or t0) - t0, 6),
                 "hosts_live": r.get("hosts_live"),
                 "slo_breaches": r.get("slo_breaches"),
                 "tick": r.get("tick")}
                for r in self.fleet_records if r.get("event") == "fleet"]

    def report(self) -> dict:
        """The one machine-readable fleet dict (tools/fleet_report --json
        prints it verbatim; the CI acceptance asserts into it)."""
        acct = self.accounting()
        scenario = self.scenario()
        return {
            "hosts": sorted(self.hosts),
            "scenario": ({k: scenario.get(k) for k in
                          ("name", "seed", "hosts", "ticks", "tick_s")}
                         if scenario else None),
            "fleet": acct,
            "restart_classes": {str(h): cls for h, cls in
                                sorted(self.restart_classes().items())},
            "restart_histogram": self.restart_histogram(),
            "slo_breaches": self.slo_breaches(),
            "elasticity": self.elasticity(),
            "per_tenant": self.per_tenant(),
            "serving": self.serving_totals(),
            "traces": self.traces(),
            "hosts_live": self.hosts_live_timeline(),
            "autoscale": self.autoscale(),
        }
