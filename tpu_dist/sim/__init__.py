"""tpu_dist.sim — trace-driven fleet simulation on one CPU box.

The ROADMAP north star claims "heavy traffic from millions of users"; this
package is what turns that claim into a regression-gated number. Every
piece it composes already exists one-process-at-a-time — deterministic
fault injection (:mod:`tpu_dist.obs.faults`), goodput/SLO accounting
(:mod:`tpu_dist.obs.goodput`), the serve-trace replay
(:mod:`tpu_dist.engine.serve`), the elastic supervisor + consensus
(:mod:`tpu_dist.parallel.supervisor`) — and the simulator runs them
*together*: N supervised serve-engine processes on virtual CPU devices
under one declarative **scenario schedule** (diurnal Poisson traffic,
preemption waves, slow-host skew, host returns), each writing its normal
attempt ledger plus the supervisor ``.sup.jsonl`` sibling.

Modules (attribute access is lazy, PEP 562, for the same reason as
:mod:`tpu_dist.parallel`: the scenario grammar and the fleet stitcher must
import on a login/CI host with no jax installed):

* :mod:`~tpu_dist.sim.scenario` — the schedule grammar + deterministic
  compiler (stdlib-only; same schedule + seed -> identical admitted
  requests and injected faults);
* :mod:`~tpu_dist.sim.fleet` — the :class:`FleetLedger` stitcher: cross-
  host discovery (the fleet analog of ``ledger_report``'s attempt
  discovery), clock normalization, and the fleet accounting rollup
  (stdlib-only);
* :mod:`~tpu_dist.sim.runner` — :class:`FleetSim`, the driver that
  launches one :class:`~tpu_dist.parallel.supervisor.Supervisor` per
  virtual host and executes the scenario's consensus actions (jax-free
  itself; only the worker children import jax);
* :mod:`~tpu_dist.sim.worker` — the child process entry
  (``python -m tpu_dist.sim.worker``): a tiny TransformerLM behind a
  :class:`~tpu_dist.engine.serve.ServeEngine`, replaying its host's
  arrival slice in paced tick time.

``tools/fleet_report.py`` renders the stitched fleet (goodput summing to
aggregate wall, restart-class histogram, SLO breaches, elasticity
timeline, per-tenant percentiles); ``tests/test_fleet.py`` pins the CI
acceptance scenario in ``scripts/fleet_ci.json``.
"""

import importlib

_LAZY = {
    "scenario": None,
    "fleet": None,
    "runner": None,
    "worker": None,
    # scenario grammar
    "Scenario": "scenario", "HostPlan": "scenario", "Arrival": "scenario",
    "load_scenario": "scenario",
    # fleet stitcher
    "FleetLedger": "fleet",
    # driver
    "FleetSim": "runner",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    target = _LAZY.get(name)
    if name not in _LAZY:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    if target is None:
        return importlib.import_module(f"{__name__}.{name}")
    module = importlib.import_module(f"{__name__}.{target}")
    return getattr(module, name)


def __dir__():
    return __all__
