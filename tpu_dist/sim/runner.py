"""FleetSim: drive one scenario across N supervised virtual hosts.

The driver is jax-free by the same construction as the supervisor it
composes (only the worker children import jax): per host it runs one
:class:`~tpu_dist.parallel.supervisor.Supervisor` (in a thread) around
``python -m tpu_dist.sim.worker``, exports that host's compiled fault
spec as ``TPU_DIST_FAULTS``, and gives the scenario's ``consensus_host``
a real :class:`~tpu_dist.parallel.consensus.ConsensusDir` — so a
preemption wave's ``leave`` and the later ``register`` (the host return)
drive the PR 12 membership path for real: epoch bump, mid-attempt
SIGTERM, rescale relaunch, ``shrink``/``expand`` scale events in the
``.sup.jsonl`` sibling.

Scheduling is on the **fleet clock**: consensus actions fire when every
live (not scheduled-down, not finished) host's published tick
(``<ledger>.tick`` sidecar) has reached the action's tick — tick gating
both orders the actions deterministically w.r.t. the traffic and proves
the gated hosts are actually serving (a host mid-restart holds the clock
until it resumes). A wall deadline backstops a wedged fleet.

Outputs under ``out_dir``::

    scenario.json   # the normalized schedule (self-contained artifact)
    fleet.jsonl     # the runner's own ledger: scenario + fleet events
    host<N>/        # each host's attempt ledgers + .sup sibling + sidecars
    report.json     # the stitched FleetLedger report
    headline.json   # bench_track-shaped point carrying fleet.goodput_ratio

``python -m tpu_dist.sim.runner --scenario scripts/fleet_ci.json --out
/tmp/fleet`` is the CLI; ``tools/fleet_report.py`` renders the result.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from tpu_dist.obs.autoscale import (AutoscalePolicy, CapacityMonitor,
                                    LedgerTailer, emit_decision)
from tpu_dist.obs.ledger import Ledger
from tpu_dist.obs.metrics import MetricsRegistry, metrics_ledger_sink
from tpu_dist.parallel.consensus import ConsensusDir
from tpu_dist.parallel.supervisor import RestartPolicy, Supervisor
from tpu_dist.sim.scenario import (Scenario, compile_host_plans,
                                   load_scenario)


_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _scrubbed_env(extra: Dict[str, str]) -> Dict[str, str]:
    """A child env with no inherited TPU_DIST/XLA state (the test
    harness's own knobs must not leak into the simulated hosts).
    ``python -m tpu_dist.sim.worker`` must resolve from any cwd, so the
    package root rides PYTHONPATH."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("TPU_DIST") and k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO_ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.update(extra)
    return env


class FleetSim:
    """One scenario run (see module docstring). ``scenario`` may be a
    path or a parsed :class:`~tpu_dist.sim.scenario.Scenario`."""

    def __init__(self, scenario, out_dir: str, *,
                 python: str = sys.executable,
                 stall_timeout_s: float = 300.0,
                 max_restarts: int = 6):
        self.sc: Scenario = (scenario if isinstance(scenario, Scenario)
                             else load_scenario(scenario))
        self.out = out_dir
        self.python = python
        self.stall_timeout_s = stall_timeout_s
        self.max_restarts = max_restarts
        self.plans, self.actions = compile_host_plans(self.sc)
        self.results: Dict[int, object] = {}
        self._sups: Dict[int, Supervisor] = {}
        self._breaches = 0
        # autoscaling (round 20, obs.autoscale): standby hosts start
        # parked; the CapacityMonitor, fed by tailing every host ledger,
        # decides when they join (and when elastic hosts leave again)
        auto = self.sc.autoscale or {}
        pol = auto.get("policy")
        if isinstance(pol, str) and not os.path.exists(pol):
            # checked-in scenarios name their policy repo-relative
            # (scripts/autoscale_policy.json) — resolve it from anywhere
            pol = os.path.join(_REPO_ROOT, pol)
        self.policy: Optional[AutoscalePolicy] = (
            None if pol is None else
            AutoscalePolicy.from_doc(pol) if isinstance(pol, dict)
            else AutoscalePolicy.load(pol))
        self.standby = set(self.sc.standby_hosts())
        self.decisions: List[dict] = []

    # -- wiring -----------------------------------------------------------
    def _host_dir(self, h: int) -> str:
        return os.path.join(self.out, f"host{h}")

    def _ledger_path(self, h: int) -> str:
        return os.path.join(self._host_dir(h), "run.jsonl")

    def _build_supervisor(self, h: int, cdir: str,
                          scenario_path: str) -> Supervisor:
        plan = self.plans[h]
        sc = self.sc
        env = _scrubbed_env({
            "TPU_DIST_NUM_PROCESSES": str(sc.hosts),
            "TPU_DIST_PROCESS_ID": str(h),
            **({"TPU_DIST_FAULTS": plan.faults} if plan.faults else {}),
        })
        # a preempted-with-return host must stay genuinely absent until
        # its return tick: the first restart's backoff covers the gap
        holdoff = plan.restart_holdoff_ticks * sc.tick_s * plan.skew
        policy = RestartPolicy(
            max_restarts=self.max_restarts,
            backoff_base_s=max(holdoff, 0.2),
            backoff_max_s=max(holdoff * 2, 30.0),
            stall_timeout_s=self.stall_timeout_s,
            # the sim's SIGTERM faults are the schedule, not host loss
            shrink_on_host_loss=False)
        consensus = (ConsensusDir(cdir, h, planned=self._planned(),
                                  lease_s=3600.0)
                     if h == sc.consensus_host else None)
        # with a policy configured, the consensus host re-tunes the plan
        # deterministically at every new world size (the PR 15
        # retune-on-rescale residue) and stamps its hash into the
        # decision's `applied` follow-up event
        retune = None
        if consensus is not None and self.policy is not None:
            retune = {"device_kind": "TPU v5 lite",
                      "devices_per_host": max(sc.worker_devices, 1),
                      "plan_dir": os.path.join(self.out, "plans")}
        return Supervisor(
            [self.python, "-m", "tpu_dist.sim.worker",
             "--scenario", scenario_path, "--host", str(h)],
            ledger=self._ledger_path(h), policy=policy, env=env,
            poll_s=0.1, consensus=consensus, consensus_poll_s=0.25,
            retune=retune)

    def _planned(self) -> int:
        """The baseline (planned) world size: standby hosts are extra
        elastic capacity ABOVE plan, so the consensus host's first
        resolve at the parked-standby world must not read as a shrink."""
        return self.sc.hosts - (len(self.standby) if self.policy else 0)

    def _read_tick(self, h: int) -> int:
        try:
            with open(self._ledger_path(h) + ".tick") as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    # -- the autoscaling loop (round 20, obs.autoscale) -------------------
    def _autoscale_step(self, monitor: CapacityMonitor,
                        tailer: LedgerTailer, clock: int, live: list,
                        peers: Dict[int, ConsensusDir], parked: set,
                        elastic: set, gone: set, down: set,
                        fleet_ledger: Ledger, start_host) -> None:
        """Feed the monitor from every host's growing ledgers, evaluate
        the policy at the fleet clock, and EXECUTE any decision through
        the machinery that already owns capacity: consensus membership
        (register a parked standby / leave an elastic host) whose epoch
        bump the consensus-host supervisor turns into the shrink/expand
        rescale — stamped with the decision id for the 1:1 pairing."""
        sc = self.sc
        paths = sorted(glob.glob(os.path.join(
            glob.escape(self.out), "host*", "run*.jsonl")))
        for rec in tailer.poll(paths):
            monitor.observe(rec)
        # capacity is what decisions CONTROL (parked standby out, removed
        # hosts out) — not thread liveness: a host finishing its trace is
        # not a scale-down, and must not re-open headroom under the max
        capacity = sc.hosts - len(parked) - len(gone)
        dec = monitor.evaluate(tick=clock, hosts_live=capacity)
        if dec is None:
            return
        emit_decision(fleet_ledger, dec)
        self.decisions.append(dec)
        n = dec["target_hosts"] - dec["hosts_from"]
        csup = self._sups.get(sc.consensus_host)
        if dec["direction"] == "up":
            for h in sorted(parked)[:max(n, 0)]:
                # seed a FRESH cursor at the fleet clock: the new host
                # serves from now on (pre-start arrivals were never
                # admitted anywhere) and publishes its tick immediately
                # so the fleet clock never snaps back to zero
                base = self._ledger_path(h)
                with open(base + ".cursor.json", "w") as f:
                    json.dump({"tick": clock, "done": [], "fresh": True}, f)
                with open(base + ".tick", "w") as f:
                    f.write(f"{clock}\n")
                if csup is not None:
                    csup.autoscale_decision = dec["decision"]
                peers[h].register()
                parked.discard(h)
                elastic.add(h)
                start_host(h)
        else:
            cands = sorted((h for h in elastic
                            if h in live and h != sc.consensus_host),
                           reverse=True)
            for h in cands[:max(-n, 0)]:
                if csup is not None:
                    csup.autoscale_decision = dec["decision"]
                peers[h].leave()
                down.add(h)      # the clock must not wait on it
                gone.add(h)      # permanently out: sheds hand off
                elastic.discard(h)
                sup = self._sups.get(h)
                if sup is not None:
                    sup.request_stop()

    def _handoff_step(self, gone: set, handoff_done: set,
                      live: list) -> None:
        """Once a permanently-removed host's drain cursor lands (it
        carries the `shed` descriptors), append them to the lowest
        surviving host's handoff sidecar — the worker re-admits each at
        its scheduled tick under a `readmit` span, so no shed request is
        lost and the request stays one trace across hosts."""
        for h in sorted(gone - handoff_done):
            cursor = self._ledger_path(h) + ".cursor.json"
            try:
                with open(cursor) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if "shed" not in doc:
                continue        # not drained yet — retry next poll
            handoff_done.add(h)
            shed = [e for e in (doc.get("shed") or ())
                    if isinstance(e, dict) and e.get("rid") is not None]
            survivors = [s for s in live if s != h]
            if not shed or not survivors:
                continue
            dst = self._ledger_path(min(survivors)) + ".handoff.jsonl"
            try:
                with open(dst, "a") as f:
                    for e in shed:
                        f.write(json.dumps({**e, "from_host": h}) + "\n")
            except OSError:
                pass    # the report will show the loss — never crash

    # -- the run ----------------------------------------------------------
    def run(self, timeout_s: Optional[float] = None) -> dict:
        sc = self.sc
        os.makedirs(self.out, exist_ok=True)
        for h in range(sc.hosts):
            os.makedirs(self._host_dir(h), exist_ok=True)
        scenario_path = os.path.join(self.out, "scenario.json")
        with open(scenario_path, "w") as f:
            json.dump(sc.to_doc(), f, indent=1)
        if timeout_s is None:
            # paced trace + a compile/restart allowance per expected launch
            launches = sc.hosts + sum(
                len(p.expected_classes) - 1 for p in self.plans.values())
            timeout_s = sc.wall_estimate_s() * 4 + 90.0 * launches + 120.0

        fleet_ledger = Ledger(os.path.join(self.out, "fleet.jsonl"))
        registry = MetricsRegistry()
        fleet_ledger.add_sink(metrics_ledger_sink(registry))
        fleet_ledger.emit("scenario", name=sc.name, seed=sc.seed,
                          hosts=sc.hosts, ticks=sc.ticks,
                          tick_s=sc.tick_s, consensus_host=sc.consensus_host,
                          events=[dict(ev) for ev in sc.events])

        cdir = os.path.join(self.out, "consensus")
        peers = {h: ConsensusDir(cdir, h, planned=self._planned(),
                                 lease_s=3600.0)
                 for h in range(sc.hosts)}
        parked: set = set(self.standby) if self.policy is not None else set()
        for h, c in peers.items():
            if h not in parked:
                c.register()

        threads: Dict[int, threading.Thread] = {}

        def _start_host(h: int) -> None:
            sup = self._build_supervisor(h, cdir, scenario_path)
            self._sups[h] = sup

            def _run(h=h, sup=sup):
                self.results[h] = sup.run()

            t = threading.Thread(target=_run, name=f"fleet-sup-{h}",
                                 daemon=True)
            threads[h] = t
            t.start()

        for h in range(sc.hosts):
            if h not in parked:
                _start_host(h)

        monitor = (CapacityMonitor(self.policy,
                                   hosts_live=sc.hosts - len(parked))
                   if self.policy is not None else None)
        tailer = LedgerTailer()
        elastic: set = set()        # hosts an up-decision admitted
        gone: set = set()           # hosts a down-decision removed for good
        handoff_done: set = set()
        pending = list(self.actions)
        down: set = set()
        t_start = time.monotonic()
        force_after = t_start + timeout_s * 0.75
        last_fleet_emit = 0.0
        while any(t.is_alive() for t in threads.values()):
            now = time.monotonic()
            if now - t_start > timeout_s:
                for sup in self._sups.values():
                    sup.request_stop()
                break
            # fleet clock: every live gated host must have reached the tick
            live = [h for h, t in threads.items()
                    if t.is_alive() and h not in down]
            clock = min((self._read_tick(h) for h in live), default=None)
            while pending and ((clock is not None
                                and clock >= pending[0].tick)
                               or now > force_after or not live):
                act = pending.pop(0)
                if act.action == "leave":
                    peers[act.host].leave()
                    down.add(act.host)
                elif act.action == "register":
                    peers[act.host].register()
                    down.discard(act.host)
            if monitor is not None and clock is not None:
                self._autoscale_step(monitor, tailer, clock, live, peers,
                                     parked, elastic, gone, down,
                                     fleet_ledger, _start_host)
                self._handoff_step(gone, handoff_done, live)
            if now - last_fleet_emit >= 1.0:
                last_fleet_emit = now
                fleet_ledger.emit("fleet", hosts_live=len(live),
                                  goodput_ratio=None, slo_breaches=None,
                                  final=False, tick=clock)
            time.sleep(0.1)
        for t in threads.values():
            t.join(timeout=max(timeout_s * 0.25, 30.0))

        from tpu_dist.sim.fleet import FleetLedger

        stitched = FleetLedger.discover(self.out)
        report = stitched.report()
        report["supervisors"] = {
            str(h): {"status": getattr(r, "status", "unjoined"),
                     "attempts": [a.failure_class
                                  for a in getattr(r, "attempts", ())]}
            for h, r in sorted(self.results.items())}
        acct = report.get("fleet") or {}
        fleet_ledger.emit("fleet", hosts_live=0,
                          goodput_ratio=acct.get("goodput_ratio"),
                          slo_breaches=report.get("slo_breaches"),
                          final=True)
        fleet_ledger.close()
        with open(os.path.join(self.out, "report.json"), "w") as f:
            json.dump(report, f, indent=1, default=str)
        # the bench_track-shaped point: fleet.goodput_ratio is the gated
        # number (tools/bench_track.py abstains on pre-fleet history);
        # autoscale_lag_ticks — burst onset to the first up decision —
        # rides along as the lower-is-better reaction-time gate
        burst0 = min((int(ev["tick"]) for ev in sc.events
                      if ev["type"] == "burst"), default=None)
        up0 = next((d["tick"] for d in self.decisions
                    if d["direction"] == "up"), None)
        lag = (up0 - burst0 if burst0 is not None and up0 is not None
               else None)
        with open(os.path.join(self.out, "headline.json"), "w") as f:
            json.dump({"metric": "fleet_sim_goodput",
                       "value": acct.get("goodput_ratio"),
                       "unit": "ratio",
                       "fleet": {"goodput_ratio": acct.get("goodput_ratio"),
                                 "slo_breaches": report.get("slo_breaches"),
                                 "hosts": sc.hosts,
                                 **({"autoscale_lag_ticks": lag,
                                     "autoscale_decisions":
                                         len(self.decisions)}
                                    if self.policy is not None else {})}},
                      f, indent=1)
        return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", required=True,
                    help="scenario JSON/YAML (tpu_dist.sim.scenario)")
    ap.add_argument("--out", required=True, help="fleet output directory")
    ap.add_argument("--timeout-s", type=float, default=0.0,
                    help="wall bound for the whole fleet (0 = derived "
                    "from the schedule)")
    ap.add_argument("--json", action="store_true",
                    help="print the fleet report JSON on stdout")
    args = ap.parse_args(argv)
    sim = FleetSim(args.scenario, args.out)
    report = sim.run(timeout_s=args.timeout_s or None)
    if args.json:
        print(json.dumps(report, default=str))
    else:
        acct = report.get("fleet") or {}
        print(f"fleet '{(report.get('scenario') or {}).get('name')}': "
              f"{len(report['hosts'])} host(s), goodput ratio "
              f"{acct.get('goodput_ratio')}, "
              f"{report.get('slo_breaches')} SLO breach(es), "
              f"restart histogram {report.get('restart_histogram')} — "
              f"full report: {os.path.join(args.out, 'report.json')}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
