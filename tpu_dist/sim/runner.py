"""FleetSim: drive one scenario across N supervised virtual hosts.

The driver is jax-free by the same construction as the supervisor it
composes (only the worker children import jax): per host it runs one
:class:`~tpu_dist.parallel.supervisor.Supervisor` (in a thread) around
``python -m tpu_dist.sim.worker``, exports that host's compiled fault
spec as ``TPU_DIST_FAULTS``, and gives the scenario's ``consensus_host``
a real :class:`~tpu_dist.parallel.consensus.ConsensusDir` — so a
preemption wave's ``leave`` and the later ``register`` (the host return)
drive the PR 12 membership path for real: epoch bump, mid-attempt
SIGTERM, rescale relaunch, ``shrink``/``expand`` scale events in the
``.sup.jsonl`` sibling.

Scheduling is on the **fleet clock**: consensus actions fire when every
live (not scheduled-down, not finished) host's published tick
(``<ledger>.tick`` sidecar) has reached the action's tick — tick gating
both orders the actions deterministically w.r.t. the traffic and proves
the gated hosts are actually serving (a host mid-restart holds the clock
until it resumes). A wall deadline backstops a wedged fleet.

Outputs under ``out_dir``::

    scenario.json   # the normalized schedule (self-contained artifact)
    fleet.jsonl     # the runner's own ledger: scenario + fleet events
    host<N>/        # each host's attempt ledgers + .sup sibling + sidecars
    report.json     # the stitched FleetLedger report
    headline.json   # bench_track-shaped point carrying fleet.goodput_ratio

``python -m tpu_dist.sim.runner --scenario scripts/fleet_ci.json --out
/tmp/fleet`` is the CLI; ``tools/fleet_report.py`` renders the result.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from tpu_dist.obs.ledger import Ledger
from tpu_dist.obs.metrics import MetricsRegistry, metrics_ledger_sink
from tpu_dist.parallel.consensus import ConsensusDir
from tpu_dist.parallel.supervisor import RestartPolicy, Supervisor
from tpu_dist.sim.scenario import (Scenario, compile_host_plans,
                                   load_scenario)


_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _scrubbed_env(extra: Dict[str, str]) -> Dict[str, str]:
    """A child env with no inherited TPU_DIST/XLA state (the test
    harness's own knobs must not leak into the simulated hosts).
    ``python -m tpu_dist.sim.worker`` must resolve from any cwd, so the
    package root rides PYTHONPATH."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("TPU_DIST") and k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO_ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.update(extra)
    return env


class FleetSim:
    """One scenario run (see module docstring). ``scenario`` may be a
    path or a parsed :class:`~tpu_dist.sim.scenario.Scenario`."""

    def __init__(self, scenario, out_dir: str, *,
                 python: str = sys.executable,
                 stall_timeout_s: float = 300.0,
                 max_restarts: int = 6):
        self.sc: Scenario = (scenario if isinstance(scenario, Scenario)
                             else load_scenario(scenario))
        self.out = out_dir
        self.python = python
        self.stall_timeout_s = stall_timeout_s
        self.max_restarts = max_restarts
        self.plans, self.actions = compile_host_plans(self.sc)
        self.results: Dict[int, object] = {}
        self._sups: Dict[int, Supervisor] = {}
        self._breaches = 0

    # -- wiring -----------------------------------------------------------
    def _host_dir(self, h: int) -> str:
        return os.path.join(self.out, f"host{h}")

    def _ledger_path(self, h: int) -> str:
        return os.path.join(self._host_dir(h), "run.jsonl")

    def _build_supervisor(self, h: int, cdir: str,
                          scenario_path: str) -> Supervisor:
        plan = self.plans[h]
        sc = self.sc
        env = _scrubbed_env({
            "TPU_DIST_NUM_PROCESSES": str(sc.hosts),
            "TPU_DIST_PROCESS_ID": str(h),
            **({"TPU_DIST_FAULTS": plan.faults} if plan.faults else {}),
        })
        # a preempted-with-return host must stay genuinely absent until
        # its return tick: the first restart's backoff covers the gap
        holdoff = plan.restart_holdoff_ticks * sc.tick_s * plan.skew
        policy = RestartPolicy(
            max_restarts=self.max_restarts,
            backoff_base_s=max(holdoff, 0.2),
            backoff_max_s=max(holdoff * 2, 30.0),
            stall_timeout_s=self.stall_timeout_s,
            # the sim's SIGTERM faults are the schedule, not host loss
            shrink_on_host_loss=False)
        consensus = (ConsensusDir(cdir, h, planned=sc.hosts, lease_s=3600.0)
                     if h == sc.consensus_host else None)
        return Supervisor(
            [self.python, "-m", "tpu_dist.sim.worker",
             "--scenario", scenario_path, "--host", str(h)],
            ledger=self._ledger_path(h), policy=policy, env=env,
            poll_s=0.1, consensus=consensus, consensus_poll_s=0.25)

    def _read_tick(self, h: int) -> int:
        try:
            with open(self._ledger_path(h) + ".tick") as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    # -- the run ----------------------------------------------------------
    def run(self, timeout_s: Optional[float] = None) -> dict:
        sc = self.sc
        os.makedirs(self.out, exist_ok=True)
        for h in range(sc.hosts):
            os.makedirs(self._host_dir(h), exist_ok=True)
        scenario_path = os.path.join(self.out, "scenario.json")
        with open(scenario_path, "w") as f:
            json.dump(sc.to_doc(), f, indent=1)
        if timeout_s is None:
            # paced trace + a compile/restart allowance per expected launch
            launches = sc.hosts + sum(
                len(p.expected_classes) - 1 for p in self.plans.values())
            timeout_s = sc.wall_estimate_s() * 4 + 90.0 * launches + 120.0

        fleet_ledger = Ledger(os.path.join(self.out, "fleet.jsonl"))
        registry = MetricsRegistry()
        fleet_ledger.add_sink(metrics_ledger_sink(registry))
        fleet_ledger.emit("scenario", name=sc.name, seed=sc.seed,
                          hosts=sc.hosts, ticks=sc.ticks,
                          tick_s=sc.tick_s, consensus_host=sc.consensus_host,
                          events=[dict(ev) for ev in sc.events])

        cdir = os.path.join(self.out, "consensus")
        peers = {h: ConsensusDir(cdir, h, planned=sc.hosts, lease_s=3600.0)
                 for h in range(sc.hosts)}
        for c in peers.values():
            c.register()

        threads: Dict[int, threading.Thread] = {}
        for h in range(sc.hosts):
            sup = self._build_supervisor(h, cdir, scenario_path)
            self._sups[h] = sup

            def _run(h=h, sup=sup):
                self.results[h] = sup.run()

            t = threading.Thread(target=_run, name=f"fleet-sup-{h}",
                                 daemon=True)
            threads[h] = t
            t.start()

        pending = list(self.actions)
        down: set = set()
        t_start = time.monotonic()
        force_after = t_start + timeout_s * 0.75
        last_fleet_emit = 0.0
        while any(t.is_alive() for t in threads.values()):
            now = time.monotonic()
            if now - t_start > timeout_s:
                for sup in self._sups.values():
                    sup.request_stop()
                break
            # fleet clock: every live gated host must have reached the tick
            live = [h for h, t in threads.items()
                    if t.is_alive() and h not in down]
            clock = min((self._read_tick(h) for h in live), default=None)
            while pending and ((clock is not None
                                and clock >= pending[0].tick)
                               or now > force_after or not live):
                act = pending.pop(0)
                if act.action == "leave":
                    peers[act.host].leave()
                    down.add(act.host)
                elif act.action == "register":
                    peers[act.host].register()
                    down.discard(act.host)
            if now - last_fleet_emit >= 1.0:
                last_fleet_emit = now
                fleet_ledger.emit("fleet", hosts_live=len(live),
                                  goodput_ratio=None, slo_breaches=None,
                                  final=False)
            time.sleep(0.1)
        for t in threads.values():
            t.join(timeout=max(timeout_s * 0.25, 30.0))

        from tpu_dist.sim.fleet import FleetLedger

        stitched = FleetLedger.discover(self.out)
        report = stitched.report()
        report["supervisors"] = {
            str(h): {"status": getattr(r, "status", "unjoined"),
                     "attempts": [a.failure_class
                                  for a in getattr(r, "attempts", ())]}
            for h, r in sorted(self.results.items())}
        acct = report.get("fleet") or {}
        fleet_ledger.emit("fleet", hosts_live=0,
                          goodput_ratio=acct.get("goodput_ratio"),
                          slo_breaches=report.get("slo_breaches"),
                          final=True)
        fleet_ledger.close()
        with open(os.path.join(self.out, "report.json"), "w") as f:
            json.dump(report, f, indent=1, default=str)
        # the bench_track-shaped point: fleet.goodput_ratio is the gated
        # number (tools/bench_track.py abstains on pre-fleet history)
        with open(os.path.join(self.out, "headline.json"), "w") as f:
            json.dump({"metric": "fleet_sim_goodput",
                       "value": acct.get("goodput_ratio"),
                       "unit": "ratio",
                       "fleet": {"goodput_ratio": acct.get("goodput_ratio"),
                                 "slo_breaches": report.get("slo_breaches"),
                                 "hosts": sc.hosts}}, f, indent=1)
        return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", required=True,
                    help="scenario JSON/YAML (tpu_dist.sim.scenario)")
    ap.add_argument("--out", required=True, help="fleet output directory")
    ap.add_argument("--timeout-s", type=float, default=0.0,
                    help="wall bound for the whole fleet (0 = derived "
                    "from the schedule)")
    ap.add_argument("--json", action="store_true",
                    help="print the fleet report JSON on stdout")
    args = ap.parse_args(argv)
    sim = FleetSim(args.scenario, args.out)
    report = sim.run(timeout_s=args.timeout_s or None)
    if args.json:
        print(json.dumps(report, default=str))
    else:
        acct = report.get("fleet") or {}
        print(f"fleet '{(report.get('scenario') or {}).get('name')}': "
              f"{len(report['hosts'])} host(s), goodput ratio "
              f"{acct.get('goodput_ratio')}, "
              f"{report.get('slo_breaches')} SLO breach(es), "
              f"restart histogram {report.get('restart_histogram')} — "
              f"full report: {os.path.join(args.out, 'report.json')}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
