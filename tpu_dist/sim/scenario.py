"""Declarative fleet-scenario schedules + their deterministic compiler.

A scenario is one JSON (or YAML, when pyyaml is importable) document that
names everything a fleet run does: how many virtual hosts, how long (in
**ticks** — the scheduler-iteration unit the serve engine and the trace
replay already use, so schedules are machine-speed-independent), what the
traffic looks like (diurnal Poisson arrivals over weighted tenants with
mixed prompt/output lengths), and which operational events hit which host
at which tick (preemption waves, crashes, hangs, slow-host skew, traffic
bursts, host returns).

:func:`compile_host_plans` turns the document into per-host work: the
admitted-request arrival schedule, the ``TPU_DIST_FAULTS`` spec string
(:mod:`tpu_dist.obs.faults` grammar — the injection machinery is reused,
not reinvented), the pacing skew factor, and the fleet-level consensus
actions (``leave``/``register`` — the PR 12 membership path). The compile
is a pure function of (schedule, seed): same inputs -> byte-identical
arrivals and fault sequences, which is what lets CI assert exact event
counts (tests/test_fleet.py) and lets a report reader re-derive what a
run *should* have seen.

Stdlib-only by construction (``random.Random`` is a cross-platform-stable
Mersenne twister; no numpy, no jax): ``scripts/lint.sh`` imports this on
a bare host as a no-jax gate, the same contract as the supervisor and
consensus policy modules.
"""

from __future__ import annotations

import json
import math
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# operational event types a schedule may carry
EVENT_TYPES = ("preempt", "crash", "hang", "slow_host", "burst")

# rid namespace stride: request ids are unique fleet-wide by construction
# (host h's rids live in [h * stride, (h+1) * stride))
RID_STRIDE = 1_000_000


@dataclass(frozen=True)
class Tenant:
    """One traffic class: relative weight + prompt/output length ranges."""

    name: str
    weight: float = 1.0
    prompt: Tuple[int, int] = (4, 8)     # inclusive token-length range
    out: Tuple[int, int] = (2, 6)


@dataclass(frozen=True)
class Arrival:
    """One admitted request of the compiled schedule."""

    tick: int
    rid: int
    tenant: str
    prompt_len: int
    out_len: int


@dataclass(frozen=True)
class FleetAction:
    """One consensus-membership action the runner executes when the fleet
    clock (min live-host tick) reaches ``tick``."""

    tick: int
    action: str        # "leave" | "register"
    host: int


@dataclass
class HostPlan:
    """Everything one virtual host needs: its arrivals, its fault spec,
    its pacing skew, and (for preempted-with-return hosts) the restart
    hold-off that keeps it genuinely absent until its return tick."""

    host: int
    arrivals: List[Arrival] = field(default_factory=list)
    faults: str = ""
    skew: float = 1.0
    restart_holdoff_ticks: int = 0
    expected_classes: List[str] = field(default_factory=list)


@dataclass
class Scenario:
    """The parsed schedule (see module docstring for the grammar tour)."""

    name: str
    seed: int
    hosts: int
    ticks: int
    tick_s: float = 0.02
    consensus_host: int = 0
    model: Dict = field(default_factory=dict)
    serve: Dict = field(default_factory=dict)
    worker_devices: int = 1
    # traffic
    base_rate: float = 0.1       # mean arrivals/tick/host at the diurnal mean
    amplitude: float = 0.0       # diurnal swing as a fraction of base_rate
    period: int = 0              # diurnal period in ticks (0 = flat)
    phase: float = 0.0           # fraction of a period
    tenants: List[Tenant] = field(default_factory=list)
    events: List[Dict] = field(default_factory=list)
    # autoscaling (round 20, obs.autoscale): {"policy": <path or inline
    # policy doc>, "standby_hosts": [host, ...]} — standby hosts start
    # PARKED (no worker, not registered) and join only when a scale-up
    # decision admits them; None = fixed capacity (every host live)
    autoscale: Optional[Dict] = None

    def standby_hosts(self) -> List[int]:
        return sorted(int(h) for h in
                      ((self.autoscale or {}).get("standby_hosts") or ()))

    def rate(self, tick: int, host: int) -> float:
        """Mean arrivals for (tick, host): the diurnal curve plus any
        burst events covering this tick. Clamped at zero (a deep diurnal
        trough is an idle fleet, not a negative one)."""
        r = self.base_rate
        if self.period > 0 and self.amplitude:
            r *= 1.0 + self.amplitude * math.sin(
                2.0 * math.pi * (tick / self.period + self.phase))
        for ev in self.events:
            if ev["type"] != "burst":
                continue
            if ev.get("hosts") is not None and host not in ev["hosts"]:
                continue
            if ev["tick"] <= tick < ev["tick"] + ev.get("ticks", 1):
                r += ev.get("rate", 0.0)
        return max(r, 0.0)

    def to_doc(self) -> Dict:
        """The JSON-able document form (round-trips through
        :func:`parse_scenario`): the runner re-writes the scenario beside
        its outputs so a fleet directory is self-contained."""
        return {
            "name": self.name, "seed": self.seed, "hosts": self.hosts,
            "ticks": self.ticks, "tick_s": self.tick_s,
            "consensus_host": self.consensus_host,
            "worker_devices": self.worker_devices,
            "model": dict(self.model), "serve": dict(self.serve),
            "traffic": {
                "base_rate": self.base_rate, "amplitude": self.amplitude,
                "period": self.period, "phase": self.phase,
                "tenants": [{"name": t.name, "weight": t.weight,
                             "prompt": list(t.prompt), "out": list(t.out)}
                            for t in self.tenants]},
            "events": [dict(ev) for ev in self.events],
            **({"autoscale": dict(self.autoscale)}
               if self.autoscale is not None else {})}

    def wall_estimate_s(self) -> float:
        """Lower-bound wall estimate of one host's paced trace (runner
        timeouts scale this up; compiles and restarts come on top)."""
        max_skew = max([1.0] + [ev.get("factor", 1.0) for ev in self.events
                                if ev["type"] == "slow_host"])
        return self.ticks * self.tick_s * max_skew


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"scenario: {msg}")


def parse_scenario(doc: Dict) -> Scenario:
    """Validate + build a :class:`Scenario` from a parsed document."""
    _require(isinstance(doc, dict), "document must be a JSON/YAML mapping")
    for key in ("name", "seed", "hosts", "ticks"):
        _require(key in doc, f"missing required key {key!r}")
    traffic = doc.get("traffic") or {}
    tenants = []
    for t in traffic.get("tenants", [{"name": "default"}]):
        _require(isinstance(t, dict) and t.get("name"),
                 f"tenant entries need a name ({t!r})")
        prompt = tuple(t.get("prompt", (4, 8)))
        out = tuple(t.get("out", (2, 6)))
        _require(len(prompt) == 2 and 1 <= prompt[0] <= prompt[1],
                 f"tenant {t['name']!r}: prompt range must be [lo, hi], "
                 f"lo >= 1 (got {prompt})")
        _require(len(out) == 2 and 1 <= out[0] <= out[1],
                 f"tenant {t['name']!r}: out range must be [lo, hi], "
                 f"lo >= 1 (got {out})")
        _require(float(t.get("weight", 1.0)) > 0,
                 f"tenant {t['name']!r}: weight must be > 0")
        tenants.append(Tenant(name=str(t["name"]),
                              weight=float(t.get("weight", 1.0)),
                              prompt=(int(prompt[0]), int(prompt[1])),
                              out=(int(out[0]), int(out[1]))))
    sc = Scenario(
        name=str(doc["name"]), seed=int(doc["seed"]),
        hosts=int(doc["hosts"]), ticks=int(doc["ticks"]),
        tick_s=float(doc.get("tick_s", 0.02)),
        consensus_host=int(doc.get("consensus_host", 0)),
        model=dict(doc.get("model") or {}),
        serve=dict(doc.get("serve") or {}),
        worker_devices=int(doc.get("worker_devices", 1)),
        base_rate=float(traffic.get("base_rate", 0.1)),
        amplitude=float(traffic.get("amplitude", 0.0)),
        period=int(traffic.get("period", 0)),
        phase=float(traffic.get("phase", 0.0)),
        tenants=tenants,
        events=[dict(ev) for ev in doc.get("events", [])],
        autoscale=(dict(doc["autoscale"])
                   if doc.get("autoscale") is not None else None))
    _require(sc.hosts >= 1, "hosts must be >= 1")
    _require(sc.ticks >= 1, "ticks must be >= 1")
    _require(sc.tick_s > 0, "tick_s must be > 0")
    _require(0 <= sc.consensus_host < sc.hosts,
             f"consensus_host {sc.consensus_host} out of range")
    max_total = (max(t.prompt[1] + t.out[1] for t in sc.tenants)
                 if sc.tenants else 0)
    model_max = int(sc.model.get("max_len", 64))
    _require(max_total <= model_max,
             f"longest tenant request ({max_total} tokens) exceeds "
             f"model max_len ({model_max})")
    for ev in sc.events:
        _require(isinstance(ev, dict) and ev.get("type") in EVENT_TYPES,
                 f"unknown event type in {ev!r} (types: {EVENT_TYPES})")
        kind = ev["type"]
        if kind in ("preempt", "crash", "hang", "burst"):
            _require(0 <= int(ev.get("tick", -1)) < sc.ticks,
                     f"{kind} event needs a tick inside [0, {sc.ticks})")
        if kind in ("preempt", "crash", "hang"):
            hosts = ev.get("hosts")
            _require(isinstance(hosts, list) and hosts
                     and all(0 <= int(h) < sc.hosts for h in hosts),
                     f"{kind} event needs a non-empty in-range hosts list")
            _require(sc.consensus_host not in hosts,
                     f"{kind} event may not target the consensus host "
                     f"{sc.consensus_host} (it anchors membership)")
        if kind == "preempt" and ev.get("return_tick") is not None:
            _require(int(ev["tick"]) < int(ev["return_tick"]) <= sc.ticks,
                     "preempt return_tick must lie in (tick, ticks]")
        if kind == "slow_host":
            _require(0 <= int(ev.get("host", -1)) < sc.hosts,
                     "slow_host event needs an in-range host")
            _require(float(ev.get("factor", 0)) >= 1.0,
                     "slow_host factor must be >= 1.0")
    if sc.autoscale is not None:
        _require(isinstance(sc.autoscale, dict),
                 "autoscale must be a mapping")
        pol = sc.autoscale.get("policy")
        _require(isinstance(pol, (str, dict)) and pol,
                 "autoscale needs a 'policy' (path or inline document)")
        standby = sc.standby_hosts()
        _require(all(0 <= h < sc.hosts for h in standby),
                 f"autoscale standby_hosts out of range {standby}")
        _require(sc.consensus_host not in standby,
                 f"consensus host {sc.consensus_host} cannot be standby "
                 "(it anchors membership)")
        _require(len(set(standby)) == len(standby),
                 f"duplicate autoscale standby_hosts {standby}")
    return sc


def load_scenario(path: str) -> Scenario:
    """Parse a scenario file: JSON always; ``.yaml``/``.yml`` when pyyaml
    is importable (it is an optional nicety, never a dependency)."""
    with open(path) as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        try:
            import yaml
        except ImportError as e:
            raise ValueError(
                f"{path}: YAML scenario but pyyaml is not installed — "
                "use the JSON form") from e
        doc = yaml.safe_load(text)
    else:
        doc = json.loads(text)
    return parse_scenario(doc)


def _host_rng(seed: int, host: int) -> random.Random:
    """Per-host substream: decorrelated across hosts, reproducible across
    runs/platforms (``random.Random`` core draws are version-stable)."""
    return random.Random(seed * 1_000_003 + host)


def _poisson(rng: random.Random, lam: float) -> int:
    """Knuth's Poisson sampler — exact for the small per-tick rates a
    scenario uses, stdlib-only."""
    if lam <= 0:
        return 0
    limit = math.exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


def _pick_tenant(rng: random.Random, tenants: List[Tenant]) -> Tenant:
    total = sum(t.weight for t in tenants)
    x = rng.random() * total
    for t in tenants:
        x -= t.weight
        if x <= 0:
            return t
    return tenants[-1]


def compile_host_plans(sc: Scenario) -> Tuple[Dict[int, HostPlan],
                                              List[FleetAction]]:
    """The deterministic compile: ``(schedule, seed) -> ({host: HostPlan},
    fleet consensus actions)``.

    Arrivals: one per-host Poisson stream over :meth:`Scenario.rate`, each
    arrival assigned a weighted tenant and per-request prompt/output
    lengths from the same substream. Faults: scenario events become
    :mod:`tpu_dist.obs.faults` spec entries (``preempt`` ->
    ``preempt_sigterm@step=T``, ``crash`` -> ``hard_exit@step=T``,
    ``hang`` -> ``hang@step=T,secs=S``), each gated on the attempt the
    restart chain puts it at: a host's k-th disruption can only fire on
    attempt k (every earlier disruption consumed one restart), so a
    restarted worker neither re-fires an old wave nor starves a later
    one behind an ``attempt=0`` gate it can no longer satisfy. Fleet
    actions: a ``preempt`` with a ``return_tick`` emits the consensus
    ``leave`` / ``register`` pair the runner drives through the PR 12
    membership path.

    ``expected_classes`` per host is the schedule's own prediction of
    the FLEET REPORT's restart classification (record-mode
    ``classify_attempt`` — tests assert the report matches it EXACTLY):
    every event on a host contributes its class in tick order, the
    consensus host contributes one ``preemption_snapshotted`` per
    membership change (the mid-attempt rescale relaunch), and every host
    ends ``clean``. A ``hang`` predicts ``crash``, not ``stall``: the
    serve worker runs no watchdog (its ledger tail is the liveness
    signal), so the SIGKILLed attempt leaves neither a ``run_end`` nor a
    ``stall`` event and record-mode classification reads ``crash`` — the
    supervisor's own live-side result (which saw the kill) still says
    ``stall``.
    """
    tenants = sc.tenants or [Tenant(name="default")]
    plans = {h: HostPlan(host=h) for h in range(sc.hosts)}
    for h in range(sc.hosts):
        rng = _host_rng(sc.seed, h)
        seq = 0
        for tick in range(sc.ticks):
            for _ in range(_poisson(rng, sc.rate(tick, h))):
                t = _pick_tenant(rng, tenants)
                plans[h].arrivals.append(Arrival(
                    tick=tick, rid=h * RID_STRIDE + seq, tenant=t.name,
                    prompt_len=rng.randint(*t.prompt),
                    out_len=rng.randint(*t.out)))
                seq += 1

    actions: List[FleetAction] = []
    fault_entries: Dict[int, List[str]] = {h: [] for h in range(sc.hosts)}
    disruptions: Dict[int, List[Tuple[int, str]]] = \
        {h: [] for h in range(sc.hosts)}   # (tick, class) per host
    membership_ticks: List[int] = []
    for ev in sorted(sc.events, key=lambda e: int(e.get("tick", 0))):
        kind = ev["type"]
        if kind == "slow_host":
            plans[int(ev["host"])].skew = float(ev.get("factor", 1.0))
            continue
        if kind == "burst":
            continue  # folded into rate()
        tick = int(ev["tick"])
        for h in (int(x) for x in ev["hosts"]):
            # this host's k-th disruption lands on attempt k (each prior
            # disruption ended one attempt and started the next)
            att = len(disruptions[h])
            if kind == "preempt":
                fault_entries[h].append(
                    f"preempt_sigterm@step={tick},attempt={att}")
                disruptions[h].append((tick, "preemption_snapshotted"))
                if ev.get("return_tick") is not None:
                    ret = int(ev["return_tick"])
                    actions.append(FleetAction(tick, "leave", h))
                    actions.append(FleetAction(ret, "register", h))
                    membership_ticks += [tick, ret]
                    plans[h].restart_holdoff_ticks = max(
                        plans[h].restart_holdoff_ticks, ret - tick)
            elif kind == "crash":
                fault_entries[h].append(
                    f"hard_exit@step={tick},attempt={att}")
                disruptions[h].append((tick, "crash"))
            elif kind == "hang":
                secs = float(ev.get("secs", 3600.0))
                fault_entries[h].append(
                    f"hang@step={tick},attempt={att},secs={secs:g}")
                # record-mode class (see docstring): SIGKILL leaves no
                # run_end and no stall event -> the report reads "crash"
                disruptions[h].append((tick, "crash"))
    for tick in sorted(membership_ticks):
        disruptions[sc.consensus_host].append(
            (tick, "preemption_snapshotted"))
    for h, plan in plans.items():
        plan.faults = ";".join(fault_entries[h])
        plan.expected_classes = [cls for _, cls in
                                 sorted(disruptions[h],
                                        key=lambda tc: tc[0])] + ["clean"]
    actions.sort(key=lambda a: (a.tick, a.host))
    return plans, actions


def expected_restart_classes(sc: Scenario) -> Dict[int, List[str]]:
    """Schedule -> the exact per-host attempt classification the fleet
    report must show (the CI acceptance contract)."""
    plans, _ = compile_host_plans(sc)
    return {h: plan.expected_classes for h, plan in plans.items()}
