"""Fleet-sim worker: one virtual host = one supervised serve process.

``python -m tpu_dist.sim.worker --scenario s.json --host 2`` replays host
2's slice of the compiled scenario through a real
:class:`~tpu_dist.engine.serve.ServeEngine` over a tiny
:func:`~tpu_dist.models.transformer.tiny_lm`, on virtual CPU devices (the
conftest trick, applied before jax initializes). Everything it emits is
the NORMAL per-run observability surface — ``run_start`` / ``compile`` /
windowed ``step`` records / ``admit`` / ``request`` / ``kv_cache`` /
``slo`` / ``goodput`` / ``run_end`` through :class:`~tpu_dist.obs.RunObs`
— so the fleet stitcher aggregates ordinary ledgers, not a bespoke sim
format, and every fleet rollup (goodput, SLO breaches, restart classes)
is computed by the SAME code that serves single-host runs.

Time is paced in scenario ticks (``tick_s`` per tick, stretched by the
host's slow-host ``skew`` factor): arrivals are submitted when the global
tick reaches their scheduled tick, so the admitted schedule is
machine-speed-independent — a slow box makes ticks late, never different.
The global tick survives restarts through a cursor sidecar
(``<ledger>.cursor.json``: resume tick + completed rids), so a preempted
host resumes where the fleet clock left it instead of replaying from
zero; a ``<ledger>.tick`` sidecar publishes the current tick for the
runner's fleet-clock gate.

Faults ride the standard machinery: the supervisor exports
``TPU_DIST_FAULTS`` from the scenario compile, and the tick loop checks
:func:`~tpu_dist.obs.faults.fire_step` once per tick — ``hard_exit``
kills, ``hang`` wedges, ``preempt_sigterm`` lands on the RunObs
coordinated-preemption handler, which this loop honors by draining the
serve engine (finish in-flight, shed the queue, free pages), stamping
``run_end status=preempted`` and exiting ``PREEMPT_SNAPSHOT_RC`` so the
supervisor classifies ``preemption_snapshotted``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import dataclass, field

# ticks per step-record window (and per cursor/tick-file refresh)
WINDOW_TICKS = 8


@dataclass
class SimWorkerConfig:
    """The RunObs-facing config (run_start stamps it whole)."""

    ledger_path: str = ""
    attempt: int = 0
    job_id: str = ""
    scenario: str = ""
    host: int = 0
    skew: float = 1.0
    tick_s: float = 0.02
    resume: str = ""
    watchdog_factor: float = 0.0     # serve ticks are ms-scale; the
    skew_every: int = 0              # supervisor's ledger tail is liveness
    health: str = "record"
    goodput_every_s: float = 0.0     # final partition only
    metrics_port: int = 0
    faults: str = ""
    serve: dict = field(default_factory=dict)
    model: dict = field(default_factory=dict)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description="fleet-sim serve worker (one virtual host)")
    ap.add_argument("--scenario", required=True,
                    help="scenario JSON/YAML (tpu_dist.sim.scenario)")
    ap.add_argument("--host", type=int, required=True)
    ap.add_argument("--ledger-path", default="",
                    help="base attempt-ledger path (the supervisor "
                    "forwards this)")
    ap.add_argument("--attempt", type=int, default=0,
                    help="-1 = auto next free index (supervisor lineage)")
    ap.add_argument("--devices", type=int, default=0,
                    help="virtual CPU device count (0 = scenario's "
                    "worker_devices)")
    ap.add_argument("--metrics-port", type=int, default=0)
    # tolerated supervisor forwardings (serving has no checkpoint/mesh)
    ap.add_argument("--checkpoint-dir", default="")
    ap.add_argument("--resume", default="")
    ap.add_argument("--mesh-shape", default="")
    ap.add_argument("--mesh-axes", default="")
    return ap


def _cursor_path(base: str) -> str:
    return base + ".cursor.json"


def _read_cursor(base: str):
    """(resume tick, completed rids, fresh): ``fresh`` marks a cursor the
    RUNNER seeded for an autoscale-admitted standby host — the host did
    not exist before its start tick, so pre-start arrivals are dropped
    outright (they were never admitted anywhere) instead of re-admitted."""
    try:
        with open(_cursor_path(base)) as f:
            doc = json.load(f)
        return (int(doc.get("tick", 0)), set(doc.get("done", [])),
                bool(doc.get("fresh")))
    except (OSError, ValueError):
        return 0, set(), False


def _write_cursor(base: str, tick: int, done, shed=None) -> None:
    """``shed`` (drain time only) publishes the descriptors of every
    request this host leaves unserved — rid/tenant/lengths/tick, all the
    schedule needs — so the runner can hand them to a SURVIVING host
    instead of dropping them (the ROADMAP-14 residue)."""
    tmp = _cursor_path(base) + ".tmp"
    try:
        doc = {"tick": tick, "done": sorted(done)}
        if shed is not None:
            doc["shed"] = shed
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, _cursor_path(base))
    except OSError:
        pass  # progress bookkeeping must never kill the host


def _write_tick(base: str, tick: int) -> None:
    try:
        with open(base + ".tick", "w") as f:
            f.write(f"{tick}\n")
    except OSError:
        pass


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    # virtual devices BEFORE jax initializes (the conftest 8-device trick)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist._compat import set_cpu_device_count
    from tpu_dist.sim.scenario import compile_host_plans, load_scenario

    sc = load_scenario(args.scenario)
    devices = args.devices or sc.worker_devices
    try:
        set_cpu_device_count(max(devices, 1))
    except RuntimeError:
        pass  # backend already initialized (in-process test harness)

    plans, _actions = compile_host_plans(sc)
    if args.host not in plans:
        raise SystemExit(f"host {args.host} not in scenario "
                         f"(hosts: {sc.hosts})")
    plan = plans[args.host]

    import jax.numpy as jnp
    import numpy as np

    from tpu_dist.engine.serve import DecodeRequest, ServeConfig, ServeEngine
    from tpu_dist.models.transformer import tiny_lm
    from tpu_dist.obs import RunObs
    from tpu_dist.obs.reqtrace import RequestTracer
    from tpu_dist.parallel.supervisor import PREEMPT_SNAPSHOT_RC

    model_kw = {"vocab_size": 64, "num_layers": 1, "d_model": 32,
                "num_heads": 2, "max_len": 64, **sc.model}
    serve_kw = {"max_slots": 2, "page_size": 8, "num_pages": 64,
                "kv_event_every": 32, **sc.serve}
    cfg = SimWorkerConfig(
        ledger_path=args.ledger_path, attempt=args.attempt,
        job_id=f"{sc.name}-h{args.host}", scenario=sc.name,
        host=args.host, skew=plan.skew, tick_s=sc.tick_s,
        metrics_port=args.metrics_port, serve=serve_kw, model=model_kw)

    obs = RunObs("fleet_sim", cfg, unit="tok/s")
    obs.enable_preempt_snapshot()   # SIGTERM = drain request, not death
    obs.run_start()

    base = args.ledger_path or ""
    start_tick, done, fresh = (_read_cursor(base) if base
                               else (0, set(), False))
    arrivals = [a for a in plan.arrivals if a.rid not in done
                and not (fresh and a.tick < start_tick)]

    lm = tiny_lm(**model_kw)
    params = lm.init({"params": jax.random.PRNGKey(sc.seed)},
                     jnp.zeros((1, model_kw["max_len"]), jnp.int32),
                     train=False)["params"]
    # trace context (obs.reqtrace): the namespace is the SCENARIO name,
    # not this host's job_id — every host derives the same trace_id for
    # the same rid, so a request shed here and re-admitted elsewhere
    # stitches into one trace (sim.fleet.FleetLedger.traces)
    tracer = RequestTracer(obs.ledger, job_id=cfg.job_id,
                           attempt=obs.attempt, host=args.host,
                           trace_ns=sc.name)
    eng = ServeEngine(lm, params, ServeConfig(**serve_kw),
                      ledger=obs.ledger, tracer=tracer)
    arrival_rng = np.random.default_rng(sc.seed * 7919 + args.host)

    def _prompt(a):
        # content is irrelevant to the schedule; lengths are the load
        return arrival_rng.integers(1, model_kw["vocab_size"],
                                    a.prompt_len).astype(np.int32)

    def _drain_and_exit(reason: str, tick: int) -> int:
        comps = eng.drain(reason=reason, emit_run_end=False)
        for c in comps:
            done.add(c.rid)
        # publish every request this host leaves unserved (queued-then-
        # shed, not-yet-arrived, and any handed-off intake still pending):
        # the runner re-admits them on a surviving host when this host is
        # gone for good, or this host re-admits them itself on return
        shed = [{"rid": a.rid, "tick": a.tick, "tenant": a.tenant,
                 "prompt_len": a.prompt_len, "out_len": a.out_len}
                for a in arrivals if a.rid not in done]
        shed += [e for e in pending_handoff
                 if e.get("rid") is not None and e["rid"] not in done]
        if base:
            _write_cursor(base, tick, done, shed=shed)
            _write_tick(base, tick)
        obs.run_end(status="preempted", snapshot_tick=tick,
                    completed=eng.completed, rejected=eng.rejected)
        return PREEMPT_SNAPSHOT_RC

    # cross-host shed handoff (round 20): the runner appends descriptors
    # of a permanently-gone host's unserved requests to this sidecar; the
    # survivor admits each at its scheduled tick with a `readmit` span,
    # so the request stays ONE trace across hosts (shared trace_ns)
    from tpu_dist.obs.autoscale import LedgerTailer

    handoff_tail = LedgerTailer()
    handoff_path = base + ".handoff.jsonl" if base else ""
    pending_handoff: list = []

    tick = start_tick
    i = 0
    window_t0 = time.perf_counter()
    window_device_s = 0.0
    window_dispatch_s = 0.0
    window_tokens = 0
    window_start_tick = tick
    emitted_compile = False
    t_run0 = time.perf_counter()
    status_extra = {}
    try:
        while (tick < sc.ticks or i < len(arrivals) or pending_handoff
               or eng.queue or any(s is not None for s in eng.slots)):
            if tick > sc.ticks * 10 + 100_000:
                raise RuntimeError(f"worker did not drain by tick {tick}")
            # coordinated preemption (SIGTERM via RunObs, or an injected
            # preempt_deadline advance notice below)
            if obs.preempt_pending():
                return _drain_and_exit(obs.preempt_source or "sigterm",
                                       tick)
            effects = obs.fire_step_faults(tick)
            if "preempt_deadline" in effects:
                return _drain_and_exit("preempt_deadline", tick)
            t0 = time.perf_counter()
            while i < len(arrivals) and arrivals[i].tick <= tick:
                a = arrivals[i]
                if start_tick > 0 and a.tick < start_tick:
                    # this rid was scheduled before the resume point and
                    # never completed — a prior attempt shed it, and this
                    # attempt is the re-admission. The zero-duration
                    # readmit span binds the two attempts' spans into one
                    # trace (same derived trace_id)
                    t_now = time.monotonic()
                    tid, sid, par = tracer.ids(a.rid, "readmit")
                    obs.ledger.emit(
                        "span", trace_id=tid, span_id=sid, parent_id=par,
                        name="readmit", rid=a.rid,
                        start=round(t_now, 6), end=round(t_now, 6),
                        from_tick=a.tick, at_tick=tick, tenant=a.tenant,
                        **tracer.attrs())
                eng.submit(DecodeRequest(a.rid, _prompt(a), a.out_len,
                                         tenant=a.tenant))
                i += 1
            if handoff_path:
                pending_handoff.extend(
                    e for e in handoff_tail.poll([handoff_path])
                    if e.get("rid") is not None)
            if pending_handoff:
                later = []
                for e in pending_handoff:
                    if int(e.get("tick", 0)) > tick:
                        later.append(e)
                        continue
                    rid = int(e["rid"])
                    if rid in done:
                        continue
                    # the handed-off request joins ITS OWN trace: the
                    # trace_ns is the scenario name, so this host derives
                    # the same trace_id the origin host shed under
                    t_now = time.monotonic()
                    tid, sid, par = tracer.ids(rid, "readmit")
                    obs.ledger.emit(
                        "span", trace_id=tid, span_id=sid, parent_id=par,
                        name="readmit", rid=rid,
                        start=round(t_now, 6), end=round(t_now, 6),
                        from_tick=e.get("tick"), at_tick=tick,
                        tenant=e.get("tenant"), handoff=True,
                        **tracer.attrs())
                    eng.submit(DecodeRequest(
                        rid, arrival_rng.integers(
                            1, model_kw["vocab_size"],
                            max(int(e.get("prompt_len") or 4), 1)
                        ).astype(np.int32),
                        max(int(e.get("out_len") or 2), 1),
                        tenant=e.get("tenant")))
                pending_handoff = later
            window_dispatch_s += time.perf_counter() - t0
            t0 = time.perf_counter()
            comps = eng.step()
            window_device_s += time.perf_counter() - t0
            for c in comps:
                done.add(c.rid)
                window_tokens += c.n_generated
            tick += 1
            # pacing: the global tick maps to wall time at tick_s x skew;
            # a slow machine just runs late (schedules never change)
            target = window_t0 + (tick - window_start_tick) \
                * sc.tick_s * plan.skew
            sleep = target - time.perf_counter()
            if sleep > 0:
                time.sleep(sleep)
            if tick % WINDOW_TICKS == 0 or tick >= sc.ticks:
                now = time.perf_counter()
                warm = not emitted_compile
                if warm:
                    # engines emit 'compile' right after the warm
                    # dispatch; the run_start->compile gap is the startup
                    # badput and the warm record below stays uncharged
                    obs.ledger.emit("compile", program="serve_tick",
                                    seconds=round(now - t_run0, 3))
                    emitted_compile = True
                obs.step(step=tick, loss=None, n_items=window_tokens,
                         wall_s=now - window_t0, data_s=0.0,
                         dispatch_s=window_dispatch_s,
                         device_s=window_device_s,
                         steps_in_dispatch=max(tick - window_start_tick, 1),
                         warm=warm, queue_depth=len(eng.queue),
                         active_seqs=sum(s is not None for s in eng.slots))
                obs.heartbeat()
                if base:
                    _write_cursor(base, tick, done)
                    _write_tick(base, tick)
                window_t0 = now
                window_start_tick = tick
                window_device_s = window_dispatch_s = 0.0
                window_tokens = 0
        eng._emit_kv_cache()  # final pool-pressure snapshot
        if base:
            _write_cursor(base, tick, done)
            _write_tick(base, tick)
        status_extra = {"completed": eng.completed,
                        "rejected": eng.rejected, "final_tick": tick}
        return 0
    finally:
        obs.run_end(**status_extra)


if __name__ == "__main__":
    raise SystemExit(main())
